"""Device-resident memory state: structure-of-arrays arena in HBM.

This is the TPU-native replacement for the reference's object graph of Python
dicts (``memory_shard.py`` node/edge dicts + ``vector_store.py`` LanceDB rows).
All numeric per-memory fields live in fixed-capacity device arrays so that the
hot operations — similarity search, decay sweeps, importance scoring, linking —
are single batched XLA programs instead of O(N) Python loops (reference hot
loops at ``memory_system.py:464-470``, ``:797-836``, ``:838-891``).

Design notes (SURVEY §7.1):
- Static shapes: capacity is fixed per-compile; growth doubles capacity on the
  host (rare, amortized). Batched mutations pad their index vectors to
  power-of-two buckets so jit caches stay small.
- A sentinel scratch row at index ``capacity`` absorbs padded writes, so every
  scatter runs with a full static-size index vector and no masking branches.
- Embeddings are stored L2-normalized; cosine similarity is a plain dot
  product and retrieval is one matvec + ``lax.top_k``.
- ``tenant_id`` is a first-class column: multi-tenant isolation is a vectorized
  mask, replacing the reference's per-user SQL filters (``vector_store.py:118``).

State ownership & donation invariants
-------------------------------------
Every mutation kernel below ships as a PAIR of jit specializations over one
impl: the default export (e.g. ``arena_add``) donates its state argument(s)
so XLA scatters in place — a small write costs the scatter, not a full-arena
HBM copy (~1.5 GB at 1M×768 bf16) — and a ``*_copy`` twin keeps the classic
copy-on-write semantics. Donation consumes the input buffers: after a call
to the donated variant, EVERY live reference to the old state (the pytree
AND any leaf array pulled out of it) is deleted, and using one raises
``RuntimeError: Array has been deleted``.

Who may hold a reference to an ``ArenaState``/``EdgeState``:
- ``MemoryIndex`` owns the live state and is the only durable holder. Its
  mutation gate (``core/index.py``) donates ONLY when it can prove, under
  ``_state_lock``, that it holds the sole reference; otherwise it runs the
  ``*_copy`` twin, so a concurrent reader's snapshot is never invalidated.
- Readers (search/link/sweep paths) may snapshot ``index.state`` for the
  duration of one operation — the gate sees the raised refcount and falls
  back to copying. They must re-snapshot per operation, never cache across
  mutations.
- Direct callers of the donated module-level kernels (bench, tests) own
  the handoff themselves: treat the argument as consumed, thread the
  returned state forward, and never touch the old pytree or its leaves.
- A donated state pytree must hold one DISTINCT buffer per leaf (the
  runtime rejects donating the same buffer twice). ``init_arena`` /
  ``init_edges`` guarantee this; hand-built states must too.
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from lazzaro_tpu.ops.chunking import (QUERY_CHUNK, chunked_map,
                                      chunked_map_multi)

NEG_INF = -1e30

TYPE_IDS = {"semantic": 0, "episodic": 1, "procedural": 2}
TYPE_NAMES = {v: k for k, v in TYPE_IDS.items()}


@struct.dataclass
class ArenaState:
    """Node arena. All arrays have leading dim ``capacity + 1`` (last row is
    the sentinel scratch row).

    Paged mode (ISSUE 17): when ``row_map``/``inv_map`` are set, ONLY ``emb``
    is pool-shaped ``[pool_n, d]`` — every other column stays logical
    ``[cap+1]``. ``row_map[logical] -> pool slot`` (unmapped rows point at
    the pool sentinel slot ``pool_n - 1``, which is all-zeros) and
    ``inv_map[slot] -> logical`` (free slots hold -1; the sentinel slot
    holds ``capacity``). Dense mode keeps both maps ``None`` and every
    kernel below reduces to the identity indirection."""

    emb: jax.Array            # [cap+1, d] dense / [pool_n, d] paged
    salience: jax.Array       # [cap+1] f32 in [0, 1]
    timestamp: jax.Array      # [cap+1] f32 seconds (host-epoch offset)
    last_accessed: jax.Array  # [cap+1] f32
    access_count: jax.Array   # [cap+1] i32
    type_id: jax.Array        # [cap+1] i32 (TYPE_IDS)
    shard_id: jax.Array       # [cap+1] i32
    tenant_id: jax.Array      # [cap+1] i32
    alive: jax.Array          # [cap+1] bool
    is_super: jax.Array       # [cap+1] bool
    row_map: Optional[jax.Array] = None   # [cap+1] i32 logical -> pool slot
    inv_map: Optional[jax.Array] = None   # [pool_n] i32 pool slot -> logical

    @property
    def capacity(self) -> int:
        # salience (not emb): emb is pool-shaped under paging
        return self.salience.shape[0] - 1

    @property
    def dim(self) -> int:
        return self.emb.shape[1]

    @property
    def pool_rows(self) -> int:
        """Physical embedding rows (== capacity + 1 when dense)."""
        return self.emb.shape[0]


@struct.dataclass
class PageTable:
    """Device-side free-list for the paged embedding pool (ISSUE 17).

    ``free_slots`` is a LIFO stack of pool slot indices with one trailing
    scratch entry (index ``pool_n - 1``) that absorbs masked pushes, so
    every push/pop runs with full static-size scatters and no branches.
    ``free_top`` is the live stack depth (entries below it are free pool
    slots; the newest free slot — popped first — sits at ``free_top - 1``)."""

    free_slots: jax.Array     # [pool_n] i32 (last entry = scratch)
    free_top: jax.Array       # [] i32

    @property
    def stack_cap(self) -> int:
        return self.free_slots.shape[0] - 1


@struct.dataclass
class EdgeState:
    """Edge arena: directed weighted associations, by arena row index."""

    src: jax.Array           # [E+1] i32 arena row of source node
    tgt: jax.Array           # [E+1] i32
    weight: jax.Array        # [E+1] f32 in [0, 1]
    co: jax.Array            # [E+1] i32 co-occurrence count
    last_updated: jax.Array  # [E+1] f32
    alive: jax.Array         # [E+1] bool
    tenant_id: jax.Array     # [E+1] i32 (tenant of the owning graph)

    @property
    def capacity(self) -> int:
        return self.src.shape[0] - 1


# Pallas top-k geometry: arenas at/above the dispatch threshold allocate row
# counts in TOPK_BLOCK multiples so the blocked kernel never needs a padded
# copy of the embedding matrix (extra rows are ordinary free capacity).
TOPK_BLOCK = 4096
PALLAS_TOPK_MIN_ROWS = 262_144


@struct.dataclass
class SemanticRing:
    """Device-resident semantic query cache (ISSUE 20): a small ring of
    recent query embeddings + their packed top-k serving results, probed
    as an extra candidate group inside every fused serving kernel. Row
    ``R`` (the last) is a scratch sentinel — ring writes that must be
    dropped scatter there, the probe never reads it (same trick as the
    arena's sentinel row).

    Validity and the rotation head are HOST-owned and ride each dispatch
    as sidecar inputs: invalidation (a lifecycle/dedup write touching a
    cached entry's rows, or a tenant-scoped flush) is a host bitmask
    flip, never a device dispatch. An entry is usable for a query only
    when tenant / gate flag / serving mode match, ``stored_k`` covers
    the query's k, the nprobe matches (IVF/PQ), and the stored
    embedding's cosine clears the threshold."""

    emb: jax.Array       # [R+1, d] f32 normalized query embeddings
    tenant: jax.Array    # [R+1] i32 owning tenant
    gate_on: jax.Array   # [R+1] bool gate flag the entry was served under
    mode: jax.Array      # [R+1] i32 serving-mode id (SEM_MODE_IDS)
    stored_k: jax.Array  # [R+1] i32 result depth the entry can serve
    nprobe: jax.Array    # [R+1] i32 probe width (0 for dense modes)
    gate_s: jax.Array    # [R+1] f32 cached gate score
    gate_r: jax.Array    # [R+1] i32 cached gate row
    ann_s: jax.Array     # [R+1, K] f32 cached top-k scores (desc, NEG_INF pad)
    ann_r: jax.Array     # [R+1, K] i32 cached top-k rows (sentinel pad)

    @property
    def slots(self) -> int:
        return self.tenant.shape[0] - 1

    @property
    def width(self) -> int:
        return self.ann_s.shape[1]


# Serving-mode ids for the ring's mode column: a cached entry only serves
# queries dispatched through the SAME kernel family (scores are not
# comparable across coarse stages, and the tiered window width differs).
SEM_MODE_IDS = {
    "exact": 0, "quant": 1, "ivf": 2, "ivf_quant": 3, "pq": 4,
    "tiered": 5, "ivf_tiered": 6, "pq_tiered": 7,
}


def init_semantic_ring(slots: int, dim: int, width: int,
                       row_sentinel: int = 0) -> SemanticRing:
    """Fresh (all-invalid, from the host's view) ring. ``width`` must
    cover the widest candidate window any serving kernel packs (k, or
    k+slack for the tiered families); ``row_sentinel`` pre-fills the row
    columns with the arena sentinel so a never-written slot can't alias
    row 0 even if misused."""
    if slots < 1:
        raise ValueError("semantic ring needs at least one slot")
    n = slots + 1
    return SemanticRing(
        emb=jnp.zeros((n, dim), jnp.float32),
        tenant=jnp.full((n,), -1, jnp.int32),
        gate_on=jnp.zeros((n,), bool),
        mode=jnp.full((n,), -1, jnp.int32),
        stored_k=jnp.zeros((n,), jnp.int32),
        nprobe=jnp.zeros((n,), jnp.int32),
        gate_s=jnp.full((n,), NEG_INF, jnp.float32),
        gate_r=jnp.full((n,), row_sentinel, jnp.int32),
        ann_s=jnp.full((n, width), NEG_INF, jnp.float32),
        ann_r=jnp.full((n, width), row_sentinel, jnp.int32),
    )


def init_arena(capacity: int, dim: int, dtype=jnp.float32) -> ArenaState:
    n = capacity + 1
    return ArenaState(
        emb=jnp.zeros((n, dim), dtype=dtype),
        salience=jnp.zeros((n,), jnp.float32),
        timestamp=jnp.zeros((n,), jnp.float32),
        last_accessed=jnp.zeros((n,), jnp.float32),
        access_count=jnp.zeros((n,), jnp.int32),
        type_id=jnp.zeros((n,), jnp.int32),
        shard_id=jnp.full((n,), -1, jnp.int32),
        tenant_id=jnp.full((n,), -1, jnp.int32),
        alive=jnp.zeros((n,), bool),
        is_super=jnp.zeros((n,), bool),
    )


def init_edges(capacity: int) -> EdgeState:
    n = capacity + 1
    return EdgeState(
        src=jnp.full((n,), -1, jnp.int32),
        tgt=jnp.full((n,), -1, jnp.int32),
        weight=jnp.zeros((n,), jnp.float32),
        co=jnp.zeros((n,), jnp.int32),
        last_updated=jnp.zeros((n,), jnp.float32),
        alive=jnp.zeros((n,), bool),
        tenant_id=jnp.full((n,), -1, jnp.int32),
    )


def grow_arena(state: ArenaState, new_capacity: int) -> ArenaState:
    """Host-side reallocation (not jitted; rare, amortized O(1))."""
    old = state.capacity
    assert new_capacity > old
    fresh = init_arena(new_capacity, state.dim, state.emb.dtype)

    def copy(new, cur):
        return new.at[:old].set(cur[:old])

    return ArenaState(
        emb=copy(fresh.emb, state.emb),
        salience=copy(fresh.salience, state.salience),
        timestamp=copy(fresh.timestamp, state.timestamp),
        last_accessed=copy(fresh.last_accessed, state.last_accessed),
        access_count=copy(fresh.access_count, state.access_count),
        type_id=copy(fresh.type_id, state.type_id),
        shard_id=copy(fresh.shard_id, state.shard_id),
        tenant_id=copy(fresh.tenant_id, state.tenant_id),
        alive=copy(fresh.alive, state.alive),
        is_super=copy(fresh.is_super, state.is_super),
    )


def grow_edges(state: EdgeState, new_capacity: int) -> EdgeState:
    old = state.capacity
    assert new_capacity > old
    fresh = init_edges(new_capacity)

    def copy(new, cur):
        return new.at[:old].set(cur[:old])

    return EdgeState(
        src=copy(fresh.src, state.src),
        tgt=copy(fresh.tgt, state.tgt),
        weight=copy(fresh.weight, state.weight),
        co=copy(fresh.co, state.co),
        last_updated=copy(fresh.last_updated, state.last_updated),
        alive=copy(fresh.alive, state.alive),
        tenant_id=copy(fresh.tenant_id, state.tenant_id),
    )


# ---------------------------------------------------------------------------
# Paged arena (ISSUE 17): pool init/growth + the logical<->physical
# indirection helpers every kernel routes its emb access through. All
# helpers are the identity when ``row_map`` is None, so dense arenas trace
# exactly the same programs as before.
# ---------------------------------------------------------------------------


def init_arena_paged(capacity: int, dim: int, pool_slots: int,
                     dtype=jnp.float32) -> Tuple[ArenaState, PageTable]:
    """Paged arena: logical columns at ``[cap+1]``, emb pool at
    ``[pool_slots + 1, d]`` (last slot = all-zero pool sentinel). The free
    stack starts full, ordered so slot 0 pops first (host mirror parity)."""
    n = capacity + 1
    pool_n = pool_slots + 1
    base = init_arena(capacity, dim, dtype)
    state = base.replace(
        emb=jnp.zeros((pool_n, dim), dtype=dtype),
        row_map=jnp.full((n,), pool_n - 1, jnp.int32),
        inv_map=jnp.full((pool_n,), -1, jnp.int32).at[pool_n - 1]
                   .set(capacity),
    )
    ptable = PageTable(
        free_slots=jnp.concatenate([
            jnp.arange(pool_n - 2, -1, -1, dtype=jnp.int32),
            jnp.zeros((1,), jnp.int32)]),
        free_top=jnp.int32(pool_n - 1),
    )
    return state, ptable


def grow_arena_paged(state: ArenaState, new_capacity: int) -> ArenaState:
    """Logical growth WITHOUT touching the embedding pool: metadata columns
    realloc+copy (a few MB), ``row_map`` extends with pool-sentinel fill,
    and the ``[pool_n, d]`` emb buffer — the term that dominates arena
    bytes — is carried over by reference. This is the copy-free growth
    claim: O(metadata), never O(N·d). The pool grows independently (and by
    page multiples) via ``grow_pool`` when free slots run out."""
    old = state.capacity
    assert new_capacity > old
    assert state.row_map is not None
    pool_sent = state.emb.shape[0] - 1
    fresh = init_arena(new_capacity, state.dim, state.emb.dtype)

    def copy(new, cur):
        return new.at[:old].set(cur[:old])

    n = new_capacity + 1
    return state.replace(
        salience=copy(fresh.salience, state.salience),
        timestamp=copy(fresh.timestamp, state.timestamp),
        last_accessed=copy(fresh.last_accessed, state.last_accessed),
        access_count=copy(fresh.access_count, state.access_count),
        type_id=copy(fresh.type_id, state.type_id),
        shard_id=copy(fresh.shard_id, state.shard_id),
        tenant_id=copy(fresh.tenant_id, state.tenant_id),
        alive=copy(fresh.alive, state.alive),
        is_super=copy(fresh.is_super, state.is_super),
        row_map=jnp.full((n,), pool_sent, jnp.int32)
                   .at[:old].set(state.row_map[:old]),
        inv_map=jnp.where(state.inv_map == old, new_capacity,
                          state.inv_map),
    )


def grow_pool(state: ArenaState, ptable: PageTable, new_pool_slots: int
              ) -> Tuple[ArenaState, PageTable]:
    """Grow the physical embedding pool by whole pages (host-side, rare).
    Copies the OLD pool rows only (pool ≈ live set, not logical capacity),
    rebinds the sentinel slot to the new last index, converts the old
    sentinel slot into an ordinary free slot (it is all-zero and unbound),
    and pushes the freed slots in ONE fixed order (old sentinel first,
    then the new slots ascending) — the host mirror replays the same
    order, so device and mirror stay pop-for-pop identical."""
    assert state.row_map is not None
    old_pool_n = state.emb.shape[0]
    new_pool_n = new_pool_slots + 1
    assert new_pool_n > old_pool_n
    old_sent = old_pool_n - 1
    new_sent = new_pool_n - 1
    cap = state.capacity
    emb = jnp.zeros((new_pool_n, state.dim), state.emb.dtype)
    emb = emb.at[:old_pool_n].set(state.emb)
    row_map = jnp.where(state.row_map == old_sent, new_sent, state.row_map)
    inv_map = jnp.full((new_pool_n,), -1, jnp.int32)
    inv_map = inv_map.at[:old_pool_n].set(state.inv_map)
    inv_map = inv_map.at[old_sent].set(-1).at[new_sent].set(cap)
    # new free slots, deepest-first push order: old sentinel, then the
    # new slots ascending (so the highest new slot pops first)
    added = np.concatenate([
        np.asarray([old_sent], np.int32),
        np.arange(old_pool_n, new_sent, dtype=np.int32)])
    top = int(ptable.free_top)
    free = np.full((new_pool_n,), 0, np.int32)
    free[:top] = np.asarray(ptable.free_slots)[:top]
    free[top:top + len(added)] = added
    return (state.replace(emb=emb, row_map=row_map, inv_map=inv_map),
            PageTable(free_slots=jnp.asarray(free),
                      free_top=jnp.int32(top + len(added))))


def _nrows(state: ArenaState) -> int:
    """Logical row count ``cap + 1`` (emb.shape[0] is pool-shaped when
    paged — every full-corpus scan sizes by a logical column instead)."""
    return state.salience.shape[0]


def _phys(state: ArenaState, rows: jax.Array) -> jax.Array:
    """Logical row indices -> physical emb rows (identity when dense).
    Unbound logical rows — including the logical sentinel — land on the
    all-zero pool sentinel slot, so stray gathers read zeros and stray
    scatters are absorbed exactly like the dense scratch row."""
    if state.row_map is None:
        return rows
    return state.row_map[rows]


def _pool_mask(state: ArenaState, mask: jax.Array) -> jax.Array:
    """Re-index a logical ``[cap+1]`` bool mask into pool space
    ``[pool_n]`` for whole-corpus scans over the paged emb. Free pool
    slots (inv_map == -1) are masked off."""
    if state.row_map is None:
        return mask
    inv = state.inv_map
    return mask[jnp.maximum(inv, 0)] & (inv >= 0)


def _pool_col(state: ArenaState, col: jax.Array) -> jax.Array:
    """Re-index a logical per-row column (e.g. shard_id) into pool space
    so row-wise compares line up with a pool-space scan. Free slots read
    row 0's value — callers must pair this with a ``_pool_mask``-derived
    validity mask."""
    if state.row_map is None:
        return col
    return col[jnp.maximum(state.inv_map, 0)]


def _pool_to_logical(state: ArenaState, rows: jax.Array) -> jax.Array:
    """Pool-space top-k survivor indices -> logical rows (identity when
    dense). Free slots map to the logical sentinel ``capacity``."""
    if state.row_map is None:
        return rows
    inv = state.inv_map[rows]
    return jnp.where(inv >= 0, inv, jnp.int32(state.capacity))


def _page_alloc(state: ArenaState, ptable: PageTable, rows: jax.Array,
                live: jax.Array
                ) -> Tuple[ArenaState, PageTable, jax.Array, jax.Array]:
    """Bind pool slots to logical ``rows`` inside a fused dispatch:
    prefix-sum pop from the free stack (the PR 3 edge-slot compactor
    idiom). Rows already bound, sentinel-padded rows, and ``~live`` rows
    allocate nothing. Returns ``(state, ptable, pops, overflow)`` — the
    pop count and an exhaustion flag ride the packed readback tail; the
    host pre-grows the pool so overflow is a can't-happen guard, not a
    recovery path (an exhausted pop leaves the row unbound, its scatters
    absorbed by the pool sentinel)."""
    cap = state.capacity
    pool_sent = state.emb.shape[0] - 1
    # suppress duplicate rows within the batch: only the FIRST occurrence
    # pops (same tri-mask as _page_free — keeps the host mirror's replay
    # pop-for-pop when one batch names a row twice)
    eq = rows[:, None] == rows[None, :]
    first = ~jnp.any(eq & (jnp.arange(rows.shape[0])[:, None]
                           > jnp.arange(rows.shape[0])[None, :]), axis=1)
    need = (first & live & (rows < cap)
            & (state.row_map[rows] == pool_sent))
    rank = jnp.cumsum(need.astype(jnp.int32)) - 1
    idx = ptable.free_top - 1 - rank
    ok = need & (idx >= 0)
    slots = jnp.where(ok, ptable.free_slots[jnp.maximum(idx, 0)],
                      pool_sent)
    rows_b = jnp.where(ok, rows, cap)
    row_map = state.row_map.at[rows_b].set(slots.astype(jnp.int32))
    inv_map = state.inv_map.at[slots].set(rows_b.astype(jnp.int32))
    # re-pin the sentinel bindings every masked scatter routed through them
    row_map = row_map.at[cap].set(pool_sent)
    inv_map = inv_map.at[pool_sent].set(cap)
    pops = ok.sum().astype(jnp.int32)
    overflow = (need & ~ok).any()
    return (state.replace(row_map=row_map, inv_map=inv_map),
            ptable.replace(free_top=ptable.free_top - pops),
            pops, overflow)


def _page_free(state: ArenaState, ptable: PageTable, rows: jax.Array
               ) -> Tuple[ArenaState, PageTable, jax.Array]:
    """Unbind logical ``rows`` from their pool slots and push the slots
    back on the free stack (delete + tier-demote reclamation). Freed
    slots' emb rows are ZEROED — bit-parity with the dense
    commit-then-zero demote, and re-allocation hands out clean rows.
    Unbound/sentinel rows and intra-batch duplicates push nothing (their
    scatters land on the stack scratch entry)."""
    cap = state.capacity
    pool_sent = state.emb.shape[0] - 1
    slots = state.row_map[rows]
    # suppress duplicate rows within the batch: only the FIRST occurrence
    # pushes (a tri-mask over pairwise equality, B is a padded bucket)
    eq = rows[:, None] == rows[None, :]
    first = ~jnp.any(eq & (jnp.arange(rows.shape[0])[:, None]
                           > jnp.arange(rows.shape[0])[None, :]), axis=1)
    do = first & (rows < cap) & (slots < pool_sent)
    rank = jnp.cumsum(do.astype(jnp.int32)) - 1
    stack_cap = ptable.free_slots.shape[0] - 1
    pos = jnp.where(do, jnp.minimum(ptable.free_top + rank, stack_cap),
                    stack_cap)
    slots_b = jnp.where(do, slots, pool_sent)
    rows_b = jnp.where(do, rows, cap)
    free_slots = ptable.free_slots.at[pos].set(
        jnp.where(do, slots, ptable.free_slots[stack_cap]).astype(jnp.int32))
    row_map = state.row_map.at[rows_b].set(pool_sent)
    inv_map = state.inv_map.at[slots_b].set(-1)
    row_map = row_map.at[cap].set(pool_sent)
    inv_map = inv_map.at[pool_sent].set(cap)
    emb = state.emb.at[slots_b].set(0)
    pushes = do.sum().astype(jnp.int32)
    return (state.replace(emb=emb, row_map=row_map, inv_map=inv_map),
            ptable.replace(free_slots=free_slots,
                           free_top=ptable.free_top + pushes),
            pushes)


# ---------------------------------------------------------------------------
# Jitted mutation kernels. Index vectors are sentinel-padded on the host
# (see pad_rows) so shapes bucket to powers of two. Each kernel is one impl
# jitted twice: the donated default (zero-copy in-place scatter) and a
# ``*_copy`` twin for callers that cannot prove sole ownership of the state
# (see the module docstring's donation invariants).
# ---------------------------------------------------------------------------


def _donated_pair(impl, donate=(0,), **jit_kwargs):
    """(donated, copying) jit pair over one mutation impl."""
    return (jax.jit(impl, donate_argnums=donate, **jit_kwargs),
            jax.jit(impl, **jit_kwargs))


def pad_rows(rows: np.ndarray, sentinel: int, min_bucket: int = 8) -> np.ndarray:
    """Pad an int row-index vector to a size bucket with the sentinel row
    index, bounding the number of distinct jit specializations: powers of
    two up to 4096, then multiples of 1024 — a 5,000-row conversation
    batch pays a 5,120-row scan, not an 8,192-row one (pow2 padding wasted
    ~1.6× of every whole-arena link/dedup matmul at that size, and the
    kernels-per-bucket count stays small either way)."""
    n = len(rows)
    if n > 4096:
        bucket = -(-n // 1024) * 1024
    else:
        bucket = max(min_bucket, 1 << (max(1, n - 1)).bit_length())
    out = np.full((bucket,), sentinel, np.int32)
    out[:n] = rows
    return out


@jax.jit
def normalize(x: jax.Array) -> jax.Array:
    n = jnp.linalg.norm(x.astype(jnp.float32), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) / jnp.maximum(n, 1e-9)).astype(x.dtype)


from lazzaro_tpu.ops.chunking import nt_dot  # noqa: E402  (re-export: scans
#                                              score through this helper)


def _arena_add(
    state: ArenaState,
    rows: jax.Array,        # [B] i32, sentinel-padded
    emb: jax.Array,         # [B, d] (normalized by caller or here)
    salience: jax.Array,    # [B] f32
    timestamp: jax.Array,   # [B] f32
    type_id: jax.Array,     # [B] i32
    shard_id: jax.Array,    # [B] i32
    tenant_id: jax.Array,   # [B] i32
    is_super: jax.Array,    # [B] bool
) -> ArenaState:
    emb = normalize(emb).astype(state.emb.dtype)
    new_emb = state.emb.at[_phys(state, rows)].set(emb)
    if state.row_map is not None:
        # the pool sentinel absorbs padded/dup scatters but must STAY
        # all-zero: every unbound logical row aliases it, and tiered
        # rescore reads those zeros for bit-parity with the dense
        # demote-zeroed rows
        new_emb = new_emb.at[state.emb.shape[0] - 1].set(0)
    return state.replace(
        emb=new_emb,
        salience=state.salience.at[rows].set(salience),
        timestamp=state.timestamp.at[rows].set(timestamp),
        last_accessed=state.last_accessed.at[rows].set(timestamp),
        access_count=state.access_count.at[rows].set(0),
        type_id=state.type_id.at[rows].set(type_id),
        shard_id=state.shard_id.at[rows].set(shard_id),
        tenant_id=state.tenant_id.at[rows].set(tenant_id),
        alive=state.alive.at[rows].set(True),
        is_super=state.is_super.at[rows].set(is_super),
    )


arena_add, arena_add_copy = _donated_pair(_arena_add)


def _arena_delete(state: ArenaState, rows: jax.Array) -> ArenaState:
    return state.replace(
        alive=state.alive.at[rows].set(False),
        tenant_id=state.tenant_id.at[rows].set(-1),
    )


arena_delete, arena_delete_copy = _donated_pair(_arena_delete)


def _arena_update_access(
    state: ArenaState,
    rows: jax.Array,
    now: jax.Array,
    boost: jax.Array,
    cap_salience: float = 1.0,
) -> ArenaState:
    """access_count += 1, salience += boost (capped), refresh last_accessed.

    Mirrors ``buffer_graph.py:79-86`` (update_access) and the neighbor boost in
    ``memory_system.py:242-260`` — one scatter instead of per-node Python."""
    sal = state.salience.at[rows].add(boost)
    sal = jnp.minimum(sal, cap_salience)
    return state.replace(
        access_count=state.access_count.at[rows].add(1),
        salience=sal,
        last_accessed=state.last_accessed.at[rows].set(now),
    )


arena_update_access, arena_update_access_copy = _donated_pair(
    _arena_update_access, static_argnames=("cap_salience",))


def _arena_boost(state: ArenaState, rows: jax.Array, now: jax.Array,
                 boost: jax.Array) -> ArenaState:
    """Associative neighbor boost: salience += boost (cap 1.0) and freshness
    inheritance (last_accessed = now) WITHOUT an access_count bump — exact
    parity with ``_boost_neighbors`` (memory_system.py:242-260)."""
    sal = jnp.minimum(state.salience.at[rows].add(boost), 1.0)
    return state.replace(
        salience=sal,
        last_accessed=state.last_accessed.at[rows].set(now),
    )


arena_boost, arena_boost_copy = _donated_pair(_arena_boost)


def _arena_merge_touch(state: ArenaState, rows: jax.Array,
                       candidate_salience: jax.Array, now: jax.Array) -> ArenaState:
    """Dedup-merge bookkeeping: salience = max(salience, candidate),
    access_count += 1, last_accessed = now (memory_system.py:732-741)."""
    sal = state.salience.at[rows].max(candidate_salience)
    return state.replace(
        salience=sal,
        access_count=state.access_count.at[rows].add(1),
        last_accessed=state.last_accessed.at[rows].set(now),
    )


arena_merge_touch, arena_merge_touch_copy = _donated_pair(_arena_merge_touch)


def _arena_set_salience(state: ArenaState, rows: jax.Array, values: jax.Array) -> ArenaState:
    return state.replace(salience=state.salience.at[rows].set(values))


arena_set_salience, arena_set_salience_copy = _donated_pair(_arena_set_salience)


def _arena_set_parentage(state: ArenaState, rows: jax.Array, is_super: jax.Array) -> ArenaState:
    return state.replace(is_super=state.is_super.at[rows].set(is_super))


arena_set_parentage, arena_set_parentage_copy = _donated_pair(_arena_set_parentage)


def _arena_restore_access(state: ArenaState, rows: jax.Array,
                          access_count: jax.Array,
                          last_accessed: jax.Array) -> ArenaState:
    """Reload path: ``arena_add`` zeroes access history for fresh inserts;
    restored rows get their persisted counters back so importance-ranked
    eviction keeps favoring heavily-used memories across restarts."""
    return state.replace(
        access_count=state.access_count.at[rows].set(access_count),
        last_accessed=state.last_accessed.at[rows].set(last_accessed),
    )


arena_restore_access, arena_restore_access_copy = _donated_pair(_arena_restore_access)


def _arena_decay(state: ArenaState, tenant: jax.Array, rate: jax.Array,
                 floor: jax.Array) -> ArenaState:
    """Asymptotic salience decay toward ``floor``:  s' = floor + (s-floor)(1-rate).

    Tenant-masked and vectorized over the whole arena (reference loops per
    node of the current user's graph, ``memory_shard.py:64-77``)."""
    s = state.salience
    decayed = floor + (s - floor) * (1.0 - rate)
    mask = state.alive & (state.tenant_id == tenant)
    return state.replace(salience=jnp.where(mask, decayed, s))


arena_decay, arena_decay_copy = _donated_pair(_arena_decay)


# ---------------------------------------------------------------------------
# Retrieval / scoring kernels
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("super_filter",))
def arena_mask(state: ArenaState, tenant: jax.Array,
               super_filter: int = 0) -> jax.Array:
    """The retrieval row mask: alive ∧ tenant ∧ super-node filter. Shared by
    ``arena_search`` (single-chip) and the shard_map mesh searcher
    (core/index.py) so tenant-isolation semantics live in one place."""
    mask = state.alive & (state.tenant_id == tenant)
    if super_filter == 1:
        mask = mask & state.is_super
    elif super_filter == -1:
        mask = mask & ~state.is_super
    return mask


@functools.partial(jax.jit, static_argnames=("k", "super_filter", "impl"))
def arena_search(
    state: ArenaState,
    query: jax.Array,      # [d] or [Q, d]
    tenant: jax.Array,     # scalar i32
    k: int,
    super_filter: int = 0,  # 0: any, 1: only super nodes, -1: exclude super
    impl: str = "auto",     # "auto" | "xla" | "pallas"
    cold: Optional[jax.Array] = None,  # [cap+1] bool residency column
) -> Tuple[jax.Array, jax.Array]:
    """Masked cosine top-k over the whole arena. Replaces
    ``LanceDBStore.search_nodes`` (vector_store.py:132-140) AND the super-node
    fast-path scan (memory_system.py:464-470) — same kernel, different mask.

    Dispatch (all static at trace time): big block-aligned arenas on TPU
    take the blocked Pallas kernel — it streams the matrix through VMEM
    with per-block top-k, so no [Q, N] f32 score tensor ever lands in HBM
    (4 GB per 1k queries at 1M rows) and the final sort runs over
    nblocks·k candidates instead of N. (An earlier "1.6× faster" claim
    came from a broken clock — the tunneled backend acks dispatch on
    block_until_ready, r4 post-mortem; on this rig per-call latency is
    round-trip-dominated and the two impls measure equal. The HBM-traffic
    advantage is structural.) Everything else takes the one-matmul XLA
    path. Callers with a row-sharded arena must pass ``impl="xla"``
    (pallas_call has no GSPMD partitioning rule) or go through the
    shard_map composition in ``ops/topk.make_sharded_topk``."""
    q = normalize(jnp.atleast_2d(query)).astype(state.emb.dtype)
    lmask = arena_mask(state, tenant, super_filter)
    # Tier residency (ISSUE 18 parity fix): a DENSE-layout demote zero-fills
    # the master row but leaves it alive, so without this mask a cold row
    # would surface as a score-0.0 top-k tail — while the PAGED layout frees
    # the slot and `_pool_mask` drops it. Masking cold rows to -inf here
    # makes the two layouts bit-identical (no-op under paging).
    if cold is not None:
        lmask = lmask & ~cold
    # paged arenas scan the emb POOL: the logical mask re-indexes into pool
    # space (free slots masked off) and survivors map back to logical rows
    mask = _pool_mask(state, lmask)
    n, nq = state.emb.shape[0], q.shape[0]
    use_pallas = impl == "pallas" or (
        impl == "auto"
        and jax.default_backend() in ("tpu", "axon")
        and n >= PALLAS_TOPK_MIN_ROWS and n % TOPK_BLOCK == 0
        and nq <= 128 and k <= 16)
    if use_pallas:
        from lazzaro_tpu.ops.pallas_topk import masked_topk_arena
        top_scores, top_rows = masked_topk_arena(state.emb, mask, q, k)
    else:
        def chunk(q_c):
            scores = nt_dot(q_c, state.emb)                       # [C, pool]
            return jax.lax.top_k(jnp.where(mask[None, :], scores, NEG_INF), k)

        # Big query fleets stream through [512, cap+1] tiles inside ONE
        # dispatch (HBM-bounded; one host round trip for the whole batch).
        top_scores, top_rows = chunked_map(chunk, q)
    top_rows = _pool_to_logical(state, top_rows)
    if query.ndim == 1:
        return top_scores[0], top_rows[0]
    return top_scores, top_rows


def _arena_link_candidates_multi(
    state: ArenaState,
    new_rows: jax.Array,   # [B] i32 rows to find candidates FOR (whole batch)
    excl_rows: jax.Array,  # [E] i32 rows excluded as candidates (ALL new rows)
    tenant: jax.Array,
    k: int,
    shard_modes: Tuple[int, ...] = (1, 0),
    # 0: any shard, 1: same shard only, -1: other shards only
) -> Tuple[jax.Array, ...]:
    """For each new node, top-k most similar existing nodes (excluding self
    and other new rows), for SEVERAL shard modes in one pass. One batched
    matmul replaces reference hot loops #2/#3 (``memory_system.py:797-836``
    within-shard, ``:838-891`` cross-shard) — and because every mode is just
    a different mask over the SAME score matrix, the arena is streamed from
    HBM once and the [C, cap+1] scores are re-masked per mode: two modes
    cost one matmul, not two (the matmul dominates the top-k).

    Batches past QUERY_CHUNK stream through ``lax.map`` in [512, cap+1] f32
    tiles INSIDE this one dispatch — the tile bounds HBM at 1M rows, and a
    whole-conversation link batch costs ONE host round trip (the tunneled
    backend charges ~70 ms per readback, r4 measurement; the old host-side
    chunk loop paid it per 512 rows). Returns ``(scores, rows)`` pairs
    flattened in ``shard_modes`` order."""
    lmask = state.alive & (state.tenant_id == tenant) & ~state.is_super
    # exclude the new rows themselves from candidates
    excl = jnp.zeros((_nrows(state),), bool).at[excl_rows].set(True)
    mask = _pool_mask(state, lmask & ~excl)       # pool-space scan mask
    shard_pool = _pool_col(state, state.shard_id)

    def chunk(rows_c):
        q = state.emb[_phys(state, rows_c)]       # [C, d]
        scores = nt_dot(q, state.emb)             # [C, pool]
        same = None
        outs = []
        for sm in shard_modes:
            full_mask = mask[None, :]
            if sm != 0:
                if same is None:
                    same = (state.shard_id[rows_c][:, None]
                            == shard_pool[None, :])
                full_mask = full_mask & (same if sm == 1 else ~same)
            s, r = jax.lax.top_k(jnp.where(full_mask, scores, NEG_INF), k)
            outs.extend((s, _pool_to_logical(state, r)))
        return tuple(outs)

    return chunked_map(chunk, new_rows)


arena_link_candidates_multi = jax.jit(
    _arena_link_candidates_multi, static_argnames=("k", "shard_modes"))


def arena_link_candidates(
    state: ArenaState,
    new_rows: jax.Array,
    excl_rows: jax.Array,
    tenant: jax.Array,
    k: int,
    shard_mode: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Single-mode view of ``arena_link_candidates_multi``."""
    s, r = arena_link_candidates_multi(state, new_rows, excl_rows, tenant, k,
                                       (shard_mode,))
    return s, r


@jax.jit
def arena_importance(state: ArenaState, now: jax.Array,
                     w_sal: jax.Array, w_acc: jax.Array, w_rec: jax.Array) -> jax.Array:
    """importance = salience*w1 + min(1, access/10)*w2 + 1/(1+days_old)*w3.

    Parity with ``_enforce_buffer_limit`` scoring (memory_system.py:544-549):
    days_old counts from last_accessed. Computed for every row in one pass;
    dead rows get +inf so they never rank as eviction candidates."""
    days_old = jnp.maximum(now - state.last_accessed, 0.0) / 86400.0
    imp = (state.salience * w_sal
           + jnp.minimum(1.0, state.access_count.astype(jnp.float32) / 10.0) * w_acc
           + 1.0 / (1.0 + days_old) * w_rec)
    return jnp.where(state.alive, imp, jnp.inf)


@functools.partial(jax.jit, static_argnames=("k",))
def arena_evict_candidates(state: ArenaState, tenant: jax.Array, now: jax.Array,
                           w_sal: jax.Array, w_acc: jax.Array, w_rec: jax.Array,
                           k: int) -> Tuple[jax.Array, jax.Array]:
    """Rows of the k least-important alive, non-super nodes for a tenant."""
    imp = arena_importance(state, now, w_sal, w_acc, w_rec)
    mask = state.alive & (state.tenant_id == tenant) & ~state.is_super
    imp = jnp.where(mask, imp, jnp.inf)
    neg_scores, rows = jax.lax.top_k(-imp, k)
    return -neg_scores, rows


@jax.jit
def arena_mean_embedding(state: ArenaState, rows: jax.Array) -> jax.Array:
    """Mean of child embeddings → super-node centroid (memory_system.py:916-917).
    Sentinel-padded rows contribute zero weight."""
    valid = (rows < state.capacity)[:, None].astype(jnp.float32)
    embs = state.emb[_phys(state, rows)].astype(jnp.float32) * valid
    mean = embs.sum(0) / jnp.maximum(valid.sum(), 1.0)
    return normalize(mean)


# ---------------------------------------------------------------------------
# Edge kernels
# ---------------------------------------------------------------------------


def _edges_add(state: EdgeState, slots: jax.Array, src: jax.Array, tgt: jax.Array,
               weight: jax.Array, co: jax.Array, now: jax.Array,
               tenant: jax.Array, live: jax.Array) -> EdgeState:
    """``live`` is False for sentinel-padded positions so the scratch slot
    never becomes an alive phantom edge."""
    return state.replace(
        src=state.src.at[slots].set(src),
        tgt=state.tgt.at[slots].set(tgt),
        weight=state.weight.at[slots].set(jnp.clip(weight, 0.0, 1.0)),
        co=state.co.at[slots].set(co),
        last_updated=state.last_updated.at[slots].set(now),
        alive=state.alive.at[slots].set(live),
        tenant_id=state.tenant_id.at[slots].set(tenant),
    )


edges_add, edges_add_copy = _donated_pair(_edges_add)


def _edges_reinforce(state: EdgeState, slots: jax.Array, bump: jax.Array,
                     now: jax.Array) -> EdgeState:
    """Existing edge: weight += bump (capped at 1.0), co_occurrence += 1
    (parity: memory_shard.py:42-52)."""
    w = jnp.minimum(state.weight.at[slots].add(bump), 1.0)
    return state.replace(
        weight=w,
        co=state.co.at[slots].add(1),
        last_updated=state.last_updated.at[slots].set(now),
    )


edges_reinforce, edges_reinforce_copy = _donated_pair(_edges_reinforce)


def _edges_decay(state: EdgeState, tenant: jax.Array, rate: jax.Array) -> EdgeState:
    """weight *= (1 - rate) for the tenant's alive edges (memory_shard.py:64-71)."""
    mask = state.alive & (state.tenant_id == tenant)
    w = jnp.where(mask, state.weight * (1.0 - rate), state.weight)
    return state.replace(weight=w)


edges_decay, edges_decay_copy = _donated_pair(_edges_decay)


def _prune_compact(weak: jax.Array, prune_cap: int) -> Tuple[jax.Array, jax.Array]:
    """Prefix-sum compaction of a weak-edge mask into a dense [prune_cap]
    vector of slot indices (-1 padded, ascending slot order) — the PR 3
    pool-compactor idiom pointed at prune victims, so host cleanup walks
    O(pruned) slots instead of re-scanning every live edge. Returns
    ``(ok, slots)`` where ``ok`` is the mask of edges actually compacted
    (== ``weak`` whenever ``prune_cap`` covers the weak count; the host
    sizes it off the live-edge count so the cap can never bind — edges
    past it stay alive and are caught by the overflow counter rather
    than silently leaking from the host mirror)."""
    weak = jax.lax.optimization_barrier(weak)
    pos = jnp.cumsum(weak.astype(jnp.int32)) - 1
    ok = weak & (pos < prune_cap)
    slot_ids = jnp.arange(weak.shape[0], dtype=jnp.int32)
    buf = jnp.full((prune_cap + 1,), -1, jnp.int32)
    buf = buf.at[jnp.where(ok, jnp.minimum(pos, prune_cap - 1),
                           prune_cap)].set(slot_ids)
    return ok, buf[:prune_cap]


def _edges_prune(state: EdgeState, tenant: jax.Array, threshold: jax.Array,
                 prune_cap: int) -> Tuple[EdgeState, jax.Array]:
    """Kill the tenant's edges with weight < threshold; returns
    ``(state, pruned_slots)`` where ``pruned_slots`` is the compacted
    [prune_cap] slot-index vector (-1 padded) from :func:`_prune_compact`."""
    weak = state.alive & (state.tenant_id == tenant) & (state.weight < threshold)
    ok, slots = _prune_compact(weak, prune_cap)
    return state.replace(alive=state.alive & ~ok), slots


edges_prune, edges_prune_copy = _donated_pair(
    _edges_prune, static_argnames=("prune_cap",))


def _decay_fused(arena: ArenaState, edges: EdgeState, tenant: jax.Array,
                 rate: jax.Array, floor: jax.Array
                 ) -> Tuple[ArenaState, EdgeState]:
    """Classic per-tenant decay, arena + edges folded into ONE dispatch
    (ISSUE 19 satellite): the old ``MemoryIndex.decay`` paid two device
    round trips per tenant per tick — same arithmetic, half the dispatches.
    Bitwise identical to ``_arena_decay`` ∘ ``_edges_decay``."""
    return (_arena_decay(arena, tenant, rate, floor),
            _edges_decay(edges, tenant, rate))


decay_fused, decay_fused_copy = _donated_pair(_decay_fused, donate=(0, 1))


def _edges_delete_for_nodes(state: EdgeState, node_rows: jax.Array) -> EdgeState:
    """Remove all edges touching any of ``node_rows`` (eviction cleanup,
    memory_system.py:560-570). node_rows is a small sentinel-padded batch, so
    a broadcast membership test [E, B] is one fused VPU pass."""
    touched_src = (state.src[:, None] == node_rows[None, :]).any(axis=1)
    touched_tgt = (state.tgt[:, None] == node_rows[None, :]).any(axis=1)
    return state.replace(alive=state.alive & ~(touched_src | touched_tgt))


edges_delete_for_nodes, edges_delete_for_nodes_copy = _donated_pair(
    _edges_delete_for_nodes)


# ---------------------------------------------------------------------------
# Device-side lifecycle: decay + prune + archive as ONE all-tenant sweep
# ---------------------------------------------------------------------------

# Counter leaves riding the packed-payload tail (ISSUE 19): decayed arena
# rows, decayed edges, pruned edges, weak-edge total, prune overflow flag.
LIFECYCLE_TAIL = 5


def _bitcast_f32(x: jax.Array) -> jax.Array:
    """int32 → f32 bit-pattern view so int sections can ride the single
    flat f32 payload; the host views them back with ``.view(np.int32)``."""
    return jax.lax.bitcast_convert_type(x.astype(jnp.int32), jnp.float32)


def _lifecycle_core(arena: ArenaState, edges: EdgeState, passes: jax.Array,
                    verdict_tids: jax.Array, rate: jax.Array,
                    floor: jax.Array, threshold: jax.Array, now: jax.Array,
                    w_sal: jax.Array, w_acc: jax.Array, w_rec: jax.Array,
                    prune_cap: int, archive_k: int):
    """Shard-local body of the all-tenant maintenance sweep. Both the
    single-chip jit and the ``make_lifecycle_sharded`` shard_map trace this
    one function, so single-chip/mesh parity is structural.

    ``passes`` is a dense [Tc] per-tenant-id owed-decay-pass table (0 =
    tenant not swept this tick) — the per-row pass count is one gather by
    ``tenant_id``, honoring the ``decay_pass`` stamping discipline from
    ``MemorySystem`` without an O(cap × tenants) mask product.

    Bit-parity with the classic host loop: the steady-state single owed
    pass multiplies by ``(1 - rate)`` ONCE — the exact expression
    ``_arena_decay`` / ``_edges_decay`` evaluate — and only catch-up ticks
    (p > 1, e.g. after a deferred sweep) take the closed form
    ``(1 - rate) ** p`` that the checkpoint-load replay already uses.
    Per-tenant stages are disjoint by tenant mask, so fusing all tenants
    into one scatter is order-equivalent to the classic per-tenant loop."""
    tc = passes.shape[0]

    def owed(tid):
        inb = (tid >= 0) & (tid < tc)
        return jnp.where(inb, passes[jnp.clip(tid, 0, tc - 1)], 0)

    # (a) closed-form salience decay over every swept tenant's live rows
    p = owed(arena.tenant_id)
    d_mask = arena.alive & (p > 0)
    base = arena.salience - floor
    stepped = floor + base * (1.0 - rate)
    closed = floor + base * jnp.power(1.0 - rate, p.astype(jnp.float32))
    arena = arena.replace(salience=jnp.where(
        d_mask, jnp.where(p == 1, stepped, closed), arena.salience))

    # (b) edge-weight decay, then weak-edge prune on the DECAYED weights
    # (classic order: decay tick precedes the prune pass)
    ep = owed(edges.tenant_id)
    e_mask = edges.alive & (ep > 0)
    w = edges.weight
    w_new = jnp.where(
        e_mask,
        jnp.where(ep == 1, w * (1.0 - rate),
                  w * jnp.power(1.0 - rate, ep.astype(jnp.float32))),
        w)
    weak = e_mask & (w_new < threshold)
    ok, pruned_slots = _prune_compact(weak, prune_cap)
    edges = edges.replace(weight=w_new, alive=edges.alive & ~ok)

    # (c) importance verdicts on the decayed salience (classic order:
    # ``evict_candidates`` after the decay tick) — bottom-k per verdict
    # tenant, the archive-means-demote feed for the TierPump
    imp = jax.lax.optimization_barrier(
        arena_importance(arena, now, w_sal, w_acc, w_rec))

    def bottom_k(t):
        mask = (arena.alive & (arena.tenant_id == t) & ~arena.is_super
                & (t >= 0))
        neg_scores, rows = jax.lax.top_k(
            -jnp.where(mask, imp, jnp.inf), archive_k)
        return -neg_scores, rows

    v_imps, v_rows = jax.vmap(bottom_k)(verdict_tids)
    counters = jnp.stack([
        d_mask.sum().astype(jnp.int32),
        e_mask.sum().astype(jnp.int32),
        ok.sum().astype(jnp.int32),
        weak.sum().astype(jnp.int32),
        (weak & ~ok).any().astype(jnp.int32),
    ])
    return arena, edges, v_imps, v_rows, pruned_slots, counters


def _lifecycle_payload(v_imps, v_rows, pruned_slots, counters) -> jax.Array:
    """ONE flat f32 payload so the whole sweep comes home in ONE transfer:
    [Tv·k] verdict importances | [Tv·k] verdict rows (bitcast) |
    [prune_cap] pruned slots (bitcast) | [LIFECYCLE_TAIL] counters
    (bitcast). Static offsets — the host slices by shape, no header."""
    return jnp.concatenate([
        v_imps.astype(jnp.float32).reshape(-1),
        _bitcast_f32(v_rows).reshape(-1),
        _bitcast_f32(pruned_slots),
        _bitcast_f32(counters),
    ])


def _lifecycle_sweep(arena: ArenaState, edges: EdgeState, passes: jax.Array,
                     verdict_tids: jax.Array, rate: jax.Array,
                     floor: jax.Array, threshold: jax.Array, now: jax.Array,
                     w_sal: jax.Array, w_acc: jax.Array, w_rec: jax.Array,
                     prune_cap: int, archive_k: int
                     ) -> Tuple[ArenaState, EdgeState, jax.Array]:
    """ONE donated dispatch + ONE packed readback: salience decay, edge
    decay + weak-edge prune (compacted victim slots ride the readback like
    the paged free-list leaves), and per-tenant bottom-k archive verdicts
    — over the live arena and edge pool for ALL tenants at once."""
    arena, edges, v_imps, v_rows, pruned_slots, counters = _lifecycle_core(
        arena, edges, passes, verdict_tids, rate, floor, threshold, now,
        w_sal, w_acc, w_rec, prune_cap, archive_k)
    return arena, edges, _lifecycle_payload(v_imps, v_rows, pruned_slots,
                                            counters)


lifecycle_sweep, lifecycle_sweep_copy = _donated_pair(
    _lifecycle_sweep, donate=(0, 1),
    static_argnames=("prune_cap", "archive_k"))


def _lifecycle_sweep_read(arena: ArenaState, edges: EdgeState,
                          passes: jax.Array, verdict_tids: jax.Array,
                          rate: jax.Array, floor: jax.Array,
                          threshold: jax.Array, now: jax.Array,
                          w_sal: jax.Array, w_acc: jax.Array,
                          w_rec: jax.Array, prune_cap: int, archive_k: int
                          ) -> jax.Array:
    """Read-only twin: payload only, states untouched (dry-run / gauges)."""
    return _lifecycle_sweep(arena, edges, passes, verdict_tids, rate, floor,
                            threshold, now, w_sal, w_acc, w_rec,
                            prune_cap, archive_k)[2]


lifecycle_sweep_read = jax.jit(_lifecycle_sweep_read,
                               static_argnames=("prune_cap", "archive_k"))


# ---------------------------------------------------------------------------
# Fused ingest: the whole per-conversation mutation sequence in ONE program
# ---------------------------------------------------------------------------


def _shadow_scatter(shadow, rows: jax.Array, emb_stored: jax.Array):
    """Incremental int8 serving-shadow maintenance INSIDE the fused ingest
    program: quantize exactly the rows being written (``emb_stored`` is the
    normalized arena-dtype embedding the node scatter stores) and scatter
    their codes + scales into the shadow — an O(batch) update instead of
    the host-side O(arena) lazy re-quantize the dirty flag used to force.
    ``shadow`` is ``(q8, scale)`` or None (int8 serving off / shadow not
    yet built); None passes through untouched."""
    if shadow is None:
        return None
    from lazzaro_tpu.ops.quant import quantize_rows

    q8, scale = shadow
    q_new, s_new = quantize_rows(emb_stored)
    return (q8.at[rows].set(q_new), scale.at[rows].set(s_new))


def _pq_scatter(pq, rows: jax.Array, emb_stored: jax.Array):
    """Incremental PQ code maintenance INSIDE the fused ingest program
    (ISSUE 16, the PQ twin of ``_shadow_scatter``): encode exactly the
    rows being written against the FROZEN codebook — m small
    [B, dsub]×[dsub, 256] matmuls, the same argmax ``ops.pq.encode_pq``
    runs over the whole arena — and scatter their m-byte codes in place.
    An O(batch) update instead of the offline full re-encode the old
    ``_pq_dirty`` flag forced; codebook drift is handled by the rare
    ``ivf_maintenance`` re-seed, never here. ``pq`` is ``(book_cent
    [m, 256, dsub] f32, codes [cap+1, m] u8)`` or None (PQ serving off /
    no published pack); None passes through untouched. Sentinel-padded
    rows encode into the sentinel row — harmless, every serving scan
    masks it."""
    if pq is None:
        return None
    book_cent, codes = pq
    m, _, dsub = book_cent.shape
    x = emb_stored.astype(jnp.float32).reshape(rows.shape[0], m, dsub)
    cnorm = jnp.sum(book_cent * book_cent, axis=2)              # [m, 256]
    scores = (2.0 * jnp.einsum("nmd,mkd->nmk", x, book_cent)
              - cnorm[None, :, :])                              # [B, m, 256]
    new = jnp.argmax(scores, axis=2).astype(jnp.uint8)
    return (book_cent, codes.at[rows].set(new))


def _ivf_online_assign(cent: jax.Array, qf: jax.Array, live: jax.Array
                       ) -> jax.Array:
    """Cluster assignment of the accepted batch against the CURRENT
    centroids — the marginal [B, C] matmul the online-IVF tentpole rides
    on (the same dispatch already streams the [B, rows] dedup/link score
    matrix, so C ≈ √rows extra columns are noise). Ties resolve to the
    lowest centroid id (``argmax``), matching ``ops.ivf._assign_device``.
    Dead/padded facts route to bucket C (one past the end — every scatter
    built on it drops)."""
    cs = jnp.dot(qf, cent.T, preferred_element_type=jnp.float32)  # [B, C]
    assign = jnp.argmax(cs, axis=1).astype(jnp.int32)
    return jnp.where(live, assign, cent.shape[0])


def _ivf_online_update(ivf, rows: jax.Array, qf: jax.Array,
                       live: jax.Array, eta_scale: jax.Array):
    """Online IVF maintenance INSIDE the fused ingest program (ISSUE 12):
    score the accepted facts against the centroids, append each live row
    to its cluster's member table via the same prefix-sum compaction idiom
    as the gated link insert (an accepted append whose position lands past
    the cluster capacity scatters out of bounds — dropped, never a phantom
    write — and its readback position reports -1 so the host re-inserts
    it into the exact-scan extras, exactly like link-pool overflow), then
    blend a bounded mini-batch spherical k-means step into the centroids:
    ``cent_c ← normalize((1 - η_c)·cent_c + η_c·mean(batch_c))`` with
    ``η_c = eta_scale · b_c / (count_c + b_c)`` — the classic mini-batch
    step, so a mature cluster barely moves per batch and the update term
    is O(B·C·d), not O(rows).

    ``ivf = (cent [C, d] f32 normalized, members [C, M] i32 -1-padded,
    counts [C] i32 live-prefix occupancy)``; all three are donated state.
    Returns ``(new_ivf, assign [B] (-1 = not live), pos [B] (member slot,
    -1 = overflowed/not live), (overflow, occupancy, appends, shift_ppm)
    int32 scalars for the readback tail)``."""
    cent, members, counts = ivf
    C, M = members.shape
    b = rows.shape[0]
    a = _ivf_online_assign(cent, qf, live)                 # [B], dead -> C
    assign = jnp.where(live, a, -1)
    # append position = cluster occupancy + rank among EARLIER live facts
    # of the same cluster (intra-batch prefix sum, the PR 3 compaction
    # idiom applied per cluster)
    same = (a[:, None] == a[None, :]) & live[None, :]
    rank = (same & jnp.tri(b, k=-1, dtype=bool)).sum(axis=1)
    counts_pre = counts
    pos = jnp.where(live, counts_pre[jnp.where(live, a, 0)]
                    + rank.astype(jnp.int32), -1)
    ok = live & (pos >= 0) & (pos < M)
    a_s = jnp.where(ok, a, C)                              # OOB -> dropped
    p_s = jnp.where(ok, pos, M)
    members = members.at[a_s, p_s].set(rows.astype(jnp.int32))
    counts = counts_pre.at[a_s].add(ok.astype(jnp.int32))
    # mini-batch centroid step (overflowed facts still inform the mean —
    # they are real cluster mass even though their member slot spilled)
    sums = jnp.zeros((C, qf.shape[1]), jnp.float32
                     ).at[a].add(jnp.where(live[:, None], qf, 0.0))
    bc = jnp.zeros((C,), jnp.float32).at[a].add(live.astype(jnp.float32))
    tot = counts_pre.astype(jnp.float32)
    eta = jnp.clip(eta_scale * bc / jnp.maximum(tot + bc, 1.0), 0.0, 1.0)
    mean = sums / jnp.maximum(bc[:, None], 1.0)
    prop = cent * (1.0 - eta[:, None]) + mean * eta[:, None]
    nrm = jnp.linalg.norm(prop, axis=1, keepdims=True)
    moved = (bc[:, None] > 0) & (nrm > 1e-9)
    new_cent = jnp.where(moved, prop / jnp.maximum(nrm, 1e-9), cent)
    # staleness proxy riding the readback tail: total angular drift of the
    # touched centroids this batch, in parts-per-million of cosine
    shift = jnp.where(bc > 0, 1.0 - (new_cent * cent).sum(axis=1), 0.0)
    tail = (
        (live & ~ok).any().astype(jnp.int32),              # overflow flag
        jnp.minimum(counts.sum(), jnp.int32(C * M)).astype(jnp.int32),
        ok.sum().astype(jnp.int32),                        # appends
        jnp.clip(jnp.round(shift.sum() * 1e6), 0,
                 2 ** 30).astype(jnp.int32),               # shift ppm
    )
    pos_rb = jnp.where(ok, pos, -1)
    return (new_cent, members, counts), assign, pos_rb, tail


# Number of wide + tail readback leaves _ivf_online_update appends to the
# fused ingest readback (assign, pos, overflow, occupancy, appends, shift).
IVF_INGEST_TAIL = 6


def _ivf_drop_rows(ivf_members: jax.Array, drop_map: jax.Array
                   ) -> jax.Array:
    """Scrub rows out of the member tables (tier demotion: a demoted row's
    exact master embedding is zeroed, so its member slot must not feed the
    exact in-kernel rescore — the full-corpus int8 shadow coarse path
    covers it instead). Slots become -1 holes; occupancy counts are NOT
    rewound (append positions stay monotone until the next re-seed packs
    the table). O(C·M) elementwise — runs on the background demote path,
    never a serving query."""
    safe = jnp.maximum(ivf_members, 0)
    hit = (ivf_members >= 0) & drop_map[safe]
    return jnp.where(hit, -1, ivf_members)


ivf_members_drop = jax.jit(_ivf_drop_rows, donate_argnums=(0,))
ivf_members_drop_copy = jax.jit(_ivf_drop_rows)


def _ingest_fused(
    arena: ArenaState,
    edges: EdgeState,
    shadow,                  # (q8 [cap+1, d] i8, scale [cap+1] f32) or None
    ivf,                     # (cent [C,d], members [C,M], counts [C]) or None
    pq,                      # (book_cent [m,256,dsub], codes [cap+1,m]) or None
    ptable,                  # PageTable or None (dense arena)
    rows: jax.Array,         # [B] i32 new-node rows, sentinel-padded
    emb: jax.Array,          # [B, d]
    salience: jax.Array,     # [B] f32
    timestamp: jax.Array,    # [B] f32
    type_id: jax.Array,      # [B] i32
    shard_id: jax.Array,     # [B] i32
    tenant_id: jax.Array,    # [B] i32
    is_super: jax.Array,     # [B] bool
    touch_rows: jax.Array,   # [M] i32 dedup-merge rows, sentinel-padded
    touch_sal: jax.Array,    # [M] f32 candidate saliences
    chain_slots: jax.Array,  # [C] i32 edge slots, sentinel-padded
    chain_src: jax.Array,    # [C] i32 arena rows (-1 padding)
    chain_tgt: jax.Array,    # [C] i32
    chain_w: jax.Array,      # [C] f32
    link_pool: jax.Array,    # [P+1] i32 compaction slot pool (last = sentinel)
    pool_len: jax.Array,     # scalar i32: REAL slots at the pool head
    now: jax.Array,
    tenant: jax.Array,
    link_gate: jax.Array,
    link_scale: jax.Array,
    ivf_eta: jax.Array,      # centroid learning-rate scale (inert w/o ivf)
    k: int,
    shard_modes: Tuple[int, ...] = (1, 0),
) -> Tuple[ArenaState, EdgeState, object, object, object,
           Tuple[jax.Array, ...]]:
    """The per-conversation ingest sequence — ``arena_add`` →
    ``arena_merge_touch`` → ``arena_link_candidates_multi`` → gated
    ``edges_add`` — fused into ONE donated device program.

    The host hands the kernel a POOL of edge slots covering the worst case
    (every potential (mode, new-row, candidate) link); the gate (score >
    link_gate, valid non-sentinel query row, not a duplicate of an earlier
    mode's hit) is evaluated ON DEVICE and accepted edges are prefix-sum
    compacted into the pool's leading slots — rejected candidates never
    write the edge arena, and the host reclaims the untouched pool suffix
    as one slice. Host round trips per conversation drop from ~4
    dispatches + 1 readback to 1 + 1: the returned per-mode ``(scores,
    cands, pos)`` triples (pos = pool position, -1 = rejected) are the
    single packed readback the host needs for id decode and edge
    bookkeeping. With int8 serving on, the shadow codes for the written
    rows update in the same program (``_shadow_scatter``). With online IVF
    tables threaded (``ivf``), the written rows are scored against the
    centroids, appended to their clusters' member tables, and the
    mini-batch centroid step runs — all inside this same dispatch
    (``_ivf_online_update``; the extra readback leaves trail the link
    counters). With PQ serving on, the written rows' m-byte codes are
    re-encoded against the frozen codebook in the same program
    (``_pq_scatter``) — no extra dispatches, no extra readback leaves.
    With a paged arena (``ptable`` threaded), every valid row binds a pool
    slot via the prefix-sum free-stack pop FIRST (``_page_alloc``), and
    the pop count / post-pop stack depth / overflow flag ride the SAME
    packed readback as trailing leaves (``PAGE_INGEST_TAIL``) — paging
    adds an int32 gather to the scatters and scans, never a dispatch."""
    qf = normalize(emb)
    emb_stored = qf.astype(arena.emb.dtype)
    valid_q = rows < arena.capacity        # sentinel-padded rows make no edges
    page_tail = ()
    if ptable is not None:
        arena, ptable, pops, p_over = _page_alloc(arena, ptable, rows,
                                                  valid_q)
        page_tail = (pops, ptable.free_top, p_over.astype(jnp.int32))
    arena = _arena_add(arena, rows, emb, salience, timestamp, type_id,
                       shard_id, tenant_id, is_super)
    shadow = _shadow_scatter(shadow, rows, emb_stored)
    pq = _pq_scatter(pq, rows, emb_stored)
    arena = _arena_merge_touch(arena, touch_rows, touch_sal, now)
    link_flat = _arena_link_candidates_multi(arena, rows, rows, tenant, k,
                                             shard_modes)
    n_chain = chain_slots.shape[0]
    edges = _edges_add(edges, chain_slots, chain_src, chain_tgt, chain_w,
                       jnp.ones((n_chain,), jnp.int32), now, tenant,
                       chain_src >= 0)
    edges, outs = _gated_link_insert(edges, link_flat, link_pool, pool_len,
                                     rows, valid_q, now, tenant, link_gate,
                                     link_scale, shard_modes)
    if ivf is not None:
        leaf = outs[0].shape
        ivf, a_rb, p_rb, tail = _ivf_online_update(ivf, rows, qf, valid_q,
                                                   ivf_eta)
        outs = outs + tuple(
            jnp.broadcast_to(x[:, None], leaf) for x in (a_rb, p_rb)
        ) + tuple(jnp.broadcast_to(t, leaf) for t in tail)
    if page_tail:
        leaf = outs[0].shape
        outs = outs + tuple(jnp.broadcast_to(t, leaf) for t in page_tail)
    return arena, edges, shadow, ivf, pq, ptable, outs


def _gated_link_insert(edges, link_flat, link_pool, pool_len, src_rows,
                       valid_q, now, tenant, link_gate, link_scale,
                       shard_modes):
    """Device-gated similarity-edge insert with prefix-sum slot compaction
    (ROADMAP ceiling #2), shared by the fused ingest kernels: per shard
    mode the gate verdict (gate pass, valid source row, not already
    inserted by an earlier mode) is evaluated on device, then accepted
    edges across ALL modes pack into a dense PREFIX of the host-provided
    slot pool via a cumulative sum over the gate mask. Rejected candidates
    scatter to the sentinel slot — the edge arena never sees speculative
    dead writes — and ONE ``_edges_add`` covers every mode. The readback
    triples carry each candidate's pool position (-1 = rejected) so the
    host can register accepted keys and reclaim the unused pool suffix as
    a single contiguous slice.

    ``pool_len`` (device scalar: the count of REAL slots at the pool's
    head — the tail up to the jit bucket is sentinel padding) lets the
    host size the pool by its measured link-acceptance rate instead of
    the 2·B·k worst case (``MemoryConfig.link_accept_hint``): an accepted
    edge whose prefix-sum position lands past ``pool_len`` scatters to
    the sentinel slot (never a phantom write), its readback position
    still carries the TRUE prefix position so the host can identify and
    re-insert exactly the overflowed edges, and the trailing overflow
    flag in the packed readback tells the host a retry is needed at
    all."""
    # The link-scan top-k results feed BOTH the gate logic here and the
    # packed readback; the barrier stops XLA from splitting those consumers
    # into duplicate full-arena sorts (same fix as _search_fused_scan).
    link_flat = jax.lax.optimization_barrier(link_flat)
    pool_cap = link_pool.shape[0] - 1      # last pool entry = sentinel slot
    per_mode = []
    prior = []                             # (cands, live) of earlier modes
    for mi in range(len(shard_modes)):
        scores, cand = link_flat[2 * mi], link_flat[2 * mi + 1]
        live = (scores > link_gate) & valid_q[:, None]
        for p_cand, p_live in prior:
            # an (src, cand) pair an earlier mode already inserted must not
            # become a second live edge row (mode masks overlap: every
            # same-shard candidate is also an any-shard candidate)
            dup = (cand[:, :, None] == p_cand[:, None, :]) & p_live[:, None, :]
            live = live & ~dup.any(-1)
        prior.append((cand, live))
        per_mode.append((scores, cand, live))
    live_all = jnp.concatenate([lv.reshape(-1) for _, _, lv in per_mode])
    pos_all = jnp.cumsum(live_all.astype(jnp.int32)) - 1
    ok = live_all & (pos_all < jnp.minimum(pool_len, pool_cap))
    slots = link_pool[jnp.where(ok, jnp.minimum(pos_all, pool_cap - 1),
                                pool_cap)]
    overflow = (live_all & ~ok).any()
    src_all = jnp.concatenate([
        jnp.broadcast_to(src_rows[:, None], c.shape).reshape(-1)
        for _, c, _ in per_mode])
    cand_all = jnp.concatenate([c.reshape(-1) for _, c, _ in per_mode])
    w_all = jnp.concatenate([(s * link_scale).reshape(-1)
                             for s, _, _ in per_mode])
    edges = _edges_add(edges, slots, src_all, cand_all, w_all,
                       jnp.ones((live_all.size,), jnp.int32), now, tenant,
                       ok)
    outs = []
    off = 0
    for scores, cand, live in per_mode:
        m = live.size
        pos_m = jnp.where(live.reshape(-1), pos_all[off:off + m],
                          -1).reshape(live.shape)
        outs.extend((scores, cand, pos_m))
        off += m
    # trailing counter leaves, broadcast to the common readback leaf shape
    # so the whole tuple still fetches in ONE packed transfer (ISSUE 6:
    # the overflow flag, the device-gated accepted-link count, and the
    # pool-slot occupancy ride the readback — bytes, not dispatches)
    leaf = per_mode[0][2].shape
    accepted = live_all.sum().astype(jnp.int32)
    pool_used = jnp.minimum(accepted, jnp.minimum(pool_len, pool_cap))
    outs.append(jnp.broadcast_to(overflow.astype(jnp.int32), leaf))
    outs.append(jnp.broadcast_to(accepted, leaf))
    outs.append(jnp.broadcast_to(pool_used.astype(jnp.int32), leaf))
    return edges, tuple(outs)


PAGE_INGEST_TAIL = 3  # trailing paged leaves: pops, free_top, overflow

ingest_fused, ingest_fused_copy = _donated_pair(
    _ingest_fused, donate=(0, 1, 2, 3, 4, 5),
    static_argnames=("k", "shard_modes"))


# ---------------------------------------------------------------------------
# Fused ingest WITH device-side dedup: the probe that decides merge-vs-insert
# runs against the pre-add arena INSIDE the same dispatch (ROADMAP item 2),
# so ingest is one round trip end-to-end.
#
# The scan and resolve bodies below are the SHARD-LOCAL CORES of the pod
# ingest program too (``make_ingest_fused_sharded``): the single-chip kernel
# and the distributed kernel trace the same functions, so parity is
# structural — the PR 5 recipe applied to the write path (ISSUE 9).
# ---------------------------------------------------------------------------


def _ingest_scan_core(state: ArenaState, qd: jax.Array, q_shard: jax.Array,
                      probe_excl: jax.Array, link_excl: jax.Array,
                      tenant: jax.Array, k: int,
                      shard_modes: Tuple[int, ...],
                      chunk: int = QUERY_CHUNK,
                      with_probe: bool = True):
    """The whole-arena ingest scan: dedup-probe top-1 plus the per-mode
    link top-k over ONE score matrix — the probe and every link mode are
    just different masks, so the arena streams from HBM once per ingest
    batch (the pre-refactor kernel paid two full matmuls: probe, then the
    post-add link scan; the exclusion mask makes the pre-add scan
    equivalent — the batch's own rows are excluded as candidates either
    way, and no other row's embedding changes between the two points).

    ``qd`` is each fact's normalized arena-dtype embedding (exactly the
    bytes the node scatter stores, so scores match a post-add gather of
    the live rows bit for bit). ``probe_excl`` masks the sentinel scratch
    row out of the probe — the classic host probe drops the id-less
    sentinel at decode; in-kernel the mask does (a previous batch's
    padding can leave the sentinel alive, and a dedup hit on it would
    silently eat a fact). ``link_excl`` additionally masks the batch's
    own rows out of the link candidates. Shard-local by construction:
    single-chip callers pass the whole arena, the sharded program passes
    each chip's local slice with localized exclusion masks — and, because
    a chip's slice is n× narrower, an n×-wider ``chunk`` at the SAME
    [chunk × rows] f32 tile budget (fewer, denser gemms; chunking never
    changes any per-row output, so parity is unaffected). Returns the
    flat tuple ``(p_s [B,1], p_r [B,1], s_mode, r_mode, ...)``;
    ``with_probe=False`` (the non-dedup sharded program) skips the probe
    group — the link modes alone, post-add semantics — and then
    ``probe_excl`` only shapes the link mask."""
    pmask = _pool_mask(state, state.alive & (state.tenant_id == tenant)
                       & ~state.is_super & ~probe_excl)
    lmask = pmask & ~_pool_mask(state, link_excl)
    shard_pool = _pool_col(state, state.shard_id)

    def body(q_c, qs_c):
        scores = nt_dot(q_c, state.emb)               # [C, pool rows] f32
        outs = []
        if with_probe:
            s, r = jax.lax.top_k(
                jnp.where(pmask[None, :], scores, NEG_INF), 1)
            outs.extend((s, _pool_to_logical(state, r)))
        same = None
        for sm in shard_modes:
            m = lmask[None, :]
            if sm != 0:
                if same is None:
                    same = qs_c[:, None] == shard_pool[None, :]
                m = m & (same if sm == 1 else ~same)
            s, r = jax.lax.top_k(jnp.where(m, scores, NEG_INF), k)
            outs.extend((s, _pool_to_logical(state, r)))
        return tuple(outs)

    return chunked_map_multi(body, (qd, q_shard), chunk=chunk)


def _dedup_resolve(qf: jax.Array, rows: jax.Array, valid: jax.Array,
                   chain_gid: jax.Array, p_s: jax.Array, p_r: jax.Array,
                   dedup_gate: jax.Array, cap: int):
    """Sequential duplicate resolution shared by the single-chip and the
    sharded fused ingest (replicated compute on the pod — the inputs are
    the replicated batch plus the MERGED probe top-1): intra-batch gram
    picks the best match among EARLIER valid facts (sentinel padding rows
    share one unit vector and must never match anything), the scan blends
    it with the pre-add probe, chains targets (a dup-of-a-dup merges into
    the surviving node), and tracks the chain predecessor (last LIVE fact
    of the same shard group — a dup in the middle bridges its neighbors,
    exactly like the host path that skips it). Returns ``(target [B] i32,
    dup [B] bool, chain_src [B] i32)``."""
    b = rows.shape[0]
    gram = nt_dot(qf, qf)
    tril = jnp.where(jnp.tri(b, k=-1, dtype=bool) & valid[None, :],
                     gram, NEG_INF)
    g_j = jnp.argmax(tril, axis=1)
    g_s = tril[jnp.arange(b), g_j]

    def step(carry, i):
        target, dup, last = carry
        use_g = g_s[i] > p_s[i]
        best_s = jnp.where(use_g, g_s[i], p_s[i])
        best_t = jnp.where(use_g, target[g_j[i]], p_r[i])
        is_dup = valid[i] & (best_s > dedup_gate)
        target = target.at[i].set(jnp.where(is_dup, best_t, rows[i]))
        dup = dup.at[i].set(is_dup)
        live_i = valid[i] & ~is_dup
        gid = jnp.maximum(chain_gid[i], 0)
        prev = jnp.where(chain_gid[i] >= 0, last[gid], -1)
        src_i = jnp.where(live_i & (prev >= 0), prev, -1)
        last = last.at[gid].set(jnp.where(live_i, rows[i], last[gid]))
        return (target, dup, last), src_i

    init = (jnp.full((b,), cap, jnp.int32), jnp.zeros((b,), bool),
            jnp.full((b,), -1, jnp.int32))
    (target, dup, _), chain_src = jax.lax.scan(step, init, jnp.arange(b))
    return target, dup, chain_src


def _ingest_dedup_fused(
    arena: ArenaState,
    edges: EdgeState,
    shadow,                  # (q8 [cap+1, d] i8, scale [cap+1] f32) or None
    ivf,                     # (cent [C,d], members [C,M], counts [C]) or None
    pq,                      # (book_cent [m,256,dsub], codes [cap+1,m]) or None
    ptable,                  # PageTable or None (dense arena)
    rows: jax.Array,         # [B] i32 candidate row per fact, sentinel-padded
    emb: jax.Array,          # [B, d]
    salience: jax.Array,     # [B] f32 (doubles as the merge-touch candidate)
    timestamp: jax.Array,    # [B] f32
    type_id: jax.Array,      # [B] i32
    shard_id: jax.Array,     # [B] i32
    tenant_id: jax.Array,    # [B] i32
    is_super: jax.Array,     # [B] bool
    chain_gid: jax.Array,    # [B] i32 densified shard-group id, -1 padding
    chain_slots: jax.Array,  # [B] i32 edge slot per fact, sentinel-padded
    link_pool: jax.Array,    # [P+1] i32 compaction slot pool (last = sentinel)
    pool_len: jax.Array,     # scalar i32: REAL slots at the pool head
    now: jax.Array,
    tenant: jax.Array,
    dedup_gate: jax.Array,   # cosine threshold; > 1.0 disables dedup
    chain_w: jax.Array,
    link_gate: jax.Array,
    link_scale: jax.Array,
    ivf_eta: jax.Array,      # centroid learning-rate scale (inert w/o ivf)
    k: int,
    shard_modes: Tuple[int, ...] = (1, 0),
) -> Tuple[ArenaState, EdgeState, object, object, object,
           Tuple[jax.Array, ...]]:
    """``_ingest_fused`` plus the dedup probe the classic pipeline pays a
    separate dispatch+readback for: masked top-1 against the PRE-add arena
    and an intra-batch gram resolve duplicate facts ON DEVICE, duplicate
    rows are scattered to the sentinel (never become alive nodes), their
    merge targets get the merge-touch, and chain edges link consecutive
    LIVE facts per shard group (a dup in the middle bridges its
    neighbors, exactly like the host path that skips it). The packed
    readback adds ``(dup, target, chain_src)`` so the host can finish id
    bookkeeping — still ONE dispatch + ONE readback per mega-batch."""
    cap = arena.capacity
    b = rows.shape[0]
    valid = rows < cap
    qf = normalize(emb)                    # f32 — intra gram parity w/ host
    qd = qf.astype(arena.emb.dtype)        # arena dtype — probe parity

    # Paged arena: bind a pool slot to EVERY valid row up front — dup
    # verdicts aren't known until the resolve, and allocating for the
    # whole batch keeps the free-stack op replayable on the host mirror
    # at dispatch time (LIFO order parity under concurrent demote pushes).
    # Dup rows keep their slots bound-but-dead: their logical rows are
    # never alive, the host reuses them first (its row free-list is LIFO
    # too), so over-residency is bounded by one batch.
    page_tail = ()
    if ptable is not None:
        arena, ptable, pops, p_over = _page_alloc(arena, ptable, rows,
                                                  valid)
        page_tail = (pops, ptable.free_top, p_over.astype(jnp.int32))

    # ONE whole-arena score matrix feeds BOTH the pre-add dedup probe and
    # the per-mode link scans (_ingest_scan_core): the probe sees the same
    # visibility the classic host probe has (its batch insert also lands
    # after the probe), and the link candidates exclude the batch's own
    # rows — so the pre-add scan is exactly the post-add-with-exclusion
    # scan the unfused path runs, at HALF the HBM traffic.
    probe_excl = jnp.arange(cap + 1) == cap
    link_excl = (jnp.zeros((cap + 1,), bool).at[rows].set(True)
                 | probe_excl)
    flat = _ingest_scan_core(arena, qd, shard_id, probe_excl, link_excl,
                             tenant, k, shard_modes)
    p_s, p_r = flat[0][:, 0], flat[1][:, 0]
    link_flat = flat[2:]

    target, dup, chain_src = _dedup_resolve(qf, rows, valid, chain_gid,
                                            p_s, p_r, dedup_gate, cap)

    live_new = valid & ~dup
    add_rows = jnp.where(live_new, rows, cap)
    arena = _arena_add(arena, add_rows, emb, salience, timestamp, type_id,
                       shard_id, tenant_id, is_super)
    shadow = _shadow_scatter(shadow, add_rows, qd)
    pq = _pq_scatter(pq, add_rows, qd)
    touch_rows = jnp.where(dup, target, cap)
    arena = _arena_merge_touch(arena, touch_rows, salience, now)
    chain_live = chain_src >= 0
    edges = _edges_add(edges, chain_slots, chain_src, rows,
                       jnp.broadcast_to(chain_w, (b,)),
                       jnp.ones((b,), jnp.int32), now, tenant, chain_live)
    edges, outs = _gated_link_insert(edges, link_flat, link_pool, pool_len,
                                     rows, live_new, now, tenant, link_gate,
                                     link_scale, shard_modes)
    if ivf is not None:
        # Online IVF maintenance (ISSUE 12): the SAME dispatch scores the
        # surviving facts against the centroids, appends them to their
        # clusters' member tables, and blends the mini-batch centroid
        # step — assignments are never stale behind an offline rebuild.
        # Duplicates never append (live_new gates them); merge targets
        # already sit in their clusters.
        ivf, a_rb, p_rb, tail = _ivf_online_update(ivf, rows, qf, live_new,
                                                   ivf_eta)
        outs = outs + tuple(
            jnp.broadcast_to(x[:, None], (b, k)) for x in (a_rb, p_rb)
        ) + tuple(jnp.broadcast_to(t, (b, k)) for t in tail)
    if page_tail:
        outs = outs + tuple(jnp.broadcast_to(t, (b, k)) for t in page_tail)
    # [B] verdicts broadcast to [B, k] so every readback leaf has one shape
    # and the host fetches them all in ONE packed transfer
    wide = tuple(jnp.broadcast_to(a[:, None], (b, k))
                 for a in (dup.astype(jnp.int32), target, chain_src))
    return arena, edges, shadow, ivf, pq, ptable, wide + outs


ingest_dedup_fused, ingest_dedup_fused_copy = _donated_pair(
    _ingest_dedup_fused, donate=(0, 1, 2, 3, 4, 5),
    static_argnames=("k", "shard_modes"))


# ---------------------------------------------------------------------------
# Pod-scale fused INGEST (ISSUE 9): the whole ``ingest_dedup_fused`` program
# — dedup probe, intra-batch gram resolve, node scatter, merge touch, both
# link scans, gated edge insert with prefix-sum pool compaction, incremental
# int8 shadow update — composed with the device mesh as ONE distributed
# shard_map dispatch + ONE packed readback. The write-path mirror of
# ``make_fused_sharded`` (PR 5):
#
# - Every arena column, the edge arena, and the int8 shadow are row-sharded
#   over the mesh axis; the fact batch (rows, embeddings, metadata, edge
#   slots, link pool) is replicated.
# - Each chip runs the SAME shard-local scan core the single-chip kernel
#   traces (``_ingest_scan_core`` — dedup-probe top-1 + per-mode link top-k
#   over one local score matrix), and the ONLY cross-chip traffic is ONE
#   all_gather merging probe + every link mode's candidates in a single
#   grouped combine (``ops.topk.sharded_grouped_topk_merge``).
# - The dedup resolve, gate verdicts, and prefix-sum pool compaction are
#   then REPLICATED arithmetic on the merged lists (identical on every
#   chip), and all writes land owner-chip-local: row/slot index vectors are
#   localized per chip with non-owned entries routed one-past-the-end —
#   XLA drops out-of-bounds scatter updates, the PR 5 boost-scatter trick —
#   so the node scatter, merge touch, shadow update, chain edges, and the
#   compacted link insert are all shard-local writes through the SAME
#   mutation kernels (``_arena_add`` / ``_arena_merge_touch`` /
#   ``_shadow_scatter`` / ``_edges_add`` / ``_gated_link_insert``) the
#   single-chip program traces. Parity is structural.
# - The packed readback (dup verdicts, per-mode candidate triples, overflow
#   flag, accepted-link count, pool occupancy) is replicated output — the
#   host fetches it once, exactly like the single-chip readback.
# ---------------------------------------------------------------------------


class IngestShardedKernels(NamedTuple):
    """The jit entry points one ``make_ingest_fused_sharded`` call builds:
    the donated distributed ingest program and its copy-on-write twin (for
    callers that cannot prove sole ownership of the states — also the
    surface the peak-HBM gauge AOT-lowers, since it has no donation).
    Tests and bench wrap the caller's dispatch hook to count calls — each
    call is exactly ONE distributed dispatch."""

    ingest: Callable
    ingest_copy: Callable


def make_ingest_fused_sharded(mesh, axis: str, *, k: int,
                              shard_modes: Tuple[int, ...] = (1, 0),
                              with_shadow: bool = False,
                              with_ivf: bool = False,
                              with_pq: bool = False,
                              dedup: bool = True
                              ) -> IngestShardedKernels:
    """Build the distributed fused ingest program for ``mesh``.

    Call signature (``with_shadow=False``, ``dedup=True``)::

        ingest(arena, edges, rows [B], emb [B,d], salience [B],
               timestamp [B], type_id [B], shard_id [B], tenant_id [B],
               is_super [B], chain_gid [B], chain_slots [B],
               link_pool [P+1], pool_len, now, tenant, dedup_gate,
               chain_w, link_gate, link_scale, ivf_eta)
            -> (arena, edges, outs)

    with ``arena``/``edges`` row-sharded over ``axis`` and every batch
    input replicated; ``outs`` is bit-compatible with the single-chip
    ``ingest_dedup_fused`` readback tuple (3 wide dup/target/chain leaves,
    3 per shard mode, 3 trailing counters — all [B, k], fetched with
    ``utils.batching.fetch_packed`` in ONE transfer). ``rows``,
    ``chain_slots``, and ``link_pool`` carry GLOBAL row / edge-slot ids;
    the global sentinel row/slot is the LAST row/slot of the last shard,
    so the single-chip sentinel-routing convention carries over unchanged.
    ``with_shadow=True`` inserts ``(q8 [rows,d] i8, scale [rows] f32)``
    row-sharded args after ``edges`` and returns them updated — the
    incremental int8 shadow maintenance riding the same dispatch.

    ``with_ivf=True`` (ISSUE 12) additionally threads the ONLINE IVF
    tables: ``cent [C, d]`` replicated, ``members [n, C, M]`` stacked
    per shard with LOCAL row indices (the same layout ``make_fused_
    sharded`` mode="ivf" serves from, so the live ingest-maintained
    tables feed the pod serving kernel directly), and ``counts [n, C]``
    REPLICATED per-(shard, cluster) occupancy — replicated so every chip
    computes identical append positions / overflow verdicts and the
    readback stays replicated arithmetic without a second collective.
    The centroid scores ride the existing grouped all_gather as one more
    candidate group (each chip scores its ``C/n`` slice of the
    replicated centroid block and contributes its local top-1; when
    ``C % n != 0`` every chip scores the full block and the merge is a
    no-op), member appends land owner-chip-local through the same OOB
    scatter routing as every other write, and the mini-batch centroid
    step is replicated arithmetic. Readback grows the same 6 trailing
    leaves as the single-chip kernel (assign, member pos, overflow,
    occupancy, appends, centroid shift).

    ``with_pq=True`` (ISSUE 16) threads the PQ pack after the IVF
    tables: ``book_cent [m, 256, dsub]`` replicated (frozen between
    re-seeds) and ``codes [rows, m]`` u8 row-sharded with the master.
    The accepted rows' codes are re-encoded against the codebook and
    scattered owner-chip-local through the same localized row vector as
    the node scatter (``_pq_scatter`` — replicated arithmetic, local
    write). No extra readback leaves, no extra collectives.

    ``dedup=False`` builds the NON-dedup program instead (ROADMAP
    residual: ``ingest_batch`` under a mesh) — the ``_ingest_fused``
    semantics composed with the mesh: explicit merge-touch rows and
    chain triples, post-add link scan, no probe group in the merge::

        ingest(arena, edges, rows [B], emb [B,d], salience, timestamp,
               type_id, shard_id, tenant_id, is_super, touch_rows [M],
               touch_sal [M], chain_slots [C], chain_src [C],
               chain_tgt [C], chain_w [C], link_pool [P+1], pool_len,
               now, tenant, link_gate, link_scale, ivf_eta)
            -> (arena, edges, outs)

    with ``outs`` bit-compatible with the single-chip ``ingest_fused``
    readback (3 leaves per shard mode + 3 trailing counters).

    ``ingest`` donates the state arguments (zero-copy shard-local
    scatters); ``ingest_copy`` is the non-donating twin."""
    from jax.sharding import PartitionSpec as P

    from lazzaro_tpu.ops.topk import sharded_grouped_topk_merge
    from lazzaro_tpu.utils.compat import shard_map

    shard_modes = tuple(shard_modes)
    n_modes = len(shard_modes)
    n_shards = mesh.shape[axis]

    def _localize(idx, base, n_local):
        """Global index vector → this chip's local indices; non-owned
        entries route to ``n_local`` (one past the end — OOB scatter
        updates are dropped, never wrapped)."""
        loc = idx - base
        return jnp.where((loc >= 0) & (loc < n_local), loc, n_local)

    def _split_state(rest):
        shadow = ivf = pq = None
        if with_shadow:
            shadow, rest = (rest[0], rest[1]), rest[2:]
        if with_ivf:
            # members arrive stacked [1, C, M] inside shard_map
            ivf, rest = (rest[0], rest[1][0], rest[2]), rest[3:]
        if with_pq:
            pq, rest = (rest[0], rest[1]), rest[2:]
        return shadow, ivf, pq, rest

    def _cent_group(ivf, qf, shard):
        """This chip's centroid-slice top-1 as one more merge candidate
        group: (score [B,1], GLOBAL centroid id [B,1])."""
        cent = ivf[0]
        C = cent.shape[0]
        if C % n_shards == 0 and n_shards > 1:
            c_loc = C // n_shards
            cent_l = jax.lax.dynamic_slice_in_dim(
                cent, shard * c_loc, c_loc, 0)
            s1, i1 = jax.lax.top_k(
                jnp.dot(qf, cent_l.T, preferred_element_type=jnp.float32),
                1)
            return s1, (i1 + shard * c_loc).astype(jnp.int32)
        s1, i1 = jax.lax.top_k(
            jnp.dot(qf, cent.T, preferred_element_type=jnp.float32), 1)
        return s1, i1.astype(jnp.int32)

    def _ivf_sharded_update(ivf, rows, qf, live, assign, ivf_eta, shard,
                            local_n):
        """The mesh twin of ``_ivf_online_update``: append positions,
        overflow verdicts, occupancy counts and the centroid step are
        REPLICATED arithmetic (counts carries every shard's occupancy);
        only the member-table scatter is owner-chip-local. Member
        positions are per-(shard, cluster) — each chip's table has its
        own dense prefix, so single-chip and mesh positions differ while
        the served candidate UNION stays identical (overflow aside)."""
        cent, mem_l, counts = ivf
        C = cent.shape[0]
        M = mem_l.shape[1]
        b = rows.shape[0]
        owner = jnp.clip(rows // local_n, 0, n_shards - 1)
        a = jnp.where(live, assign, C)
        same = ((a[:, None] == a[None, :])
                & (owner[:, None] == owner[None, :]) & live[None, :])
        rank = (same & jnp.tri(b, k=-1, dtype=bool)).sum(axis=1)
        counts_pre = counts
        cnt = counts_pre[jnp.where(live, owner, 0),
                         jnp.where(live, a, 0)]
        pos = jnp.where(live, cnt + rank.astype(jnp.int32), -1)
        ok = live & (pos >= 0) & (pos < M)
        o_s = jnp.where(ok, owner, n_shards)
        a_s = jnp.where(ok, a, C)
        counts = counts_pre.at[o_s, a_s].add(ok.astype(jnp.int32))
        mine = ok & (owner == shard)
        a_m = jnp.where(mine, a, C)
        p_m = jnp.where(mine, pos, M)
        mem_l = mem_l.at[a_m, p_m].set(
            (rows - shard * local_n).astype(jnp.int32))
        # centroid step: replicated, with the GLOBAL per-cluster mass
        # (sum over shards) as the learning-rate denominator — the same
        # total the single-chip kernel uses
        sums = jnp.zeros((C, qf.shape[1]), jnp.float32
                         ).at[a].add(jnp.where(live[:, None], qf, 0.0))
        bc = jnp.zeros((C,), jnp.float32).at[a].add(live.astype(
            jnp.float32))
        tot = counts_pre.sum(axis=0).astype(jnp.float32)
        eta = jnp.clip(ivf_eta * bc / jnp.maximum(tot + bc, 1.0), 0.0, 1.0)
        mean = sums / jnp.maximum(bc[:, None], 1.0)
        prop = cent * (1.0 - eta[:, None]) + mean * eta[:, None]
        nrm = jnp.linalg.norm(prop, axis=1, keepdims=True)
        new_cent = jnp.where((bc[:, None] > 0) & (nrm > 1e-9),
                             prop / jnp.maximum(nrm, 1e-9), cent)
        shift = jnp.where(bc > 0, 1.0 - (new_cent * cent).sum(axis=1), 0.0)
        tail = (
            (live & ~ok).any().astype(jnp.int32),
            jnp.minimum(counts.sum(), jnp.int32(n_shards * C * M)
                        ).astype(jnp.int32),
            ok.sum().astype(jnp.int32),
            jnp.clip(jnp.round(shift.sum() * 1e6), 0,
                     2 ** 30).astype(jnp.int32),
        )
        return ((new_cent, mem_l, counts), jnp.where(live, assign, -1),
                jnp.where(ok, pos, -1), tail)

    def _ivf_outs(ivf_new, a_rb, p_rb, tail, b):
        return tuple(
            jnp.broadcast_to(x[:, None], (b, k)) for x in (a_rb, p_rb)
        ) + tuple(jnp.broadcast_to(t, (b, k)) for t in tail)

    def _pack_state(arena, edges, shadow, ivf, pq, outs):
        out = (arena, edges)
        if with_shadow:
            out = out + (shadow[0], shadow[1])
        if with_ivf:
            out = out + (ivf[0], ivf[1][None, :, :], ivf[2])
        if with_pq:
            out = out + (pq[0], pq[1])
        return out + (outs,)

    def _local(arena, edges, *rest):
        shadow, ivf, pq, rest = _split_state(rest)
        (rows, emb, salience, timestamp, type_id, shard_id_v, tenant_id_v,
         is_super, chain_gid, chain_slots, link_pool, pool_len, now, tenant,
         dedup_gate, chain_w, link_gate, link_scale, ivf_eta) = rest
        shard = jax.lax.axis_index(axis)
        local_n = arena.emb.shape[0]
        cap = n_shards * local_n - 1           # GLOBAL capacity / sentinel
        local_e = edges.src.shape[0]
        b = rows.shape[0]
        k_l = max(1, min(k, local_n))
        valid = rows < cap
        qf = normalize(emb)
        qd = qf.astype(arena.emb.dtype)

        # Shard-local scan: the SAME core the single-chip kernel traces,
        # over this chip's rows — exclusion masks localized (the global
        # sentinel lives on the LAST shard only).
        row_base = shard * local_n
        rows_l = _localize(rows, row_base, local_n)
        probe_excl = jnp.arange(local_n) == (cap - row_base)
        link_excl = (jnp.zeros((local_n,), bool).at[rows_l].set(True)
                     | probe_excl)
        # each chip's slice is n× narrower than the whole arena, so the
        # scan streams n×-wider query chunks at the SAME f32 tile budget
        # the single-chip QUERY_CHUNK bounds — fewer, denser gemms
        flat = _ingest_scan_core(arena, qd, shard_id_v, probe_excl,
                                 link_excl, tenant, k_l, shard_modes,
                                 chunk=min(QUERY_CHUNK * n_shards, 4096))
        # ONE all_gather merges the probe AND every link mode's local
        # candidates (grouped combine; candidate ids globalized first, so
        # masked/garbage entries route to the global sentinel row) — and
        # with online IVF the centroid scores ride the SAME collective as
        # a fourth candidate group.
        cat_s = [flat[2 * g] for g in range(1 + n_modes)]
        cat_i = [_globalize_rows(flat[2 * g + 1], flat[2 * g], shard,
                                 local_n, n_shards)
                 for g in range(1 + n_modes)]
        widths = [1] + [k_l] * n_modes
        ks = [1] + [k] * n_modes
        if ivf is not None:
            c_s, c_i = _cent_group(ivf, qf, shard)
            cat_s.append(c_s)
            cat_i.append(c_i)
            widths.append(1)
            ks.append(1)
        merged = sharded_grouped_topk_merge(
            axis, jnp.concatenate(cat_s, axis=1),
            jnp.concatenate(cat_i, axis=1), widths=widths, ks=ks)
        merged = jax.lax.optimization_barrier(merged)
        p_s, p_r = merged[0][0][:, 0], merged[0][1][:, 0]
        link_flat = tuple(a for pair in merged[1 + 0:1 + n_modes]
                          for a in pair)
        assign = merged[-1][1][:, 0] if ivf is not None else None

        # Dedup resolve + gate logic are replicated arithmetic from here —
        # every chip computes identical verdicts, then scatters ONLY the
        # rows/slots it owns.
        target, dup, chain_src = _dedup_resolve(qf, rows, valid, chain_gid,
                                                p_s, p_r, dedup_gate, cap)
        live_new = valid & ~dup
        add_rows = jnp.where(live_new, rows, cap)
        add_l = _localize(add_rows, row_base, local_n)
        arena = _arena_add(arena, add_l, emb, salience, timestamp, type_id,
                           shard_id_v, tenant_id_v, is_super)
        shadow = _shadow_scatter(shadow, add_l, qd)
        pq = _pq_scatter(pq, add_l, qd)
        touch_l = _localize(jnp.where(dup, target, cap), row_base, local_n)
        arena = _arena_merge_touch(arena, touch_l, salience, now)

        slot_base = shard * local_e
        chain_live = chain_src >= 0
        chain_l = _localize(chain_slots, slot_base, local_e)
        edges = _edges_add(edges, chain_l, chain_src, rows,
                           jnp.broadcast_to(chain_w, (b,)),
                           jnp.ones((b,), jnp.int32), now, tenant,
                           chain_live)
        # The compacting gated insert runs UNCHANGED — it only ever touches
        # slots through the pool array, so handing it a pool whose entries
        # are pre-localized (non-owned → OOB) makes every accepted edge an
        # owner-chip-local write while positions/readback stay global.
        pool_l = _localize(link_pool, slot_base, local_e)
        edges, outs = _gated_link_insert(edges, link_flat, pool_l, pool_len,
                                         rows, live_new, now, tenant,
                                         link_gate, link_scale, shard_modes)
        if ivf is not None:
            ivf, a_rb, p_rb, tail = _ivf_sharded_update(
                ivf, rows, qf, live_new, assign, ivf_eta, shard, local_n)
            outs = outs + _ivf_outs(ivf, a_rb, p_rb, tail, b)
        wide = tuple(jnp.broadcast_to(a[:, None], (b, k))
                     for a in (dup.astype(jnp.int32), target, chain_src))
        return _pack_state(arena, edges, shadow, ivf, pq, wide + outs)

    def _local_plain(arena, edges, *rest):
        """The non-dedup program (``ingest_batch`` under a mesh): the
        SAME semantics as the single-chip ``_ingest_fused`` — node
        scatter, explicit merge touch, POST-add link scan per shard mode,
        explicit chain triples, gated compacted link insert — shard-local
        scans, one grouped all_gather, owner-chip writes."""
        shadow, ivf, pq, rest = _split_state(rest)
        (rows, emb, salience, timestamp, type_id, shard_id_v, tenant_id_v,
         is_super, touch_rows, touch_sal, chain_slots, chain_src,
         chain_tgt, chain_w, link_pool, pool_len, now, tenant, link_gate,
         link_scale, ivf_eta) = rest
        shard = jax.lax.axis_index(axis)
        local_n = arena.emb.shape[0]
        cap = n_shards * local_n - 1
        local_e = edges.src.shape[0]
        b = rows.shape[0]
        k_l = max(1, min(k, local_n))
        qf = normalize(emb)
        qd = qf.astype(arena.emb.dtype)
        row_base = shard * local_n
        rows_l = _localize(rows, row_base, local_n)
        arena = _arena_add(arena, rows_l, emb, salience, timestamp,
                           type_id, shard_id_v, tenant_id_v, is_super)
        shadow = _shadow_scatter(shadow, rows_l, qd)
        pq = _pq_scatter(pq, rows_l, qd)
        touch_l = _localize(touch_rows, row_base, local_n)
        arena = _arena_merge_touch(arena, touch_l, touch_sal, now)
        # post-add link scan, batch rows excluded as candidates — the
        # single-chip kernel's _arena_link_candidates_multi semantics
        # (no probe group, no sentinel exclusion: decode drops id-less
        # hits host-side exactly like the single-chip path)
        link_excl = jnp.zeros((local_n,), bool).at[rows_l].set(True)
        flat = _ingest_scan_core(arena, qd, shard_id_v,
                                 jnp.zeros((local_n,), bool), link_excl,
                                 tenant, k_l, shard_modes,
                                 chunk=min(QUERY_CHUNK * n_shards, 4096),
                                 with_probe=False)
        cat_s = [flat[2 * g] for g in range(n_modes)]
        cat_i = [_globalize_rows(flat[2 * g + 1], flat[2 * g], shard,
                                 local_n, n_shards)
                 for g in range(n_modes)]
        widths = [k_l] * n_modes
        ks = [k] * n_modes
        if ivf is not None:
            c_s, c_i = _cent_group(ivf, qf, shard)
            cat_s.append(c_s)
            cat_i.append(c_i)
            widths.append(1)
            ks.append(1)
        merged = sharded_grouped_topk_merge(
            axis, jnp.concatenate(cat_s, axis=1),
            jnp.concatenate(cat_i, axis=1), widths=widths, ks=ks)
        merged = jax.lax.optimization_barrier(merged)
        link_flat = tuple(a for pair in merged[:n_modes] for a in pair)
        assign = merged[-1][1][:, 0] if ivf is not None else None

        n_chain = chain_slots.shape[0]
        slot_base = shard * local_e
        chain_l = _localize(chain_slots, slot_base, local_e)
        edges = _edges_add(edges, chain_l, chain_src, chain_tgt, chain_w,
                           jnp.ones((n_chain,), jnp.int32), now, tenant,
                           chain_src >= 0)
        valid_q = rows < cap
        pool_l = _localize(link_pool, slot_base, local_e)
        edges, outs = _gated_link_insert(edges, link_flat, pool_l,
                                         pool_len, rows, valid_q, now,
                                         tenant, link_gate, link_scale,
                                         shard_modes)
        if ivf is not None:
            ivf, a_rb, p_rb, tail = _ivf_sharded_update(
                ivf, rows, qf, valid_q, assign, ivf_eta, shard, local_n)
            outs = outs + _ivf_outs(ivf, a_rb, p_rb, tail, b)
        return _pack_state(arena, edges, shadow, ivf, pq, outs)

    arena_specs = ArenaState(
        emb=P(axis, None), salience=P(axis), timestamp=P(axis),
        last_accessed=P(axis), access_count=P(axis), type_id=P(axis),
        shard_id=P(axis), tenant_id=P(axis), alive=P(axis),
        is_super=P(axis))
    edge_specs = EdgeState(
        src=P(axis), tgt=P(axis), weight=P(axis), co=P(axis),
        last_updated=P(axis), alive=P(axis), tenant_id=P(axis))
    shadow_specs = (P(axis, None), P(axis)) if with_shadow else ()
    # cent replicated, members stacked per shard, counts replicated
    ivf_specs = ((P(None, None), P(axis, None, None), P(None, None))
                 if with_ivf else ())
    # codebook replicated (frozen), codes row-sharded with the master
    pq_specs = ((P(None, None, None), P(axis, None)) if with_pq else ())
    if dedup:
        batch_specs = (
            P(None),        # rows
            P(None, None),  # emb
            P(None), P(None), P(None), P(None), P(None), P(None),  # per-fact
            P(None),        # chain_gid
            P(None),        # chain_slots
            P(None),        # link_pool
            P(), P(), P(), P(), P(), P(), P(), P(),  # pool_len..ivf_eta
        )
        n_out = 3 + 3 * n_modes + 3 + (IVF_INGEST_TAIL if with_ivf else 0)
        fn = _local
    else:
        batch_specs = (
            P(None),        # rows
            P(None, None),  # emb
            P(None), P(None), P(None), P(None), P(None), P(None),  # per-fact
            P(None), P(None),                  # touch_rows, touch_sal
            P(None), P(None), P(None), P(None),  # chain slot/src/tgt/w
            P(None),        # link_pool
            P(), P(), P(), P(), P(), P(),  # pool_len..ivf_eta scalars
        )
        n_out = 3 * n_modes + 3 + (IVF_INGEST_TAIL if with_ivf else 0)
        fn = _local_plain
    out_state = (arena_specs, edge_specs) + shadow_specs + ivf_specs \
        + pq_specs
    mapped = shard_map(
        fn, mesh=mesh,
        in_specs=(arena_specs, edge_specs) + shadow_specs + ivf_specs
        + pq_specs + batch_specs,
        out_specs=out_state + (tuple(P(None, None) for _ in range(n_out)),),
        check_vma=False)
    donate = tuple(range(2 + len(shadow_specs) + len(ivf_specs)
                         + len(pq_specs)))
    return IngestShardedKernels(
        ingest=jax.jit(mapped, donate_argnums=donate),
        ingest_copy=jax.jit(mapped))


# ---------------------------------------------------------------------------
# Fused retrieval: the per-chat-turn serving sequence — super-node gate +
# main-arena ANN + CSR neighbor gather + neighbor/access boosts — in ONE
# donated device program with ONE packed readback (the serving-side analog
# of ingest_fused; see ISSUE 2).
# ---------------------------------------------------------------------------


def _csr_neighbor_rows(state: ArenaState, csr_indptr: jax.Array,
                       csr_nbr: jax.Array, acc_rows: jax.Array,
                       tenant_c: jax.Array, max_nbr: int) -> jax.Array:
    """CSR neighbor gather for the access-boosted rows with per-query dedup
    (sentinel row's indptr slice is empty, so masked rows gather nothing).
    Shared by the exact and quantized fused serving scans."""
    cap = state.capacity
    start = csr_indptr[acc_rows]
    end = csr_indptr[acc_rows + 1]
    idx = start[:, :, None] + jnp.arange(max_nbr)[None, None, :]
    ok = idx < end[:, :, None]
    nbr = jnp.where(ok, csr_nbr[jnp.minimum(idx, csr_nbr.shape[0] - 1)],
                    -1)
    flat = nbr.reshape(nbr.shape[0], -1)                  # [C, M]
    m = flat.shape[1]
    safe = jnp.maximum(flat, 0)
    valid_n = ((flat >= 0) & state.alive[safe]
               & (state.tenant_id[safe] == tenant_c[:, None]))
    # per-query dedup (keep first occurrence): classic boosts a shared
    # neighbor ONCE per turn however many retrieved nodes touch it...
    dup = ((flat[:, :, None] == flat[:, None, :])
           & jnp.tri(m, k=-1, dtype=bool)[None, :, :]).any(-1)
    # ...and never boosts a node that was itself retrieved
    in_res = (flat[:, :, None] == acc_rows[:, None, :]).any(-1)
    return jnp.where(valid_n & ~dup & ~in_res, flat, cap)


def _ragged_topk_mask(ann_s: jax.Array, ann_r: jax.Array, k_c: jax.Array,
                      sentinel: int):
    """Per-query top-k boundary mask — the core ragged-serving move
    (ISSUE 7): the scan computed top-``K`` to the batch CEILING (a static
    kernel constant), and each query's own ``k`` arrives as DEVICE data
    (``k_c`` [C] i32). Positions at or past a query's k are routed to
    (NEG_INF, sentinel), so decode, the live-length counter, and the boost
    tail all see exactly the per-request result — one compiled kernel per
    (mode × geometry) serves any mix of request shapes. Equivalent to a
    per-query ``top_k(k_i)`` because the ceiling top-k is score-sorted."""
    col = jnp.arange(ann_s.shape[1])[None, :]
    live = col < k_c[:, None]
    return (jnp.where(live, ann_s, NEG_INF),
            jnp.where(live, ann_r, sentinel))


def _gate_and_boost_rows(state: ArenaState, csr_indptr, csr_nbr, gate_s,
                         gate_r, ann_s, ann_r, valid_c, tenant_c, gate_c,
                         boost_c, super_gate, cap_take: int, max_nbr: int,
                         cap_c=None):
    """The post-top-k tail both serving scans share: the device-side gate
    verdict, the access-boost row list, and the CSR neighbor gather.

    The hierarchy decision happens ON DEVICE: where the gate fires the host
    serves super-node children it alone knows, so the device must NOT boost
    the ANN rows (the host falls back to the classic boost for those
    queries — exact parity on the fast path).

    ``cap_c`` (optional [C] i32) is the ragged per-query retrieval cap:
    ``cap_take`` stays the STATIC slice ceiling, and each query's own cap
    masks within it, so one kernel serves mixed per-request caps."""
    cap = state.capacity
    fast = gate_c & (gate_s > super_gate)
    do_boost = boost_c & valid_c & ~fast                  # [C]
    take = (ann_s[:, :cap_take] > NEG_INF / 2) & do_boost[:, None]
    if cap_c is not None:
        take = take & (jnp.arange(cap_take)[None, :] < cap_c[:, None])
    acc_rows = jnp.where(take, ann_r[:, :cap_take], cap)  # [C, cap_take]
    nbr_rows = _csr_neighbor_rows(state, csr_indptr, csr_nbr, acc_rows,
                                  tenant_c, max_nbr)
    return fast, acc_rows, nbr_rows


def _exact_two_tier(state: ArenaState, q_c: jax.Array, tenant_c: jax.Array,
                    k_gate: int, k_ann: int):
    """Masked super top-``k_gate`` + masked main top-``k_ann`` over ONE
    score matrix (the arena streams from HBM once; the two retrieval tiers
    are just different masks, same trick as the multi-mode link scan).
    The shard-local core of the exact fused scan: single-chip callers pass
    the whole arena, the sharded program passes each chip's local slice.

    The trailing barrier is the PR 2 consumer-split fix: the top-k results
    feed BOTH the packed readback and the boost gather chain; without it
    XLA (CPU at least) splits the consumers into two full [C, cap] sorts —
    measured 2.4× on the whole fused program at 65k rows."""
    qn = normalize(q_c).astype(state.emb.dtype)
    scores = nt_dot(qn, state.emb)                        # [C, pool rows] f32
    alive_p = _pool_mask(state, state.alive)
    ten_p = _pool_col(state, state.tenant_id)
    alive_t = alive_p[None, :] & (ten_p[None, :] == tenant_c[:, None])
    sup = _pool_col(state, state.is_super)[None, :]
    gate_s, gate_r = jax.lax.top_k(
        jnp.where(alive_t & sup, scores, NEG_INF), k_gate)
    ann_s, ann_r = jax.lax.top_k(
        jnp.where(alive_t & ~sup, scores, NEG_INF), k_ann)
    gate_r = _pool_to_logical(state, gate_r)
    ann_r = _pool_to_logical(state, ann_r)
    return jax.lax.optimization_barrier((gate_s, gate_r, ann_s, ann_r))


# ---------------------------------------------------------------------------
# Semantic query cache (ISSUE 20): a SemanticRing probe riding INSIDE every
# fused serving kernel. The per-dispatch flow, all in the one program:
#
#   probe     — top-1 cosine of each (normalized) query against the ring,
#               masked by tenant / gate flag / mode / stored_k / nprobe and
#               the HOST-owned valid bits; >= threshold is a hit.
#   early-out — queries are stably sorted misses-first, and the family's
#               chunk function runs under a ``lax.while_loop`` over fixed
#               ``sem_block``-sized blocks with a DYNAMIC trip count of
#               ceil(n_miss / block): blocks past the miss prefix never
#               execute, so an 80%-hit batch pays ~20% of the scan FLOPs
#               while shapes stay static and the dispatch count stays ONE.
#   subst     — hit queries' gate/ann columns come from the cached entry
#               (re-masked at the query's own ragged k; the gate VERDICT is
#               recomputed against the current threshold); their boost rows
#               stay at the scatter sentinel — semantic hits defer boosts to
#               the host exactly like exact-cache hits.
#   writeback — the last R misses rotate into slots (head + rank) % R in
#               the same dispatch (LIFO, like the paged arena's free stack);
#               dropped writes scatter to the ring's scratch row.
#
# The sorted order is stable, so rank j IS the j-th miss in batch order —
# the host mirrors head/slot assignment from the readback's sem column
# alone, and ships the valid bits + head back in on the next dispatch.
# With the cache disabled (``sem=None``) nothing here traces; with the
# cache cold the sort is the identity permutation and every block runs, so
# results stay bit-identical to the cache-off program.
# ---------------------------------------------------------------------------


def _semantic_probe(ring: SemanticRing, sem_valid: jax.Array, qn: jax.Array,
                    tenant_q: jax.Array, q_valid: jax.Array,
                    gate_on_q: jax.Array, k_need: jax.Array,
                    npr_need: jax.Array, mode_id: jax.Array,
                    thresh: jax.Array):
    """Top-1 cosine probe of the ring. Returns (hit [Q] bool, slot [Q]
    i32). ``sem_valid`` is the host-owned [R] validity mask; an entry is
    eligible only when tenant, gate flag, mode, and nprobe match and its
    stored depth covers the query's k."""
    r = ring.slots
    sims = nt_dot(qn, ring.emb[:r])                        # [Q, R]
    ok = (sem_valid[:r] & (ring.stored_k[:r] > 0))[None, :]
    ok = ok & (ring.mode[:r][None, :] == mode_id)
    ok = ok & (ring.tenant[:r][None, :] == tenant_q[:, None])
    ok = ok & (ring.gate_on[:r][None, :] == gate_on_q[:, None])
    ok = ok & (ring.stored_k[:r][None, :] >= k_need[:, None])
    ok = ok & (ring.nprobe[:r][None, :] == npr_need[:, None])
    s = jnp.where(ok, sims, NEG_INF)
    slot = jnp.argmax(s, axis=1).astype(jnp.int32)
    hit = q_valid & (jnp.max(s, axis=1) >= thresh)
    return hit, slot


def _semantic_blocked(chunk_fn, arrays, n_miss: jax.Array, block: int,
                      capacity: int):
    """Run ``chunk_fn`` (any family's per-chunk closure) over the sorted
    batch in static ``block``-sized pieces with a dynamic trip count —
    only ceil(n_miss / block) blocks execute. Skipped queries keep safe
    fillers that mirror a fully-masked scan: NEG_INF scores, sentinel
    rows, False flags (the boost scatter's sentinel routing and decode's
    live counters treat them exactly like masked pad queries)."""
    b = arrays[0].shape[0]
    block = max(1, min(int(block), b))
    pad = (-b) % block
    if pad:
        arrays = tuple(
            jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
            for a in arrays)
    shapes = jax.eval_shape(chunk_fn, *tuple(a[:block] for a in arrays))

    def _fill(sd):
        shape = (b + pad,) + tuple(sd.shape[1:])
        if sd.dtype == jnp.bool_:
            return jnp.zeros(shape, jnp.bool_)
        if jnp.issubdtype(sd.dtype, jnp.floating):
            return jnp.full(shape, NEG_INF, sd.dtype)
        return jnp.full(shape, capacity, sd.dtype)

    outs0 = tuple(_fill(s) for s in shapes)
    n_run = (n_miss.astype(jnp.int32) + block - 1) // block

    def cond(carry):
        return carry[0] < n_run

    def body(carry):
        i, outs = carry[0], carry[1:]
        start = i * block
        sub = tuple(jax.lax.dynamic_slice_in_dim(a, start, block, 0)
                    for a in arrays)
        res = chunk_fn(*sub)
        outs = tuple(
            jax.lax.dynamic_update_slice_in_dim(o, r, start, 0)
            for o, r in zip(outs, res))
        return (i + 1,) + outs

    out = jax.lax.while_loop(cond, body,
                             (jnp.zeros((), jnp.int32),) + outs0)[1:]
    return tuple(o[:b] for o in out)


def _semantic_substitute(ring: SemanticRing, hit: jax.Array, slot: jax.Array,
                         gate_on_q: jax.Array, super_gate: jax.Array, outs,
                         k_q, rag_slack: int, capacity: int):
    """Splice cached results over the hit queries' (filler) scan outputs.
    The cached list is sliced to this kernel's static window and re-masked
    at the query's own ragged k (+slack for the tiered window); the gate
    verdict is recomputed against the CURRENT threshold so a runtime
    super-gate change can't serve a stale verdict."""
    gate_s, gate_r, ann_s, ann_r, fast = outs[:5]
    w = ann_s.shape[1]
    if ring.width < w:
        raise ValueError(
            f"semantic ring width {ring.width} < kernel window {w}; size "
            "the ring at the serving k ceiling (+slack for tiered modes)")
    c_gs = ring.gate_s[slot]
    c_gr = ring.gate_r[slot]
    c_as = ring.ann_s[slot, :w]
    c_ar = ring.ann_r[slot, :w]
    if k_q is not None:
        kf = jnp.minimum(k_q + rag_slack, w) if rag_slack else k_q
        c_as, c_ar = _ragged_topk_mask(c_as, c_ar, kf, capacity)
    c_fast = gate_on_q & (c_gs > super_gate)
    h1 = hit[:, None]
    return (jnp.where(hit, c_gs, gate_s),
            jnp.where(hit, c_gr, gate_r),
            jnp.where(h1, c_as, ann_s),
            jnp.where(h1, c_ar, ann_r),
            jnp.where(hit, c_fast, fast)) + tuple(outs[5:])


def _semantic_writeback(ring: SemanticRing, head: jax.Array, qn: jax.Array,
                        tenant_q: jax.Array, gate_on_q: jax.Array,
                        gate_s: jax.Array, gate_r: jax.Array,
                        ann_s: jax.Array, ann_r: jax.Array, rank: jax.Array,
                        write_mask: jax.Array, k_need: jax.Array,
                        npr_need: jax.Array, mode_id: jax.Array,
                        capacity: int) -> SemanticRing:
    """LIFO slot rotation inside the dispatch: miss ``rank`` lands in slot
    ``(head + rank) % R``; suppressed writes scatter to the scratch row.
    Callers pass rank in BATCH order among misses (the stable sort
    preserves it), so the host can mirror the slot assignment from the
    readback alone."""
    r = ring.slots
    slot_w = jnp.where(write_mask,
                       jnp.mod(head + rank, r), r).astype(jnp.int32)
    w = ann_s.shape[1]
    if w < ring.width:
        ann_s = jnp.pad(ann_s, ((0, 0), (0, ring.width - w)),
                        constant_values=NEG_INF)
        ann_r = jnp.pad(ann_r, ((0, 0), (0, ring.width - w)),
                        constant_values=capacity)
    b = qn.shape[0]
    return ring.replace(
        emb=ring.emb.at[slot_w].set(qn),
        tenant=ring.tenant.at[slot_w].set(tenant_q.astype(jnp.int32)),
        gate_on=ring.gate_on.at[slot_w].set(gate_on_q),
        mode=ring.mode.at[slot_w].set(
            jnp.broadcast_to(mode_id, (b,)).astype(jnp.int32)),
        stored_k=ring.stored_k.at[slot_w].set(k_need.astype(jnp.int32)),
        nprobe=ring.nprobe.at[slot_w].set(npr_need.astype(jnp.int32)),
        gate_s=ring.gate_s.at[slot_w].set(gate_s),
        gate_r=ring.gate_r.at[slot_w].set(gate_r.astype(jnp.int32)),
        ann_s=ring.ann_s.at[slot_w].set(ann_s),
        ann_r=ring.ann_r.at[slot_w].set(ann_r.astype(jnp.int32)))


def _semantic_scan_core(chunk_fn, arrays, state: ArenaState, sem,
                        super_gate: jax.Array, *, k: int, block: int,
                        rag_slack: int = 0, nprobe_val: int = 0):
    """The full in-dispatch semantic-cache flow around one family's chunk
    closure: probe → miss-first stable sort → blocked early-out scan →
    unsort → substitution → ring writeback. ``arrays`` is the family's
    per-query tuple ``(q, q_valid, tenant, gate_on, boost_on[, k_q,
    cap_q[, nprobe_q]])``; returns the family's output tuple (dup counter
    zeroed for skipped queries) + ``(sem_col, new_ring)`` where sem_col
    is ``1 + slot`` for hits and 0 for misses."""
    ring, sem_valid, head, thresh, mode_id = sem
    q, q_valid, tenant, gate_on = arrays[0], arrays[1], arrays[2], arrays[3]
    nq = q.shape[0]
    k_q = arrays[5] if len(arrays) > 5 else None
    npr_q = arrays[7] if len(arrays) > 7 else None
    qn = normalize(q).astype(jnp.float32)
    k_need = k_q if k_q is not None else jnp.full((nq,), k, jnp.int32)
    npr_need = (npr_q if npr_q is not None
                else jnp.full((nq,), nprobe_val, jnp.int32))
    hit, slot = _semantic_probe(ring, sem_valid, qn, tenant, q_valid,
                                gate_on, k_need, npr_need, mode_id, thresh)
    miss = q_valid & ~hit
    order = jnp.argsort((~miss).astype(jnp.int32), stable=True)
    inv = jnp.argsort(order)
    n_miss = miss.sum().astype(jnp.int32)
    sorted_arrays = tuple(a[order] for a in arrays)
    outs_s = _semantic_blocked(chunk_fn, sorted_arrays, n_miss, block,
                               state.capacity)
    rank = jnp.arange(nq, dtype=jnp.int32)
    write_mask = miss[order] & (rank >= n_miss - ring.slots)
    ring2 = _semantic_writeback(
        ring, head, qn[order], sorted_arrays[2], sorted_arrays[3],
        outs_s[0], outs_s[1], outs_s[2], outs_s[3], rank, write_mask,
        k_need[order], npr_need[order], mode_id, state.capacity)
    outs = tuple(o[inv] for o in outs_s)
    outs = _semantic_substitute(ring, hit, slot, gate_on, super_gate, outs,
                                k_q, rag_slack, state.capacity)
    if len(outs) > 7:
        # trailing dup counter (IVF/PQ): skipped queries carried the int
        # filler — a hit or pad query suppressed zero duplicates
        outs = outs[:7] + (jnp.where(miss, outs[7], 0),) + tuple(outs[8:])
    sem_col = jnp.where(hit, 1 + slot, 0).astype(jnp.int32)
    return tuple(outs) + (sem_col, ring2)


def _search_fused_scan(state: ArenaState, csr_indptr: jax.Array,
                       csr_nbr: jax.Array, q: jax.Array, q_valid: jax.Array,
                       tenant: jax.Array, gate_on: jax.Array,
                       boost_on: jax.Array, super_gate: jax.Array,
                       k: int, cap_take: int, max_nbr: int,
                       k_q=None, cap_q=None, scan_chunk: int = 0,
                       sem=None, sem_block: int = 16):
    """Per-chunk compute phase: the exact two-tier top-k core, the
    device-side gate verdict, and the CSR neighbor gather with per-query
    dedup. Returns sentinel-padded row lists for the scatter phase
    (``capacity`` is the sentinel row index).

    With ``k_q``/``cap_q`` ([Q] i32 device sidecars) the scan is RAGGED:
    ``k`` and ``cap_take`` become the static batch ceilings the compute
    runs to, and each query masks at its own top-k boundary
    (``_ragged_topk_mask``) — per-request shapes are data, not trace
    constants.

    ``scan_chunk > 0`` (ISSUE 11) overrides the default ``QUERY_CHUNK``
    streaming width: the HBM planner shrinks the ``[chunk, rows]`` score
    tile — the dominant transient of the dispatch — to fit a throttled
    budget WITHOUT splitting the turn. Results are bit-identical (the
    per-query computation never sees the chunk boundary); only the
    streaming granularity, and therefore the peak footprint, changes."""
    ragged = k_q is not None

    def chunk(q_c, valid_c, tenant_c, gate_c, boost_c, *rag):
        gate_s, gate_r, ann_s, ann_r = _exact_two_tier(state, q_c, tenant_c,
                                                       1, k)
        gate_s, gate_r = gate_s[:, 0], gate_r[:, 0]
        cap_c = None
        if ragged:
            k_c, cap_c = rag
            ann_s, ann_r = _ragged_topk_mask(ann_s, ann_r, k_c,
                                             state.capacity)
        fast, acc_rows, nbr_rows = _gate_and_boost_rows(
            state, csr_indptr, csr_nbr, gate_s, gate_r, ann_s, ann_r,
            valid_c, tenant_c, gate_c, boost_c, super_gate, cap_take,
            max_nbr, cap_c=cap_c)
        return gate_s, gate_r, ann_s, ann_r, fast, acc_rows, nbr_rows

    arrays = (q, q_valid, tenant, gate_on, boost_on)
    if ragged:
        arrays = arrays + (k_q, cap_q)
    if sem is None:
        return chunked_map_multi(chunk, arrays,
                                 chunk=(scan_chunk or QUERY_CHUNK))
    return _semantic_scan_core(chunk, arrays, state, sem, super_gate,
                               k=k, block=sem_block)


def _search_fused(
    state: ArenaState,
    csr_indptr: jax.Array,   # [cap+2] i32 neighbor-list offsets per row
    csr_nbr: jax.Array,      # [E_pad] i32 neighbor rows (bidirectional)
    q: jax.Array,            # [Q, d] padded query batch
    q_valid: jax.Array,      # [Q] bool (False for pad rows)
    tenant: jax.Array,       # [Q] i32 per-query tenant (cross-tenant batch)
    gate_on: jax.Array,      # [Q] bool hierarchy gate enabled
    boost_on: jax.Array,     # [Q] bool apply device boosts for this query
    now: jax.Array,
    super_gate: jax.Array,
    acc_boost: jax.Array,
    nbr_boost: jax.Array,
    k: int,
    cap_take: int,           # retrieval cap: how many top rows get boosted
    max_nbr: int,
    sem=None,                # (ring, valid [R], head, thresh, mode_id)
    sem_block: int = 16,
) -> Tuple[ArenaState, Tuple[jax.Array, ...]]:
    """One dispatch for a padded cross-tenant query batch: gate + ANN +
    neighbor gather + both boosts. Scatter counts make a mega-batch exact
    w.r.t. serial classic turns: a row retrieved by two queries gets TWO
    access bumps (``.add``), while within one query each neighbor is
    boosted once (the per-query dedup above) — matching what per-turn
    ``update_access`` + ``_boost_neighbors`` calls would have done.

    ``sem`` threads the semantic query cache through the SAME dispatch
    (probe / early-out / substitution / ring writeback — see
    ``_semantic_scan_core``); when present the return gains the updated
    ring: ``(state, ring, packed)``."""
    res = _search_fused_scan(state, csr_indptr, csr_nbr, q, q_valid, tenant,
                             gate_on, boost_on, super_gate, k, cap_take,
                             max_nbr, sem=sem, sem_block=sem_block)
    return _sem_finish(state, res, sem, now, acc_boost, nbr_boost)


def _boost_scatter(state: ArenaState, acc_rows: jax.Array,
                   nbr_rows: jax.Array, now: jax.Array, acc_boost: jax.Array,
                   nbr_boost: jax.Array, zero_last: bool = True
                   ) -> ArenaState:
    """Scatter phase shared by every fused serving kernel: count-weighted
    access/neighbor salience boosts, capped at 1.0, with freshness
    inheritance for every touched row. Single-chip callers route masked
    rows to the in-range sentinel row (``zero_last=True`` zeroes its
    count); the shard-local scatters route non-owned rows OUT of range
    instead — XLA drops out-of-bounds scatter updates — so they pass
    ``zero_last=False``."""
    n = _nrows(state)
    acc_cnt = jnp.zeros((n,), jnp.int32).at[acc_rows.reshape(-1)].add(1)
    nbr_cnt = jnp.zeros((n,), jnp.int32).at[nbr_rows.reshape(-1)].add(1)
    if zero_last:
        acc_cnt = acc_cnt.at[n - 1].set(0)
        nbr_cnt = nbr_cnt.at[n - 1].set(0)
    sal = (state.salience + acc_cnt.astype(jnp.float32) * acc_boost
           + nbr_cnt.astype(jnp.float32) * nbr_boost)
    touched = (acc_cnt > 0) | (nbr_cnt > 0)
    return state.replace(
        salience=jnp.where(touched, jnp.minimum(sal, 1.0), state.salience),
        access_count=state.access_count + acc_cnt,
        last_accessed=jnp.where(touched, now, state.last_accessed))


# Width of the device-counter tail _pack_retrieval appends to every fused
# serving readback (ISSUE 6): per query [n_live, n_dedup_dropped,
# n_acc_boost_rows, n_nbr_boost_rows, sem] as bitcast int32. The marginal
# cost of device-side observability is these 20 bytes per query riding the
# ONE readback that already exists — never an extra dispatch or transfer.
# ``sem`` (ISSUE 20) is the semantic-cache verdict: 0 for a miss, 1+slot
# for a ring hit — the host mirrors ring occupancy and the row→slot
# reverse index from this column alone.
RETRIEVAL_TAIL = 5


def _pack_retrieval(gate_s, gate_r, ann_s, ann_r, fast, dup=None, acc=None,
                    nbr=None, sem=None) -> jax.Array:
    """ONE [Q, 3 + 2k + RETRIEVAL_TAIL] f32 readback array: [gate_score,
    gate_row(bitcast), ann_scores..k, ann_rows(bitcast)..k, fast,
    counters..5]. Packing happens in-kernel so the host pays exactly one
    device→host transfer and zero extra dispatches (int rows are bitcast,
    not cast — undone with a host-side ``.view(int32)``, same trick as
    ``utils.batching.fetch_packed``).

    The counter tail carries the device-side serving counters: live top-k
    hits (host derives the top-k shortfall against each request's k),
    duplicate candidates the IVF in-kernel dedup suppressed (``dup``;
    zero for the dense paths), the access/neighbor boost-scatter row
    counts (``acc``/``nbr``; zero for read twins, whose boost masks are
    all-off), and the semantic-cache verdict (``sem``; zero when the ring
    is absent)."""
    bc = lambda a: jax.lax.bitcast_convert_type(a.astype(jnp.int32),  # noqa: E731
                                                jnp.float32)
    q = gate_s.shape[0]
    zeros = jnp.zeros((q,), jnp.int32)
    n_live = (ann_s > NEG_INF / 2).sum(axis=-1).astype(jnp.int32)
    dup = zeros if dup is None else dup.astype(jnp.int32)
    acc = zeros if acc is None else acc.astype(jnp.int32)
    nbr = zeros if nbr is None else nbr.astype(jnp.int32)
    sem = zeros if sem is None else sem.astype(jnp.int32)
    return jnp.concatenate([
        gate_s[:, None], bc(gate_r)[:, None], ann_s, bc(ann_r),
        fast.astype(jnp.float32)[:, None],
        bc(n_live)[:, None], bc(dup)[:, None], bc(acc)[:, None],
        bc(nbr)[:, None], bc(sem)[:, None]], axis=1)


def _boost_row_counts(capacity: int, acc_rows: jax.Array,
                      nbr_rows: jax.Array):
    """Per-query counts of rows the boost scatter will actually touch
    (sentinel-routed entries excluded) — the device-side 'boost-scatter
    count' rider. Shared by every single-chip fused serving kernel."""
    acc = (acc_rows != capacity).sum(axis=-1)
    nbr = (nbr_rows != capacity).sum(axis=-1)
    return acc, nbr


def _sem_finish(state: ArenaState, res, sem, now, acc_boost, nbr_boost):
    """Shared serve-twin tail across every fused serving family: unpack
    the scan result (which carries ``(sem_col, new_ring)`` extras when the
    semantic cache rode the dispatch), apply the boost scatter, pack the
    readback. With the cache on the twin returns ``(state, ring, packed)``
    — the ring is NOT donated (it is small and the caller swaps it in
    after the dispatch), the arena donation story is unchanged."""
    if sem is None:
        core, sem_col, ring2 = res, None, None
    else:
        core, sem_col, ring2 = res[:-2], res[-2], res[-1]
    gate_s, gate_r, ann_s, ann_r, fast, acc_rows, nbr_rows = core[:7]
    n_dup = core[7] if len(core) > 7 else None
    n_acc, n_nbr = _boost_row_counts(state.capacity, acc_rows, nbr_rows)
    state = _boost_scatter(state, acc_rows, nbr_rows, now, acc_boost,
                           nbr_boost)
    packed = _pack_retrieval(gate_s, gate_r, ann_s, ann_r, fast, dup=n_dup,
                             acc=n_acc, nbr=n_nbr, sem=sem_col)
    if sem is None:
        return state, packed
    return state, ring2, packed


def _sem_finish_read(res, sem):
    """Read-twin tail twin of ``_sem_finish``: no boost scatter, but the
    ring writeback still lands (read fleets warm the cache too), so with
    the cache on the read twin returns ``(ring, packed)``."""
    if sem is None:
        core, sem_col, ring2 = res, None, None
    else:
        core, sem_col, ring2 = res[:-2], res[-2], res[-1]
    gate_s, gate_r, ann_s, ann_r, fast = core[:5]
    n_dup = core[7] if len(core) > 7 else None
    packed = _pack_retrieval(gate_s, gate_r, ann_s, ann_r, fast, dup=n_dup,
                             sem=sem_col)
    if sem is None:
        return packed
    return ring2, packed


search_fused, search_fused_copy = _donated_pair(
    _search_fused, static_argnames=("k", "cap_take", "max_nbr",
                                    "sem_block"))


@functools.partial(jax.jit, static_argnames=("k", "cap_take", "max_nbr",
                                             "sem_block"))
def search_fused_read(state: ArenaState, csr_indptr: jax.Array,
                      csr_nbr: jax.Array, q: jax.Array, q_valid: jax.Array,
                      tenant: jax.Array, gate_on: jax.Array,
                      super_gate: jax.Array, k: int, cap_take: int,
                      max_nbr: int, sem=None,
                      sem_block: int = 16) -> jax.Array:
    """Read-only twin of ``search_fused`` for batches where NO query wants
    boosts (pure ``search_memories`` fleets): same compute, no state
    mutation, so the ownership/donation dance is skipped entirely. With
    ``sem`` the semantic ring still rides (misses write back — read
    fleets warm the cache) and the return becomes ``(ring, packed)``."""
    boost_off = jnp.zeros(q_valid.shape, bool)
    res = _search_fused_scan(
        state, csr_indptr, csr_nbr, q, q_valid, tenant, gate_on, boost_off,
        super_gate, k, cap_take, max_nbr, sem=sem, sem_block=sem_block)
    return _sem_finish_read(res, sem)


# ---------------------------------------------------------------------------
# Quantized fused serving (ISSUE 3): the same single-dispatch chat-turn
# program, but the whole-arena scan streams the int8 shadow (half the HBM
# bytes, int8×int8→int32 on the MXU) for a coarse top-(k+slack), then the
# few survivors are EXACTLY rescored from the master arena with a gathered-
# row dot before the gate / CSR gather / boost scatter run unchanged. This
# is the EdgeRAG two-stage idiom fused into one program: at 1M rows the
# coarse scan is the bandwidth floor and the rescore is O(Q·(k+slack)·d).
# ---------------------------------------------------------------------------


def _quant_two_tier(state: ArenaState, q8a: jax.Array, scale_a: jax.Array,
                    q_c: jax.Array, tenant_c: jax.Array, k: int, slack: int):
    """Two-stage quantized two-tier core: int8 coarse scan over the shadow
    (``q8a`` codes + ``scale_a`` per-row scales, ops/quant.py layout) for
    BOTH retrieval tiers — super gate candidates and main ANN candidates
    are different masks over the ONE int8 score matrix — then an exact
    bf16/f32 rescore of the k+slack survivors via a gathered-row dot. The
    slack absorbs the ~1e-2 int8 ranking error at the k boundary (ISSUE 3
    satellite: config-driven, shared with the IVF over-fetch) so the exact
    top-k can't lose a true member the coarse scan ranked at k+3.

    Shard-local by construction (the shadow row-shards like the master, and
    the rescore gather only touches local rows): single-chip callers pass
    the whole arena + shadow, the sharded program each chip's slices.
    Returns exact-scored ``(gate_s [C,1], gate_r [C,1], ann_s [C,k],
    ann_r [C,k])``; the super gate is threshold-sensitive (0.4), so its
    VERDICT uses the exact rescored score — quantization error can only
    cost a gate candidate ranked below coarse position 1+slack, never flip
    the threshold comparison itself."""
    from lazzaro_tpu.ops.quant import quantize_rows

    n = _nrows(state)
    k_fetch = min(k + slack, n)
    g_fetch = min(1 + slack, n)
    qn = normalize(q_c)                                   # [C, d] f32
    qq, qs = quantize_rows(qn)
    dots = jax.lax.dot_general(
        qq, q8a, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)                 # [C, rows] i32
    coarse = (dots.astype(jnp.float32)
              * qs[:, None] * scale_a[None, :])
    alive_t = state.alive[None, :] & (
        state.tenant_id[None, :] == tenant_c[:, None])
    sup = state.is_super[None, :]
    cg_s, cg_r = jax.lax.top_k(
        jnp.where(alive_t & sup, coarse, NEG_INF), g_fetch)
    ca_s, ca_r = jax.lax.top_k(
        jnp.where(alive_t & ~sup, coarse, NEG_INF), k_fetch)
    # Same consumer-split hazard as _exact_two_tier: the coarse top-k
    # feeds both the rescore gather and (via it) the readback — without
    # the barrier XLA can duplicate the full-arena sorts.
    cg_s, cg_r, ca_s, ca_r = jax.lax.optimization_barrier(
        (cg_s, cg_r, ca_s, ca_r))
    qd = qn.astype(state.emb.dtype)

    def rescore(rows_c, coarse_s):
        g = state.emb[_phys(state, rows_c)]               # [C, kf, d]
        ex = jnp.einsum("cd,ckd->ck", qd, g,
                        preferred_element_type=jnp.float32)
        return jnp.where(coarse_s > NEG_INF / 2, ex, NEG_INF)

    ann_ex = rescore(ca_r, ca_s)
    ann_s, sel = jax.lax.top_k(ann_ex, k)
    ann_r = jnp.take_along_axis(ca_r, sel, axis=1)
    gate_ex = rescore(cg_r, cg_s)
    g_s, g_sel = jax.lax.top_k(gate_ex, 1)
    g_r = jnp.take_along_axis(cg_r, g_sel, axis=1)
    return g_s, g_r, ann_s, ann_r


def _search_fused_quant_scan(state: ArenaState, q8a: jax.Array,
                             scale_a: jax.Array, csr_indptr: jax.Array,
                             csr_nbr: jax.Array, q: jax.Array,
                             q_valid: jax.Array, tenant: jax.Array,
                             gate_on: jax.Array, boost_on: jax.Array,
                             super_gate: jax.Array, k: int, slack: int,
                             cap_take: int, max_nbr: int,
                             k_q=None, cap_q=None, scan_chunk: int = 0,
                             sem=None, sem_block: int = 16):
    """Quantized per-chunk compute phase: the int8 coarse-scan + exact
    rescore core, then the shared gate/CSR/boost tail. ``k_q``/``cap_q``
    make it ragged (see ``_search_fused_scan``): the coarse fetch and the
    exact rescore run to the static ceiling, the boundary mask is
    per-query data. ``scan_chunk`` is the planner's streaming-width
    override (ISSUE 11; bit-identical, smaller score tile)."""
    ragged = k_q is not None

    def chunk(q_c, valid_c, tenant_c, gate_c, boost_c, *rag):
        g_s, g_r, ann_s, ann_r = _quant_two_tier(state, q8a, scale_a, q_c,
                                                 tenant_c, k, slack)
        gate_s, gate_r = g_s[:, 0], g_r[:, 0]
        cap_c = None
        if ragged:
            k_c, cap_c = rag
            ann_s, ann_r = _ragged_topk_mask(ann_s, ann_r, k_c,
                                             state.capacity)
        fast, acc_rows, nbr_rows = _gate_and_boost_rows(
            state, csr_indptr, csr_nbr, gate_s, gate_r, ann_s, ann_r,
            valid_c, tenant_c, gate_c, boost_c, super_gate, cap_take,
            max_nbr, cap_c=cap_c)
        return gate_s, gate_r, ann_s, ann_r, fast, acc_rows, nbr_rows

    arrays = (q, q_valid, tenant, gate_on, boost_on)
    if ragged:
        arrays = arrays + (k_q, cap_q)
    if sem is None:
        return chunked_map_multi(chunk, arrays,
                                 chunk=(scan_chunk or QUERY_CHUNK))
    return _semantic_scan_core(chunk, arrays, state, sem, super_gate,
                               k=k, block=sem_block)


def _search_fused_quant(
    state: ArenaState,
    q8a: jax.Array,          # [cap+1, d] i8 serving shadow codes
    scale_a: jax.Array,      # [cap+1] f32 per-row scales
    csr_indptr: jax.Array,
    csr_nbr: jax.Array,
    q: jax.Array,
    q_valid: jax.Array,
    tenant: jax.Array,
    gate_on: jax.Array,
    boost_on: jax.Array,
    now: jax.Array,
    super_gate: jax.Array,
    acc_boost: jax.Array,
    nbr_boost: jax.Array,
    k: int,
    slack: int,
    cap_take: int,
    max_nbr: int,
    sem=None,
    sem_block: int = 16,
) -> Tuple[ArenaState, jax.Array]:
    """``search_fused`` with the int8 coarse scan + exact rescore stage:
    one donated dispatch + one packed readback per coalesced batch, int8
    mode included. Only the arena state is donated — the shadow is a
    long-lived read-only replica (boost scatters touch salience/access/
    freshness, never the embeddings, so the codes stay valid)."""
    res = _search_fused_quant_scan(state, q8a, scale_a, csr_indptr, csr_nbr,
                                   q, q_valid, tenant, gate_on, boost_on,
                                   super_gate, k, slack, cap_take, max_nbr,
                                   sem=sem, sem_block=sem_block)
    return _sem_finish(state, res, sem, now, acc_boost, nbr_boost)


search_fused_quant, search_fused_quant_copy = _donated_pair(
    _search_fused_quant, static_argnames=("k", "slack", "cap_take",
                                          "max_nbr", "sem_block"))


@functools.partial(jax.jit, static_argnames=("k", "slack", "cap_take",
                                             "max_nbr", "sem_block"))
def search_fused_quant_read(state: ArenaState, q8a: jax.Array,
                            scale_a: jax.Array, csr_indptr: jax.Array,
                            csr_nbr: jax.Array, q: jax.Array,
                            q_valid: jax.Array, tenant: jax.Array,
                            gate_on: jax.Array, super_gate: jax.Array,
                            k: int, slack: int, cap_take: int,
                            max_nbr: int, sem=None,
                            sem_block: int = 16) -> jax.Array:
    """Read-only twin of ``search_fused_quant`` (pure ``search_memories``
    fleets in int8 mode): same coarse-scan + exact-rescore compute, no
    state mutation, no donation dance."""
    boost_off = jnp.zeros(q_valid.shape, bool)
    res = _search_fused_quant_scan(
        state, q8a, scale_a, csr_indptr, csr_nbr, q, q_valid, tenant,
        gate_on, boost_off, super_gate, k, slack, cap_take, max_nbr,
        sem=sem, sem_block=sem_block)
    return _sem_finish_read(res, sem)


# ---------------------------------------------------------------------------
# Tiered memory (ISSUE 8): HBM hot set + host-resident cold tier.
#
# Residency is a per-row device column (``cold`` [cap+1] bool, owned by
# ``tier.TierManager``): a demoted row keeps its metadata columns (alive,
# tenant, salience — decay sweeps and masks keep working) AND its int8
# shadow codes, but surrenders its full-precision embedding to the host
# ``ColdStore`` (the arena row is zeroed by the donated ``tier_demote``
# scatter; the paged-arena follow-up reclaims the physical bytes). The int8
# shadow therefore stays the FULL-CORPUS scan structure — per cold row the
# chip holds d bytes of codes instead of d codes + 2d bytes of bf16 master,
# the TF-Engram/EdgeRAG shape.
#
# Serving: ``search_fused_tiered`` is the quantized fused chat-turn program
# with a tier-aware rescore — the int8 coarse scan covers the whole corpus,
# HOT survivors rescore exactly from the master in-kernel, COLD survivors
# keep their coarse score and raise a per-query cold flag (their exact rows
# live host-side). Hot-only turns therefore stay ONE dispatch + ONE packed
# readback with exact scores and in-kernel boosts; a turn whose candidate
# set touches cold rows defers its boosts (same suppression slot as the
# gate fast path) and pays ONE bounded second dispatch
# (``tier_cold_finish``): exact rescore of the host-gathered cold vectors,
# final re-rank over the SAME k+slack candidate set, and the deferred
# gate/CSR/boost tail — never a full-arena fault-in.
# ---------------------------------------------------------------------------


def _tier_demote(state: ArenaState, rows: jax.Array) -> ArenaState:
    """Surrender the full-precision embeddings of ``rows`` (the host cold
    store holds the exact bytes; metadata columns and the int8 shadow stay).
    Sentinel-padded rows zero the scratch row, which is never scored."""
    zeros = jnp.zeros((rows.shape[0], state.emb.shape[1]), state.emb.dtype)
    return state.replace(emb=state.emb.at[rows].set(zeros))


tier_demote, tier_demote_copy = _donated_pair(_tier_demote)


def _tier_promote(state: ArenaState, rows: jax.Array,
                  vecs: jax.Array) -> ArenaState:
    """Restore promoted rows' exact embeddings (``vecs`` carries the cold
    store's bytes in the arena dtype — the round trip is bit-exact, so the
    int8 shadow codes stay valid without a requantize)."""
    return state.replace(emb=state.emb.at[rows].set(
        vecs.astype(state.emb.dtype)))


tier_promote, tier_promote_copy = _donated_pair(_tier_promote)


def _tier_demote_paged(state: ArenaState, ptable: PageTable,
                       rows: jax.Array
                       ) -> Tuple[ArenaState, PageTable, jax.Array]:
    """Paged demote: surrender the rows' pool slots back to the free
    stack (``_page_free`` zeroes the slots — the paged analogue of the
    dense zero-scatter, except the bytes become REUSABLE capacity instead
    of dead zeros). Emptied pages are real reclaimed HBM the next grow
    never has to allocate."""
    return _page_free(state, ptable, rows)


tier_demote_paged, tier_demote_paged_copy = _donated_pair(
    _tier_demote_paged, donate=(0, 1))


def _tier_promote_paged(state: ArenaState, ptable: PageTable,
                        rows: jax.Array, vecs: jax.Array
                        ) -> Tuple[ArenaState, PageTable, jax.Array]:
    """Paged promote: re-bind pool slots (prefix-sum pop; the host
    pre-checks its mirror so the stack never runs dry mid-dispatch) and
    scatter the cold store's exact bytes at the fresh physical rows."""
    valid = rows < state.capacity
    state, ptable, pops, _ = _page_alloc(state, ptable, rows, valid)
    state = state.replace(emb=state.emb.at[_phys(state, rows)].set(
        vecs.astype(state.emb.dtype)))
    return state, ptable, pops


tier_promote_paged, tier_promote_paged_copy = _donated_pair(
    _tier_promote_paged, donate=(0, 1))


def _arena_delete_paged(state: ArenaState, ptable: PageTable,
                        rows: jax.Array
                        ) -> Tuple[ArenaState, PageTable, jax.Array]:
    """Delete + free: the dense ``_arena_delete`` column scrub plus the
    pool-slot push — deleted rows' HBM is immediately reusable."""
    state = _arena_delete(state, rows)
    return _page_free(state, ptable, rows)


arena_delete_paged, arena_delete_paged_copy = _donated_pair(
    _arena_delete_paged, donate=(0, 1))


def _arena_add_paged(state: ArenaState, ptable: PageTable, rows: jax.Array,
                     emb: jax.Array, salience: jax.Array,
                     timestamp: jax.Array, type_id: jax.Array,
                     shard_id: jax.Array, tenant_id: jax.Array,
                     is_super: jax.Array
                     ) -> Tuple[ArenaState, PageTable, jax.Array]:
    """Direct (non-fused) paged add: bind slots, then the usual column
    scatters with the emb write routed through ``row_map``."""
    valid = rows < state.capacity
    state, ptable, pops, _ = _page_alloc(state, ptable, rows, valid)
    state = _arena_add(state, rows, emb, salience, timestamp, type_id,
                       shard_id, tenant_id, is_super)
    return state, ptable, pops


arena_add_paged, arena_add_paged_copy = _donated_pair(
    _arena_add_paged, donate=(0, 1))


def _tiered_two_tier(state: ArenaState, q8a: jax.Array, scale_a: jax.Array,
                     cold: jax.Array, q_c: jax.Array, tenant_c: jax.Array,
                     k: int, slack: int):
    """Tier-aware two-stage core: int8 coarse scan over the full-corpus
    shadow (both retrieval tiers, same masks as ``_quant_two_tier``), then
    a residency-split rescore — hot survivors exact from the master, cold
    survivors keep the coarse score (their exact rows are host-resident).
    Returns the candidates K+SLACK WIDE sorted by the blended score, so a
    caller whose query touched cold rows can finish (exact cold rescore +
    final re-rank) over the SAME candidate set without re-running the
    scan, plus the per-query cold flag. Super rows are pinned hot by the
    tiering policy, so the gate verdict is always exact."""
    n = _nrows(state)
    k_fetch = min(k + slack, n)
    g_fetch = min(1 + slack, n)
    qn = normalize(q_c)                                   # [C, d] f32
    from lazzaro_tpu.ops.quant import quantize_rows

    qq, qs = quantize_rows(qn)
    dots = jax.lax.dot_general(
        qq, q8a, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)                 # [C, rows] i32
    coarse = (dots.astype(jnp.float32)
              * qs[:, None] * scale_a[None, :])
    alive_t = state.alive[None, :] & (
        state.tenant_id[None, :] == tenant_c[:, None])
    sup = state.is_super[None, :]
    cg_s, cg_r = jax.lax.top_k(
        jnp.where(alive_t & sup, coarse, NEG_INF), g_fetch)
    ca_s, ca_r = jax.lax.top_k(
        jnp.where(alive_t & ~sup, coarse, NEG_INF), k_fetch)
    # consumer-split hazard, same as _quant_two_tier
    cg_s, cg_r, ca_s, ca_r = jax.lax.optimization_barrier(
        (cg_s, cg_r, ca_s, ca_r))
    qd = qn.astype(state.emb.dtype)

    def rescore(rows_c, coarse_s):
        # cold rows are UNBOUND under paging: _phys routes them to the
        # all-zero pool sentinel, so their exact rescore is 0 — exactly
        # the dense demote-zeroed read (the blend keeps coarse either way)
        g = state.emb[_phys(state, rows_c)]               # [C, kf, d]
        ex = jnp.einsum("cd,ckd->ck", qd, g,
                        preferred_element_type=jnp.float32)
        return jnp.where(coarse_s > NEG_INF / 2, ex, NEG_INF)

    ann_ex = rescore(ca_r, ca_s)
    live = ca_s > NEG_INF / 2
    is_cold = cold[ca_r] & live
    # cold candidates carry their COARSE score into the ranking (their
    # exact row is host-side); hot candidates are already exact
    blend = jnp.where(is_cold, ca_s, ann_ex)
    ann_s, sel = jax.lax.top_k(blend, k_fetch)            # full sort
    ann_r = jnp.take_along_axis(ca_r, sel, axis=1)
    cold_any = jnp.take_along_axis(is_cold, sel, axis=1).any(axis=-1)
    gate_ex = rescore(cg_r, cg_s)
    g_s, g_sel = jax.lax.top_k(gate_ex, 1)
    g_r = jnp.take_along_axis(cg_r, g_sel, axis=1)
    return g_s, g_r, ann_s, ann_r, cold_any


def _search_fused_tiered_scan(state: ArenaState, q8a: jax.Array,
                              scale_a: jax.Array, cold: jax.Array,
                              csr_indptr: jax.Array, csr_nbr: jax.Array,
                              q: jax.Array, q_valid: jax.Array,
                              tenant: jax.Array, gate_on: jax.Array,
                              boost_on: jax.Array, super_gate: jax.Array,
                              k: int, slack: int, cap_take: int,
                              max_nbr: int, k_q=None, cap_q=None,
                              scan_chunk: int = 0,
                              sem=None, sem_block: int = 16):
    """Tiered per-chunk compute phase: the tier-aware two-stage core, then
    the shared gate/CSR/boost tail with cold-hit queries' boosts DEFERRED
    (suppressed exactly like the gate fast path — the host applies them in
    the bounded ``tier_cold_finish`` dispatch after the exact re-rank, so
    boost rows always follow the FINAL ranking). ``k_q``/``cap_q`` make it
    ragged; the per-query boundary masks at k_i + slack so the host keeps
    each query's full candidate window for the finish."""
    ragged = k_q is not None

    def chunk(q_c, valid_c, tenant_c, gate_c, boost_c, *rag):
        g_s, g_r, ann_s, ann_r, cold_any = _tiered_two_tier(
            state, q8a, scale_a, cold, q_c, tenant_c, k, slack)
        gate_s, gate_r = g_s[:, 0], g_r[:, 0]
        cap_c = None
        if ragged:
            k_c, cap_c = rag
            kf = jnp.minimum(k_c + slack, ann_s.shape[1])
            ann_s, ann_r = _ragged_topk_mask(ann_s, ann_r, kf,
                                             state.capacity)
        fast, acc_rows, nbr_rows = _gate_and_boost_rows(
            state, csr_indptr, csr_nbr, gate_s, gate_r, ann_s, ann_r,
            valid_c, tenant_c, gate_c, boost_c & ~cold_any, super_gate,
            cap_take, max_nbr, cap_c=cap_c)
        return gate_s, gate_r, ann_s, ann_r, fast, acc_rows, nbr_rows

    arrays = (q, q_valid, tenant, gate_on, boost_on)
    if ragged:
        arrays = arrays + (k_q, cap_q)
    if sem is None:
        return chunked_map_multi(chunk, arrays,
                                 chunk=(scan_chunk or QUERY_CHUNK))
    # the tiered candidate window is k+slack wide and the ragged boundary
    # masks at k_i + slack — the substitution must re-mask the same way
    return _semantic_scan_core(chunk, arrays, state, sem, super_gate,
                               k=k, block=sem_block, rag_slack=slack)


def _search_fused_tiered(
    state: ArenaState,
    q8a: jax.Array,          # [cap+1, d] i8 FULL-corpus shadow codes
    scale_a: jax.Array,      # [cap+1] f32
    cold: jax.Array,         # [cap+1] bool residency column (True = cold)
    csr_indptr: jax.Array,
    csr_nbr: jax.Array,
    q: jax.Array,
    q_valid: jax.Array,
    tenant: jax.Array,
    gate_on: jax.Array,
    boost_on: jax.Array,
    now: jax.Array,
    super_gate: jax.Array,
    acc_boost: jax.Array,
    nbr_boost: jax.Array,
    k: int,
    slack: int,
    cap_take: int,
    max_nbr: int,
    sem=None,
    sem_block: int = 16,
) -> Tuple[ArenaState, jax.Array]:
    """``search_fused_quant`` with the residency column threaded through:
    ONE donated dispatch + ONE packed readback whose candidate block is
    k+slack wide. Hot-only queries boost in-kernel; cold-hit queries come
    back unboosted with their candidate window for the finish dispatch."""
    res = _search_fused_tiered_scan(state, q8a, scale_a, cold, csr_indptr,
                                    csr_nbr, q, q_valid, tenant, gate_on,
                                    boost_on, super_gate, k, slack,
                                    cap_take, max_nbr, sem=sem,
                                    sem_block=sem_block)
    return _sem_finish(state, res, sem, now, acc_boost, nbr_boost)


search_fused_tiered, search_fused_tiered_copy = _donated_pair(
    _search_fused_tiered, static_argnames=("k", "slack", "cap_take",
                                           "max_nbr", "sem_block"))


@functools.partial(jax.jit, static_argnames=("k", "slack", "cap_take",
                                             "max_nbr", "sem_block"))
def search_fused_tiered_read(state: ArenaState, q8a: jax.Array,
                             scale_a: jax.Array, cold: jax.Array,
                             csr_indptr: jax.Array, csr_nbr: jax.Array,
                             q: jax.Array, q_valid: jax.Array,
                             tenant: jax.Array, gate_on: jax.Array,
                             super_gate: jax.Array, k: int, slack: int,
                             cap_take: int, max_nbr: int, sem=None,
                             sem_block: int = 16) -> jax.Array:
    """Read-only tiered twin (pure ``search_memories`` fleets)."""
    boost_off = jnp.zeros(q_valid.shape, bool)
    res = _search_fused_tiered_scan(
        state, q8a, scale_a, cold, csr_indptr, csr_nbr, q, q_valid, tenant,
        gate_on, boost_off, super_gate, k, slack, cap_take, max_nbr,
        sem=sem, sem_block=sem_block)
    return _sem_finish_read(res, sem)


def _search_fused_tiered_ragged(
    state: ArenaState,
    q8a: jax.Array,
    scale_a: jax.Array,
    cold: jax.Array,
    csr_indptr: jax.Array,
    csr_nbr: jax.Array,
    q: jax.Array,
    q_valid: jax.Array,
    tenant: jax.Array,
    gate_on: jax.Array,
    boost_on: jax.Array,
    k_q: jax.Array,
    cap_q: jax.Array,
    now: jax.Array,
    super_gate: jax.Array,
    acc_boost: jax.Array,
    nbr_boost: jax.Array,
    k: int,
    slack: int,
    cap_take: int,
    max_nbr: int,
    scan_chunk: int = 0,
    sem=None,
    sem_block: int = 16,
) -> Tuple[ArenaState, jax.Array]:
    """Tiered serving with the (k, cap) sidecar: each query's candidate
    window masks at its own k_i + slack boundary."""
    res = _search_fused_tiered_scan(state, q8a, scale_a, cold, csr_indptr,
                                    csr_nbr, q, q_valid, tenant, gate_on,
                                    boost_on, super_gate, k, slack,
                                    cap_take, max_nbr, k_q=k_q, cap_q=cap_q,
                                    scan_chunk=scan_chunk, sem=sem,
                                    sem_block=sem_block)
    return _sem_finish(state, res, sem, now, acc_boost, nbr_boost)


search_fused_tiered_ragged, search_fused_tiered_ragged_copy = _donated_pair(
    _search_fused_tiered_ragged,
    static_argnames=("k", "slack", "cap_take", "max_nbr", "scan_chunk",
                     "sem_block"))


@functools.partial(jax.jit, static_argnames=("k", "slack", "cap_take",
                                             "max_nbr", "scan_chunk",
                                             "sem_block"))
def search_fused_tiered_ragged_read(state: ArenaState, q8a: jax.Array,
                                    scale_a: jax.Array, cold: jax.Array,
                                    csr_indptr: jax.Array,
                                    csr_nbr: jax.Array, q: jax.Array,
                                    q_valid: jax.Array, tenant: jax.Array,
                                    gate_on: jax.Array, k_q: jax.Array,
                                    super_gate: jax.Array, k: int,
                                    slack: int, cap_take: int,
                                    max_nbr: int,
                                    scan_chunk: int = 0, sem=None,
                                    sem_block: int = 16) -> jax.Array:
    boost_off = jnp.zeros(q_valid.shape, bool)
    cap_q = jnp.zeros(q_valid.shape, jnp.int32)
    res = _search_fused_tiered_scan(
        state, q8a, scale_a, cold, csr_indptr, csr_nbr, q, q_valid, tenant,
        gate_on, boost_off, super_gate, k, slack, cap_take, max_nbr,
        k_q=k_q, cap_q=cap_q, scan_chunk=scan_chunk, sem=sem,
        sem_block=sem_block)
    return _sem_finish_read(res, sem)


def _cold_rerank(q: jax.Array, cand_rows: jax.Array, cand_s: jax.Array,
                 cold_m: jax.Array, cold_vecs: jax.Array, k: int,
                 sentinel: int):
    """Exact re-rank of a tiered candidate window: cold positions rescore
    against the host-gathered exact vectors (same einsum shape as the
    in-kernel hot rescore, so scores are bit-identical to an all-hot
    serve), hot positions keep their already-exact scores; final top-k.
    ``cold_vecs`` carries zeros at hot positions — their lanes are
    discarded by the ``where``."""
    qd = normalize(q).astype(cold_vecs.dtype)
    ex = jnp.einsum("cd,ckd->ck", qd, cold_vecs,
                    preferred_element_type=jnp.float32)
    live = cand_s > NEG_INF / 2
    scores = jnp.where(cold_m & live, ex,
                       jnp.where(live, cand_s, NEG_INF))
    ann_s, sel = jax.lax.top_k(scores, k)
    rows_safe = jnp.where(live, cand_rows, sentinel)
    ann_r = jnp.take_along_axis(rows_safe, sel, axis=1)
    ann_r = jnp.where(ann_s > NEG_INF / 2, ann_r, sentinel)
    return jax.lax.optimization_barrier((ann_s, ann_r))


@functools.partial(jax.jit, static_argnames=("k", "sentinel"))
def tier_cold_rescore(q: jax.Array, cand_rows: jax.Array,
                      cand_s: jax.Array, cold_m: jax.Array,
                      cold_vecs: jax.Array, gate_s: jax.Array,
                      gate_r: jax.Array, fast: jax.Array, k: int,
                      sentinel: int) -> jax.Array:
    """Read-only cold finish: exact re-rank of the candidate windows, no
    state mutation (pure ``search_memories`` fleets, and the pod path's
    result finish). Gate results pass through from the first dispatch —
    super rows are pinned hot, so they were exact already."""
    ann_s, ann_r = _cold_rerank(q, cand_rows, cand_s, cold_m, cold_vecs, k,
                                int(sentinel))
    return _pack_retrieval(gate_s, gate_r, ann_s, ann_r, fast)


def _tier_cold_finish(
    state: ArenaState,
    csr_indptr: jax.Array,   # FLAT global CSR (single-chip layout)
    csr_nbr: jax.Array,
    q: jax.Array,            # [C2, d] the cold-hit queries
    tenant: jax.Array,       # [C2] i32
    cand_rows: jax.Array,    # [C2, KF] candidate window from dispatch 1
    cand_s: jax.Array,       # [C2, KF] blended scores (exact where hot)
    cold_m: jax.Array,       # [C2, KF] bool cold positions
    cold_vecs: jax.Array,    # [C2, KF, d] host-gathered exact rows
    gate_s: jax.Array,       # [C2] gate passthrough from dispatch 1
    gate_r: jax.Array,       # [C2] i32
    fast: jax.Array,         # [C2] bool device gate verdicts
    boost_on: jax.Array,     # [C2] bool
    cap_q: jax.Array,        # [C2] i32 per-query retrieval cap
    now: jax.Array,
    acc_boost: jax.Array,
    nbr_boost: jax.Array,
    k: int,
    cap_take: int,
    max_nbr: int,
) -> Tuple[ArenaState, jax.Array]:
    """The bounded second dispatch of a cold-hit turn: exact rescore of
    the host-gathered cold rows, final re-rank over the SAME k+slack
    candidate window dispatch 1 scanned, then the deferred gate/CSR/boost
    tail — ``_csr_neighbor_rows`` + ``_boost_scatter``, the same code the
    all-hot kernels run, so boost semantics are identical, just applied
    after the final ranking. O(C2 · (k+slack) · d): never a full-arena
    scan, never a fault-in."""
    cap = state.capacity
    ann_s, ann_r = _cold_rerank(q, cand_rows, cand_s, cold_m, cold_vecs, k,
                                cap)
    take = ((ann_s[:, :cap_take] > NEG_INF / 2)
            & boost_on[:, None] & ~fast[:, None]
            & (jnp.arange(cap_take)[None, :] < cap_q[:, None]))
    acc_rows = jnp.where(take, ann_r[:, :cap_take], cap)
    nbr_rows = _csr_neighbor_rows(state, csr_indptr, csr_nbr, acc_rows,
                                  tenant, max_nbr)
    n_acc, n_nbr = _boost_row_counts(cap, acc_rows, nbr_rows)
    state = _boost_scatter(state, acc_rows, nbr_rows, now, acc_boost,
                           nbr_boost)
    return state, _pack_retrieval(gate_s, gate_r, ann_s, ann_r, fast,
                                  acc=n_acc, nbr=n_nbr)


tier_cold_finish, tier_cold_finish_copy = _donated_pair(
    _tier_cold_finish, static_argnames=("k", "cap_take", "max_nbr"))


# ---------------------------------------------------------------------------
# Fused IVF serving (ISSUE 4): the same single-dispatch chat-turn program,
# but the coarse stage is the CENTROID prefilter — the query batch scores
# C ≈ √N centroids, visits the top-nprobe clusters, gathers ONLY those
# clusters' member rows (plus the exact-scan extras: sealed+fresh residual
# and the super rows), and scores just the candidates before the existing
# super-gate / CSR-gather / boost-scatter tail runs unchanged. Candidate
# HBM traffic per query drops from N·d to ~(C + nprobe·N/C)·d (~25×
# analytically at 1M rows) while keeping the ONE-dispatch + ONE-readback
# invariant the dense and int8 paths already guarantee. With the int8
# shadow on, the candidate scan itself becomes two-stage (int8 gathered
# coarse + exact f32 rescore of the k+slack survivors) — PR 3's machinery
# applied to the gathered rows instead of the whole arena.
# ---------------------------------------------------------------------------

# Candidate tensors are [q_chunk, nprobe·M + E, d]; small chunks bound the
# gather footprint the same way ops/ivf.ivf_search's q_chunk does.
IVF_SERVE_CHUNK = 8


def _dedup_topk(scores: jax.Array, rows: jax.Array, sentinel: int, k: int
                ) -> Tuple[jax.Array, jax.Array]:
    """Top-k over a small over-fetched candidate list keeping only the
    FIRST occurrence of each arena row. IVF candidate lists can carry
    duplicates — a reused slot sitting in both a stale member slot and the
    residual, or a super row in both its cluster and the extras — and a
    duplicate must neither consume a result slot (k-shortfall) nor get a
    double access boost (the classic path dedups host-side in
    ``decode_topk``). ``scores`` is sorted descending (a top-k output), so
    keeping the first occurrence keeps the best. Invalid entries are
    routed to the sentinel row with NEG_INF intact. Also returns the
    per-query count of live duplicates suppressed — the device-side
    'dedup hits' counter riding the packed readback (ISSUE 6)."""
    r = jnp.where(scores > NEG_INF / 2, rows, sentinel)
    m = r.shape[1]
    dup = ((r[:, :, None] == r[:, None, :])
           & jnp.tri(m, k=-1, dtype=bool)[None, :, :]).any(-1)
    n_dup = (dup & (r != sentinel)).sum(axis=-1).astype(jnp.int32)
    s = jnp.where(dup, NEG_INF, scores)
    top_s, sel = jax.lax.top_k(s, k)
    top_r = jnp.take_along_axis(r, sel, axis=1)
    return top_s, jnp.where(top_s > NEG_INF / 2, top_r, sentinel), n_dup


def _ivf_two_tier(state: ArenaState, shadow, centroids: jax.Array,
                  members: jax.Array, extras: jax.Array, q_c: jax.Array,
                  tenant_c: jax.Array, k: int, nprobe: int, slack: int,
                  nprobe_c=None):
    """IVF two-tier core: coarse centroid prefilter + member gather
    (``ops.ivf.gather_rows`` — the same candidate assembly as the classic
    IVF scan, barrier included), per-query tenant masking over the
    candidates, candidate scoring (exact bf16/f32, or int8-gathered coarse
    + exact rescore when ``shadow`` is present), and duplicate-row dedup
    at the top-k boundary. Both retrieval tiers are masks over the ONE
    candidate score matrix, same trick as the dense scans.

    Shard-local by construction when given per-shard tables whose member/
    extras entries are LOCAL row indices (``ops.ivf.shard_serve_tables``):
    the gathers then only touch the chip's own arena slice. Returns
    ``(gate_s [C], gate_r [C], ann_s [C,k], ann_r [C,k], n_dup [C])``
    with rows routed to the sentinel (``state.capacity``) where invalid;
    ``n_dup`` counts the duplicates the in-kernel dedup dropped.

    ``nprobe_c`` (optional [C] i32) makes the probe width RAGGED: the
    gather still visits the static ceiling ``nprobe`` clusters (the
    candidate tensor shape is a trace constant), but a query's candidates
    from clusters ranked at or past its own nprobe are masked invalid —
    per-query recall/latency trade as device data, one compiled kernel.
    The gather layout is cluster-rank-major (``gather_rows``), so the
    rank of a member candidate is just its position divided by the
    member-table width; extras stay valid at every probe width."""
    from lazzaro_tpu.ops.ivf import gather_rows

    cap = state.capacity
    L = nprobe * members.shape[1] + extras.shape[0]
    k_fetch = min(k + slack, L)
    g_fetch = min(1 + slack, L)
    qn = normalize(q_c)                               # [C, d] f32
    cand, safe = gather_rows(centroids, members, extras, qn, nprobe)
    valid = ((cand >= 0) & state.alive[safe]
             & (state.tenant_id[safe] == tenant_c[:, None]))
    if nprobe_c is not None:
        m_w = members.shape[1]
        pos = jnp.arange(L)
        in_members = pos < nprobe * m_w
        rank = pos // max(m_w, 1)
        valid = valid & (~in_members[None, :]
                         | (rank[None, :] < nprobe_c[:, None]))
    sup = state.is_super[safe]
    qd = qn.astype(state.emb.dtype)

    def rescore(rows_c, coarse_s):
        g = state.emb[_phys(state, rows_c)]           # [C, kf, d]
        ex = jnp.einsum("cd,ckd->ck", qd, g,
                        preferred_element_type=jnp.float32)
        return jnp.where(coarse_s > NEG_INF / 2, ex, NEG_INF)

    if shadow is None:
        vecs = state.emb[_phys(state, safe)]          # [C, L, d]
        sc = jnp.einsum("cd,cld->cl", qd, vecs,
                        preferred_element_type=jnp.float32)
        a_s0, a_pos = jax.lax.top_k(
            jnp.where(valid & ~sup, sc, NEG_INF), k_fetch)
        g_s0, g_pos = jax.lax.top_k(
            jnp.where(valid & sup, sc, NEG_INF), 1)
        # Consumer-split hazard (see _exact_two_tier): the top-k feeds
        # both the packed readback and the boost gather chain.
        a_s0, a_pos, g_s0, g_pos = jax.lax.optimization_barrier(
            (a_s0, a_pos, g_s0, g_pos))
        ann_ex = a_s0
        a_rows = jnp.take_along_axis(cand, a_pos, axis=1)
        gate_s = g_s0[:, 0]
        gate_r0 = jnp.take_along_axis(cand, g_pos, axis=1)[:, 0]
    else:
        from lazzaro_tpu.ops.quant import quantize_rows

        q8a, scale_a = shadow
        qq, qs = quantize_rows(qn)
        d8 = jnp.einsum("cd,cld->cl", qq, q8a[safe],
                        preferred_element_type=jnp.int32)
        coarse = (d8.astype(jnp.float32)
                  * qs[:, None] * scale_a[safe])      # [C, L]
        a_s0, a_pos = jax.lax.top_k(
            jnp.where(valid & ~sup, coarse, NEG_INF), k_fetch)
        g_s0, g_pos = jax.lax.top_k(
            jnp.where(valid & sup, coarse, NEG_INF), g_fetch)
        a_s0, a_pos, g_s0, g_pos = jax.lax.optimization_barrier(
            (a_s0, a_pos, g_s0, g_pos))
        # exact rescore of the few survivors from the master — scores
        # and the 0.4 gate verdict never see quantization error
        a_rows0 = jnp.take_along_axis(cand, a_pos, axis=1)
        a_rows_safe = jnp.where(a_s0 > NEG_INF / 2, a_rows0, cap)
        ann_ex = rescore(a_rows_safe, a_s0)
        g_rows0 = jnp.take_along_axis(cand, g_pos, axis=1)
        g_rows_safe = jnp.where(g_s0 > NEG_INF / 2, g_rows0, cap)
        gate_ex = rescore(g_rows_safe, g_s0)
        g_s, g_sel = jax.lax.top_k(gate_ex, 1)
        gate_s = g_s[:, 0]
        gate_r0 = jnp.take_along_axis(g_rows_safe, g_sel, axis=1)[:, 0]
        a_rows = a_rows_safe

    ann_s, ann_r, n_dup = _dedup_topk(ann_ex, a_rows, cap, k)
    gate_r = jnp.where(gate_s > NEG_INF / 2, gate_r0, cap)
    return gate_s, gate_r, ann_s, ann_r, n_dup


def _search_fused_ivf_scan(state: ArenaState, shadow, centroids: jax.Array,
                           members: jax.Array, extras: jax.Array,
                           csr_indptr: jax.Array, csr_nbr: jax.Array,
                           q: jax.Array, q_valid: jax.Array,
                           tenant: jax.Array, gate_on: jax.Array,
                           boost_on: jax.Array, super_gate: jax.Array,
                           k: int, nprobe: int, slack: int, cap_take: int,
                           max_nbr: int, k_q=None, cap_q=None,
                           nprobe_q=None, scan_chunk: int = 0,
                           sem=None, sem_block: int = 16):
    """IVF per-chunk compute phase: the coarse-prefilter two-tier core,
    then the shared gate/CSR/boost tail. ``k_q``/``cap_q``/``nprobe_q``
    make it ragged: the gather and candidate scan run to the static
    ceilings, each query masks at its own k / cap / probe-width boundary
    (see ``_search_fused_scan`` / ``_ivf_two_tier``)."""
    ragged = k_q is not None

    def body(q_c, valid_c, tenant_c, gate_c, boost_c, *rag):
        nprobe_c = rag[2] if ragged else None
        gate_s, gate_r, ann_s, ann_r, n_dup = _ivf_two_tier(
            state, shadow, centroids, members, extras, q_c, tenant_c, k,
            nprobe, slack, nprobe_c=nprobe_c)
        cap_c = None
        if ragged:
            k_c, cap_c = rag[0], rag[1]
            ann_s, ann_r = _ragged_topk_mask(ann_s, ann_r, k_c,
                                             state.capacity)
        fast, acc_rows, nbr_rows = _gate_and_boost_rows(
            state, csr_indptr, csr_nbr, gate_s, gate_r, ann_s, ann_r,
            valid_c, tenant_c, gate_c, boost_c, super_gate, cap_take,
            max_nbr, cap_c=cap_c)
        return (gate_s, gate_r, ann_s, ann_r, fast, acc_rows, nbr_rows,
                n_dup)

    arrays = (q, q_valid, tenant, gate_on, boost_on)
    if ragged:
        arrays = arrays + (k_q, cap_q, nprobe_q)
    if sem is None:
        return chunked_map_multi(body, arrays,
                                 chunk=min(scan_chunk or IVF_SERVE_CHUNK,
                                           IVF_SERVE_CHUNK))
    return _semantic_scan_core(body, arrays, state, sem, super_gate,
                               k=k, block=sem_block, nprobe_val=nprobe)


def _search_fused_ivf(
    state: ArenaState,
    shadow,                  # (q8 [cap+1, d] i8, scale [cap+1] f32) or None
    centroids: jax.Array,    # [C, d] f32 L2-normalized (ops/ivf.py build)
    members: jax.Array,      # [C, M] i32 arena rows, -1 padded
    extras: jax.Array,       # [E] i32 residual + fresh + super rows, -1 pad
    csr_indptr: jax.Array,
    csr_nbr: jax.Array,
    q: jax.Array,
    q_valid: jax.Array,
    tenant: jax.Array,
    gate_on: jax.Array,
    boost_on: jax.Array,
    now: jax.Array,
    super_gate: jax.Array,
    acc_boost: jax.Array,
    nbr_boost: jax.Array,
    k: int,
    nprobe: int,
    slack: int,
    cap_take: int,
    max_nbr: int,
    sem=None,
    sem_block: int = 16,
) -> Tuple[ArenaState, jax.Array]:
    """``search_fused`` with the IVF centroid prefilter + member gather as
    the coarse stage: ONE donated dispatch + ONE packed readback per
    coalesced batch in IVF mode. Only the arena state is donated — the
    centroid/member/extras tables and the optional int8 shadow are
    long-lived read-only replicas (the boost scatter touches salience/
    access/freshness, never embeddings or routing)."""
    res = _search_fused_ivf_scan(state, shadow, centroids, members, extras,
                                 csr_indptr, csr_nbr, q, q_valid, tenant,
                                 gate_on, boost_on, super_gate, k, nprobe,
                                 slack, cap_take, max_nbr, sem=sem,
                                 sem_block=sem_block)
    return _sem_finish(state, res, sem, now, acc_boost, nbr_boost)


search_fused_ivf, search_fused_ivf_copy = _donated_pair(
    _search_fused_ivf, static_argnames=("k", "nprobe", "slack", "cap_take",
                                        "max_nbr", "sem_block"))


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "slack",
                                             "cap_take", "max_nbr",
                                             "sem_block"))
def search_fused_ivf_read(state: ArenaState, shadow, centroids: jax.Array,
                          members: jax.Array, extras: jax.Array,
                          csr_indptr: jax.Array, csr_nbr: jax.Array,
                          q: jax.Array, q_valid: jax.Array,
                          tenant: jax.Array, gate_on: jax.Array,
                          super_gate: jax.Array, k: int, nprobe: int,
                          slack: int, cap_take: int, max_nbr: int,
                          sem=None, sem_block: int = 16
                          ) -> jax.Array:
    """Read-only twin of ``search_fused_ivf`` (pure ``search_memories``
    fleets in IVF mode): same coarse prefilter + candidate scan, no state
    mutation, no donation dance."""
    boost_off = jnp.zeros(q_valid.shape, bool)
    res = _search_fused_ivf_scan(
        state, shadow, centroids, members, extras, csr_indptr, csr_nbr, q,
        q_valid, tenant, gate_on, boost_off, super_gate, k, nprobe, slack,
        cap_take, max_nbr, sem=sem, sem_block=sem_block)
    return _sem_finish_read(res, sem)


# ---------------------------------------------------------------------------
# Ragged fused serving (ISSUE 7): the SAME three single-dispatch chat-turn
# programs, but per-query k / cap_take / nprobe are DEVICE DATA — int32
# sidecar columns riding next to the query batch — instead of trace
# constants. The static kernel constants collapse to per-mode CEILINGS
# (``k`` = serve_k_max, ``cap_take`` = the config cap, ``nprobe`` = the
# build's probe width): the scan bodies compute to the ceiling and each
# query masks at its own top-k boundary (``_ragged_topk_mask``), its own
# retrieval cap (``_gate_and_boost_rows`` cap_c), and its own probe width
# (``_ivf_two_tier`` nprobe_c). One compiled kernel per (mode × geometry)
# therefore serves ANY mix of request shapes — a k=100 request no longer
# re-keys the whole batch's kernel or inflates its neighbors' top-k
# beyond masked compute, and mixed-size traffic stops burning compile
# cache entries. The packed readback's n_live counter becomes the
# per-query live LENGTH (the PR 6 shortfall tail generalized): decode
# reads exactly k_i live entries per request out of the K-wide rows.
# ---------------------------------------------------------------------------


def _search_fused_ragged(
    state: ArenaState,
    csr_indptr: jax.Array,
    csr_nbr: jax.Array,
    q: jax.Array,            # [Q, d] padded query batch
    q_valid: jax.Array,      # [Q] bool
    tenant: jax.Array,       # [Q] i32
    gate_on: jax.Array,      # [Q] bool
    boost_on: jax.Array,     # [Q] bool
    k_q: jax.Array,          # [Q] i32 per-query k (0 for pad rows)
    cap_q: jax.Array,        # [Q] i32 per-query retrieval cap
    now: jax.Array,
    super_gate: jax.Array,
    acc_boost: jax.Array,
    nbr_boost: jax.Array,
    k: int,                  # STATIC k ceiling (serve_k_max)
    cap_take: int,           # STATIC cap ceiling
    max_nbr: int,
    scan_chunk: int = 0,     # planner streaming-width override (ISSUE 11)
    sem=None,
    sem_block: int = 16,
) -> Tuple[ArenaState, jax.Array]:
    """``search_fused`` with the per-query (k, cap) sidecar: ONE donated
    dispatch + ONE packed readback for a mixed-shape batch."""
    res = _search_fused_scan(state, csr_indptr, csr_nbr, q, q_valid, tenant,
                             gate_on, boost_on, super_gate, k, cap_take,
                             max_nbr, k_q=k_q, cap_q=cap_q,
                             scan_chunk=scan_chunk, sem=sem,
                             sem_block=sem_block)
    return _sem_finish(state, res, sem, now, acc_boost, nbr_boost)


search_fused_ragged, search_fused_ragged_copy = _donated_pair(
    _search_fused_ragged, static_argnames=("k", "cap_take", "max_nbr",
                                           "scan_chunk", "sem_block"))


@functools.partial(jax.jit, static_argnames=("k", "cap_take", "max_nbr",
                                             "scan_chunk", "sem_block"))
def search_fused_ragged_read(state: ArenaState, csr_indptr: jax.Array,
                             csr_nbr: jax.Array, q: jax.Array,
                             q_valid: jax.Array, tenant: jax.Array,
                             gate_on: jax.Array, k_q: jax.Array,
                             super_gate: jax.Array, k: int, cap_take: int,
                             max_nbr: int, scan_chunk: int = 0,
                             sem=None, sem_block: int = 16) -> jax.Array:
    """Read-only ragged twin (pure ``search_memories`` fleets): per-query
    k as data, no state mutation."""
    boost_off = jnp.zeros(q_valid.shape, bool)
    cap_q = jnp.zeros(q_valid.shape, jnp.int32)
    res = _search_fused_scan(
        state, csr_indptr, csr_nbr, q, q_valid, tenant, gate_on, boost_off,
        super_gate, k, cap_take, max_nbr, k_q=k_q, cap_q=cap_q,
        scan_chunk=scan_chunk, sem=sem, sem_block=sem_block)
    return _sem_finish_read(res, sem)


def _search_fused_quant_ragged(
    state: ArenaState,
    q8a: jax.Array,
    scale_a: jax.Array,
    csr_indptr: jax.Array,
    csr_nbr: jax.Array,
    q: jax.Array,
    q_valid: jax.Array,
    tenant: jax.Array,
    gate_on: jax.Array,
    boost_on: jax.Array,
    k_q: jax.Array,
    cap_q: jax.Array,
    now: jax.Array,
    super_gate: jax.Array,
    acc_boost: jax.Array,
    nbr_boost: jax.Array,
    k: int,
    slack: int,
    cap_take: int,
    max_nbr: int,
    scan_chunk: int = 0,
    sem=None,
    sem_block: int = 16,
) -> Tuple[ArenaState, jax.Array]:
    """``search_fused_quant`` with the (k, cap) sidecar: the int8 coarse
    fetch and exact rescore run to the k ceiling, the boundary is data."""
    res = _search_fused_quant_scan(state, q8a, scale_a, csr_indptr, csr_nbr,
                                   q, q_valid, tenant, gate_on, boost_on,
                                   super_gate, k, slack, cap_take, max_nbr,
                                   k_q=k_q, cap_q=cap_q,
                                   scan_chunk=scan_chunk, sem=sem,
                                   sem_block=sem_block)
    return _sem_finish(state, res, sem, now, acc_boost, nbr_boost)


search_fused_quant_ragged, search_fused_quant_ragged_copy = _donated_pair(
    _search_fused_quant_ragged,
    static_argnames=("k", "slack", "cap_take", "max_nbr", "scan_chunk",
                     "sem_block"))


@functools.partial(jax.jit, static_argnames=("k", "slack", "cap_take",
                                             "max_nbr", "scan_chunk",
                                             "sem_block"))
def search_fused_quant_ragged_read(state: ArenaState, q8a: jax.Array,
                                   scale_a: jax.Array,
                                   csr_indptr: jax.Array,
                                   csr_nbr: jax.Array, q: jax.Array,
                                   q_valid: jax.Array, tenant: jax.Array,
                                   gate_on: jax.Array, k_q: jax.Array,
                                   super_gate: jax.Array, k: int,
                                   slack: int, cap_take: int,
                                   max_nbr: int, scan_chunk: int = 0,
                                   sem=None,
                                   sem_block: int = 16) -> jax.Array:
    boost_off = jnp.zeros(q_valid.shape, bool)
    cap_q = jnp.zeros(q_valid.shape, jnp.int32)
    res = _search_fused_quant_scan(
        state, q8a, scale_a, csr_indptr, csr_nbr, q, q_valid, tenant,
        gate_on, boost_off, super_gate, k, slack, cap_take, max_nbr,
        k_q=k_q, cap_q=cap_q, scan_chunk=scan_chunk, sem=sem,
        sem_block=sem_block)
    return _sem_finish_read(res, sem)


def _search_fused_ivf_ragged(
    state: ArenaState,
    shadow,
    centroids: jax.Array,
    members: jax.Array,
    extras: jax.Array,
    csr_indptr: jax.Array,
    csr_nbr: jax.Array,
    q: jax.Array,
    q_valid: jax.Array,
    tenant: jax.Array,
    gate_on: jax.Array,
    boost_on: jax.Array,
    k_q: jax.Array,
    cap_q: jax.Array,
    nprobe_q: jax.Array,     # [Q] i32 per-query probe width (≤ nprobe)
    now: jax.Array,
    super_gate: jax.Array,
    acc_boost: jax.Array,
    nbr_boost: jax.Array,
    k: int,
    nprobe: int,             # STATIC probe ceiling (the build's width)
    slack: int,
    cap_take: int,
    max_nbr: int,
    scan_chunk: int = 0,
    sem=None,
    sem_block: int = 16,
) -> Tuple[ArenaState, jax.Array]:
    """``search_fused_ivf`` with the (k, cap, nprobe) sidecar: the member
    gather visits the ceiling probe width, each query masks candidates
    past its own — recall/latency per request, one kernel."""
    res = _search_fused_ivf_scan(state, shadow, centroids, members, extras,
                                 csr_indptr, csr_nbr, q, q_valid, tenant,
                                 gate_on, boost_on, super_gate, k, nprobe,
                                 slack, cap_take, max_nbr, k_q=k_q,
                                 cap_q=cap_q, nprobe_q=nprobe_q,
                                 scan_chunk=scan_chunk, sem=sem,
                                 sem_block=sem_block)
    return _sem_finish(state, res, sem, now, acc_boost, nbr_boost)


search_fused_ivf_ragged, search_fused_ivf_ragged_copy = _donated_pair(
    _search_fused_ivf_ragged,
    static_argnames=("k", "nprobe", "slack", "cap_take", "max_nbr",
                     "scan_chunk", "sem_block"))


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "slack",
                                             "cap_take", "max_nbr",
                                             "scan_chunk", "sem_block"))
def search_fused_ivf_ragged_read(state: ArenaState, shadow,
                                 centroids: jax.Array, members: jax.Array,
                                 extras: jax.Array, csr_indptr: jax.Array,
                                 csr_nbr: jax.Array, q: jax.Array,
                                 q_valid: jax.Array, tenant: jax.Array,
                                 gate_on: jax.Array, k_q: jax.Array,
                                 nprobe_q: jax.Array,
                                 super_gate: jax.Array, k: int, nprobe: int,
                                 slack: int, cap_take: int, max_nbr: int,
                                 scan_chunk: int = 0, sem=None,
                                 sem_block: int = 16) -> jax.Array:
    boost_off = jnp.zeros(q_valid.shape, bool)
    cap_q = jnp.zeros(q_valid.shape, jnp.int32)
    res = _search_fused_ivf_scan(
        state, shadow, centroids, members, extras, csr_indptr, csr_nbr, q,
        q_valid, tenant, gate_on, boost_off, super_gate, k, nprobe, slack,
        cap_take, max_nbr, k_q=k_q, cap_q=cap_q, nprobe_q=nprobe_q,
        scan_chunk=scan_chunk, sem=sem, sem_block=sem_block)
    return _sem_finish_read(res, sem)


# ---------------------------------------------------------------------------
# IVF × tiering (ISSUE 12): the coarse stage when BOTH a published IVF build
# and demoted rows exist — the dense-scan fallback PR 8 shipped with is gone.
# Hot candidates come from the IVF member gather (exact in-kernel rescore
# from the master, whose hot rows are intact), COLD rows come from the
# full-corpus int8 shadow restricted to the cold residency mask (demoted
# rows drop out of the member tables on demotion, and their master row is
# zeroed, so the shadow coarse path is the one structure that still covers
# them). The two candidate streams merge at the k+slack boundary with the
# same in-kernel row dedup as the IVF kernel, cold survivors keep their
# coarse score and ride the EXISTING bounded tier_cold_finish dispatch —
# the packed readback is layout-identical to the tiered kernels, so the
# host finish path is unchanged.
# ---------------------------------------------------------------------------


def _ivf_tiered_two_tier(state: ArenaState, q8a: jax.Array,
                         scale_a: jax.Array, cold: jax.Array,
                         centroids: jax.Array, members: jax.Array,
                         extras: jax.Array, q_c: jax.Array,
                         tenant_c: jax.Array, k: int, nprobe: int,
                         slack: int, nprobe_c=None):
    """Tier-aware IVF core: centroid prefilter + member gather for the hot
    tier (exact master rescore — members hold hot rows only; a cold row
    that slipped a member scrub is masked by the residency column, never
    exactly rescored against its zeroed master row), int8 coarse scan over
    the COLD rows only, blended top-(k+slack) with row dedup. The gate
    tier stays IVF-gathered (supers are pinned hot and every super row
    rides the extras). Returns ``(g_s, g_r, ann_s [C, k+slack], ann_r,
    n_dup, cold_any)`` — the tiered candidate-window contract."""
    from lazzaro_tpu.ops.ivf import gather_rows
    from lazzaro_tpu.ops.quant import quantize_rows

    cap = state.capacity
    n = _nrows(state)
    L = nprobe * members.shape[1] + extras.shape[0]
    k_fetch = min(k + slack, L + n)
    k_hot = min(k + slack, L)
    k_cold = min(k + slack, n)
    qn = normalize(q_c)                                   # [C, d] f32
    qd = qn.astype(state.emb.dtype)
    cand, safe = gather_rows(centroids, members, extras, qn, nprobe)
    valid = ((cand >= 0) & state.alive[safe] & ~cold[safe]
             & (state.tenant_id[safe] == tenant_c[:, None]))
    if nprobe_c is not None:
        m_w = members.shape[1]
        pos = jnp.arange(L)
        in_members = pos < nprobe * m_w
        rank = pos // max(m_w, 1)
        valid = valid & (~in_members[None, :]
                         | (rank[None, :] < nprobe_c[:, None]))
    sup = state.is_super[safe]
    vecs = state.emb[_phys(state, safe)]                  # [C, L, d]
    sc = jnp.einsum("cd,cld->cl", qd, vecs,
                    preferred_element_type=jnp.float32)
    h_s, h_pos = jax.lax.top_k(jnp.where(valid & ~sup, sc, NEG_INF), k_hot)
    g_s0, g_pos = jax.lax.top_k(jnp.where(valid & sup, sc, NEG_INF), 1)
    # cold tier: int8 coarse over the residency-masked full-corpus shadow
    qq, qs = quantize_rows(qn)
    dots = jax.lax.dot_general(
        qq, q8a, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)                 # [C, rows]
    coarse = dots.astype(jnp.float32) * qs[:, None] * scale_a[None, :]
    cold_m = (cold[None, :] & state.alive[None, :]
              & ~state.is_super[None, :]
              & (state.tenant_id[None, :] == tenant_c[:, None]))
    c_s, c_r = jax.lax.top_k(jnp.where(cold_m, coarse, NEG_INF), k_cold)
    h_s, h_pos, g_s0, g_pos, c_s, c_r = jax.lax.optimization_barrier(
        (h_s, h_pos, g_s0, g_pos, c_s, c_r))
    h_rows = jnp.take_along_axis(cand, h_pos, axis=1)
    # blended window: hot exact ++ cold coarse, one more top-k + dedup
    all_s = jnp.concatenate([h_s, c_s], axis=1)
    all_r = jnp.concatenate([h_rows, c_r], axis=1)
    ann_s, ann_r, n_dup = _dedup_topk(all_s, all_r, cap, k_fetch)
    is_cold = cold[jnp.minimum(ann_r, n - 1)] & (ann_s > NEG_INF / 2)
    cold_any = is_cold.any(axis=-1)
    gate_s = g_s0[:, 0]
    gate_r0 = jnp.take_along_axis(cand, g_pos, axis=1)[:, 0]
    gate_r = jnp.where(gate_s > NEG_INF / 2, gate_r0, cap)
    return gate_s, gate_r, ann_s, ann_r, n_dup, cold_any


def _search_fused_ivf_tiered_scan(state: ArenaState, q8a: jax.Array,
                                  scale_a: jax.Array, cold: jax.Array,
                                  centroids: jax.Array, members: jax.Array,
                                  extras: jax.Array, csr_indptr: jax.Array,
                                  csr_nbr: jax.Array, q: jax.Array,
                                  q_valid: jax.Array, tenant: jax.Array,
                                  gate_on: jax.Array, boost_on: jax.Array,
                                  super_gate: jax.Array, k: int,
                                  nprobe: int, slack: int, cap_take: int,
                                  max_nbr: int, k_q=None, cap_q=None,
                                  nprobe_q=None, scan_chunk: int = 0,
                                  sem=None, sem_block: int = 16):
    """IVF×tiered per-chunk compute: the tier-aware IVF core, then the
    shared gate/CSR/boost tail with cold-hit queries' boosts deferred to
    the bounded finish dispatch — exactly the tiered scan's contract, so
    ``tier.serve.tiered_decode_and_finish`` decodes this readback
    unchanged."""
    ragged = k_q is not None

    def chunk(q_c, valid_c, tenant_c, gate_c, boost_c, *rag):
        np_c = rag[2] if ragged else None
        g_s, g_r, ann_s, ann_r, n_dup, cold_any = _ivf_tiered_two_tier(
            state, q8a, scale_a, cold, centroids, members, extras, q_c,
            tenant_c, k, nprobe, slack, nprobe_c=np_c)
        cap_c = None
        if ragged:
            k_c, cap_c = rag[0], rag[1]
            kf = jnp.minimum(k_c + slack, ann_s.shape[1])
            ann_s, ann_r = _ragged_topk_mask(ann_s, ann_r, kf,
                                             state.capacity)
        fast, acc_rows, nbr_rows = _gate_and_boost_rows(
            state, csr_indptr, csr_nbr, g_s, g_r, ann_s, ann_r,
            valid_c, tenant_c, gate_c, boost_c & ~cold_any, super_gate,
            cap_take, max_nbr, cap_c=cap_c)
        return g_s, g_r, ann_s, ann_r, fast, acc_rows, nbr_rows, n_dup

    arrays = (q, q_valid, tenant, gate_on, boost_on)
    if ragged:
        arrays = arrays + (k_q, cap_q, nprobe_q)
    if sem is None:
        return chunked_map_multi(chunk, arrays,
                                 chunk=(scan_chunk or IVF_SERVE_CHUNK))
    return _semantic_scan_core(chunk, arrays, state, sem, super_gate,
                               k=k, block=sem_block, rag_slack=slack,
                               nprobe_val=nprobe)


def _search_fused_ivf_tiered(
    state: ArenaState,
    q8a: jax.Array,
    scale_a: jax.Array,
    cold: jax.Array,
    centroids: jax.Array,
    members: jax.Array,
    extras: jax.Array,
    csr_indptr: jax.Array,
    csr_nbr: jax.Array,
    q: jax.Array,
    q_valid: jax.Array,
    tenant: jax.Array,
    gate_on: jax.Array,
    boost_on: jax.Array,
    now: jax.Array,
    super_gate: jax.Array,
    acc_boost: jax.Array,
    nbr_boost: jax.Array,
    k: int,
    nprobe: int,
    slack: int,
    cap_take: int,
    max_nbr: int,
    sem=None,
    sem_block: int = 16,
) -> Tuple[ArenaState, jax.Array]:
    """ONE donated dispatch + ONE packed readback: IVF coarse stage for the
    hot tier, cold-masked int8 coarse for the demoted rows, tiered
    candidate window (k+slack wide) for the bounded finish."""
    res = _search_fused_ivf_tiered_scan(
        state, q8a, scale_a, cold, centroids, members, extras,
        csr_indptr, csr_nbr, q, q_valid, tenant, gate_on, boost_on,
        super_gate, k, nprobe, slack, cap_take, max_nbr, sem=sem,
        sem_block=sem_block)
    return _sem_finish(state, res, sem, now, acc_boost, nbr_boost)


search_fused_ivf_tiered, search_fused_ivf_tiered_copy = _donated_pair(
    _search_fused_ivf_tiered,
    static_argnames=("k", "nprobe", "slack", "cap_take", "max_nbr",
                     "sem_block"))


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "slack",
                                             "cap_take", "max_nbr",
                                             "sem_block"))
def search_fused_ivf_tiered_read(state: ArenaState, q8a: jax.Array,
                                 scale_a: jax.Array, cold: jax.Array,
                                 centroids: jax.Array, members: jax.Array,
                                 extras: jax.Array, csr_indptr: jax.Array,
                                 csr_nbr: jax.Array, q: jax.Array,
                                 q_valid: jax.Array, tenant: jax.Array,
                                 gate_on: jax.Array, super_gate: jax.Array,
                                 k: int, nprobe: int, slack: int,
                                 cap_take: int, max_nbr: int,
                                 sem=None, sem_block: int = 16) -> jax.Array:
    boost_off = jnp.zeros(q_valid.shape, bool)
    res = _search_fused_ivf_tiered_scan(
        state, q8a, scale_a, cold, centroids, members, extras,
        csr_indptr, csr_nbr, q, q_valid, tenant, gate_on, boost_off,
        super_gate, k, nprobe, slack, cap_take, max_nbr, sem=sem,
        sem_block=sem_block)
    return _sem_finish_read(res, sem)


def _search_fused_ivf_tiered_ragged(
    state: ArenaState,
    q8a: jax.Array,
    scale_a: jax.Array,
    cold: jax.Array,
    centroids: jax.Array,
    members: jax.Array,
    extras: jax.Array,
    csr_indptr: jax.Array,
    csr_nbr: jax.Array,
    q: jax.Array,
    q_valid: jax.Array,
    tenant: jax.Array,
    gate_on: jax.Array,
    boost_on: jax.Array,
    k_q: jax.Array,
    cap_q: jax.Array,
    nprobe_q: jax.Array,
    now: jax.Array,
    super_gate: jax.Array,
    acc_boost: jax.Array,
    nbr_boost: jax.Array,
    k: int,
    nprobe: int,
    slack: int,
    cap_take: int,
    max_nbr: int,
    scan_chunk: int = 0,
    sem=None,
    sem_block: int = 16,
) -> Tuple[ArenaState, jax.Array]:
    """IVF×tiered serving with the (k, cap, nprobe) sidecar."""
    res = _search_fused_ivf_tiered_scan(
        state, q8a, scale_a, cold, centroids, members, extras,
        csr_indptr, csr_nbr, q, q_valid, tenant, gate_on, boost_on,
        super_gate, k, nprobe, slack, cap_take, max_nbr, k_q=k_q,
        cap_q=cap_q, nprobe_q=nprobe_q, scan_chunk=scan_chunk, sem=sem,
        sem_block=sem_block)
    return _sem_finish(state, res, sem, now, acc_boost, nbr_boost)


search_fused_ivf_tiered_ragged, search_fused_ivf_tiered_ragged_copy = \
    _donated_pair(_search_fused_ivf_tiered_ragged,
                  static_argnames=("k", "nprobe", "slack", "cap_take",
                                   "max_nbr", "scan_chunk", "sem_block"))


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "slack",
                                             "cap_take", "max_nbr",
                                             "scan_chunk", "sem_block"))
def search_fused_ivf_tiered_ragged_read(
        state: ArenaState, q8a: jax.Array, scale_a: jax.Array,
        cold: jax.Array, centroids: jax.Array, members: jax.Array,
        extras: jax.Array, csr_indptr: jax.Array, csr_nbr: jax.Array,
        q: jax.Array, q_valid: jax.Array, tenant: jax.Array,
        gate_on: jax.Array, k_q: jax.Array, nprobe_q: jax.Array,
        super_gate: jax.Array, k: int, nprobe: int, slack: int,
        cap_take: int, max_nbr: int, scan_chunk: int = 0,
        sem=None, sem_block: int = 16) -> jax.Array:
    boost_off = jnp.zeros(q_valid.shape, bool)
    cap_q = jnp.zeros(q_valid.shape, jnp.int32)
    res = _search_fused_ivf_tiered_scan(
        state, q8a, scale_a, cold, centroids, members, extras,
        csr_indptr, csr_nbr, q, q_valid, tenant, gate_on, boost_off,
        super_gate, k, nprobe, slack, cap_take, max_nbr, k_q=k_q,
        cap_q=cap_q, nprobe_q=nprobe_q, scan_chunk=scan_chunk, sem=sem,
        sem_block=sem_block)
    return _sem_finish_read(res, sem)


# ---------------------------------------------------------------------------
# Fused IVF-PQ serving (ISSUE 16): the last serving mode leaves the classic
# multi-dispatch path. The ADC table build (query × codebook sub-distances),
# the m-byte PQ scan over the top-nprobe clusters' LIVE member tables (the
# PR 12 donated tables — PQ finally sees online IVF), the exact f32
# shortlist rescore from gathered master rows at the coarse_fetch_slack
# window, and the super-gate/CSR-gather/boost-scatter tail all fuse into
# ONE donated dispatch + ONE packed readback. Structurally this is the int8
# branch of ``_ivf_two_tier`` with the coarse stage swapped: instead of a
# d-byte int8 row the candidate costs m bytes (m·1-byte code gather + m LUT
# adds), so the coarse tier reads ~d/m× less HBM per candidate — the
# substrate for the billion-row full-corpus scan (ROADMAP item 5). The gate
# verdict and every returned score come from the exact rescore, so ADC
# error never leaks past the shortlist boundary.
# ---------------------------------------------------------------------------


def _pq_flat_lut(book_cent: jax.Array, qn: jax.Array) -> jax.Array:
    """ADC lookup tables for a query chunk: each query's inner product
    with every subspace centroid, flattened to ``[C, m·256]`` so a row's
    score is an m-gather + sum over its byte codes (offset by subspace).
    The build is tiny — m gemms of [C, dsub]×[dsub, 256] — and amortizes
    over every candidate the chunk touches (same LUT layout as the
    classic ``ops.pq.ivf_pq_search``, traced into the fused program)."""
    m, _, dsub = book_cent.shape
    lut = jnp.einsum("cmd,mkd->cmk", qn.reshape(qn.shape[0], m, dsub),
                     book_cent, preferred_element_type=jnp.float32)
    return lut.reshape(qn.shape[0], m * 256)


def _pq_adc_scores(flat_lut: jax.Array, codes_g: jax.Array) -> jax.Array:
    """Asymmetric-distance scores for per-query gathered codes: ``codes_g
    [C, L, m]`` u8 → ``[C, L]`` f32 approximate inner products. One take
    per (candidate, subspace) against the query's flat LUT."""
    m = codes_g.shape[-1]
    offs = (jnp.arange(m) * 256).astype(jnp.int32)
    idx = codes_g.astype(jnp.int32) + offs[None, None, :]
    return jax.vmap(
        lambda fl, ix: jnp.take(fl, ix, axis=0).sum(-1))(flat_lut, idx)


def _pq_two_tier(state: ArenaState, book_cent: jax.Array, codes: jax.Array,
                 centroids: jax.Array, members: jax.Array,
                 extras: jax.Array, q_c: jax.Array, tenant_c: jax.Array,
                 k: int, nprobe: int, slack: int, nprobe_c=None):
    """IVF-PQ two-tier core: coarse centroid prefilter + member gather
    (``ops.ivf.gather_rows`` — identical candidate assembly to the IVF
    kernels, extras included, so fresh/residual/super rows are always in
    the window), ADC coarse scoring from the m-byte codes, exact f32
    rescore of the k+slack shortlist from the master arena, duplicate-row
    dedup at the top-k boundary. The incremental ``_pq_scatter`` keeps
    every live row's codes current, so no candidate needs a staleness
    escape hatch. Shard-local by construction when given per-shard tables
    with LOCAL row indices (the codes slab row-shards with the master).
    Returns the ``(gate_s, gate_r, ann_s, ann_r, n_dup)`` contract of
    ``_ivf_two_tier``; ``nprobe_c`` raggedness is identical."""
    from lazzaro_tpu.ops.ivf import gather_rows

    cap = state.capacity
    L = nprobe * members.shape[1] + extras.shape[0]
    k_fetch = min(k + slack, L)
    g_fetch = min(1 + slack, L)
    qn = normalize(q_c)                               # [C, d] f32
    cand, safe = gather_rows(centroids, members, extras, qn, nprobe)
    valid = ((cand >= 0) & state.alive[safe]
             & (state.tenant_id[safe] == tenant_c[:, None]))
    if nprobe_c is not None:
        m_w = members.shape[1]
        pos = jnp.arange(L)
        in_members = pos < nprobe * m_w
        rank = pos // max(m_w, 1)
        valid = valid & (~in_members[None, :]
                         | (rank[None, :] < nprobe_c[:, None]))
    sup = state.is_super[safe]
    qd = qn.astype(state.emb.dtype)

    # coarse tier: m bytes per candidate — the LUT gather, not a matmul
    flat_lut = _pq_flat_lut(book_cent, qn)
    coarse = _pq_adc_scores(flat_lut, codes[safe])    # [C, L]
    a_s0, a_pos = jax.lax.top_k(
        jnp.where(valid & ~sup, coarse, NEG_INF), k_fetch)
    g_s0, g_pos = jax.lax.top_k(
        jnp.where(valid & sup, coarse, NEG_INF), g_fetch)
    a_s0, a_pos, g_s0, g_pos = jax.lax.optimization_barrier(
        (a_s0, a_pos, g_s0, g_pos))

    # exact rescore of the few survivors from the master — scores and the
    # gate verdict never see ADC error (same contract as the int8 path)
    def rescore(rows_c, coarse_s):
        g = state.emb[_phys(state, rows_c)]           # [C, kf, d]
        ex = jnp.einsum("cd,ckd->ck", qd, g,
                        preferred_element_type=jnp.float32)
        return jnp.where(coarse_s > NEG_INF / 2, ex, NEG_INF)

    a_rows0 = jnp.take_along_axis(cand, a_pos, axis=1)
    a_rows_safe = jnp.where(a_s0 > NEG_INF / 2, a_rows0, cap)
    ann_ex = rescore(a_rows_safe, a_s0)
    g_rows0 = jnp.take_along_axis(cand, g_pos, axis=1)
    g_rows_safe = jnp.where(g_s0 > NEG_INF / 2, g_rows0, cap)
    gate_ex = rescore(g_rows_safe, g_s0)
    g_s, g_sel = jax.lax.top_k(gate_ex, 1)
    gate_s = g_s[:, 0]
    gate_r0 = jnp.take_along_axis(g_rows_safe, g_sel, axis=1)[:, 0]
    ann_s, ann_r, n_dup = _dedup_topk(ann_ex, a_rows_safe, cap, k)
    gate_r = jnp.where(gate_s > NEG_INF / 2, gate_r0, cap)
    return gate_s, gate_r, ann_s, ann_r, n_dup


def _search_fused_pq_scan(state: ArenaState, book_cent: jax.Array,
                          codes: jax.Array, centroids: jax.Array,
                          members: jax.Array, extras: jax.Array,
                          csr_indptr: jax.Array, csr_nbr: jax.Array,
                          q: jax.Array, q_valid: jax.Array,
                          tenant: jax.Array, gate_on: jax.Array,
                          boost_on: jax.Array, super_gate: jax.Array,
                          k: int, nprobe: int, slack: int, cap_take: int,
                          max_nbr: int, k_q=None, cap_q=None,
                          nprobe_q=None, scan_chunk: int = 0,
                          sem=None, sem_block: int = 16):
    """PQ per-chunk compute phase: the ADC two-tier core, then the shared
    gate/CSR/boost tail. Ragged sidecars behave exactly as in
    ``_search_fused_ivf_scan``."""
    ragged = k_q is not None

    def body(q_c, valid_c, tenant_c, gate_c, boost_c, *rag):
        nprobe_c = rag[2] if ragged else None
        gate_s, gate_r, ann_s, ann_r, n_dup = _pq_two_tier(
            state, book_cent, codes, centroids, members, extras, q_c,
            tenant_c, k, nprobe, slack, nprobe_c=nprobe_c)
        cap_c = None
        if ragged:
            k_c, cap_c = rag[0], rag[1]
            ann_s, ann_r = _ragged_topk_mask(ann_s, ann_r, k_c,
                                             state.capacity)
        fast, acc_rows, nbr_rows = _gate_and_boost_rows(
            state, csr_indptr, csr_nbr, gate_s, gate_r, ann_s, ann_r,
            valid_c, tenant_c, gate_c, boost_c, super_gate, cap_take,
            max_nbr, cap_c=cap_c)
        return (gate_s, gate_r, ann_s, ann_r, fast, acc_rows, nbr_rows,
                n_dup)

    arrays = (q, q_valid, tenant, gate_on, boost_on)
    if ragged:
        arrays = arrays + (k_q, cap_q, nprobe_q)
    if sem is None:
        return chunked_map_multi(body, arrays,
                                 chunk=min(scan_chunk or IVF_SERVE_CHUNK,
                                           IVF_SERVE_CHUNK))
    return _semantic_scan_core(body, arrays, state, sem, super_gate,
                               k=k, block=sem_block, nprobe_val=nprobe)


def _search_fused_pq(
    state: ArenaState,
    book_cent: jax.Array,    # [m, 256, dsub] f32 frozen PQ codebook
    codes: jax.Array,        # [cap+1, m] u8 live codes (incrementally kept)
    centroids: jax.Array,    # [C, d] f32 L2-normalized (ops/ivf.py build)
    members: jax.Array,      # [C, M] i32 arena rows, -1 padded
    extras: jax.Array,       # [E] i32 residual + fresh + super rows, -1 pad
    csr_indptr: jax.Array,
    csr_nbr: jax.Array,
    q: jax.Array,
    q_valid: jax.Array,
    tenant: jax.Array,
    gate_on: jax.Array,
    boost_on: jax.Array,
    now: jax.Array,
    super_gate: jax.Array,
    acc_boost: jax.Array,
    nbr_boost: jax.Array,
    k: int,
    nprobe: int,
    slack: int,
    cap_take: int,
    max_nbr: int,
    sem=None,
    sem_block: int = 16,
) -> Tuple[ArenaState, jax.Array]:
    """``search_fused_ivf`` with the m-byte ADC scan as the coarse stage:
    ONE donated dispatch + ONE packed readback per coalesced batch in PQ
    mode. Only the arena state is donated — the codebook, codes slab, and
    coarse tables are long-lived read-only replicas (the boost scatter
    touches salience/access/freshness, never embeddings or codes)."""
    res = _search_fused_pq_scan(state, book_cent, codes, centroids, members,
                                extras, csr_indptr, csr_nbr, q, q_valid,
                                tenant, gate_on, boost_on, super_gate, k,
                                nprobe, slack, cap_take, max_nbr, sem=sem,
                                sem_block=sem_block)
    return _sem_finish(state, res, sem, now, acc_boost, nbr_boost)


search_fused_pq, search_fused_pq_copy = _donated_pair(
    _search_fused_pq, static_argnames=("k", "nprobe", "slack", "cap_take",
                                       "max_nbr", "sem_block"))


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "slack",
                                             "cap_take", "max_nbr",
                                             "sem_block"))
def search_fused_pq_read(state: ArenaState, book_cent: jax.Array,
                         codes: jax.Array, centroids: jax.Array,
                         members: jax.Array, extras: jax.Array,
                         csr_indptr: jax.Array, csr_nbr: jax.Array,
                         q: jax.Array, q_valid: jax.Array,
                         tenant: jax.Array, gate_on: jax.Array,
                         super_gate: jax.Array, k: int, nprobe: int,
                         slack: int, cap_take: int, max_nbr: int,
                         sem=None, sem_block: int = 16
                         ) -> jax.Array:
    """Read-only twin of ``search_fused_pq`` (pure ``search_memories``
    fleets in PQ mode): same ADC scan + exact rescore, no state mutation,
    no donation dance."""
    boost_off = jnp.zeros(q_valid.shape, bool)
    res = _search_fused_pq_scan(
        state, book_cent, codes, centroids, members, extras, csr_indptr,
        csr_nbr, q, q_valid, tenant, gate_on, boost_off, super_gate, k,
        nprobe, slack, cap_take, max_nbr, sem=sem, sem_block=sem_block)
    return _sem_finish_read(res, sem)


def _search_fused_pq_ragged(
    state: ArenaState,
    book_cent: jax.Array,
    codes: jax.Array,
    centroids: jax.Array,
    members: jax.Array,
    extras: jax.Array,
    csr_indptr: jax.Array,
    csr_nbr: jax.Array,
    q: jax.Array,
    q_valid: jax.Array,
    tenant: jax.Array,
    gate_on: jax.Array,
    boost_on: jax.Array,
    k_q: jax.Array,
    cap_q: jax.Array,
    nprobe_q: jax.Array,     # [Q] i32 per-query probe width (≤ nprobe)
    now: jax.Array,
    super_gate: jax.Array,
    acc_boost: jax.Array,
    nbr_boost: jax.Array,
    k: int,
    nprobe: int,             # STATIC probe ceiling (the build's width)
    slack: int,
    cap_take: int,
    max_nbr: int,
    scan_chunk: int = 0,
    sem=None,
    sem_block: int = 16,
) -> Tuple[ArenaState, jax.Array]:
    """``search_fused_pq`` with the (k, cap, nprobe) sidecar: the member
    gather and ADC scan run to the ceilings, each query masks at its own
    boundaries — one compiled PQ kernel for mixed-shape traffic."""
    res = _search_fused_pq_scan(state, book_cent, codes, centroids, members,
                                extras, csr_indptr, csr_nbr, q, q_valid,
                                tenant, gate_on, boost_on, super_gate, k,
                                nprobe, slack, cap_take, max_nbr, k_q=k_q,
                                cap_q=cap_q, nprobe_q=nprobe_q,
                                scan_chunk=scan_chunk, sem=sem,
                                sem_block=sem_block)
    return _sem_finish(state, res, sem, now, acc_boost, nbr_boost)


search_fused_pq_ragged, search_fused_pq_ragged_copy = _donated_pair(
    _search_fused_pq_ragged,
    static_argnames=("k", "nprobe", "slack", "cap_take", "max_nbr",
                     "scan_chunk", "sem_block"))


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "slack",
                                             "cap_take", "max_nbr",
                                             "scan_chunk", "sem_block"))
def search_fused_pq_ragged_read(state: ArenaState, book_cent: jax.Array,
                                codes: jax.Array, centroids: jax.Array,
                                members: jax.Array, extras: jax.Array,
                                csr_indptr: jax.Array, csr_nbr: jax.Array,
                                q: jax.Array, q_valid: jax.Array,
                                tenant: jax.Array, gate_on: jax.Array,
                                k_q: jax.Array, nprobe_q: jax.Array,
                                super_gate: jax.Array, k: int, nprobe: int,
                                slack: int, cap_take: int, max_nbr: int,
                                scan_chunk: int = 0, sem=None,
                                sem_block: int = 16) -> jax.Array:
    boost_off = jnp.zeros(q_valid.shape, bool)
    cap_q = jnp.zeros(q_valid.shape, jnp.int32)
    res = _search_fused_pq_scan(
        state, book_cent, codes, centroids, members, extras, csr_indptr,
        csr_nbr, q, q_valid, tenant, gate_on, boost_off, super_gate, k,
        nprobe, slack, cap_take, max_nbr, k_q=k_q, cap_q=cap_q,
        nprobe_q=nprobe_q, scan_chunk=scan_chunk, sem=sem,
        sem_block=sem_block)
    return _sem_finish_read(res, sem)


# ---------------------------------------------------------------------------
# PQ × tiering (ISSUE 16): lifts the last tiering incompatibility. Hot
# candidates come from the IVF member gather with exact in-kernel rescore —
# unchanged from the IVF×tiered kernel — and COLD rows come from the
# full-corpus ADC scan restricted to the cold residency mask (a demoted
# row's master embedding is zeroed, but its m-byte codes stay valid: the
# incremental scatter only touches written rows, and the re-seed full
# encode patches cold rows from the host ColdStore). The blended k+slack
# window, the deferred boosts, and the packed readback are layout-identical
# to the tiered kernels, so ``tier.serve.tiered_decode_and_finish`` —
# including the bounded exact-rescore finish dispatch for cold survivors —
# runs unchanged.
# ---------------------------------------------------------------------------


def _pq_tiered_two_tier(state: ArenaState, book_cent: jax.Array,
                        codes: jax.Array, cold: jax.Array,
                        centroids: jax.Array, members: jax.Array,
                        extras: jax.Array, q_c: jax.Array,
                        tenant_c: jax.Array, k: int, nprobe: int,
                        slack: int, nprobe_c=None):
    """Tier-aware PQ core: exact member gather for the hot tier, ADC
    coarse over the COLD rows only (m bytes per cold row — the cheapest
    full-corpus coverage any mode has), blended top-(k+slack) with row
    dedup. Contract identical to ``_ivf_tiered_two_tier``."""
    from lazzaro_tpu.ops.ivf import gather_rows

    cap = state.capacity
    n = _nrows(state)
    L = nprobe * members.shape[1] + extras.shape[0]
    k_fetch = min(k + slack, L + n)
    k_hot = min(k + slack, L)
    k_cold = min(k + slack, n)
    qn = normalize(q_c)                                   # [C, d] f32
    qd = qn.astype(state.emb.dtype)
    cand, safe = gather_rows(centroids, members, extras, qn, nprobe)
    valid = ((cand >= 0) & state.alive[safe] & ~cold[safe]
             & (state.tenant_id[safe] == tenant_c[:, None]))
    if nprobe_c is not None:
        m_w = members.shape[1]
        pos = jnp.arange(L)
        in_members = pos < nprobe * m_w
        rank = pos // max(m_w, 1)
        valid = valid & (~in_members[None, :]
                         | (rank[None, :] < nprobe_c[:, None]))
    sup = state.is_super[safe]
    vecs = state.emb[_phys(state, safe)]                  # [C, L, d]
    sc = jnp.einsum("cd,cld->cl", qd, vecs,
                    preferred_element_type=jnp.float32)
    h_s, h_pos = jax.lax.top_k(jnp.where(valid & ~sup, sc, NEG_INF), k_hot)
    g_s0, g_pos = jax.lax.top_k(jnp.where(valid & sup, sc, NEG_INF), 1)
    # cold tier: ADC coarse over the residency-masked full-corpus codes
    flat_lut = _pq_flat_lut(book_cent, qn)
    m = book_cent.shape[0]
    offs = (jnp.arange(m) * 256).astype(jnp.int32)
    idx_full = codes.astype(jnp.int32) + offs[None, :]    # [rows, m]
    coarse = jax.vmap(
        lambda fl: jnp.take(fl, idx_full, axis=0).sum(-1))(flat_lut)
    cold_m = (cold[None, :] & state.alive[None, :]
              & ~state.is_super[None, :]
              & (state.tenant_id[None, :] == tenant_c[:, None]))
    c_s, c_r = jax.lax.top_k(jnp.where(cold_m, coarse, NEG_INF), k_cold)
    h_s, h_pos, g_s0, g_pos, c_s, c_r = jax.lax.optimization_barrier(
        (h_s, h_pos, g_s0, g_pos, c_s, c_r))
    h_rows = jnp.take_along_axis(cand, h_pos, axis=1)
    # blended window: hot exact ++ cold coarse, one more top-k + dedup
    all_s = jnp.concatenate([h_s, c_s], axis=1)
    all_r = jnp.concatenate([h_rows, c_r], axis=1)
    ann_s, ann_r, n_dup = _dedup_topk(all_s, all_r, cap, k_fetch)
    is_cold = cold[jnp.minimum(ann_r, n - 1)] & (ann_s > NEG_INF / 2)
    cold_any = is_cold.any(axis=-1)
    gate_s = g_s0[:, 0]
    gate_r0 = jnp.take_along_axis(cand, g_pos, axis=1)[:, 0]
    gate_r = jnp.where(gate_s > NEG_INF / 2, gate_r0, cap)
    return gate_s, gate_r, ann_s, ann_r, n_dup, cold_any


def _search_fused_pq_tiered_scan(state: ArenaState, book_cent: jax.Array,
                                 codes: jax.Array, cold: jax.Array,
                                 centroids: jax.Array, members: jax.Array,
                                 extras: jax.Array, csr_indptr: jax.Array,
                                 csr_nbr: jax.Array, q: jax.Array,
                                 q_valid: jax.Array, tenant: jax.Array,
                                 gate_on: jax.Array, boost_on: jax.Array,
                                 super_gate: jax.Array, k: int,
                                 nprobe: int, slack: int, cap_take: int,
                                 max_nbr: int, k_q=None, cap_q=None,
                                 nprobe_q=None, scan_chunk: int = 0,
                                 sem=None, sem_block: int = 16):
    """PQ×tiered per-chunk compute: the tier-aware PQ core, then the
    shared gate/CSR/boost tail with cold-hit queries' boosts deferred to
    the bounded finish dispatch — the tiered scan's contract."""
    ragged = k_q is not None

    def chunk(q_c, valid_c, tenant_c, gate_c, boost_c, *rag):
        np_c = rag[2] if ragged else None
        g_s, g_r, ann_s, ann_r, n_dup, cold_any = _pq_tiered_two_tier(
            state, book_cent, codes, cold, centroids, members, extras,
            q_c, tenant_c, k, nprobe, slack, nprobe_c=np_c)
        cap_c = None
        if ragged:
            k_c, cap_c = rag[0], rag[1]
            kf = jnp.minimum(k_c + slack, ann_s.shape[1])
            ann_s, ann_r = _ragged_topk_mask(ann_s, ann_r, kf,
                                             state.capacity)
        fast, acc_rows, nbr_rows = _gate_and_boost_rows(
            state, csr_indptr, csr_nbr, g_s, g_r, ann_s, ann_r,
            valid_c, tenant_c, gate_c, boost_c & ~cold_any, super_gate,
            cap_take, max_nbr, cap_c=cap_c)
        return g_s, g_r, ann_s, ann_r, fast, acc_rows, nbr_rows, n_dup

    arrays = (q, q_valid, tenant, gate_on, boost_on)
    if ragged:
        arrays = arrays + (k_q, cap_q, nprobe_q)
    if sem is None:
        return chunked_map_multi(chunk, arrays,
                                 chunk=(scan_chunk or IVF_SERVE_CHUNK))
    return _semantic_scan_core(chunk, arrays, state, sem, super_gate,
                               k=k, block=sem_block, rag_slack=slack,
                               nprobe_val=nprobe)


def _search_fused_pq_tiered(
    state: ArenaState,
    book_cent: jax.Array,
    codes: jax.Array,
    cold: jax.Array,
    centroids: jax.Array,
    members: jax.Array,
    extras: jax.Array,
    csr_indptr: jax.Array,
    csr_nbr: jax.Array,
    q: jax.Array,
    q_valid: jax.Array,
    tenant: jax.Array,
    gate_on: jax.Array,
    boost_on: jax.Array,
    now: jax.Array,
    super_gate: jax.Array,
    acc_boost: jax.Array,
    nbr_boost: jax.Array,
    k: int,
    nprobe: int,
    slack: int,
    cap_take: int,
    max_nbr: int,
    sem=None,
    sem_block: int = 16,
) -> Tuple[ArenaState, jax.Array]:
    """ONE donated dispatch + ONE packed readback: IVF member gather for
    the hot tier, cold-masked ADC coarse for the demoted rows, tiered
    candidate window (k+slack wide) for the bounded finish."""
    res = _search_fused_pq_tiered_scan(
        state, book_cent, codes, cold, centroids, members, extras,
        csr_indptr, csr_nbr, q, q_valid, tenant, gate_on, boost_on,
        super_gate, k, nprobe, slack, cap_take, max_nbr, sem=sem,
        sem_block=sem_block)
    return _sem_finish(state, res, sem, now, acc_boost, nbr_boost)


search_fused_pq_tiered, search_fused_pq_tiered_copy = _donated_pair(
    _search_fused_pq_tiered,
    static_argnames=("k", "nprobe", "slack", "cap_take", "max_nbr",
                     "sem_block"))


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "slack",
                                             "cap_take", "max_nbr",
                                             "sem_block"))
def search_fused_pq_tiered_read(state: ArenaState, book_cent: jax.Array,
                                codes: jax.Array, cold: jax.Array,
                                centroids: jax.Array, members: jax.Array,
                                extras: jax.Array, csr_indptr: jax.Array,
                                csr_nbr: jax.Array, q: jax.Array,
                                q_valid: jax.Array, tenant: jax.Array,
                                gate_on: jax.Array, super_gate: jax.Array,
                                k: int, nprobe: int, slack: int,
                                cap_take: int, max_nbr: int,
                                sem=None, sem_block: int = 16) -> jax.Array:
    boost_off = jnp.zeros(q_valid.shape, bool)
    res = _search_fused_pq_tiered_scan(
        state, book_cent, codes, cold, centroids, members, extras,
        csr_indptr, csr_nbr, q, q_valid, tenant, gate_on, boost_off,
        super_gate, k, nprobe, slack, cap_take, max_nbr, sem=sem,
        sem_block=sem_block)
    return _sem_finish_read(res, sem)


def _search_fused_pq_tiered_ragged(
    state: ArenaState,
    book_cent: jax.Array,
    codes: jax.Array,
    cold: jax.Array,
    centroids: jax.Array,
    members: jax.Array,
    extras: jax.Array,
    csr_indptr: jax.Array,
    csr_nbr: jax.Array,
    q: jax.Array,
    q_valid: jax.Array,
    tenant: jax.Array,
    gate_on: jax.Array,
    boost_on: jax.Array,
    k_q: jax.Array,
    cap_q: jax.Array,
    nprobe_q: jax.Array,
    now: jax.Array,
    super_gate: jax.Array,
    acc_boost: jax.Array,
    nbr_boost: jax.Array,
    k: int,
    nprobe: int,
    slack: int,
    cap_take: int,
    max_nbr: int,
    scan_chunk: int = 0,
    sem=None,
    sem_block: int = 16,
) -> Tuple[ArenaState, jax.Array]:
    """PQ×tiered serving with the (k, cap, nprobe) sidecar."""
    res = _search_fused_pq_tiered_scan(
        state, book_cent, codes, cold, centroids, members, extras,
        csr_indptr, csr_nbr, q, q_valid, tenant, gate_on, boost_on,
        super_gate, k, nprobe, slack, cap_take, max_nbr, k_q=k_q,
        cap_q=cap_q, nprobe_q=nprobe_q, scan_chunk=scan_chunk,
        sem=sem, sem_block=sem_block)
    return _sem_finish(state, res, sem, now, acc_boost, nbr_boost)


search_fused_pq_tiered_ragged, search_fused_pq_tiered_ragged_copy = \
    _donated_pair(_search_fused_pq_tiered_ragged,
                  static_argnames=("k", "nprobe", "slack", "cap_take",
                                   "max_nbr", "scan_chunk", "sem_block"))


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "slack",
                                             "cap_take", "max_nbr",
                                             "scan_chunk", "sem_block"))
def search_fused_pq_tiered_ragged_read(
        state: ArenaState, book_cent: jax.Array, codes: jax.Array,
        cold: jax.Array, centroids: jax.Array, members: jax.Array,
        extras: jax.Array, csr_indptr: jax.Array, csr_nbr: jax.Array,
        q: jax.Array, q_valid: jax.Array, tenant: jax.Array,
        gate_on: jax.Array, k_q: jax.Array, nprobe_q: jax.Array,
        super_gate: jax.Array, k: int, nprobe: int, slack: int,
        cap_take: int, max_nbr: int, scan_chunk: int = 0,
        sem=None, sem_block: int = 16) -> jax.Array:
    boost_off = jnp.zeros(q_valid.shape, bool)
    cap_q = jnp.zeros(q_valid.shape, jnp.int32)
    res = _search_fused_pq_tiered_scan(
        state, book_cent, codes, cold, centroids, members, extras,
        csr_indptr, csr_nbr, q, q_valid, tenant, gate_on, boost_off,
        super_gate, k, nprobe, slack, cap_take, max_nbr, k_q=k_q,
        cap_q=cap_q, nprobe_q=nprobe_q, scan_chunk=scan_chunk, sem=sem,
        sem_block=sem_block)
    return _sem_finish_read(res, sem)


# ---------------------------------------------------------------------------
# Pod-scale fused serving (ISSUE 5): the SAME chat-turn program — two-tier
# scan, super gate, CSR neighbor gather, boost scatters — composed with the
# device mesh as ONE distributed shard_map dispatch + ONE packed readback.
#
# Geometry: every arena column (and the int8 shadow / per-shard IVF tables /
# per-shard CSR) is row-sharded over the mesh axis; queries and per-query
# metadata are replicated. Each chip runs the shard-local two-tier core
# over its own rows (exact, int8-coarse + exact rescore, or IVF centroid
# prefilter over LOCAL member tables), produces local top-(k[+slack])
# candidates, and the ONLY cross-chip traffic is (a) the k-candidate
# all_gather + global top-k merge (ops.topk.sharded_topk_merge — the
# make_sharded_topk combine) and (b) a small pmax that replicates the
# owner-gathered CSR neighbor windows. The gate verdict and the boost ROW
# LISTS are then replicated computation, and each chip scatters boosts for
# exactly the rows it owns (non-owned rows route out of range — XLA drops
# OOB scatter updates), so the whole tail is shard-local writes.
#
# Parity with the single-chip kernels is structural: the per-row score
# computation, mask arithmetic, neighbor dedup, and capped boost adds are
# the same code (_exact_two_tier / _quant_two_tier / _ivf_two_tier /
# _boost_scatter); only the partitioning differs.
# ---------------------------------------------------------------------------


class FusedShardedKernels(NamedTuple):
    """The jit entry points one ``make_fused_sharded`` call builds: the
    donated serving program, its copy-on-write twin (for callers that
    cannot prove sole ownership of the state), and the read-only twin for
    batches with no boosts requested. Tests and bench wrap the factory to
    count calls — each call is exactly ONE distributed dispatch."""

    serve: Callable
    serve_copy: Callable
    read: Callable


def _globalize_rows(rows: jax.Array, scores: jax.Array, shard: jax.Array,
                    local_n: int, n_shards: int) -> jax.Array:
    """Local candidate rows → global row ids; NEG_INF (masked/garbage)
    entries route to the GLOBAL sentinel row so they can never collide
    with a real row after the cross-chip merge."""
    sent = n_shards * local_n - 1
    return jnp.where(scores > NEG_INF / 2, rows + shard * local_n, sent)


def make_fused_sharded(mesh, axis: str, *, k: int, cap_take: int,
                       max_nbr: int, mode: str = "exact", slack: int = 0,
                       nprobe: int = 0, ragged: bool = False,
                       scan_chunk: int = 0,
                       sem: bool = False) -> FusedShardedKernels:
    """Build the distributed fused chat-turn serving program for ``mesh``.

    ``mode`` picks the shard-local coarse stage:

    - ``"exact"``     — bf16/f32 whole-shard scan (``_exact_two_tier``)
    - ``"quant"``     — int8 shadow coarse top-(k+slack) + exact rescore
                        (``_quant_two_tier``); extra tables ``(q8, scale)``
                        row-sharded like the master
    - ``"ivf"``       — centroid prefilter + LOCAL member gather
                        (``_ivf_two_tier``); tables ``(centroids [C,d]
                        replicated, members [n,C,M], extras [n,E])`` with
                        member/extras entries as LOCAL row indices per
                        shard (``ops.ivf.shard_serve_tables``)
    - ``"ivf_quant"`` — IVF prefilter + int8-gathered coarse + exact
                        rescore; tables ``(q8, scale, centroids, members,
                        extras)``
    - ``"pq"``        — IVF prefilter + m-byte ADC coarse + exact rescore
                        (``_pq_two_tier``, ISSUE 16); tables ``(book_cent
                        [m,256,dsub] replicated, codes [rows,m] row-
                        sharded with the master, centroids, members,
                        extras)`` — the ADC LUT build is replicated
                        arithmetic, candidates ride the existing merge

    Call signatures (tables is the mode's tuple above, ``()`` for exact):

    ``serve(state, tables, csr_indptr [n,L+1], csr_nbr [n,E], q [Q,d],
    q_valid [Q], tenant [Q], gate_on [Q], boost_on [Q], now, super_gate,
    acc_boost, nbr_boost) -> (state, packed [Q, 3+2k])`` — donates the
    state (ONE distributed dispatch, shard-local boost scatters in place);
    ``serve_copy`` is the non-donating twin; ``read(state, tables,
    csr_indptr, csr_nbr, q, q_valid, tenant, gate_on, super_gate) ->
    packed`` skips the mutation entirely.

    The per-shard CSR carries each chip's OWN rows' neighbor lists with
    GLOBAL neighbor ids; Q is bounded by the scheduler's padded batch
    (≤ ``QUERY_CHUNK`` — the local cores stream bigger fleets through the
    usual chunked tiles, IVF at ``IVF_SERVE_CHUNK`` to bound the gather
    footprint).

    ``ragged=True`` (ISSUE 7) builds the per-query-shape variant: ``k`` /
    ``cap_take`` / ``nprobe`` become static CEILINGS and the call
    signatures gain three replicated [Q] i32 sidecar columns —
    ``serve(state, tables, csr_indptr, csr_nbr, q, q_valid, tenant,
    gate_on, boost_on, k_q, cap_q, nprobe_q, now, super_gate, acc_boost,
    nbr_boost)`` and ``read(..., gate_on, k_q, nprobe_q, super_gate)`` —
    so ONE compiled distributed program serves any mix of request shapes
    (the shard-local scans and the all_gather merge run to the ceiling;
    each query masks at its own boundaries, ``ops.topk.sharded_topk_merge``
    applying the k mask at the merge). ``nprobe_q`` is accepted and
    ignored by the dense modes so every mode shares one ragged ABI.

    ``scan_chunk > 0`` (ISSUE 17 satellite — the pod twin of the ISSUE 11
    single-chip override) narrows every chip's shard-local streaming tile:
    the planner can fit an over-budget pod geometry by shrinking the
    ``[chunk, local_rows]`` score transient instead of splitting the turn
    into extra dispatches. Bit-identical results — only the streaming
    granularity changes — and still ONE distributed dispatch.

    ``sem=True`` (ISSUE 20) threads the semantic query-cache ring through
    the distributed program: every call signature gains a trailing
    ``sem_state = (ring, valid, head, thresh, mode_id)`` pytree
    (REPLICATED — the ring rides every chip identically) and the serve
    twins return ``(state, ring, packed)`` / read returns ``(ring,
    packed)``. The mesh variant is substitution-only: the probe,
    result substitution, and writeback are replicated arithmetic after
    the merge (the shard-local scans still run — skipping blocks would
    desynchronize the all_gather), so pod hits save the readback-side
    work and keep the ring warm for the single-chip replicas, and the
    packed layout still carries the per-query sem verdict column."""
    from jax.sharding import PartitionSpec as P

    from lazzaro_tpu.ops.topk import sharded_topk_merge
    from lazzaro_tpu.utils.compat import shard_map

    if mode not in ("exact", "quant", "ivf", "ivf_quant", "tiered", "pq"):
        raise ValueError(f"unknown fused-sharded mode {mode!r}")
    if cap_take > k:
        raise ValueError("cap_take must not exceed k")
    n_shards = mesh.shape[axis]
    chunk = scan_chunk or (IVF_SERVE_CHUNK
                           if mode.startswith("ivf") or mode == "pq"
                           else QUERY_CHUNK)
    # Tiered mode (ISSUE 8): the merged candidate block stays k+slack wide
    # so the host can finish cold-hit queries (exact rescore of host-
    # gathered rows + final re-rank) over the same window.
    k_merge = k + slack if mode == "tiered" else k

    def _scan_merge(arena, tables, q, tenant, k_q=None, nprobe_q=None):
        """Shard-local two-tier candidates → globalize → ONE all_gather +
        global top-k per tier. Returns replicated (gate_s [Q], gate_r [Q],
        ann_s [Q,k], ann_r [Q,k], n_dup [Q]) with GLOBAL row ids; the dup
        counter (IVF in-kernel dedup hits, per-shard counts summed with a
        tiny psum riding the same dispatch) is zero for the dense modes.
        ``k_q``/``nprobe_q`` make it ragged: local scans run to the
        ceiling, the merge masks each query at its own k boundary."""
        shard = jax.lax.axis_index(axis)
        local_n = arena.emb.shape[0]
        k_l = max(1, min(k, local_n))
        if mode == "quant":
            q8_l, scale_l = tables
        elif mode == "tiered":
            q8_l, scale_l, cold_l = tables
        elif mode == "ivf":
            cent, mem2, ext2 = tables
            mem_l, ext_l, shadow_l = mem2[0], ext2[0], None
        elif mode == "ivf_quant":
            q8_l, scale_l, cent, mem2, ext2 = tables
            mem_l, ext_l, shadow_l = mem2[0], ext2[0], (q8_l, scale_l)
        elif mode == "pq":
            book_l, codes_l, cent, mem2, ext2 = tables
            mem_l, ext_l = mem2[0], ext2[0]

        def core(q_c, tenant_c, *rag):
            nprobe_c = rag[0] if rag else None
            zeros = jnp.zeros((q_c.shape[0],), jnp.int32)
            off = jnp.zeros((q_c.shape[0],), bool)
            if mode == "exact":
                g_s, g_r, a_s, a_r = _exact_two_tier(arena, q_c, tenant_c,
                                                     1, k_l)
                return g_s, g_r, a_s, a_r, zeros, off
            if mode == "quant":
                g_s, g_r, a_s, a_r = _quant_two_tier(
                    arena, q8_l, scale_l, q_c, tenant_c, k_l, slack)
                return g_s, g_r, a_s, a_r, zeros, off
            if mode == "tiered":
                g_s, g_r, a_s, a_r, cold_c = _tiered_two_tier(
                    arena, q8_l, scale_l, cold_l, q_c, tenant_c, k_l,
                    slack)
                return g_s, g_r, a_s, a_r, zeros, cold_c
            if mode == "pq":
                g_s, g_r, a_s, a_r, n_dup = _pq_two_tier(
                    arena, book_l, codes_l, cent, mem_l, ext_l, q_c,
                    tenant_c, k_l, nprobe, slack, nprobe_c=nprobe_c)
                return g_s[:, None], g_r[:, None], a_s, a_r, n_dup, off
            g_s, g_r, a_s, a_r, n_dup = _ivf_two_tier(
                arena, shadow_l, cent, mem_l, ext_l, q_c, tenant_c, k_l,
                nprobe, slack, nprobe_c=nprobe_c)
            return g_s[:, None], g_r[:, None], a_s, a_r, n_dup, off

        arrays = (q, tenant)
        if nprobe_q is not None and (mode.startswith("ivf")
                                     or mode == "pq"):
            arrays = arrays + (nprobe_q,)
        g_s, g_r, a_s, a_r, dup_l, cold_l_q = chunked_map_multi(
            core, arrays, chunk=chunk)
        n_dup = jax.lax.psum(dup_l, axis)
        # a query is a cold hit if ANY shard's candidate window touched a
        # cold row — the psum rides the same dispatch
        cold_any = jax.lax.psum(cold_l_q.astype(jnp.int32), axis) > 0
        sent = n_shards * local_n - 1          # the global sentinel row
        k_q_eff = k_q if (k_q is None or mode != "tiered") else k_q + slack
        km = min(k_merge, n_shards * a_s.shape[1])
        ann_s, ann_r = sharded_topk_merge(
            axis, a_s, _globalize_rows(a_r, a_s, shard, local_n, n_shards),
            km, k_q=k_q_eff, sentinel=sent)
        g_ms, g_mr = sharded_topk_merge(
            axis, g_s, _globalize_rows(g_r, g_s, shard, local_n, n_shards),
            1)
        # The PR 2 consumer-split fix applies at the merge boundary too:
        # the merged top-k feeds both the packed readback and (in the
        # serve twins) the boost gather tail.
        return jax.lax.optimization_barrier(
            (g_ms[:, 0], g_mr[:, 0], ann_s, ann_r, n_dup, cold_any))

    def _boost_tail(arena, indptr_l, nbr_l, ann_s, ann_r, fast, q_valid,
                    tenant, boost_on, now, acc_boost, nbr_boost,
                    cap_q=None):
        """The gate/CSR/boost tail against the row-sharded edge arena:
        owner chips gather their rows' CSR neighbor windows (merged to all
        chips with one small pmax), the per-query dedup / in-result masks
        are replicated arithmetic on the merged id lists (exactly
        ``_csr_neighbor_rows``'s), and each chip scatters boosts ONLY for
        rows it owns — non-owned rows route out of range and XLA drops
        the updates, so no boost ever crosses a chip boundary."""
        shard = jax.lax.axis_index(axis)
        local_n = arena.emb.shape[0]
        sent = n_shards * local_n - 1          # == the global sentinel row
        do_boost = boost_on & q_valid & ~fast
        take = (ann_s[:, :cap_take] > NEG_INF / 2) & do_boost[:, None]
        if cap_q is not None:
            take = take & (jnp.arange(cap_take)[None, :] < cap_q[:, None])
        acc_rows = jnp.where(take, ann_r[:, :cap_take], sent)  # global rows
        base = shard * local_n
        loc = acc_rows - base
        mine = (loc >= 0) & (loc < local_n) & (acc_rows != sent)
        safe_loc = jnp.clip(loc, 0, local_n - 1)
        start = jnp.where(mine, indptr_l[safe_loc], 0)
        end = jnp.where(mine, indptr_l[safe_loc + 1], 0)
        idx = start[:, :, None] + jnp.arange(max_nbr)[None, None, :]
        ok = idx < end[:, :, None]
        nbrw = jnp.where(ok, nbr_l[jnp.minimum(idx, nbr_l.shape[0] - 1)],
                         -1)
        # exactly one chip owns each accessed row; everyone else holds -1,
        # so a pmax replicates the true windows — the only tail collective
        nbrw = jax.lax.pmax(nbrw, axis)
        flat = nbrw.reshape(nbrw.shape[0], -1)              # [Q, M]
        m = flat.shape[1]
        dup = ((flat[:, :, None] == flat[:, None, :])
               & jnp.tri(m, k=-1, dtype=bool)[None, :, :]).any(-1)
        in_res = (flat[:, :, None] == acc_rows[:, None, :]).any(-1)
        nloc = flat - base
        nmine = (nloc >= 0) & (nloc < local_n) & (flat >= 0)
        nsafe = jnp.clip(nloc, 0, local_n - 1)
        nvalid = (nmine & arena.alive[nsafe]
                  & (arena.tenant_id[nsafe] == tenant[:, None]))
        nbr_idx = jnp.where(nvalid & ~dup & ~in_res, nloc, local_n)
        acc_idx = jnp.where(mine, loc, local_n)
        # Device-side boost counters for the readback tail: the access
        # rows are replicated arithmetic (count once, identically on every
        # chip); the neighbor validity checks are per-owner, so the
        # per-chip counts sum with one tiny psum inside the same dispatch.
        n_acc = (acc_rows != sent).sum(axis=-1).astype(jnp.int32)
        n_nbr = jax.lax.psum(
            (nbr_idx != local_n).sum(axis=-1).astype(jnp.int32), axis)
        return _boost_scatter(arena, acc_idx, nbr_idx, now, acc_boost,
                              nbr_boost, zero_last=False), n_acc, n_nbr

    def _sem_apply(sem_state, sent, q, q_valid, tenant, gate_on,
                   super_gate, merged, k_q=None, nprobe_q=None):
        """Replicated probe → substitute → writeback after the merge.
        Every chip computes the identical verdicts and the identical next
        ring (replicated inputs, replicated arithmetic), so the ring's
        out-spec stays P(None...) with zero extra collectives."""
        ring, sem_valid, head, thresh, mode_id = sem_state
        gate_s, gate_r, ann_s, ann_r, n_dup, cold_any = merged
        nq = q.shape[0]
        qn = normalize(q).astype(jnp.float32)
        k_need = (k_q if k_q is not None
                  else jnp.full((nq,), k, jnp.int32))
        npr_need = (nprobe_q if nprobe_q is not None
                    else jnp.full((nq,), nprobe, jnp.int32))
        hit, slot = _semantic_probe(ring, sem_valid, qn, tenant, q_valid,
                                    gate_on, k_need, npr_need, mode_id,
                                    thresh)
        miss = q_valid & ~hit
        rank = jnp.cumsum(miss.astype(jnp.int32)) - 1
        n_miss = miss.sum().astype(jnp.int32)
        write_mask = miss & (rank >= n_miss - ring.slots)
        ring2 = _semantic_writeback(ring, head, qn, tenant, gate_on,
                                    gate_s, gate_r, ann_s, ann_r, rank,
                                    write_mask, k_need, npr_need, mode_id,
                                    sent)
        fast0 = gate_on & (gate_s > super_gate)
        rag_slack = slack if mode == "tiered" else 0
        gate_s, gate_r, ann_s, ann_r, fast = _semantic_substitute(
            ring, hit, slot, gate_on, super_gate,
            (gate_s, gate_r, ann_s, ann_r, fast0), k_q, rag_slack, sent)
        n_dup = jnp.where(hit, 0, n_dup)
        sem_col = jnp.where(hit, 1 + slot, 0).astype(jnp.int32)
        return (gate_s, gate_r, ann_s, ann_r, fast, n_dup,
                cold_any & ~hit, hit, sem_col, ring2)

    def _serve_local(arena, tables, indptr2, nbr2, q, q_valid, tenant,
                     gate_on, boost_on, now, super_gate, acc_boost,
                     nbr_boost, sem_state=None):
        merged = _scan_merge(arena, tables, q, tenant)
        if sem_state is None:
            gate_s, gate_r, ann_s, ann_r, n_dup, cold_any = merged
            fast = gate_on & (gate_s > super_gate)
            arena, n_acc, n_nbr = _boost_tail(
                arena, indptr2[0], nbr2[0], ann_s, ann_r, fast, q_valid,
                tenant, boost_on & ~cold_any, now, acc_boost, nbr_boost)
            packed = _pack_retrieval(gate_s, gate_r, ann_s, ann_r, fast,
                                     dup=n_dup, acc=n_acc, nbr=n_nbr)
            return arena, packed
        sent = n_shards * arena.emb.shape[0] - 1
        (gate_s, gate_r, ann_s, ann_r, fast, n_dup, cold_eff, hit,
         sem_col, ring2) = _sem_apply(sem_state, sent, q, q_valid, tenant,
                                      gate_on, super_gate, merged)
        arena, n_acc, n_nbr = _boost_tail(
            arena, indptr2[0], nbr2[0], ann_s, ann_r, fast, q_valid,
            tenant, boost_on & ~cold_eff & ~hit, now, acc_boost,
            nbr_boost)
        packed = _pack_retrieval(gate_s, gate_r, ann_s, ann_r, fast,
                                 dup=n_dup, acc=n_acc, nbr=n_nbr,
                                 sem=sem_col)
        return arena, ring2, packed

    def _read_local(arena, tables, indptr2, nbr2, q, q_valid, tenant,
                    gate_on, super_gate, sem_state=None):
        merged = _scan_merge(arena, tables, q, tenant)
        if sem_state is None:
            gate_s, gate_r, ann_s, ann_r, n_dup, _cold = merged
            fast = gate_on & (gate_s > super_gate)
            return _pack_retrieval(gate_s, gate_r, ann_s, ann_r, fast,
                                   dup=n_dup)
        sent = n_shards * arena.emb.shape[0] - 1
        (gate_s, gate_r, ann_s, ann_r, fast, n_dup, _cold, _hit,
         sem_col, ring2) = _sem_apply(sem_state, sent, q, q_valid, tenant,
                                      gate_on, super_gate, merged)
        return ring2, _pack_retrieval(gate_s, gate_r, ann_s, ann_r, fast,
                                      dup=n_dup, sem=sem_col)

    def _serve_local_ragged(arena, tables, indptr2, nbr2, q, q_valid,
                            tenant, gate_on, boost_on, k_q, cap_q,
                            nprobe_q, now, super_gate, acc_boost,
                            nbr_boost, sem_state=None):
        merged = _scan_merge(arena, tables, q, tenant, k_q=k_q,
                             nprobe_q=nprobe_q)
        if sem_state is None:
            gate_s, gate_r, ann_s, ann_r, n_dup, cold_any = merged
            fast = gate_on & (gate_s > super_gate)
            arena, n_acc, n_nbr = _boost_tail(
                arena, indptr2[0], nbr2[0], ann_s, ann_r, fast, q_valid,
                tenant, boost_on & ~cold_any, now, acc_boost, nbr_boost,
                cap_q=cap_q)
            packed = _pack_retrieval(gate_s, gate_r, ann_s, ann_r, fast,
                                     dup=n_dup, acc=n_acc, nbr=n_nbr)
            return arena, packed
        sent = n_shards * arena.emb.shape[0] - 1
        (gate_s, gate_r, ann_s, ann_r, fast, n_dup, cold_eff, hit,
         sem_col, ring2) = _sem_apply(sem_state, sent, q, q_valid, tenant,
                                      gate_on, super_gate, merged,
                                      k_q=k_q, nprobe_q=nprobe_q)
        arena, n_acc, n_nbr = _boost_tail(
            arena, indptr2[0], nbr2[0], ann_s, ann_r, fast, q_valid,
            tenant, boost_on & ~cold_eff & ~hit, now, acc_boost,
            nbr_boost, cap_q=cap_q)
        packed = _pack_retrieval(gate_s, gate_r, ann_s, ann_r, fast,
                                 dup=n_dup, acc=n_acc, nbr=n_nbr,
                                 sem=sem_col)
        return arena, ring2, packed

    def _read_local_ragged(arena, tables, indptr2, nbr2, q, q_valid,
                           tenant, gate_on, k_q, nprobe_q, super_gate,
                           sem_state=None):
        merged = _scan_merge(arena, tables, q, tenant, k_q=k_q,
                             nprobe_q=nprobe_q)
        if sem_state is None:
            gate_s, gate_r, ann_s, ann_r, n_dup, _cold = merged
            fast = gate_on & (gate_s > super_gate)
            return _pack_retrieval(gate_s, gate_r, ann_s, ann_r, fast,
                                   dup=n_dup)
        sent = n_shards * arena.emb.shape[0] - 1
        (gate_s, gate_r, ann_s, ann_r, fast, n_dup, _cold, _hit,
         sem_col, ring2) = _sem_apply(sem_state, sent, q, q_valid, tenant,
                                      gate_on, super_gate, merged,
                                      k_q=k_q, nprobe_q=nprobe_q)
        return ring2, _pack_retrieval(gate_s, gate_r, ann_s, ann_r, fast,
                                      dup=n_dup, sem=sem_col)

    state_specs = ArenaState(
        emb=P(axis, None), salience=P(axis), timestamp=P(axis),
        last_accessed=P(axis), access_count=P(axis), type_id=P(axis),
        shard_id=P(axis), tenant_id=P(axis), alive=P(axis),
        is_super=P(axis))
    tables_specs = {
        "exact": (),
        "quant": (P(axis, None), P(axis)),
        "tiered": (P(axis, None), P(axis), P(axis)),
        "ivf": (P(None, None), P(axis, None, None), P(axis, None)),
        "ivf_quant": (P(axis, None), P(axis), P(None, None),
                      P(axis, None, None), P(axis, None)),
        "pq": (P(None, None, None), P(axis, None), P(None, None),
               P(axis, None, None), P(axis, None)),
    }[mode]
    common = (state_specs, tables_specs, P(axis, None), P(axis, None),
              P(None, None), P(None), P(None), P(None))
    # Semantic ring (ISSUE 20): REPLICATED on every chip — the probe /
    # substitute / writeback are replicated arithmetic after the merge.
    ring_specs = SemanticRing(
        emb=P(None, None), tenant=P(None), gate_on=P(None), mode=P(None),
        stored_k=P(None), nprobe=P(None), gate_s=P(None), gate_r=P(None),
        ann_s=P(None, None), ann_r=P(None, None))
    sem_in = ((ring_specs, P(None), P(), P(), P()),) if sem else ()
    serve_out = ((state_specs, ring_specs, P(None, None)) if sem
                 else (state_specs, P(None, None)))
    read_out = (ring_specs, P(None, None)) if sem else P(None, None)
    if ragged:
        # + (boost_on, k_q, cap_q, nprobe_q) replicated sidecars
        mapped_serve = shard_map(
            _serve_local_ragged, mesh=mesh,
            in_specs=common + (P(None), P(None), P(None), P(None),
                               P(), P(), P(), P()) + sem_in,
            out_specs=serve_out, check_vma=False)
        mapped_read = shard_map(
            _read_local_ragged, mesh=mesh,
            in_specs=common + (P(None), P(None), P()) + sem_in,
            out_specs=read_out, check_vma=False)
    else:
        mapped_serve = shard_map(
            _serve_local, mesh=mesh,
            in_specs=common + (P(None), P(), P(), P(), P()) + sem_in,
            out_specs=serve_out, check_vma=False)
        mapped_read = shard_map(
            _read_local, mesh=mesh, in_specs=common + (P(),) + sem_in,
            out_specs=read_out, check_vma=False)
    return FusedShardedKernels(
        serve=jax.jit(mapped_serve, donate_argnums=(0,)),
        serve_copy=jax.jit(mapped_serve),
        read=jax.jit(mapped_read))


class LifecycleShardedKernels(NamedTuple):
    """The jit entry points one ``make_lifecycle_sharded`` call builds:
    the donated all-tenant sweep, its copy-on-write twin, and the
    read-only payload twin. Each call is exactly ONE distributed
    dispatch — the jit-counter tests wrap the factory to pin that."""

    sweep: Callable
    sweep_copy: Callable
    read: Callable


def make_lifecycle_sharded(mesh, axis: str, *, prune_cap: int,
                           archive_k: int) -> LifecycleShardedKernels:
    """Distributed twin of ``lifecycle_sweep``: the decay scatters and the
    importance arithmetic are element-wise over the row-sharded columns
    (shard-local, zero traffic), weak-edge compaction runs shard-local
    with victim slots globalized before ONE all_gather re-compaction, and
    the per-tenant bottom-k verdicts merge through ``sharded_topk_merge``
    (replicated verdict arithmetic — every chip holds the identical
    payload, so the host reads ONE replicated buffer).

    Call signature mirrors the single-chip jit: ``sweep(arena, edges,
    passes [Tc], verdict_tids [Tv], rate, floor, threshold, now, w_sal,
    w_acc, w_rec) -> (arena, edges, payload)`` with ``prune_cap`` /
    ``archive_k`` baked in at build time (the host caches one program per
    (prune_cap, archive_k) bucket, same discipline as the ingest
    factory). The payload's pruned-slot and verdict-row sections carry
    GLOBAL ids, so the host decode is identical to single-chip."""
    from jax.sharding import PartitionSpec as P

    from lazzaro_tpu.ops.topk import sharded_topk_merge
    from lazzaro_tpu.utils.compat import shard_map

    n_shards = mesh.shape[axis]

    def _local(arena, edges, passes, verdict_tids, rate, floor, threshold,
               now, w_sal, w_acc, w_rec):
        shard = jax.lax.axis_index(axis)
        local_n = arena.salience.shape[0]
        local_e = edges.src.shape[0]
        # full prune_cap per shard: skew-proof (one shard may hold every
        # weak edge) and still tiny — [prune_cap] i32 per chip
        arena, edges, v_imps_l, v_rows_l, slots_l, counters = \
            _lifecycle_core(arena, edges, passes, verdict_tids, rate,
                            floor, threshold, now, w_sal, w_acc, w_rec,
                            prune_cap, archive_k)
        # pruned slots: local → global ids, ONE all_gather, re-compact.
        # Shard-major flatten of ascending local slots IS globally
        # ascending, so the merged list keeps single-chip slot order.
        g_slots = jnp.where(slots_l >= 0, slots_l + shard * local_e, -1)
        flat = jax.lax.all_gather(g_slots, axis).reshape(-1)
        okg = flat >= 0
        posg = jnp.cumsum(okg.astype(jnp.int32)) - 1
        buf = jnp.full((prune_cap + 1,), -1, jnp.int32)
        buf = buf.at[jnp.where(okg & (posg < prune_cap),
                               jnp.minimum(posg, prune_cap - 1),
                               prune_cap)].set(flat)
        over_g = (okg & (posg >= prune_cap)).any().astype(jnp.int32)
        # verdicts: local bottom-k per tenant → globalize → merged bottom-k
        # (merge runs on negated importances so descending == bottom)
        neg_l = -v_imps_l
        g_rows = _globalize_rows(v_rows_l, neg_l, shard, local_n, n_shards)
        neg_m, rows_m = sharded_topk_merge(
            axis, neg_l, g_rows, archive_k,
            sentinel=n_shards * local_n - 1)
        cg = jax.lax.psum(counters, axis)
        cg = jnp.concatenate([
            cg[:4], jnp.maximum(jnp.minimum(cg[4:5], 1), over_g[None])])
        payload = _lifecycle_payload(-neg_m, rows_m, buf[:prune_cap], cg)
        return arena, edges, payload

    def _read_local(*args):
        return _local(*args)[2]

    state_specs = ArenaState(
        emb=P(axis, None), salience=P(axis), timestamp=P(axis),
        last_accessed=P(axis), access_count=P(axis), type_id=P(axis),
        shard_id=P(axis), tenant_id=P(axis), alive=P(axis),
        is_super=P(axis))
    edge_specs = EdgeState(
        src=P(axis), tgt=P(axis), weight=P(axis), co=P(axis),
        last_updated=P(axis), alive=P(axis), tenant_id=P(axis))
    in_specs = (state_specs, edge_specs, P(None), P(None),
                P(), P(), P(), P(), P(), P(), P())
    mapped = shard_map(_local, mesh=mesh, in_specs=in_specs,
                       out_specs=(state_specs, edge_specs, P(None)),
                       check_vma=False)
    mapped_read = shard_map(_read_local, mesh=mesh, in_specs=in_specs,
                            out_specs=P(None), check_vma=False)
    return LifecycleShardedKernels(
        sweep=jax.jit(mapped, donate_argnums=(0, 1)),
        sweep_copy=jax.jit(mapped),
        read=jax.jit(mapped_read))


def _arena_apply_boosts(state: ArenaState, rows: jax.Array,
                        acc_cnt: jax.Array, nbr_cnt: jax.Array,
                        now_vals: jax.Array, acc_boost: jax.Array,
                        nbr_boost: jax.Array) -> ArenaState:
    """Deferred boost flush: cache-hit chat turns accumulate (access,
    neighbor) boost COUNTS on the host instead of paying a device dispatch
    per turn; this scatter applies many turns' worth in one program.
    Positive capped adds commute, so applying the summed counts equals the
    serial per-turn sequence. ``now_vals`` carries each row's latest
    queue-time timestamp (padding rows use -inf so ``.max`` is a no-op)."""
    sal = state.salience.at[rows].add(
        acc_cnt.astype(jnp.float32) * acc_boost
        + nbr_cnt.astype(jnp.float32) * nbr_boost)
    return state.replace(
        salience=jnp.minimum(sal, 1.0),
        access_count=state.access_count.at[rows].add(acc_cnt),
        last_accessed=state.last_accessed.at[rows].max(now_vals))


arena_apply_boosts, arena_apply_boosts_copy = _donated_pair(
    _arena_apply_boosts)


@functools.partial(jax.jit, static_argnames=("max_neighbors",))
def edges_neighbors(state: EdgeState, rows: jax.Array, min_weight: jax.Array,
                    max_neighbors: int = 32) -> Tuple[jax.Array, jax.Array]:
    """Bidirectional neighbor lookup for a batch of node rows.

    Returns (neighbor_rows [B, max_neighbors] sentinel=-1, weights). Replaces
    the O(E) per-node scan in ``memory_shard.py:54-62``."""
    src, tgt = state.src, state.tgt
    live = state.alive & (state.weight >= min_weight)

    def one(row):
        out_mask = live & (src == row)
        in_mask = live & (tgt == row)
        cand = jnp.where(out_mask, tgt, jnp.where(in_mask, src, -1))
        w = jnp.where(out_mask | in_mask, state.weight, NEG_INF)
        top_w, idx = jax.lax.top_k(w, max_neighbors)
        neigh = jnp.where(top_w > NEG_INF / 2, cand[idx], -1)
        return neigh, jnp.where(top_w > NEG_INF / 2, top_w, 0.0)

    return jax.vmap(one)(rows)
