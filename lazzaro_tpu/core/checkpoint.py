"""Fast binary checkpoint of the HBM arena (index-scale save/restore).

The row-wise durable store (``core/store.py``) mirrors the reference's
LanceDB role: per-node dict rows, fine at conversational scale, but a
1M-node graph serializes ~1.5 GB of embeddings through Python lists —
minutes. This module is the TPU-scale complement: one bulk device→host
transfer per column, written as raw numpy arrays (``.npz``), with a small
JSON sidecar for host bookkeeping (id maps, tenant/shard vocabularies,
epoch). bfloat16 columns are bit-cast through uint16 since the npy format
has no bf16 descriptor.

Restore rebuilds a ``MemoryIndex`` wholesale: free lists come from the alive
masks, edge-slot keys from the live edge rows — nothing quadratic, nothing
per-row in Python except the id list itself.

Reference parity note: the reference's checkpoint story is LanceDB
delete-all-then-rewrite per conversation plus JSON snapshots
(memory_system.py:1275-1302, :1216-1273, SURVEY §5 checkpoint/resume); this
is the equivalent durability mechanism at index scale.
"""

from __future__ import annotations

import json
import os
import tempfile
import zlib
from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
import ml_dtypes

from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.index import MemoryIndex, _EdgeSlotMap
from lazzaro_tpu.reliability import faults
from lazzaro_tpu.reliability.errors import CheckpointCorrupt

_ARENA_COLS = ("emb", "salience", "timestamp", "last_accessed", "access_count",
               "type_id", "shard_id", "tenant_id", "alive", "is_super")
_EDGE_COLS = ("src", "tgt", "weight", "co", "last_updated", "alive", "tenant_id")

FORMAT_VERSION = 1


def _host(arr) -> Tuple[np.ndarray, str]:
    """Device array → (numpy array, dtype tag); bf16 bit-cast to uint16.
    Multi-host meshes: shards on non-addressable devices are gathered to
    every process first (np.asarray alone would raise)."""
    if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
        from jax.experimental import multihost_utils
        arr = multihost_utils.process_allgather(arr, tiled=True)
    a = np.asarray(arr)
    if a.dtype == ml_dtypes.bfloat16:
        return a.view(np.uint16), "bfloat16"
    return a, str(a.dtype)


def _device(a: np.ndarray, tag: str):
    if tag == "bfloat16":
        a = a.view(ml_dtypes.bfloat16)
    return jnp.asarray(a)


def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _file_crc(path: str) -> int:
    """crc32 of a file's bytes, streamed (the npz payload can be GBs)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 22)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def _verify_version_dir(vdir: str) -> None:
    """Per-file checksum verification (ISSUE 10 satellite): every version
    dir carries a ``checksums.json`` written BEFORE the commit rename; a
    payload whose bytes no longer match (torn write the filesystem lied
    about, bit rot, truncation) raises the typed
    :class:`CheckpointCorrupt` instead of loading garbage. Pre-ISSUE-10
    checkpoints without the sidecar still load (np.load decode errors are
    typed below either way)."""
    sums_path = os.path.join(vdir, "checksums.json")
    try:
        with open(sums_path) as f:
            sums = json.load(f)
    except FileNotFoundError:
        return                       # legacy checkpoint: no sidecar
    except (OSError, json.JSONDecodeError) as e:
        raise CheckpointCorrupt(
            f"unreadable checksum sidecar {sums_path}: {e}") from e
    for fname, want in sums.items():
        fpath = os.path.join(vdir, fname)
        try:
            got = _file_crc(fpath)
        except OSError as e:
            raise CheckpointCorrupt(
                f"checkpoint payload {fpath} unreadable: {e}") from e
        if got != int(want):
            raise CheckpointCorrupt(
                f"checkpoint payload {fpath} failed its checksum "
                f"(crc32 {got:#010x} != recorded {int(want):#010x}) — "
                f"torn or corrupted write; refusing to load")


def _write_versioned(ckpt_dir: str, arrays: Dict[str, np.ndarray],
                     meta: Dict) -> None:
    """Stage arrays.npz + meta.json into a new version dir, flip CURRENT.

    Multi-host: only process 0 touches the filesystem. Every process already
    holds the full arrays (the collective allgather in ``_host`` runs on all
    of them, BEFORE this call), so gating here means N processes on a shared
    filesystem don't race each other's staging dirs and CURRENT flips. All
    ranks then barrier so no rank can read-back before the snapshot exists,
    and rank 0's success/failure is broadcast so a write error (ENOSPC/EIO)
    raises on EVERY rank — without that, ranks != 0 would return success
    while rank 0 raised, and the pod would silently diverge on whether the
    checkpoint exists (r3 advisor finding)."""
    if jax.process_count() > 1 and jax.process_index() != 0:
        _ckpt_barrier()
        if not _broadcast_ok(True):       # learn rank 0's outcome
            raise RuntimeError(
                "checkpoint write failed on process 0; no new version was "
                "committed (see rank 0's log for the underlying IO error)")
        return
    try:
        _write_versioned_rank0(ckpt_dir, arrays, meta)
    except BaseException:
        # The barrier + outcome broadcast run even when the write fails:
        # the other ranks are already waiting in them, and skipping either
        # would turn a write error on rank 0 into a whole-pod hang.
        _ckpt_barrier()
        _broadcast_ok(False)
        raise
    _ckpt_barrier()
    _broadcast_ok(True)


def _write_versioned_rank0(ckpt_dir: str, arrays: Dict[str, np.ndarray],
                           meta: Dict) -> None:
    os.makedirs(ckpt_dir, exist_ok=True)
    cur = _read_current(ckpt_dir)
    next_n = int(cur[1:]) + 1 if cur else 1
    while os.path.exists(os.path.join(ckpt_dir, f"v{next_n}")):
        next_n += 1
    vname = f"v{next_n}"
    tmp = tempfile.mkdtemp(dir=ckpt_dir, prefix=".stage-")
    try:
        np.savez(os.path.join(tmp, "arrays.npz"), **arrays)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        # Per-file checksums (ISSUE 10): recorded at write time, verified
        # by every load — a torn/corrupt payload raises the typed
        # CheckpointCorrupt instead of deserializing garbage. The sidecar
        # covers the tier residency + ColdStore payload too (they ride
        # arrays.npz).
        sums = {"arrays.npz": _file_crc(os.path.join(tmp, "arrays.npz")),
                "meta.json": _file_crc(os.path.join(tmp, "meta.json"))}
        with open(os.path.join(tmp, "checksums.json"), "w") as f:
            json.dump(sums, f)
            f.flush()
            os.fsync(f.fileno())
        # rename alone doesn't make the payload durable: fsync the staged
        # files and both directories around the rename, or a power cut can
        # leave CURRENT pointing at a version whose npz is garbage.
        _fsync_path(os.path.join(tmp, "arrays.npz"))
        _fsync_path(tmp)
        os.replace(tmp, os.path.join(ckpt_dir, vname))
        _fsync_path(ckpt_dir)
    except BaseException:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    fd, ptr_tmp = tempfile.mkstemp(dir=ckpt_dir, prefix=".cur-")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(vname)
            f.flush()
            os.fsync(f.fileno())
        os.replace(ptr_tmp, _current_path(ckpt_dir))
        _fsync_path(ckpt_dir)
    except BaseException:
        if os.path.exists(ptr_tmp):
            os.unlink(ptr_tmp)
        raise
    import shutil
    for entry in os.listdir(ckpt_dir):
        if entry != vname and (entry.startswith("v") or entry.startswith(".stage-")):
            shutil.rmtree(os.path.join(ckpt_dir, entry), ignore_errors=True)
    # Fault point "checkpoint.torn" (ISSUE 10): the armed hook corrupts
    # the COMMITTED payload after the flip — modeling a torn write the
    # fsync chain failed to make durable. The recovery matrix then pins
    # that load raises the typed CheckpointCorrupt, never garbage.
    faults.fire("checkpoint.torn", dir=os.path.join(ckpt_dir, vname))


def _broadcast_ok(local_ok: bool) -> bool:
    """All ranks learn rank 0's write outcome (single-process: identity).
    The value broadcast is rank 0's — ranks != 0 pass a placeholder."""
    if jax.process_count() <= 1:
        return local_ok
    from jax.experimental import multihost_utils
    flag = np.asarray(multihost_utils.broadcast_one_to_all(
        np.asarray(1 if local_ok else 0, np.int32)))
    return bool(flag)


def _ckpt_barrier() -> None:
    """Cross-process rendezvous after a gated write: every rank leaves
    save_index only once rank 0's CURRENT flip is durable, so a save →
    immediate load on any rank never sees a missing/stale snapshot."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("lazzaro_ckpt_write")


def _read_versioned(ckpt_dir: str):
    cur = _read_current(ckpt_dir)
    if cur is None:
        raise FileNotFoundError(f"no checkpoint at {ckpt_dir} (missing CURRENT)")
    vdir = os.path.join(ckpt_dir, cur)
    _verify_version_dir(vdir)
    try:
        with open(os.path.join(vdir, "meta.json")) as f:
            meta = json.load(f)
        return np.load(os.path.join(vdir, "arrays.npz")), meta
    except (CheckpointCorrupt, FileNotFoundError):
        raise
    except Exception as e:             # noqa: BLE001 — typed re-raise
        # np.load raises zipfile.BadZipFile on a torn npz, json a decode
        # error on a torn sidecar — surface every decode failure as the
        # one typed error instead of letting garbage half-load.
        raise CheckpointCorrupt(
            f"checkpoint {vdir} failed to decode: {e}") from e


def _current_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, "CURRENT")


def _read_current(ckpt_dir: str) -> Optional[str]:
    try:
        with open(_current_path(ckpt_dir)) as f:
            name = f.read().strip()
        return name or None
    except FileNotFoundError:
        return None


def read_meta(ckpt_dir: str) -> Dict:
    """The CURRENT version's meta.json alone — cheap pairing/diagnostic
    reads (e.g. snapshot-id verification) without the array payload."""
    cur = _read_current(ckpt_dir)
    if cur is None:
        raise FileNotFoundError(f"no CURRENT checkpoint in {ckpt_dir}")
    with open(os.path.join(ckpt_dir, cur, "meta.json")) as f:
        return json.load(f)


def save_index(index: MemoryIndex, ckpt_dir: str,
               extra_meta: Optional[Dict] = None) -> None:
    """Write a new versioned snapshot under ``ckpt_dir`` and flip the
    ``CURRENT`` pointer file atomically.

    Layout: ``ckpt_dir/CURRENT`` names the live version directory
    (``v<N>/arrays.npz`` + ``v<N>/meta.json``). The payload is staged into a
    hidden tempdir, renamed into place, and only then does one atomic
    ``CURRENT`` replace make it live — a crash at ANY point leaves the
    previous snapshot readable (single-replace semantics, same contract as
    ArrowStore._atomic_write). Superseded version dirs are pruned after the
    flip."""
    arrays: Dict[str, np.ndarray] = {}
    dtypes: Dict[str, str] = {}
    for col in _ARENA_COLS:
        arrays[f"arena_{col}"], dtypes[f"arena_{col}"] = _host(
            getattr(index.state, col))
    for col in _EDGE_COLS:
        arrays[f"edge_{col}"], dtypes[f"edge_{col}"] = _host(
            getattr(index.edge_state, col))
    # id map: two aligned columns instead of a dict (1M-entry JSON dicts
    # are the slow path this module exists to avoid)
    ids = list(index.id_to_row.keys())
    arrays["node_rows"] = np.asarray(
        [index.id_to_row[i] for i in ids], np.int64)
    meta = {
        "format_version": FORMAT_VERSION,
        "dim": index.dim,
        "dtype": "bfloat16" if index.dtype == jnp.bfloat16 else str(
            np.dtype(index.dtype)),
        "epoch": index.epoch,
        "column_dtypes": dtypes,
        "node_ids": ids,
        "tenants": index._tenants,
        "shards": index._shards,
        # Fused-path observability counters survive restarts (ISSUE 6
        # satellite: a checkpoint load used to silently zero them, so a
        # dashboard's overflow rate reset on every restore).
        "counters": {"link_pool_overflows": index.link_pool_overflows},
    }
    # PQ serving pack (ISSUE 16): codebook + the complete m-byte code slab
    # ride the snapshot — rebuilding them on load would be exactly the
    # offline encode pass the incremental maintenance killed. The meta
    # block mirrors the ``counters`` idiom: absent in older checkpoints,
    # restored verbatim when present, and ``complete`` records the
    # dirty-free invariant (the pack is never saved half-encoded).
    pack = getattr(index, "_pq_pack", None)
    if pack is not None and pack[1] is not None:
        arrays["pq_book_cent"] = np.asarray(pack[0].centroids, np.float32)
        arrays["pq_codes"] = np.asarray(pack[1], np.uint8)
        meta["pq"] = {"m": int(pack[0].m), "dim": int(pack[0].dim),
                      "complete": True}
    # Tiered memory (ISSUE 8): the residency column and the cold store's
    # payload (exact vectors in the wire dtype + their shadow codes) ride
    # the same snapshot, so a reloaded index serves bit-identically to the
    # pre-save one on a mixed hot/cold corpus — the arena's zeroed cold
    # embeddings alone would silently lose those rows.
    if getattr(index, "tiering", None) is not None:
        tier = index.tiering
        arrays.update(tier.export_arrays())
        meta["tier"] = {
            "hot_budget_rows": tier.hot_budget_rows,
            "high_watermark": tier.high_watermark,
            "low_watermark": tier.low_watermark,
            "chunk_rows": tier.chunk_rows,
            "min_idle_s": tier.min_idle_s,
            "promote_hits": tier.promote_hits,
            "hysteresis_s": tier.hysteresis_s,
        }
    # Paged arena (ISSUE 17): the logical→physical row_map, inv_map and
    # the host mirror's free stack ride the snapshot (the ``arena_emb``
    # column above is already pool-shaped in a paged index). The device
    # PageTable is NOT fetched — mirror and device are pop-for-pop
    # identical by construction, so load rebuilds the device stack from
    # the mirror arrays.
    if index.state.row_map is not None:
        arrays["arena_row_map"] = np.asarray(index.state.row_map, np.int32)
        arrays["arena_inv_map"] = np.asarray(index.state.inv_map, np.int32)
        arrays.update(index._pager.export_arrays())
        meta["paged"] = {"page_rows": int(index._pager.page_rows),
                         "pool_slots": int(index._pager.pool_slots)}
    # Semantic query cache (ISSUE 20): the warm ring survives restarts —
    # the device leaves plus the host mirror's validity/tenant/head ride
    # the snapshot; the row→slot reverse index rebuilds from the ring's
    # own candidate rows on load. Same meta idiom as ``tier``/``paged``:
    # absent in older checkpoints, geometry recorded for the load-time
    # match (a mismatched ring restores COLD, never wrong).
    sem = getattr(index, "_sem_host", None)
    if sem is not None:
        arrays.update(sem.export_arrays())
        meta["semantic_cache"] = {"slots": sem.slots, "width": sem.width,
                                  "threshold": sem.threshold}
    if extra_meta:
        meta.update(extra_meta)
    _write_versioned(ckpt_dir, arrays, meta)


def load_index(ckpt_dir: str, mesh=None, shard_axis: str = "data",
               int8_serving: bool = False, ivf_nprobe: int = 0,
               pq_serving: bool = False, coarse_slack: int = 8,
               **index_kwargs) -> MemoryIndex:
    """Rebuild a MemoryIndex from the snapshot ``CURRENT`` points at.

    ``mesh``: restore row-sharded over the mesh axis (the saved total row
    count must divide the axis size — mesh-created indexes guarantee this
    via capacity rounding). ``int8_serving``/``ivf_nprobe`` flow into the
    constructor so the single-chip clamp + warning apply in the one place
    they live; a restored system keeps serving in its configured mode (the
    next consolidation pass rebuilds the coarse IVF stage)."""
    data, meta = _read_versioned(ckpt_dir)
    if meta.get("kind") == "sharded":
        raise ValueError(f"{ckpt_dir} is a sharded-index checkpoint — use "
                         f"load_sharded_index")
    if meta["format_version"] != FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint format {meta['format_version']}")
    dtypes = meta["column_dtypes"]

    arena = S.ArenaState(**{
        col: _device(data[f"arena_{col}"], dtypes[f"arena_{col}"])
        for col in _ARENA_COLS})
    edges = S.EdgeState(**{
        col: _device(data[f"edge_{col}"], dtypes[f"edge_{col}"])
        for col in _EDGE_COLS})
    pg_meta = meta.get("paged")
    if pg_meta is not None:
        if mesh is not None:
            raise ValueError(
                "paged-arena checkpoints are single-chip (the pod path "
                "keeps the dense device layout) — load without a mesh")
        arena = arena.replace(
            row_map=jnp.asarray(np.asarray(data["arena_row_map"],
                                           np.int32)),
            inv_map=jnp.asarray(np.asarray(data["arena_inv_map"],
                                           np.int32)))

    dt = jnp.bfloat16 if meta["dtype"] == "bfloat16" else jnp.dtype(meta["dtype"])
    index = MemoryIndex(meta["dim"], capacity=1, edge_capacity=1, dtype=dt,
                        epoch=meta["epoch"], mesh=mesh, shard_axis=shard_axis,
                        int8_serving=int8_serving, ivf_nprobe=ivf_nprobe,
                        pq_serving=pq_serving, coarse_slack=coarse_slack,
                        **index_kwargs)
    index.state = arena        # setter re-shards over the mesh if given
    index.edge_state = edges
    if pg_meta is not None:
        from lazzaro_tpu.core.paging import PageAllocator

        pool_slots = int(pg_meta["pool_slots"])
        stack = np.asarray(data["page_stack"], np.int32)
        # device free stack rebuilt from the mirror (they are identical
        # by the pop-for-pop replay invariant; save never fetches it)
        free = np.zeros((pool_slots + 1,), np.int32)
        free[:len(stack)] = stack
        index.paged = True
        index.page_rows = int(pg_meta["page_rows"])
        index._ptable = S.PageTable(free_slots=jnp.asarray(free),
                                    free_top=jnp.int32(len(stack)))
        index._pager = PageAllocator.from_arrays(
            arena.capacity, pool_slots, index.page_rows,
            stack, data["page_row_slot"])

    node_rows = data["node_rows"].astype(np.int64)
    node_ids = np.asarray(meta["node_ids"], object)
    index.id_to_row = dict(zip(node_ids.tolist(), node_rows.tolist()))
    index.row_to_id = dict(zip(node_rows.tolist(), node_ids.tolist()))
    index._tenants = {k: int(v) for k, v in meta["tenants"].items()}
    index._shards = {k: int(v) for k, v in meta["shards"].items()}
    # restore fused-path counters (absent in pre-ISSUE-6 checkpoints)
    index.link_pool_overflows = int(
        meta.get("counters", {}).get("link_pool_overflows", 0))

    # PQ pack (ISSUE 16): restore the saved codebook + complete code slab
    # so the restored index serves PQ (and maintains codes incrementally)
    # without an offline re-encode; absent in pre-ISSUE-16 checkpoints,
    # and dropped when the snapshot's slab no longer matches the arena.
    if pq_serving and "pq" in meta and "pq_book_cent" in data:
        from lazzaro_tpu.ops.pq import PQCodebook

        codes = np.asarray(data["pq_codes"], np.uint8)
        if codes.shape[0] == arena.capacity + 1:
            book = PQCodebook(
                centroids=jnp.asarray(
                    np.asarray(data["pq_book_cent"], np.float32)),
                dim=int(meta["pq"]["dim"]))
            index._pq_pack = (book, jnp.asarray(codes))

    # Free lists via vectorized set-difference (descending, so allocation
    # pops low rows first — same shape as a fresh index).
    cap = arena.capacity
    free = np.setdiff1d(np.arange(cap, dtype=np.int64), node_rows,
                        assume_unique=False)
    index._free_rows = free[::-1].tolist()

    # Super-row bookkeeping from the restored is_super column: the fused
    # IVF serving kernel's extras must carry every super row (exact gate
    # verdicts), and this path bypasses ``add``'s tracking.
    sup_rows = np.flatnonzero(np.asarray(arena.is_super)[:cap]
                              & np.asarray(arena.alive)[:cap])
    index._super_rows = {int(r) for r in sup_rows}
    index._super_rows_frozen = tuple(sorted(index._super_rows))

    # Edge bookkeeping: map only LIVE slots' rows → ids through a dense
    # row→id table (no per-dead-slot Python work at 1M scale).
    edge_alive = np.asarray(edges.alive)[:edges.capacity]
    live_slots = np.flatnonzero(edge_alive)
    id_by_row = np.full((cap + 1,), None, object)
    id_by_row[node_rows] = node_ids
    src_ids = id_by_row[np.asarray(edges.src)[live_slots]]
    tgt_ids = id_by_row[np.asarray(edges.tgt)[live_slots]]
    index.edge_slots = _EdgeSlotMap({
        (s, t): int(slot)
        for s, t, slot in zip(src_ids.tolist(), tgt_ids.tolist(),
                              live_slots.tolist())
        if s is not None and t is not None})
    free_e = np.setdiff1d(np.arange(edges.capacity, dtype=np.int64),
                          np.asarray(sorted(index.edge_slots.values()),
                                     np.int64))
    index._free_edge_slots = free_e[::-1].tolist()

    # Tenant membership: one gather of the tenant column + per-tenant masks.
    tenant_per_node = np.asarray(arena.tenant_id)[node_rows]
    index.tenant_nodes = {
        t: set(node_ids[tenant_per_node == tid].tolist())
        for t, tid in index._tenants.items()}

    # Tiered memory (ISSUE 8): reattach the manager and restore residency
    # + cold-store contents (``tier_cold_dir`` is a runtime choice, so a
    # restored cold tier starts in host RAM regardless of where it lived).
    if "tier" in meta and "tier_cold_mask" in data:
        tier_kw = dict(meta["tier"])
        budget = int(tier_kw.pop("hot_budget_rows"))
        tmgr = index.enable_tiering(budget, **tier_kw)
        tmgr.import_arrays(data)
    # Semantic query cache (ISSUE 20): restore the warm ring when the
    # restored index also enabled the cache AND the saved geometry
    # matches the configured one; otherwise the fresh empty ring stands
    # (a cold cache, never a wrong one).
    if "sem_emb" in data and index._sem_host is not None:
        index._sem_host.import_arrays(data)
    return index


# ---------------------------------------------------------------------------
# Pod-sharded index (parallel.index.ShardedMemoryIndex)
# ---------------------------------------------------------------------------

def save_sharded_index(index, ckpt_dir: str) -> None:
    """Checkpoint a ``ShardedMemoryIndex``: the full arena column set
    (ISSUE 5 — the pod index now carries every serving column: access
    counters, super flags, timestamps) is gathered to host (cross-process
    allgather when the mesh spans hosts) and written under the same
    versioned-CURRENT layout as ``save_index``; the host edge map rides
    the JSON sidecar so the CSR shadow rebuilds on load."""
    st = index.state
    arrays: Dict[str, np.ndarray] = {}
    dtypes: Dict[str, str] = {}
    for col in _ARENA_COLS:
        arrays[f"arena_{col}"], dtypes[f"arena_{col}"] = _host(
            getattr(st, col))
    ids = list(index.id_to_row.keys())
    arrays["node_rows"] = np.asarray([index.id_to_row[i] for i in ids],
                                     np.int64)
    meta = {
        "format_version": FORMAT_VERSION,
        "kind": "sharded",
        "dim": index.dim,
        "capacity": index.capacity,
        "axis": index.axis,
        "epoch": index.epoch,
        "tenant_affinity": index.tenant_affinity,
        "column_dtypes": dtypes,
        "node_ids": ids,
        "tenants": index._tenants,
        "edges": [[s, t, w] for (s, t), w in index.edges.items()],
    }
    _write_versioned(ckpt_dir, arrays, meta)


def load_sharded_index(ckpt_dir: str, mesh, k: int = 10):
    """Rebuild a ``ShardedMemoryIndex`` on ``mesh`` from ``save_sharded_index``
    output. The mesh axis size must divide the saved row count (any mesh
    whose axis size divides it works — checkpoints are portable across pod
    shapes)."""
    from lazzaro_tpu.parallel.index import ShardedMemoryIndex

    data, meta = _read_versioned(ckpt_dir)
    if meta.get("kind") != "sharded":
        raise ValueError(f"{ckpt_dir} is not a sharded-index checkpoint")
    if meta["format_version"] != FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint format {meta['format_version']}")
    dtypes = meta["column_dtypes"]

    dt = (jnp.bfloat16 if dtypes["arena_emb"] == "bfloat16"
          else jnp.dtype(dtypes["arena_emb"]))
    n_parts = mesh.shape[meta["axis"]]
    total = int(meta["capacity"]) + 1
    if total % n_parts != 0:
        raise ValueError(
            f"saved row count {total} does not divide the mesh axis "
            f"({n_parts}) — pick a pod shape whose axis divides it")
    index = ShardedMemoryIndex(
        mesh, dim=meta["dim"], capacity=meta["capacity"],
        axis=meta["axis"], dtype=dt, epoch=meta.get("epoch"),
        tenant_affinity=meta["tenant_affinity"], k=k)
    arena = S.ArenaState(**{
        col: _device(data[f"arena_{col}"], dtypes[f"arena_{col}"])
        for col in _ARENA_COLS})
    index.state = arena                     # setter re-shards over the mesh

    node_rows = data["node_rows"].astype(np.int64)
    node_ids = np.asarray(meta["node_ids"], object)
    index.id_to_row = dict(zip(node_ids.tolist(), node_rows.tolist()))
    index.row_to_id = dict(zip(node_rows.tolist(), node_ids.tolist()))
    index._tenants = {t: int(v) for t, v in meta["tenants"].items()}
    index.edges = {(s, t): float(w) for s, t, w in meta.get("edges", [])}
    index._csr_dirty = True
    sup = np.asarray(data["arena_is_super"]).astype(bool)
    index._super_rows = set(np.flatnonzero(sup[:index.capacity]).tolist())
    # Per-partition free lists via vectorized set-difference (descending
    # within each — no per-row Python at 1M-capacity scale); the global
    # sentinel row is never allocatable.
    taken = np.concatenate([node_rows, [index.capacity]])
    index._free = [
        np.setdiff1d(np.arange(p * index.part_rows, (p + 1) * index.part_rows,
                               dtype=np.int64), taken)[::-1].tolist()
        for p in range(index.n_parts)]
    return index
