"""Five-domain evolving user profile.

Parity target: reference ``core/profile.py`` (59 LoC): fixed domains
(preferences, personality_traits, knowledge_domains, interaction_style,
key_experiences), ``update_domain`` only accepts known domains, and
``get_context`` renders title-cased "Domain: content" lines.
"""

from __future__ import annotations

import time
from typing import Any, Dict

DOMAINS = (
    "preferences",
    "personality_traits",
    "knowledge_domains",
    "interaction_style",
    "key_experiences",
)


class Profile:
    def __init__(self) -> None:
        self.data: Dict[str, str] = {d: "" for d in DOMAINS}
        self.last_updated: float = time.time()

    def update_domain(self, domain: str, content: str) -> bool:
        if domain not in self.data:
            return False
        self.data[domain] = content
        self.last_updated = time.time()
        return True

    def get_context(self) -> str:
        lines = [
            f"{domain.replace('_', ' ').title()}: {content}"
            for domain, content in self.data.items()
            if content
        ]
        return "\n".join(lines) if lines else "No profile data yet."

    def to_dict(self) -> Dict[str, Any]:
        return {"data": dict(self.data), "last_updated": self.last_updated}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Profile":
        p = cls()
        data = d.get("data", d)
        for k, v in data.items():
            if k in p.data and isinstance(v, str):
                p.data[k] = v
        p.last_updated = d.get("last_updated", time.time())
        return p
