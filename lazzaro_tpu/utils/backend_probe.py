"""Hang-proof JAX backend probing.

Round-3 post-mortem (VERDICT.md "What's weak" #1/#6): a wedged TPU tunnel
makes ``jax.devices()`` HANG — not raise — so any in-process probe can stall
a driver hook forever (r03's rc=124) and an ``except`` block never fires.
Every entry point that might touch a flaky accelerator backend must instead:

1. probe the backend in a **subprocess with a hard timeout** (this module),
2. on failure, fall back to CPU **before** this process initializes a
   backend (``force_cpu``), and
3. still emit its artifact (a JSON line, a dry-run result) so the driver
   always captures something parseable.

The probe is honest: it runs a real matmul and reads the result back to the
host. On the tunneled "axon" backend, ``block_until_ready`` acknowledges
dispatch rather than completion (VERDICT.md weak #2), so device→host readback
is the only sync primitive trusted anywhere in this codebase's timed or
health-checked paths.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
from typing import Dict, Optional

# Env vars that enable the tunneled TPU plugin; popped to guarantee a pure
# CPU child/process. Harmless if absent.
ACCEL_ENV_VARS = (
    "PALLAS_AXON_POOL_IPS",
    "PALLAS_AXON_REMOTE_COMPILE",
)

_PROBE_CODE = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp
ds = jax.devices()
x = jnp.full((128, 128), 0.5, jnp.bfloat16)
y = np.asarray(x @ x)          # real compute + forced device->host readback
assert float(y[0, 0]) == 32.0, float(y[0, 0])   # 128 * 0.5 * 0.5
print(json.dumps({"ok": True, "platform": jax.default_backend(),
                  "device_count": len(ds), "device0": str(ds[0])}))
"""


def _steer_cpu(env: Dict[str, str], n_devices: Optional[int]) -> Dict[str, str]:
    """Single shared mutation: strip the accelerator-plugin vars, pin
    JAX_PLATFORMS=cpu, and (optionally) force an n-device host topology.
    Used by both ``cpu_env`` (subprocess copies) and ``force_cpu``
    (in-place on os.environ) so the two can never drift."""
    for var in ACCEL_ENV_VARS:
        env.pop(var, None)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       env.get("XLA_FLAGS", ""))
        env["XLA_FLAGS"] = (flags
                            + f" --xla_force_host_platform_device_count={n_devices}")
    return env


def cpu_env(n_devices: Optional[int] = None,
            base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """A copy of the environment guaranteed to initialize a CPU-only JAX,
    optionally with an ``n_devices``-way virtual device topology (the same
    mesh substrate tests/conftest.py uses)."""
    return _steer_cpu(dict(os.environ if base is None else base), n_devices)


def env_forced_cpu_devices() -> int:
    """Device count knowable from the environment ALONE (zero jax calls):
    >0 only when JAX_PLATFORMS pins cpu AND no accelerator-plugin env var
    is present. The second condition is load-bearing: the tunneled-TPU
    sitecustomize hook registers its backend whenever its env vars are set,
    OVERRIDING a shell-level ``JAX_PLATFORMS=cpu`` — trusting the variable
    alone silently bypassed every probe gate (r4 review finding). Returns
    the forced host device count (default 1) when genuinely CPU-pinned."""
    if any(os.environ.get(var) for var in ACCEL_ENV_VARS):
        return 0
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms.split(",")[0].strip().lower() != "cpu":
        return 0
    # XLA's flag parser is last-occurrence-wins; mirror that when callers
    # have appended the flag more than once.
    found = re.findall(r"--xla_force_host_platform_device_count=(\d+)",
                       os.environ.get("XLA_FLAGS", ""))
    return int(found[-1]) if found else 1


def probe_backend(timeout: float = 90.0,
                  env: Optional[Dict[str, str]] = None) -> Dict[str, object]:
    """Initialize the default backend in a SUBPROCESS and run one verified
    matmul with device→host readback. Returns
    ``{"ok", "platform", "device_count", "device0", "error"}`` and never
    blocks longer than ``timeout`` seconds, whatever the backend does."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            env=dict(os.environ) if env is None else env,
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": f"backend probe timed out after {timeout:.0f}s"}
    except OSError as e:
        return {"ok": False, "error": f"probe spawn failed: {e}"}
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
        return {"ok": False,
                "error": f"probe rc={proc.returncode}: {' | '.join(tail)[:500]}"}
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"ok": False, "error": f"unparseable probe output: {proc.stdout[:200]!r}"}


def ensure_healthy_or_cpu(timeout: float = 90.0, retries: int = 0,
                          retry_wait: float = 20.0) -> Dict[str, object]:
    """The one health-gate policy every entry point shares: no-op when the
    environment already genuinely forces CPU; otherwise subprocess-probe the
    default backend (with optional retries) and steer THIS process onto CPU
    if the accelerator is unhealthy. Returns the final health dict — callers
    inspect ``ok`` to decide on degraded-mode behavior (bench caps N, the
    driver hooks log). Centralizing it keeps the 'JAX_PLATFORMS=cpu alone is
    not proof of CPU' invariant (see env_forced_cpu_devices) in one place."""
    import time

    if env_forced_cpu_devices() > 0:
        return {"ok": True, "platform": "cpu", "forced_by_env": True}
    health = probe_backend(timeout=timeout)
    for _ in range(retries):
        if health.get("ok"):
            break
        print(f"[backend_probe] probe failed ({health.get('error')}); "
              f"retrying in {retry_wait:.0f}s", file=sys.stderr, flush=True)
        time.sleep(retry_wait)
        health = probe_backend(timeout=timeout)
    if not health.get("ok"):
        force_cpu()
    return health


def force_cpu(n_devices: Optional[int] = None) -> None:
    """Steer THIS process onto the CPU backend. Only effective before the
    first backend touch (imports are fine; ``jax.devices()`` is not) — call
    it right after a failed ``probe_backend`` and before any jnp op."""
    _steer_cpu(os.environ, n_devices)
    import jax
    jax.config.update("jax_platforms", "cpu")
