"""Hang-proof JAX backend probing.

Round-3 post-mortem (VERDICT.md "What's weak" #1/#6): a wedged TPU tunnel
makes ``jax.devices()`` HANG — not raise — so any in-process probe can stall
a driver hook forever (r03's rc=124) and an ``except`` block never fires.
Every entry point that might touch a flaky accelerator backend must instead:

1. probe the backend in a **subprocess with a hard timeout** (this module),
2. on failure, fall back to CPU **before** this process initializes a
   backend (``force_cpu``), and
3. still emit its artifact (a JSON line, a dry-run result) so the driver
   always captures something parseable.

The probe is honest: it runs a real matmul and reads the result back to the
host. On the tunneled "axon" backend, ``block_until_ready`` acknowledges
dispatch rather than completion (VERDICT.md weak #2), so device→host readback
is the only sync primitive trusted anywhere in this codebase's timed or
health-checked paths.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
from typing import Dict, Optional

# Env vars that enable the tunneled TPU plugin; popped to guarantee a pure
# CPU child/process. Harmless if absent.
ACCEL_ENV_VARS = (
    "PALLAS_AXON_POOL_IPS",
    "PALLAS_AXON_REMOTE_COMPILE",
)

_PROBE_CODE = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp
ds = jax.devices()
x = jnp.full((128, 128), 0.5, jnp.bfloat16)
y = np.asarray(x @ x)          # real compute + forced device->host readback
assert float(y[0, 0]) == 32.0, float(y[0, 0])   # 128 * 0.5 * 0.5
print(json.dumps({"ok": True, "platform": jax.default_backend(),
                  "device_count": len(ds), "device0": str(ds[0])}))
"""


def cpu_env(n_devices: Optional[int] = None,
            base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """A copy of the environment guaranteed to initialize a CPU-only JAX,
    optionally with an ``n_devices``-way virtual device topology (the same
    mesh substrate tests/conftest.py uses)."""
    env = dict(os.environ if base is None else base)
    for var in ACCEL_ENV_VARS:
        env.pop(var, None)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       env.get("XLA_FLAGS", ""))
        env["XLA_FLAGS"] = (flags
                            + f" --xla_force_host_platform_device_count={n_devices}")
    return env


def env_forced_cpu_devices() -> int:
    """Device count knowable from the environment ALONE (zero jax calls):
    >0 only when JAX_PLATFORMS pins cpu, in which case the forced host
    device count (default 1) is returned."""
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms.split(",")[0].strip().lower() != "cpu":
        return 0
    # XLA's flag parser is last-occurrence-wins; mirror that when callers
    # have appended the flag more than once.
    found = re.findall(r"--xla_force_host_platform_device_count=(\d+)",
                       os.environ.get("XLA_FLAGS", ""))
    return int(found[-1]) if found else 1


def probe_backend(timeout: float = 90.0,
                  env: Optional[Dict[str, str]] = None) -> Dict[str, object]:
    """Initialize the default backend in a SUBPROCESS and run one verified
    matmul with device→host readback. Returns
    ``{"ok", "platform", "device_count", "device0", "error"}`` and never
    blocks longer than ``timeout`` seconds, whatever the backend does."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            env=dict(os.environ) if env is None else env,
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": f"backend probe timed out after {timeout:.0f}s"}
    except OSError as e:
        return {"ok": False, "error": f"probe spawn failed: {e}"}
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
        return {"ok": False,
                "error": f"probe rc={proc.returncode}: {' | '.join(tail)[:500]}"}
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"ok": False, "error": f"unparseable probe output: {proc.stdout[:200]!r}"}


def force_cpu(n_devices: Optional[int] = None) -> None:
    """Steer THIS process onto the CPU backend. Only effective before the
    first backend touch (imports are fine; ``jax.devices()`` is not) — call
    it right after a failed ``probe_backend`` and before any jnp op."""
    for var in ACCEL_ENV_VARS:
        os.environ.pop(var, None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        os.environ["XLA_FLAGS"] = (
            re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
            + f" --xla_force_host_platform_device_count={n_devices}")
    import jax
    jax.config.update("jax_platforms", "cpu")
