from lazzaro_tpu.utils.telemetry import Telemetry, timed

__all__ = ["Telemetry", "timed"]
