"""Version shims for the jax APIs this tree uses across toolchain pins.

The graft rigs pin different jax versions: newer ones export
``jax.shard_map`` (replication-check kwarg ``check_vma``), 0.4.x rigs only
ship ``jax.experimental.shard_map.shard_map`` (same kwarg named
``check_rep``). Every shard_map call in the tree imports from here so the
difference is absorbed in exactly one place.
"""

from __future__ import annotations

try:                                    # jax >= 0.5: top-level export
    from jax import shard_map as _shard_map
    _CHECK_KWARG = "check_vma"
except ImportError:                     # jax 0.4.x: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KWARG = "check_rep"

_CHECK_NAMES = ("check_vma", "check_rep")


def shard_map(f=None, **kwargs):
    """``jax.shard_map`` with the replication-check kwarg translated to
    whatever this jax version calls it. Accepts either spelling."""
    for name in _CHECK_NAMES:
        if name in kwargs and name != _CHECK_KWARG:
            kwargs[_CHECK_KWARG] = kwargs.pop(name)
    if f is None:                       # decorator-style usage
        return lambda g: _shard_map(g, **kwargs)
    return _shard_map(f, **kwargs)


# --- profiler annotations ---------------------------------------------------
# The serving telemetry layer wraps every fused dispatch in a profiler
# annotation so TPU profiler captures (``jax.profiler.trace``) line up with
# the host-side Telemetry spans. jax 0.4.37 ships both TraceAnnotation and
# StepTraceAnnotation under ``jax.profiler``; older/newer pins may move or
# drop them, so the serving path imports the shimmed constructors here and
# degrades to a no-op context manager instead of crashing the hot path.

try:
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except ImportError:                     # pragma: no cover - toolchain variance
    _TraceAnnotation = None

try:
    from jax.profiler import StepTraceAnnotation as _StepTraceAnnotation
except ImportError:                     # pragma: no cover - toolchain variance
    _StepTraceAnnotation = None


class _NullAnnotation:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullAnnotation()


def trace_annotation(name: str):
    """Context manager marking a named region on the device-profiler
    timeline (``jax.profiler.TraceAnnotation``), or a no-op when this jax
    doesn't expose it. Cheap enough for the per-dispatch hot path."""
    if _TraceAnnotation is None:
        return _NULL
    return _TraceAnnotation(name)


def step_trace_annotation(name: str, step_num: int):
    """``jax.profiler.StepTraceAnnotation`` (gives profiler tooling a step
    axis — one serving mega-batch == one step), or a no-op shim."""
    if _StepTraceAnnotation is None:
        return _NULL
    return _StepTraceAnnotation(name, step_num=step_num)
