"""Version shims for the jax APIs this tree uses across toolchain pins.

The graft rigs pin different jax versions: newer ones export
``jax.shard_map`` (replication-check kwarg ``check_vma``), 0.4.x rigs only
ship ``jax.experimental.shard_map.shard_map`` (same kwarg named
``check_rep``). Every shard_map call in the tree imports from here so the
difference is absorbed in exactly one place.
"""

from __future__ import annotations

try:                                    # jax >= 0.5: top-level export
    from jax import shard_map as _shard_map
    _CHECK_KWARG = "check_vma"
except ImportError:                     # jax 0.4.x: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KWARG = "check_rep"

_CHECK_NAMES = ("check_vma", "check_rep")


def shard_map(f=None, **kwargs):
    """``jax.shard_map`` with the replication-check kwarg translated to
    whatever this jax version calls it. Accepts either spelling."""
    for name in _CHECK_NAMES:
        if name in kwargs and name != _CHECK_KWARG:
            kwargs[_CHECK_KWARG] = kwargs.pop(name)
    if f is None:                       # decorator-style usage
        return lambda g: _shard_map(g, **kwargs)
    return _shard_map(f, **kwargs)
