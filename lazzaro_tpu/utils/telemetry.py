"""Serving telemetry: the process-wide metrics registry.

The reference tracks metrics in an ad-hoc dict on MemorySystem with inline
emoji prints (SURVEY §5: retrieval_times[], consolidation_times[], tiered
⚡/✓/⏱ latency prints, no structured logging). Since ISSUE 6 this module is
the one sink every serving-path measurement flows into:

- **timers** — ring-buffered latency samples with percentile summaries
  (``record`` / ``span``): queue wait per request, device dispatch wall
  time per mega-batch, readback decode, chat retrieval, consolidation;
-- **counters** — monotonic totals (``bump``): requests, dispatches per
  mode, the device-side counters decoded from the packed readback tail
  (gate hits, top-k shortfall, dedup hits, boost-scatter rows, link-pool
  occupancy/overflow);
- **gauges** — last-value observations (``gauge``): batch occupancy,
  compile-cache entries, ``memory_analysis()`` peak-HBM per
  (mode × geometry × mesh) kernel.

Every metric name may carry labels (``labels={"tenant": ...}``); the
(name, labels) pair canonicalizes to one key in Prometheus sample syntax,
so ``prometheus()`` can render the whole registry as a text exposition and
``snapshot()`` as a JSON-able dict (bench artifacts embed it; the
dashboard serves both). Label cardinality is clamped per metric so a
million distinct tenants cannot grow the registry without bound — excess
label values collapse into ``"~other"``.

Instances are thread-safe and cheap (a deque append / int add under one
lock). ``REGISTRY`` is the process-wide default used by components
constructed standalone; ``MemorySystem`` owns a private instance so two
systems in one process (tests, multi-user benches) never mix samples.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import defaultdict, deque
from contextlib import contextmanager
from typing import Deque, Dict, Optional

import numpy as np

logger = logging.getLogger("lazzaro_tpu.telemetry")

# Per-metric bound on distinct label COMBINATIONS. Overflowing values are
# folded into one "~other" series, so a tenant explosion degrades to a
# coarse aggregate instead of unbounded memory.
MAX_LABEL_SETS = 256


def _fmt_labels(labels: Dict[str, object]) -> str:
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return "{" + inner + "}"


def split_key(key: str):
    """``name{k="v",...}`` → (name, label_str) — the inverse of the
    canonical key the registry stores under."""
    i = key.find("{")
    if i < 0:
        return key, ""
    return key[:i], key[i:]


class Telemetry:
    def __init__(self, window: int = 10_000, enabled: bool = True):
        # ``enabled=False`` turns every writer into a cheap no-op (the
        # MemoryConfig.serve_telemetry switch) — readers keep working on
        # whatever was recorded before the flip.
        self.enabled = bool(enabled)
        self.window = window
        self._lock = threading.Lock()
        self.timers: Dict[str, Deque[float]] = defaultdict(
            lambda: deque(maxlen=window))
        self.counters: Dict[str, int] = defaultdict(int)
        self.gauges: Dict[str, float] = {}
        self._series_per_name: Dict[str, int] = defaultdict(int)
        self._known_keys = set()

    # ------------------------------------------------------------------ keys
    def _key(self, name: str, labels: Optional[Dict] = None) -> str:
        if not labels:
            return name
        key = name + _fmt_labels(labels)
        # cardinality clamp: past the per-name budget, new label sets fold
        # into one "~other" series (existing keys keep recording)
        with self._lock:
            if key not in self._known_keys:
                if self._series_per_name[name] >= MAX_LABEL_SETS:
                    return name + _fmt_labels(
                        {k: "~other" for k in labels})
                self._series_per_name[name] += 1
                self._known_keys.add(key)
        return key

    # --------------------------------------------------------------- writers
    def record(self, name: str, value_ms: float,
               labels: Optional[Dict] = None) -> None:
        if not self.enabled:
            return
        self.timers[self._key(name, labels)].append(float(value_ms))

    def bump(self, name: str, n: int = 1,
             labels: Optional[Dict] = None) -> None:
        if n == 0 or not self.enabled:
            return
        key = self._key(name, labels)
        with self._lock:
            self.counters[key] += int(n)

    def gauge(self, name: str, value: float,
              labels: Optional[Dict] = None) -> None:
        if not self.enabled:
            return
        self.gauges[self._key(name, labels)] = float(value)

    @contextmanager
    def span(self, name: str, labels: Optional[Dict] = None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, (time.perf_counter() - t0) * 1e3, labels)

    # --------------------------------------------------------------- readers
    def counter_total(self, name: str) -> int:
        """Sum of a counter across every label set (e.g. all modes)."""
        with self._lock:
            return sum(v for k, v in self.counters.items()
                       if split_key(k)[0] == name)

    def timer_count(self, name: str) -> int:
        """Sample count of a timer across every label set."""
        return sum(len(v) for k, v in self.timers.items()
                   if split_key(k)[0] == name)

    def timer_values(self, name: str) -> list:
        """All ring-buffered samples of a timer across every label set."""
        out: list = []
        for k, v in list(self.timers.items()):
            if split_key(k)[0] == name:
                out.extend(v)
        return out

    def summary(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for name, values in list(self.timers.items()):
            arr = np.asarray(values)
            if arr.size:
                out[name] = {
                    "count": int(arr.size),
                    "avg_ms": float(arr.mean()),
                    "p50_ms": float(np.percentile(arr, 50)),
                    "p95_ms": float(np.percentile(arr, 95)),
                }
        with self._lock:
            for name, count in self.counters.items():
                out[name] = {"count": count}
        return out

    def snapshot(self) -> Dict[str, Dict]:
        """One JSON-able view of the whole registry — embedded in bench
        artifacts and served by the dashboard's ``/api/metrics``."""
        timers: Dict[str, Dict[str, float]] = {}
        for name, values in list(self.timers.items()):
            arr = np.asarray(values)
            if arr.size:
                timers[name] = {
                    "count": int(arr.size),
                    "avg_ms": float(arr.mean()),
                    "p50_ms": float(np.percentile(arr, 50)),
                    "p95_ms": float(np.percentile(arr, 95)),
                    "max_ms": float(arr.max()),
                }
        with self._lock:
            counters = dict(self.counters)
        return {"timers": timers, "counters": counters,
                "gauges": dict(self.gauges)}

    def prometheus(self, prefix: str = "lazzaro") -> str:
        """Prometheus text exposition (v0.0.4) of the registry. Metric
        names sanitize ``.`` → ``_``; timers expose ``_count`` /
        ``_avg_ms`` / ``_p50_ms`` / ``_p95_ms`` gauges, counters expose
        ``_total``, gauges expose their value as-is — all with the
        original label sets preserved."""
        def san(name: str) -> str:
            return f"{prefix}_{name}".replace(".", "_").replace("-", "_")

        lines = []
        typed = set()

        def emit(full_name: str, label_str: str, kind: str, value) -> None:
            if full_name not in typed:
                typed.add(full_name)
                lines.append(f"# TYPE {full_name} {kind}")
            lines.append(f"{full_name}{label_str} {value}")

        snap = self.snapshot()
        for key, stats in sorted(snap["timers"].items()):
            base, label_str = split_key(key)
            for suffix, val in (("count", stats["count"]),
                                ("avg_ms", stats["avg_ms"]),
                                ("p50_ms", stats["p50_ms"]),
                                ("p95_ms", stats["p95_ms"])):
                emit(f"{san(base)}_{suffix}", label_str, "gauge", val)
        for key, val in sorted(snap["counters"].items()):
            base, label_str = split_key(key)
            emit(f"{san(base)}_total", label_str, "counter", val)
        for key, val in sorted(snap["gauges"].items()):
            base, label_str = split_key(key)
            emit(san(base), label_str, "gauge", val)
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self.timers.clear()
            self.counters.clear()
            self.gauges.clear()
            self._series_per_name.clear()
            self._known_keys.clear()

    @staticmethod
    def tier(latency_ms: float) -> str:
        """The reference's emoji latency tiers (memory_system.py:332-337)."""
        return "⚡" if latency_ms < 100 else ("✓" if latency_ms < 200 else "⏱")


# The process-wide default registry: components constructed standalone
# (a bare MemoryIndex, a QueryScheduler in a test harness) record here;
# MemorySystem threads its own instance through everything it owns.
REGISTRY = Telemetry()


def default_registry() -> Telemetry:
    return REGISTRY


def record_device_counters(tel: Telemetry, counters, fast, gate_on, valid,
                           k_req, sem_active: bool = False) -> None:
    """Fold one fused readback's device-counter tail into the registry —
    shared by the single-chip (``core.index``) and pod
    (``parallel.index``) decoders. ``counters`` is the
    ``utils.batching.unpack_retrieval`` tail ([Q, 5] int32: live, dup,
    acc-boost rows, nbr-boost rows, semantic verdict), ``fast`` the
    device gate verdicts, ``gate_on``/``valid`` the per-query flags,
    ``k_req`` each request's asked-for k (shortfall counts against THAT,
    not the padded kernel bucket). ``sem_active`` marks dispatches that
    actually carried the semantic ring — without it a cache-off turn
    would count every query as a semantic miss (the column is always
    present, just all-zero)."""
    v = np.asarray(valid, bool)
    if not v.any():
        return
    live = np.asarray(counters[:, 0])[v]
    want = np.asarray(k_req)[v]
    g_on = np.asarray(gate_on, bool)[v]
    f = np.asarray(fast, bool)[v]
    tel.bump("device.gate_hit", int((g_on & f).sum()))
    tel.bump("device.gate_miss", int((g_on & ~f).sum()))
    tel.bump("device.topk_shortfall", int(np.maximum(want - live, 0).sum()))
    tel.bump("device.dedup_hits", int(counters[:, 1][v].sum()))
    tel.bump("device.boost_rows", int(counters[:, 2][v].sum()))
    tel.bump("device.nbr_boost_rows", int(counters[:, 3][v].sum()))
    if sem_active and counters.shape[1] > 4:
        n_hit = int((counters[:, 4][v] > 0).sum())
        tel.bump("serve.semantic_hits", n_hit)
        tel.bump("serve.semantic_misses", int(v.sum()) - n_hit)


def peak_bytes(memory_stats) -> Optional[float]:
    """Peak live bytes of one compiled fused program, from
    ``compiled.memory_analysis()`` ("Memory Safe Computations with XLA" —
    compile-time introspection is cheap). None when the backend doesn't
    report (some TPU runtimes return None pre-execution)."""
    if memory_stats is None:
        return None
    try:
        return float(memory_stats.argument_size_in_bytes
                     + memory_stats.output_size_in_bytes
                     + memory_stats.temp_size_in_bytes
                     - memory_stats.alias_size_in_bytes)
    except AttributeError:
        return None


@contextmanager
def timed(label: str, sink=None):
    t0 = time.perf_counter()
    yield
    ms = (time.perf_counter() - t0) * 1e3
    if sink is not None:
        sink.record(label, ms)
    else:
        # library users silence this via the standard logging config
        # instead of the old unconditional print
        logger.info("[%s %s: %.1fms]", Telemetry.tier(ms), label, ms)
