"""Lightweight timing/metrics helpers.

The reference tracks metrics in an ad-hoc dict on MemorySystem with inline
emoji prints (SURVEY §5: retrieval_times[], consolidation_times[], tiered
⚡/✓/⏱ latency prints, no structured logging). This module centralizes that:
named ring-buffered timers with percentile summaries, usable standalone.
"""

from __future__ import annotations

import time
from collections import defaultdict, deque
from contextlib import contextmanager
from typing import Deque, Dict

import numpy as np


class Telemetry:
    def __init__(self, window: int = 10_000):
        self.window = window
        self.timers: Dict[str, Deque[float]] = defaultdict(
            lambda: deque(maxlen=window))
        self.counters: Dict[str, int] = defaultdict(int)

    def record(self, name: str, value_ms: float) -> None:
        self.timers[name].append(value_ms)

    def bump(self, name: str, n: int = 1) -> None:
        self.counters[name] += n

    @contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, (time.perf_counter() - t0) * 1e3)

    def summary(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for name, values in self.timers.items():
            arr = np.asarray(values)
            if arr.size:
                out[name] = {
                    "count": int(arr.size),
                    "avg_ms": float(arr.mean()),
                    "p50_ms": float(np.percentile(arr, 50)),
                    "p95_ms": float(np.percentile(arr, 95)),
                }
        for name, count in self.counters.items():
            out[name] = {"count": count}
        return out

    @staticmethod
    def tier(latency_ms: float) -> str:
        """The reference's emoji latency tiers (memory_system.py:332-337)."""
        return "⚡" if latency_ms < 100 else ("✓" if latency_ms < 200 else "⏱")


@contextmanager
def timed(label: str, sink=None):
    t0 = time.perf_counter()
    yield
    ms = (time.perf_counter() - t0) * 1e3
    if sink is not None:
        sink.record(label, ms)
    else:
        print(f"[{Telemetry.tier(ms)} {label}: {ms:.1f}ms]")
