"""Process-stable hashing for placement decisions.

Python's builtin ``hash(str)`` is salted per process (PYTHONHASHSEED),
so anything derived from it — like a tenant's replica home group —
silently changes across restarts. Placement must be durable: an overlay
tenant's rows exist ONLY on its home group, and journal replay after a
crash must re-home facts to the SAME group that holds the surviving
rows. Every placement-affecting hash in the tree routes through here.
"""

from __future__ import annotations

import zlib


def stable_str_hash(s: str) -> int:
    """Deterministic non-negative hash of ``s`` — same value in every
    process, every PYTHONHASHSEED, every platform."""
    return zlib.crc32(s.encode("utf-8")) & 0xFFFFFFFF


def tenant_home_group(tenant: str, n_groups: int) -> int:
    """The tenant's stable home replica group in ``[0, n_groups)``. Used
    by BOTH the write-side placement (ReplicaPlacement) and the
    read-side router (ReplicaRouter) so affine reads always land where
    the tenant's overlay rows live — including after a restart."""
    return stable_str_hash(tenant) % n_groups
