"""Host-side batching helpers shared by the index classes and the encoder.

Static shapes are the XLA contract: every distinct batch size compiles a new
kernel specialization, so hosts bucket batch dims to powers of two. The
decode loop turns kernel output (scores + arena rows with NEG_INF sentinels)
back into host id lists — one implementation, used by both the single-chip
and pod-sharded indexes.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@functools.cache
def _packer(int_flags: Tuple[bool, ...]):
    # Lazy so importing this module never initializes a JAX backend.
    import jax
    import jax.numpy as jnp

    @jax.jit
    def pack(*arrs):
        return jnp.stack([
            jax.lax.bitcast_convert_type(a.astype(jnp.int32), jnp.float32)
            if flag else a.astype(jnp.float32)
            for a, flag in zip(arrs, int_flags)])
    return pack


def fetch_packed(*arrays) -> Tuple[np.ndarray, ...]:
    """Read N same-shape f32/int device arrays back to host in ONE transfer.

    Every device→host readback pays a full dispatch round trip — on the
    tunneled TPU backend that's ~70 ms flat (measured r4), so the common
    kernel-output pattern ``np.asarray(scores); np.asarray(rows)`` doubles
    (or worse) every search/link/evict latency. Int arrays are bitcast (not
    cast) to f32 on device, stacked with the float arrays, and the single
    [N, ...] array is fetched; the bitcast is undone with a zero-copy
    ``.view`` on host. The stack is an extra on-device op, but dispatch is
    async — only readbacks block."""
    int_flags = tuple(np.issubdtype(np.dtype(a.dtype), np.integer)
                      for a in arrays)
    packed = np.asarray(_packer(int_flags)(*arrays))
    return tuple(packed[i].view(np.int32) if flag else packed[i]
                 for i, flag in enumerate(int_flags))


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (1 for n <= 1 — a single item needs no
    padding; mapping 1 → 2 would double every single-query dispatch)."""
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def pad_to_pow2(arr: np.ndarray) -> np.ndarray:
    """Pad axis 0 with zero rows up to the power-of-two bucket."""
    n = arr.shape[0]
    bucket = next_pow2(n)
    if bucket == n:
        return arr
    pad = np.zeros((bucket - n,) + arr.shape[1:], arr.dtype)
    return np.concatenate([arr, pad])


def bucket_size(n: int, granularity: int) -> int:
    """Query-batch bucket of the ragged serving path (ISSUE 7): LINEAR
    multiples of ``granularity`` instead of powers of two once batches
    pass the granularity — pow2 wastes up to ~50% of every dispatch's
    padded slots (a 33-request batch pays 64 kernel slots), linear
    buckets waste at most ``granularity - 1``. Below the granularity the
    pow2 ladder is kept (1, 2, 4): a lone request must keep costing a
    1-slot dispatch, not ``granularity`` slots. Distinct jit
    specializations stay bounded either way (log2(g) small buckets +
    max_batch/g linear ones)."""
    g = max(1, int(granularity))
    n = max(1, int(n))
    if n <= g:
        return next_pow2(n)
    return -(-n // g) * g


def pad_to_bucket(arr: np.ndarray, granularity: int) -> np.ndarray:
    """Pad axis 0 with zero rows up to the linear ``granularity`` bucket
    (the ragged-serving replacement for :func:`pad_to_pow2`)."""
    n = arr.shape[0]
    bucket = bucket_size(n, granularity)
    if bucket == n:
        return arr
    pad = np.zeros((bucket - n,) + arr.shape[1:], arr.dtype)
    return np.concatenate([arr, pad])


class LRUKernelCache:
    """Tiny LRU map bounding a compiled-kernel cache (ISSUE 7 satellite):
    before ragged serving, per-(mode × k-bucket) keys grew without bound
    under mixed-k traffic and ``kernel.cache_entries`` could only watch;
    now the cap evicts the least-recently-served program (dropping a jit
    wrapper frees its compiled executables once no caller holds it).
    Not thread-safe by itself — callers serialize through their own
    locks (the serving dispatch already does)."""

    def __init__(self, max_entries: int = 8):
        from collections import OrderedDict
        self.max_entries = max(1, int(max_entries))
        self._d = OrderedDict()
        self.evictions = 0

    def get(self, key):
        v = self._d.get(key)
        if v is not None:
            self._d.move_to_end(key)
        return v

    def put(self, key, value) -> None:
        self._d[key] = value
        self._d.move_to_end(key)
        while len(self._d) > self.max_entries:
            self._d.popitem(last=False)
            self.evictions += 1

    def __len__(self) -> int:
        return len(self._d)

    def __contains__(self, key) -> bool:
        return key in self._d

    def keys(self):
        return list(self._d.keys())


def decode_topk(scores: np.ndarray, rows: np.ndarray,
                row_to_id: Dict[int, str], neg_inf: float,
                limit: Optional[int] = None,
                lengths: Optional[Sequence[int]] = None
                ) -> List[Tuple[List[str], List[float]]]:
    """Per query: drop NEG_INF sentinels, rows without a live id mapping,
    and repeated rows (a slot reused after delete can appear in both a
    stale IVF member slot and the fresh residual — scores are sorted
    descending, so keeping the first occurrence keeps the best); return
    (ids, scores) pairs. ``limit`` caps each list AFTER dedup — the IVF
    serving path over-fetches k + slack so duplicates can't shrink the
    result below k, then trims back here. ``lengths`` is the RAGGED
    decode bound (ISSUE 7): the packed readback's per-query live-length
    counter, so a k=4 request in a K-ceiling batch scans 4 columns of its
    row instead of all K (live entries are a sorted prefix — everything
    past a query's own k was masked to NEG_INF on device)."""
    out: List[Tuple[List[str], List[float]]] = []
    for qi in range(scores.shape[0]):
        ids: List[str] = []
        sc: List[float] = []
        seen = set()
        n_cols = scores.shape[1]
        if lengths is not None:
            n_cols = min(n_cols, max(0, int(lengths[qi])))
        for s, r in zip(scores[qi, :n_cols], rows[qi, :n_cols]):
            if limit is not None and len(ids) >= limit:
                break
            if s <= neg_inf / 2:
                continue
            r = int(r)
            if r in seen:
                continue
            seen.add(r)
            node_id = row_to_id.get(r)
            if node_id is not None:
                ids.append(node_id)
                sc.append(float(s))
        out.append((ids, sc))
    return out


def empty_results(n: int) -> List[Tuple[List[str], List[float]]]:
    """n independent ([], []) pairs — NOT `[([], [])] * n`, which aliases
    the same two lists across every entry."""
    return [([], []) for _ in range(n)]


# Column names of the device-counter tail every fused serving readback
# carries (core.state.RETRIEVAL_TAIL int32 columns after the fast bit):
# live top-k hits, in-kernel dedup drops, access-boost rows scattered,
# neighbor-boost rows scattered, semantic-cache verdict (0 = miss,
# 1 + ring slot on a hit).
RETRIEVAL_COUNTERS = ("live", "dedup_dropped", "acc_boost_rows",
                      "nbr_boost_rows", "semantic")


def unpack_retrieval(host: np.ndarray, k: int
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                np.ndarray, np.ndarray, np.ndarray]:
    """Host half of ``core.state._pack_retrieval``: split the ONE
    [Q, 3 + 2k + 4] packed readback into (gate_scores, gate_rows,
    ann_scores, ann_rows, fast, counters). Row and counter columns were
    bitcast (not cast) on device, so the int view reverses them
    losslessly; ``counters`` is the [Q, 4] int32 device-counter tail
    (column names in :data:`RETRIEVAL_COUNTERS` — ISSUE 6 observability
    riding the existing transfer). Shared by the single-chip and the
    pod-sharded fused serving decoders."""
    ann_s = host[:, 2:2 + k]
    ann_r = np.ascontiguousarray(host[:, 2 + k:2 + 2 * k]).view(np.int32)
    gate_s = host[:, 0]
    gate_r = np.ascontiguousarray(host[:, 1:2]).view(np.int32)[:, 0]
    fast = host[:, 2 + 2 * k] > 0.5
    counters = np.ascontiguousarray(
        host[:, 3 + 2 * k:3 + 2 * k + len(RETRIEVAL_COUNTERS)]
    ).view(np.int32)
    return gate_s, gate_r, ann_s, ann_r, fast, counters


class FlushPolicy:
    """Time/size flush decision shared by ``IngestCoalescer`` (ingest side)
    and ``serve.QueryScheduler`` (query side).

    A batch flushes when it holds ``max_items`` entries OR when its oldest
    entry has waited ``max_wait_s`` — so bursty load coalesces into dense
    device batches while trickle load is never held hostage to a size
    threshold it will not reach. ``max_wait_s <= 0`` means "flush on every
    check" (the eager pre-policy behavior)."""

    def __init__(self, max_items: int, max_wait_s: float):
        self.max_items = max(1, int(max_items))
        self.max_wait_s = float(max_wait_s)
        self._oldest: Optional[float] = None

    def note_add(self, now: float) -> None:
        if self._oldest is None:
            self._oldest = now

    def should_flush(self, n_items: int, now: float,
                     oldest: Optional[float] = None) -> bool:
        """``oldest`` overrides the internally-tracked first-add time —
        callers that pop partial batches (the query scheduler) know the
        true head-of-queue age; callers that drain whole buffers (the
        ingest coalescer) rely on ``note_add``/``reset``."""
        if n_items <= 0:
            return False
        if self.max_wait_s <= 0 or n_items >= self.max_items:
            return True
        if oldest is None:
            oldest = self._oldest
        return oldest is not None and (now - oldest) >= self.max_wait_s

    def wait_remaining(self, now: float,
                       oldest: Optional[float] = None) -> float:
        """Seconds until the oldest entry's deadline (0 when due; a large
        value when empty — callers use it as a condition-wait timeout)."""
        if oldest is None:
            oldest = self._oldest
        if oldest is None:
            return 3600.0
        if self.max_wait_s <= 0:
            return 0.0
        return max(0.0, oldest + self.max_wait_s - now)

    @property
    def oldest(self) -> Optional[float]:
        """First-add time of the current buffer (None when empty) — the
        coalesce-wait telemetry reads it at drain time."""
        return self._oldest

    def reset(self) -> None:
        self._oldest = None


class IngestCoalescer:
    """Cross-conversation ingest batcher for the fused single-dispatch
    pipeline.

    Consolidation extracts a fact list per drained conversation; this
    buffer coalesces the lists of EVERY buffered conversation into padded
    mega-batches so the fused ingest kernel (``state.ingest_fused``)
    dispatches once per mega-batch instead of once per conversation.
    Conversations are kept whole when they fit under ``max_facts`` — the
    cap bounds the padded jit bucket (and the [B, capacity] link-scan
    tile) — and only oversized single conversations are split.

    ``drain`` returns ``(facts, n_conversations)`` mega-batches and empties
    the buffer; nothing is ever withheld across a drain, so durability
    bookkeeping (WAL, in-flight batches) stays with the caller.

    With ``max_wait_s > 0`` the coalescer also carries a time/size flush
    policy (``FlushPolicy``): ``should_flush`` stays False while the buffer
    is small AND young, so a steady trickle of single conversations
    accumulates into one dense fused dispatch instead of draining one
    conversation at a time (ROADMAP open item 3). The caller decides when
    to consult the policy and remains responsible for durability of
    deferred facts (the source turns stay in the WAL until their facts are
    ingested). ``max_wait_s = 0`` (default) preserves the eager behavior:
    every check says flush.
    """

    def __init__(self, max_facts: int = 8192, max_wait_s: float = 0.0):
        self.max_facts = max(1, int(max_facts))
        self.policy = FlushPolicy(self.max_facts, max_wait_s)
        self._convs: List[List[dict]] = []

    def add_conversation(self, facts: Sequence[dict],
                         now: Optional[float] = None) -> None:
        if facts:
            import time as _time
            self._convs.append(list(facts))
            self.policy.note_add(now if now is not None else _time.time())

    def should_flush(self, now: Optional[float] = None) -> bool:
        import time as _time
        return self.policy.should_flush(
            len(self), now if now is not None else _time.time())

    def oldest_age_s(self, now: Optional[float] = None) -> float:
        """Age of the oldest buffered conversation (0.0 when empty) — the
        per-mega-batch coalesce-wait the ingest telemetry records at drain
        time (ISSUE 9 satellite: the write-path twin of the serving
        queue-wait span)."""
        import time as _time
        oldest = self.policy.oldest
        if oldest is None:
            return 0.0
        return max(0.0, (now if now is not None else _time.time()) - oldest)

    def __len__(self) -> int:
        return sum(len(c) for c in self._convs)

    @property
    def pending_conversations(self) -> int:
        return len(self._convs)

    def requeue(self, batches: Sequence[Tuple[Sequence[dict], int]],
                now: Optional[float] = None) -> None:
        """Put drained-but-not-ingested mega-batches BACK at the front of
        the buffer (ISSUE 10): an ingest dispatch failure must not lose
        the facts the drain already popped — they retry on the next
        flush, ahead of anything buffered since, and the durable ingest
        journal keeps them crash-safe meanwhile."""
        if not batches:
            return
        import time as _time
        self._convs = [list(facts) for facts, _ in batches
                       if facts] + self._convs
        if self._convs:
            self.policy.note_add(now if now is not None else _time.time())

    def drain(self) -> List[Tuple[List[dict], int]]:
        batches: List[Tuple[List[dict], int]] = []
        batch: List[dict] = []
        n_convs = 0
        convs, self._convs = self._convs, []
        self.policy.reset()
        for conv in convs:
            while len(conv) > self.max_facts:          # oversized: split
                if batch:
                    batches.append((batch, n_convs))
                    batch, n_convs = [], 0
                batches.append((conv[:self.max_facts], 1))
                conv = conv[self.max_facts:]
            if batch and len(batch) + len(conv) > self.max_facts:
                batches.append((batch, n_convs))
                batch, n_convs = [], 0
            if conv:
                batch = batch + conv
                n_convs += 1
        if batch:
            batches.append((batch, n_convs))
        return batches
