from lazzaro_tpu.parallel.mesh import (make_mesh, replica_group_meshes,
                                       single_device_mesh, spec)
from lazzaro_tpu.parallel.ring_attention import make_ring_attention
from lazzaro_tpu.parallel.ulysses import make_ulysses_attention

__all__ = ["make_mesh", "replica_group_meshes", "single_device_mesh",
           "spec", "make_ring_attention", "make_ulysses_attention"]
