from lazzaro_tpu.parallel.mesh import make_mesh, single_device_mesh, spec

__all__ = ["make_mesh", "single_device_mesh", "spec"]
