"""ShardedMemoryIndex: the memory index spread across a device mesh.

This is the pod-scale variant of ``core.index.MemoryIndex`` (SURVEY §2.3's
"index model-parallelism" + "tenant partitioning = mesh sharding"): the
embedding matrix, masks, and numeric columns are row-sharded over the mesh
'data' axis (HBM-resident on every chip), queries are replicated, and search
is local-top-k → all_gather → global-top-k over ICI.

Tenant partitioning (the EP analog): with ``tenant_affinity`` on, every
tenant's rows are allocated inside one mesh partition (hash(tenant) % n),
so per-tenant sweeps (decay, eviction scoring) touch one chip's rows and
multi-tenant fleets spread across the pod — replacing the reference's
row-level `user_id` BTREE filter (vector_store.py:55) with physical placement.
Multi-host works unchanged: build the mesh after ``jax.distributed.initialize``.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lazzaro_tpu.ops.topk import make_sharded_topk

NEG_INF = -1e30


class ShardedMemoryIndex:
    def __init__(self, mesh: Mesh, dim: int, capacity: int = 1 << 20,
                 axis: str = "data", dtype=jnp.bfloat16,
                 tenant_affinity: bool = True, k: int = 10):
        self.mesh = mesh
        self.axis = axis
        self.dim = dim
        self.n_parts = mesh.shape[axis]
        assert capacity % self.n_parts == 0, "capacity must divide the mesh axis"
        self.capacity = capacity
        self.part_rows = capacity // self.n_parts
        self.tenant_affinity = tenant_affinity

        self._row_sh = NamedSharding(mesh, P(axis))
        self._mat_sh = NamedSharding(mesh, P(axis, None))
        self._rep = NamedSharding(mesh, P())

        self.emb = jax.device_put(jnp.zeros((capacity, dim), dtype), self._mat_sh)
        self.alive = jax.device_put(jnp.zeros((capacity,), bool), self._row_sh)
        self.tenant = jax.device_put(jnp.full((capacity,), -1, jnp.int32), self._row_sh)
        self.salience = jax.device_put(jnp.zeros((capacity,), jnp.float32), self._row_sh)

        # host bookkeeping: per-partition free lists, global id maps
        self._free: List[List[int]] = [
            list(range((p + 1) * self.part_rows - 1, p * self.part_rows - 1, -1))
            for p in range(self.n_parts)]
        self.id_to_row: Dict[str, int] = {}
        self.row_to_id: Dict[int, str] = {}
        self._tenants: Dict[str, int] = {}

        self._k = k
        self._search = make_sharded_topk(mesh, axis, k=k)
        # Per-row tenant serving kernel (ROADMAP ceiling #4), built lazily
        # on the first coalesced serve: pod-scale mixed-tenant batches
        # dispatch ONCE total instead of once per tenant group.
        self._serve_search = None
        self._update = jax.jit(self._update_impl, donate_argnums=(0, 1, 2, 3))
        self._decay = jax.jit(self._decay_impl, donate_argnums=(0,))

    # ------------------------------------------------------------------ util
    def tenant_id(self, name: str) -> int:
        if name not in self._tenants:
            self._tenants[name] = len(self._tenants)
        return self._tenants[name]

    def _partition_for(self, tenant: str) -> int:
        if not self.tenant_affinity:
            return int(np.random.default_rng(abs(hash(tenant)) % 2**32).integers(self.n_parts))
        return abs(hash(tenant)) % self.n_parts

    def _alloc(self, tenant: str, n: int) -> List[int]:
        """Allocate rows, preferring the tenant's home partition, spilling
        round-robin to others when full."""
        home = self._partition_for(tenant)
        order = [home] + [p for p in range(self.n_parts) if p != home]
        rows: List[int] = []
        for p in order:
            while self._free[p] and len(rows) < n:
                rows.append(self._free[p].pop())
            if len(rows) == n:
                break
        if len(rows) < n:
            raise RuntimeError("ShardedMemoryIndex capacity exhausted")
        return rows

    @staticmethod
    def _update_impl(emb, alive, tenant, salience, rows, new_emb, new_tenant,
                     new_salience, live):
        emb = emb.at[rows].set(new_emb)
        alive = alive.at[rows].set(live)
        tenant = tenant.at[rows].set(new_tenant)
        salience = salience.at[rows].set(new_salience)
        return emb, alive, tenant, salience

    @staticmethod
    def _decay_impl(salience, alive, tenant, tid, rate, floor):
        mask = alive & (tenant == tid)
        return jnp.where(mask, floor + (salience - floor) * (1.0 - rate), salience)

    # ------------------------------------------------------------------- api
    def add(self, ids: Sequence[str], embeddings: np.ndarray, tenant: str,
            saliences: Optional[Sequence[float]] = None) -> List[int]:
        n = len(ids)
        if n == 0:
            return []
        if saliences is None:
            saliences = [0.5] * n
        rows = []
        fresh = self._alloc(tenant, sum(1 for i in ids if i not in self.id_to_row))
        fi = 0
        for node_id in ids:
            if node_id in self.id_to_row:
                rows.append(self.id_to_row[node_id])
            else:
                r = fresh[fi]; fi += 1
                self.id_to_row[node_id] = r
                self.row_to_id[r] = node_id
                rows.append(r)

        emb = np.asarray(embeddings, np.float32).reshape(n, self.dim)
        emb = emb / np.maximum(np.linalg.norm(emb, axis=1, keepdims=True), 1e-9)
        tid = self.tenant_id(tenant)
        self.emb, self.alive, self.tenant, self.salience = self._update(
            self.emb, self.alive, self.tenant, self.salience,
            jnp.asarray(np.asarray(rows, np.int32)),
            jnp.asarray(emb.astype(np.float32)).astype(self.emb.dtype),
            jnp.full((n,), tid, jnp.int32),
            jnp.asarray(np.asarray(saliences, np.float32)),
            jnp.ones((n,), bool))
        return rows

    def delete(self, ids: Sequence[str]) -> None:
        rows = [self.id_to_row.pop(i) for i in ids if i in self.id_to_row]
        if not rows:
            return
        n = len(rows)
        for r in rows:
            self.row_to_id.pop(r, None)
            self._free[r // self.part_rows].append(r)
        self.emb, self.alive, self.tenant, self.salience = self._update(
            self.emb, self.alive, self.tenant, self.salience,
            jnp.asarray(np.asarray(rows, np.int32)),
            jnp.zeros((n, self.dim), self.emb.dtype),
            jnp.full((n,), -1, jnp.int32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), bool))

    def search(self, query: np.ndarray, tenant: str
               ) -> Tuple[List[str], List[float]]:
        """Distributed masked top-k: local per-chip → all_gather → global.
        Single-query view of ``search_batch``."""
        return self.search_batch(np.asarray(query, np.float32)[None, :],
                                 tenant)[0]

    def search_batch(self, queries: np.ndarray, tenant: str
                     ) -> List[Tuple[List[str], List[float]]]:
        """Multi-query distributed top-k: Q queries share one local-score
        matmul per chip and one all_gather — fleet serving over the pod.
        Q is bucketed to a power of two: each distinct query-batch shape
        would otherwise retrace the pod-wide shard_map kernel (multi-second
        compiles are most expensive exactly here)."""
        from lazzaro_tpu.utils.batching import (decode_topk, empty_results,
                                                pad_to_pow2)

        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]
        nq = queries.shape[0]
        tid = self._tenants.get(tenant)
        if tid is None or nq == 0:
            return empty_results(nq)
        norms = np.maximum(np.linalg.norm(queries, axis=1, keepdims=True), 1e-9)
        q = pad_to_pow2(queries / norms)
        mask = self.alive & (self.tenant == tid)
        scores, rows = self._search(self.emb, mask, jnp.asarray(q))
        return decode_topk(np.asarray(scores)[:nq], np.asarray(rows)[:nq],
                           self.row_to_id, NEG_INF)

    def serve_requests(self, reqs) -> List:
        """``serve.QueryScheduler`` executor for the pod-sharded path: one
        coalesced batch of :class:`serve.RetrievalRequest`s becomes ONE
        distributed top-k for the whole mixed-tenant batch — each query
        carries its tenant id into the kernel as a replicated column and
        isolation is the per-row ``tenant_col == query_tenant`` mask
        (ROADMAP ceiling #4; previously the batch dispatched once per
        tenant group). No edge arena lives here, so boost/gate requests
        serve as plain reads: ``fast`` and ``boosted`` stay False and the
        orchestrator's classic host path pays any boosts."""
        from lazzaro_tpu.ops.topk import make_sharded_multitenant_topk
        from lazzaro_tpu.serve.scheduler import RetrievalResult
        from lazzaro_tpu.utils.batching import decode_topk, pad_to_pow2

        results = [RetrievalResult() for _ in reqs]
        nq = len(reqs)
        if nq == 0:
            return results
        q = np.zeros((nq, self.dim), np.float32)
        tids = np.full((nq,), -1, np.int32)
        for i, r in enumerate(reqs):
            v = np.asarray(r.query, np.float32).reshape(-1)
            tid = self._tenants.get(r.tenant)
            if v.size != self.dim or tid is None:
                continue                    # tenant -1 matches no rows
            q[i] = v / max(float(np.linalg.norm(v)), 1e-9)
            tids[i] = tid
        if (tids < 0).all():
            return results
        if self._serve_search is None:
            self._serve_search = make_sharded_multitenant_topk(
                self.mesh, self.axis, k=self._k)
        qp = pad_to_pow2(q)
        tp = np.full((qp.shape[0],), -1, np.int32)
        tp[:nq] = tids
        scores, rows = self._serve_search(self.emb, self.alive, self.tenant,
                                          jnp.asarray(qp), jnp.asarray(tp))
        decoded = decode_topk(np.asarray(scores)[:nq], np.asarray(rows)[:nq],
                              self.row_to_id, NEG_INF)
        for i, (ids, sc) in enumerate(decoded):
            k = int(reqs[i].k)
            results[i].ids = ids[:k]
            results[i].scores = sc[:k]
        return results

    def decay(self, tenant: str, rate: float, floor: float = 0.2) -> None:
        tid = self._tenants.get(tenant)
        if tid is None:
            return
        self.salience = self._decay(self.salience, self.alive, self.tenant,
                                    jnp.int32(tid), jnp.float32(rate),
                                    jnp.float32(floor))

    def partition_of(self, node_id: str) -> Optional[int]:
        row = self.id_to_row.get(node_id)
        return None if row is None else row // self.part_rows
