"""ShardedMemoryIndex: the memory index spread across a device mesh.

This is the pod-scale variant of ``core.index.MemoryIndex`` (SURVEY §2.3's
"index model-parallelism" + "tenant partitioning = mesh sharding"): every
arena column — embeddings, salience, access counters, tenant and super-node
flags — is row-sharded over the mesh 'data' axis (HBM-resident on every
chip), queries are replicated, and serving is shard-local scan →
``all_gather`` merge → shard-local boost scatters.

Serving (ISSUE 5): ``serve_requests`` runs the FULL chat-turn retrieval
program — masked super-node top-1 gate, main ANN top-k, CSR neighbor
gather over a row-sharded edge arena, and the neighbor- + access-salience
boost scatters — as ONE distributed ``shard_map`` dispatch + ONE packed
readback per coalesced mega-batch (``core.state.make_fused_sharded``; the
pre-ISSUE-5 pod path served a plain multitenant top-k that silently
DROPPED the gate, the neighbor gather, and every boost). Per-request
tenants ride into the kernel as a replicated column, so one mixed-tenant
batch dispatches once with mask-enforced isolation; boosts land as
shard-local scatters (each chip writes only the rows it owns — no boost
ever crosses a chip boundary), and the kernel batch is keyed on the batch
max-k (pow2-bucketed), so a request's ``k`` is never silently truncated
to a construction-time constant. With ``int8_serving`` the shard-local
scan streams the per-chip int8 shadow (coarse top-(k+slack) + exact
rescore — on real TPU that also rides the MXU int8 path), and with a
build published by ``ivf_build`` it becomes the centroid prefilter over
per-shard LOCAL member tables. ``serve_fused=False`` keeps the classic
single-purpose multitenant top-k for A/B and fallback.

Tenant partitioning (the EP analog): with ``tenant_affinity`` on, every
tenant's rows are allocated inside one mesh partition (hash(tenant) % n),
so per-tenant sweeps (decay, eviction scoring) touch one chip's rows and
multi-tenant fleets spread across the pod — replacing the reference's
row-level `user_id` BTREE filter (vector_store.py:55) with physical placement.
Multi-host works unchanged: build the mesh after ``jax.distributed.initialize``.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.index import (_EdgeSlotMap, build_host_csr,
                                    link_pool_dev, link_pool_size,
                                    split_csr)
from lazzaro_tpu.ops.topk import make_sharded_topk
from lazzaro_tpu.parallel.mesh import shard_stacked
from lazzaro_tpu.plan import Geometry, HbmPlanner
from lazzaro_tpu.reliability import faults
from lazzaro_tpu.reliability.errors import (ArenaPoisoned, DeviceOom,
                                            PlanInfeasible)
from lazzaro_tpu.reliability.guard import (check_not_poisoned,
                                           is_resource_exhausted,
                                           run_guarded)
from lazzaro_tpu.utils.batching import (LRUKernelCache, bucket_size,
                                        decode_topk, empty_results,
                                        fetch_packed, next_pow2,
                                        pad_to_bucket, pad_to_pow2,
                                        unpack_retrieval)
from lazzaro_tpu.utils.compat import trace_annotation
from lazzaro_tpu.utils.telemetry import (default_registry, peak_bytes,
                                         record_device_counters)

NEG_INF = -1e30


@jax.jit
def _shadow_update(q8, scale, rows, emb_stored):
    """Incremental int8-shadow maintenance for freshly written rows —
    O(batch), mirroring the fused-ingest ``_shadow_scatter``."""
    from lazzaro_tpu.ops.quant import quantize_rows

    q_new, s_new = quantize_rows(emb_stored)
    return q8.at[rows].set(q_new), scale.at[rows].set(s_new)


@jax.jit
def _pq_codes_update(book_cent, codes, rows, emb_stored):
    """Incremental PQ-code maintenance for freshly written rows (ISSUE
    16) — the non-fused-write twin of the in-kernel ``_pq_scatter``:
    encode the stored vectors against the frozen codebook and patch the
    batch's rows in place."""
    from lazzaro_tpu.ops.pq import encode_pq

    return codes.at[rows].set(encode_pq(book_cent, emb_stored))


class ShardedMemoryIndex:
    # References to the arena pytree at the donation gate when this index
    # is the sole owner: the ``_arena`` attribute, the ``cur`` local, and
    # ``sys.getrefcount``'s own argument (same contract as MemoryIndex).
    _SOLE_REFS = 3

    def __init__(self, mesh: Mesh, dim: int, capacity: int = 1 << 20,
                 axis: str = "data", dtype=jnp.bfloat16,
                 tenant_affinity: bool = True, k: int = 10,
                 serve_fused: bool = True, int8_serving: bool = False,
                 pq_serving: bool = False,
                 coarse_slack: int = 8, cap_take: int = 5,
                 max_nbr: int = 32, super_gate: float = 0.4,
                 acc_boost: float = 0.05, nbr_boost: float = 0.02,
                 epoch: Optional[float] = None, telemetry=None,
                 telemetry_hbm: bool = False, serve_ragged: bool = True,
                 serve_k_max: int = 128, serve_pad_granularity: int = 8,
                 serve_kernel_cache_max: int = 8,
                 edge_capacity: int = 1 << 17,
                 ingest_fused: bool = True,
                 ivf_online: bool = True,
                 ivf_member_cap_factor: int = 4,
                 ivf_online_eta: float = 1.0,
                 hbm_budget_bytes: int = 0,
                 hbm_headroom_fraction: float = 0.1,
                 plan_max_splits: int = 16,
                 plan_calibration_path: Optional[str] = None,
                 planner: Optional[HbmPlanner] = None,
                 semantic_cache: bool = False,
                 semantic_cache_slots: int = 64,
                 semantic_cache_threshold: float = 0.985,
                 semantic_cache_block: int = 16):
        self.mesh = mesh
        # Serving telemetry (ISSUE 6): same registry contract as
        # MemoryIndex — spans per dispatch, device counters decoded from
        # the packed readback tail, opt-in peak-HBM gauges per kernel.
        self.telemetry = telemetry if telemetry is not None \
            else default_registry()
        self.telemetry_hbm = bool(telemetry_hbm)
        self._hbm_recorded: set = set()
        # Admission-time HBM planner (ISSUE 11): same contract as
        # MemoryIndex — the pod path admits fused, splits the query batch
        # into planned sub-dispatches, or rejects typed. (The distributed
        # kernels keep their built-in chunk structure; the scan-chunk
        # override is a single-chip degradation rung.)
        self.planner = planner if planner is not None else HbmPlanner(
            budget_bytes=hbm_budget_bytes,
            headroom_fraction=hbm_headroom_fraction,
            telemetry=self.telemetry,
            granularity=max(1, int(serve_pad_granularity)),
            max_splits=plan_max_splits,
            calibration_path=plan_calibration_path)
        self.dispatch_count = 0
        self.axis = axis
        self.dim = dim
        self.n_parts = mesh.shape[axis]
        # Replica-group serving (ISSUE 18): set >1 by ReplicaPlacement on
        # each group's index — this index then owns one FULL arena copy
        # row-sharded over a group-local sub-mesh, and the group count
        # rides into geometry admission and the peak-HBM gauge labels so
        # the planner/CI can see the fleet-wide replication factor.
        self.replica_groups = 1
        # Row geometry: the arena carries capacity+1 rows (last = the
        # sentinel scratch row, core.state contract) and the TOTAL must
        # divide the mesh axis — capacity is rounded UP, never rejected.
        total = capacity + 1
        total = -(-total // self.n_parts) * self.n_parts
        self.capacity = total - 1
        self.part_rows = total // self.n_parts
        self.tenant_affinity = tenant_affinity
        self.dtype = dtype
        self.epoch = float(epoch if epoch is not None else time.time())

        self.serve_fused = bool(serve_fused)
        self.int8_serving = bool(int8_serving)
        self.pq_serving = bool(pq_serving)
        self.coarse_slack = max(0, int(coarse_slack))
        self.cap_take = int(cap_take)
        self.max_nbr = int(max_nbr)
        self.super_gate = float(super_gate)
        self.acc_boost = float(acc_boost)
        self.nbr_boost = float(nbr_boost)

        self._row_sh = NamedSharding(mesh, P(axis))
        self._mat_sh = NamedSharding(mesh, P(axis, None))
        self._rep = NamedSharding(mesh, P())
        self._stacked = shard_stacked(mesh, axis)
        # Donation-safe recovery (ISSUE 10): same contract as MemoryIndex —
        # transient failures retry through the copying twin, a consumed
        # input poisons the index and raises typed.
        self.dispatch_retry_max = 2
        self.dispatch_retry_backoff_s = 0.005
        self._poisoned = False

        self._state_lock = threading.RLock()
        self._arena = self._reshard(S.init_arena(self.capacity, dim, dtype))

        # host bookkeeping: per-partition free lists (the global sentinel
        # row — the last row of the last partition — is never allocated),
        # global id maps, host edge map for the CSR shadow, super rows.
        self._free: List[List[int]] = [
            [r for r in range((p + 1) * self.part_rows - 1,
                              p * self.part_rows - 1, -1)
             if r != self.capacity]
            for p in range(self.n_parts)]
        self.id_to_row: Dict[str, int] = {}
        self.row_to_id: Dict[int, str] = {}
        self._tenants: Dict[str, int] = {}
        self.edges: Dict[Tuple[str, str], float] = {}
        self._csr_cache = None             # (indptr_dev, nbr_dev)
        self._csr_dirty = True
        self._super_rows: set = set()

        # int8 serving shadow (row-sharded like the master; rebuilt lazily,
        # maintained incrementally by add()'s scatter once built)
        self._int8_shadow = None
        self._int8_dirty = True

        # PQ serving pack (ISSUE 16): ``(book_cent [m,256,dsub] replicated,
        # codes [rows,m] u8 row-sharded with the master)``. Published
        # COMPLETE by ivf_build, then maintained incrementally — the fused
        # ingest's in-kernel ``_pq_scatter`` and add()'s host patch — so
        # the pack never carries a dirty flag.
        self._pq_pack = None

        # Pod-scale fused ingest (ISSUE 9): a row-sharded edge arena is
        # the write target of the distributed ingest program — the fused
        # kernel's gated link insert compacts accepted edges into it
        # owner-chip-local — while the host edge map (``self.edges``)
        # mirrors every accepted edge from the packed readback, so the
        # serving CSR build and checkpoints are unchanged. Slots are
        # GLOBAL ids; the last slot of the last shard is the sentinel.
        self.ingest_fused = bool(ingest_fused)
        total_e = edge_capacity + 1
        total_e = -(-total_e // self.n_parts) * self.n_parts
        self.edge_capacity = total_e - 1
        self._edge_state = self._reshard(S.init_edges(self.edge_capacity))
        self._free_edge_slots: List[int] = list(
            range(self.edge_capacity - 1, -1, -1))
        self.edge_slots: _EdgeSlotMap = _EdgeSlotMap()
        self._ingest_cache = LRUKernelCache(serve_kernel_cache_max)
        self._ingest_classic_cache = LRUKernelCache(serve_kernel_cache_max)
        self.link_pool_overflows = 0
        self.ingest_dispatch_count = 0

        # IVF serve tables (publish via ivf_build): centroids replicated,
        # member/extras tables split per shard with LOCAL row indices
        self._ivf = None          # (centroids_dev, members_np, residual_np,
        #                            nprobe)
        self._ivf_routed = None   # np bool [rows]
        self._ivf_fresh: List[int] = []
        self._ivf_tabs_cache = None
        # Online IVF maintenance (ISSUE 12), pod twin: with a seeded
        # build, the LIVE coarse tables — ``(cent [C,d] replicated,
        # members [n,C,M] stacked per shard with LOCAL row ids — the
        # exact layout make_fused_sharded mode="ivf" serves from —
        # counts [n,C] REPLICATED per-(shard, cluster) occupancy)`` —
        # ride the distributed ingest dispatch as donated state: the
        # centroid scores join the grouped all_gather as a fourth
        # candidate group, member appends land owner-chip-local, and the
        # mini-batch centroid step is replicated arithmetic.
        self.ivf_online = bool(ivf_online)
        self.ivf_member_cap_factor = max(1, int(ivf_member_cap_factor))
        self.ivf_online_eta = float(ivf_online_eta)
        self._ivf_dev = None      # (cent, members_sh, counts) live tables

        # Tiered memory (ISSUE 8): attach_tiering hangs a TierManager here
        # (per-shard host cold stores — one per mesh partition — plus the
        # row-sharded residency column). ``_emb_gen`` guards the pump's
        # gather→scatter window against racing embedding writes.
        self.tiering = None
        self._emb_gen = 0
        self._csr_flat_cache = None

        self._k = k
        self._search = make_sharded_topk(mesh, axis, k=k)
        # Ragged pod serving (ISSUE 7): per-query k/cap/nprobe sidecars,
        # kernels keyed per MODE at the serve_k_max ceiling.
        self.serve_ragged = bool(serve_ragged)
        self.serve_k_max = max(1, int(serve_k_max))
        self.serve_pad_granularity = max(1, int(serve_pad_granularity))
        # Classic pod serving kernels (serve_fused=False A/B + fallback),
        # keyed by the batch max-k pow2 bucket so a request's k above the
        # construction-time default retraces instead of truncating.
        # LRU-capped (ISSUE 7 satellite) like the fused cache below.
        self._serve_search_cache = LRUKernelCache(serve_kernel_cache_max)
        # Fused distributed serving programs — per-mode keys with ragged
        # serving, (mode, k_bucket) without; LRU-capped so mixed-k
        # non-ragged traffic can no longer grow it without bound.
        self._fused_cache = LRUKernelCache(serve_kernel_cache_max)

        # Semantic query cache (ISSUE 20): the ring is REPLICATED over
        # the mesh (probe/substitute/writeback run identically on every
        # chip after the all_gather merge), so the single-chip host
        # mirror works unchanged — same hit masks, same LIFO replay.
        self._sem_host = None
        if semantic_cache:
            from lazzaro_tpu.core.index import SemanticCacheHost
            self._sem_host = SemanticCacheHost(
                semantic_cache_slots, dim,
                self.serve_k_max + self.coarse_slack,
                semantic_cache_threshold, semantic_cache_block,
                telemetry=self.telemetry)

    # ------------------------------------------------------------------ util
    def _reshard(self, pytree):
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(
                a, self._mat_sh if a.ndim == 2 else self._row_sh), pytree)

    @property
    def state(self) -> S.ArenaState:
        with self._state_lock:
            return self._arena

    @state.setter
    def state(self, s: S.ArenaState) -> None:
        self._arena = self._reshard(s)

    # Legacy column views (checkpointing, tests, bench poke these).
    @property
    def emb(self):
        return self.state.emb

    @property
    def alive(self):
        return self.state.alive

    @property
    def tenant(self):
        return self.state.tenant_id

    @property
    def salience(self):
        return self.state.salience

    def tenant_id(self, name: str) -> int:
        if name not in self._tenants:
            self._tenants[name] = len(self._tenants)
        return self._tenants[name]

    def _partition_for(self, tenant: str) -> int:
        if not self.tenant_affinity:
            return int(np.random.default_rng(
                abs(hash(tenant)) % 2**32).integers(self.n_parts))
        return abs(hash(tenant)) % self.n_parts

    def _alloc(self, tenant: str, n: int) -> List[int]:
        """Allocate rows, preferring the tenant's home partition, spilling
        round-robin to others when full."""
        home = self._partition_for(tenant)
        order = [home] + [p for p in range(self.n_parts) if p != home]
        rows: List[int] = []
        for p in order:
            while self._free[p] and len(rows) < n:
                rows.append(self._free[p].pop())
            if len(rows) == n:
                break
        if len(rows) < n:
            raise RuntimeError("ShardedMemoryIndex capacity exhausted")
        return rows

    @property
    def poisoned(self) -> bool:
        """True once a donated dispatch consumed this index's state and
        then failed (recovery: checkpoint restore + journal replay)."""
        return self._poisoned

    def _guarded(self, call, donated, copying, sole, states, mode):
        """Donation-safe executor (ISSUE 10) — the pod twin of
        ``MemoryIndex._guarded``: copy-twin retries on transient failure,
        typed ``ArenaPoisoned`` when the input was consumed."""
        check_not_poisoned(self._poisoned, "ShardedMemoryIndex")
        try:
            return run_guarded(call, donated, copying, sole, states,
                               telemetry=self.telemetry, mode=mode,
                               retries=self.dispatch_retry_max,
                               backoff_s=self.dispatch_retry_backoff_s)
        except ArenaPoisoned:
            self._poisoned = True
            raise

    def _apply_arena(self, donated, copying, *args, **kwargs) -> None:
        """The zero-copy mutation gate (PR 1 contract): donate when this
        index provably holds the sole reference to the arena pytree,
        otherwise run the copying twin so a concurrent reader's snapshot
        is never invalidated."""
        with self._state_lock:
            cur = self._arena
            sole = sys.getrefcount(cur) <= self._SOLE_REFS
            out = self._guarded(lambda fn: fn(cur, *args, **kwargs),
                                donated, copying, sole, (cur,),
                                "pod_arena")
            del cur
            self.state = out

    # The device-program entry point every serve goes through — tests and
    # bench wrap it to count dispatches (one call == one dispatch). The
    # count ALSO lands in the telemetry registry (ISSUE 6 satellite: it
    # used to be reachable only by wrapping this hook).
    def _dispatch(self, fn, *args, **kwargs):
        self.dispatch_count += 1
        self.telemetry.bump("serve.dispatches", labels={"mode": "pod"})
        return fn(*args, **kwargs)

    # The write-path twin: every device program the ingest path runs —
    # the ONE distributed fused dispatch, or each step of the host-driven
    # classic sequence — goes through here, so bench and the jit-counter
    # tests measure ``dispatches_per_conversation`` by wrapping one hook.
    def _ingest_dispatch(self, fn, *args, **kwargs):
        self.dispatch_count += 1
        self.ingest_dispatch_count += 1
        return fn(*args, **kwargs)

    # ------------------------------------------------------- edge arena
    @property
    def edge_state(self) -> S.EdgeState:
        with self._state_lock:
            return self._edge_state

    @edge_state.setter
    def edge_state(self, s: S.EdgeState) -> None:
        self._edge_state = self._reshard(s)

    def _alloc_edge_slots(self, n: int) -> List[int]:
        if len(self._free_edge_slots) < n:
            raise RuntimeError("ShardedMemoryIndex edge capacity exhausted")
        return [self._free_edge_slots.pop() for _ in range(n)]

    def _apply_edges(self, donated, copying, *args, **kwargs) -> None:
        """Edge-arena twin of ``_apply_arena`` (same donation gate)."""
        with self._state_lock:
            cur = self._edge_state
            sole = sys.getrefcount(cur) <= self._SOLE_REFS
            out = self._guarded(
                lambda fn: self._ingest_dispatch(fn, cur, *args, **kwargs),
                donated, copying, sole, (cur,), "pod_edges")
            del cur
            self.edge_state = out

    def _edges_insert_device(self, triples, tenant_id_val: int,
                             now_rel: float) -> List[Tuple[str, str]]:
        """Insert NEW edges into the device edge arena + host maps (the
        classic write path's edge step, and the fused path's overflow
        retry). Keys already registered are skipped."""
        fresh = [(s, t, w) for s, t, w in triples
                 if (s, t) not in self.edge_slots
                 and s in self.id_to_row and t in self.id_to_row]
        if not fresh:
            return []
        slots = self._alloc_edge_slots(len(fresh))
        ecap = self.edge_capacity
        padded = S.pad_rows(np.asarray(slots, np.int32), ecap)
        b = len(padded)
        src_r = np.full((b,), -1, np.int32)
        tgt_r = np.full((b,), -1, np.int32)
        w_arr = np.zeros((b,), np.float32)
        live = np.zeros((b,), bool)
        made = []
        for i, ((s, t, w), slot) in enumerate(zip(fresh, slots)):
            src_r[i] = self.id_to_row[s]
            tgt_r[i] = self.id_to_row[t]
            w_arr[i] = w
            live[i] = True
            self.edge_slots[(s, t)] = slot
            self.edges[(s, t)] = float(w)
            made.append((s, t))
        self._apply_edges(
            S.edges_add, S.edges_add_copy, jnp.asarray(padded),
            jnp.asarray(src_r), jnp.asarray(tgt_r), jnp.asarray(w_arr),
            jnp.ones((b,), jnp.int32), jnp.float32(now_rel),
            jnp.int32(tenant_id_val), jnp.asarray(live))
        self._csr_dirty = True
        return made

    # --------------------------------------------------- fused pod ingest
    def _ingest_kernels(self, k: int, shard_modes: Tuple[int, ...],
                        with_shadow: bool, with_ivf: bool = False,
                        with_pq: bool = False
                        ) -> S.IngestShardedKernels:
        key = (k, shard_modes, with_shadow, with_ivf, with_pq)
        kern = self._ingest_cache.get(key)
        if kern is None:
            kern = S.make_ingest_fused_sharded(
                self.mesh, self.axis, k=k, shard_modes=shard_modes,
                with_shadow=with_shadow, with_ivf=with_ivf,
                with_pq=with_pq)
            self._ingest_cache.put(key, kern)
            self.telemetry.gauge("kernel.cache_entries",
                                 len(self._ingest_cache),
                                 labels={"surface": "pod_ingest"})
        return kern

    def ingest(self, ids: Sequence[str], embeddings: np.ndarray,
               tenant: str, saliences: Optional[Sequence[float]] = None, *,
               dedup_gate: float = 0.95, chain: bool = False,
               chain_weight: float = 0.5, link_k: int = 3,
               link_gate: float = 0.5, link_scale: float = 0.8,
               shard_modes: Sequence[int] = (0,),
               link_accept_hint: float = 1.0,
               now: Optional[float] = None) -> Dict:
        """The pod WRITE path as ONE distributed dispatch (ISSUE 9): dedup
        probe (shard-local top-1 → all_gather merge), intra-batch resolve,
        owner-chip node scatter, merge touch, link scans, gated edge
        insert with prefix-sum pool compaction, and the incremental int8
        shadow update — the full ``ingest_dedup_fused`` program composed
        with the mesh (``state.make_ingest_fused_sharded``), replacing the
        host-driven multi-op sequence (probe dispatch + resolve + add
        scatter + shadow scatter + link-scan dispatch + edge insert) the
        pre-ISSUE-9 pod write path needed for the same semantics.
        ``ingest_fused=False`` keeps that classic sequence for A/B and
        fallback — same verdicts, many dispatches.

        ``ids`` must be fresh (the consolidation contract — the dedup
        verdict decides merge-vs-insert, so re-adding an existing id goes
        through :meth:`add`). Returns ``{"rows", "created", "merged",
        "links", "chains", "counters"}`` with ``merged`` mapping each
        duplicate fact's id to the id it merged into and ``links`` the
        gate-passing similarity edges the device inserted."""
        n = len(ids)
        out_empty = {"rows": [], "created": [], "merged": {}, "links": [],
                     "chains": [], "counters": {}}
        if n == 0:
            return out_empty
        if self.planner is not None and self.planner.active:
            # admission gate (ISSUE 11): typed rejection BEFORE rows or
            # edge slots are allocated; mega-batch splitting happens at
            # the coalescer drain via ``plan_ingest``
            self.planner.check_feasible(
                self._ingest_geometry(n, link_k), chunkable=False)
        for node_id in ids:
            if node_id in self.id_to_row:
                raise ValueError(f"ingest() requires fresh ids: {node_id!r}")
        if saliences is None:
            saliences = [0.5] * n
        shard_modes = tuple(shard_modes)
        emb_np = np.asarray(embeddings, np.float32).reshape(n, self.dim)
        now_abs = now if now is not None else time.time()
        if not self.ingest_fused:
            return self._ingest_classic(
                ids, emb_np, tenant, saliences, dedup_gate=dedup_gate,
                chain=chain, chain_weight=chain_weight, link_k=link_k,
                link_gate=link_gate, link_scale=link_scale,
                shard_modes=shard_modes, now=now_abs)
        tid = self.tenant_id(tenant)
        rows = self._alloc(tenant, n)
        k_eff = max(1, min(int(link_k), self.capacity))
        n_modes = len(shard_modes)
        pool_need = link_pool_size(n_modes * n * k_eff, link_accept_hint)
        n_chain = n if chain else 0
        slots = self._alloc_edge_slots(n_chain + pool_need)
        chain_slot_list = slots[:n_chain]
        link_pool_list = slots[n_chain:]
        ecap = self.edge_capacity
        padded = S.pad_rows(np.asarray(rows, np.int32), self.capacity)
        b = len(padded)

        def pad(vals, fill=0.0, dt=np.float32):
            out = np.full((b,), fill, dt)
            out[:n] = vals
            return out

        emb_p = np.zeros((b, self.dim), np.float32)
        emb_p[:n] = emb_np
        emb_p[n:, 0] = 1.0      # sentinel rows: unit vector (normalizable)
        gids = pad(([0] * n) if chain else ([-1] * n), -1, np.int32)
        chain_slots = np.full((b,), ecap, np.int32)
        chain_slots[:n_chain] = chain_slot_list
        pool_dev = link_pool_dev(link_pool_list, n_modes * b * k_eff, ecap)
        now_rel = now_abs - self.epoch
        with self._state_lock:
            with_shadow = (
                self.int8_serving and not self._int8_dirty
                and self._int8_shadow is not None
                and self._int8_shadow[0].shape[0] == self.capacity + 1)
            with_ivf = self.ivf_online and self._ivf_dev is not None
            with_pq = (self._pq_pack is not None
                       and self._pq_pack[1].shape[0] == self.capacity + 1)
        kern = self._ingest_kernels(k_eff, shard_modes, with_shadow,
                                    with_ivf, with_pq)
        dev_args = (
            jnp.asarray(padded), jnp.asarray(emb_p),
            jnp.asarray(pad(np.asarray(saliences, np.float32))),
            jnp.full((b,), now_rel, jnp.float32),
            jnp.zeros((b,), jnp.int32),
            jnp.asarray(pad([0] * n, -1, np.int32)),
            jnp.asarray(pad([tid] * n, -1, np.int32)),
            jnp.asarray(pad([False] * n, False, bool)),
            jnp.asarray(gids), jnp.asarray(chain_slots), pool_dev,
            jnp.int32(len(link_pool_list)), jnp.float32(now_rel),
            jnp.int32(tid), jnp.float32(dedup_gate),
            jnp.float32(chain_weight), jnp.float32(link_gate),
            jnp.float32(link_scale), jnp.float32(self.ivf_online_eta))
        self._maybe_record_ingest_hbm(kern, dev_args, with_shadow, b,
                                      with_ivf=with_ivf, with_pq=with_pq)
        tel = self.telemetry
        t0 = time.perf_counter()
        with trace_annotation("lz.ingest.pod_fused"):
            with self._state_lock:
                arena, edges = self._arena, self._edge_state
                shadow = self._int8_shadow if with_shadow else None
                ivf = self._ivf_dev if with_ivf else None
                pq = self._pq_pack if with_pq else None
                sole = (sys.getrefcount(arena) <= self._SOLE_REFS
                        and sys.getrefcount(edges) <= self._SOLE_REFS
                        and (shadow is None
                             or (sys.getrefcount(shadow[0]) <= 2
                                 and sys.getrefcount(shadow[1]) <= 2))
                        and (ivf is None
                             or (sys.getrefcount(ivf[0]) <= 2
                                 and sys.getrefcount(ivf[1]) <= 2
                                 and sys.getrefcount(ivf[2]) <= 2))
                        and (pq is None
                             or (sys.getrefcount(pq[0]) <= 2
                                 and sys.getrefcount(pq[1]) <= 2)))
                state_args = ((arena, edges)
                              + (shadow if shadow is not None else ())
                              + (ivf if ivf is not None else ())
                              + (pq if pq is not None else ()))
                got = self._guarded(
                    lambda fn: self._ingest_dispatch(fn, *state_args,
                                                     *dev_args),
                    kern.ingest, kern.ingest_copy, sole,
                    (arena, edges, shadow, ivf, pq), "pod_ingest")
                new_arena, new_edges, got = got[0], got[1], got[2:]
                if shadow is not None:
                    self._int8_shadow = (got[0], got[1])
                    got = got[2:]
                if ivf is not None:
                    self._ivf_dev = (got[0], got[1], got[2])
                    got = got[3:]
                if pq is not None:
                    self._pq_pack = (got[0], got[1])
                    got = got[2:]
                flat = got[0]
                del arena, edges, shadow, ivf, pq
                self._arena = new_arena
                self._edge_state = new_edges
            host = fetch_packed(*flat)          # the ONE readback
        tel.record("ingest.dispatch_ms", (time.perf_counter() - t0) * 1e3,
                   labels={"kind": "pod_fused"})
        tel.bump("ingest.dispatches", labels={"kind": "pod_fused"})
        return self._ingest_finish_host(
            ids, rows, host, chain_slot_list, link_pool_list,
            shard_modes=shard_modes, k_eff=k_eff, tid=tid,
            chain_weight=chain_weight, link_scale=link_scale,
            now_abs=now_abs, shadow_fresh=with_shadow,
            ivf_fresh=with_ivf)

    def _ingest_finish_host(self, ids, rows, host, chain_slot_list,
                            link_pool_list, *, shard_modes, k_eff, tid,
                            chain_weight, link_scale, now_abs,
                            shadow_fresh, ivf_fresh=False) -> Dict:
        """Host bookkeeping after the ONE fused readback: register
        surviving ids, free duplicate rows, mirror accepted edges into the
        host map, reclaim the untouched pool suffix, retry overflowed
        links (one extra dispatch for that rare batch only)."""
        n = len(ids)
        n_modes = len(shard_modes)
        tel = self.telemetry
        dup = host[0][:n, 0] > 0
        target = host[1][:n, 0]
        chain_src = host[2][:n, 0]
        ctr = host[3 + 3 * n_modes:]
        tel.bump("ingest.dedup_hits", int(dup.sum()))
        tel.bump("ingest.links_accepted", int(ctr[1][0, 0]))
        tel.bump("ingest.pool_slots_used", int(ctr[2][0, 0]))
        live_rows: List[int] = []
        merged: Dict[str, Optional[str]] = {}
        for i in range(n):
            r = rows[i]
            if dup[i]:
                self._free[r // self.part_rows].append(r)
                merged[ids[i]] = self.row_to_id.get(int(target[i]))
            else:
                self.id_to_row[ids[i]] = r
                self.row_to_id[r] = ids[i]
                live_rows.append(r)
        reclaim: List[int] = []
        chains: List[Tuple[str, str]] = []
        for i, slot in enumerate(chain_slot_list):
            src_id = (self.row_to_id.get(int(chain_src[i]))
                      if chain_src[i] >= 0 else None)
            key = (src_id, ids[i]) if src_id and not dup[i] else None
            if key is not None and key not in self.edge_slots:
                self.edge_slots[key] = slot
                self.edges[key] = float(chain_weight)
                chains.append(key)
            else:
                reclaim.append(slot)
        links: List[Tuple[str, str, float]] = []
        overflowed: List[Tuple[str, str, float]] = []
        pool_real = len(link_pool_list)
        consumed = 0
        for mi in range(n_modes):
            sc = host[3 + 3 * mi]
            cd = host[3 + 3 * mi + 1]
            ps = host[3 + 3 * mi + 2]
            for bi in range(n):
                if dup[bi]:
                    continue
                nid = ids[bi]
                for j in range(k_eff):
                    p = int(ps[bi, j])
                    if p < 0:
                        continue            # rejected: no slot consumed
                    s = float(sc[bi, j])
                    cid = (self.row_to_id.get(int(cd[bi, j]))
                           if s > NEG_INF / 2 else None)
                    w = min(1.0, max(0.0, s * link_scale))
                    if p >= pool_real:
                        if cid is not None \
                                and (nid, cid) not in self.edge_slots:
                            overflowed.append((nid, cid, w))
                            links.append((nid, cid, w))
                        continue
                    consumed = max(consumed, p + 1)
                    key = (nid, cid)
                    if cid is not None and key not in self.edge_slots:
                        self.edge_slots[key] = link_pool_list[p]
                        self.edges[key] = w
                        links.append((nid, cid, w))
                    else:
                        reclaim.append(link_pool_list[p])
        # dup facts' accepted positions never exist (valid_q gates them),
        # but their pool PREFIX positions may still have been consumed by
        # earlier live facts — the suffix comes back whole either way
        self._free_edge_slots.extend(link_pool_list[consumed:])
        self._free_edge_slots.extend(reclaim)
        self._csr_dirty = True
        if not shadow_fresh:
            self._int8_dirty = True
        self._emb_gen += 1
        if ivf_fresh:
            # Online IVF (ISSUE 12): in-dispatch member appends — routed
            # immediately; cluster-capacity spills join the exact-scan
            # extras (readback position -1), like link-pool overflow.
            ivf_ctr = ctr[3:]
            pos_w = ivf_ctr[1]
            routed = self._ivf_routed
            spilled = []
            for i in range(n):
                if dup[i]:
                    continue
                r = rows[i]
                if int(pos_w[i, 0]) >= 0:
                    if routed is not None:
                        routed[r] = True
                elif not (routed is not None and routed[r]) \
                        and r not in self._ivf_fresh:
                    spilled.append(r)
            if spilled:
                tel.bump("ivf.member_overflows", len(spilled))
                self._ivf_fresh.extend(spilled)
                self._ivf_tabs_cache = None
            dev = self._ivf_dev
            if dev is not None:
                slots = int(np.prod(dev[1].shape))
                tel.gauge("ivf.member_pool_occupancy",
                          float(ivf_ctr[3][0, 0]) / max(slots, 1))
            tel.bump("ivf.appends", int(ivf_ctr[4][0, 0]))
            tel.bump("ivf.centroid_shift_ppm", int(ivf_ctr[5][0, 0]))
        elif self._ivf is not None and live_rows:
            routed = self._ivf_routed
            for r in live_rows:
                if not routed[r] and r not in self._ivf_fresh:
                    self._ivf_fresh.append(r)
            self._ivf_tabs_cache = None
        if self.tiering is not None and live_rows:
            self.tiering.on_rows_written(live_rows)
        if self._sem_host is not None:
            # dedup-merge touched rows: exactly those slots; accepted new
            # rows: the whole tenant (a fresh fact changes its top-k
            # invisibly to any row-level index)
            self._sem_host.invalidate_rows(
                int(target[i]) for i in range(n) if dup[i])
            if live_rows:
                self._sem_host.invalidate_tenant(tid)
        if overflowed:
            self.link_pool_overflows += 1
            tel.bump("ingest.link_pool_overflows")
            self._edges_insert_device(overflowed, tid, now_abs - self.epoch)
        return {
            "rows": rows,
            "created": [i for i, d in zip(ids, dup) if not d],
            "merged": merged, "links": links, "chains": chains,
            "counters": {"dedup_hits": int(dup.sum()),
                         "links_accepted": int(ctr[1][0, 0]),
                         "pool_slots_used": int(ctr[2][0, 0]),
                         "overflow": bool(ctr[0][0, 0])},
        }

    def _ingest_classic(self, ids, emb_np, tenant, saliences, *, dedup_gate,
                        chain, chain_weight, link_k, link_gate, link_scale,
                        shard_modes, now) -> Dict:
        """The host-driven pod write sequence with the SAME semantics as
        the fused program (the A/B baseline and ``ingest_fused=False``
        fallback): probe dispatch → host dedup resolve → arena add (+
        shadow scatter) → merge touch → one link-scan dispatch per shard
        mode → host gate → edge-insert dispatch. Each device step routes
        through ``_ingest_dispatch``, so the dispatch-count gap vs the
        fused path is measured, not asserted."""
        tid = self.tenant_id(tenant)
        n = len(ids)
        k_eff = max(1, min(int(link_k), self.capacity))
        norms = np.maximum(np.linalg.norm(emb_np, axis=1, keepdims=True),
                           1e-9)
        qn = (emb_np / norms).astype(np.float32)
        st = self.state
        # probe: masked top-1 over the pre-add arena (one dispatch; the
        # mask arithmetic itself is extra eager device work — part of why
        # the host-driven path loses)
        probe_kern = self._ingest_classic_cache.get(("probe", 1))
        if probe_kern is None:
            probe_kern = make_sharded_topk(self.mesh, self.axis, k=1)
            self._ingest_classic_cache.put(("probe", 1), probe_kern)
        mask = st.alive & (st.tenant_id == tid) & ~st.is_super
        p_s, p_r = self._ingest_dispatch(probe_kern, st.emb, mask,
                                         jnp.asarray(qn))
        p_s, p_r = fetch_packed(p_s, p_r)
        p_s, p_r = p_s[:, 0], p_r[:, 0]
        # drop id-less probe hits (the sentinel/stale rows the classic
        # decode path filters) and resolve duplicates on host
        p_ok = np.asarray([self.row_to_id.get(int(r)) is not None
                           for r in p_r])
        p_s = np.where(p_ok, p_s, NEG_INF)
        gram = qn @ qn.T
        dup = np.zeros((n,), bool)
        # a dup's target is either an existing arena ROW (probe hit) or an
        # earlier FACT of this batch (intra hit, chained through that
        # fact's own resolution — rows for live facts exist only after
        # the add below)
        t_row = np.full((n,), -1, np.int64)
        t_fact = np.full((n,), -1, np.int64)
        chain_src_id: List[Optional[str]] = [None] * n
        last_live: Optional[str] = None
        for i in range(n):
            best_s, tr_i, tf_i = float(p_s[i]), int(p_r[i]), -1
            if i > 0:
                j = int(np.argmax(gram[i, :i]))
                if float(gram[i, j]) > best_s:
                    best_s = float(gram[i, j])
                    if dup[j]:              # dup-of-a-dup: same survivor
                        tr_i, tf_i = int(t_row[j]), int(t_fact[j])
                    else:
                        tr_i, tf_i = -1, j
            if best_s > dedup_gate:
                dup[i] = True
                t_row[i], t_fact[i] = tr_i, tf_i
                continue
            if chain and last_live is not None:
                chain_src_id[i] = last_live
            if chain:
                last_live = ids[i]
        live_idx = [i for i in range(n) if not dup[i]]
        live_ids = [ids[i] for i in live_idx]
        rows_all = np.full((n,), -1, np.int64)
        if live_ids:
            got = self.add(live_ids, emb_np[live_idx], tenant,
                           saliences=[saliences[i] for i in live_idx])
            for i, r in zip(live_idx, got):
                rows_all[i] = r
        merged: Dict[str, Optional[str]] = {}
        t_rows, t_sals = [], []
        for i in range(n):
            if dup[i]:
                tgt_id = (ids[int(t_fact[i])] if t_fact[i] >= 0
                          else self.row_to_id.get(int(t_row[i])))
                merged[ids[i]] = tgt_id
                r = self.id_to_row.get(tgt_id) if tgt_id else None
                if r is not None:
                    t_rows.append(int(r))
                    t_sals.append(float(saliences[i]))
        now_rel = now - self.epoch
        if t_rows and self._sem_host is not None:
            # same taxonomy as the fused path: merge targets row-level
            # (add() above already flushed the tenant for the live rows)
            self._sem_host.invalidate_rows(t_rows)
        if t_rows:
            padded = S.pad_rows(np.asarray(t_rows, np.int32), self.capacity)
            sal = np.zeros((len(padded),), np.float32)
            sal[:len(t_sals)] = t_sals
            with self._state_lock:
                cur = self._arena
                sole = sys.getrefcount(cur) <= self._SOLE_REFS
                out = self._guarded(
                    lambda fn: self._ingest_dispatch(
                        fn, cur, jnp.asarray(padded), jnp.asarray(sal),
                        jnp.float32(now_rel)),
                    S.arena_merge_touch, S.arena_merge_touch_copy, sole,
                    (cur,), "pod_arena")
                del cur
                self.state = out
        links: List[Tuple[str, str, float]] = []
        chains: List[Tuple[str, str]] = []
        if live_ids:
            # link scans: one distributed top-k per shard mode over the
            # post-add arena, new rows excluded as candidates
            st = self.state
            excl = jnp.zeros((self.capacity + 1,), bool).at[
                jnp.asarray(rows_all[live_idx].astype(np.int32))].set(True)
            base = (st.alive & (st.tenant_id == tid) & ~st.is_super
                    & ~excl)
            link_kern = self._ingest_classic_cache.get(("link", k_eff))
            if link_kern is None:
                link_kern = make_sharded_topk(self.mesh, self.axis,
                                              k=k_eff)
                self._ingest_classic_cache.put(("link", k_eff), link_kern)
            q_live = jnp.asarray(qn[live_idx])
            seen: set = set()
            for sm in shard_modes:
                # the pod surface writes one shard group (add() stamps
                # shard_id 0), so every mode shares the base mask
                l_s, l_r = self._ingest_dispatch(link_kern, st.emb, base,
                                                 q_live)
                l_s, l_r = fetch_packed(l_s, l_r)
                for li, bi in enumerate(live_idx):
                    nid = ids[bi]
                    for s, r in zip(l_s[li], l_r[li]):
                        cid = (self.row_to_id.get(int(r))
                               if s > NEG_INF / 2 else None)
                        if cid is None or float(s) <= link_gate:
                            continue
                        if (nid, cid) in seen:
                            continue
                        seen.add((nid, cid))
                        links.append((nid, cid,
                                      min(1.0, max(0.0,
                                                   float(s) * link_scale))))
            if chain:
                chains = [(chain_src_id[i], ids[i]) for i in live_idx
                          if chain_src_id[i] is not None]
            triples = ([(s, t, chain_weight) for s, t in chains]
                       + links)
            if triples:
                self._edges_insert_device(triples, tid, now_rel)
        return {
            "rows": [int(r) for r in rows_all],
            "created": live_ids, "merged": merged, "links": links,
            "chains": chains,
            "counters": {"dedup_hits": int(dup.sum()),
                         "links_accepted": len(links),
                         "pool_slots_used": 0, "overflow": False},
        }

    def _ingest_geometry(self, n: int, link_k: int = 3) -> Geometry:
        return Geometry(
            kind="ingest", mode="ingest", batch=max(1, int(n)),
            rows=self.capacity + 1, dim=self.dim,
            k=max(1, int(link_k)),
            dtype_bytes=int(np.dtype(self.dtype).itemsize),
            mesh_parts=self.n_parts, edge_cap=self.edge_capacity,
            link_k=max(1, int(link_k)),
            ivf=1 if (self.ivf_online and self._ivf_dev is not None)
            else 0,
            pq=1 if self._pq_pack is not None else 0,
            replica_groups=self.replica_groups)

    def plan_ingest(self, n: int, link_k: int = 3):
        """Pod twin of ``MemoryIndex.plan_ingest`` (ISSUE 11): admission
        decision for an ``n``-fact distributed ingest mega-batch; raises
        the typed :class:`PlanInfeasible` when no split fits."""
        return self.planner.check_feasible(
            self._ingest_geometry(n, link_k), chunkable=False)

    def _maybe_record_ingest_hbm(self, kern, dev_args, with_shadow: bool,
                                 b: int, with_ivf: bool = False,
                                 with_pq: bool = False) -> None:
        """Opt-in peak-HBM gauge for one pod ingest-kernel geometry
        (AOT lower + ``memory_analysis()`` of the non-donating twin; one
        extra compile, zero extra dispatches) — feeds the
        ``scripts/check_hbm_budget.py`` write-path gate."""
        if not self.telemetry_hbm or not self.telemetry.enabled:
            return    # never consume the once-key while warmup mutes the registry
        key = ("ingest", b, with_shadow, with_ivf, with_pq)
        if key in self._hbm_recorded:
            return
        self._hbm_recorded.add(key)
        try:
            with self._state_lock:
                sh = self._int8_shadow if with_shadow else None
                ivf = self._ivf_dev if with_ivf else None
                pq = self._pq_pack if with_pq else None
                args = ((self._arena, self._edge_state)
                        + ((sh[0], sh[1]) if sh is not None else ())
                        + (ivf if ivf is not None else ())
                        + (pq if pq is not None else ())
                        + dev_args)
            peak = peak_bytes(
                kern.ingest_copy.lower(*args).compile().memory_analysis())
        except Exception:   # noqa: BLE001 — never fail the write path
            return
        if peak is not None:
            labels = {"path": "ingest", "batch": str(b),
                      "rows": str(self.capacity + 1),
                      "mesh": f"{self.n_parts}x{self.axis}"}
            if with_ivf:
                labels["ivf"] = "true"
            if with_pq:
                labels["pq"] = "true"
            if self.replica_groups > 1:
                labels["groups"] = str(self.replica_groups)
            self.telemetry.gauge("kernel.peak_hbm_bytes", peak,
                                 labels=labels)
            self.planner.observe_gauge(self._ingest_geometry(b), peak)

    def warmup_ingest(self, geometries=(256,), *, dedup_gate: float = 0.95,
                      link_k: int = 3) -> Dict[int, float]:
        """Pod twin of ``MemoryIndex.warmup_ingest`` (ISSUE 9 satellite):
        pre-compile the distributed fused ingest program for the given
        fact-batch geometries by driving :meth:`ingest` with a throwaway
        tenant and deleting the rows afterwards — the live corpus is
        unchanged, the jit cache entries live traffic hits are warm. Wall
        time lands in ``kernel.warmup_ms{path="ingest",batch}``."""
        out: Dict[int, float] = {}
        tel = self.telemetry
        rng = np.random.default_rng(0)
        buckets = sorted({len(S.pad_rows(np.zeros((g,), np.int32),
                                         self.capacity))
                          for g in geometries if g > 0})
        for g in buckets:
            if self.planner is not None and self.planner.active:
                # planner compile gate (ISSUE 11): skip geometries the
                # admission path would refuse; warm the planned sub-batch
                try:
                    d = self.plan_ingest(g, link_k=link_k)
                except PlanInfeasible:
                    tel.bump("plan.warmup_skipped",
                             labels={"path": "ingest"})
                    continue
                if d.splits > 1:
                    g = max(1, -(-g // d.splits))
            t0 = time.perf_counter()
            prev = tel.enabled
            tel.enabled = False
            try:
                ids = [f"~warm:{g}:{i}" for i in range(g)]
                got = self.ingest(
                    ids, rng.standard_normal((g, self.dim)), "~warmup",
                    dedup_gate=float(dedup_gate), link_k=link_k)
                self.delete(got["created"])
            finally:
                tel.enabled = prev
            ms = (time.perf_counter() - t0) * 1e3
            tel.record("kernel.warmup_ms", ms,
                       labels={"path": "ingest", "batch": str(g)})
            out[g] = ms
        return out

    # ------------------------------------------------------- tiered memory
    def attach_tiering(self, hot_budget_rows: int, **kw):
        """Attach a :class:`tier.TierManager` with one host ColdStore per
        mesh partition (each chip's demoted rows bucket to its own store).
        Serving switches to the distributed tiered program while any row
        is cold; cold-hit turns finish with the shared bounded rescore
        dispatch (plain jnp under jit — GSPMD partitions it against the
        row-sharded arena)."""
        from lazzaro_tpu.tier import TierManager

        self.tiering = TierManager(self, hot_budget_rows, **kw)
        return self.tiering

    def _flat_csr_for(self):
        """Replicated FLAT CSR over the host edge map for the tiered
        cold-finish kernel (the per-shard split ``_csr_sharded`` builds is
        the wrong layout for the GSPMD-partitioned finish)."""
        import jax.numpy as jnp

        cache = self._csr_flat_cache
        n = self.capacity + 1
        if cache is not None and cache[0] == len(self.edges) \
                and cache[1] == n:
            return cache[2], cache[3]
        indptr, nbr = build_host_csr(list(self.edges.keys()),
                                     self.id_to_row, n)
        dev = (jnp.asarray(indptr), jnp.asarray(nbr))
        self._csr_flat_cache = (len(self.edges), n, dev[0], dev[1])
        return dev

    # ------------------------------------------------------------------- api
    def add(self, ids: Sequence[str], embeddings: np.ndarray, tenant: str,
            saliences: Optional[Sequence[float]] = None,
            supers: Optional[Sequence[bool]] = None) -> List[int]:
        n = len(ids)
        if n == 0:
            return []
        if saliences is None:
            saliences = [0.5] * n
        if supers is None:
            supers = [False] * n
        # Happy path (ISSUE 18 satellite): with live online-IVF tables, an
        # all-fresh add() rides the fused ingest program — same one-dispatch
        # write, and the in-kernel assignment routes the rows into member
        # slots instead of spilling them to the exact-scan extras
        # (``ivf.add_extras_spills`` stops counting here). The gates are
        # pinned so ingest() IS add(): dedup_gate above max cosine so no
        # fact ever merges (every id keeps its own row), link_gate above
        # max cosine so no edge inserts. Re-adds (overwrite in place) and
        # super-node adds keep the classic scatter below — ingest() owns
        # neither semantics.
        if (self.ingest_fused and self.ivf_online
                and self._ivf_dev is not None and not any(supers)
                and all(i not in self.id_to_row for i in ids)):
            self.ingest(ids, embeddings, tenant, saliences,
                        dedup_gate=1.5, link_k=1, link_gate=1.5,
                        link_accept_hint=0.0)
            return [self.id_to_row[i] for i in ids]
        rows = []
        fresh = self._alloc(tenant,
                            sum(1 for i in ids if i not in self.id_to_row))
        fi = 0
        for node_id in ids:
            if node_id in self.id_to_row:
                rows.append(self.id_to_row[node_id])
            else:
                r = fresh[fi]; fi += 1
                self.id_to_row[node_id] = r
                self.row_to_id[r] = node_id
                rows.append(r)

        emb = np.asarray(embeddings, np.float32).reshape(n, self.dim)
        tid = self.tenant_id(tenant)
        rows_np = np.asarray(rows, np.int32)
        padded = S.pad_rows(rows_np, self.capacity)
        b = len(padded)

        def pad(vals, fill=0.0, dt=np.float32):
            out = np.full((b,), fill, dt)
            out[:n] = vals
            return out

        emb_p = np.zeros((b, self.dim), np.float32)
        emb_p[:n] = emb
        emb_dev = jnp.asarray(emb_p)
        self._apply_arena(
            S.arena_add, S.arena_add_copy,
            jnp.asarray(padded), emb_dev,
            jnp.asarray(pad(np.asarray(saliences, np.float32))),
            jnp.full((b,), time.time() - self.epoch, jnp.float32),
            jnp.zeros((b,), jnp.int32),
            jnp.zeros((b,), jnp.int32),
            jnp.asarray(pad(tid, -1, np.int32)),
            jnp.asarray(pad(np.asarray(supers, bool), False, bool)))
        for r, is_sup in zip(rows, supers):
            (self._super_rows.add if is_sup
             else self._super_rows.discard)(r)
        # int8 shadow: incremental scatter when a maintained shadow exists
        # (O(batch)); otherwise it rebuilds lazily at the next serve.
        shadow = self._int8_shadow
        if (self.int8_serving and shadow is not None and not self._int8_dirty
                and shadow[0].shape[0] == self.capacity + 1):
            stored = S.normalize(emb_dev).astype(self.dtype)
            q8, scale = _shadow_update(shadow[0], shadow[1],
                                       jnp.asarray(padded), stored)
            self._int8_shadow = (jax.device_put(q8, self._mat_sh),
                                 jax.device_put(scale, self._row_sh))
        else:
            self._int8_dirty = True
        # PQ codes: patched in place against the frozen codebook — the
        # pack stays COMPLETE through every write path (ISSUE 16).
        pack = self._pq_pack
        if pack is not None and pack[1].shape[0] == self.capacity + 1:
            stored = S.normalize(emb_dev).astype(self.dtype)
            codes = _pq_codes_update(pack[0], pack[1],
                                     jnp.asarray(padded), stored)
            self._pq_pack = (pack[0], jax.device_put(codes, self._mat_sh))
        # IVF freshness: unrouted rows serve exactly from the extras until
        # the next ivf_build folds them into clusters. Spills through this
        # non-fused write surface are counted (ISSUE 16 satellite) so the
        # exact-scan extras burden stays measurable.
        if self._ivf is not None:
            routed = self._ivf_routed
            spilled = 0
            for r in rows:
                if not routed[r] and r not in self._ivf_fresh:
                    self._ivf_fresh.append(r)
                    spilled += 1
            if spilled:
                self.telemetry.bump("ivf.add_extras_spills", spilled)
            self._ivf_tabs_cache = None
        self._emb_gen += 1
        if self.tiering is not None:       # a re-added cold row is hot again
            self.tiering.on_rows_written(rows)
        if self._sem_host is not None:     # new facts change tenant top-k
            self._sem_host.invalidate_tenant(tid)
        return rows

    def delete(self, ids: Sequence[str]) -> None:
        rows = [self.id_to_row.pop(i) for i in ids if i in self.id_to_row]
        if not rows:
            return
        gone = set(ids)
        dead_edges = [key for key in self.edges
                      if key[0] in gone or key[1] in gone]
        for key in dead_edges:
            del self.edges[key]
            slot = self.edge_slots.pop(key, None)
            if slot is not None:      # reclaim the device edge-arena slot
                self._free_edge_slots.append(slot)
        if dead_edges:
            self._csr_dirty = True
        for r in rows:
            self.row_to_id.pop(r, None)
            self._super_rows.discard(r)
            self._free[r // self.part_rows].append(r)
            if self._ivf is not None:
                # un-route freed slots so a re-used row joins the fresh
                # extras (exact) instead of inheriting a stale cluster
                self._ivf_routed[r] = False
                if r in self._ivf_fresh:
                    self._ivf_fresh.remove(r)
        if self._ivf is not None:
            self._ivf_tabs_cache = None
        if self.tiering is not None:       # freed cold rows leave the store
            self.tiering.on_rows_deleted(rows)
        if self._sem_host is not None:
            self._sem_host.invalidate_rows(rows)
        padded = S.pad_rows(np.asarray(rows, np.int32), self.capacity)
        self._apply_arena(S.arena_delete, S.arena_delete_copy,
                          jnp.asarray(padded))

    def add_edges(self, triples: Sequence[Tuple[str, str, float]],
                  tenant: Optional[str] = None) -> None:
        """Register association edges (host bookkeeping + CSR shadow; the
        device side is the per-shard CSR the fused serving program
        gathers). ``tenant`` is accepted for MemoryIndex API parity —
        edge visibility is governed by the endpoint rows' tenant column."""
        changed = False
        for src, tgt, w in triples:
            if src in self.id_to_row and tgt in self.id_to_row:
                self.edges[(src, tgt)] = float(w)
                changed = True
        if changed:
            self._csr_dirty = True

    def set_super(self, ids: Sequence[str], flag: bool = True) -> None:
        """Mark rows as super nodes (the gate tier of the fused program)."""
        rows = [self.id_to_row[i] for i in ids if i in self.id_to_row]
        if not rows:
            return
        for r in rows:
            (self._super_rows.add if flag else self._super_rows.discard)(r)
        padded = S.pad_rows(np.asarray(rows, np.int32), self.capacity)
        b = len(padded)
        flags = np.zeros((b,), bool)
        flags[:len(rows)] = flag
        self._apply_arena(S.arena_set_parentage, S.arena_set_parentage_copy,
                          jnp.asarray(padded), jnp.asarray(flags))
        if self._ivf is not None:
            self._ivf_tabs_cache = None       # extras carry every super row

    def search(self, query: np.ndarray, tenant: str
               ) -> Tuple[List[str], List[float]]:
        """Distributed masked top-k: local per-chip → all_gather → global.
        Single-query view of ``search_batch``."""
        return self.search_batch(np.asarray(query, np.float32)[None, :],
                                 tenant)[0]

    def search_batch(self, queries: np.ndarray, tenant: str
                     ) -> List[Tuple[List[str], List[float]]]:
        """Multi-query distributed top-k: Q queries share one local-score
        matmul per chip and one all_gather — fleet serving over the pod.
        Q is bucketed to a power of two: each distinct query-batch shape
        would otherwise retrace the pod-wide shard_map kernel (multi-second
        compiles are most expensive exactly here)."""
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]
        nq = queries.shape[0]
        tid = self._tenants.get(tenant)
        if tid is None or nq == 0:
            return empty_results(nq)
        norms = np.maximum(np.linalg.norm(queries, axis=1, keepdims=True), 1e-9)
        q = pad_to_pow2(queries / norms)
        st = self.state
        mask = st.alive & (st.tenant_id == tid)
        scores, rows = self._dispatch(self._search, st.emb, mask,
                                      jnp.asarray(q))
        return decode_topk(np.asarray(scores)[:nq], np.asarray(rows)[:nq],
                           self.row_to_id, NEG_INF)

    # --------------------------------------------------- fused pod serving
    def _csr_sharded(self):
        """Per-shard CSR slices of the host edge map (each chip's own
        rows' neighbor lists, global neighbor ids), re-uploaded only after
        an edge-topology change."""
        if self._csr_cache is not None and not self._csr_dirty:
            return self._csr_cache
        self._csr_dirty = False
        indptr, nbr = build_host_csr(list(self.edges.keys()),
                                     self.id_to_row, self.capacity + 1)
        ish, nsh = split_csr(indptr, nbr, self.n_parts)
        self._csr_cache = (jax.device_put(ish, self._stacked),
                           jax.device_put(nsh, self._stacked))
        return self._csr_cache

    def _int8_shadow_for(self):
        """(Re)build the row-sharded int8 shadow from the current master;
        after the first build, ``add()`` maintains it incrementally."""
        with self._state_lock:
            shadow = self._int8_shadow
            if (not self._int8_dirty and shadow is not None
                    and shadow[0].shape[0] == self.capacity + 1):
                return shadow
            from lazzaro_tpu.ops.quant import quantize_rows

            q8, scale = quantize_rows(self._arena.emb)
            shadow = (jax.device_put(q8, self._mat_sh),
                      jax.device_put(scale, self._row_sh))
            self._int8_shadow = shadow
            self._int8_dirty = False
            return shadow

    def ivf_build(self, n_clusters: Optional[int] = None, nprobe: int = 8,
                  iters: int = 8) -> bool:
        """Offline coarse build for the pod path: k-means over the (host-
        gathered) master, then the member/extras tables are split into
        per-shard LOCAL-row tables (``ops.ivf.shard_serve_tables``) so the
        distributed fused kernel's gathers never leave a chip. Returns
        False when the arena is too small to benefit."""
        from lazzaro_tpu.ops.ivf import build_ivf

        st = self.state
        mask = np.asarray(st.alive)
        if int(mask.sum()) < 2 * max(4, nprobe):
            return False
        ivf = build_ivf(st.emb, mask, n_clusters=n_clusters, iters=iters,
                        member_cap_factor=self.ivf_member_cap_factor)
        members = np.asarray(ivf.members)
        residual = np.asarray(ivf.residual)
        routed = np.zeros((self.capacity + 1,), bool)
        m = members.ravel()
        routed[m[(m >= 0) & (m <= self.capacity)]] = True
        r = residual[(residual >= 0) & (residual <= self.capacity)]
        routed[r] = True
        with self._state_lock:
            self._ivf = (jax.device_put(ivf.centroids, self._rep), members,
                         residual, min(int(nprobe), ivf.n_clusters))
            self._ivf_routed = routed
            self._ivf_fresh = []
            self._ivf_tabs_cache = None
            self._publish_online_tables(members)
            self._publish_pq(st, mask)
        if self._sem_host is not None:
            # a (re)build flips the serving mode / coarse routing for
            # every tenant — cached windows may no longer be reproducible
            self._sem_host.invalidate_tenant(None)
        return True

    def _publish_pq(self, st: S.ArenaState, mask_np: np.ndarray) -> None:
        """Train + publish the COMPLETE PQ pack for the pod path (ISSUE
        16): codebook replicated, the full-slab encode row-sharded with
        the master. After this one build the pack is maintained
        incrementally (fused ingest's ``_pq_scatter``, add()'s host
        patch) — there is no dirty flag to clear. Caller holds
        ``_state_lock``."""
        if not self.pq_serving:
            self._pq_pack = None
            return
        from lazzaro_tpu.ops.pq import encode_pq, train_pq

        book = train_pq(st.emb, mask_np)
        codes = encode_pq(book.centroids, st.emb)
        self._pq_pack = (
            jax.device_put(book.centroids, self._rep),
            jax.device_put(codes, self._mat_sh))
        self.telemetry.bump("pq.publishes", labels={"surface": "pod"})

    def _pq_tables(self, k_bucket: int):
        """(book_cent, codes_sh, centroids, members_sh, extras_sh, nprobe)
        device tables for the fused ``mode="pq"`` pod program, or None to
        fall through to the IVF/dense routing (no pack, no coarse build,
        or a stale-capacity slab after growth)."""
        if not self.pq_serving:
            return None
        with self._state_lock:
            pack = self._pq_pack
        if pack is None or pack[1].shape[0] != self.capacity + 1:
            return None
        ivf_tabs = self._ivf_tables(k_bucket)
        if ivf_tabs is None:
            return None
        cent, mem_sh, ext_sh, nprobe = ivf_tabs
        return pack[0], pack[1], cent, mem_sh, ext_sh, nprobe

    def _publish_online_tables(self, members: np.ndarray) -> None:
        """Seed the LIVE pod coarse tables from a build (ISSUE 12): the
        per-shard LOCAL-row member split becomes the array the
        distributed ingest appends through AND the serving kernel
        gathers from; ``counts [n, C]`` is each (shard, cluster) append
        cursor, replicated so the ingest kernel's verdicts stay
        replicated arithmetic. Caller holds ``_state_lock``."""
        if not self.ivf_online or self._ivf is None:
            self._ivf_dev = None
            return
        from lazzaro_tpu.ops.ivf import shard_serve_tables

        cent = self._ivf[0]
        mem_sh, _ = shard_serve_tables(members,
                                       np.zeros((0,), np.int64),
                                       self.n_parts, self.part_rows)
        counts = (mem_sh >= 0).sum(axis=-1).astype(np.int32)
        self._ivf_dev = (
            jax.device_put(jnp.asarray(cent, jnp.float32), self._rep),
            jax.device_put(jnp.asarray(mem_sh), self._stacked),
            jax.device_put(jnp.asarray(counts), self._rep))

    def _ivf_tables(self, k_bucket: int):
        """(centroids, members_sh, extras_sh, nprobe) device tables for the
        fused IVF program, or None to serve dense (no build, or too few
        candidates per shard to fill k). With online maintenance the
        centroid/member tables are the LIVE device arrays the distributed
        ingest maintains (never cached — their identity IS the snapshot);
        only the extras split (sealed residual + overflow/add spills +
        supers) is host-assembled and cached."""
        if self._ivf is None:
            return None
        live = self._ivf_dev if self.ivf_online else None
        cache = self._ivf_tabs_cache
        if cache is not None and cache[0] >= k_bucket:
            ext_sh_dev, nprobe, n_static = cache[1]
            if live is not None:
                n_cand = nprobe * live[1].shape[2] + n_static
                if n_cand < k_bucket + self.coarse_slack:
                    return None
                return live[0], live[1], ext_sh_dev, nprobe
            return cache[2]
        from lazzaro_tpu.ops.ivf import pack_extras, shard_serve_tables

        cent, members, residual, nprobe = self._ivf
        extras = pack_extras(residual, self._ivf_fresh,
                             sorted(self._super_rows))
        n_cand = nprobe * members.shape[1] + extras.shape[0]
        if n_cand < k_bucket + self.coarse_slack:
            return None
        mem_sh, ext_sh = shard_serve_tables(members, extras, self.n_parts,
                                            self.part_rows)
        ext_sh_dev = jax.device_put(ext_sh, self._stacked)
        if live is not None:
            tabs = (live[0], live[1], ext_sh_dev, nprobe)
        else:
            tabs = (cent, jax.device_put(mem_sh, self._stacked),
                    ext_sh_dev, nprobe)
        self._ivf_tabs_cache = (k_bucket,
                                (ext_sh_dev, nprobe, extras.shape[0]),
                                tabs)
        return tabs

    def _fused_kernels(self, mode: str, k_bucket: int, nprobe: int,
                       ragged: bool = False, scan_chunk: int = 0,
                       sem: bool = False) -> S.FusedShardedKernels:
        # With ragged kernels k_bucket/nprobe are the fixed per-mode
        # ceilings, so the cache key collapses to one entry per mode.
        # A planner scan_chunk override keys separately: same ONE
        # dispatch, smaller in-kernel score tile (ISSUE 17 satellite —
        # the pod path chunks the scan instead of splitting batches).
        key = ((mode, "ragged", k_bucket, nprobe) if ragged
               else (mode, k_bucket, nprobe))
        if scan_chunk:
            key = key + ("chunk", scan_chunk)
        if sem:
            key = key + ("sem",)
        kern = self._fused_cache.get(key)
        if kern is None:
            kern = S.make_fused_sharded(
                self.mesh, self.axis, k=k_bucket,
                cap_take=min(self.cap_take, k_bucket), max_nbr=self.max_nbr,
                mode=mode, slack=self.coarse_slack, nprobe=nprobe,
                ragged=ragged, scan_chunk=scan_chunk, sem=sem)
            self._fused_cache.put(key, kern)
            self.telemetry.gauge("kernel.cache_entries",
                                 len(self._fused_cache),
                                 labels={"surface": "pod_fused"})
        return kern

    def _serve_mode_hint(self, reqs) -> Tuple[str, int]:
        """Cheap (mode, k-ceiling) prediction of the pod dispatch's
        routing — the planner's geometry key (mirror of
        ``MemoryIndex._serve_mode_hint``)."""
        ragged = self.serve_ragged and self.serve_fused
        if ragged:
            k_bucket = int(min(max(self.serve_k_max, self.cap_take, 1),
                               self.capacity))
        else:
            k_req = max((min(int(r.k), self.capacity) for r in reqs),
                        default=1)
            k_bucket = min(max(next_pow2(max(self.cap_take, k_req, 1)), 1),
                           self.capacity)
        tm = self.tiering
        if tm is not None and tm.cold_count > 0:
            return "sharded_tiered", k_bucket
        if self._ivf is not None and self.serve_fused:
            if self.pq_serving and self._pq_pack is not None:
                return "sharded_pq", k_bucket
            return "sharded_ivf", k_bucket
        if self.int8_serving:
            return "sharded_quant", k_bucket
        return "sharded_exact", k_bucket

    def _serve_geometry(self, nq: int, mode: str, k_bucket: int) -> Geometry:
        ragged = self.serve_ragged and self.serve_fused
        pad_n = (bucket_size(nq, self.serve_pad_granularity) if ragged
                 else next_pow2(nq))
        return Geometry(
            kind="serve", mode=mode, batch=pad_n, rows=self.capacity + 1,
            dim=self.dim, k=k_bucket,
            dtype_bytes=int(np.dtype(self.dtype).itemsize),
            mesh_parts=self.n_parts, edge_cap=self.edge_capacity,
            nprobe=int(self._ivf[3] if self._ivf is not None else 0),
            replica_groups=self.replica_groups,
            sem_slots=(self._sem_host.slots if self._sem_host is not None
                       else 0),
            sem_width=(self._sem_host.width if self._sem_host is not None
                       else 0))

    def serve_requests(self, reqs) -> List:
        """Memory-safe entry point of the pod serving path (ISSUE 11):
        the distributed geometry is ADMITTED against the HBM planner
        before anything compiles — fused single distributed dispatch when
        the prediction fits, PLANNED sub-dispatches riding the linear pad
        buckets when it doesn't, typed :class:`PlanInfeasible` when no
        split fits; a runtime ``RESOURCE_EXHAUSTED`` gets ONE replan
        through the copy twins. Planner disabled (default) = zero-overhead
        passthrough. See :meth:`_serve_requests_once` for the dispatch."""
        nq = len(reqs)
        planner = self.planner
        if (nq == 0 or planner is None or not planner.active
                or not self.id_to_row):
            try:
                return self._serve_requests_once(reqs)
            except DeviceOom:
                raise
            except Exception as e:  # noqa: BLE001 — typed OOM, uniform
                if not is_resource_exhausted(e):
                    raise
                self.telemetry.bump("reliability.oom",
                                    labels={"mode": "serve_pod"})
                raise DeviceOom(
                    f"pod serving dispatch exhausted device memory and "
                    f"no planner budget is configured to replan it: {e}"
                ) from e
        check_not_poisoned(self._poisoned)
        mode, k_bucket = self._serve_mode_hint(reqs)
        geom = self._serve_geometry(nq, mode, k_bucket)
        # chunkable: an over-budget pod geometry first shrinks the
        # in-kernel scan tile (STILL one distributed dispatch) and only
        # then splits the batch (ISSUE 17 satellite — previously the pod
        # path could only split).
        decision = planner.check_feasible(geom, chunkable=True)
        return self._serve_planned(reqs, geom, decision, replanned=False)

    def _serve_planned(self, reqs, geom, decision,
                       replanned: bool) -> List:
        tel = self.telemetry
        n = len(reqs)
        splits = max(1, min(decision.splits, n))
        per = -(-n // splits)
        groups = [reqs[i:i + per] for i in range(0, n, per)]
        if len(groups) > 1:
            tel.bump("plan.planned_turns", labels={"path": "serve"})
            tel.bump("plan.split_dispatches", len(groups),
                     labels={"path": "serve"})
        if decision.scan_chunk:
            tel.bump("plan.scan_chunked_turns", labels={"path": "serve"})
        out: List = []
        done = 0
        try:
            for g in groups:
                out.extend(self._serve_requests_once(
                    g, force_copy=replanned,
                    scan_chunk=decision.scan_chunk))
                done += len(g)
        except Exception as e:      # noqa: BLE001 — OOM-only replan below
            if not is_resource_exhausted(e):
                raise
            if replanned:
                tel.bump("plan.infeasible", labels={"path": "serve"})
                raise PlanInfeasible(
                    f"replanned pod dispatch still exhausted device "
                    f"memory (mode={geom.mode}, batch={geom.batch}): "
                    f"{e}") from e
            self.planner.note_oom(geom)
            harder = self.planner.replan_after_oom(geom, decision,
                                                   chunkable=True)
            if harder is None:
                tel.bump("plan.infeasible", labels={"path": "serve"})
                raise PlanInfeasible(
                    f"pod dispatch exhausted device memory and no harder "
                    f"split fits (mode={geom.mode}, batch={geom.batch})"
                ) from e
            tel.bump("plan.oom_replans", labels={"path": "serve"})
            out.extend(self._serve_planned(reqs[done:], geom, harder,
                                           replanned=True))
        return out

    def _serve_requests_once(self, reqs, force_copy: bool = False,
                             scan_chunk: int = 0) -> List:
        """``serve.QueryScheduler`` executor for the pod-sharded path: one
        coalesced batch of :class:`serve.RetrievalRequest`s becomes ONE
        distributed dispatch + ONE packed readback running the FULL
        chat-turn program — super gate, ANN top-k, CSR neighbor gather,
        shard-local boost scatters — for the whole mixed-tenant batch
        (per-query tenant column; queries with an unknown tenant match
        nothing). The kernel is keyed on the batch max-k (pow2-bucketed),
        so ``k`` above the construction-time default retraces once per
        bucket instead of silently truncating. ``serve_fused=False`` keeps
        the classic gate-less multitenant top-k (A/B + fallback)."""
        from lazzaro_tpu.serve.scheduler import RetrievalResult

        results = [RetrievalResult() for _ in reqs]
        nq = len(reqs)
        if nq == 0 or not self.id_to_row:
            return results
        dim = self.dim
        ragged = self.serve_ragged and self.serve_fused
        cap_s = self.cap_take
        if ragged:
            # static per-mode k ceiling: the kernel key never depends on
            # the batch's k mix (ISSUE 7)
            k_bucket = int(min(max(self.serve_k_max, cap_s, 1),
                               self.capacity))
            cap_s = min(self.cap_take, k_bucket)
        q = np.zeros((nq, dim), np.float32)
        valid = np.zeros((nq,), bool)
        tids = np.full((nq,), -1, np.int32)
        gate_on = np.zeros((nq,), bool)
        boost_on = np.zeros((nq,), bool)
        k_arr = np.zeros((nq,), np.int32)
        cap_arr = np.zeros((nq,), np.int32)
        for i, r in enumerate(reqs):
            v = np.asarray(r.query, np.float32).reshape(-1)
            tid = self._tenants.get(r.tenant)
            if v.size != dim or tid is None:
                continue                    # tenant -1 matches no rows
            q[i] = v
            valid[i] = True
            tids[i] = tid
            gate_on[i] = bool(getattr(r, "gate_enabled", False))
            boost_on[i] = bool(getattr(r, "boost", False))
            if ragged:
                k_arr[i] = min(max(int(r.k), cap_s, 1), k_bucket)
                rc = getattr(r, "cap_take", None)
                cap_arr[i] = min(int(rc) if rc else cap_s, cap_s)
        if not valid.any():
            return results
        if not ragged:
            k_req = max((min(int(r.k), self.capacity)
                         for i, r in enumerate(reqs) if valid[i]),
                        default=1)
            k_eff = max(self.cap_take, k_req, 1)
            k_bucket = min(max(next_pow2(k_eff), 1), self.capacity)
        # Ragged batches bucket LINEARLY (granularity slots of worst-case
        # padding) instead of to the next power of two (~50% worst case —
        # the pow2 padding tax this PR kills).
        qp = (pad_to_bucket(q, self.serve_pad_granularity) if ragged
              else pad_to_pow2(q))
        pad_n = qp.shape[0]
        tel = self.telemetry
        # Coalesce/pad inflation: padded kernel slots vs live requests,
        # kernel k (max-k bucket, or the ragged ceiling).
        tel.bump("serve.live_requests", nq)
        tel.bump("serve.padded_slots", pad_n)
        tel.gauge("serve.batch_occupancy", nq / pad_n)
        tel.record("serve.k_bucket", k_bucket)
        if ragged:
            for kv in k_arr[valid]:
                tel.record("serve.k_request", float(kv))

        def padb(arr, fill=False, dt=bool):
            out = np.full((pad_n,), fill, dt)
            out[:nq] = arr
            return out

        if not self.serve_fused:
            return self._serve_classic(reqs, results, valid, qp, tids,
                                       k_bucket)

        tm = self.tiering
        tiered = tm is not None and tm.cold_count > 0
        pq_tabs = None if tiered else self._pq_tables(k_bucket)
        ivf_tabs = (None if tiered or pq_tabs is not None
                    else self._ivf_tables(k_bucket))
        use_quant = self.int8_serving
        if tiered:
            # full-corpus int8 coarse scan + tier-aware rescore: the only
            # structure that still covers demoted rows (ISSUE 8)
            nprobe = 0
            mode = "tiered"
            ivf_tabs = None
            tables = (*self._int8_shadow_for(), tm.cold_mask_dev())
        elif pq_tabs is not None:
            # m-byte ADC coarse over the shared IVF candidate assembly +
            # exact rescore — the smallest-resident pod mode (ISSUE 16)
            book_cent, codes_sh, cent, mem_sh, ext_sh, nprobe = pq_tabs
            mode = "pq"
            ivf_tabs = pq_tabs       # nprobe sidecar routing below
            tables = (book_cent, codes_sh, cent, mem_sh, ext_sh)
        elif ivf_tabs is not None:
            cent, mem_sh, ext_sh, nprobe = ivf_tabs
            mode = "ivf_quant" if use_quant else "ivf"
            tables = ((*self._int8_shadow_for(), cent, mem_sh, ext_sh)
                      if use_quant else (cent, mem_sh, ext_sh))
        else:
            nprobe = 0
            mode = "quant" if use_quant else "exact"
            tables = self._int8_shadow_for() if use_quant else ()
        # Semantic query cache (ISSUE 20): the replicated ring rides the
        # SAME distributed dispatch. Tiered pods cache the k+slack
        # candidate window, so their guard adds the slack.
        semh = self._sem_host
        sem_state = None
        if semh is not None and mode in S.SEM_MODE_IDS:
            win = k_bucket + (self.coarse_slack if tiered else 0)
            if win <= semh.width:
                sem_state = semh.tuple_for(mode)
        sem_tail = () if sem_state is None else (sem_state,)
        kern = self._fused_kernels(mode, k_bucket, nprobe, ragged=ragged,
                                   scan_chunk=scan_chunk,
                                   sem=sem_state is not None)
        csr_i, csr_n = self._csr_sharded()
        args = (tables, csr_i, csr_n, jnp.asarray(qp),
                jnp.asarray(padb(valid)),
                jnp.asarray(padb(tids, -1, np.int32)),
                jnp.asarray(padb(gate_on)))
        if ragged:
            # per-query sidecar columns (replicated over the mesh): k,
            # retrieval cap, and — for the IVF modes — probe width
            k_dev = jnp.asarray(padb(k_arr, 0, np.int32))
            capq_dev = jnp.asarray(padb(cap_arr, 0, np.int32))
            if ivf_tabs is not None:
                np_arr = np.zeros((nq,), np.int32)
                for i, r in enumerate(reqs):
                    rn = getattr(r, "nprobe", None)
                    np_arr[i] = (min(max(int(rn), 1), nprobe) if rn
                                 else nprobe)
                np_arr[~valid] = 0
            else:
                np_arr = np.zeros((nq,), np.int32)
            npq_dev = jnp.asarray(padb(np_arr, 0, np.int32))
            read_extra = (k_dev, npq_dev, jnp.float32(self.super_gate))
        else:
            read_extra = (jnp.float32(self.super_gate),)
        self._maybe_record_hbm(mode, kern, args, k_bucket,
                               read_extra=read_extra + sem_tail,
                               ragged=ragged)
        # Fault point "plan.oom" (ISSUE 11): an HBM allocation failure the
        # admission plan missed; serve_requests answers with one replan.
        faults.fire("plan.oom", mode=f"pod_{mode}", batch=pad_n)
        t0 = time.perf_counter()
        with trace_annotation(f"lz.serve.pod_{mode}"):
            if boost_on.any():
                now_rel = time.time() - self.epoch
                with self._state_lock:
                    cur = self._arena
                    sole = (not force_copy
                            and sys.getrefcount(cur) <= self._SOLE_REFS)
                    boost_extra = ((jnp.asarray(padb(boost_on)), k_dev,
                                    capq_dev, npq_dev) if ragged
                                   else (jnp.asarray(padb(boost_on)),))
                    out = self._guarded(
                        lambda fn: self._dispatch(
                            fn, cur, *args, *boost_extra,
                            jnp.float32(now_rel),
                            jnp.float32(self.super_gate),
                            jnp.float32(self.acc_boost),
                            jnp.float32(self.nbr_boost), *sem_tail),
                        kern.serve, kern.serve_copy, sole, (cur,),
                        "serve_pod")
                    if sem_state is not None:
                        new_state, sem_ring2, packed = out
                    else:
                        new_state, packed = out
                    del cur
                    self.state = new_state
            else:
                out = self._dispatch(kern.read, self.state, *args,
                                     *read_extra, *sem_tail)
                if sem_state is not None:
                    sem_ring2, packed = out
                else:
                    packed = out
            host = np.asarray(packed)          # the ONE readback
        tel.record("serve.dispatch_ms", (time.perf_counter() - t0) * 1e3,
                   labels={"mode": f"pod_{mode}"})
        if tiered:
            from lazzaro_tpu.tier.serve import tiered_decode_and_finish
            if sem_state is not None:
                k_unpack = (host.shape[1] - 8) // 2
                g_s, g_r, a_s, a_r, _, ctr = unpack_retrieval(host[:nq],
                                                              k_unpack)
                semh.note_readback(sem_ring2, ctr[:, 4], valid, tids,
                                   g_s, g_r, a_s, a_r)
            with tel.span("serve.decode_ms"):
                return tiered_decode_and_finish(
                    self, tm, reqs, results, valid, boost_on, q, tids,
                    host, k_bucket=k_bucket, cap_take=cap_s,
                    max_nbr=self.max_nbr, acc_boost=self.acc_boost,
                    nbr_boost=self.nbr_boost,
                    now_rel=time.time() - self.epoch, ragged=ragged,
                    cap_arr=(cap_arr if ragged else None), tel=tel)
        with tel.span("serve.decode_ms"):
            gate_s, gate_r, ann_s, ann_r, fast, counters = unpack_retrieval(
                host[:nq], k_bucket)
            for i, r in enumerate(reqs):
                if not valid[i]:
                    continue
                res = results[i]
                ids, scores = decode_topk(
                    ann_s[i:i + 1], ann_r[i:i + 1], self.row_to_id,
                    NEG_INF, limit=min(int(r.k), self.capacity),
                    lengths=(counters[i:i + 1, 0] if ragged else None))[0]
                res.ids, res.scores = ids, scores
                if gate_s[i] > NEG_INF / 2:
                    res.gate_id = self.row_to_id.get(int(gate_r[i]))
                    res.gate_score = float(gate_s[i])
                res.fast = bool(fast[i])
                res.boosted = bool(boost_on[i] and not fast[i])
        if sem_state is not None:
            semh.note_readback(sem_ring2, counters[:, 4], valid, tids,
                               gate_s, gate_r, ann_s, ann_r)
        record_device_counters(
            tel, counters, fast, gate_on, valid,
            np.asarray([min(int(r.k), self.capacity) for r in reqs]),
            sem_active=sem_state is not None)
        return results

    def _maybe_record_hbm(self, mode: str, kern, args, k_bucket,
                          read_extra=None, ragged: bool = False) -> None:
        """Opt-in peak-HBM gauge for one pod serving geometry (AOT lower +
        ``memory_analysis()`` of the read twin; one extra compile, zero
        extra dispatches)."""
        if not self.telemetry_hbm or not self.telemetry.enabled:
            return    # never consume the once-key while warmup mutes the registry
        key = (mode, k_bucket, ragged)
        if key in self._hbm_recorded:
            return
        self._hbm_recorded.add(key)
        if read_extra is None:
            read_extra = (jnp.float32(self.super_gate),)
        try:
            peak = peak_bytes(kern.read.lower(
                self.state, *args, *read_extra
            ).compile().memory_analysis())
        except Exception:   # noqa: BLE001 — never fail the serve
            return
        if peak is not None:
            labels = {"mode": f"pod_{mode}", "k": str(k_bucket),
                      "rows": str(self.capacity + 1),
                      "batch": str(int(args[3].shape[0])),
                      "mesh": f"{self.n_parts}x{self.axis}"}
            if mode == "pq":
                labels["pq"] = "true"
            if self.replica_groups > 1:
                labels["groups"] = str(self.replica_groups)
            # the sem operand is the one TUPLE in the read tail (the
            # base extras are device scalars/arrays)
            sem_on = (self._sem_host is not None and bool(read_extra)
                      and isinstance(read_extra[-1], tuple))
            if sem_on:
                # ring geometry for check_hbm_budget.py's semantic-cache
                # sweep (ISSUE 20): resident ring + [batch, slots] probe
                labels["sem_slots"] = str(self._sem_host.slots)
                labels["sem_width"] = str(self._sem_host.width)
            self.telemetry.gauge("kernel.peak_hbm_bytes", peak,
                                 labels=labels)
            self.planner.observe_gauge(
                Geometry(kind="serve", mode=f"pod_{mode}",
                         batch=int(args[3].shape[0]),
                         rows=self.capacity + 1, dim=self.dim,
                         k=int(k_bucket),
                         dtype_bytes=int(np.dtype(self.dtype).itemsize),
                         mesh_parts=self.n_parts,
                         edge_cap=self.edge_capacity,
                         replica_groups=self.replica_groups,
                         sem_slots=(self._sem_host.slots if sem_on else 0),
                         sem_width=(self._sem_host.width if sem_on
                                    else 0)),
                peak)

    def warmup_serving(self, geometries=(8, 64),
                       k: Optional[int] = None) -> Dict[tuple, float]:
        """Pod twin of ``MemoryIndex.warmup_serving`` (ISSUE 7 satellite):
        pre-compile the distributed fused serving program for the given
        query-batch geometries by driving ``serve_requests`` with a
        synthetic tenant that owns no rows — a numeric no-op on the arena
        that populates exactly the jit cache entries live traffic hits.
        Telemetry counters are suppressed while warming; wall time lands
        in ``kernel.warmup_ms{mode,batch}``."""
        from lazzaro_tpu.serve.scheduler import RetrievalRequest

        out: Dict[tuple, float] = {}
        if not self.id_to_row:
            return out
        tel = self.telemetry
        mode = ("quant" if self.int8_serving else "exact")
        if self._ivf is not None:
            mode = "ivf_quant" if self.int8_serving else "ivf"
        self._tenants.setdefault("~warmup", -2)   # matches no arena row
        kk = int(k if k is not None else self.serve_k_max)
        buckets = sorted({
            (bucket_size(g, self.serve_pad_granularity)
             if (self.serve_ragged and self.serve_fused) else next_pow2(g))
            for g in geometries if g > 0})
        for g in buckets:
            zero_q = np.zeros((self.dim,), np.float32)
            t0 = time.perf_counter()
            prev = tel.enabled
            tel.enabled = False
            try:
                # routed through the planner-gated entry (ISSUE 11): a
                # planned-split geometry warms its sub-dispatch kernels,
                # an infeasible one is skipped typed
                self.serve_requests(
                    [RetrievalRequest(query=zero_q, tenant="~warmup", k=kk,
                                      gate_enabled=True, boost=(i == 0))
                     for i in range(g)])
                self.serve_requests(
                    [RetrievalRequest(query=zero_q, tenant="~warmup", k=kk,
                                      gate_enabled=True)
                     for i in range(g)])
            except PlanInfeasible:
                tel.enabled = prev
                tel.bump("plan.warmup_skipped", labels={"path": "serve"})
                continue
            finally:
                tel.enabled = prev
            ms = (time.perf_counter() - t0) * 1e3
            tel.record("kernel.warmup_ms", ms,
                       labels={"mode": f"pod_{mode}", "batch": str(g)})
            out[(f"pod_{mode}", g)] = ms
        return out

    def _serve_classic(self, reqs, results, valid, qp, tids, k_bucket):
        """The pre-ISSUE-5 pod path, kept for A/B and fallback: ONE
        distributed multitenant top-k per batch — correct ids and scores,
        but no gate verdict, no neighbor gather, no boosts (``fast`` and
        ``boosted`` stay False; the orchestrator's classic host path pays
        any boosts)."""
        from lazzaro_tpu.ops.topk import make_sharded_multitenant_topk

        kern = self._serve_search_cache.get(k_bucket)
        if kern is None:
            kern = make_sharded_multitenant_topk(self.mesh, self.axis,
                                                 k=k_bucket)
            self._serve_search_cache.put(k_bucket, kern)
        norms = np.maximum(np.linalg.norm(qp, axis=1, keepdims=True), 1e-9)
        tp = np.full((qp.shape[0],), -1, np.int32)
        tp[:len(tids)] = tids
        st = self.state
        scores, rows = self._dispatch(kern, st.emb, st.alive, st.tenant_id,
                                      jnp.asarray(qp / norms),
                                      jnp.asarray(tp))
        nq = len(reqs)
        decoded = decode_topk(np.asarray(scores)[:nq], np.asarray(rows)[:nq],
                              self.row_to_id, NEG_INF)
        for i, (ids, sc) in enumerate(decoded):
            if not valid[i]:
                continue
            k = min(int(reqs[i].k), self.capacity)
            results[i].ids = ids[:k]
            results[i].scores = sc[:k]
        return results

    def decay(self, tenant: str, rate: float, floor: float = 0.2) -> None:
        tid = self._tenants.get(tenant)
        if tid is None:
            return
        self._apply_arena(S.arena_decay, S.arena_decay_copy,
                          jnp.int32(tid), jnp.float32(rate),
                          jnp.float32(floor))

    def partition_of(self, node_id: str) -> Optional[int]:
        row = self.id_to_row.get(node_id)
        return None if row is None else row // self.part_rows
