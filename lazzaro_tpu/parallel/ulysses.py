"""Ulysses (DeepSpeed-style) sequence parallelism via all-to-all.

The second canonical long-context scheme, complementing ring attention
(``parallel/ring_attention.py``): instead of streaming K/V around a device
ring (n ``ppermute`` hops, O(n) latency), one ``all_to_all`` re-shards the
activations from sequence-sharded to head-sharded, each device computes FULL
dense attention over the whole sequence for its subset of heads, and a second
``all_to_all`` restores sequence sharding. Two collectives total, so it wins
when heads ≥ devices and the sequence fits per-device HBM after the swap;
ring wins at extreme lengths where the full sequence never fits. The
reference has no model execution at all (SURVEY §2.3) — both schemes are
TPU-native capabilities of the in-tree LM stack.
"""

from __future__ import annotations

import jax
from lazzaro_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def make_ulysses_attention(mesh: Mesh, axis: str = "sp"):
    """Returns ``attn(q, k, v) -> out`` for q/k/v [B, T, H, D] sharded along
    T over ``axis`` (same contract as ``make_ring_attention``). Causal.

    Requires H % n_devices == 0: the all-to-all scatters heads across the
    axis while gathering the sequence.
    """
    n = mesh.shape[axis]

    def local_fn(q, k, v):
        B, Tc, H, D = q.shape          # local chunk: T/n positions, all H heads
        if H % n:
            raise ValueError(f"ulysses needs heads ({H}) divisible by mesh "
                             f"axis '{axis}' ({n}); use ring attention")
        if k.shape[2] != H or v.shape[2] != H:
            raise ValueError("ulysses requires full MHA (kv heads == q heads);"
                             " repeat GQA kv heads first or use ring attention")

        def seq_to_heads(x):
            # [B, Tc, H, D] seq-sharded → [B, n·Tc, H/n, D] head-sharded.
            # split_axis=2 scatters heads over the axis; concat_axis=1
            # gathers the full sequence. tiled=True keeps pure reshape
            # semantics (no added major axis).
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        def heads_to_seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
        # Full-sequence dense causal attention on the head shard — the same
        # oracle formulation ring attention is verified against.
        from lazzaro_tpu.parallel.ring_attention import reference_causal_attention
        return heads_to_seq(reference_causal_attention(qg, kg, vg))

    mapped = shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(None, axis, None, None),) * 3,
        out_specs=P(None, axis, None, None),
        check_vma=False,
    )
    return jax.jit(mapped)
