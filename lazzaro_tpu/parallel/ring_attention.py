"""Ring attention: sequence-parallel causal attention over a device ring.

Long-context support the reference cannot have (it never runs a model; its
"long context" strategy is the memory system itself — SURVEY §5). For the
in-tree decoder LM, sequences are sharded along time over a mesh axis; each
device holds a Q/K/V chunk, computes flash-style streaming-softmax block
attention against the K/V chunk it currently holds, and passes K/V around the
ring with ``ppermute`` — n_devices steps, each overlapping compute with an
ICI hop. Memory per chip is O(T/n · d) instead of O(T · d).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from lazzaro_tpu.utils.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG = -1e30


def _block_attn(q, k, v, q_pos, k_pos, m, l, acc, scale):
    """One streaming-softmax accumulation step.

    q [B,Tq,H,D], k/v [B,Tk,Hkv,D] with H %% Hkv == 0 (the GQA repeat is
    done HERE, per block, so ring hops move only Hkv heads), *_pos
    [Tq]/[Tk] global positions, m/l [B,H,Tq] running max / denominator,
    acc [B,H,Tq,D]."""
    H, Hkv = q.shape[2], k.shape[2]
    if H != Hkv:
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    mask = (k_pos[None, :] <= q_pos[:, None])[None, None, :, :]  # causal
    scores = jnp.where(mask, scores, NEG)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    p = jnp.where(mask, p, 0.0)                                 # kill dead blocks
    l_new = l * corr + p.sum(axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def make_ring_attention(mesh: Mesh, axis: str = "sp",
                        batch_axis: str | None = None):
    """Returns ``attn(q, k, v) -> out`` where q/k/v are [B, T, H, D] sharded
    along T over ``axis`` (and along B over ``batch_axis`` when given, so the
    ring composes with data parallelism inside one mesh); output has the same
    sharding. Causal; assumes global positions 0..T-1 in contiguous blocks
    (GSPMD's block partitioning of the T dim)."""
    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def local_fn(q, k, v):
        B, Tc, H, D = q.shape
        scale = 1.0 / np.sqrt(D)
        i = jax.lax.axis_index(axis)
        q_pos = i * Tc + jnp.arange(Tc)

        m0 = jnp.full((B, H, Tc), NEG, jnp.float32)
        l0 = jnp.zeros((B, H, Tc), jnp.float32)
        acc0 = jnp.zeros((B, H, Tc, D), jnp.float32)

        def step(s, carry):
            m, l, acc, k_cur, v_cur = carry
            # after s hops, we hold the chunk originally on device (i - s) mod n
            j = (i - s) % n
            k_pos = j * Tc + jnp.arange(Tc)
            m, l, acc = _block_attn(q, k_cur, v_cur, q_pos, k_pos, m, l, acc, scale)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return m, l, acc, k_nxt, v_nxt

        m, l, acc, _, _ = jax.lax.fori_loop(0, n, step, (m0, l0, acc0, k, v))
        out = acc / jnp.maximum(l[..., None], 1e-30)
        return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)

    mapped = shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(batch_axis, axis, None, None),) * 3,
        out_specs=P(batch_axis, axis, None, None),
        check_vma=False,
    )
    return jax.jit(mapped)


def reference_causal_attention(q, k, v) -> jax.Array:
    """Dense single-device causal attention (correctness oracle)."""
    B, T, H, D = q.shape
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(D)
    mask = jnp.tril(jnp.ones((T, T), bool))
    scores = jnp.where(mask[None, None], scores, NEG)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
