"""Replica-group serving: QPS that scales with chip count (ISSUE 18).

The row-sharded pod index turns chips into CAPACITY: every fused serving
dispatch scans every chip and pays the all_gather merge, so an 8-chip
fleet serves ONE mega-batch at a time and aggregate QPS is flat in chip
count (PR 5's 4-way rig measures 47.2 QPS vs 65.1 single-chip — the
merge + dispatch overhead eats the fan-out on small corpora). The north
star is read-dominated traffic from millions of users; for that,
Pancake's placement (PAPERS.md) is the right shape: replicate the shared
hot tier across serving groups, partition the per-agent overlays.

``ReplicaPlacement`` partitions the fleet into ``n_groups`` contiguous
group-local sub-meshes (``parallel.mesh.replica_group_meshes``), each
holding a FULL :class:`~lazzaro_tpu.parallel.index.ShardedMemoryIndex` —
master emb, int8 shadow, live IVF/PQ tables, edge CSR — row-sharded over
its own ``chips/n_groups`` devices. Every serving kernel compiles per
group against the group's sub-mesh, so the shard-local two-tier cores
and the ``sharded_topk_merge`` combine reuse UNCHANGED: the merge
collective narrows to the group axis automatically. Each routed turn is
still exactly ONE distributed dispatch + ONE packed readback — but a
turn now pays the dispatch fan-out and merge of ``chips/G`` devices
instead of the whole fleet, and independent groups serve independent
turn streams, so aggregate QPS scales with G (BENCH_REPLICA measures
the 1→2→4-group aggregate on the CPU mesh rig).

Writes are a fan-out of the PR 10 :class:`IngestJournal` — a replica
group is just a journal SUBSCRIBER:

- ``ingest()`` durably appends the fact batch, applies it to the
  tenant's HOME group through the normal fused ingest dispatch, then
  replays it per group through the SAME path. Replay is idempotent: ids
  a group already registered are filtered host-side, and content-level
  duplicates resolve through the in-dispatch dedup probe — a crash
  anywhere in the fan-out (the ``replica.mid_replay`` fault point)
  recovers by replaying ``journal.pending()`` past each group's
  applied-seq cursor, with zero lost and zero double-ingested facts.
- ``commit()`` happens only once EVERY group's cursor passed a seq, so
  the journal always holds whatever some subscriber still needs.
- **overlay tenants** (``overlay=True``) partition instead of
  replicating: their facts carry an overlay marker in the journal and
  apply ONLY to the home group — tenant isolation by placement, and the
  replay filter keeps it through crash recovery too. The registration
  itself is durable (a journal record that survives commit/compaction),
  so a restarted process keeps the tenant partitioned and pinned.

Staleness is bounded and MEASURED, not assumed: ``append()`` stamps each
seq, ``staleness()`` reports the age of the oldest batch any group has
not yet applied (gauged per group as ``serve.replica_staleness_s``
alongside the ``journal.replica_lag`` seqno gap), and callers compare it
against the configured ``serve_replica_staleness_s`` window.

Reads route each coalesced mega-batch to exactly ONE group:
tenant-affine for overlay tenants (their rows exist nowhere else —
which is also read-your-writes), least-loaded for shared-tier traffic.
``make_router()`` wires the policy into per-group
:class:`~lazzaro_tpu.serve.scheduler.QueryScheduler` instances via
:class:`~lazzaro_tpu.serve.scheduler.ReplicaRouter` — per-group
admission queues and circuit breakers, so one sick group degrades or
sheds alone instead of the fleet.
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from lazzaro_tpu.parallel.index import ShardedMemoryIndex
from lazzaro_tpu.parallel.mesh import replica_group_meshes
from lazzaro_tpu.reliability import faults
from lazzaro_tpu.reliability.journal import IngestJournal
from lazzaro_tpu.utils.hashing import tenant_home_group
from lazzaro_tpu.utils.telemetry import default_registry


class ReplicaPlacement:
    """G replica groups over the device fleet, each a full pod index on a
    group-local sub-mesh, kept fresh by journal-replay subscription."""

    def __init__(self, n_groups: int, dim: int, *,
                 journal: Optional[IngestJournal] = None,
                 journal_path: Optional[str] = None,
                 staleness_s: float = 5.0,
                 axis: str = "data", devices=None,
                 telemetry=None, **index_kw):
        self.telemetry = telemetry if telemetry is not None \
            else default_registry()
        meshes = replica_group_meshes(n_groups, axis, devices)
        self.n_groups = len(meshes)
        self.dim = dim
        self.staleness_s = float(staleness_s)
        self.groups: List[ShardedMemoryIndex] = []
        for mesh in meshes:
            idx = ShardedMemoryIndex(mesh, dim, axis=axis,
                                     telemetry=self.telemetry, **index_kw)
            idx.replica_groups = self.n_groups
            self.groups.append(idx)
        if journal is None:
            if journal_path is None:
                journal_path = os.path.join(
                    tempfile.mkdtemp(prefix="lz-replica-"), "ingest.waljournal")
            journal = IngestJournal(journal_path)
        self.journal = journal
        # Per-group applied-seq cursor: group g has applied every journal
        # batch with seq <= _applied[g]. Starts at 0 so batches left
        # pending by a previous process replay to EVERY group on the
        # first replicate()/catch_up() — the idempotence filters make
        # that safe regardless of which groups had applied them.
        self._applied: List[int] = [0] * self.n_groups
        # Overlay registration is DURABLE (journal records that survive
        # commit/compaction): a new process over the same journal keeps
        # pinning a previously-overlay tenant's reads to its home group
        # and keeps its future writes partitioned.
        self.overlay_tenants: set = set(self.journal.overlay_tenants)
        self._turns: List[int] = [0] * self.n_groups
        self._route_lock = threading.Lock()
        self._rr = 0

    # ------------------------------------------------------------- placement
    def group_for_tenant(self, tenant: str) -> int:
        """Stable home-group assignment (same idiom as the pod index's
        row-partition affinity): a tenant's overlay rows live only here,
        and its shared writes run their PRIMARY fused ingest here.
        Process-stable (CRC32, not the salted builtin ``hash``) so a
        restarted process re-homes journal replay and overlay reads to
        the SAME group that holds the surviving rows."""
        return tenant_home_group(tenant, self.n_groups)

    @property
    def dispatch_count(self) -> int:
        return sum(g.dispatch_count for g in self.groups)

    # ----------------------------------------------------------------- write
    def ingest(self, ids: Sequence[str], embeddings: np.ndarray,
               tenant: str, saliences: Optional[Sequence[float]] = None, *,
               overlay: bool = False, replicate: bool = True,
               **ingest_kw) -> Dict:
        """Journal-append → primary fused ingest on the tenant's home
        group → replay fan-out to every subscriber group → commit.
        Returns the PRIMARY group's ingest result (rows are home-group
        row ids; replicas allocate their own). ``overlay=True`` marks the
        tenant overlay from here on: this and future batches for it
        apply to the home group ONLY and reads pin there.
        ``replicate=False`` defers the fan-out (the batch stays pending
        in the journal until the next ``replicate()``/``catch_up()``) —
        the bounded-staleness window a deployment would open by batching
        subscriber replays, measured by ``staleness()``."""
        n = len(ids)
        if n == 0:
            return {"rows": [], "created": [], "merged": {}, "links": [],
                    "chains": [], "counters": {}}
        if overlay:
            self.overlay_tenants.add(tenant)
            self.journal.register_overlay(tenant)
        ov = tenant in self.overlay_tenants
        emb = np.asarray(embeddings, np.float32).reshape(n, self.dim)
        if saliences is None:
            saliences = [0.5] * n
        facts = [{"id": str(i), "emb": e.tolist(), "tenant": tenant,
                  "salience": float(s), "overlay": ov}
                 for i, e, s in zip(ids, emb, saliences)]
        seq = self.journal.append(facts)
        home = self.group_for_tenant(tenant)
        # Catch home up on any OLDER pending batches first (deferred
        # fan-outs appended by tenants homed elsewhere). A cursor may
        # only advance over contiguously-applied seqs: jumping it past a
        # batch home never applied would let commit(min(_applied))
        # retire that batch from the journal while home still needs it.
        for pseq, pfacts in self.journal.pending():
            if self._applied[home] < pseq < seq:
                self._apply_batch(home, pfacts, **ingest_kw)
                self._applied[home] = pseq
        out = self._apply_batch(home, facts, **ingest_kw)
        self._applied[home] = max(self._applied[home], seq)
        self.telemetry.bump(
            "serve.replica_overlay_writes" if ov else "serve.replica_writes",
            labels={"group": str(home)})
        if replicate:
            self.replicate()
        else:
            self._update_gauges()
        return out

    def _apply_batch(self, g: int, facts: List[dict], **ingest_kw) -> Dict:
        """Apply one journal batch to group ``g`` through its normal
        ingest path. Idempotence is two-layer: ids the group already
        registered are filtered HERE (exact — covers a replayed batch
        whose dispatch finished before the crash), and facts whose
        content already landed under a merged id resolve through the
        in-dispatch dedup probe (covers everything else)."""
        idx = self.groups[g]
        out = {"rows": [], "created": [], "merged": {}, "links": [],
               "chains": [], "counters": {}}
        by_tenant: Dict[str, List[dict]] = {}
        for f in facts:
            if f.get("overlay") and self.group_for_tenant(
                    f.get("tenant", "")) != g:
                continue            # overlay fact: home group only
            if f["id"] in idx.id_to_row:
                self.telemetry.bump("journal.replica_replay_skipped",
                                    labels={"group": str(g)})
                continue            # already applied here: exact replay skip
            by_tenant.setdefault(f.get("tenant", ""), []).append(f)
        for tenant, fs in by_tenant.items():
            got = idx.ingest([f["id"] for f in fs],
                             np.asarray([f["emb"] for f in fs], np.float32),
                             tenant, [f["salience"] for f in fs],
                             **ingest_kw)
            out["rows"].extend(got["rows"])
            out["created"].extend(got["created"])
            out["merged"].update(got["merged"])
            out["links"].extend(got["links"])
            out["chains"].extend(got["chains"])
            for k, v in got.get("counters", {}).items():
                out["counters"][k] = out["counters"].get(k, 0) + v
        return out

    def replicate(self) -> int:
        """Drain the journal to every subscriber group past its cursor,
        then commit whatever EVERY group has applied. This is both the
        steady-state fan-out (called by every ``ingest``) and the crash
        recovery path (``catch_up``) — same code, same idempotence.
        Returns the number of per-group batch applications performed."""
        applied_n = 0
        for g in range(self.n_groups):
            for seq, facts in self.journal.pending():
                if seq <= self._applied[g]:
                    continue
                # Fault point "replica.mid_replay": a raise here models
                # the fan-out dying with the batch applied on SOME groups
                # and the cursor/commit not yet advanced — recovery is
                # simply calling this method again.
                faults.fire("replica.mid_replay", group=g, seq=seq)
                self._apply_batch(g, facts)
                self._applied[g] = seq
                applied_n += 1
                self.telemetry.bump("journal.replica_replayed",
                                    labels={"group": str(g)})
        self.journal.commit(min(self._applied))
        self._update_gauges()
        return applied_n

    catch_up = replicate

    # ------------------------------------------------------------ staleness
    def lag(self) -> int:
        """Worst per-group journal seqno gap (``journal.replica_lag``)."""
        return max(self.journal.lag(a) for a in self._applied)

    def staleness(self) -> float:
        """Age of the oldest journal batch some group has not applied —
        the measured bounded-staleness window, to compare against the
        configured ``serve_replica_staleness_s``."""
        return max(self.journal.oldest_age(a) for a in self._applied)

    def _update_gauges(self) -> None:
        for g, applied in enumerate(self._applied):
            self.telemetry.gauge("journal.replica_lag",
                                 self.journal.lag(applied),
                                 labels={"group": str(g)})
            self.telemetry.gauge("serve.replica_staleness_s",
                                 self.journal.oldest_age(applied),
                                 labels={"group": str(g)})
        if self.staleness() > self.staleness_s:
            self.telemetry.bump("serve.replica_staleness_violations")

    # ----------------------------------------------------------------- read
    def route_batch(self, reqs) -> int:
        """The group ONE coalesced mega-batch routes to: the home group
        when the batch carries overlay tenants (they must agree — the
        per-request router in :meth:`make_router` never mixes homes),
        least-loaded round-robin otherwise. Selecting a group RESERVES
        the turn (``_turns`` bumps under the same lock acquisition), so
        concurrent callers never all pick the same least-loaded group."""
        homes = {self.group_for_tenant(r.tenant) for r in reqs
                 if r.tenant in self.overlay_tenants}
        if len(homes) > 1:
            raise ValueError(
                "one mega-batch mixes overlay tenants with different home "
                "groups — route per request (make_router) instead")
        with self._route_lock:
            if homes:
                g = homes.pop()
            else:
                lo = min(self._turns)
                candidates = [g for g, t in enumerate(self._turns)
                              if t == lo]
                g = candidates[self._rr % len(candidates)]
                self._rr += 1
            self._turns[g] += 1
            return g

    def serve(self, reqs) -> List:
        """Serve one coalesced mega-batch on exactly one group: ONE
        distributed dispatch + ONE packed readback, group-local."""
        g = self.route_batch(reqs)
        self.telemetry.bump("serve.replica_routed_turns",
                            labels={"group": str(g)})
        return self.groups[g].serve_requests(reqs)

    def make_router(self, **sched_kw):
        """Per-group :class:`QueryScheduler`s behind the routing policy —
        the production wiring (per-group admission + breaker state).
        Shares ``overlay_tenants`` by reference, so a tenant that turns
        overlay after router construction pins immediately."""
        from lazzaro_tpu.serve.scheduler import ReplicaRouter

        return ReplicaRouter([g.serve_requests for g in self.groups],
                             affine_tenants=self.overlay_tenants,
                             telemetry=self.telemetry, **sched_kw)

    # ------------------------------------------------------------- maintain
    def ivf_build(self, **kw) -> None:
        for g in self.groups:
            g.ivf_build(**kw)

    def warmup_serving(self, *a, **kw) -> None:
        for g in self.groups:
            g.warmup_serving(*a, **kw)

    def stats(self) -> dict:
        return {
            "n_groups": self.n_groups,
            "applied_seq": list(self._applied),
            "last_seq": self.journal.last_seq,
            "pending": self.journal.pending_count,
            "lag": self.lag(),
            "staleness_s": self.staleness(),
            "staleness_bound_s": self.staleness_s,
            "overlay_tenants": len(self.overlay_tenants),
            "turns": list(self._turns),
        }
