"""Device-mesh construction and sharding helpers.

The reference has no distributed backend (SURVEY §2.3 — its only cross-process
channel is LanceDB version polling). Here the mesh IS the backend: user
partitions and index rows map onto mesh axes, and XLA collectives over ICI/DCN
replace anything NCCL-shaped.

Axis conventions:
- ``data``  — index rows / batch data parallelism (DP; index "TP analog")
- ``model`` — tensor parallelism for the in-tree encoder/LLM (TP)
Multi-host: call ``jax.distributed.initialize()`` before ``make_mesh`` and the
same code spans slices over DCN.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axis_names: Sequence[str] = ("data",),
              axis_sizes: Optional[Sequence[int]] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axis_sizes is None:
        sizes = [1] * len(axis_names)
        sizes[0] = n
        axis_sizes = sizes
    total = int(np.prod(axis_sizes))
    if total != n:
        raise ValueError(f"mesh {tuple(axis_sizes)} needs {total} devices, have {n}")
    dev_array = np.array(devices).reshape(axis_sizes)
    return Mesh(dev_array, tuple(axis_names))


def single_device_mesh() -> Mesh:
    return make_mesh(("data",), (1,), devices=jax.devices()[:1])


def replica_group_meshes(n_groups: int, axis: str = "data",
                         devices: Optional[Sequence[jax.Device]] = None
                         ) -> Tuple[Mesh, ...]:
    """Partition the device fleet into ``n_groups`` contiguous group-local
    sub-meshes (replica-group serving, ISSUE 18): each group holds a FULL
    copy of the arena row-sharded over its own ``len(devices)/n_groups``
    chips, so the fused serving program compiled per group keeps the exact
    single-group structure — the ``sharded_topk_merge`` all_gather simply
    narrows to the group's sub-mesh and never crosses groups. Contiguous
    device ranges keep each group's merge collective on neighboring chips
    (the same locality argument as ``make_hybrid_mesh``'s ICI-inside
    layout).

    ``n_groups`` must divide the device count; 1 returns the classic
    whole-fleet mesh unchanged."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    n_groups = int(n_groups)
    if n_groups < 1 or n % n_groups:
        raise ValueError(
            f"replica_groups={n_groups} must divide the {n}-device fleet")
    per = n // n_groups
    return tuple(
        make_mesh((axis,), (per,), devices=devices[g * per:(g + 1) * per])
        for g in range(n_groups))


def make_hybrid_mesh(ici_axes: Sequence[str], ici_sizes: Sequence[int],
                     dcn_axis: str = "slice",
                     num_slices: Optional[int] = None) -> Mesh:
    """Multi-slice mesh: a DCN axis across slices, ICI axes within each.

    Lay shardings out so collectives on ``ici_axes`` ride the intra-slice
    interconnect and only the ``dcn_axis`` (put FIRST, slowest-varying)
    crosses the data-center network — e.g. data-parallel over slices,
    tensor/index-parallel within. Call ``jax.distributed.initialize()``
    first on multi-host deployments.

    Requires ``prod(ici_sizes)`` devices per slice (extra devices in a
    slice are unused). On platforms with no slice topology (CPU, single
    slice) the result is the same axes with a size-1 ``dcn_axis``, so mesh
    consumers never special-case slice count.
    """
    per_slice = int(np.prod(ici_sizes))
    groups: dict = {}
    for d in jax.devices():
        groups.setdefault(getattr(d, "slice_index", 0) or 0, []).append(d)
    slice_ids = sorted(groups)
    n_slices = num_slices if num_slices is not None else len(slice_ids)
    if len(slice_ids) < n_slices:
        raise ValueError(f"requested {n_slices} slices, platform exposes "
                         f"{len(slice_ids)}")
    short = [s for s in slice_ids[:n_slices] if len(groups[s]) < per_slice]
    if short:
        raise ValueError(f"slices {short} have fewer than prod(ici_sizes)="
                         f"{per_slice} devices")
    # Topology-aware ICI ordering within each slice (single-slice included —
    # naive reshape could put logically adjacent mesh neighbors on
    # physically non-adjacent chips), explicit stacking across slices
    # (documented create_device_mesh contract — no reliance on
    # create_hybrid_device_mesh's internal block layout).
    from jax.experimental import mesh_utils
    per_slice_arrays = [
        mesh_utils.create_device_mesh(tuple(ici_sizes),
                                      devices=groups[s][:per_slice])
        for s in slice_ids[:n_slices]]
    dev_array = np.stack(per_slice_arrays)
    return Mesh(dev_array, (dcn_axis,) + tuple(ici_axes))


def spec(mesh: Mesh, *axes: Optional[str]) -> NamedSharding:
    return NamedSharding(mesh, P(*axes))


def shard_stacked(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Sharding for host-STACKED per-shard tables ``[n_shards, ...]`` (the
    per-shard CSR slices and IVF member/extras tables the fused pod
    serving program consumes): the leading dim is the shard axis, so chip
    ``p`` holds exactly its own ``[1, ...]`` slice and the shard_map body
    squeezes it off. Trailing dims (left unspecified in the PartitionSpec)
    replicate within the slice."""
    return NamedSharding(mesh, P(axis))
