"""Device-mesh construction and sharding helpers.

The reference has no distributed backend (SURVEY §2.3 — its only cross-process
channel is LanceDB version polling). Here the mesh IS the backend: user
partitions and index rows map onto mesh axes, and XLA collectives over ICI/DCN
replace anything NCCL-shaped.

Axis conventions:
- ``data``  — index rows / batch data parallelism (DP; index "TP analog")
- ``model`` — tensor parallelism for the in-tree encoder/LLM (TP)
Multi-host: call ``jax.distributed.initialize()`` before ``make_mesh`` and the
same code spans slices over DCN.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axis_names: Sequence[str] = ("data",),
              axis_sizes: Optional[Sequence[int]] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axis_sizes is None:
        sizes = [1] * len(axis_names)
        sizes[0] = n
        axis_sizes = sizes
    total = int(np.prod(axis_sizes))
    if total != n:
        raise ValueError(f"mesh {tuple(axis_sizes)} needs {total} devices, have {n}")
    dev_array = np.array(devices).reshape(axis_sizes)
    return Mesh(dev_array, tuple(axis_names))


def single_device_mesh() -> Mesh:
    return make_mesh(("data",), (1,), devices=jax.devices()[:1])


def spec(mesh: Mesh, *axes: Optional[str]) -> NamedSharding:
    return NamedSharding(mesh, P(*axes))
