"""Interactive CLI REPL.

Parity target: reference ``cli/main.py`` (153 LoC) — same slash commands
(/start /end /stats /profile /memories [n] /consolidate /merge /prune [t]
/config /set <k> <v> /save [f] /load [f] /users /switch <u> /quit /help),
streaming chat path. Differences by design:
- offline-first: no API key required (HeuristicLLM + HashingEmbedder run on
  device); pass OPENAI_API_KEY + --remote to use the OpenAI shim.
- /save and /load actually work (the reference's reference
  ``memory.persistence.filepath`` crashes — SURVEY §2.2 quirk list).
"""

from __future__ import annotations

import argparse
import os
import sys


def build_memory(args) -> "MemorySystem":
    from lazzaro_tpu.core.memory_system import MemorySystem

    llm = embedder = None
    if args.remote:
        api_key = os.getenv("OPENAI_API_KEY", "")
        if not api_key:
            print("⚠ --remote requires OPENAI_API_KEY; falling back to on-device providers.")
        else:
            from lazzaro_tpu.core.providers import OpenAIEmbedder, OpenAILLM
            llm = OpenAILLM(api_key)
            embedder = OpenAIEmbedder(api_key)
    elif args.encoder:
        from lazzaro_tpu.core.providers import EncoderEmbedder
        embedder = EncoderEmbedder()

    return MemorySystem(
        db_dir=args.db_dir,
        user_id=args.user,
        llm_provider=llm,
        embedding_provider=embedder,
        max_buffer_size=args.max_buffer_size,
        prune_threshold=args.prune_threshold,
    )


HELP = ("Available commands: /start, /end, /stats, /profile, /memories [n], "
        "/consolidate, /merge, /prune [thresh], /config, /set <k> <v>, "
        "/save [file], /load [file], /snapshot [dir], /restore [dir], "
        "/users, /switch <user>, /quit")

CONFIG_PARAMS = ["max_buffer_size", "prune_threshold", "consolidate_every",
                 "auto_consolidate", "auto_prune", "enable_sharding",
                 "enable_hierarchy", "enable_caching", "enable_async"]


def handle_command(memory, user_input: str) -> bool:
    """Process one slash command; returns False when the REPL should exit."""
    parts = user_input.split()
    cmd = parts[0].lower()

    if cmd == "/quit":
        if memory.conversation_active:
            print("\n" + memory.end_conversation())
        print("\n👋 Goodbye!")
        return False
    elif cmd == "/start":
        print("\n" + memory.start_conversation())
    elif cmd == "/end":
        print("\n" + memory.end_conversation())
    elif cmd == "/stats":
        print(memory.display_stats())
    elif cmd == "/profile":
        print(memory.display_profile())
    elif cmd == "/memories":
        limit = int(parts[1]) if len(parts) > 1 else 10
        print(memory.display_memories(limit=limit))
    elif cmd == "/consolidate":
        print("\n" + memory.run_consolidation())
    elif cmd == "/merge":
        print("\n🔄 Merging similar nodes...")
        merged = memory._merge_similar_nodes()
        print(f"✓ Merged {merged} similar nodes")
    elif cmd == "/prune":
        threshold = float(parts[1]) if len(parts) > 1 else memory.prune_threshold
        print(f"\n🔄 Pruning edges below {threshold}...")
        pruned = memory._prune_weak_edges(threshold)
        print(f"✓ Pruned {pruned} weak edges")
    elif cmd == "/config":
        print("\n⚙️ Configuration:")
        for param in CONFIG_PARAMS:
            print(f"  • {param}: {getattr(memory, param)}")
    elif cmd == "/set":
        if len(parts) < 3:
            print("⚠ Usage: /set <parameter> <value>")
            return True
        param, value_str = parts[1], parts[2]
        if not hasattr(memory, param):
            print(f"⚠ Unknown parameter: {param}")
            return True
        try:
            val_type = type(getattr(memory, param))
            if val_type is bool:
                value = value_str.lower() in ("true", "1", "on", "yes")
            else:
                value = val_type(value_str)
            setattr(memory, param, value)
            print(f"✓ Set {param} = {value}")
        except ValueError:
            print(f"⚠ Invalid value for {param}")
    elif cmd == "/save":
        memory._save_to_persistence()
        filename = parts[1] if len(parts) > 1 else "memory_state.json"
        print("\n" + memory.save_state(filename))
    elif cmd == "/load":
        if len(parts) > 1:
            print("\n" + memory.load_state(parts[1]))
        else:
            memory._load_from_persistence()
            print(f"\n✓ Reloaded user '{memory.user_id}' from {memory.config.db_dir}")
    elif cmd == "/snapshot":
        target = parts[1] if len(parts) > 1 else "memory_snapshot"
        print("\n" + memory.save_snapshot(target))
    elif cmd == "/restore":
        target = parts[1] if len(parts) > 1 else "memory_snapshot"
        print("\n" + memory.load_snapshot(target))
    elif cmd == "/users":
        for u in memory.get_all_users():
            marker = " ←" if u == memory.user_id else ""
            print(f"  • {u}{marker}")
    elif cmd == "/switch":
        if len(parts) < 2:
            print("⚠ Usage: /switch <user_id>")
        else:
            memory.switch_user(parts[1])
    elif cmd == "/help":
        print(HELP)
    else:
        print(f"⚠ Unknown command: {cmd}. Try /help")
    return True


def interactive_chat(args=None) -> None:
    args = args or parse_args([])
    print("=" * 60)
    print("  LAZZARO-TPU MEMORY SYSTEM — CLI")
    print("=" * 60)
    print("\n" + HELP)

    memory = build_memory(args)
    while True:
        try:
            user_input = input("\nYou: ").strip()
            if not user_input:
                continue
            if user_input.startswith("/"):
                if not handle_command(memory, user_input):
                    break
            else:
                first = True
                print("Assistant: ", end="", flush=True)
                for event in memory.chat_stream(user_input):
                    if event["type"] == "token":
                        print(event["content"], end="", flush=True)
                        first = False
                    elif event["type"] == "info" and first:
                        print(f"\n{event['content']}")
                print()
        except (KeyboardInterrupt, EOFError):
            print("\n👋 Goodbye!")
            break
        except Exception as e:  # keep the REPL alive (parity :146-147)
            print(f"\n⚠ Error: {e}")
    memory.close()


def parse_args(argv):
    p = argparse.ArgumentParser(prog="lazzaro-tpu-cli",
                                description="TPU-native memory system REPL")
    p.add_argument("--db-dir", default="db")
    p.add_argument("--user", default="default")
    p.add_argument("--max-buffer-size", type=int, default=10)
    p.add_argument("--prune-threshold", type=float, default=0.5)
    p.add_argument("--remote", action="store_true",
                   help="use OpenAI providers (needs OPENAI_API_KEY)")
    p.add_argument("--encoder", action="store_true",
                   help="use the on-TPU flax encoder for embeddings")
    return p.parse_args(argv)


def main() -> None:
    interactive_chat(parse_args(sys.argv[1:]))


if __name__ == "__main__":
    main()
