"""Lazy native build: compile csrc/lazzaro_native.cc into a cached .so.

The reference ships no native code of its own — it rides LanceDB/pyarrow
wheels (SURVEY.md §2). Here the native host library is in-tree, so the build
has to be self-contained: one ``g++ -O3 -shared`` invocation, cached by source
hash, with a CMakeLists.txt alongside for formal builds. Import never fails —
callers check ``load() is not None`` and fall back to pure Python/numpy.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "csrc", "lazzaro_native.cc")

_CXX_FLAGS = ["-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
              "-fvisibility=default", "-Wall"]


def _cache_dir() -> str:
    override = os.environ.get("LAZZARO_NATIVE_CACHE")
    if override:
        return override
    return os.path.join(_HERE, "_build")


def _source_tag() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.blake2b(f.read(), digest_size=8).hexdigest()


def so_path() -> str:
    return os.path.join(_cache_dir(), f"liblazzaro_native-{_source_tag()}.so")


def build(verbose: bool = False) -> Optional[str]:
    """Compile if needed; returns the .so path or None when no toolchain."""
    path = so_path()
    if os.path.exists(path):
        return path
    cxx = os.environ.get("CXX", "g++")
    os.makedirs(_cache_dir(), exist_ok=True)
    # Build to a temp name then atomic-rename so concurrent importers never
    # dlopen a half-written object.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_cache_dir())
    os.close(fd)
    cmd = [cxx, *_CXX_FLAGS, _SRC, "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        os.unlink(tmp)
        return None
    if proc.returncode != 0:
        if verbose:
            print(proc.stderr)
        os.unlink(tmp)
        return None
    os.replace(tmp, path)
    return path


_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def load() -> Optional[ctypes.CDLL]:
    """dlopen the native library (building it on first use); None if
    unavailable. Set LAZZARO_DISABLE_NATIVE=1 to force the Python paths."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("LAZZARO_DISABLE_NATIVE"):
        return None
    path = build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None

    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)

    lib.lz_abi_version.restype = ctypes.c_int32
    lib.lz_blake2b8.restype = ctypes.c_uint64
    lib.lz_blake2b8.argtypes = [u8p, ctypes.c_int64]
    lib.lz_encode_batch.restype = None
    lib.lz_encode_batch.argtypes = [u8p, i64p, ctypes.c_int64, ctypes.c_int32,
                                    ctypes.c_int32, i32p]
    lib.lz_masked_topk_f32.restype = None
    lib.lz_masked_topk_f32.argtypes = [f32p, u8p, f32p, ctypes.c_int64,
                                       ctypes.c_int64, ctypes.c_int32,
                                       ctypes.c_int32, f32p, i64p]
    lib.lz_crc32.restype = ctypes.c_uint32
    lib.lz_crc32.argtypes = [u8p, ctypes.c_int64]
    lib.lz_wal_append.restype = ctypes.c_int64
    lib.lz_wal_append.argtypes = [ctypes.c_char_p, u8p, ctypes.c_int64,
                                  ctypes.c_int32]
    lib.lz_wal_load.restype = ctypes.c_void_p  # malloc'd; freed via lz_free
    lib.lz_wal_load.argtypes = [ctypes.c_char_p, i64p]
    lib.lz_free.restype = None
    lib.lz_free.argtypes = [ctypes.c_void_p]
    lib.lz_wal_reset.restype = ctypes.c_int64
    lib.lz_wal_reset.argtypes = [ctypes.c_char_p]

    if lib.lz_abi_version() != 1:
        return None
    _LIB = lib
    return _LIB
