"""Native host runtime: SIMD masked top-k, blake2b hash tokenization, WAL.

High-level, numpy-facing API over ``csrc/lazzaro_native.cc`` (built lazily by
``build.py``). Every entry point has a pure-Python fallback so the framework
runs unchanged on hosts without a C++ toolchain:

- ``masked_topk(emb, alive, query, k)``   — host cosine top-k (multithreaded
  C++, else vectorized numpy). Device-side search lives in ``core.state`` /
  ``ops.topk``; this backs store-only consumers (ArrowStore.search_nodes,
  reference vector_store.py:132-140).
- ``encode_batch(texts, vocab, max_len)`` — HashTokenizer-compatible batch
  encoding (bit-identical for ASCII; non-ASCII rows route through Python).
- ``WriteAheadLog``                        — CRC-framed durable journal with
  torn-tail recovery; used by MemorySystem to make short-term turns survive a
  crash (the reference persists only at conversation end,
  memory_system.py:648, and loses in-flight turns).
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from lazzaro_tpu.native.build import build, load, so_path  # noqa: F401


def available() -> bool:
    return load() is not None


# ---------------------------------------------------------------------------
# masked top-k
# ---------------------------------------------------------------------------


def _topk_numpy(emb: np.ndarray, alive: Optional[np.ndarray],
                query: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
    n = emb.shape[0]
    qn = float(np.linalg.norm(query))
    scores = np.full(n, -1e30, np.float32)
    if n and qn > 0:
        norms = np.linalg.norm(emb, axis=1)
        ok = norms > 0
        if alive is not None:
            ok &= alive.astype(bool)
        scores[ok] = emb[ok] @ query.astype(np.float32) / (norms[ok] * qn)
    k_eff = min(k, n)
    idx = np.argpartition(-scores, k_eff - 1)[:k_eff] if k_eff else np.array([], np.int64)
    order = idx[np.lexsort((idx, -scores[idx]))]
    out_scores = np.full(k, -1e30, np.float32)
    out_rows = np.full(k, -1, np.int64)
    valid = scores[order] > -1e30
    order = order[valid]
    out_scores[: len(order)] = scores[order]
    out_rows[: len(order)] = order
    return out_scores, out_rows


def masked_topk(emb: np.ndarray, alive: Optional[np.ndarray],
                query: np.ndarray, k: int,
                nthreads: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Cosine top-k over row-major [n, d] f32 with an optional alive mask.

    Returns (scores[k] f32 desc, rows[k] i64); missing slots are
    (-1e30, -1). Ties break on the lower row index, matching the C++ side.
    """
    emb = np.ascontiguousarray(emb, np.float32)
    query = np.ascontiguousarray(query, np.float32)
    n, d = emb.shape
    lib = load()
    if lib is None or n == 0:
        return _topk_numpy(emb, alive, query, k)
    alive_arr = None
    alive_ptr = ctypes.POINTER(ctypes.c_uint8)()
    if alive is not None:
        alive_arr = np.ascontiguousarray(alive, np.uint8)
        alive_ptr = alive_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    out_scores = np.empty(k, np.float32)
    out_rows = np.empty(k, np.int64)
    lib.lz_masked_topk_f32(
        emb.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), alive_ptr,
        query.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n, d, k,
        nthreads,
        out_scores.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out_rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    return out_scores, out_rows


# ---------------------------------------------------------------------------
# batch tokenization
# ---------------------------------------------------------------------------


def encode_batch(texts: Sequence[str], vocab_size: int,
                 max_len: int) -> np.ndarray:
    """[n, max_len] int32 token ids, HashTokenizer-compatible."""
    from lazzaro_tpu.models.tokenizer import HashTokenizer

    n = len(texts)
    out = np.empty((n, max_len), np.int32)
    lib = load()
    native_rows: List[int] = []
    python_rows: List[int] = []
    for i, t in enumerate(texts):
        (native_rows if (lib is not None and t.isascii()) else python_rows).append(i)

    if native_rows:
        blobs = [texts[i].encode("utf-8") for i in native_rows]
        offsets = np.zeros(len(blobs) + 1, np.int64)
        np.cumsum([len(b) for b in blobs], out=offsets[1:])
        concat = np.frombuffer(b"".join(blobs) or b"\0", np.uint8).copy()
        sub = np.empty((len(blobs), max_len), np.int32)
        lib.lz_encode_batch(
            concat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(blobs), vocab_size, max_len,
            sub.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        out[native_rows] = sub
    if python_rows:
        tok = HashTokenizer(vocab_size, max_len)
        for i in python_rows:
            out[i] = tok.encode(texts[i])
    return out


def blake2b8(data: bytes) -> int:
    lib = load()
    if lib is None:
        import hashlib
        return int.from_bytes(
            hashlib.blake2b(data, digest_size=8).digest(), "little")
    buf = np.frombuffer(data or b"\0", np.uint8).copy()
    return int(lib.lz_blake2b8(
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(data)))


# ---------------------------------------------------------------------------
# write-ahead log
# ---------------------------------------------------------------------------


class WriteAheadLog:
    """Append-only CRC-framed journal (native when available, else Python).

    A crash mid-append leaves at most one torn tail record; ``replay``
    silently discards it. Record payloads are opaque bytes.
    """

    _MAGIC = 0x4C5A5731

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self.fsync = fsync
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)

    def append(self, payload: bytes) -> None:
        lib = load()
        if lib is not None:
            buf = np.frombuffer(payload or b"\0", np.uint8).copy()
            rc = lib.lz_wal_append(
                self.path.encode(),
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                len(payload), 1 if self.fsync else 0)
            if rc != 0:
                raise OSError(f"WAL append failed (rc={rc}) for {self.path}")
            return
        import struct
        import zlib
        rec = struct.pack("<III", self._MAGIC, len(payload),
                          zlib.crc32(payload)) + payload
        with open(self.path, "ab") as f:
            f.write(rec)
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())

    def replay(self) -> List[bytes]:
        lib = load()
        if lib is not None:
            out_len = ctypes.c_int64()
            ptr = lib.lz_wal_load(self.path.encode(), ctypes.byref(out_len))
            if not ptr or out_len.value <= 0:
                if ptr:
                    lib.lz_free(ptr)
                return []
            raw = ctypes.string_at(ptr, out_len.value)
            lib.lz_free(ptr)
            records, pos = [], 0
            while pos + 4 <= len(raw):
                ln = int.from_bytes(raw[pos:pos + 4], "little")
                records.append(raw[pos + 4:pos + 4 + ln])
                pos += 4 + ln
            return records
        import struct
        import zlib
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return []
        records, pos = [], 0
        while pos + 12 <= len(raw):
            magic, ln, crc = struct.unpack_from("<III", raw, pos)
            if magic != self._MAGIC or pos + 12 + ln > len(raw):
                break
            payload = raw[pos + 12:pos + 12 + ln]
            if zlib.crc32(payload) != crc:
                break
            records.append(payload)
            pos += 12 + ln
        return records

    def reset(self) -> None:
        lib = load()
        if lib is not None:
            rc = lib.lz_wal_reset(self.path.encode())
            if rc != 0:
                raise OSError(f"WAL reset failed (rc={rc}) for {self.path}")
            return
        with open(self.path, "wb"):
            pass
