// lazzaro_tpu native host runtime.
//
// The reference (thelaycon/lazzaro) delegates all native-performance work to
// external wheels: LanceDB (Rust) for ANN + durability, pyarrow (C++) for
// columnar IO, numpy (C) for similarity math (SURVEY.md §2). This library is
// the in-tree equivalent for the HOST side of the TPU framework — the device
// side is JAX/XLA/Pallas; everything here backs the host paths:
//
//   1. lz_masked_topk_f32  — multithreaded masked cosine top-k over row-major
//      f32 embeddings. Backs ArrowStore.search_nodes (protocol-parity search
//      for store-only consumers, reference vector_store.py:132-140) on hosts
//      without an accelerator.
//   2. lz_encode_batch     — batch hash-bucket tokenization (blake2b-8, RFC
//      7693), bit-identical to models/tokenizer.py::HashTokenizer for ASCII
//      text. Removes the per-token hashlib round-trips from the encoder's
//      host preprocessing.
//   3. lz_wal_*            — a CRC-32-framed append-only write-ahead log with
//      explicit fsync. The reference persists only at conversation end
//      (memory_system.py:648) and has no crash story (SURVEY §5 "failure
//      detection: none"); the WAL journals short-term turns so an agent
//      process crash loses nothing.
//
// Plain C ABI (extern "C") consumed via ctypes — no pybind11 in this image.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------------------
// blake2b (RFC 7693), unkeyed, 8-byte digest — matches hashlib.blake2b(
// token, digest_size=8) so native and Python tokenizers agree bucket-for-
// bucket (models/tokenizer.py::_bucket).
// ---------------------------------------------------------------------------

static const uint64_t B2B_IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
};

static const uint8_t B2B_SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
};

static inline uint64_t rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

static inline uint64_t load64le(const uint8_t* p) {
  uint64_t v;
  memcpy(&v, p, 8);  // little-endian hosts only (x86-64 / aarch64)
  return v;
}

static void b2b_compress(uint64_t h[8], const uint8_t block[128], uint64_t t,
                         bool last) {
  uint64_t v[16], m[16];
  for (int i = 0; i < 8; i++) v[i] = h[i];
  for (int i = 0; i < 8; i++) v[i + 8] = B2B_IV[i];
  v[12] ^= t;  // low word of the offset counter; messages here are < 2^64
  if (last) v[14] = ~v[14];
  for (int i = 0; i < 16; i++) m[i] = load64le(block + 8 * i);

#define B2B_G(a, b, c, d, x, y)           \
  do {                                    \
    v[a] = v[a] + v[b] + (x);             \
    v[d] = rotr64(v[d] ^ v[a], 32);       \
    v[c] = v[c] + v[d];                   \
    v[b] = rotr64(v[b] ^ v[c], 24);       \
    v[a] = v[a] + v[b] + (y);             \
    v[d] = rotr64(v[d] ^ v[a], 16);       \
    v[c] = v[c] + v[d];                   \
    v[b] = rotr64(v[b] ^ v[c], 63);       \
  } while (0)

  for (int r = 0; r < 12; r++) {
    const uint8_t* s = B2B_SIGMA[r];
    B2B_G(0, 4, 8, 12, m[s[0]], m[s[1]]);
    B2B_G(1, 5, 9, 13, m[s[2]], m[s[3]]);
    B2B_G(2, 6, 10, 14, m[s[4]], m[s[5]]);
    B2B_G(3, 7, 11, 15, m[s[6]], m[s[7]]);
    B2B_G(0, 5, 10, 15, m[s[8]], m[s[9]]);
    B2B_G(1, 6, 11, 12, m[s[10]], m[s[11]]);
    B2B_G(2, 7, 8, 13, m[s[12]], m[s[13]]);
    B2B_G(3, 4, 9, 14, m[s[14]], m[s[15]]);
  }
#undef B2B_G

  for (int i = 0; i < 8; i++) h[i] ^= v[i] ^ v[i + 8];
}

// 8-byte unkeyed blake2b of data[0:len], returned as the uint64 whose
// little-endian serialization is the digest (== int.from_bytes(d, "little")).
uint64_t lz_blake2b8(const uint8_t* data, int64_t len) {
  uint64_t h[8];
  for (int i = 0; i < 8; i++) h[i] = B2B_IV[i];
  h[0] ^= 0x01010000ULL ^ 8ULL;  // depth=1, fanout=1, outlen=8, no key

  uint64_t t = 0;
  while (len > 128) {
    t += 128;
    b2b_compress(h, data, t, false);
    data += 128;
    len -= 128;
  }
  uint8_t block[128];
  memset(block, 0, sizeof(block));
  memcpy(block, data, (size_t)len);
  t += (uint64_t)len;
  b2b_compress(h, block, t, true);
  return h[0];
}

// ---------------------------------------------------------------------------
// Batch hash tokenization.
//
// Mirrors HashTokenizer.encode: lowercase, split on [a-z0-9]+ runs, bucket =
// RESERVED + blake2b8(token) % (vocab_size - RESERVED); layout
// [CLS] tok... [SEP] PAD..., truncated to max_len (at most max_len - 2
// content tokens). ASCII-exact vs the Python implementation; callers route
// non-ASCII strings through Python.
// ---------------------------------------------------------------------------

enum { LZ_PAD = 0, LZ_CLS = 1, LZ_SEP = 2, LZ_RESERVED = 4 };

void lz_encode_one(const uint8_t* text, int64_t len, int32_t vocab_size,
                   int32_t max_len, int32_t* out) {
  const uint64_t space = (uint64_t)(vocab_size - LZ_RESERVED);
  if (max_len <= 0) return;
  int32_t pos = 0;
  out[pos++] = LZ_CLS;  // matches Python ids[:max_len]: CLS survives, SEP may not
  std::vector<uint8_t> tok;  // tokens can be arbitrarily long; hash them whole
  for (int64_t i = 0; i <= len && pos < max_len - 1; i++) {
    uint8_t c = (i < len) ? text[i] : 0;
    if (c >= 'A' && c <= 'Z') c = c - 'A' + 'a';
    bool is_tok = (c >= 'a' && c <= 'z') || (c >= '0' && c <= '9');
    if (is_tok) {
      tok.push_back(c);
    } else if (!tok.empty()) {
      out[pos++] = LZ_RESERVED +
                   (int32_t)(lz_blake2b8(tok.data(), (int64_t)tok.size()) % space);
      tok.clear();
    }
  }
  if (pos < max_len) out[pos++] = LZ_SEP;
  while (pos < max_len) out[pos++] = LZ_PAD;
}

// texts: concatenated UTF-8 bytes; offsets: n+1 cumulative byte offsets.
// out: [n, max_len] int32, row-major.
void lz_encode_batch(const uint8_t* texts, const int64_t* offsets, int64_t n,
                     int32_t vocab_size, int32_t max_len, int32_t* out) {
  for (int64_t i = 0; i < n; i++) {
    lz_encode_one(texts + offsets[i], offsets[i + 1] - offsets[i], vocab_size,
                  max_len, out + i * max_len);
  }
}

// ---------------------------------------------------------------------------
// Masked cosine top-k.
//
// emb: [n, d] row-major f32 (need not be pre-normalized); alive: [n] u8 mask;
// query: [d] f32. Writes k (score, row) pairs sorted descending; rows with
// alive==0 or zero norm never appear (emitted as row=-1, score=-inf when
// fewer than k alive rows exist). nthreads<=0 picks hardware concurrency.
// ---------------------------------------------------------------------------

struct TopKHeap {  // fixed-size min-heap on score
  float* scores;
  int64_t* rows;
  int32_t k;
  int32_t size = 0;

  void push(float s, int64_t r) {
    if (size < k) {
      scores[size] = s;
      rows[size] = r;
      size++;
      sift_up(size - 1);
    } else if (s > scores[0]) {
      scores[0] = s;
      rows[0] = r;
      sift_down(0);
    }
  }
  void sift_up(int32_t i) {
    while (i > 0) {
      int32_t p = (i - 1) / 2;
      if (scores[p] <= scores[i]) break;
      std::swap(scores[p], scores[i]);
      std::swap(rows[p], rows[i]);
      i = p;
    }
  }
  void sift_down(int32_t i) {
    for (;;) {
      int32_t l = 2 * i + 1, r = 2 * i + 2, m = i;
      if (l < size && scores[l] < scores[m]) m = l;
      if (r < size && scores[r] < scores[m]) m = r;
      if (m == i) break;
      std::swap(scores[m], scores[i]);
      std::swap(rows[m], rows[i]);
      i = m;
    }
  }
};

static void topk_range(const float* emb, const uint8_t* alive,
                       const float* query, int64_t d, int64_t lo, int64_t hi,
                       float inv_qnorm, TopKHeap* heap) {
  for (int64_t i = lo; i < hi; i++) {
    if (alive && !alive[i]) continue;
    const float* row = emb + i * d;
    float dot = 0.f, sq = 0.f;
    for (int64_t j = 0; j < d; j++) {  // auto-vectorizes under -O3
      dot += row[j] * query[j];
      sq += row[j] * row[j];
    }
    if (sq <= 0.f) continue;
    heap->push(dot * inv_qnorm / sqrtf(sq), i);
  }
}

void lz_masked_topk_f32(const float* emb, const uint8_t* alive,
                        const float* query, int64_t n, int64_t d, int32_t k,
                        int32_t nthreads, float* out_scores,
                        int64_t* out_rows) {
  float qsq = 0.f;
  for (int64_t j = 0; j < d; j++) qsq += query[j] * query[j];
  for (int32_t i = 0; i < k; i++) {
    out_scores[i] = -1e30f;
    out_rows[i] = -1;
  }
  if (qsq <= 0.f || n <= 0) return;
  float inv_qnorm = 1.f / sqrtf(qsq);

  if (nthreads <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    nthreads = hc ? (int32_t)hc : 4;
  }
  // Below ~64k rows the thread spawn costs more than it saves.
  int64_t min_rows_per_thread = 65536;
  int32_t t = (int32_t)((n + min_rows_per_thread - 1) / min_rows_per_thread);
  if (t < nthreads) nthreads = t < 1 ? 1 : t;

  std::vector<std::vector<float>> tscores(nthreads, std::vector<float>(k));
  std::vector<std::vector<int64_t>> trows(nthreads, std::vector<int64_t>(k));
  std::vector<TopKHeap> heaps(nthreads);
  std::vector<std::thread> workers;
  int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int32_t ti = 0; ti < nthreads; ti++) {
    heaps[ti] = TopKHeap{tscores[ti].data(), trows[ti].data(), k, 0};
    int64_t lo = ti * chunk, hi = std::min(n, lo + chunk);
    workers.emplace_back(topk_range, emb, alive, query, d, lo, hi, inv_qnorm,
                         &heaps[ti]);
  }
  for (auto& w : workers) w.join();

  TopKHeap merged{out_scores, out_rows, k, 0};
  for (int32_t i = 0; i < k; i++) {  // reset sentinel fill before merging
    out_scores[i] = -1e30f;
    out_rows[i] = -1;
  }
  for (int32_t ti = 0; ti < nthreads; ti++)
    for (int32_t i = 0; i < heaps[ti].size; i++)
      merged.push(tscores[ti][i], trows[ti][i]);

  // Heap → descending order (stable tie-break on row asc for determinism).
  struct Pair {
    float s;
    int64_t r;
  };
  std::vector<Pair> pairs(merged.size);
  for (int32_t i = 0; i < merged.size; i++) pairs[i] = {out_scores[i], out_rows[i]};
  std::sort(pairs.begin(), pairs.end(), [](const Pair& a, const Pair& b) {
    if (a.s != b.s) return a.s > b.s;
    return a.r < b.r;
  });
  for (int32_t i = 0; i < k; i++) {
    if (i < (int32_t)pairs.size()) {
      out_scores[i] = pairs[i].s;
      out_rows[i] = pairs[i].r;
    } else {
      out_scores[i] = -1e30f;
      out_rows[i] = -1;
    }
  }
}

// ---------------------------------------------------------------------------
// Write-ahead log.
//
// On-disk framing per record: u32 magic 'LZW1' | u32 payload_len |
// u32 crc32(payload) | payload bytes. Append is a single write(2) followed by
// fdatasync, so a crash mid-append leaves at most one torn tail record, which
// replay detects (bad magic/len/crc) and discards.
// ---------------------------------------------------------------------------

static const uint32_t LZ_WAL_MAGIC = 0x4c5a5731u;  // "LZW1" little-endian

static uint32_t crc32_update(uint32_t crc, const uint8_t* p, size_t len) {
  static uint32_t table[256];
  static std::atomic<bool> ready{false};
  if (!ready.load(std::memory_order_acquire)) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int j = 0; j < 8; j++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    ready.store(true, std::memory_order_release);
  }
  crc = ~crc;
  for (size_t i = 0; i < len; i++) crc = table[(crc ^ p[i]) & 0xff] ^ (crc >> 8);
  return ~crc;
}

uint32_t lz_crc32(const uint8_t* p, int64_t len) {
  return crc32_update(0, p, (size_t)len);
}

// Appends one record; returns 0 on success, negative errno-style code on
// failure. do_fsync=1 makes the record durable before returning.
int64_t lz_wal_append(const char* path, const uint8_t* data, int64_t len,
                      int32_t do_fsync) {
  int fd = open(path, O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (fd < 0) return -1;
  uint32_t header[3] = {LZ_WAL_MAGIC, (uint32_t)len,
                        crc32_update(0, data, (size_t)len)};
  std::vector<uint8_t> buf(sizeof(header) + (size_t)len);
  memcpy(buf.data(), header, sizeof(header));
  if (len > 0) memcpy(buf.data() + sizeof(header), data, (size_t)len);
  const uint8_t* p = buf.data();
  size_t remaining = buf.size();
  while (remaining > 0) {
    ssize_t w = write(fd, p, remaining);
    if (w < 0) {
      close(fd);
      return -2;
    }
    p += w;
    remaining -= (size_t)w;
  }
  int rc = 0;
  if (do_fsync && fdatasync(fd) != 0) rc = -3;
  close(fd);
  return rc;
}

// Loads all valid records. Returns a malloc'd buffer of concatenated
// (u32 len | payload) entries and sets *out_len to its size; caller frees via
// lz_free. Returns nullptr with *out_len = -1 if the file doesn't exist,
// *out_len = 0 for an empty/fully-torn log. Scanning stops at the first
// invalid record (torn tail).
uint8_t* lz_wal_load(const char* path, int64_t* out_len) {
  *out_len = -1;
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  fseek(f, 0, SEEK_END);
  long fsize = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> raw((size_t)fsize);
  if (fsize > 0 && fread(raw.data(), 1, (size_t)fsize, f) != (size_t)fsize) {
    fclose(f);
    *out_len = 0;
    return nullptr;
  }
  fclose(f);

  std::vector<uint8_t> out;
  size_t pos = 0;
  while (pos + 12 <= raw.size()) {
    uint32_t magic, len, crc;
    memcpy(&magic, raw.data() + pos, 4);
    memcpy(&len, raw.data() + pos + 4, 4);
    memcpy(&crc, raw.data() + pos + 8, 4);
    if (magic != LZ_WAL_MAGIC || pos + 12 + len > raw.size()) break;
    if (crc32_update(0, raw.data() + pos + 12, len) != crc) break;
    uint32_t len_le = len;
    out.insert(out.end(), (uint8_t*)&len_le, (uint8_t*)&len_le + 4);
    out.insert(out.end(), raw.data() + pos + 12, raw.data() + pos + 12 + len);
    pos += 12 + len;
  }
  *out_len = (int64_t)out.size();
  if (out.empty()) return nullptr;
  uint8_t* ret = (uint8_t*)malloc(out.size());
  memcpy(ret, out.data(), out.size());
  return ret;
}

void lz_free(uint8_t* p) { free(p); }

// Truncates (resets) the log; returns 0 on success.
int64_t lz_wal_reset(const char* path) {
  int fd = open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  close(fd);
  return 0;
}

int32_t lz_abi_version() { return 1; }

}  // extern "C"
