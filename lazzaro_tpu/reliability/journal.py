"""Durable ingest journal: extracted facts survive any crash window.

The turn-level WAL (``native.WriteAheadLog`` driven by MemorySystem's
``_journal_sync``) already guarantees no *turn* is lost — but turns are
raw conversation text: replaying them re-runs the LLM extraction, and the
extraction → coalescer → fused-dispatch window used to be the one place
extracted FACTS existed only in process memory. A crash between buffering
and the fused ingest dispatch meant re-paying the LLM call at best and —
if the source turns had already been retired — losing facts outright.

``IngestJournal`` closes that window with the classic append → dispatch →
commit discipline over the same CRC-framed record format as the turn WAL:

- ``append(facts)`` durably logs one conversation's extracted facts the
  moment extraction returns (BEFORE they enter the coalescer), assigning
  a monotonically increasing sequence number;
- ``commit(seq)`` appends a commit marker once every fact up to ``seq``
  has landed in the arena (the coalescer drains everything, so one
  marker retires the whole drain);
- ``pending()`` replays the log tolerantly (torn tail dropped by the CRC
  framing) and returns the uncommitted batches in append order — the
  startup path feeds them back through the normal ingest, where the
  EXISTING in-dispatch dedup probe makes replay idempotent: facts that
  did land before the crash resolve as duplicates, facts that didn't are
  ingested now. Zero lost facts, zero double-ingest.

The log compacts (resets to empty) whenever a commit retires everything
outstanding, so steady-state size is one drain's worth of facts.

Replica serving (ISSUE 18) layers on the same discipline without any
format change: a replica group is just a journal SUBSCRIBER. Writes
apply to a primary group through the normal fused ingest, then each
other group replays the same ``(seq, facts)`` batches through its own
normal path (idempotent via the in-dispatch dedup probe); the placement
layer keeps a per-group applied-seq cursor and only ``commit()``s once
EVERY group has applied. ``append()`` additionally stamps an in-memory
wall-clock per seq so ``oldest_age()`` / ``lag()`` can measure the
bounded-staleness window (``serve_replica_staleness_s``) and the
``journal.replica_lag`` gauge — purely in-memory observability, never
persisted (a restart re-replays pending batches anyway).

Overlay-tenant registration IS persisted: ``register_overlay()``
appends an ``{"op": "overlay"}`` record that survives ``commit()``
(compaction rewrites the registrations into the fresh log), so a new
process rebuilds the overlay-tenant set from ``overlay_tenants`` and a
previously-overlay tenant keeps partitioning — its reads keep pinning
to the home group and its future writes never replicate fleet-wide.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Tuple

from lazzaro_tpu.native import WriteAheadLog


class IngestJournal:
    """Append/commit journal of extracted-fact batches (one per
    conversation), built on the CRC-framed WAL."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self._wal = WriteAheadLog(path, fsync=fsync)
        self._lock = threading.Lock()
        self._pending: Dict[int, List[dict]] = {}
        # seq -> append wall-time (in-memory only; staleness observability
        # for replica subscribers — see the module docstring)
        self._append_ts: Dict[int, float] = {}
        # durable overlay-tenant registrations (survive commit/compaction)
        self._overlays: set = set()
        self._next_seq = 1
        self._replay_into_memory()

    # ------------------------------------------------------------- internal
    def _replay_into_memory(self) -> None:
        pending: Dict[int, List[dict]] = {}
        committed = 0
        for payload in self._wal.replay():
            try:
                rec = json.loads(payload.decode("utf-8"))
            except (UnicodeDecodeError, json.JSONDecodeError):
                continue                      # foreign/garbled record
            if not isinstance(rec, dict):
                continue
            op = rec.get("op")
            seq = int(rec.get("seq", 0))
            if op == "add" and isinstance(rec.get("facts"), list):
                pending[seq] = rec["facts"]
            elif op == "commit":
                committed = max(committed, seq)
            elif op == "overlay" and isinstance(rec.get("tenant"), str):
                self._overlays.add(rec["tenant"])
        self._pending = {s: f for s, f in pending.items() if s > committed}
        top = max(pending.keys(), default=0)
        self._next_seq = max(top, committed) + 1

    # ------------------------------------------------------------------ api
    def append(self, facts: List[dict]) -> int:
        """Durably log one conversation's extracted facts; returns the
        assigned sequence number (0 when there is nothing to log)."""
        facts = [f for f in facts if isinstance(f, dict)]
        if not facts:
            return 0
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._wal.append(json.dumps(
                {"op": "add", "seq": seq, "facts": facts}).encode("utf-8"))
            self._pending[seq] = facts
            self._append_ts[seq] = time.time()
            return seq

    def commit(self, seq: int) -> None:
        """Mark every batch with sequence <= ``seq`` as durably ingested.
        Compacts the log file when nothing is left outstanding."""
        if seq <= 0:
            return
        with self._lock:
            for s in [s for s in self._pending if s <= seq]:
                del self._pending[s]
            for s in [s for s in self._append_ts if s <= seq]:
                del self._append_ts[s]
            if not self._pending:
                # everything retired: truncating IS the commit record —
                # but overlay registrations must outlive compaction, so
                # rewrite them into the fresh log
                self._wal.reset()
                for tenant in sorted(self._overlays):
                    self._wal.append(json.dumps(
                        {"op": "overlay",
                         "tenant": tenant}).encode("utf-8"))
            else:
                self._wal.append(json.dumps(
                    {"op": "commit", "seq": seq}).encode("utf-8"))

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._next_seq - 1

    @property
    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    @property
    def pending_facts(self) -> int:
        with self._lock:
            return sum(len(f) for f in self._pending.values())

    def pending(self) -> List[Tuple[int, List[dict]]]:
        """Uncommitted (seq, facts) batches in append order — the startup
        replay set (and each replica subscriber's replay feed, filtered
        past its applied-seq cursor)."""
        with self._lock:
            return sorted(self._pending.items())

    # --------------------------------------------------- replica placement
    def register_overlay(self, tenant: str) -> None:
        """Durably mark ``tenant`` as overlay (partitioned, home-group
        only). The registration survives commit/compaction and restarts,
        so placement stays correct for the tenant's whole lifetime."""
        with self._lock:
            if tenant in self._overlays:
                return
            self._overlays.add(tenant)
            self._wal.append(json.dumps(
                {"op": "overlay", "tenant": tenant}).encode("utf-8"))

    @property
    def overlay_tenants(self) -> set:
        """Copy of the durably-registered overlay tenants (rebuilt from
        the log on startup)."""
        with self._lock:
            return set(self._overlays)

    # ------------------------------------------------- replica observability
    def lag(self, applied_seq: int) -> int:
        """How many appended batches a subscriber at ``applied_seq`` has
        not yet applied — the ``journal.replica_lag`` gauge per group."""
        with self._lock:
            return sum(1 for s in self._pending if s > applied_seq)

    def oldest_age(self, applied_seq: int, now: float = None) -> float:
        """Age (seconds) of the OLDEST appended batch a subscriber at
        ``applied_seq`` has not yet applied — 0.0 when fully caught up.
        This is the measured bounded-staleness window a replica group
        exposes (compare against ``serve_replica_staleness_s``). Batches
        appended before this process started carry no timestamp and
        count as age 0 (they are replayed immediately on startup)."""
        now = time.time() if now is None else now
        with self._lock:
            ts = [self._append_ts[s] for s in self._pending
                  if s > applied_seq and s in self._append_ts]
            if not ts:
                return 0.0
            return max(0.0, now - min(ts))

    def reset(self) -> None:
        with self._lock:
            self._pending.clear()
            self._append_ts.clear()
            self._overlays.clear()
            self._wal.reset()
