"""Donation-safe guarded dispatch execution.

Donation is what makes the fused hot paths zero-copy (PR 1), but it also
means a dispatch that fails mid-flight may have already consumed the ONLY
copy of the arena: after ``jit(donate_argnums=0)`` raises, the input
pytree's buffers are either intact (the failure happened before execution
— tracing error, injected fault, host OOM building an operand) or deleted
(the runtime consumed them before dying). The two cases need opposite
treatment, and conflating them is how a transient error becomes silent
state loss:

- **Input intact** → the failure was transient from the state's point of
  view. Retry through the *non-donating* ``*_copy`` twin (bounded, with
  backoff): the copy twin cannot consume the input, so a retry can never
  make things worse, and a success leaves the index exactly where the
  donated dispatch would have. Each retry bumps
  ``serve.dispatch_retries{mode,reason}``.
- **Input intact but RESOURCE_EXHAUSTED** → not a transient at all: the
  identical geometry re-fails identically, so retrying is pure waste.
  Reclassified (ISSUE 11) into the typed
  :class:`~lazzaro_tpu.reliability.errors.DeviceOom` immediately — the
  serving/ingest wrappers answer with ONE planner replan (smaller
  sub-dispatches / chunked scan, through the copy twins) and give up
  typed (``PlanInfeasible``) if that fails too.
- **Input consumed ("poisoned")** → there is nothing left to retry with.
  Raise :class:`~lazzaro_tpu.reliability.errors.ArenaPoisoned` so the
  caller marks the index poisoned and every later touch fails typed and
  fast instead of surfacing XLA's "Array has been deleted" from a random
  depth; recovery is checkpoint restore + ingest-journal replay
  (``reliability.poisoned`` counts these).

``run_guarded`` is the one implementation both donation gates use
(``core.index.MemoryIndex`` and ``parallel.index.ShardedMemoryIndex``);
the fault point ``index.dispatch`` fires per attempt inside it, which is
how the recovery matrix drives both branches deterministically.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

from lazzaro_tpu.reliability import faults
from lazzaro_tpu.reliability.errors import (ArenaPoisoned, DeviceOom,
                                            ReliabilityError)

# Substrings that identify an HBM allocation failure across backends: the
# gRPC/XLA status name, the PJRT message text, and the CUDA/TPU allocator
# phrasing. Matching on text is deliberate — jaxlib's XlaRuntimeError does
# not subclass per-status, and the fault injector raises plain RuntimeErrors
# carrying the same marker.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM when allocating", "Resource exhausted")


def is_resource_exhausted(e: BaseException) -> bool:
    """True when ``e`` is an HBM allocation failure (or the typed
    :class:`DeviceOom` it gets reclassified into). These are NON-transient:
    the identical geometry re-fails identically, so they route to the
    planner (split/chunk) instead of the retry ladder."""
    if isinstance(e, DeviceOom):
        return True
    msg = f"{type(e).__name__}: {e}"
    return any(m in msg for m in _OOM_MARKERS)


def is_poisoned(states: Sequence) -> bool:
    """True when any device leaf of the given pytrees has been deleted
    (a failed donated dispatch consumed it)."""
    import jax

    for tree in states:
        if tree is None:
            continue
        for leaf in jax.tree_util.tree_leaves(tree):
            if hasattr(leaf, "is_deleted"):
                try:
                    if leaf.is_deleted():
                        return True
                except Exception:   # noqa: BLE001 — conservative: unknown
                    return True     # buffer state counts as unusable
    return False


def run_guarded(call: Callable, donated: Callable, copying: Callable,
                sole: bool, states: Sequence, *, telemetry=None,
                mode: str = "mutate", retries: int = 2,
                backoff_s: float = 0.005,
                fault_point: str = "index.dispatch"):
    """Execute one state dispatch with donation-safe recovery.

    ``call(fn)`` must invoke ``fn`` on the captured state + args;
    ``donated``/``copying`` are the twin kernels and ``sole`` is the
    refcount gate's verdict (computed by the caller BEFORE building the
    ``call`` closure — the closure itself holds a reference). ``states``
    are the pytrees a failed donated dispatch may have consumed; they are
    probed after every failure and an intact state is retried through the
    copying twin only. Raises :class:`ArenaPoisoned` when the state is
    gone, or the last error when retries are exhausted."""
    fn = donated if sole else copying
    attempt = 0
    while True:
        try:
            faults.fire(fault_point, states=states, mode=mode,
                        attempt=attempt)
            return call(fn)
        except ArenaPoisoned:
            raise
        except Exception as e:               # noqa: BLE001 — typed below
            if is_poisoned(states):
                if telemetry is not None:
                    telemetry.bump("reliability.poisoned",
                                   labels={"mode": mode})
                raise ArenaPoisoned(
                    f"donated {mode} dispatch failed after consuming its "
                    f"input ({type(e).__name__}: {e}); restore from "
                    f"checkpoint and replay the ingest journal") from e
            if is_resource_exhausted(e):
                # ISSUE 11: RESOURCE_EXHAUSTED is NOT a transient — the
                # identical geometry re-fails identically, so retry-with-
                # backoff just burns the budget re-failing. Reclassify
                # typed so the serving/ingest wrappers can plan-and-
                # rechunk (one replan through the copy twins) instead.
                if telemetry is not None:
                    telemetry.bump("reliability.oom",
                                   labels={"mode": mode})
                raise DeviceOom(
                    f"{mode} dispatch exhausted device memory "
                    f"({type(e).__name__}: {e}); replan the geometry "
                    f"(split the batch / chunk the scan) instead of "
                    f"retrying it") from e
            if attempt >= retries:
                raise
            if telemetry is not None:
                telemetry.bump("serve.dispatch_retries",
                               labels={"mode": mode,
                                       "reason": type(e).__name__})
            time.sleep(backoff_s * (2 ** attempt))
            attempt += 1
            fn = copying          # never donate on a retry


def check_not_poisoned(flag: bool, what: str = "index") -> None:
    """Entry-point guard: raise typed-and-fast on a poisoned index."""
    if flag:
        raise ArenaPoisoned(
            f"{what} is poisoned (a donated dispatch consumed its state "
            f"and failed); restore from checkpoint and replay the ingest "
            f"journal")


__all__ = ["is_poisoned", "is_resource_exhausted", "run_guarded",
           "check_not_poisoned", "ArenaPoisoned", "DeviceOom",
           "ReliabilityError"]
