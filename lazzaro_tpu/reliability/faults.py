"""Named fault-injection points for the recovery matrix.

Production code calls :func:`fire` at a handful of *named* points — the
places where the failure model says a crash hurts most. When nothing is
armed (always, in production) ``fire`` is one attribute load and a falsy
check; when a test or the bench's recovery stage arms a point, the next
``fire`` there runs the plan's hook (e.g. poison the donated state,
truncate a checkpoint file) and/or raises, a bounded number of times.
This is how the CI'd recovery matrix drives every failure mode
deterministically instead of hoping a race reproduces.

Injection points (grep for ``faults.fire`` to find the exact sites):

====================  =====================================================
``index.dispatch``    inside the guarded donation gate, per attempt, just
                      before the device call (core + pod index)
``scheduler.worker``  QueryScheduler worker loop, after batch admission,
                      OUTSIDE the demuxed executor try — a raise here is a
                      worker-thread death, not a demuxed executor error
``ingest.worker``     MemorySystem._async_consolidate, between journal
                      append and the fused ingest dispatches
``pump.mid_chunk``    TierManager.demote_rows, after the cold-store commit
                      and before the hot zero-scatter
``checkpoint.torn``   checkpoint._write_versioned_rank0, after the CURRENT
                      flip — the hook corrupts the committed payload to
                      model a torn write the filesystem lied about
``coldstore.read``    ColdStore.gather, before copying rows out
``plan.oom``          the fused serving dispatch region (single-chip and
                      pod index), just before the device call — arm with
                      ``exc=oom_error`` to model an HBM allocation
                      failure (``RESOURCE_EXHAUSTED``) the admission
                      planner's prediction missed; recovery is ONE
                      replan into split sub-dispatches via the copy
                      twins (ISSUE 11)
``replica.mid_replay``  ReplicaPlacement.replicate, between a
                      subscriber group's per-batch ingest replays — a
                      raise here models the fan-out dying with the
                      journal batch applied on SOME groups but not
                      committed; recovery is the journal replay on the
                      next write/catch-up, idempotent via the
                      in-dispatch dedup probe (ISSUE 18)
====================  =====================================================

Arming is process-global (the injected sites live on background threads),
guarded by a lock, and always bounded: a plan fires ``times`` times then
disarms itself, so a forgotten ``armed()`` context can never wedge a
suite. The injected exception defaults to :class:`InjectedFault` so tests
can assert the failure they see is *theirs*.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from typing import Callable, Dict, Optional

from lazzaro_tpu.reliability.errors import ReliabilityError


class InjectedFault(ReliabilityError):
    """Default exception raised at an armed injection point."""


class _Plan:
    __slots__ = ("point", "times", "exc", "hook", "fired")

    def __init__(self, point: str, times: int,
                 exc: Optional[Callable[[], BaseException]],
                 hook: Optional[Callable[[dict], None]]):
        self.point = point
        self.times = int(times)
        self.exc = exc
        self.hook = hook
        self.fired = 0


class FaultInjector:
    """Registry of armed fault plans (one per point)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._plans: Dict[str, _Plan] = {}
        self._fired: Dict[str, int] = {}
        # Fast-path flag read without the lock: fire() is on every hot
        # dispatch, so the disarmed cost must be a single falsy check.
        self.active = False

    def arm(self, point: str, times: int = 1, *,
            exc: Optional[Callable[[], BaseException]] = InjectedFault,
            hook: Optional[Callable[[dict], None]] = None) -> None:
        """Arm ``point`` to fail the next ``times`` visits. ``exc=None``
        makes the fault silent (hook-only — e.g. corrupt a file and let
        the caller believe the write succeeded)."""
        with self._lock:
            self._plans[point] = _Plan(point, times, exc, hook)
            self.active = True

    def disarm(self, point: str) -> None:
        with self._lock:
            self._plans.pop(point, None)
            self.active = bool(self._plans)

    def clear(self) -> None:
        with self._lock:
            self._plans.clear()
            self._fired.clear()
            self.active = False

    def fired(self, point: str) -> int:
        """How many times ``point`` actually fired (survives disarm)."""
        with self._lock:
            return self._fired.get(point, 0)

    def fire(self, point: str, **ctx) -> None:
        """Called by production code at a named injection point. No-op
        unless the point is armed; otherwise runs the hook and raises the
        planned exception (``times``-bounded)."""
        if not self.active:
            return
        with self._lock:
            plan = self._plans.get(point)
            if plan is None or plan.times <= 0:
                return
            plan.times -= 1
            plan.fired += 1
            self._fired[point] = self._fired.get(point, 0) + 1
            if plan.times <= 0:
                self._plans.pop(point, None)
                self.active = bool(self._plans)
            hook, exc = plan.hook, plan.exc
        # hook/raise outside the lock: hooks touch files and device state
        if hook is not None:
            hook(ctx)
        if exc is not None:
            raise exc()

    @contextmanager
    def armed(self, point: str, times: int = 1, *,
              exc: Optional[Callable[[], BaseException]] = InjectedFault,
              hook: Optional[Callable[[dict], None]] = None):
        """Scoped arming; always disarms on exit."""
        self.arm(point, times, exc=exc, hook=hook)
        try:
            yield self
        finally:
            self.disarm(point)


# Process-wide injector: the injected sites run on background actor
# threads, so the registry must be shared the way the telemetry default
# registry is.
INJECTOR = FaultInjector()


def fire(point: str, **ctx) -> None:
    """Module-level fast path (the one production sites call)."""
    if INJECTOR.active:
        INJECTOR.fire(point, **ctx)


# --------------------------------------------------------------------- hooks
def oom_error() -> BaseException:
    """Exception factory for ``plan.oom`` / ``index.dispatch`` arming: a
    plain RuntimeError carrying the XLA allocator's RESOURCE_EXHAUSTED
    marker, so ``guard.is_resource_exhausted`` classifies it exactly like
    a real HBM allocation failure (jaxlib's XlaRuntimeError cannot be
    constructed portably from Python)."""
    return RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 1073741824 bytes "
        "(injected by reliability.faults.oom_error)")


def poison_states_hook(ctx: dict) -> None:
    """Hook for ``index.dispatch``: delete the donated state's device
    buffers before raising, so the failure models a dispatch that died
    AFTER consuming its donated input (the poisoned-arena case)."""
    import jax

    for tree in ctx.get("states", ()):
        for leaf in jax.tree_util.tree_leaves(tree):
            if hasattr(leaf, "delete") and not leaf.is_deleted():
                leaf.delete()


def torn_write_hook(keep_bytes: int = 256) -> Callable[[dict], None]:
    """Hook factory for ``checkpoint.torn``: truncate the committed
    ``arrays.npz`` to ``keep_bytes`` — the classic torn write (CURRENT
    points at the version, the payload is garbage)."""
    def _hook(ctx: dict) -> None:
        path = os.path.join(ctx["dir"], "arrays.npz")
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(min(keep_bytes, size))
    return _hook
