"""Reliability layer for the fused serving/ingest stack (ISSUE 10).

Four pieces, spanning the donation machinery, all three async actors
(QueryScheduler, IngestCoalescer's consolidation worker, TierPump), and
durability:

- :mod:`~lazzaro_tpu.reliability.guard` — donation-safe dispatch
  execution: poisoning detection after a failed donated dispatch,
  bounded copy-twin retries, typed :class:`ArenaPoisoned`.
- :mod:`~lazzaro_tpu.reliability.watchdog` — the serving circuit
  breaker behind the QueryScheduler's dispatch deadlines and
  degradation ladder.
- :mod:`~lazzaro_tpu.reliability.journal` — the durable ingest journal
  (append → dispatch → commit; idempotent replay via the dedup probe).
- :mod:`~lazzaro_tpu.reliability.faults` — named fault-injection points
  driving the CI'd recovery matrix (tests/test_fault_injection.py).

Typed errors live in :mod:`~lazzaro_tpu.reliability.errors`; an actor
that fails does so with one of them, never by hanging a future.
"""

from lazzaro_tpu.reliability.errors import (ArenaPoisoned,
                                            CheckpointCorrupt,
                                            ColdReadError, DeviceOom,
                                            DispatchTimeout, LoadShed,
                                            PlanInfeasible,
                                            ReliabilityError,
                                            WorkerCrashed)
from lazzaro_tpu.reliability import faults
from lazzaro_tpu.reliability.guard import (check_not_poisoned, is_poisoned,
                                           is_resource_exhausted,
                                           run_guarded)
from lazzaro_tpu.reliability.journal import IngestJournal
from lazzaro_tpu.reliability.watchdog import CircuitBreaker

__all__ = [
    "ReliabilityError", "ArenaPoisoned", "DispatchTimeout", "LoadShed",
    "WorkerCrashed", "CheckpointCorrupt", "ColdReadError", "DeviceOom",
    "PlanInfeasible",
    "run_guarded", "is_poisoned", "is_resource_exhausted",
    "check_not_poisoned", "IngestJournal", "CircuitBreaker", "faults",
]
