"""Typed failure vocabulary for the serving/ingest reliability layer.

Every failure mode an actor can surface to a caller gets its own exception
class, so callers (and tests) can branch on *what* went wrong instead of
string-matching a generic RuntimeError — and so a future that fails does
it with a diagnosis, never by hanging. The taxonomy mirrors the failure
model in README "Failure model & recovery":

- :class:`ArenaPoisoned` — a donated dispatch failed AFTER consuming its
  input buffers; the in-HBM state is gone and only checkpoint restore +
  journal replay can bring the index back. Every subsequent mutation and
  serve raises this immediately instead of surfacing XLA's generic
  "Array has been deleted".
- :class:`DispatchTimeout` — the per-dispatch watchdog deadline expired;
  the affected requests' futures fail with this while the stuck dispatch
  is left to finish (its results are discarded) and the circuit breaker
  records the failure.
- :class:`LoadShed` — admission control refused the request outright
  (queue depth or byte budget exceeded). Callers should back off; the
  device never saw the request.
- :class:`WorkerCrashed` — an actor's worker thread died outside the
  demuxed dispatch path; in-flight futures fail with this and the worker
  restarts.
- :class:`CheckpointCorrupt` — a checkpoint payload failed its checksum
  or could not be decoded (torn write, bit rot); raised instead of
  loading garbage.
- :class:`ColdReadError` — the host cold tier could not produce bytes
  for a row the residency column says it owns.
- :class:`DeviceOom` — a dispatch failed with ``RESOURCE_EXHAUSTED``
  (HBM allocation). NON-transient by definition: retrying the identical
  geometry re-fails identically, so the guard raises this instead of
  burning the retry budget; the serving/ingest wrappers answer with ONE
  replan (smaller sub-dispatches / a chunked arena scan, through the
  copy twins) before giving up typed.
- :class:`PlanInfeasible` — the admission-time HBM planner
  (``lazzaro_tpu.plan``) found NO split of the requested geometry that
  fits ``hbm_budget_bytes`` minus headroom (or a post-OOM replan
  re-failed). Shed like :class:`LoadShed`: raised at admission or
  resolved into the request futures, never by hanging them.
"""

from __future__ import annotations


class ReliabilityError(RuntimeError):
    """Base class for every typed reliability failure."""


class ArenaPoisoned(ReliabilityError):
    """A donated dispatch consumed its input state and then failed —
    the live arena/edge buffers are gone. Recover by reloading the last
    checkpoint and replaying the ingest journal."""


class DispatchTimeout(ReliabilityError):
    """The dispatch watchdog deadline expired for this request's batch."""


class LoadShed(ReliabilityError):
    """Admission control rejected the request before it was queued."""


class WorkerCrashed(ReliabilityError):
    """The owning actor's worker thread died; the request was failed
    rather than left to block forever. The worker restarts automatically."""


class CheckpointCorrupt(ReliabilityError):
    """Checkpoint payload failed checksum/decoding — refusing to load."""


class ColdReadError(ReliabilityError):
    """The cold tier failed to produce a row it is marked as owning."""


class DeviceOom(ReliabilityError):
    """A dispatch failed allocating HBM (``RESOURCE_EXHAUSTED``). Not a
    transient: the same geometry re-fails identically, so the response is
    a replan (split/chunk through the planner), never a backoff retry."""


class PlanInfeasible(ReliabilityError):
    """No batch split or scan chunking fits this geometry inside the HBM
    budget (``hbm_budget_bytes`` minus headroom) — the request is shed
    before (or instead of) compiling a program that would OOM."""
