"""Circuit breaker for the serving dispatch path.

Under sustained pressure (dispatch timeouts, repeated executor failures)
the right move at fleet scale is to *degrade*, not to keep feeding a
struggling device full-cost work: Pancake's agent-fleet framing makes
overload the normal operating regime, and a breaker that sheds to a
cheaper serving mode keeps tail latency bounded while the device
recovers. States are the classic three:

- **closed** — healthy; every success resets the failure streak.
- **open** — ``threshold`` consecutive failures tripped it; for
  ``cooldown_s`` the scheduler serves every batch in DEGRADED mode
  (reduced per-request ``nprobe``/``cap_take`` — cheaper device work,
  same k results; see ``QueryScheduler._degrade_batch``).
- **half-open** — cooldown elapsed; the next batch probes at full
  quality. Success closes the breaker, failure re-opens it with a fresh
  cooldown.

The breaker never *rejects* work (that is admission control's job —
``QueryScheduler`` shed budgets); it only picks the degradation rung.
``reliability.breaker_state`` gauges the state (0 closed / 1 half-open /
2 open), ``reliability.breaker_opens`` counts trips.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    def __init__(self, threshold: int = 5, cooldown_s: float = 5.0,
                 telemetry=None, name: str = "serve"):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.telemetry = telemetry
        self.name = name
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self.opens = 0

    def _gauge(self) -> None:
        if self.telemetry is not None:
            self.telemetry.gauge("reliability.breaker_state",
                                 _STATE_CODE[self._state],
                                 labels={"name": self.name})

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def degraded(self, now: Optional[float] = None) -> bool:
        """Should the next batch run in degraded mode? OPEN inside the
        cooldown → yes; cooldown elapsed → transition to HALF_OPEN and
        probe at full quality."""
        now = time.time() if now is None else now
        with self._lock:
            if self._state == OPEN:
                if now - self._opened_at >= self.cooldown_s:
                    self._state = HALF_OPEN
                    self._gauge()
                    return False
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state != CLOSED:
                self._state = CLOSED
                self._gauge()

    def record_failure(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        with self._lock:
            self._failures += 1
            if (self._state == HALF_OPEN
                    or self._failures >= self.threshold):
                if self._state != OPEN:
                    self.opens += 1
                    if self.telemetry is not None:
                        self.telemetry.bump("reliability.breaker_opens",
                                            labels={"name": self.name})
                self._state = OPEN
                self._opened_at = now
                self._failures = 0
                self._gauge()

    def stats(self) -> dict:
        with self._lock:
            return {"state": self._state, "opens": self.opens,
                    "consecutive_failures": self._failures,
                    "threshold": self.threshold,
                    "cooldown_s": self.cooldown_s}
