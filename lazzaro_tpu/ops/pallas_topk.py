"""Pallas TPU kernel: fused masked cosine scoring + two-stage exact top-k.

The retrieval hot op (SURVEY §7.2). The XLA path materializes a [Q, N] f32
score matrix in HBM and runs a full-width ``lax.top_k`` over N (sort-network
heavy at N=1M). This kernel streams the embedding matrix through VMEM once,
blocks of BLK rows at a time: each grid step computes the block's scores on
the MXU, applies the alive/tenant mask additively, and keeps only the block's
top-K (iterative max-and-suppress on the VPU) — so HBM traffic is the
embedding read plus a tiny [nblocks, Q, K] candidate tensor, and the final
exact top-k runs over nblocks·K ≪ N candidates.

Use ``interpret=True`` (automatic on CPU) for tests.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e30


def _topk_block_kernel(k: int):
    def kernel(q_ref, emb_ref, madd_ref, out_s_ref, out_i_ref):
        blk_idx = pl.program_id(0)
        emb_blk = emb_ref[:]                        # [BLK, d]
        q = q_ref[:]                                # [Q, d]
        scores = jax.lax.dot_general(
            q, emb_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)     # [Q, BLK]
        scores = scores + madd_ref[:]               # additive mask [1, BLK]
        blk = scores.shape[1]
        col = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        base = blk_idx * blk
        for t in range(k):                          # iterative max-and-suppress
            m = jnp.max(scores, axis=1, keepdims=True)           # [Q, 1]
            hit = scores == m
            idx = jnp.min(jnp.where(hit, col, blk), axis=1,
                          keepdims=True)                          # first argmax
            out_s_ref[0, :, t] = m[:, 0]
            out_i_ref[0, :, t] = idx[:, 0] + base
            scores = jnp.where(col == idx, NEG, scores)
    return kernel


@functools.partial(jax.jit, static_argnames=("k", "block_rows", "interpret"))
def pallas_masked_topk(emb: jax.Array, madd: jax.Array, queries: jax.Array,
                       k: int = 10, block_rows: int = 4096,
                       interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """emb [N, d] (L2-normalized, N % block_rows == 0), madd [N] additive mask
    (0 alive / -1e30 dead), queries [Q, d]. Returns (scores [Q,k], rows [Q,k]).
    """
    n, d = emb.shape
    assert n % block_rows == 0, f"N={n} must be a multiple of {block_rows}"
    nblocks = n // block_rows
    q = queries.astype(emb.dtype)
    nq = q.shape[0]
    madd2 = madd.reshape(1, n).astype(jnp.float32)

    grid_spec = pl.GridSpec(
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((nq, d), lambda b: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, d), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_rows), lambda b: (0, b),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, nq, k), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nq, k), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
    )
    block_s, block_i = pl.pallas_call(
        _topk_block_kernel(k),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((nblocks, nq, k), jnp.float32),
            jax.ShapeDtypeStruct((nblocks, nq, k), jnp.int32),
        ],
        interpret=interpret,
    )(q, emb, madd2)

    # Stage 2: exact top-k over the nblocks*k candidates per query.
    cand_s = jnp.moveaxis(block_s, 0, 1).reshape(nq, nblocks * k)
    cand_i = jnp.moveaxis(block_i, 0, 1).reshape(nq, nblocks * k)
    top_s, pos = jax.lax.top_k(cand_s, k)
    top_i = jnp.take_along_axis(cand_i, pos, axis=1)
    return top_s, top_i


@functools.partial(jax.jit, static_argnames=("k", "block_rows", "interpret"))
def pallas_masked_topk_ragged(emb: jax.Array, madd: jax.Array,
                              queries: jax.Array, k_q: jax.Array,
                              k: int = 10, block_rows: int = 4096,
                              interpret: bool = False
                              ) -> Tuple[jax.Array, jax.Array]:
    """Ragged-K variant of the blocked scan (ISSUE 7): ``k`` is the STATIC
    batch ceiling the VMEM-streaming kernel computes to — the per-block
    max-and-suppress loop and the stage-2 candidate sort are compiled
    once per (geometry, ceiling) — and ``k_q`` ([Q] i32 device data) is
    each query's own k. Positions at or past a query's k come back as
    (NEG, -1), exactly the per-query ``top_k(k_i)`` result because the
    ceiling output is score-sorted. One compiled kernel therefore serves
    any mix of request k's ≤ the ceiling; mixed-k fleets stop burning a
    compile-cache entry per distinct k."""
    top_s, top_i = pallas_masked_topk(emb, madd, queries, k=k,
                                      block_rows=block_rows,
                                      interpret=interpret)
    live = jnp.arange(k)[None, :] < k_q[:, None]
    return jnp.where(live, top_s, NEG), jnp.where(live, top_i, -1)


def masked_topk_arena_ragged(emb: jax.Array, mask: jax.Array,
                             queries: jax.Array, k_q: jax.Array,
                             k: int = 10) -> Tuple[jax.Array, jax.Array]:
    """Ragged twin of :func:`masked_topk_arena`: boolean mask → additive
    mask, block size fitted to VMEM, per-query k as data against the
    static ``k`` ceiling."""
    n, d = emb.shape
    blk = fit_block_rows(n, d, emb.dtype.itemsize)
    assert blk, f"arena rows {n} have no VMEM-fitting block divisor >= 512"
    madd = jnp.where(mask, 0.0, NEG).astype(jnp.float32)
    on_tpu = jax.default_backend() in ("tpu", "axon")
    return pallas_masked_topk_ragged(emb, madd, queries.astype(emb.dtype),
                                     k_q, k=k, block_rows=blk,
                                     interpret=not on_tpu)


def masked_topk_auto(emb, madd, queries, k=10, block_rows=4096):
    """Dispatch: pallas on TPU, interpret-mode pallas elsewhere."""
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    return pallas_masked_topk(emb, madd, queries, k=k, block_rows=block_rows,
                              interpret=not on_tpu)


# One embedding block's VMEM budget: blocks are double-buffered and the
# scoped-vmem ceiling is 16 MB, so ~6 MB per block leaves room for the
# [Q, blk] f32 score tile and outputs (blk=8192 at d=768 OOMs — measured).
_BLOCK_BYTES = 6 * 1024 * 1024


def fit_block_rows(n: int, d: int, itemsize: int) -> int:
    """Largest power-of-two block ≤ 4096 that fits the VMEM budget AND
    divides ``n``; 0 when no block ≥ 512 divides n (caller falls back to the
    XLA path). Shared by the single-chip arena dispatch and the shard_map
    per-shard dispatch, whose local row counts are N/n_shards."""
    blk = 4096
    while blk > 512 and blk * d * itemsize > _BLOCK_BYTES:
        blk //= 2
    while blk >= 512 and n % blk != 0:
        blk //= 2
    return blk if blk >= 512 else 0


def masked_topk_arena(emb: jax.Array, mask: jax.Array, queries: jax.Array,
                      k: int = 10) -> Tuple[jax.Array, jax.Array]:
    """The ``arena_search`` serving path: boolean mask → additive mask, block
    size fitted to VMEM for the embedding dtype/width. Requires
    ``emb.shape[0] %% block == 0`` — arenas allocate row counts in
    ``state.TOPK_BLOCK`` multiples precisely so no padded copy of the matrix
    is ever made here."""
    n, d = emb.shape
    blk = fit_block_rows(n, d, emb.dtype.itemsize)
    assert blk, f"arena rows {n} have no VMEM-fitting block divisor >= 512"
    madd = jnp.where(mask, 0.0, NEG).astype(jnp.float32)
    on_tpu = jax.default_backend() in ("tpu", "axon")
    return pallas_masked_topk(emb, madd, queries.astype(emb.dtype),
                              k=k, block_rows=blk, interpret=not on_tpu)
