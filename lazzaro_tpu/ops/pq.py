"""IVF-PQ serving: member storage at m bytes per row + exact refinement.

The int8 shadow (ops/quant.py) halves scan bytes; product quantization
goes an order of magnitude further — the missing member of the serving-
mode family (VERDICT r4 "what's missing" #3; reference analog: LanceDB's
DEFAULT index family is IVF-PQ over the raw vectors,
vector_store.py:132-140, which this composes the same way: IVF coarse
routing from ops/ivf.py + PQ member scan + exact re-rank).

Geometry: split d dims into ``m`` subspaces of d/m dims; per-subspace
k-means learns 256 centroids; a row stores one byte per subspace
(codes [N, m] u8 — 96 bytes/row at 768-d/m=96 vs 1536 bytes bf16, 16×).
A query (1) scores the IVF centroids and picks ``nprobe`` clusters
exactly as the plain-IVF path does, (2) gathers the candidates' CODES
(~nprobe·N/C rows × m bytes instead of × d·2 bytes), scores them with a
per-query lookup table of partial dots (asymmetric distance), (3) takes
a top-R shortlist and REFINES: the shortlist's exact bf16 rows are
gathered from the master arena and re-scored, so the final top-k carries
EXACT scores — recall is set by the coarse probes and shortlist depth,
not by quantization error.

A deliberate non-goal is the flat (non-IVF) PQ scan: asymmetric-distance
over ALL rows is a per-row LUT gather, which the MXU has no use for —
on TPU the whole-arena alternatives are the one-matmul exact/int8 scans.
PQ earns its bytes exactly where LanceDB uses it: on the candidate set
behind the coarse stage, where the gather is thousands of rows, not
millions.

Like the int8 shadow, PQ state is a SERVING SHADOW over the mutable
master: codebooks train on a row sample (spherical geometry is
stationary under the system's mutations — new facts, not new geometry),
codes re-encode lazily when rows change, and threshold-gated callers
(dedup 0.95 / link 0.5) always bypass to the exact master.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp

from lazzaro_tpu.ops.chunking import chunked_map
from lazzaro_tpu.ops.ivf import NEG_INF, gather_candidates


@dataclass
class PQCodebook:
    centroids: jax.Array      # [m, 256, dsub] f32
    dim: int

    @property
    def m(self) -> int:
        return self.centroids.shape[0]

    @property
    def dsub(self) -> int:
        return self.centroids.shape[2]


@functools.partial(jax.jit, static_argnames=("iters",))
def _subspace_kmeans(x: jax.Array, init: jax.Array, iters: int) -> jax.Array:
    """Plain L2 k-means in one subspace. x: [S, dsub] sample rows,
    init: [256, dsub]. Empty clusters keep their previous centroid."""

    def step(cent, _):
        # assignment by L2: argmax(2·x·c - |c|²) — |x|² is constant per row
        scores = (2.0 * x @ cent.T
                  - jnp.sum(cent * cent, axis=1)[None, :])     # [S, 256]
        a = jnp.argmax(scores, axis=1)
        sums = jnp.zeros_like(cent).at[a].add(x)
        counts = jnp.zeros((cent.shape[0],), jnp.float32).at[a].add(1.0)
        new = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), cent)
        return new, None

    cent, _ = jax.lax.scan(step, init, None, length=iters)
    return cent


def train_pq(emb: jax.Array, mask_np: np.ndarray, m: int = None,
             sample: int = 65536, iters: int = 12, seed: int = 0
             ) -> PQCodebook:
    """Learn per-subspace codebooks from a row sample of the alive arena.

    ``m`` defaults to d/8 (dsub=8): ~0.5-1% cosine reconstruction error on
    unit rows — comfortably inside the serving top-k's refinement margin
    (the final ranking is exact anyway). Training cost is m small k-means
    over ≤``sample`` rows, a few hundred ms on either backend."""
    d = emb.shape[1]
    if m is None:
        # largest divisor of d with dsub >= 8 — embed_dim is configurable
        # (300-d GloVe etc.), so the default must never raise from the
        # background maintenance hook
        m = next((cand for cand in range(max(1, d // 8), 0, -1)
                  if d % cand == 0), 1)
    if d % m != 0:
        raise ValueError(f"dim {d} not divisible by m={m}")
    dsub = d // m
    alive_rows = np.nonzero(mask_np)[0]
    if len(alive_rows) == 0:
        raise ValueError("cannot train PQ over an empty arena")
    rng = np.random.default_rng(seed)
    if len(alive_rows) > sample:
        alive_rows = rng.choice(alive_rows, size=sample, replace=False)
    x = emb[jnp.asarray(np.sort(alive_rows))].astype(jnp.float32)  # [S, d]
    xs = x.reshape(x.shape[0], m, dsub)                            # [S, m, ds]

    n_init = min(256, x.shape[0])
    init_rows = rng.choice(x.shape[0], size=n_init, replace=False)
    if n_init < 256:
        init_rows = np.concatenate(
            [init_rows, rng.choice(x.shape[0], size=256 - n_init)])
    init = xs[jnp.asarray(init_rows)]                              # [256, m, ds]

    cents = jax.vmap(_subspace_kmeans, in_axes=(1, 1, None), out_axes=0)(
        xs, init, iters)                                           # [m, 256, ds]
    return PQCodebook(centroids=cents, dim=d)


@jax.jit
def encode_pq(book_cent: jax.Array, emb: jax.Array) -> jax.Array:
    """codes [N, m] u8: per-subspace nearest centroid (L2). One fused
    pass: m small [chunk, dsub]×[dsub, 256] matmuls per row chunk."""
    m, _, dsub = book_cent.shape
    cnorm = jnp.sum(book_cent * book_cent, axis=2)                 # [m, 256]

    def chunk(rows):
        x = emb[rows].astype(jnp.float32).reshape(rows.shape[0], m, dsub)
        scores = (2.0 * jnp.einsum("nmd,mkd->nmk", x, book_cent)
                  - cnorm[None, :, :])                             # [C, m, 256]
        return jnp.argmax(scores, axis=2).astype(jnp.uint8)

    return chunked_map(chunk, jnp.arange(emb.shape[0], dtype=jnp.int32))


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "r", "q_chunk"))
def ivf_pq_search(centroids: jax.Array, members: jax.Array,
                  residual: jax.Array, book_cent: jax.Array,
                  codes: jax.Array, emb: jax.Array, mask: jax.Array,
                  queries: jax.Array, k: int, nprobe: int = 8,
                  r: int = 128, q_chunk: int = 8
                  ) -> Tuple[jax.Array, jax.Array]:
    """Coarse (IVF centroids) → PQ member scan → exact refine, ONE dispatch.

    The candidate set comes from the SAME shared coarse stage as
    ``ops.ivf.ivf_search`` (``gather_candidates``), but only the MEMBER
    candidates are scored through their m-byte codes; the residual
    (fresh/overflow) rows go straight into the exact refine set, so the
    IVF freshness invariant — residual rows are scanned exactly — holds
    under PQ too, at the same gather cost the exact member scan already
    paid for them. The top-``r`` member shortlist plus the residual are
    re-scored EXACTLY from the bf16 master: returned scores match the
    exact path for every row the shortlist keeps."""
    q = queries.astype(jnp.float32)
    q = q / jnp.maximum(jnp.linalg.norm(q, axis=-1, keepdims=True), 1e-9)
    nprobe = min(nprobe, centroids.shape[0])
    m, _, dsub = book_cent.shape
    offs = jnp.arange(m, dtype=jnp.int32) * 256                    # [m]
    n_res = residual.shape[0]

    def chunk(q_c):                                                # [qc, d]
        qc = q_c.shape[0]
        cand, safe, valid = gather_candidates(centroids, members, residual,
                                              mask, q_c, nprobe)
        n_mem = cand.shape[1] - n_res                              # members
        # asymmetric distance over the MEMBER part: per-query LUT of
        # partial dots + code gather (m bytes per candidate row)
        qs = q_c.reshape(qc, m, dsub)
        lut = jnp.einsum("qmd,mkd->qmk", qs, book_cent)            # [qc, m, 256]
        flat_lut = lut.reshape(qc, -1)                             # [qc, m*256]
        idx = (codes[safe[:, :n_mem]].astype(jnp.int32)
               + offs[None, None, :])                              # [qc, Lm, m]
        s = jax.vmap(lambda fl, ix: jnp.take(fl, ix).sum(-1))(
            flat_lut, idx)                                         # [qc, Lm]
        s = jnp.where(valid[:, :n_mem], s, NEG_INF)

        # member shortlist ∪ residual → exact re-rank from the master
        r_eff = min(r, s.shape[1])
        _, pos = jax.lax.top_k(s, r_eff)
        short = jnp.concatenate(
            [jnp.take_along_axis(cand[:, :n_mem], pos, axis=1),
             cand[:, n_mem:]], axis=1)                             # [qc, R+Rres]
        s_safe = jnp.maximum(short, 0)
        vecs = emb[s_safe].astype(jnp.float32)                     # [qc, ., d]
        exact = jnp.einsum("qrd,qd->qr", vecs, q_c)
        ok = (short >= 0) & mask[s_safe]
        exact = jnp.where(ok, exact, NEG_INF)
        top_s, tpos = jax.lax.top_k(exact, min(k, short.shape[1]))
        return top_s, jnp.take_along_axis(short, tpos, axis=1)

    return chunked_map(chunk, q, chunk=q_chunk)
