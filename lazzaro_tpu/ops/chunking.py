"""Device-side streaming map — the "[chunk, N] tile bounds HBM" rule, once.

Whole-arena scans (search, linking, pairwise merge) score a [B, capacity+1]
f32 matrix; at 1M rows that is ~4 GB per 1k queries, and the naive all-pairs
form is ~4 TB. Every such kernel therefore streams row-chunks through
``lax.map`` INSIDE one jitted dispatch: HBM holds a single [chunk, N] tile
(512×1M×4 B ≈ 2 GB), while the host still pays exactly ONE round trip for
the whole batch (~70 ms each on the tunneled TPU backend, r4 measurement —
the reason the loop must not live host-side).

This module is that scaffold in one place; ``core/state.py`` and
``ops/graphops.py`` express their kernels as a per-chunk body and call
:func:`chunked_map`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# [QUERY_CHUNK, capacity+1] f32 is the HBM high-water mark of every arena
# scan — ~2 GB transient beside a 1.5 GB bf16 arena on a 16 GB chip.
QUERY_CHUNK = 512


def nt_dot(q: jax.Array, rows: jax.Array) -> jax.Array:
    """``q @ rows.T`` as a direct dim-1×dim-1 contraction.

    Numerically identical to ``jnp.dot(q, rows.T)`` and lowers to the same
    MXU contraction on TPU — but on the CPU fallback the explicit ``.T``
    lowers as transpose-then-dot, which misses the fast bf16 gemm path
    (measured 31 vs 128 GFLOP/s at [4096,768]×[262k,768] on this host).
    Every whole-arena scan scores through this helper."""
    return jax.lax.dot_general(q, rows, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def chunked_map_multi(fn, arrays, chunk: int = QUERY_CHUNK):
    """``chunked_map`` over SEVERAL same-leading-dim arrays at once.

    The fused retrieval kernel maps per-query metadata (tenant id, gate
    flag, boost flag) alongside the query rows; ``lax.map`` happily maps a
    tuple pytree, so the padding/reshape scaffold is the only thing this
    adds over :func:`chunked_map`."""
    b = arrays[0].shape[0]
    if b <= chunk:
        return fn(*arrays)
    nc = -(-b // chunk)

    def prep(a):
        pad = [(0, nc * chunk - b)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, pad).reshape((nc, chunk) + a.shape[1:])

    outs = jax.lax.map(lambda t: fn(*t), tuple(prep(a) for a in arrays))
    return jax.tree_util.tree_map(
        lambda o: o.reshape((nc * chunk,) + o.shape[2:])[:b], outs)


def chunked_map(fn, xs: jax.Array, chunk: int = QUERY_CHUNK):
    """Apply ``fn`` ([C, ...] → pytree of [C, ...]) to row-chunks of ``xs``.

    Traces into the CURRENT computation (no extra dispatch): small batches
    call ``fn`` directly; larger ones are zero-padded to a chunk multiple,
    streamed via ``lax.map``, and the padding rows are sliced back off every
    output leaf. Zero-padding is safe because callers discard the padded
    tail — pad rows just recompute row 0's answer."""
    b = xs.shape[0]
    if b <= chunk:
        return fn(xs)
    nc = -(-b // chunk)
    pad = [(0, nc * chunk - b)] + [(0, 0)] * (xs.ndim - 1)
    xs_p = jnp.pad(xs, pad).reshape((nc, chunk) + xs.shape[1:])
    outs = jax.lax.map(fn, xs_p)
    return jax.tree_util.tree_map(
        lambda o: o.reshape((nc * chunk,) + o.shape[2:])[:b], outs)
