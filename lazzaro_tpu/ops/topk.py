"""Retrieval kernels: masked cosine top-k, single-chip and mesh-sharded.

The mesh-sharded path is the TPU-native replacement for LanceDB ANN search
(reference ``vector_store.py:132-140``): the embedding matrix is row-sharded
across the mesh ('data' axis) so each chip scores its local rows on the MXU,
takes a local top-k, and the k·n_chips candidates are combined with one
``all_gather`` over ICI followed by a final top-k. For 1M×768 bf16 the whole
index is ~1.5 GB — resident in HBM across a v5e-8 with room to spare.

Replica-group serving (ISSUE 18) composes with every kernel here UNCHANGED:
each replica group holds a full arena copy row-sharded over a GROUP-LOCAL
sub-mesh (``parallel.mesh.replica_group_meshes``), so the ``axis`` these
merges bind is the group's own data axis — the ``all_gather`` spans only
the group's chips and never crosses groups. Scaling serving throughput by
adding groups therefore needs no new collective: the merge narrows
automatically because the mesh it was compiled against is narrower.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from lazzaro_tpu.utils.compat import shard_map

NEG_INF = -1e30


@functools.partial(jax.jit, static_argnames=("k",))
def masked_topk(emb: jax.Array, mask: jax.Array, query: jax.Array, k: int
                ) -> Tuple[jax.Array, jax.Array]:
    """Single-device masked cosine top-k. emb rows must be L2-normalized."""
    from lazzaro_tpu.ops.chunking import nt_dot

    q = jnp.atleast_2d(query).astype(emb.dtype)
    scores = nt_dot(q, emb)
    scores = jnp.where(mask[None, :], scores, NEG_INF)
    top_s, top_i = jax.lax.top_k(scores, k)
    if query.ndim == 1:
        return top_s[0], top_i[0]
    return top_s, top_i


def sharded_topk_merge(axis: str, top_s: jax.Array, top_i: jax.Array,
                       k: int, k_q: Optional[jax.Array] = None,
                       sentinel: int = -1) -> Tuple[jax.Array, jax.Array]:
    """The ONE cross-chip combine every sharded retrieval kernel shares:
    all_gather the per-chip candidate lists ``(top_s, top_i) [Q, k_local]``
    over the mesh ``axis`` and take a global top-``k`` of the
    ``n_shards · k_local`` candidates. Must be called INSIDE shard_map
    (or pmap) with ``axis`` bound. Candidate ids must already be
    globalized by the caller (local row + shard offset).

    Tie order matches the single-chip ``lax.top_k``: candidates concatenate
    shard-major and score-descending within a shard, so equal scores
    resolve in global-row order as long as each survived its local top-k.
    Used by ``make_sharded_topk`` / ``make_sharded_int8_topk`` /
    ``make_sharded_multitenant_topk`` below and by the fused sharded
    serving programs (``core.state.make_fused_sharded``).

    ``k_q`` ([Q] i32, optional) makes the merge RAGGED (ISSUE 7): the
    combine still runs to the static ``k`` ceiling, but each query's
    merged list is masked at its OWN k boundary — scores past it become
    NEG_INF and rows route to ``sentinel`` — so one compiled distributed
    kernel serves a mixed-k batch. The masked merge is exactly the
    per-query top-``k_i``: the ceiling merge is score-sorted."""
    all_s = jax.lax.all_gather(top_s, axis)                 # [n, Q, k_l]
    all_i = jax.lax.all_gather(top_i, axis)
    q = top_s.shape[0]
    all_s = jnp.moveaxis(all_s, 0, 1).reshape(q, -1)        # [Q, n*k_l]
    all_i = jnp.moveaxis(all_i, 0, 1).reshape(q, -1)
    fin_s, fin_pos = jax.lax.top_k(all_s, k)
    fin_i = jnp.take_along_axis(all_i, fin_pos, axis=1)
    if k_q is not None:
        live = jnp.arange(k)[None, :] < k_q[:, None]
        fin_s = jnp.where(live, fin_s, NEG_INF)
        fin_i = jnp.where(live, fin_i, sentinel)
    return fin_s, fin_i


def sharded_grouped_topk_merge(axis: str, top_s: jax.Array,
                               top_i: jax.Array, widths, ks):
    """SEVERAL per-shard candidate groups merged with ONE all_gather pair
    (ISSUE 9: the fused sharded ingest needs the dedup-probe top-1 AND
    both link modes' top-k merged in the same dispatch — three
    ``sharded_topk_merge`` calls would pay three collectives each way).
    ``top_s``/``top_i`` are the groups' per-shard candidate lists
    concatenated along the k axis (``[Q, sum(widths)]``); ``widths`` gives
    each group's per-shard width and ``ks`` its merged output k. Must be
    called INSIDE shard_map with ``axis`` bound; ids must already be
    globalized. Returns one ``(scores [Q, k_g], ids [Q, k_g])`` pair per
    group.

    Tie order matches :func:`sharded_topk_merge`: each group's candidates
    concatenate shard-major ([Q, n, w] → [Q, n·w]), so equal scores
    resolve in global-row order — the same order a single-chip top-k over
    the whole arena produces."""
    all_s = jnp.moveaxis(jax.lax.all_gather(top_s, axis), 0, 1)  # [Q, n, W]
    all_i = jnp.moveaxis(jax.lax.all_gather(top_i, axis), 0, 1)
    q = top_s.shape[0]
    outs = []
    off = 0
    for w, k_g in zip(widths, ks):
        s = all_s[:, :, off:off + w].reshape(q, -1)
        i = all_i[:, :, off:off + w].reshape(q, -1)
        fin_s, pos = jax.lax.top_k(s, min(k_g, s.shape[1]))
        outs.append((fin_s, jnp.take_along_axis(i, pos, axis=1)))
        off += w
    return outs


def make_sharded_topk(mesh: Mesh, axis: str = "data", k: int = 10,
                      impl: str = "auto"):
    """Build a pjit-compiled distributed top-k over ``mesh``.

    Returns ``search(emb, mask, query) -> (scores [Q,k], global_rows [Q,k])``
    where ``emb [N, d]`` and ``mask [N]`` are sharded along ``axis`` and the
    query is replicated. Local top-k per chip → all_gather(k·chips) → global
    top-k; collectives ride ICI.

    ``impl`` picks the per-shard scorer: "xla" (one matmul + full-width
    top_k) or "pallas" (the blocked VMEM-streaming kernel,
    ``ops/pallas_topk.py`` — no [Q, N/n] HBM score tensor per shard). This
    is the composition VERDICT r3 weak #7 asked for: ``pallas_call`` has no
    GSPMD partitioning rule, but under ``shard_map`` each device sees a
    plain local array, so the blocked kernel runs per shard and only the
    k-candidate combine rides the ICI collective. "auto" uses pallas when
    the local shard is big enough to benefit (the single-chip dispatch
    threshold scaled per shard) and block-alignable; interpret mode keeps
    CPU-mesh tests exact."""
    n_shards = mesh.shape[axis]

    def local_candidates(emb_l, mask_l, query):
        # emb_l: [N/n, d], mask_l: [N/n], query: [Q, d] (replicated)
        from lazzaro_tpu.core.state import PALLAS_TOPK_MIN_ROWS
        from lazzaro_tpu.ops.pallas_topk import fit_block_rows, pallas_masked_topk

        local_n = emb_l.shape[0]
        k_eff = min(k, local_n)
        on_tpu = jax.default_backend() in ("tpu", "axon")
        blk = fit_block_rows(local_n, emb_l.shape[1], emb_l.dtype.itemsize)
        # same auto gate as the single-chip dispatch (state.arena_search),
        # with the row threshold scaled to the per-shard slice
        use_pallas = blk > 0 and k_eff <= 16 and query.shape[0] <= 128 and (
            impl == "pallas"
            or (impl == "auto" and on_tpu
                and local_n >= PALLAS_TOPK_MIN_ROWS // n_shards))
        if use_pallas:
            madd = jnp.where(mask_l, 0.0, NEG_INF).astype(jnp.float32)
            return pallas_masked_topk(emb_l, madd, query.astype(emb_l.dtype),
                                      k=k_eff, block_rows=blk,
                                      interpret=not on_tpu)
        from lazzaro_tpu.ops.chunking import nt_dot
        scores = nt_dot(query.astype(emb_l.dtype), emb_l)
        scores = jnp.where(mask_l[None, :], scores, NEG_INF)
        return jax.lax.top_k(scores, k_eff)

    def local_search(emb_l, mask_l, query):
        shard_idx = jax.lax.axis_index(axis)
        local_n = emb_l.shape[0]
        top_s, top_i = local_candidates(emb_l, mask_l, query)   # [Q, k]
        top_i = top_i + shard_idx * local_n                     # globalize rows
        return sharded_topk_merge(axis, top_s, top_i, k)

    mapped = shard_map(
        local_search,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis), P(None, None)),
        out_specs=(P(None, None), P(None, None)),
        check_vma=False,
    )

    @jax.jit
    def search(emb, mask, query):
        q = jnp.atleast_2d(query)
        return mapped(emb, mask, q)

    return search


def make_sharded_int8_topk(mesh: Mesh, axis: str = "data", k: int = 10):
    """Int8 serving composed with the mesh (VERDICT r4 next #7): the
    per-row quantized shadow is row-LOCAL state, so it shards exactly like
    the master arena. Each chip scans its own int8 rows — half the HBM
    bytes of the bf16 scan, int8×int8→int32 on the MXU (ops/quant.py) —
    takes a local top-k, and the k-candidate combine rides the same ICI
    ``all_gather`` as the exact sharded path above.

    Returns ``search(q8, scale, mask, query) -> (scores, global_rows)``
    with ``q8 [N, d] i8``, ``scale [N] f32``, ``mask [N]`` sharded along
    ``axis`` and the query replicated."""
    from lazzaro_tpu.ops.quant import quantize_rows

    def local_search(q8_l, scale_l, mask_l, query):
        shard_idx = jax.lax.axis_index(axis)
        local_n = q8_l.shape[0]
        k_eff = min(k, local_n)
        qq, qscale = quantize_rows(query)
        dots = jax.lax.dot_general(qq, q8_l, (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.int32)
        scores = (dots.astype(jnp.float32)
                  * qscale[:, None] * scale_l[None, :])
        scores = jnp.where(mask_l[None, :], scores, NEG_INF)
        top_s, top_i = jax.lax.top_k(scores, k_eff)
        top_i = top_i + shard_idx * local_n                 # globalize rows
        return sharded_topk_merge(axis, top_s, top_i, k)

    mapped = shard_map(
        local_search,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis), P(axis), P(None, None)),
        out_specs=(P(None, None), P(None, None)),
        check_vma=False,
    )

    @jax.jit
    def search(q8, scale, mask, query):
        return mapped(q8, scale, mask, jnp.atleast_2d(query))

    return search


def make_sharded_multitenant_topk(mesh: Mesh, axis: str = "data",
                                  k: int = 10):
    """Distributed masked top-k with a PER-QUERY tenant column (ROADMAP
    ceiling #4): one mixed-tenant mega-batch dispatches ONCE over the pod
    instead of once per tenant. Each chip scores its local rows for every
    query, masks with ``alive ∧ (tenant_col == query_tenant)`` — the same
    [Q, N/n] mask arithmetic the single-chip fused kernel uses — takes a
    local top-k, and the k-candidate combine rides the usual ICI
    ``all_gather``.

    Returns ``search(emb, alive, tenant_col, query, query_tenant) ->
    (scores [Q, k], global_rows [Q, k])`` with ``emb [N, d]``, ``alive
    [N]``, ``tenant_col [N]`` sharded along ``axis``; the query matrix and
    its [Q] tenant vector are replicated. Queries whose tenant id is -1
    (unknown tenant) match nothing and come back all-NEG_INF."""
    from lazzaro_tpu.ops.chunking import nt_dot

    def local_search(emb_l, alive_l, tenant_l, query, qtenant):
        shard_idx = jax.lax.axis_index(axis)
        local_n = emb_l.shape[0]
        k_eff = min(k, local_n)
        scores = nt_dot(query.astype(emb_l.dtype), emb_l)       # [Q, N/n]
        mask = alive_l[None, :] & (tenant_l[None, :] == qtenant[:, None])
        scores = jnp.where(mask, scores, NEG_INF)
        top_s, top_i = jax.lax.top_k(scores, k_eff)
        top_i = top_i + shard_idx * local_n                 # globalize rows
        return sharded_topk_merge(axis, top_s, top_i, k)

    mapped = shard_map(
        local_search,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis), P(axis), P(None, None), P(None)),
        out_specs=(P(None, None), P(None, None)),
        check_vma=False,
    )

    @jax.jit
    def search(emb, alive, tenant_col, query, qtenant):
        return mapped(emb, alive, tenant_col, jnp.atleast_2d(query), qtenant)

    return search


def shard_rows(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Row-sharding spec for [N, ...] index arrays."""
    return NamedSharding(mesh, P(axis))


def shard_matrix(mesh: Mesh, axis: str = "data") -> NamedSharding:
    return NamedSharding(mesh, P(axis, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
