from lazzaro_tpu.ops.topk import masked_topk, make_sharded_topk
from lazzaro_tpu.ops.graphops import connected_components, component_stats, pairwise_merge_candidates

__all__ = [
    "masked_topk",
    "make_sharded_topk",
    "connected_components",
    "component_stats",
    "pairwise_merge_candidates",
]
