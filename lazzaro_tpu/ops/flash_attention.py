"""Pallas TPU kernel: causal flash attention with GQA (online softmax).

The decoder LM's full-sequence attention (``models/llm.py`` Attention) is the
FLOPs-heavy op of on-TPU consolidation and training. The plain XLA path
materializes a [B, H, T, S] f32 score tensor in HBM; this kernel tiles Q into
VMEM blocks and streams K/V through VMEM one ``blk_k`` block per grid step
(accumulators live in VMEM scratch across the inner grid dimension), so the
score tensor never touches HBM, VMEM usage is independent of sequence length,
and the matmuls stay on the MXU in the input dtype (bf16) with f32
accumulation.

Grouped-query attention costs nothing here: the K/V BlockSpec index map sends
query head ``h`` to kv head ``h // rep``, so kv heads are never materialized
``rep`` times (the XLA path pays a ``jnp.repeat``).

The causal mask is END-ALIGNED: query row ``i`` (of T) attends keys
``0 .. (S - T) + i``, so chunked prefill — q = the last T positions of an
S-token context — is supported, with standard self-attention as the S == T
special case. Fully-masked kv blocks above the diagonal skip their compute
via predication.

The backward pass is a fused Pallas VJP: the forward stores one log-sum-exp
per query row (lanes-broadcast [B, H, T, 128] layout, the same residual
trick as jax's in-tree kernel) and the dQ / dK+dV kernels recompute each
score block from it — so NEITHER direction materializes a [T, S] tensor in
HBM and training peak memory is O(T·D). Measured on a v5e chip at
B=2, T=8192, H=8, D=128 (bf16): fwd+bwd temp HBM 101 MB vs 8,691 MB for the
materialized-scores XLA path (86×); at T=32768 the fused pair runs in
336 MB where the XLA backward would need ~137 GB for scores alone. The
dK/dV kernel accumulates a GQA group's rep query heads into one kv-head
block in VMEM scratch across two sequential grid dims.

Single-device semantics: under a tensor-parallel ('model') mesh the heads
axis is sharded and ``pallas_call`` has no partitioning rule — callers must
run it inside ``shard_map`` or fall back to the XLA path
(``models/llm.py`` guards this).

Use ``interpret=True`` (automatic off-TPU) for tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e30
LANES = 128   # scalar-per-row scratch is stored broadcast across lanes


def _flash_kernel(blk_q: int, blk_k: int, nk: int, offset: int, scale: float):
    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref):
        iq = pl.program_id(2)
        jk = pl.program_id(3)

        @pl.when(jk == 0)
        def _():
            m_ref[:] = jnp.full_like(m_ref, NEG)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)

        # Query rows of this block cover absolute key window up to
        # offset + iq*blk_q + blk_q - 1; kv blocks fully above it skip.
        @pl.when(jk * blk_k <= offset + iq * blk_q + blk_q - 1)
        def _():
            q = q_ref[0, 0]                                   # [blk_q, D]
            k_blk = k_ref[0, 0]                               # [blk_k, D]
            v_blk = v_ref[0, 0]
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale   # [blk_q, blk_k]
            row = offset + iq * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            col = jk * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(col <= row, s, NEG)
            m_prev = m_ref[:, :1]                             # [blk_q, 1]
            l_prev = l_ref[:, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
            acc_ref[:] = acc_ref[:] * corr + jnp.dot(
                p.astype(v_blk.dtype), v_blk,
                preferred_element_type=jnp.float32)
            m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
            l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

        @pl.when(jk == nk - 1)
        def _():
            l = jnp.maximum(l_ref[:, :1], 1e-30)
            o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)
            # log-sum-exp per row — the ONLY forward residual the fused
            # backward needs beyond q/k/v/o (softmax recomputes from it as
            # p = exp(s - lse), no [T, S] tensor ever stored in HBM).
            lse_ref[0, 0] = jnp.broadcast_to(m_ref[:, :1] + jnp.log(l),
                                             (blk_q, LANES))

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("blk_q", "blk_k", "offset", "interpret"))
def _flash_fwd_bhtd(q: jax.Array, k: jax.Array, v: jax.Array,
                    blk_q: int, blk_k: int, offset: int,
                    interpret: bool):
    """q [B, H, T, D], k/v [B, Hkv, S, D] (pre-transposed; T % blk_q == 0,
    S % blk_k == 0). ``offset`` is the UNPADDED S - T: query row i attends
    absolute keys 0..offset+i (padded tail rows/cols are positionally
    outside every real window). → ([B, H, T, D] out, [B, H, T, LANES] f32
    LSE). The LSE is logically per-row ([B, H, T]) but stored broadcast
    across the 128 lanes so it stays (8, 128)-tileable on TPU — residual
    memory is T*128 f32 per head, 128× a per-row scalar would cost."""
    B, H, T, D = q.shape
    _, Hkv, S, _ = k.shape
    assert H % Hkv == 0, f"heads {H} not a multiple of kv heads {Hkv}"
    rep = H // Hkv
    nq, nk = T // blk_q, S // blk_k
    scale = 1.0 / np.sqrt(D)

    return pl.pallas_call(
        _flash_kernel(blk_q, blk_k, nk, offset, scale),
        grid=(B, H, nq, nk),          # jk innermost: accumulators in scratch
        in_specs=[
            pl.BlockSpec((1, 1, blk_q, D), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_k, D),
                         lambda b, h, i, j: (b, h // rep, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_k, D),
                         lambda b, h, i, j: (b, h // rep, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, blk_q, D), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_q, LANES),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            # rank-4 lanes-broadcast layout: (8, 128)-tileable on TPU (the
            # same trick jax's own flash kernel uses for its l/m residuals)
            jax.ShapeDtypeStruct((B, H, T, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, LANES), jnp.float32),   # running max m
            pltpu.VMEM((blk_q, LANES), jnp.float32),   # running sum l
            pltpu.VMEM((blk_q, D), jnp.float32),       # output accumulator
        ],
        # B/H/nq are independent → Megacore-parallel; only the innermost nk
        # dimension carries the scratch accumulators and must stay sequential.
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v)


def _flash_dq_kernel(blk_q: int, blk_k: int, nk: int, offset: int,
                     scale: float):
    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, acc_ref):
        iq = pl.program_id(2)
        jk = pl.program_id(3)

        @pl.when(jk == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        @pl.when(jk * blk_k <= offset + iq * blk_q + blk_q - 1)
        def _():
            q = q_ref[0, 0]
            k_blk = k_ref[0, 0]
            v_blk = v_ref[0, 0]
            do = do_ref[0, 0]
            lse = lse_ref[0, 0][:, :1]                        # [blk_q, 1]
            delta = delta_ref[0, 0][:, :1]
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            row = offset + iq * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            col = jk * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(col <= row, s, NEG)
            p = jnp.exp(s - lse)                              # [blk_q, blk_k]
            dp = jax.lax.dot_general(
                do, v_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * scale
            acc_ref[:] += jnp.dot(ds.astype(k_blk.dtype), k_blk,
                                  preferred_element_type=jnp.float32)

        @pl.when(jk == nk - 1)
        def _():
            dq_ref[0, 0] = acc_ref[:].astype(dq_ref.dtype)

    return kernel


def _flash_dkv_kernel(blk_q: int, blk_k: int, nq: int, rep: int,
                      offset: int, scale: float):
    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dk_ref, dv_ref, dk_acc, dv_acc):
        jk = pl.program_id(1)
        h = pl.program_id(2)
        iq = pl.program_id(3)

        # One (b, kv-head, kv-block) output accumulates over the rep query
        # heads of its GQA group AND all query blocks — both grid dims are
        # sequential, so the scratch lives across the whole group.
        @pl.when((h % rep == 0) & (iq == 0))
        def _():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        @pl.when(jk * blk_k <= offset + iq * blk_q + blk_q - 1)
        def _():
            q = q_ref[0, 0]
            k_blk = k_ref[0, 0]
            v_blk = v_ref[0, 0]
            do = do_ref[0, 0]
            lse = lse_ref[0, 0][:, :1]
            delta = delta_ref[0, 0][:, :1]
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            row = offset + iq * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            col = jk * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(col <= row, s, NEG)
            p = jnp.exp(s - lse)
            dp = jax.lax.dot_general(
                do, v_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * scale
            dv_acc[:] += jax.lax.dot_general(          # p^T @ do
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dk_acc[:] += jax.lax.dot_general(          # ds^T @ q
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when((h % rep == rep - 1) & (iq == nq - 1))
        def _():
            dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
            dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("blk_q", "blk_k", "offset", "interpret"))
def _flash_bwd_bhtd(q, k, v, o, lse, do, blk_q: int, blk_k: int,
                    offset: int, interpret: bool):
    """Fused backward: q/o/do [B, H, T, D], k/v [B, Hkv, S, D], lse
    [B, H, T, LANES] (the forward's lanes-broadcast residual; logically
    per-row) → (dq [B, H, T, D], dk [B, Hkv, S, D], dv [B, Hkv, S, D]).
    Scores are recomputed per block from the stored LSE — no [T, S] HBM
    tensor. The delta residual built below is likewise broadcast to
    [B, H, T, LANES]; each of lse and delta costs T*128 f32 per head."""
    B, H, T, D = q.shape
    _, Hkv, S, _ = k.shape
    rep = H // Hkv
    nq, nk = T // blk_q, S // blk_k
    scale = 1.0 / np.sqrt(D)
    delta = jnp.einsum("bhtd,bhtd->bht", do.astype(jnp.float32),
                       o.astype(jnp.float32))                 # [B, H, T]
    delta = jnp.broadcast_to(delta[..., None], delta.shape + (LANES,))

    dq = pl.pallas_call(
        _flash_dq_kernel(blk_q, blk_k, nk, offset, scale),
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, blk_q, D), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_k, D),
                         lambda b, h, i, j: (b, h // rep, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_k, D),
                         lambda b, h, i, j: (b, h // rep, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_q, D), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_q, LANES),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_q, LANES),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, blk_q, D), lambda b, h, i, j: (b, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((blk_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        _flash_dkv_kernel(blk_q, blk_k, nq, rep, offset, scale),
        # kv-block outermost-but-one; (h, iq) sequential so the GQA group's
        # partial sums stay resident in scratch until the group finishes.
        grid=(B, nk, H, nq),
        in_specs=[
            pl.BlockSpec((1, 1, blk_q, D), lambda b, j, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_k, D),
                         lambda b, j, h, i: (b, h // rep, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_k, D),
                         lambda b, j, h, i: (b, h // rep, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_q, D), lambda b, j, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_q, LANES),
                         lambda b, j, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_q, LANES),
                         lambda b, j, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, blk_k, D),
                         lambda b, j, h, i: (b, h // rep, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_k, D),
                         lambda b, j, h, i: (b, h // rep, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, D), jnp.float32),
            pltpu.VMEM((blk_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def reference_attention(q, k, v, attn_mask, scale: float = 0.0,
                        softcap: float = 0.0):
    """Materialized-scores GQA attention — THE canonical einsum formulation,
    shared by the decoder's XLA path (``models/llm.py``), the flash VJP, and
    the parity tests. q [B,T,H,D], k/v [B,S,Hkv,D], attn_mask [B,T,S] (or
    broadcastable) → [B,T,H,D] in q's dtype.

    ``scale``: score multiplier; 0 → the standard 1/sqrt(head_dim).
    ``softcap``: >0 applies Gemma-2 logit softcapping cap·tanh(s/cap)
    BEFORE masking."""
    H, D = q.shape[2], q.shape[3]
    Hkv = k.shape[2]
    k = jnp.repeat(k, H // Hkv, axis=2)
    v = jnp.repeat(v, H // Hkv, axis=2)
    s = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32)
    s = s * (scale if scale > 0 else 1.0 / np.sqrt(D))
    if softcap > 0:
        s = softcap * jnp.tanh(s / softcap)
    s = jnp.where(attn_mask[:, None], s, NEG)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", p, v)


def _reference_gqa(q, k, v):
    """End-aligned causal reference — VJP + parity oracle."""
    T, S = q.shape[1], k.shape[1]
    row = (S - T) + jnp.arange(T)[:, None]
    col = jnp.arange(S)[None, :]
    return reference_attention(q, k, v, (col <= row)[None])


def _resolve(blk_q: int, blk_k: int, T: int, S: int, interpret):
    """Deterministic (block sizes, padded lengths, interpret) from shapes —
    shared by forward and backward so their grids always agree."""
    if interpret is None:
        interpret = jax.devices()[0].platform not in ("tpu", "axon")
    blk_q = min(blk_q, max(8, 1 << (T - 1).bit_length()))
    blk_k = min(blk_k, max(8, 1 << (S - 1).bit_length()))
    Tp = -(-T // blk_q) * blk_q
    Sp = -(-S // blk_k) * blk_k
    return blk_q, blk_k, Tp, Sp, interpret


def _pad_bhtd(x, Lp):
    """[B, L, H, D] → transposed [B, H, L, D], back-padded to Lp rows."""
    xt = jnp.moveaxis(x, 1, 2)
    L = xt.shape[2]
    if Lp != L:
        xt = jnp.pad(xt, ((0, 0), (0, 0), (0, Lp - L), (0, 0)))
    return xt


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    blk_q: int = 128, blk_k: int = 128,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Causal GQA flash attention, fused forward AND backward.

    q: [B, T, H, D]; k, v: [B, S, Hkv, D] with H % Hkv == 0 and S >= T. The
    causal diagonal is end-aligned: query row i attends keys 0..(S-T)+i
    (standard self-attention when S == T; chunked prefill when S > T).
    Sequence lengths are padded internally to the block size — padded kv
    columns fall outside every real row's causal window, so no explicit
    length mask is needed. Returns [B, T, H, D] in q's dtype.

    The VJP recomputes per-block scores from the stored log-sum-exp
    (forward residual), so neither direction ever materializes a [T, S]
    tensor in HBM — training peak memory is O(T·D), not O(T·S).
    """
    out, _, _ = _forward_with_residuals(q, k, v, blk_q, blk_k, interpret)
    return out


def _forward_with_residuals(q, k, v, blk_q, blk_k, interpret):
    B, T, H, D = q.shape
    S = k.shape[1]
    if S < T:
        raise ValueError(f"kv length {S} shorter than query length {T}")
    blk_q, blk_k, Tp, Sp, interpret = _resolve(blk_q, blk_k, T, S, interpret)
    # Back-pad both; the kernel masks by ABSOLUTE positions with the
    # unpadded offset S - T, so padded q rows are garbage (sliced off) and
    # padded kv columns sit beyond every real row's window.
    qt = _pad_bhtd(q, Tp)
    kt = _pad_bhtd(k, Sp)
    vt = _pad_bhtd(v, Sp)
    out_p, lse = _flash_fwd_bhtd(qt, kt, vt, blk_q, blk_k, S - T, interpret)
    return jnp.moveaxis(out_p[:, :, :T], 2, 1), out_p, lse


def _fwd(q, k, v, blk_q, blk_k, interpret):
    out, out_p, lse = _forward_with_residuals(q, k, v, blk_q, blk_k, interpret)
    return out, (q, k, v, out_p, lse)


def _bwd(blk_q, blk_k, interpret, res, g):
    q, k, v, out_p, lse = res
    T, S = q.shape[1], k.shape[1]
    blk_q, blk_k, Tp, Sp, interpret = _resolve(blk_q, blk_k, T, S, interpret)
    qt = _pad_bhtd(q, Tp)
    kt = _pad_bhtd(k, Sp)
    vt = _pad_bhtd(v, Sp)
    gt = _pad_bhtd(g, Tp)          # zero-padded rows contribute nothing
    dq, dk, dv = _flash_bwd_bhtd(qt, kt, vt, out_p, lse, gt,
                                 blk_q, blk_k, S - T, interpret)
    return (jnp.moveaxis(dq[:, :, :T], 2, 1),
            jnp.moveaxis(dk[:, :, :S], 2, 1),
            jnp.moveaxis(dv[:, :, :S], 2, 1))


flash_attention.defvjp(_fwd, _bwd)
