"""Pallas TPU kernel: causal flash attention with GQA (online softmax).

The decoder LM's full-sequence attention (``models/llm.py`` Attention) is the
FLOPs-heavy op of on-TPU consolidation and training. The plain XLA path
materializes a [B, H, T, S] f32 score tensor in HBM; this kernel tiles Q into
VMEM blocks and streams K/V through VMEM one ``blk_k`` block per grid step
(accumulators live in VMEM scratch across the inner grid dimension), so the
score tensor never touches HBM, VMEM usage is independent of sequence length,
and the matmuls stay on the MXU in the input dtype (bf16) with f32
accumulation.

Grouped-query attention costs nothing here: the K/V BlockSpec index map sends
query head ``h`` to kv head ``h // rep``, so kv heads are never materialized
``rep`` times (the XLA path pays a ``jnp.repeat``).

The causal mask is END-ALIGNED: query row ``i`` (of T) attends keys
``0 .. (S - T) + i``, so chunked prefill — q = the last T positions of an
S-token context — is supported, with standard self-attention as the S == T
special case. Fully-masked kv blocks above the diagonal skip their compute
via predication.

The backward pass is a custom VJP that recomputes attention with the
reference einsum formulation — forward gets the fused kernel, training gets
correct (XLA-fused) gradients. Consequence: the backward DOES materialize the
[B, H, T, S] score tensor, so training peak HBM is unchanged vs the XLA path;
the kernel's memory/speed win applies to forward-only paths (``logits_for``,
scoring, evaluation). A fused flash backward is future work.

Single-device semantics: under a tensor-parallel ('model') mesh the heads
axis is sharded and ``pallas_call`` has no partitioning rule — callers must
run it inside ``shard_map`` or fall back to the XLA path
(``models/llm.py`` guards this).

Use ``interpret=True`` (automatic off-TPU) for tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e30
LANES = 128   # scalar-per-row scratch is stored broadcast across lanes


def _flash_kernel(blk_q: int, blk_k: int, nk: int, offset: int, scale: float):
    def kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref):
        iq = pl.program_id(2)
        jk = pl.program_id(3)

        @pl.when(jk == 0)
        def _():
            m_ref[:] = jnp.full_like(m_ref, NEG)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)

        # Query rows of this block cover absolute key window up to
        # offset + iq*blk_q + blk_q - 1; kv blocks fully above it skip.
        @pl.when(jk * blk_k <= offset + iq * blk_q + blk_q - 1)
        def _():
            q = q_ref[0, 0]                                   # [blk_q, D]
            k_blk = k_ref[0, 0]                               # [blk_k, D]
            v_blk = v_ref[0, 0]
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale   # [blk_q, blk_k]
            row = offset + iq * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            col = jk * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(col <= row, s, NEG)
            m_prev = m_ref[:, :1]                             # [blk_q, 1]
            l_prev = l_ref[:, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
            acc_ref[:] = acc_ref[:] * corr + jnp.dot(
                p.astype(v_blk.dtype), v_blk,
                preferred_element_type=jnp.float32)
            m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
            l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

        @pl.when(jk == nk - 1)
        def _():
            l = jnp.maximum(l_ref[:, :1], 1e-30)
            o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("blk_q", "blk_k", "offset", "interpret"))
def _flash_fwd_bhtd(q: jax.Array, k: jax.Array, v: jax.Array,
                    blk_q: int, blk_k: int, offset: int,
                    interpret: bool) -> jax.Array:
    """q [B, H, T, D], k/v [B, Hkv, S, D] (pre-transposed; T % blk_q == 0,
    S % blk_k == 0). ``offset`` is the UNPADDED S - T: query row i attends
    absolute keys 0..offset+i (padded tail rows/cols are positionally
    outside every real window). → [B, H, T, D]."""
    B, H, T, D = q.shape
    _, Hkv, S, _ = k.shape
    assert H % Hkv == 0, f"heads {H} not a multiple of kv heads {Hkv}"
    rep = H // Hkv
    nq, nk = T // blk_q, S // blk_k
    scale = 1.0 / np.sqrt(D)

    return pl.pallas_call(
        _flash_kernel(blk_q, blk_k, nk, offset, scale),
        grid=(B, H, nq, nk),          # jk innermost: accumulators in scratch
        in_specs=[
            pl.BlockSpec((1, 1, blk_q, D), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_k, D),
                         lambda b, h, i, j: (b, h // rep, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_k, D),
                         lambda b, h, i, j: (b, h // rep, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, blk_q, D),
                               lambda b, h, i, j: (b, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((blk_q, LANES), jnp.float32),   # running max m
            pltpu.VMEM((blk_q, LANES), jnp.float32),   # running sum l
            pltpu.VMEM((blk_q, D), jnp.float32),       # output accumulator
        ],
        # B/H/nq are independent → Megacore-parallel; only the innermost nk
        # dimension carries the scratch accumulators and must stay sequential.
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v)


def reference_attention(q, k, v, attn_mask):
    """Materialized-scores GQA attention — THE canonical einsum formulation,
    shared by the decoder's XLA path (``models/llm.py``), the flash VJP, and
    the parity tests. q [B,T,H,D], k/v [B,S,Hkv,D], attn_mask [B,T,S] (or
    broadcastable) → [B,T,H,D] in q's dtype."""
    H, D = q.shape[2], q.shape[3]
    Hkv = k.shape[2]
    k = jnp.repeat(k, H // Hkv, axis=2)
    v = jnp.repeat(v, H // Hkv, axis=2)
    s = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) / np.sqrt(D)
    s = jnp.where(attn_mask[:, None], s, NEG)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", p, v)


def _reference_gqa(q, k, v):
    """End-aligned causal reference — VJP + parity oracle."""
    T, S = q.shape[1], k.shape[1]
    row = (S - T) + jnp.arange(T)[:, None]
    col = jnp.arange(S)[None, :]
    return reference_attention(q, k, v, (col <= row)[None])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    blk_q: int = 128, blk_k: int = 128,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Causal GQA flash attention.

    q: [B, T, H, D]; k, v: [B, S, Hkv, D] with H % Hkv == 0 and S >= T. The
    causal diagonal is end-aligned: query row i attends keys 0..(S-T)+i
    (standard self-attention when S == T; chunked prefill when S > T).
    Sequence lengths are padded internally to the block size — padded kv
    columns fall outside every real row's causal window, so no explicit
    length mask is needed. Returns [B, T, H, D] in q's dtype.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform not in ("tpu", "axon")
    B, T, H, D = q.shape
    S = k.shape[1]
    if S < T:
        raise ValueError(f"kv length {S} shorter than query length {T}")
    blk_q = min(blk_q, max(8, 1 << (T - 1).bit_length()))
    blk_k = min(blk_k, max(8, 1 << (S - 1).bit_length()))
    Tp = -(-T // blk_q) * blk_q
    Sp = -(-S // blk_k) * blk_k
    qt = jnp.moveaxis(q, 1, 2)                      # [B, H, T, D]
    kt = jnp.moveaxis(k, 1, 2)
    vt = jnp.moveaxis(v, 1, 2)
    # Back-pad both; the kernel masks by ABSOLUTE positions with the
    # unpadded offset S - T, so padded q rows are garbage (sliced off) and
    # padded kv columns sit beyond every real row's window.
    if Tp != T:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, Tp - T), (0, 0)))
    if Sp != S:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, Sp - S), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, Sp - S), (0, 0)))
    out = _flash_fwd_bhtd(qt, kt, vt, blk_q, blk_k, S - T, interpret)
    return jnp.moveaxis(out[:, :, :T], 2, 1)


def _fwd(q, k, v, blk_q, blk_k, interpret):
    return flash_attention(q, k, v, blk_q, blk_k, interpret), (q, k, v)


def _bwd(blk_q, blk_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(_reference_gqa, q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
