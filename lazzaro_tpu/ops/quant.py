"""Int8 serving path: quantized arena scan at half the HBM traffic.

Retrieval at 1M rows is HBM-bound: a bf16 arena streams N·d·2 bytes per
scan (~1.5 GB at 1M×768 — a ~1.9 ms floor on a v5e's 0.82 TB/s). Rows are
L2-normalized, so components live in [-1, 1] and symmetric per-row int8
quantization (x ≈ scale_r · q_r, q ∈ [-127, 127]) costs ~0.4% cosine error
— far inside the 0.95/0.5 thresholds the memory system acts on — while
halving scan bytes AND running the dot products on the MXU's int8 path
(2× bf16 peak). This is VERDICT r3 next-step #7's "int8 arena": the honest
route below the bf16 bandwidth floor, as opposed to a faster clock.

The quantized copy is a SERVING SHADOW: the bf16/f32 arena stays the
mutable master (scatter updates, decay sweeps, exact merge thresholds).
Freshness is incremental where it matters: the fused ingest program
scatters codes+scales for freshly written rows in-kernel
(``core/state._shadow_scatter`` — O(batch)), and ``core/index.py``
re-quantizes lazily only when no maintained shadow exists (first build,
arena growth, mesh path). Reference analog: LanceDB's ANN index over the
raw vectors (vector_store.py:132-140) — same split of exact store vs.
scan-optimized replica.

Serving consumes the shadow two ways: the classic ``quantized_topk`` scan
below (pure int8 ranking; mesh path via ops/topk.make_sharded_int8_topk),
and since ISSUE 3 the single-dispatch fused chat-turn program
(``core/state.search_fused_quant``) which uses the int8 scores only as a
COARSE top-(k+slack) stage and exactly rescores the survivors from the
master — returned scores and threshold verdicts never carry quantization
error there.

MEASURED (r5): the win is TPU-specific by design — on the 1-core CPU
fallback int8 is SLOWER than exact (67.4 ms vs 60.7 ms at 100k×768,
``bench_artifacts/r5_kernels_100k_cpu.json``: no int8 SIMD path there),
exactly the inversion the r4 review flagged; the halved-bytes/int8-MXU
claim applies to the TPU capture (``r5_kernels_1m_*.json`` via
scripts/tpu_watch.py whenever the tunnel is up).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from lazzaro_tpu.ops.chunking import chunked_map

NEG_INF = -1e30


@jax.jit
def quantize_rows(emb: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-row symmetric int8: returns (q [N, d] i8, scale [N] f32) with
    x ≈ scale[r] · q[r]. Zero rows quantize to zeros with scale 0."""
    x = emb.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=1)
    scale = jnp.where(amax > 0, amax / 127.0, 0.0)
    inv = jnp.where(scale > 0, 1.0 / scale, 0.0)
    q = jnp.clip(jnp.round(x * inv[:, None]), -127, 127).astype(jnp.int8)
    return q, scale


@functools.partial(jax.jit, static_argnames=("k",))
def quantized_topk(q_arena: jax.Array,    # [N, d] i8
                   scale: jax.Array,      # [N] f32
                   mask: jax.Array,       # [N] bool
                   queries: jax.Array,    # [Q, d] f32 (need not be normalized)
                   k: int) -> Tuple[jax.Array, jax.Array]:
    """Masked cosine top-k over the int8 shadow.

    The query is quantized per-row too, so the inner product runs int8×int8
    → int32 entirely on the MXU; the two scales multiply back in f32. Score
    error vs the exact scan is ≤ ~1e-2 absolute — ranking-stable for the
    system's 0.95 dedup / 0.5 link gates. Queries stream through the shared
    [chunk, N] tiles (ops/chunking.py) like every other arena scan."""
    qq, qscale = quantize_rows(queries)

    def chunk(idx_c):
        qq_c = qq[idx_c]                                       # [C, d] i8
        dots = jax.lax.dot_general(
            qq_c, q_arena, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)                  # [C, N] i32
        scores = (dots.astype(jnp.float32)
                  * qscale[idx_c][:, None] * scale[None, :])
        scores = jnp.where(mask[None, :], scores, NEG_INF)
        return jax.lax.top_k(scores, k)

    return chunked_map(chunk, jnp.arange(queries.shape[0], dtype=jnp.int32))
