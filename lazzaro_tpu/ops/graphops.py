"""Vectorized graph algorithms over the edge arena.

Replaces the reference's recursive-DFS connected components
(``buffer_graph.py:99-120``) with iterative label propagation (pointer
jumping) — XLA-friendly, no Python recursion, O(E · diameter) work fully on
device via ``lax.while_loop``.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from lazzaro_tpu.ops.chunking import chunked_map, nt_dot


@functools.partial(jax.jit, static_argnames=("num_nodes",))
def connected_components(
    src: jax.Array,        # [E] i32 (dead edges may hold -1)
    tgt: jax.Array,        # [E] i32
    edge_alive: jax.Array,  # [E] bool
    node_alive: jax.Array,  # [num_nodes] bool
    num_nodes: int,
    min_weight: jax.Array = 0.0,
    weight: jax.Array | None = None,
) -> jax.Array:
    """Label propagation: every alive node ends with the minimum row index of
    its component as its label; dead nodes get -1."""
    if weight is None:
        weight = jnp.ones_like(edge_alive, jnp.float32)
    live_e = edge_alive & (weight >= min_weight)
    s = jnp.where(live_e, src, 0)
    t = jnp.where(live_e, tgt, 0)

    labels0 = jnp.where(node_alive, jnp.arange(num_nodes, dtype=jnp.int32), jnp.int32(num_nodes))

    def body(carry):
        labels, _ = carry
        ls, lt = labels[s], labels[t]
        m = jnp.minimum(ls, lt)
        big = jnp.int32(num_nodes)
        m_s = jnp.where(live_e, m, big)
        new = labels
        new = new.at[s].min(m_s)
        new = new.at[t].min(m_s)
        # pointer jumping: label <- label[label] accelerates convergence
        new = jnp.minimum(new, new[jnp.clip(new, 0, num_nodes - 1)])
        changed = jnp.any(new != labels)
        return new, changed

    def cond(carry):
        return carry[1]

    labels, _ = jax.lax.while_loop(cond, body, (labels0, jnp.bool_(True)))
    return jnp.where(node_alive, labels, -1)


@jax.jit
def component_stats(labels: jax.Array, src: jax.Array, tgt: jax.Array,
                    edge_alive: jax.Array, weight: jax.Array,
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-component (keyed by label == component root row) node counts, edge
    counts, and summed edge weight. Used by the deep-consolidation pass
    (reference ``run_consolidation`` memory_system.py:967-989) to find
    components with >= 3 nodes and avg edge weight > 0.3 without a Python DFS."""
    n = labels.shape[0]
    alive_nodes = labels >= 0
    node_counts = jnp.zeros((n,), jnp.int32).at[jnp.clip(labels, 0)].add(
        alive_nodes.astype(jnp.int32))
    edge_lbl = jnp.where(edge_alive, labels[jnp.clip(src, 0)], 0)
    edge_counts = jnp.zeros((n,), jnp.int32).at[jnp.clip(edge_lbl, 0)].add(
        edge_alive.astype(jnp.int32))
    weight_sums = jnp.zeros((n,), jnp.float32).at[jnp.clip(edge_lbl, 0)].add(
        jnp.where(edge_alive, weight, 0.0))
    return node_counts, edge_counts, weight_sums


@functools.partial(jax.jit, static_argnames=("k", "chunk"))
def pairwise_merge_candidates(emb: jax.Array, mask: jax.Array,
                              threshold: jax.Array, k: int = 4,
                              chunk: int = 512,
                              ) -> Tuple[jax.Array, jax.Array]:
    """All-pairs near-duplicate detection as chunked matmuls + top-k.

    This implements the *intended* semantics of ``_merge_similar_nodes``
    (reference memory_system.py:1065-1120 has an indentation bug that only
    ever merges duplicates of the last node — SURVEY §2.2 says build the
    intended all-pairs version). For each row i, returns up to k rows j > i
    with cosine(i, j) > threshold; sentinel -1 elsewhere.

    Scale (VERDICT.md r3 weak #3): the score matrix is never materialized
    whole — ``chunked_map`` streams [chunk, N] f32 tiles (the shared
    HBM-bounding scaffold, ops/chunking.py), so 1M-row arenas fit a 16 GB
    chip where the old one-shot [N, N] needed ~4 TB. Each tile is still one
    MXU-bound matmul; f32 accumulation via ``preferred_element_type`` keeps
    bf16 arenas exact enough for 0.95-cosine thresholds."""
    n = emb.shape[0]
    col = jnp.arange(n, dtype=jnp.int32)

    def one_chunk(rows):
        q = emb[rows]
        scores = nt_dot(q, emb)
        upper = col[None, :] > rows[:, None]     # only j > i, no self-pairs
        valid = mask[rows][:, None] & mask[None, :] & upper
        scores = jnp.where(valid, scores, -jnp.inf)
        ts, tj = jax.lax.top_k(scores, k)
        return ts, jnp.where(ts > threshold, tj, -1)

    return chunked_map(one_chunk, col, chunk)
