"""Coarse-to-fine retrieval: centroid prefilter over the HBM arena.

The exact scan reads all N·d bytes per query batch (~1.9 ms floor at
1M×768 bf16 on a v5e). This is the OTHER honest route below that floor
(VERDICT r3 next #7, SURVEY §7.2's hierarchy-as-coarse-stage): spherical
k-means clusters the arena; a query scores C centroids (C ≈ √N), visits
only the ``nprobe`` nearest clusters' member rows, and scans those — HBM
traffic per query drops from N·d to ~(C + nprobe·N/C)·d (analytically
~25× at 1M rows with C=1024, nprobe=8). Approximate by construction:
recall is controlled by ``nprobe`` (= exact when nprobe == C, because
every alive row lives in exactly one cluster or the residual).

MEASURED (r5, clustered bench corpus, recall@5 vs the exact oracle —
``bench_artifacts/r5_kernels_100k_cpu.json``, 100k×768, single-core CPU,
backend-independent recall): nprobe=4 → 0.869 recall at 1.2 ms; nprobe=8
→ 0.884 at 4.0 ms; nprobe=16 → 0.938 at 7.1 ms; exact scan 60.7 ms —
an 8-50× measured latency win at the stated recall. TPU captures land in
``bench_artifacts/r5_kernels_1m_*.json`` whenever the tunnel is up
(scripts/tpu_watch.py).

Freshness without per-write rebuilds (the same sealed/fresh split as the
ArrowStore's LSM segments): rows added after a build go to a RESIDUAL set
that every search scans exactly; a periodic rebuild folds them into the
clusters. Skew is bounded the same way — clusters overflow their fixed
member capacity into the residual, so no row is ever silently dropped.

Reference analog: LanceDB's IVF-PQ ANN index over the raw vectors
(vector_store.py's table ANN) — here the coarse stage is an explicit,
testable kernel instead of a library call.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from lazzaro_tpu.ops.chunking import chunked_map

NEG_INF = -1e30


@functools.partial(jax.jit, static_argnames=("n_clusters", "iters"))
def _kmeans_device(emb: jax.Array, mask: jax.Array, init_rows: jax.Array,
                   n_clusters: int, iters: int) -> jax.Array:
    """Spherical k-means (cosine): normalized centroids [C, d]. Dead rows
    never contribute; a cluster that goes empty keeps its old centroid."""
    x = emb.astype(jnp.float32)
    cent = x[init_rows]                                    # [C, d]

    def assign(c):
        def chunk(rows):
            scores = jnp.dot(x[rows], c.T,
                             preferred_element_type=jnp.float32)
            return jnp.argmax(scores, axis=1).astype(jnp.int32)
        return chunked_map(chunk, jnp.arange(x.shape[0], dtype=jnp.int32))

    def step(c, _):
        a = jnp.where(mask, assign(c), n_clusters)         # dead -> bucket C
        sums = jnp.zeros((n_clusters + 1, x.shape[1]), jnp.float32
                         ).at[a].add(jnp.where(mask[:, None], x, 0.0))
        counts = jnp.zeros((n_clusters + 1,), jnp.float32).at[a].add(
            mask.astype(jnp.float32))
        new = sums[:n_clusters]
        norms = jnp.linalg.norm(new, axis=1, keepdims=True)
        new = jnp.where((counts[:n_clusters, None] > 0) & (norms > 1e-9),
                        new / jnp.maximum(norms, 1e-9), c)
        return new, None

    cent, _ = jax.lax.scan(step, cent, None, length=iters)
    return cent


@jax.jit
def _assign_device(emb: jax.Array, mask: jax.Array, cent: jax.Array
                   ) -> jax.Array:
    """Final cluster assignment [N] (dead rows -> -1)."""
    x = emb.astype(jnp.float32)

    def chunk(rows):
        scores = jnp.dot(x[rows], cent.T, preferred_element_type=jnp.float32)
        return jnp.argmax(scores, axis=1).astype(jnp.int32)

    a = chunked_map(chunk, jnp.arange(x.shape[0], dtype=jnp.int32))
    return jnp.where(mask, a, -1)


@dataclass
class IvfIndex:
    centroids: jax.Array     # [C, d] f32, L2-normalized
    members: jax.Array       # [C, M] i32 arena rows, -1 padded
    residual: jax.Array      # [R] i32 arena rows scanned exactly, -1 padded
    built_rows: int          # alive rows at build time (staleness signal)

    @property
    def n_clusters(self) -> int:
        return self.centroids.shape[0]


def _pow2(n: int, lo: int = 8) -> int:
    return max(lo, 1 << max(0, int(n - 1)).bit_length())


def build_ivf(emb: jax.Array, mask_np: np.ndarray,
              n_clusters: Optional[int] = None, iters: int = 8,
              member_cap_factor: int = 4, seed: int = 0) -> IvfIndex:
    """Cluster the alive rows and build the fixed-shape member table.

    ``member_cap_factor``: per-cluster capacity = factor · N/C (pow2-
    rounded); rows beyond a cluster's capacity overflow into the residual,
    so skewed data degrades to a bigger exact scan — never to dropped
    rows."""
    alive_rows = np.nonzero(mask_np)[0]
    n_alive = len(alive_rows)
    if n_alive == 0:
        raise ValueError("cannot build an IVF over an empty arena")
    if n_clusters is None:
        n_clusters = max(4, _pow2(int(np.sqrt(n_alive)), lo=4))
    n_clusters = min(n_clusters, n_alive)
    rng = np.random.default_rng(seed)
    init = rng.choice(alive_rows, size=n_clusters, replace=False)

    mask = jnp.asarray(mask_np)
    cent = _kmeans_device(emb, mask, jnp.asarray(init, jnp.int32),
                          n_clusters, iters)
    assign = np.asarray(_assign_device(emb, mask, cent))

    cap = _pow2(member_cap_factor * max(1, n_alive // n_clusters))
    members = np.full((n_clusters, cap), -1, np.int32)
    # vectorized table build: stable-sort rows by cluster, slice per
    # cluster (a per-row Python loop costs seconds of host time at 1M)
    a = assign[alive_rows]
    order = np.argsort(a, kind="stable")
    sorted_rows = alive_rows[order].astype(np.int32)
    counts = np.bincount(a, minlength=n_clusters)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    overflow_parts = []
    for c in range(n_clusters):            # C iterations, not N
        seg = sorted_rows[starts[c]:starts[c] + counts[c]]
        members[c, :min(cap, len(seg))] = seg[:cap]
        if len(seg) > cap:
            overflow_parts.append(seg[cap:])
    overflow = (np.concatenate(overflow_parts) if overflow_parts
                else np.zeros((0,), np.int32))
    residual = np.full((_pow2(len(overflow), lo=8),), -1, np.int32)
    residual[:len(overflow)] = overflow
    return IvfIndex(centroids=cent, members=jnp.asarray(members),
                    residual=jnp.asarray(residual), built_rows=n_alive)


def online_counts(members) -> jax.Array:
    """Per-cluster live-prefix occupancy of a member table — the ``counts``
    column the online-IVF ingest kernels (``core.state._ivf_online_update``)
    append through. Build-time tables are dense prefixes per cluster, so
    the live count IS the append cursor."""
    m = jnp.asarray(members)
    return (m >= 0).sum(axis=-1).astype(jnp.int32)


@jax.jit
def _staleness_device(emb: jax.Array, mask: jax.Array, cent: jax.Array,
                      members: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Device side of :func:`assignment_staleness`: count member-table
    slots whose row's argmax centroid (under the CURRENT centroids) is no
    longer the cluster the slot lives in."""
    assign = _assign_device(emb, mask, cent)               # [N], dead -> -1
    safe = jnp.maximum(members, 0)
    C = cent.shape[0]
    ok = (members >= 0) & (assign[safe] >= 0)
    stale = ok & (assign[safe] != jnp.arange(C)[:, None])
    return stale.sum(), ok.sum()


def assignment_staleness(emb, mask_np, cent, members) -> float:
    """Fraction of live member-table slots whose cluster no longer matches
    the row's argmax under the current centroids — the staleness number
    online IVF bounds (mini-batch centroid drift can strand old members;
    an offline rebuild by construction measures 0.0 here). An O(N·C)
    DIAGNOSTIC probe for bench/maintenance — never the serving path."""
    stale, live = _staleness_device(emb, jnp.asarray(mask_np),
                                    jnp.asarray(cent), jnp.asarray(members))
    live = int(live)
    return float(stale) / live if live else 0.0


def gather_rows(centroids: jax.Array, members: jax.Array,
                extras: jax.Array, q_c: jax.Array, nprobe: int
                ) -> Tuple[jax.Array, jax.Array]:
    """Device-friendly coarse gather, the single place EVERY member scan —
    the classic ``ivf_search``, ``ops.pq.ivf_pq_search``, and the fused
    serving kernel (``core.state.search_fused_ivf``) — assembles its
    candidate row set, so the 'identical candidate set' invariant between
    the paths is structural, not a docstring promise: score C centroids,
    take the ``nprobe`` best clusters, and return their member rows plus
    ``extras`` (the sealed residual, and for the fused path the fresh
    residual + super rows appended by the host).

    The ``optimization_barrier`` after the cluster top-k is the PR 2
    consumer-split fix: the visited-cluster ids feed both the member
    gather and (through the scores built on it) the packed readback —
    without the barrier XLA may clone the full [qc, C] centroid sort per
    consumer.

    Returns ``(cand [qc, L], safe [qc, L])`` with L = nprobe·M + len
    (extras); ``safe = max(cand, 0)`` is the gather-legal view (padding
    is -1). Callers apply their own validity mask (single-tenant kernels
    a [N] mask, the fused kernel a per-query tenant column)."""
    cs = jnp.dot(q_c, centroids.T,
                 preferred_element_type=jnp.float32)       # [qc, C]
    _, cids = jax.lax.top_k(cs, nprobe)                    # [qc, P]
    cids = jax.lax.optimization_barrier(cids)
    cand = members[cids].reshape(q_c.shape[0], -1)         # [qc, P*M]
    cand = jnp.concatenate(
        [cand, jnp.broadcast_to(extras[None, :],
                                (q_c.shape[0], extras.shape[0]))],
        axis=1)                                            # [qc, P*M+E]
    return cand, jnp.maximum(cand, 0)


def gather_candidates(centroids: jax.Array, members: jax.Array,
                      residual: jax.Array, mask: jax.Array, q_c: jax.Array,
                      nprobe: int
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Single-tenant view over :func:`gather_rows` (the exact and PQ member
    scans): adds the [N] alive/tenant mask and returns
    ``(cand, safe_rows, valid_mask)``."""
    cand, safe = gather_rows(centroids, members, residual, q_c, nprobe)
    valid = (cand >= 0) & mask[safe]
    return cand, safe, valid


def pack_extras(residual: np.ndarray, fresh_rows, super_rows) -> np.ndarray:
    """Host-side export of the exact-scan row set for the fused serving
    kernel: sealed-build residual ++ fresh rows (added post-build) ++ the
    tenant-agnostic super-node rows, -1-padded to a pow2 bucket so jit
    specializations stay bounded. Super rows ride here so the in-kernel
    super-gate top-1 sees EVERY super node exactly — the gate threshold
    (0.4) must never depend on whether a centroid routed near a super
    node. A super row can then appear twice (its cluster slot + here);
    duplicates only matter for the ANN tier, where the kernel's top-k
    dedup drops them (top-1 gates are duplicate-immune anyway)."""
    base = np.asarray(residual)
    comb = np.concatenate([base[base >= 0],
                           np.asarray(list(fresh_rows), np.int32),
                           np.asarray(list(super_rows), np.int32)])
    padded = np.full((_pow2(len(comb)),), -1, np.int32)
    padded[:len(comb)] = comb
    return padded


def shard_serve_tables(members: np.ndarray, extras: np.ndarray,
                       n_shards: int, part_rows: int
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Split the GLOBAL member/extras tables into per-shard LOCAL-row
    tables for the distributed fused IVF kernel
    (``core.state.make_fused_sharded`` mode="ivf"): shard ``p`` keeps only
    the rows it owns (global rows ``[p·part_rows, (p+1)·part_rows)``),
    re-indexed to local offsets and left-packed per cluster, -1 padded.
    The union over shards is exactly the global candidate set, so the
    distributed scan visits the same rows as the single-chip kernel —
    each from the chip whose HBM holds it. Every per-(shard, cluster)
    member list fits the global member cap, so the stacked table keeps
    the global [C, M] geometry and the local gather never widens."""
    members = np.asarray(members, np.int64)
    extras = np.asarray(extras, np.int64)
    C, M = members.shape
    out_m = np.full((n_shards, C, M), -1, np.int32)
    out_e = np.full((n_shards, max(8, extras.shape[0])), -1, np.int32)
    for p in range(n_shards):
        lo, hi = p * part_rows, (p + 1) * part_rows
        msk = (members >= lo) & (members < hi)
        # left-pack per cluster: stable-sort selected-first
        order = np.argsort(~msk, axis=1, kind="stable")
        out_m[p] = np.take_along_axis(
            np.where(msk, members - lo, -1), order, axis=1).astype(np.int32)
        sel = extras[(extras >= lo) & (extras < hi)] - lo
        out_e[p, :len(sel)] = sel.astype(np.int32)
    return out_m, out_e


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "q_chunk"))
def ivf_search(centroids: jax.Array, members: jax.Array, residual: jax.Array,
               emb: jax.Array, mask: jax.Array, queries: jax.Array,
               k: int, nprobe: int = 8, q_chunk: int = 8
               ) -> Tuple[jax.Array, jax.Array]:
    """Coarse (centroid) → fine (member gather) masked top-k.

    Per query: the shared coarse stage assembles candidates, which are
    scored exactly and top-k'd. Candidate tensors are
    [q_chunk, nprobe·M + R, d], so queries stream in small chunks to
    bound the gather footprint."""
    q = queries.astype(jnp.float32)
    q = q / jnp.maximum(jnp.linalg.norm(q, axis=-1, keepdims=True), 1e-9)
    nprobe = min(nprobe, centroids.shape[0])

    def chunk(q_c):                                        # [qc, d]
        cand, safe, valid = gather_candidates(centroids, members, residual,
                                              mask, q_c, nprobe)
        vecs = emb[safe].astype(jnp.float32)               # [qc, L, d]
        scores = jnp.einsum("qld,qd->ql", vecs, q_c)
        scores = jnp.where(valid, scores, NEG_INF)
        ts, pos = jax.lax.top_k(scores, k)
        return ts, jnp.take_along_axis(cand, pos, axis=1)

    return chunked_map(chunk, q, chunk=q_chunk)