"""Admission-time HBM planner: every fused geometry clears it BEFORE a
kernel compiles or a dispatch launches.

The planner owns three decisions (``plan/model.plan_geometry`` is the
shared decision tree; this class adds telemetry, calibration plumbing,
and the OOM-replan protocol):

- **admit** — predict the geometry's peak HBM; if it fits the budget
  minus headroom, the turn stays the usual ONE fused dispatch.
- **degrade planned** — otherwise chunk the arena scan inside the one
  dispatch (cheapest: still ``dispatches_per_turn == 1``), or split the
  query batch into planned sub-dispatches riding the existing linear pad
  buckets (``plan.split_dispatches`` counts them — a planned
  multi-dispatch turn is recorded, never silent).
- **reject typed** — a geometry no split can fit raises
  :class:`~lazzaro_tpu.reliability.errors.PlanInfeasible` (shed like
  ``LoadShed``; futures resolve with it, never hang).

When a dispatch still dies with ``RESOURCE_EXHAUSTED`` (the model
under-bounded — ``guard.run_guarded`` reclassifies it into the typed
``DeviceOom`` instead of burning retries), :meth:`note_oom` inflates the
model's family multiplier so the same geometry now predicts over budget,
and :meth:`replan_after_oom` hands the caller ONE harder decision (more
splits / smaller chunk) to retry through the copy twins.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from lazzaro_tpu.plan.model import (CostModel, Geometry, PlanDecision,
                                    plan_geometry)


class HbmPlanner:
    """One planner per index (single-chip or pod), sharing the index's
    telemetry registry. ``budget_bytes == 0`` disables it — every
    geometry admits fused, zero overhead on the hot path."""

    def __init__(self, budget_bytes: int = 0,
                 headroom_fraction: float = 0.1,
                 model: Optional[CostModel] = None,
                 telemetry=None, granularity: int = 8,
                 max_splits: int = 16, min_scan_chunk: int = 8,
                 calibration_path: Optional[str] = None):
        self.budget_bytes = max(0, int(budget_bytes))
        self.headroom_fraction = min(0.9, max(0.0,
                                              float(headroom_fraction)))
        self.calibration_path = calibration_path
        self.model = model if model is not None \
            else CostModel.load_or_default(calibration_path)
        self.telemetry = telemetry
        self.granularity = max(1, int(granularity))
        self.max_splits = max(1, int(max_splits))
        self.min_scan_chunk = max(1, int(min_scan_chunk))
        self._lock = threading.Lock()
        self._cache: Dict[tuple, PlanDecision] = {}
        self.decisions = 0
        self.oom_noted = 0

    # ----------------------------------------------------------- plumbing
    @property
    def active(self) -> bool:
        return self.budget_bytes > 0

    def _bump(self, name: str, n: int = 1, **labels) -> None:
        if self.telemetry is not None:
            self.telemetry.bump(name, n, labels=labels or None)

    def _invalidate(self) -> None:
        with self._lock:
            self._cache.clear()

    # -------------------------------------------------------------- plan
    def plan(self, g: Geometry, *, chunkable: bool = True) -> PlanDecision:
        """Plan one geometry (memoized — geometries repeat every turn;
        the cache drops whenever the model learns). Telemetry records the
        decision class and the predicted footprint."""
        if not self.active:
            return PlanDecision(True, 1, 0, 0, 0, "planner disabled")
        key = (g, chunkable)
        with self._lock:
            hit = self._cache.get(key)
        if hit is not None:
            return hit
        d = plan_geometry(self.model, g, self.budget_bytes,
                          self.headroom_fraction, chunkable=chunkable,
                          granularity=self.granularity,
                          max_splits=self.max_splits,
                          min_scan_chunk=self.min_scan_chunk)
        with self._lock:
            if len(self._cache) >= 64:
                self._cache.clear()
            self._cache[key] = d
            self.decisions += 1
        verdict = ("fused" if d.fused
                   else "chunked" if d.feasible and d.splits == 1
                   else "split" if d.feasible else "infeasible")
        self._bump("plan.decisions", verdict=verdict, path=g.kind)
        if self.telemetry is not None:
            self.telemetry.gauge(
                "plan.predicted_bytes", d.predicted_bytes,
                labels={"mode": g.mode, "batch": str(g.batch),
                        "rows": str(g.rows), "mesh": str(g.mesh_parts)})
        return d

    def check_feasible(self, g: Geometry, *,
                       chunkable: bool = True) -> PlanDecision:
        """Admission guard (scheduler / warmup / kernel-cache gates):
        returns the decision, raising the typed ``PlanInfeasible`` when
        no split fits. Import deferred so plan/model stays jax-free for
        the CI sweep."""
        d = self.plan(g, chunkable=chunkable)
        if not d.feasible:
            from lazzaro_tpu.reliability.errors import PlanInfeasible
            self._bump("plan.infeasible", path=g.kind)
            raise PlanInfeasible(
                f"{g.kind} geometry (mode={g.mode}, batch={g.batch}, "
                f"rows={g.rows}, k={g.k}, mesh={g.mesh_parts}) predicts "
                f"{d.predicted_bytes / (1 << 20):.0f} MiB — over the "
                f"{self.budget_bytes / (1 << 20):.0f} MiB budget minus "
                f"headroom, and {d.reason}")
        return d

    # ---------------------------------------------------------- calibrate
    def observe_gauge(self, g: Geometry, measured_bytes: float) -> bool:
        """Feed one AOT ``memory_analysis()`` gauge back into the model
        (called next to the ``kernel.peak_hbm_bytes`` recorders). Grows
        the multiplier when the measurement beats the prediction, drops
        the decision cache, and persists the calibration when a path was
        configured."""
        sound = self.model.observe(g, measured_bytes)
        if not sound:
            self._bump("plan.calibration_growths", path=g.kind)
            self._invalidate()
        if self.calibration_path:
            try:
                self.model.save(self.calibration_path)
            except OSError:
                pass                    # observability must never fail a serve
        return sound

    def note_oom(self, g: Geometry) -> None:
        """A dispatch the plan admitted still OOM'd: the analytic bound
        under-estimated this family. Inflate it so the SAME geometry now
        predicts over budget, and forget cached decisions."""
        self.model.inflate(g)
        self.oom_noted += 1
        self._bump("plan.oom_noted", path=g.kind)
        self._invalidate()
        if self.calibration_path:
            try:
                self.model.save(self.calibration_path)
            except OSError:
                pass

    def replan_after_oom(self, g: Geometry, prev: PlanDecision, *,
                         chunkable: bool = True
                         ) -> Optional[PlanDecision]:
        """ONE harder decision for the replan pass (the caller re-runs it
        through the copy twins): whatever the grown model now says, but
        never laxer than doubling the previous split count. None when
        even the maximal split no longer fits."""
        d = self.plan(g, chunkable=chunkable)
        floor_splits = max(2, prev.splits * 2 if prev.splits else 2)
        if d.feasible and d.splits < floor_splits:
            d = PlanDecision(True, min(floor_splits, self.max_splits),
                             d.scan_chunk, d.predicted_bytes,
                             d.budget_bytes, "post-OOM forced split")
        return d if d.feasible else None

    def stats(self) -> dict:
        return {"active": self.active,
                "budget_bytes": self.budget_bytes,
                "headroom_fraction": self.headroom_fraction,
                "decisions": self.decisions,
                "oom_noted": self.oom_noted,
                "multipliers": dict(self.model.multipliers)}


__all__ = ["HbmPlanner", "Geometry", "PlanDecision", "CostModel",
           "plan_geometry"]
