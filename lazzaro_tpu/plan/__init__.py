"""Admission-time HBM planning for the fused serving/ingest stack
(ISSUE 11, ROADMAP item 9).

``scripts/check_hbm_budget.py`` used to *observe* compiled geometries and
fail CI after the fact; a novel (mode × batch × rows × mesh) request
still OOM'd at runtime with no recovery path. This package makes the
bound a guarantee instead ("Memory Safe Computations with XLA",
PAPERS.md):

- :mod:`~lazzaro_tpu.plan.model` — analytic peak-HBM cost model,
  calibrated against the AOT ``memory_analysis()`` gauges so predictions
  over-bound every recorded measurement (residuals persisted beside the
  kernel-cache artifacts for the CI soundness sweep). Pure stdlib, so
  the CI gate imports it without jax.
- :mod:`~lazzaro_tpu.plan.planner` — the live
  :class:`~lazzaro_tpu.plan.planner.HbmPlanner` every compile gate
  consults: admit fused, chunk the arena scan in-dispatch, split the
  query batch into PLANNED sub-dispatches (``plan.split_dispatches``
  counted — never silent), or reject typed (``PlanInfeasible``). Runtime
  ``RESOURCE_EXHAUSTED`` (reclassified by ``guard.run_guarded``) feeds
  back through ``note_oom`` → one replan through the copy twins.
"""

from lazzaro_tpu.plan.model import (CostModel, Geometry, PlanDecision,
                                    plan_geometry)
from lazzaro_tpu.plan.planner import HbmPlanner

__all__ = ["CostModel", "Geometry", "PlanDecision", "plan_geometry",
           "HbmPlanner"]
