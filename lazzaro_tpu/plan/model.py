"""Analytic peak-HBM cost model for the fused serving/ingest geometries.

"Memory Safe Computations with XLA" (PAPERS.md) argues the memory bound
should be *guaranteed* before compilation, not discovered as a runtime
``RESOURCE_EXHAUSTED``. This module is the prediction half of that
guarantee: given a geometry — (kind × mode × batch × rows × k × mesh) —
it computes an analytic upper bound on the compiled program's peak HBM
from buffer accounting of what the fused kernels actually allocate:

- the RESIDENT live set every dispatch carries (arena columns + int8
  shadow + IVF tables + edge arena + CSR),
- the TRANSIENT high-water mark of the scan itself, dominated by the
  ``[min(batch, scan_chunk), rows]`` f32 score tile the chunked-map
  structure bounds (``ops/chunking.py``), plus query/readback/top-k
  workspace terms linear in the batch.

The model is deliberately conservative and then CALIBRATED against the
measured truth: every AOT ``memory_analysis()`` gauge the PR 6/PR 9
machinery records (``kernel.peak_hbm_bytes{...}``) is fed back through
:meth:`CostModel.observe`, which inflates the per-(kind, mode) safety
multiplier until the prediction over-bounds every recorded gauge. The
multipliers and the residual log persist as JSON beside the kernel-cache
artifacts (``bench_artifacts/plan_calibration.json`` by default), so CI
(``scripts/check_hbm_budget.py``) re-checks model soundness — a gauge
exceeding its prediction fails the gate — without recompiling anything.

Pure stdlib on purpose: the CI gate loads this file directly
(``importlib`` by path) so the budget sweep never pays a jax import.
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import asdict, dataclass
from typing import Dict, Optional

# Mirrors ops/chunking.QUERY_CHUNK and core/state.IVF_SERVE_CHUNK; kept as
# literals so this module stays importable without jax. A drift here only
# loosens/tightens the bound — soundness is restored by calibration.
QUERY_CHUNK = 512
IVF_SERVE_CHUNK = 32

# Per-row bytes of the non-embedding arena columns (salience, timestamp,
# last_accessed f32; access_count, type_id, shard_id, tenant_id i32;
# alive, is_super bool — padded to 4 for alignment conservatism).
ARENA_META_BYTES = 7 * 4 + 2 * 4
# Per-slot bytes of the edge arena (src, tgt i32; weight f32; co i32;
# last_updated f32; alive bool→4; tenant_id i32).
EDGE_SLOT_BYTES = 7 * 4

# Default safety multipliers per (kind, mode-family). XLA's compiled peak
# includes fusion temporaries and layout padding the analytic terms can't
# see; these start conservative and only ever grow under calibration.
_DEFAULT_MULTIPLIER = 1.25

# Fixed per-dispatch workspace floor. XLA's AOT peak carries a
# size-independent temp-buffer floor (alignment slop, collective
# scratch, the sort workspace's minimum granule) that dominates TINY
# geometries — a multiplicative model can only cover it by inflating
# the family multiplier far past what real sizes need, so it is a
# constant term instead (ISSUE 18: surfaced by the replica bench's
# 706-row ingest gauges).
DISPATCH_WORKSPACE_BYTES = 2 << 20


@dataclass(frozen=True)
class Geometry:
    """One fused-dispatch geometry the planner reasons about.

    ``rows`` is the GLOBAL padded arena length (capacity + sentinel);
    ``mesh_parts`` divides it into the per-chip slice the shard-local
    cores scan. ``batch`` is the PADDED query (or fact) batch.
    ``scan_chunk = 0`` means the kernel's default chunk structure
    (``QUERY_CHUNK``, or ``IVF_SERVE_CHUNK`` for the IVF gather).
    ``pool_rows`` (ISSUE 17) is the PHYSICAL embedding pool length of a
    paged arena — 0 means dense (pool == rows). Only the embedding slab
    and the scan tiles that stream it scale with the pool; every other
    column stays logical-length."""

    kind: str = "serve"          # "serve" | "ingest" | "lifecycle"
    mode: str = "exact"          # exact | quant | ivf | pq | tiered
    batch: int = 8
    rows: int = 1024
    dim: int = 768
    k: int = 128
    dtype_bytes: int = 4         # master-arena embedding dtype
    mesh_parts: int = 1
    edge_cap: int = 0
    nprobe: int = 0
    scan_chunk: int = 0
    pool_rows: int = 0           # paged arena: physical emb pool length
    link_k: int = 3              # ingest link-scan width per shard mode
    # Online-IVF maintenance rides the ingest dispatch (ISSUE 12): 1 adds
    # the centroid block + member/counts tables to the resident set and
    # the [batch, C] assignment tile + [C, d] update workspace to the
    # transient (serve-side IVF geometry is carried by mode="ivf").
    ivf: int = 0
    # Member-table capacity factor (slots ≈ factor · rows total).
    ivf_cap_factor: int = 4
    # PQ code maintenance rides the ingest dispatch (ISSUE 16): 1 adds
    # the u8 code slab + codebook to the resident set and the batch
    # encode tile to the transient (serve-side PQ geometry is carried by
    # mode="pq").
    pq: int = 0
    # Exact-rescore over-fetch depth (``coarse_fetch_slack``): the PQ
    # serve kernel gathers and f32-rescores ``k + slack`` shortlist rows
    # per query, so the transient term is LINEAR in it — a per-family
    # multiplier cannot absorb a knob the operator can turn.
    slack: int = 8
    # Replica-group serving (ISSUE 18): the mesh is partitioned into G
    # groups that each hold a FULL copy of the arena, so ``mesh_parts``
    # here is already the per-GROUP shard count (chips // groups) and the
    # per-chip byte terms need no change — but admission must label the
    # geometry so a planner sweep can see that G groups multiply the
    # fleet-wide resident footprint while leaving the per-chip slice
    # rows / (chips/G).
    replica_groups: int = 1
    # Semantic query cache (ISSUE 20): ring slots per serving index.
    # 0 means the cache is off. Each slot is resident device state —
    # normalized query embedding + packed top-k result columns + the
    # condition columns the probe masks on — and the probe adds a
    # [batch, slots] similarity tile to the transient set. ``sem_width``
    # is the stored result width (k, or k + slack for tiered modes).
    sem_slots: int = 0
    sem_width: int = 0

    def with_(self, **kw) -> "Geometry":
        d = asdict(self)
        d.update(kw)
        return Geometry(**d)


def _mode_family(mode: str) -> str:
    """Collapse pod/sharded prefixes onto the core scan family — the
    calibration multiplier is per family, the rows-per-chip term already
    carries the mesh geometry."""
    m = mode.replace("sharded_", "").replace("pod_", "")
    if m.startswith("pq"):
        return "pq"
    if m.startswith("ivf"):
        return "ivf"
    return (m if m in ("exact", "quant", "tiered", "ingest", "lifecycle")
            else "exact")


class CostModel:
    """Analytic buffer accounting + per-(kind, family) calibrated
    multipliers. ``predict`` returns an over-bounding byte estimate;
    ``observe`` folds a measured AOT gauge back in, growing the
    multiplier whenever the measurement beats the analytic bound."""

    def __init__(self, multipliers: Optional[Dict[str, float]] = None):
        self.multipliers: Dict[str, float] = dict(multipliers or {})
        # (geometry-ish key) -> {"predicted": .., "observed": ..} of every
        # observe() call — the residual log CI checks and bench persists.
        self.residuals: Dict[str, Dict[str, float]] = {}

    # ------------------------------------------------------------- predict
    def _mult(self, kind: str, mode: str) -> float:
        return self.multipliers.get(f"{kind}:{_mode_family(mode)}",
                                    _DEFAULT_MULTIPLIER)

    def resident_bytes(self, g: Geometry) -> int:
        """Per-chip resident live set: every dispatch carries the whole
        of it regardless of batch, so no split can shrink it — this is
        the feasibility floor."""
        rows_pc = -(-g.rows // max(1, g.mesh_parts))
        fam = _mode_family(g.mode)
        # Paged arena (ISSUE 17): the embedding slab is pool-shaped —
        # pages-in-use, not N — while the metadata columns stay logical.
        emb_rows_pc = (-(-g.pool_rows // max(1, g.mesh_parts))
                       if g.pool_rows else rows_pc)
        total = emb_rows_pc * g.dim * g.dtype_bytes \
            + rows_pc * ARENA_META_BYTES
        if g.pool_rows:
            # row_map (logical, i32) + inv_map/free-stack (pool, i32 each)
            total += rows_pc * 4 + emb_rows_pc * 8
        if fam in ("quant", "tiered", "ivf") or g.kind == "ingest":
            # int8 shadow codes + f32 scales (maintained in-kernel by the
            # fused ingest; streamed by every coarse stage). The exact
            # serve mode carries none, but ingest always may.
            if fam != "exact" or g.kind == "ingest":
                total += rows_pc * (g.dim + 4)
        if fam == "tiered":
            total += rows_pc            # residency mask (bool→byte)
        if fam == "pq":
            # u8 code slab (m ≈ dim/8 bytes per row — the smallest
            # resident coarse representation any mode carries), the
            # replicated codebook (256·dim f32 regardless of m), the
            # coarse routing tables, and the residency byte pq_tiered
            # adds (carried unconditionally: one byte/row of slack)
            m_sub = max(1, g.dim // 8)
            n_cent = max(1, int(math.sqrt(g.rows)))
            total += rows_pc * m_sub
            total += 256 * g.dim * 4
            total += n_cent * g.dim * 4 + rows_pc * 8
            total += rows_pc
        if fam == "ivf":
            # centroids (replicated) + member/extras tables ~ one int32
            # routing entry per row plus the centroid block
            n_cent = max(1, int(math.sqrt(g.rows)))
            total += n_cent * g.dim * 4 + rows_pc * 8
        if g.kind == "ingest" and g.pq:
            # PQ pack donated through the ingest dispatch (ISSUE 16):
            # the u8 code slab (row-sharded with the master) + the
            # replicated codebook.
            total += rows_pc * max(1, g.dim // 8) + 256 * g.dim * 4
        if g.kind == "ingest" and g.ivf:
            # Online-IVF state donated through the ingest dispatch
            # (ISSUE 12): centroid block (f32, replicated), member table
            # (cap_factor int32 slots per row, row-sharded with the
            # master) and the counts column.
            n_cent = max(1, int(math.sqrt(g.rows)))
            total += n_cent * (g.dim + 1) * 4
            total += rows_pc * max(1, g.ivf_cap_factor) * 4
        total += g.edge_cap * EDGE_SLOT_BYTES
        # CSR shadow (indptr + neighbor pool ≈ 2 entries/edge, i32)
        total += (rows_pc + 2) * 4 + 2 * g.edge_cap * 4
        if g.sem_slots and g.kind == "serve":
            # Semantic ring (ISSUE 20): replicated per chip — slots+1
            # rows (sentinel scratch row included) of normalized query
            # embedding, packed (score, row) result columns at the
            # stored width, and the five condition/verdict columns.
            w = g.sem_width or g.k
            total += (g.sem_slots + 1) * (g.dim * 4 + w * 8 + 25)
        return int(total)

    def transient_bytes(self, g: Geometry) -> int:
        """Scan high-water mark: the chunk-bounded score tile plus the
        batch-linear query/readback/top-k terms. THIS is what batch
        splitting and scan chunking shrink."""
        rows_pc = -(-g.rows // max(1, g.mesh_parts))
        # The dense/link scans stream the PHYSICAL embedding pool of a
        # paged arena (scores land in pool space, decoded via inv_map).
        scan_rows_pc = (-(-g.pool_rows // max(1, g.mesh_parts))
                        if g.pool_rows else rows_pc)
        fam = _mode_family(g.mode)
        if g.kind == "lifecycle":
            # The all-tenant maintenance sweep (ISSUE 19) never streams
            # the embedding slab — its high-water mark is the [tenants,
            # rows] masked-importance tile behind the per-tenant bottom-k
            # (``batch`` carries the verdict-tenant count, ``k`` the
            # archive depth), the edge decay/prune working set (decayed
            # weight copy + cumsum positions + victim buffer), and the
            # packed payload readback.
            tv = max(1, g.batch)
            tile = tv * (rows_pc + 1) * 4 * 2
            tile += 3 * g.edge_cap * 4
            tile += (2 * tv * g.k + g.edge_cap + 8) * 4
            return int(tile + DISPATCH_WORKSPACE_BYTES)
        default_chunk = (IVF_SERVE_CHUNK if fam in ("ivf", "pq")
                         else QUERY_CHUNK)
        chunk = min(g.batch, g.scan_chunk or default_chunk)
        chunk = max(1, chunk)
        if fam == "pq":
            # ADC member scan: the per-chunk flat LUT [chunk, m·256] f32,
            # the gathered candidate codes [chunk, cands, m] u8 + their
            # coarse scores, and the exact-rescore gather of the
            # k+slack shortlist from the master
            n_cent = max(1, int(math.sqrt(g.rows)))
            m = -(-g.rows // n_cent)
            m_sub = max(1, g.dim // 8)
            cands = max(1, g.nprobe or 4) * m + g.k
            tile = chunk * m_sub * 256 * 4
            tile += chunk * cands * (m_sub + 8)
            # shortlist gather + the sorted copy XLA keeps beside it —
            # k + slack rows deep (the coarse_fetch_slack knob), f32
            tile += chunk * (g.k + max(8, g.slack) + 16) \
                * (g.dim + 2) * 4 * 2
        elif fam == "ivf":
            # the gather footprint: [chunk, nprobe·M + extras, d] f32
            # candidate block; M ≈ rows/√rows member slots per cluster
            n_cent = max(1, int(math.sqrt(g.rows)))
            m = -(-g.rows // n_cent)
            cands = max(1, g.nprobe or 4) * m + g.k
            tile = chunk * cands * (g.dim + 2) * 4
        elif fam == "ingest":
            # the multi-mode link/dedup scan streams [chunk, rows] f32
            # once (PR 9 single-stream refactor) + candidate triples
            tile = chunk * (scan_rows_pc + 1) * 4 \
                + chunk * max(1, g.link_k) * 3 * 4 * 2
            if g.ivf:
                # the [batch, C] assignment tile, the [C, d] centroid
                # update workspace (sums + proposal), and the batch-wide
                # intra-cluster rank matrix (ISSUE 12)
                n_cent = max(1, int(math.sqrt(g.rows)))
                tile += g.batch * n_cent * 4
                tile += 3 * n_cent * g.dim * 4
                tile += g.batch * g.batch * 4
            if g.pq:
                # the in-dispatch batch encode (ISSUE 16): [batch, m,
                # 256] sub-distance tile against the frozen codebook
                tile += g.batch * max(1, g.dim // 8) * 256 * 4
        else:
            # dense scan: [chunk, rows] f32 scores + the two mask tiles
            # and the top-k workspace XLA materializes beside them
            tile = chunk * (scan_rows_pc + 1) * 4 * 3
        q_bytes = g.batch * g.dim * 4 * 2              # query + normalized
        readback = g.batch * (3 + 2 * g.k + 5) * 4 * 2
        sidecars = g.batch * 4 * 6                     # k/cap/nprobe/flags
        sem_tile = 0
        if g.sem_slots and g.kind == "serve":
            # probe similarity tile + miss-first sort workspace
            sem_tile = g.batch * (g.sem_slots + 8) * 4
        return int(tile + q_bytes + readback + sidecars + sem_tile
                   + DISPATCH_WORKSPACE_BYTES)

    def predict(self, g: Geometry) -> int:
        """Calibrated upper bound on the compiled program's peak HBM."""
        raw = self.resident_bytes(g) + self.transient_bytes(g)
        return int(raw * self._mult(g.kind, g.mode))

    # ----------------------------------------------------------- calibrate
    @staticmethod
    def _res_key(g: Geometry) -> str:
        return (f"{g.kind}:{g.mode}:b{g.batch}:r{g.rows}:k{g.k}"
                f":m{g.mesh_parts}" + (":ivf" if g.ivf else "")
                + (":pq" if g.pq else "")
                + (f":p{g.pool_rows}" if g.pool_rows else "")
                + (f":g{g.replica_groups}" if g.replica_groups > 1 else ""))

    def observe(self, g: Geometry, measured_bytes: float) -> bool:
        """Fold one measured AOT ``memory_analysis()`` peak back in.
        Returns True when the prediction already over-bounded it; False
        means the multiplier was GROWN so it does now (with 5% margin) —
        predictions must over-bound every recorded gauge."""
        measured = float(measured_bytes)
        predicted = self.predict(g)
        self.residuals[self._res_key(g)] = {
            "predicted": float(predicted), "observed": measured,
            "ratio": round(measured / max(predicted, 1.0), 4)}
        if measured <= predicted:
            return True
        raw = self.resident_bytes(g) + self.transient_bytes(g)
        key = f"{g.kind}:{_mode_family(g.mode)}"
        self.multipliers[key] = max(
            self.multipliers.get(key, _DEFAULT_MULTIPLIER),
            measured / max(raw, 1.0) * 1.05)
        return False

    def inflate(self, g: Geometry, factor: float = 2.0) -> None:
        """Post-OOM learning: the geometry OOM'd although the prediction
        said it fit, so the analytic bound under-estimated — grow the
        family multiplier until this geometry predicts ≥ factor × its
        previous estimate. The next plan for the same family will split
        harder (or declare infeasibility) instead of re-OOMing."""
        key = f"{g.kind}:{_mode_family(g.mode)}"
        self.multipliers[key] = \
            self.multipliers.get(key, _DEFAULT_MULTIPLIER) * float(factor)

    # -------------------------------------------------------------- persist
    def to_dict(self) -> dict:
        return {"multipliers": dict(self.multipliers),
                "residuals": dict(self.residuals)}

    def save(self, path: str) -> None:
        """Persist calibration beside the kernel-cache artifacts (atomic
        replace; the CI sweep and the next process both load it)."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "CostModel":
        with open(path) as f:
            data = json.load(f)
        model = cls(multipliers=data.get("multipliers") or {})
        model.residuals = dict(data.get("residuals") or {})
        return model

    @classmethod
    def load_or_default(cls, path: Optional[str]) -> "CostModel":
        if path:
            try:
                return cls.load(path)
            except (OSError, ValueError):
                pass
        return cls()


@dataclass(frozen=True)
class PlanDecision:
    """What the planner decided for one geometry: run it fused
    (``splits == 1, scan_chunk == 0``), chunk the arena scan inside the
    ONE dispatch, split the batch into ``splits`` planned sub-dispatches,
    or reject it (``feasible == False``)."""

    feasible: bool
    splits: int = 1
    scan_chunk: int = 0
    predicted_bytes: int = 0
    budget_bytes: int = 0
    reason: str = "fits"

    @property
    def fused(self) -> bool:
        return self.feasible and self.splits == 1 and self.scan_chunk == 0


def _bucket(n: int, granularity: int) -> int:
    g = max(1, granularity)
    return max(g, -(-n // g) * g)


def plan_geometry(model: CostModel, g: Geometry, budget_bytes: int,
                  headroom_fraction: float = 0.1, *,
                  chunkable: bool = True, granularity: int = 8,
                  max_splits: int = 16, min_scan_chunk: int = 8
                  ) -> PlanDecision:
    """The split decision tree (shared by the live planner and the CI
    sweep), cheapest-degradation-first:

    1. **fused** — the geometry fits as-is: ONE dispatch, default chunks.
    2. **chunk the scan** — halve the in-kernel query chunk (the
       ``[chunk, rows]`` score tile is the dominant transient) until the
       prediction fits: STILL one dispatch, ``dispatches_per_turn`` stays
       1, only the streaming granularity changes (bit-identical results).
    3. **split the batch** — sub-dispatches riding the existing linear
       pad buckets (each sub-batch re-buckets to ``granularity``),
       combined with the best scan chunk; a planned multi-dispatch turn,
       recorded as such.
    4. **infeasible** — the per-chip RESIDENT set alone (which no split
       can shrink) or even the maximally-split geometry exceeds the
       budget: typed rejection, shed like LoadShed.
    """
    if budget_bytes <= 0:
        return PlanDecision(True, 1, 0, model.predict(g), 0,
                            "planner disabled")
    eff = int(budget_bytes * (1.0 - max(0.0, headroom_fraction)))
    pred = model.predict(g)
    if pred <= eff:
        return PlanDecision(True, 1, 0, pred, eff, "fits")
    # The resident floor bounds what ANY split can reach.
    floor = int(model.resident_bytes(g) * model._mult(g.kind, g.mode))
    if floor > eff:
        return PlanDecision(False, 0, 0, floor, eff,
                            "resident live set alone exceeds the budget")
    fam = _mode_family(g.mode)
    default_chunk = IVF_SERVE_CHUNK if fam == "ivf" else QUERY_CHUNK
    best_chunk = 0
    if chunkable:
        c = min(g.batch, default_chunk)
        while c >= min_scan_chunk:
            p = model.predict(g.with_(scan_chunk=c))
            if p <= eff:
                return PlanDecision(True, 1, c, p, eff, "scan chunked")
            best_chunk = c
            c //= 2
        best_chunk = max(min_scan_chunk, best_chunk // 2 or min_scan_chunk)
    for s in range(2, max_splits + 1):
        sub = _bucket(-(-g.batch // s), granularity)
        sg = g.with_(batch=sub,
                     scan_chunk=(min(best_chunk, sub) if chunkable else 0))
        p = model.predict(sg)
        if p <= eff:
            return PlanDecision(True, s, sg.scan_chunk, p, eff,
                                f"batch split {s}-way")
        if sub <= granularity:
            break                       # can't split finer than one bucket
    return PlanDecision(False, 0, 0, pred, eff,
                        "no batch split or scan chunk fits the budget")


__all__ = ["Geometry", "CostModel", "PlanDecision", "plan_geometry",
           "QUERY_CHUNK", "IVF_SERVE_CHUNK", "ARENA_META_BYTES",
           "EDGE_SLOT_BYTES"]
