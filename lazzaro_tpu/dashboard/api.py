"""Dashboard HTTP API + live force-graph UI.

Parity target: reference ``dashboard/api.py`` (FastAPI, 142 LoC) — same route
surface:
  GET  /                 → HTML dashboard
  GET  /api/stats        → get_stats + user_id (after check_for_updates)
  GET  /api/users        → all user ids
  POST /api/users/switch → switch_user
  GET  /api/insights     → LLM insights
  GET  /api/export?format= → observations export
  GET  /api/graph        → {nodes, links} for the force graph
  GET  /api/profile      → profile domains
  POST /api/consolidate  → run_consolidation

Observability additions (ISSUE 6, no reference counterpart):
  GET  /metrics          → Prometheus text exposition of the system's
                           Telemetry registry (serving spans, device-side
                           readback counters, pad-waste, peak-HBM gauges)
  GET  /api/metrics      → the same registry as JSON
                           (``MemorySystem.metrics_summary()``)
  GET  /api/reliability  → the reliability layer's derived view (ISSUE 10:
                           circuit-breaker state, dispatch-retry / shed /
                           worker-restart counters, ingest-journal depth,
                           poisoned flag — ``reliability_summary()``)

Differences by design: built on stdlib ``http.server`` (zero extra deps in
this image; FastAPI optional elsewhere), and the UI is fully self-contained
vanilla JS + canvas (the reference pulls Vue/Tailwind/force-graph from CDNs,
which fails in offline deployments).
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

_ms = None
_ms_lock = threading.Lock()


def set_memory_system(ms) -> None:
    global _ms
    _ms = ms


def _template_path() -> str:
    return os.path.join(os.path.dirname(__file__), "templates", "index.html")


def _graph_payload(ms) -> dict:
    nodes, links = [], []
    for shard_key, shard in ms.shards.items():
        for node_id, node in shard.nodes.items():
            nodes.append({
                "id": node_id,
                "content": node.content,
                "type": node.type,
                "salience": node.salience,
                "shard": shard_key,
                "access_count": node.access_count,
                "is_super_node": node.is_super_node,
            })
        for (src, tgt), edge in shard.edges.items():
            links.append({
                "source": src,
                "target": tgt,
                "weight": edge.weight,
                "type": edge.edge_type,
            })
    for node_id, node in ms.super_nodes.items():
        nodes.append({
            "id": node_id,
            "content": node.content,
            "type": "super_node",
            "salience": node.salience,
            "shard": "global",
            "is_super_node": True,
        })
    return {"nodes": nodes, "links": links}


class DashboardHandler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _send(self, payload, status=200, content_type="application/json"):
        if isinstance(payload, (dict, list)):
            body = json.dumps(payload).encode()
        elif isinstance(payload, str):
            body = payload.encode()
        else:
            body = payload
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        url = urlparse(self.path)
        ms = _ms
        if url.path == "/":
            try:
                with open(_template_path()) as f:
                    self._send(f.read(), content_type="text/html")
            except FileNotFoundError:
                self._send("dashboard template missing", 500, "text/plain")
            return
        if ms is None:
            self._send({"error": "Memory system not initialized"}, 503)
            return
        with _ms_lock:
            if url.path == "/metrics":
                # Prometheus scrape surface: the SAME registry
                # metrics_summary() reads, rendered as text exposition —
                # plus the derived headline gauges so a scrape alone
                # carries the pad-waste/queue-wait numbers CI checks.
                summary = ms.metrics_summary()
                extra = []
                for key in ("pad_waste_fraction", "queue_wait_ms_p50",
                            "queue_wait_ms_p95", "serve_dispatches",
                            "ingest_dispatches", "link_pool_overflows"):
                    val = summary.get(key)
                    if val is not None:
                        extra.append(f"lazzaro_{key} {val}")
                # Paged arena (ISSUE 17): page occupancy headline — the
                # arena.pages_* gauges also ride the registry exposition
                # above; these derived rows carry the free-list totals.
                paged = summary.get("paged_arena")
                if paged:
                    for key in ("pages_total", "pages_free",
                                "fragmentation", "pops_total",
                                "pushes_total"):
                        extra.append(
                            f"lazzaro_arena_{key} {paged[key]}")
                body = ms.telemetry.prometheus()
                if extra:
                    body += "\n".join(extra) + "\n"
                self._send(body,
                           content_type="text/plain; version=0.0.4; "
                                        "charset=utf-8")
            elif url.path == "/api/metrics":
                self._send(ms.metrics_summary())
            elif url.path == "/api/reliability":
                self._send(ms.reliability_summary())
            elif url.path == "/api/stats":
                ms.check_for_updates()
                stats = ms.get_stats()
                stats["user_id"] = ms.user_id
                self._send(stats)
            elif url.path == "/api/users":
                self._send(ms.get_all_users())
            elif url.path == "/api/insights":
                self._send({"insights": ms.get_insights()})
            elif url.path == "/api/export":
                fmt = parse_qs(url.query).get("format", ["markdown"])[0]
                self._send({"content": ms.export_observations(format=fmt)})
            elif url.path == "/api/graph":
                ms.check_for_updates()
                self._send(_graph_payload(ms))
            elif url.path == "/api/profile":
                self._send({"profile": ms.profile.data,
                            "last_updated": ms.profile.last_updated})
            else:
                self._send({"error": "not found"}, 404)

    def do_POST(self):
        url = urlparse(self.path)
        ms = _ms
        if ms is None:
            self._send({"error": "Memory system not initialized"}, 503)
            return
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b"{}"
        try:
            data = json.loads(raw or b"{}")
        except json.JSONDecodeError:
            self._send({"error": "invalid JSON body"}, 400)
            return
        with _ms_lock:
            if url.path == "/api/users/switch":
                new_user = data.get("user_id")
                if not new_user:
                    self._send({"error": "User ID required"}, 400)
                    return
                ms.switch_user(new_user)
                self._send({"status": "success", "user_id": ms.user_id})
            elif url.path == "/api/consolidate":
                result = ms.run_consolidation()
                self._send({"status": "success", "result": result})
            else:
                self._send({"error": "not found"}, 404)


def make_server(ms, host: str = "0.0.0.0", port: int = 5299) -> ThreadingHTTPServer:
    set_memory_system(ms)
    return ThreadingHTTPServer((host, port), DashboardHandler)


def entry_point(host: str = "0.0.0.0", port: int = 5299,
                db_dir: str = "db") -> None:
    # The dashboard serves JSON over HTTP — it must NEVER initialize the
    # accelerator backend. In the one-tunnel TPU environment, a long-lived
    # dashboard process that touches jax.devices() holds the tunnel and
    # wedges every other JAX process (this is exactly what invalidated
    # round 3's benchmark evidence — VERDICT.md weak #1). Force CPU before
    # any jnp op runs.
    from lazzaro_tpu.utils import backend_probe
    backend_probe.force_cpu()

    from lazzaro_tpu.core.memory_system import MemorySystem

    ms = MemorySystem(load_from_disk=True, db_dir=db_dir)
    server = make_server(ms, host, port)
    print(f"📊 lazzaro-tpu dashboard on http://{host}:{port}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        ms.close()


if __name__ == "__main__":
    entry_point()
