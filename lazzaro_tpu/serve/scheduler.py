"""Cross-request query batching for the fused retrieval kernel.

Serving millions of users means the unit of device work must be the BATCH,
not the request: on the tunneled TPU backend every dispatch+readback costs a
~70 ms round trip regardless of how many queries ride in it, and BENCH_r05
rooflines put per-request serving under 1% of implied HBM bandwidth. The
``QueryScheduler`` here coalesces concurrent ``search_memories`` / ``chat``
retrievals — across callers, threads, and tenants — into padded mega-batches
the way Ragged Paged Attention coalesces ragged decode work on TPU:

- callers ``submit()`` a :class:`RetrievalRequest` and block on the returned
  future; a single worker thread owns the device dispatch (which also keeps
  the donated state mutation single-writer);
- the flush decision is the shared time/size policy (``utils.batching.
  FlushPolicy``): a full ``max_batch`` flushes immediately, a lone trickle
  request waits at most ``max_wait_us`` before it ships;
- the executor pads the popped batch to a power-of-two bucket before
  dispatch (``utils.batching.pad_to_pow2``), so the number of distinct jit
  specializations stays bounded no matter what batch sizes arrive;
- results demux back per request: the executor returns one
  :class:`RetrievalResult` per submitted request, in order, and per-request
  tenant ids ride INTO the kernel as a device column — tenant isolation is
  enforced by the same mask arithmetic as everywhere else, never by
  splitting batches.

The scheduler is deliberately generic over its ``executor`` callable:
``MemoryIndex`` plugs in the fused kernel (``search_fused_requests`` —
which routes to the exact dense, the quantized two-stage, or the IVF
coarse-prefilter program depending on ``int8_serving`` / a published IVF
build, and under a mesh to the DISTRIBUTED fused program,
``state.make_fused_sharded``, so int8, IVF, and pod modes all keep the
cross-request mega-batching, the one-dispatch turn, and zero-RTT
query-cache hits), while ``parallel.index.ShardedMemoryIndex`` plugs in
its own pod executor (``serve_requests``) — since ISSUE 5 the SAME full
chat-turn program as one distributed shard_map dispatch per mixed-tenant
mega-batch. Same coalescing, same policy, different device program.

Failure model (ISSUE 10) — a request future resolves with a RESULT or a
TYPED ERROR; it never blocks forever:

- an **executor exception** demuxes to every future of that batch (the
  PR 2 behavior) and counts a breaker failure;
- a **worker-thread death** anywhere outside the demuxed executor call
  fails the admitted batch's futures with :class:`WorkerCrashed` and the
  worker RESTARTS (``reliability.worker_restarts``) — pending requests
  stay queued and are served by the restarted worker;
- a **dispatch deadline** (``dispatch_timeout_s > 0``) arms a watchdog
  per dispatch: on expiry the batch's futures fail with
  :class:`DispatchTimeout` while the stuck dispatch is left to finish
  (its late results are discarded) and the breaker records the failure;
- **sustained pressure** opens the circuit breaker
  (``breaker_threshold`` consecutive failures/timeouts): for
  ``breaker_cooldown_s`` every batch is served DEGRADED — per-request
  ``nprobe``/``cap_take`` clamped to the cheap rung — then one
  half-open probe at full quality decides re-close vs re-open;
- **admission overload** (``shed_depth``/``shed_bytes`` exceeded) fails
  new submissions immediately with :class:`LoadShed`
  (``reliability.load_shed``) — the device never sees them;
- **memory-infeasible geometry** (ISSUE 11): with an ``admission_check``
  wired (the HBM planner's minimum-geometry probe), a submission whose
  geometry no split can fit fails immediately with the typed
  :class:`PlanInfeasible` — shed exactly like ``LoadShed``, before the
  queue, so a request that could never dispatch is never admitted. The
  executor raising ``PlanInfeasible`` mid-batch demuxes to the batch's
  futures like any other typed error (futures never hang either way).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from lazzaro_tpu.reliability import faults
from lazzaro_tpu.reliability.errors import (DispatchTimeout, LoadShed,
                                            PlanInfeasible, WorkerCrashed)
from lazzaro_tpu.reliability.watchdog import CircuitBreaker
from lazzaro_tpu.utils.batching import FlushPolicy
from lazzaro_tpu.utils.compat import step_trace_annotation
from lazzaro_tpu.utils.hashing import tenant_home_group
from lazzaro_tpu.utils.telemetry import default_registry

logger = logging.getLogger("lazzaro_tpu.serve")


@dataclass
class RetrievalRequest:
    """One query's worth of the chat-turn retrieval sequence.

    ``boost=True`` asks the device to apply the access-salience boost to the
    returned top rows and the neighbor-salience boost to their CSR
    neighbors IN the same dispatch (the chat path); ``boost=False`` is a
    pure read (``search_memories``). ``gate_enabled`` switches the
    super-node top-1 gate evaluation on (the device skips boosts for
    queries whose gate fires — the host owns the hierarchy fast path)."""

    query: np.ndarray
    tenant: str
    k: int = 10
    gate_enabled: bool = False
    boost: bool = False
    super_filter: int = -1      # reserved; the fused kernel serves both tiers
    # Ragged per-request knobs (ISSUE 7): ride into the fused kernel as
    # int32 sidecar data, so one compiled kernel serves any mix. None =
    # the index's configured default (retrieval cap / build nprobe).
    cap_take: Optional[int] = None   # per-request boost/retrieval cap
    nprobe: Optional[int] = None     # per-request IVF probe width


@dataclass
class RetrievalResult:
    ids: List[str] = field(default_factory=list)
    scores: List[float] = field(default_factory=list)
    gate_id: Optional[str] = None
    gate_score: float = float("-inf")
    fast: bool = False          # device gate verdict (gate_enabled & > gate)
    boosted: bool = False       # device applied this query's boosts
    # Tiered memory (ISSUE 8): how many of this query's final top-k rows
    # were served from the host cold tier (0 on an all-hot turn — the
    # turn then cost exactly ONE dispatch).
    cold_hits: int = 0


Executor = Callable[[List[RetrievalRequest]], List[RetrievalResult]]


def _fail_future(fut: Future, err: BaseException) -> None:
    """Set an exception, tolerating a future that already resolved (the
    watchdog and the late dispatch race by design)."""
    if fut.cancelled():
        return
    try:
        fut.set_exception(err)
    except InvalidStateError:
        pass


def _set_future(fut: Future, res) -> None:
    if fut.cancelled():
        return
    try:
        fut.set_result(res)
    except InvalidStateError:
        pass            # watchdog already failed it — late result discarded


class QueryScheduler:
    """Coalesce concurrent retrievals into dense device batches.

    One daemon worker thread pops pending requests and runs ``executor``
    on them; callers block on per-request futures. ``close()`` drains
    pending work before returning. The worker is crash-restarting and
    every failure path resolves futures with a typed error (see the
    module docstring's failure model).

    Two batching disciplines (ISSUE 7):

    - **continuous** (default): requests admit into the NEXT dispatch the
      moment the worker is free — the in-flight dispatch is the batching
      window. A lone request on an idle scheduler ships immediately
      (latency = dispatch time, never the flush timeout), and arrivals
      during a dispatch coalesce into the next one without any timer.
      Per-tenant admission control (``tenant_max_inflight``) caps how
      many of one tenant's requests enter a single dispatch, walking the
      queue oldest-first so over-cap requests keep their place for the
      next batch — one flooding tenant cannot monopolize the device.
    - **flush-boundary** (``continuous=False``, the PR 2–6 policy): a
      batch ships when it holds ``max_batch`` requests or its oldest has
      waited ``max_wait_us`` (default 2 ms). Kept for A/B and fallback.
    """

    def __init__(self, executor: Executor, max_batch: int = 64,
                 max_wait_us: int = 2000, name: str = "lz-query-scheduler",
                 telemetry=None, continuous: bool = True,
                 tenant_max_inflight: int = 0,
                 dispatch_timeout_s: float = 0.0,
                 breaker_threshold: int = 5,
                 breaker_cooldown_s: float = 5.0,
                 shed_depth: int = 0, shed_bytes: int = 0,
                 degrade_cap_take: int = 1, degrade_nprobe: int = 1,
                 admission_check: Optional[Callable] = None):
        self._executor = executor
        # Memory-safe admission (ISSUE 11): an optional callable invoked
        # with the submitted request group BEFORE it queues; raising
        # PlanInfeasible fails the group's futures typed right here —
        # shed like LoadShed, the device never sees them.
        self.admission_check = admission_check
        # Serving telemetry (ISSUE 6): every request records its
        # enqueue→flush queue wait (per-tenant label), every flushed batch
        # one batch-size sample — N coalesced requests therefore yield N
        # queue-wait samples and the executor's ONE dispatch sample.
        self.telemetry = telemetry if telemetry is not None \
            else default_registry()
        self.policy = FlushPolicy(max_batch, max_wait_us / 1e6)
        self.continuous = bool(continuous)
        self.tenant_max_inflight = max(0, int(tenant_max_inflight))
        # Reliability knobs (ISSUE 10)
        self.dispatch_timeout_s = max(0.0, float(dispatch_timeout_s))
        self.shed_depth = max(0, int(shed_depth))
        self.shed_bytes = max(0, int(shed_bytes))
        self.degrade_cap_take = max(1, int(degrade_cap_take))
        self.degrade_nprobe = max(1, int(degrade_nprobe))
        self.breaker: Optional[CircuitBreaker] = (
            CircuitBreaker(breaker_threshold, breaker_cooldown_s,
                           telemetry=self.telemetry, name=name)
            if breaker_threshold > 0 else None)
        self._cond = threading.Condition()
        self._pending: List[Tuple[RetrievalRequest, Future, float]] = []
        self._pending_bytes = 0
        self._inflight = 0
        self._closed = False
        self.batches_flushed = 0
        self.requests_served = 0
        self.requests_deferred = 0           # tenant-cap admission defers
        self.requests_shed = 0               # admission-control rejections
        self.worker_restarts = 0
        self.watchdog_timeouts = 0
        self.batch_sizes: List[int] = []     # observability (bench reads it)
        self._name = name
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._worker.start()

    @property
    def closed(self) -> bool:
        return self._closed

    # ------------------------------------------------------------- submit
    def submit(self, request: RetrievalRequest) -> "Future[RetrievalResult]":
        return self.submit_many([request])[0]

    def submit_many(self, requests: Sequence[RetrievalRequest]
                    ) -> List["Future[RetrievalResult]"]:
        """Enqueue a group atomically (a ``search_memories_batch`` fleet
        stays contiguous, so it lands in as few flushes as possible).
        Under admission overload the whole group's futures fail
        immediately with :class:`LoadShed` — the futures API is uniform,
        so callers see the typed error at ``.result()`` like any other
        failure."""
        futures = [Future() for _ in requests]
        now = time.time()
        if self.admission_check is not None and requests:
            try:
                self.admission_check(list(requests))
            except PlanInfeasible as err:
                # memory-infeasible geometry: shed typed, like LoadShed —
                # the futures resolve immediately, the queue never grows
                self.requests_shed += len(requests)
                self.telemetry.bump("plan.infeasible_shed", len(requests))
                for fut in futures:
                    _fail_future(fut, err)
                return futures
        nbytes = (sum(np.asarray(r.query).nbytes for r in requests)
                  if self.shed_bytes else 0)
        with self._cond:
            if self._closed:
                raise RuntimeError("QueryScheduler is closed")
            over_depth = (self.shed_depth and
                          len(self._pending) + len(requests)
                          > self.shed_depth)
            over_bytes = (self.shed_bytes and
                          self._pending_bytes + nbytes > self.shed_bytes)
            if over_depth or over_bytes:
                self.requests_shed += len(requests)
                self.telemetry.bump("reliability.load_shed", len(requests))
                reason = "depth" if over_depth else "bytes"
                err = LoadShed(
                    f"admission queue over {reason} budget "
                    f"({len(self._pending)} pending); retry with backoff")
                for fut in futures:
                    _fail_future(fut, err)
                return futures
            for req, fut in zip(requests, futures):
                self._pending.append((req, fut, now))
            self._pending_bytes += nbytes
            self._ensure_worker_locked()
            self._cond.notify()
        return futures

    def _ensure_worker_locked(self) -> None:
        """Respawn the worker if it is gone (belt-and-braces: the restart
        loop already survives crashes, but a dead thread must never let a
        future sit unserved)."""
        if self._closed or self._worker.is_alive():
            return
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name=self._name)
        self._worker.start()

    # ------------------------------------------------------------- worker
    def _run(self) -> None:
        """Crash-restarting wrapper: a worker death fails the admitted
        batch's futures (inside ``_serve_loop``) and restarts the loop —
        pending requests stay queued and are served after the restart.
        Only a clean close exits."""
        while True:
            try:
                self._serve_loop()
                return
            except BaseException:       # noqa: BLE001 — must not die silent
                logger.exception("query-scheduler worker crashed; "
                                 "restarting")
                self.worker_restarts += 1
                self.telemetry.bump("reliability.worker_restarts",
                                    labels={"actor": "query_scheduler"})
                with self._cond:
                    if self._closed and not self._pending:
                        return
                time.sleep(0.005)       # never spin on a persistent fault

    def _serve_loop(self) -> None:
        while True:
            with self._cond:
                while True:
                    now = time.time()
                    oldest = self._pending[0][2] if self._pending else None
                    if self._pending and (
                            self._closed or self.continuous
                            or self.policy.should_flush(len(self._pending),
                                                        now, oldest)):
                        # continuous mode: the worker being free IS the
                        # flush signal — pending work admits immediately
                        # (ISSUE 7 lone-request fix: no serve_flush_us
                        # wait on an idle scheduler).
                        break
                    if self._closed:
                        return
                    timeout = (self.policy.wait_remaining(now, oldest)
                               if self._pending else None)
                    self._cond.wait(timeout)
                batch = self._admit_locked()
                self._inflight += 1
            try:
                # Fault point "scheduler.worker" (ISSUE 10): a raise here
                # models the worker dying OUTSIDE the demuxed executor
                # call — the pre-ISSUE-10 scheduler would strand these
                # futures forever.
                try:
                    faults.fire("scheduler.worker", batch=len(batch))
                    self._execute(batch)
                except BaseException as e:
                    err = WorkerCrashed(
                        f"query-scheduler worker died mid-batch: {e!r}")
                    for _, fut, _ in batch:
                        _fail_future(fut, err)
                    raise
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def _admit_locked(self) -> List[Tuple[RetrievalRequest, Future, float]]:
        """Pop the next dispatch's batch from the pending queue (caller
        holds the lock). Oldest-first; at most ``max_batch``; with a
        tenant cap, at most ``tenant_max_inflight`` requests per tenant
        admit — over-cap requests KEEP their queue position (fairness:
        the deferred oldest request is first in line next dispatch)."""
        limit = self.policy.max_items
        cap = self.tenant_max_inflight
        if not cap:
            batch = self._pending[:limit]
            del self._pending[:len(batch)]
            self._note_admitted_locked(batch)
            return batch
        batch: List[Tuple[RetrievalRequest, Future, float]] = []
        kept: List[Tuple[RetrievalRequest, Future, float]] = []
        counts: dict = {}
        deferred = 0
        for item in self._pending:
            tenant = item[0].tenant
            if len(batch) < limit and counts.get(tenant, 0) < cap:
                batch.append(item)
                counts[tenant] = counts.get(tenant, 0) + 1
            else:
                kept.append(item)
                if len(batch) < limit:
                    deferred += 1        # capped out, not batch-full
        self._pending = kept
        self._note_admitted_locked(batch)
        if deferred:
            self.requests_deferred += deferred
            self.telemetry.bump("serve.admission_deferred", deferred)
        return batch

    def _note_admitted_locked(self, batch) -> None:
        if self.shed_bytes and batch:
            self._pending_bytes = max(
                0, self._pending_bytes
                - sum(np.asarray(req.query).nbytes for req, _, _ in batch))

    def _degrade(self, req: RetrievalRequest) -> RetrievalRequest:
        """The breaker's cheap rung: clamp the per-request knobs the
        ragged kernels read as device data (fewer IVF probes, smaller
        boost/retrieval cap) — same k results, less device work. The
        request object is copied, never mutated (the caller may retry it
        at full quality)."""
        cap = (self.degrade_cap_take if req.cap_take is None
               else min(req.cap_take, self.degrade_cap_take))
        npr = (self.degrade_nprobe if req.nprobe is None
               else min(req.nprobe, self.degrade_nprobe))
        return dataclasses.replace(req, cap_take=cap, nprobe=npr)

    def _execute(self, batch) -> None:
        reqs = [req for req, _, _ in batch]
        flush_t = time.time()
        for req, _, enq in batch:
            self.telemetry.record("serve.queue_wait_ms",
                                  (flush_t - enq) * 1e3,
                                  labels={"tenant": req.tenant})
        if self.breaker is not None and self.breaker.degraded(flush_t):
            reqs = [self._degrade(r) for r in reqs]
            self.telemetry.bump("reliability.degraded_requests", len(reqs))
        timer = None
        timed_out = threading.Event()
        if self.dispatch_timeout_s > 0:
            def _deadline():
                timed_out.set()
                self.watchdog_timeouts += 1
                self.telemetry.bump("reliability.watchdog_timeouts")
                if self.breaker is not None:
                    self.breaker.record_failure()
                err = DispatchTimeout(
                    f"dispatch exceeded the {self.dispatch_timeout_s:.3f}s "
                    f"watchdog deadline (batch of {len(batch)})")
                for _, fut, _ in batch:
                    _fail_future(fut, err)
            timer = threading.Timer(self.dispatch_timeout_s, _deadline)
            timer.daemon = True
            timer.start()
        try:
            # one mega-batch == one profiler step, so TPU captures line up
            # with the host spans batch-for-batch
            with step_trace_annotation("lz.serve.batch",
                                       self.batches_flushed):
                results = self._executor(reqs)
        except Exception as e:                      # noqa: BLE001 — demuxed
            if timer is not None:
                timer.cancel()
            if self.breaker is not None:
                self.breaker.record_failure()
            for _, fut, _ in batch:
                _fail_future(fut, e)
            return
        if timer is not None:
            timer.cancel()
        if timed_out.is_set():
            # The dispatch came back AFTER the watchdog failed its
            # futures: discard the late results (the callers have moved
            # on) but leave state/telemetry consistent.
            return
        if self.breaker is not None:
            self.breaker.record_success()
        self.batches_flushed += 1
        self.requests_served += len(batch)
        self.telemetry.bump("serve.requests", len(batch))
        self.telemetry.bump("serve.batches")
        self.telemetry.record("serve.batch_requests", len(batch))
        self.batch_sizes.append(len(batch))
        if len(self.batch_sizes) > 1024:
            del self.batch_sizes[:512]
        for (_, fut, _), res in zip(batch, results):
            _set_future(fut, res)

    def load(self) -> int:
        """Instantaneous queue depth + in-flight dispatches — the
        least-loaded routing signal :class:`ReplicaRouter` reads."""
        with self._cond:
            return len(self._pending) + self._inflight

    # ----------------------------------------------------------- lifecycle
    def flush(self, timeout: float = 30.0) -> None:
        """Block until everything submitted so far has been executed."""
        deadline = time.time() + timeout
        with self._cond:
            self._cond.notify()
            while self._pending or self._inflight:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError("QueryScheduler.flush timed out")
                self._cond.wait(min(remaining, 0.05))

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._worker.join(timeout=30.0)

    def stats(self) -> dict:
        with self._cond:
            sizes = list(self.batch_sizes)
            return {
                "batches_flushed": self.batches_flushed,
                "requests_served": self.requests_served,
                "requests_deferred": self.requests_deferred,
                "requests_shed": self.requests_shed,
                "worker_restarts": self.worker_restarts,
                "watchdog_timeouts": self.watchdog_timeouts,
                "breaker": (self.breaker.stats()
                            if self.breaker is not None else None),
                "continuous": self.continuous,
                "pending": len(self._pending),
                "mean_batch": (round(float(np.mean(sizes)), 2)
                               if sizes else None),
                "max_batch_seen": max(sizes) if sizes else None,
            }


class ReplicaRouter:
    """Group-aware routing in front of per-group :class:`QueryScheduler`s
    (replica-group serving, ISSUE 18).

    One scheduler per replica group — each with its OWN worker thread,
    admission queue, and circuit breaker, so a sick group degrades (or
    sheds) alone while the others keep serving at full quality — and a
    routing policy in front that assigns every submitted request to
    exactly one group:

    - **tenant-affine**: tenants named in ``affine_tenants`` (the
      placement layer registers every overlay tenant it ingests) always
      route to their stable home group (``utils.hashing``'s CRC32-based
      ``tenant_home_group``, the same assignment the write side uses) —
      their private rows exist ONLY on that home group, and the pinning
      also buys read-your-writes for shared-tier tenants that opt in;
    - **least-loaded**: everything else routes to the group whose
      scheduler reports the smallest queue depth + in-flight count
      (:meth:`QueryScheduler.load`), ties broken round-robin so an idle
      fleet still spreads.

    Because routing happens at submission, each group's scheduler
    coalesces ITS stream into mega-batches independently — every flushed
    mega-batch lands on exactly one group as ONE distributed dispatch +
    ONE packed readback, which is what makes aggregate QPS scale with
    group count instead of every dispatch sweeping every chip."""

    def __init__(self, executors: Sequence[Executor],
                 affine_tenants: Optional[set] = None,
                 telemetry=None, name: str = "lz-replica-router", **sched_kw):
        if not executors:
            raise ValueError("ReplicaRouter needs at least one executor")
        self.telemetry = telemetry if telemetry is not None \
            else default_registry()
        # a set passed in is kept BY REFERENCE: the placement layer shares
        # its live overlay-tenant set, so tenants that turn overlay after
        # router construction pin immediately
        self.affine_tenants = (affine_tenants if isinstance(affine_tenants,
                                                            set)
                               else set(affine_tenants or ()))
        self.schedulers = [
            QueryScheduler(ex, name=f"{name}-g{g}",
                           telemetry=self.telemetry, **sched_kw)
            for g, ex in enumerate(executors)]
        self._rr = 0
        self._rr_lock = threading.Lock()

    @property
    def n_groups(self) -> int:
        return len(self.schedulers)

    def pin_tenant(self, tenant: str) -> int:
        """Register a tenant as overlay/affine; returns its home group."""
        self.affine_tenants.add(tenant)
        return self.group_for_tenant(tenant)

    def group_for_tenant(self, tenant: str) -> int:
        """The tenant's home group (process-stable hash — the same
        assignment the write-side placement uses, so affine reads land
        where the tenant's overlay rows live, across restarts too)."""
        return tenant_home_group(tenant, len(self.schedulers))

    def route(self, request: RetrievalRequest) -> int:
        if request.tenant in self.affine_tenants:
            g = self.group_for_tenant(request.tenant)
            self.telemetry.bump("serve.replica_affine_routed",
                                labels={"group": str(g)})
            return g
        loads = [s.load() for s in self.schedulers]
        lo = min(loads)
        candidates = [g for g, v in enumerate(loads) if v == lo]
        with self._rr_lock:
            g = candidates[self._rr % len(candidates)]
            self._rr += 1
        self.telemetry.bump("serve.replica_routed",
                            labels={"group": str(g)})
        return g

    def submit(self, request: RetrievalRequest) -> "Future[RetrievalResult]":
        return self.schedulers[self.route(request)].submit(request)

    def submit_many(self, requests: Sequence[RetrievalRequest]
                    ) -> List["Future[RetrievalResult]"]:
        """Route a group of requests; each sub-group stays contiguous on
        its scheduler (the atomic-group property per group)."""
        by_group: Dict[int, List[int]] = {}
        for i, req in enumerate(requests):
            by_group.setdefault(self.route(req), []).append(i)
        futures: List[Optional[Future]] = [None] * len(requests)
        for g, idxs in by_group.items():
            got = self.schedulers[g].submit_many(
                [requests[i] for i in idxs])
            for i, fut in zip(idxs, got):
                futures[i] = fut
        return futures

    def flush(self, timeout: float = 30.0) -> None:
        for s in self.schedulers:
            s.flush(timeout)

    def close(self) -> None:
        for s in self.schedulers:
            s.close()

    def stats(self) -> dict:
        return {"n_groups": len(self.schedulers),
                "affine_tenants": len(self.affine_tenants),
                "groups": [s.stats() for s in self.schedulers]}
