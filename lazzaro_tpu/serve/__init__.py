"""Serving-path scheduling: cross-request query batching for fused retrieval."""

from lazzaro_tpu.serve.scheduler import (QueryScheduler, ReplicaRouter,
                                         RetrievalRequest, RetrievalResult)

__all__ = ["QueryScheduler", "ReplicaRouter", "RetrievalRequest",
           "RetrievalResult"]
