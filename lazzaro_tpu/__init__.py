"""lazzaro_tpu — TPU-native scalable long-term memory for AI agents.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of Lazzaro
(thelaycon/lazzaro): episodic short-term buffering, LLM fact extraction,
a semantically-sharded embedded memory graph, hybrid hierarchical+ANN
retrieval, five-domain profile evolution, biological decay, and multi-tenant
partitioning — with the similarity math, decay sweeps, and top-k retrieval
running as batched XLA programs on an HBM-resident arena instead of Python
loops over a CPU vector database.
"""

from lazzaro_tpu.config import MemoryConfig
from lazzaro_tpu.core.memory_system import MemorySystem

__version__ = "0.1.0"
__all__ = ["MemorySystem", "MemoryConfig"]
