"""LangGraph workflow nodes (parity: reference langgraph_integration.py).

No langgraph import needed — the nodes are plain callables over state dicts.
"""

from __future__ import annotations

from typing import Any, Dict

from lazzaro_tpu.integrations.common import record_turn, retrieval_context


def _msg_text(msg) -> str:
    return msg.content if hasattr(msg, "content") else str(msg)


class LazzaroLangGraph:
    def __init__(self, memory_system):
        self.memory_system = memory_system

    def get_memory_node(self):
        """Node that injects retrieved context as ``lazzaro_context``."""

        def memory_node(state: Dict[str, Any]):
            messages = state.get("messages", [])
            user_msg = (_msg_text(messages[-1]) if messages
                        else state.get("input", ""))
            if not user_msg:
                return {"lazzaro_context": ""}
            return {"lazzaro_context": retrieval_context(
                self.memory_system, user_msg, "Past Memories:")}

        return memory_node

    def get_record_node(self):
        """Node that records the last user/assistant pair."""

        def record_node(state: Dict[str, Any]):
            messages = state.get("messages", [])
            if len(messages) < 2:
                return {}
            record_turn(self.memory_system,
                        _msg_text(messages[-2]), _msg_text(messages[-1]))
            return {}

        return record_node
