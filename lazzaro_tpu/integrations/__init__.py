"""Guarded re-exports (parity: reference integrations/__init__.py:1-15)."""

__all__ = []

try:
    from lazzaro_tpu.integrations.langchain_integration import LazzaroLangChainMemory
    __all__.append("LazzaroLangChainMemory")
except ImportError:
    pass

try:
    from lazzaro_tpu.integrations.langgraph_integration import LazzaroLangGraph
    __all__.append("LazzaroLangGraph")
except ImportError:
    pass

try:
    from lazzaro_tpu.integrations.autogen_integration import LazzaroAutogenAgent
    __all__.append("LazzaroAutogenAgent")
except ImportError:
    pass

try:
    from lazzaro_tpu.integrations.adk_integration import LazzaroADKPlugin
    __all__.append("LazzaroADKPlugin")
except ImportError:
    pass
