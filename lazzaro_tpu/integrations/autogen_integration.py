"""Autogen ConversableAgent hook (parity: reference autogen_integration.py).

Registers a position-0 reply hook that injects/refreshes a
``[LAZZARO MEMORY CONTEXT]`` block in the agent's system message, records the
user turn, and returns None so the default reply generation proceeds.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Union

from lazzaro_tpu.integrations.common import record_turn, retrieval_context

CONTEXT_MARKER = "[LAZZARO MEMORY CONTEXT]"


class LazzaroAutogenAgent:
    def __init__(self, agent: Any, memory_system):
        self.agent = agent
        self.memory_system = memory_system
        self._setup_hooks()

    def _setup_hooks(self) -> None:
        try:
            from autogen import Agent, ConversableAgent
        except ImportError:
            print("⚠ Autogen not installed. Integration may not work.")
            return
        if isinstance(self.agent, ConversableAgent):
            self.agent.register_reply(
                [Agent, None],
                reply_func=self._generate_memory_aware_reply,
                position=0,
            )

    def _generate_memory_aware_reply(
        self,
        recipient: Any,
        messages: Optional[List[Dict]] = None,
        sender: Optional[Any] = None,
        config: Optional[Any] = None,
    ) -> Union[str, Dict, None]:
        if not messages:
            return None
        last_message = messages[-1].get("content", "")
        if not last_message:
            return None

        context = retrieval_context(self.memory_system, last_message,
                                    "Relevant Context:")
        if context:
            block = f"\n\n{CONTEXT_MARKER}\n{context}"
            system_msg = self.agent.system_message
            if CONTEXT_MARKER not in system_msg:
                self.agent.update_system_message(system_msg + block)
            else:
                self.agent.update_system_message(re.sub(
                    re.escape(CONTEXT_MARKER) + r".*$", block.strip(),
                    system_msg, flags=re.DOTALL))

        record_turn(self.memory_system, last_message)
        return None  # defer to the default reply pipeline
