"""LangChain drop-in memory.

Parity: reference ``integrations/langchain_integration.py`` —
``load_memory_variables`` is retrieval-only (never calls the LLM),
``save_context`` records both turns, ``clear`` ends the conversation.
Works without langchain installed (duck-typed); subclasses BaseMemory when
langchain-core is importable.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from lazzaro_tpu.integrations.common import record_turn, retrieval_context

try:
    from langchain_core.memory import BaseMemory
    from langchain_core.messages import AIMessage
    _HAS_LANGCHAIN = True
except ImportError:
    BaseMemory = object
    AIMessage = None
    _HAS_LANGCHAIN = False


class LazzaroLangChainMemory(BaseMemory):
    """LangChain ``BaseMemory`` backed by the TPU memory system."""

    memory_system: Any = None
    memory_key: str = "history"
    input_key: Optional[str] = None
    output_key: Optional[str] = None
    return_messages: bool = False

    def __init__(self, memory_system, **kwargs):
        if _HAS_LANGCHAIN:
            super().__init__(memory_system=memory_system, **kwargs)
        else:
            self.memory_system = memory_system
            for k, v in kwargs.items():
                setattr(self, k, v)

    @property
    def memory_variables(self) -> List[str]:
        return [self.memory_key]

    def load_memory_variables(self, inputs: Dict[str, Any]) -> Dict[str, Any]:
        user_message = inputs.get(self.input_key) or inputs.get("input") or ""
        if not user_message:
            return {self.memory_key: [] if self.return_messages else ""}
        context = retrieval_context(self.memory_system, user_message)
        if self.return_messages:
            if AIMessage is None:
                return {self.memory_key: [context] if context else []}
            return {self.memory_key: [AIMessage(content=context)] if context else []}
        return {self.memory_key: context}

    def save_context(self, inputs: Dict[str, Any], outputs: Dict[str, str]) -> None:
        user_input = inputs.get(self.input_key) or inputs.get("input") or ""
        ai_output = outputs.get(self.output_key) or outputs.get("output") or ""
        record_turn(self.memory_system, user_input, ai_output)

    def clear(self) -> None:
        self.memory_system.end_conversation()
