"""Shared retrieval-context assembly for framework integrations.

Every reference integration re-implements the same block — embed the query,
run `_optimized_retrieval`, render profile + memory bullets (e.g.
``integrations/langchain_integration.py:23-53``). Here it's one function.
Retrieval-only: none of these call chat(), so no LLM is invoked.
"""

from __future__ import annotations

from typing import List, Tuple


def retrieval_context(memory_system, query: str,
                      memories_header: str = "Relevant Past Memories:") -> str:
    if not query:
        return ""
    query_emb = memory_system._get_embedding(query)
    retrieved_ids = memory_system._optimized_retrieval(query_emb, query)

    parts: List[str] = []
    profile_context = memory_system.profile.get_context()
    if profile_context and profile_context != "No profile data yet.":
        parts.append(f"User Profile: {profile_context}")

    texts = []
    for nid in retrieved_ids:
        node = memory_system.buffer.get_node(nid)
        if node:
            texts.append(node.content)
    if texts:
        parts.append(memories_header + "\n" + "\n".join(texts))
    return "\n\n".join(parts)


def record_turn(memory_system, user_input: str, ai_output: str = "") -> None:
    """Record a user/assistant pair into the short-term buffer (user 0.7
    episodic, assistant 0.5 semantic — the convention used across the
    reference integrations)."""
    if not memory_system.conversation_active:
        memory_system.start_conversation()
    if user_input:
        memory_system.add_to_short_term(user_input, "episodic", salience=0.7)
        memory_system.conversation_history.append(
            {"role": "user", "content": user_input})
    if ai_output:
        memory_system.add_to_short_term(ai_output, "semantic", salience=0.5)
        memory_system.conversation_history.append(
            {"role": "assistant", "content": ai_output})
