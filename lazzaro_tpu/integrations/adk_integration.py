"""Google ADK plugin (parity: reference adk_integration.py): memory retrieval
as a JSON-schema tool + an observe() hook for recording turns."""

from __future__ import annotations

from lazzaro_tpu.integrations.common import record_turn, retrieval_context


class LazzaroADKPlugin:
    def __init__(self, memory_system):
        self.memory_system = memory_system

    def as_tool(self) -> dict:
        return {
            "name": "lazzaro_memory_retrieval",
            "description": "Retrieve relevant past memories and user profile information.",
            "parameters": {
                "type": "object",
                "properties": {
                    "query": {
                        "type": "string",
                        "description": "The current user query to find relevant memories for.",
                    }
                },
                "required": ["query"],
            },
            "func": self.retrieve,
        }

    def retrieve(self, query: str) -> str:
        context = retrieval_context(self.memory_system, query,
                                    "Relevant Memories:")
        return context if context else "No relevant memories found."

    def observe(self, user_input: str, agent_output: str) -> None:
        record_turn(self.memory_system, user_input, agent_output)
