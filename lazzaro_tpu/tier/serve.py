"""The host half of tiered serving: decode dispatch 1, finish cold hits.

Dispatch 1 (``state.search_fused_tiered*``) scanned the FULL corpus
through the int8 shadow and returned each query's k+slack candidate
window — exact scores for hot rows, coarse scores for cold rows, boosts
applied in-kernel for queries whose window is all-hot. This module:

1. decodes hot-only queries straight from the packed readback (their
   scores are final — ONE dispatch total);
2. for cold-hit queries, gathers the cold candidates' exact rows from the
   host :class:`~lazzaro_tpu.tier.ColdStore` and runs ONE bounded second
   dispatch — ``state.tier_cold_finish`` (exact rescore + final re-rank +
   the deferred gate/CSR/boost tail) when any of them asked for boosts,
   else the read-only ``state.tier_cold_rescore`` — never a full-arena
   fault-in;
3. feeds the tier telemetry (cold-hit rate, promotion hit counters).

Shared by ``core.index.MemoryIndex`` (single chip AND mesh — the finish
kernel is plain jnp under jit, so GSPMD partitions it against the
row-sharded arena with a replicated flat CSR) and
``parallel.index.ShardedMemoryIndex``.
"""

from __future__ import annotations

import sys
import time
from typing import List, Optional

import numpy as np

NEG_INF = -1e30


def _packed_k(host: np.ndarray) -> int:
    """Candidate width of a packed retrieval readback: the layout is
    [gate_s, gate_r, k·ann_s, k·ann_r, fast, 5 counters]."""
    return (host.shape[1] - 8) // 2


def tiered_decode_and_finish(index, tm, reqs, results, valid, boost_on,
                             q_np, tenants, host, *, k_bucket: int,
                             cap_take: int, max_nbr: int, acc_boost: float,
                             nbr_boost: float, now_rel: float, ragged: bool,
                             cap_arr: Optional[np.ndarray], tel) -> List:
    """Decode a tiered dispatch-1 readback and finish cold-hit queries
    with at most ONE more bounded dispatch. Mutates ``results`` in place
    and returns it."""
    import jax.numpy as jnp

    from lazzaro_tpu.core import state as S
    from lazzaro_tpu.utils.batching import (decode_topk, next_pow2,
                                            pad_to_bucket, unpack_retrieval)

    nq = len(reqs)
    cap = len(tm.cold_np) - 1
    k_unpack = _packed_k(host)
    gate_s, gate_r, ann_s, ann_r, fast, counters = unpack_retrieval(
        host[:nq], k_unpack)
    live = ann_s > NEG_INF / 2
    coldf = tm.is_cold_rows(ann_r) & live
    coldq = coldf.any(axis=1) & valid[:nq]

    # ---- hot-only queries: dispatch 1's scores are final ----------------
    for i, r in enumerate(reqs):
        if not valid[i] or coldq[i]:
            continue
        res = results[i]
        ids, scores = decode_topk(ann_s[i:i + 1], ann_r[i:i + 1],
                                  index.row_to_id, NEG_INF,
                                  limit=min(int(r.k), cap),
                                  lengths=(counters[i:i + 1, 0] if ragged
                                           else None))[0]
        res.ids, res.scores = ids, scores
        if gate_s[i] > NEG_INF / 2:
            res.gate_id = index.row_to_id.get(int(gate_r[i]))
            res.gate_score = float(gate_s[i])
        res.fast = bool(fast[i])
        res.boosted = bool(boost_on[i] and not fast[i])

    cidx = np.nonzero(coldq)[0]
    tm.note_turns(int(valid[:nq].sum()), len(cidx))
    if len(cidx) == 0:
        return results

    # ---- cold-hit queries: ONE bounded finish dispatch ------------------
    c2 = len(cidx)
    dim = q_np.shape[1]
    arena_dt = tm.stores[0].dtype
    gran = getattr(index, "serve_pad_granularity", 8)
    pad_c = (len(pad_to_bucket(np.zeros((c2, 1)), gran)) if ragged
             else next_pow2(c2))
    rows2 = np.full((pad_c, k_unpack), cap, np.int32)
    s2 = np.full((pad_c, k_unpack), NEG_INF, np.float32)
    m2 = np.zeros((pad_c, k_unpack), bool)
    q2 = np.zeros((pad_c, dim), np.float32)
    ten2 = np.full((pad_c,), -1, np.int32)
    gs2 = np.full((pad_c,), NEG_INF, np.float32)
    gr2 = np.full((pad_c,), cap, np.int32)
    fast2 = np.zeros((pad_c,), bool)
    boost2 = np.zeros((pad_c,), bool)
    capq2 = np.zeros((pad_c,), np.int32)
    for j, i in enumerate(cidx):
        rows2[j] = ann_r[i]
        s2[j] = ann_s[i]
        m2[j] = coldf[i]
        q2[j] = q_np[i]
        ten2[j] = tenants[i]
        gs2[j] = gate_s[i]
        gr2[j] = gate_r[i]
        fast2[j] = fast[i]
        boost2[j] = boost_on[i]
        capq2[j] = (int(cap_arr[i]) if (ragged and cap_arr is not None)
                    else cap_take)
    vecs2 = np.zeros((pad_c, k_unpack, dim), arena_dt)
    flat = np.nonzero(m2)
    if len(flat[0]):
        vecs2[flat] = tm.gather_cold(rows2[flat].tolist())

    k_dec = min(int(k_bucket), k_unpack)
    any_boost = bool(boost2.any())
    dev = lambda a: jnp.asarray(a)       # noqa: E731
    t0 = time.perf_counter()
    if any_boost:
        indptr_f, nbr_f = index._flat_csr_for()
        with index._state_lock:
            cur = index.state
            sole = sys.getrefcount(cur) <= index._SOLE_REFS
            new_state, packed2 = index._guarded(
                lambda fn: fn(
                    cur, indptr_f, nbr_f, dev(q2), dev(ten2), dev(rows2),
                    dev(s2), dev(m2), dev(vecs2), dev(gs2), dev(gr2),
                    dev(fast2), dev(boost2), dev(capq2),
                    jnp.float32(now_rel), jnp.float32(acc_boost),
                    jnp.float32(nbr_boost), k=k_dec, cap_take=cap_take,
                    max_nbr=max_nbr),
                S.tier_cold_finish, S.tier_cold_finish_copy, sole, (cur,),
                "serve_tiered_cold")
            del cur
            index.state = new_state
    else:
        packed2 = S.tier_cold_rescore(
            dev(q2), dev(rows2), dev(s2), dev(m2), dev(vecs2), dev(gs2),
            dev(gr2), dev(fast2), k=k_dec, sentinel=cap)
    host2 = np.asarray(packed2)          # the ONE finish readback
    tel.record("serve.dispatch_ms", (time.perf_counter() - t0) * 1e3,
               labels={"mode": "tiered_cold"})
    tel.bump("serve.dispatches", labels={"mode": "tiered_cold"})
    _, _, ann_s2, ann_r2, _, counters2 = unpack_retrieval(host2[:c2],
                                                          k_dec)
    hit_rows: List[int] = []
    for j, i in enumerate(cidx):
        r = reqs[i]
        res = results[i]
        ids, scores = decode_topk(ann_s2[j:j + 1], ann_r2[j:j + 1],
                                  index.row_to_id, NEG_INF,
                                  limit=min(int(r.k), cap))[0]
        res.ids, res.scores = ids, scores
        if gs2[j] > NEG_INF / 2:
            res.gate_id = index.row_to_id.get(int(gr2[j]))
            res.gate_score = float(gs2[j])
        res.fast = bool(fast2[j])
        res.boosted = bool(boost2[j] and not fast2[j])
        kq = min(int(r.k), k_dec)
        final = ann_r2[j][:kq][ann_s2[j][:kq] > NEG_INF / 2]
        cold_final = [int(x) for x in final if tm.cold_np[int(x)]]
        res.cold_hits = len(cold_final)
        hit_rows.extend(cold_final)
    if hit_rows:
        tm.note_cold_hits(hit_rows)
    return results
