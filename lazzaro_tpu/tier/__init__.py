"""Tiered memory (ISSUE 8): HBM hot set + host-resident cold tier.

The arena today is HBM-resident end to end, so the corpus a chip can
serve is hard-capped by HBM (~1M×768 bf16 on the bench rig). This package
is the escape TF-Engram and EdgeRAG both describe: keep a compact int8
shadow for the FULL corpus in fast memory (the fused coarse scan still
covers everything in one dispatch), demote cold full-precision rows to
host RAM (optionally memory-mapped to disk), and promote on access — with
the salience-decay machinery supplying exactly the hotness signal the
policy needs.

- :class:`ColdStore` — pinned host numpy (or ``np.memmap``) slab holding
  demoted rows' exact embeddings + their int8 codes/scales, keyed by
  arena row; per-shard buckets under a mesh.
- :class:`TierManager` — residency bookkeeping (the per-row ``cold``
  device column + host mirror), demote/promote mechanics (donated
  ``tier_demote`` / ``tier_promote`` scatters through the index's
  ownership gate), watermark + hysteresis policy, telemetry gauges.
- :class:`TierPump` — the async demotion/promotion worker: double-
  buffered chunks that overlap serving dispatches.
"""

from lazzaro_tpu.tier.cold_store import ColdStore
from lazzaro_tpu.tier.pump import TierManager, TierPump

__all__ = ["ColdStore", "TierManager", "TierPump"]
