"""Host-resident cold tier: exact rows demoted out of HBM.

A ``ColdStore`` is a growable host slab of full-precision embedding rows
keyed by arena row index, plus each row's int8 shadow codes and scale.
Three invariants make the tier transparent to serving:

- **Bit-exact round trips.** Rows are stored in the ARENA dtype (bf16
  kept as a uint16 bit view — the npy/memmap formats have no bf16
  descriptor), so demote → promote restores the identical bytes and the
  int8 shadow codes quantized before demotion stay valid forever.
- **Codes travel with the row.** The serving shadow is rebuilt lazily
  from the master arena (``quantize_rows(emb)``), and a demoted row's
  master is zeroed — the store therefore keeps the row's codes+scale so
  the rebuild can patch them back (``snapshot_codes``), keeping the
  coarse scan full-corpus.
- **Slab storage, not per-row objects.** One [slots, d] array per field,
  grown by doubling; ``path=`` switches the vector slab to ``np.memmap``
  (the SSD tier) with the same API. A million cold rows is three arrays
  and one dict, not a million Python objects.

Thread safety: one internal lock around slot allocation and the
row→slot map; gathers copy out under it.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

try:
    import ml_dtypes
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:           # pragma: no cover - image always has ml_dtypes
    ml_dtypes = None
    _BF16 = None


def _wire_dtype(dtype) -> Tuple[np.dtype, bool]:
    """(storage dtype, is_bf16): bf16 is stored as a uint16 bit view."""
    if _BF16 is not None and np.dtype(dtype) == _BF16:
        return np.dtype(np.uint16), True
    return np.dtype(dtype), False


class ColdStore:
    """Growable host slab of demoted rows (exact vecs + int8 codes)."""

    def __init__(self, dim: int, dtype=np.float32,
                 path: Optional[str] = None, initial_slots: int = 1024):
        self.dim = int(dim)
        self.dtype = np.dtype(dtype) if _BF16 is None or \
            np.dtype(dtype) != _BF16 else _BF16
        self._wire, self._bf16 = _wire_dtype(dtype)
        self.path = path
        self._lock = threading.Lock()
        self._slots = max(16, int(initial_slots))
        self._vecs = self._alloc_vecs(self._slots)
        self._codes = np.zeros((self._slots, self.dim), np.int8)
        self._scales = np.zeros((self._slots,), np.float32)
        self.row_to_slot: Dict[int, int] = {}
        self._free: List[int] = list(range(self._slots - 1, -1, -1))

    # ------------------------------------------------------------- storage
    def _alloc_vecs(self, slots: int) -> np.ndarray:
        if self.path:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            return np.memmap(self.path, dtype=self._wire, mode="w+",
                             shape=(slots, self.dim))
        return np.zeros((slots, self.dim), self._wire)

    def _grow(self, need: int) -> None:
        new_slots = self._slots
        while new_slots - len(self.row_to_slot) < need:
            new_slots *= 2
        if new_slots == self._slots:
            return
        old = np.asarray(self._vecs)
        if self.path:
            # stage into a fresh file, then swap — a crash mid-grow leaves
            # the old mapping readable
            tmp = self.path + ".grow"
            nv = np.memmap(tmp, dtype=self._wire, mode="w+",
                           shape=(new_slots, self.dim))
            nv[:self._slots] = old
            nv.flush()
            del self._vecs
            os.replace(tmp, self.path)
            self._vecs = np.memmap(self.path, dtype=self._wire, mode="r+",
                                   shape=(new_slots, self.dim))
        else:
            nv = np.zeros((new_slots, self.dim), self._wire)
            nv[:self._slots] = old
            self._vecs = nv
        nc = np.zeros((new_slots, self.dim), np.int8)
        nc[:self._slots] = self._codes
        self._codes = nc
        ns = np.zeros((new_slots,), np.float32)
        ns[:self._slots] = self._scales
        self._scales = ns
        self._free.extend(range(new_slots - 1, self._slots - 1, -1))
        self._slots = new_slots

    # ----------------------------------------------------------------- api
    def put(self, rows: Sequence[int], vecs: np.ndarray,
            codes: np.ndarray, scales: np.ndarray) -> None:
        """Store (or overwrite) demoted rows. ``vecs`` must already be in
        the arena dtype — the bytes are kept verbatim."""
        v = np.asarray(vecs)
        if self._bf16:
            v = v.view(np.uint16) if v.dtype == _BF16 else \
                np.asarray(v, _BF16).view(np.uint16)
        else:
            v = np.asarray(v, self._wire)
        with self._lock:
            fresh = sum(1 for r in rows if int(r) not in self.row_to_slot)
            if fresh > len(self._free):
                self._grow(fresh)
            for i, r in enumerate(rows):
                r = int(r)
                slot = self.row_to_slot.get(r)
                if slot is None:
                    slot = self._free.pop()
                    self.row_to_slot[r] = slot
                self._vecs[slot] = v[i]
                self._codes[slot] = codes[i]
                self._scales[slot] = float(scales[i])

    def flush(self) -> None:
        """Durably commit the vector slab (ISSUE 10): for the memmap/SSD
        tier this flushes dirty pages to the backing file, so a demote
        chunk's cold bytes are on disk BEFORE the hot master row is
        zeroed (commit-then-zero). Host-RAM slabs are a no-op."""
        with self._lock:
            if self.path and hasattr(self._vecs, "flush"):
                self._vecs.flush()

    def gather(self, rows: Sequence[int]) -> np.ndarray:
        """Exact vectors for ``rows`` in the arena dtype; rows not in the
        store come back as zeros (the caller's cold mask gates them)."""
        from lazzaro_tpu.reliability import faults

        # Fault point "coldstore.read" (ISSUE 10): models an SSD/mmap
        # read error on the cold tier — the serving finish and the
        # promote path must surface it typed, never zero-fill silently.
        faults.fire("coldstore.read", rows=len(rows))
        out = np.zeros((len(rows), self.dim), self._wire)
        with self._lock:
            for i, r in enumerate(rows):
                slot = self.row_to_slot.get(int(r))
                if slot is not None:
                    out[i] = self._vecs[slot]
        return out.view(_BF16) if self._bf16 else out

    def drop(self, rows: Sequence[int]) -> None:
        with self._lock:
            for r in rows:
                slot = self.row_to_slot.pop(int(r), None)
                if slot is not None:
                    self._free.append(slot)

    def snapshot_codes(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(rows, codes, scales) of every stored row — the shadow-rebuild
        patch (the master arena holds zeros for these rows)."""
        with self._lock:
            rows = np.fromiter(self.row_to_slot.keys(), np.int64,
                               len(self.row_to_slot))
            slots = np.fromiter(self.row_to_slot.values(), np.int64,
                                len(self.row_to_slot))
            return rows, self._codes[slots].copy(), self._scales[slots].copy()

    def snapshot_all(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                    np.ndarray]:
        """(rows, vecs_wire, codes, scales) for checkpointing — vectors in
        the wire dtype (bf16 as uint16 bits)."""
        with self._lock:
            rows = np.fromiter(self.row_to_slot.keys(), np.int64,
                               len(self.row_to_slot))
            slots = np.fromiter(self.row_to_slot.values(), np.int64,
                                len(self.row_to_slot))
            return (rows, np.asarray(self._vecs)[slots].copy(),
                    self._codes[slots].copy(), self._scales[slots].copy())

    def __contains__(self, row: int) -> bool:
        return int(row) in self.row_to_slot

    def __len__(self) -> int:
        return len(self.row_to_slot)

    @property
    def nbytes(self) -> int:
        return (np.asarray(self._vecs).nbytes + self._codes.nbytes
                + self._scales.nbytes)

    def rows(self) -> List[int]:
        with self._lock:
            return list(self.row_to_slot.keys())
