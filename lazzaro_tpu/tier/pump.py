"""Residency management + the async demotion/promotion pump.

``TierManager`` owns the tier state of one index: the per-row residency
column (``cold_np`` host mirror + its device upload, row-sharded under a
mesh), the host :class:`ColdStore` buckets (one per mesh partition), the
watermark/hysteresis policy, and the telemetry gauges. ``TierPump`` is
the background worker that runs the manager's ``run_once`` on an
interval so demotions/promotions overlap serving dispatches.

Policy (driven by the signals the decay machinery already maintains):

- **Demotion** fires when the hot row count crosses
  ``high_watermark · hot_budget_rows`` and demotes coldest-first down to
  ``low_watermark · hot_budget_rows`` — the gap between the watermarks is
  the hysteresis band that stops the pump from oscillating at the
  boundary. Coldness is the salience/recency half of the importance
  score (``w_sal · salience + w_rec / (1 + idle_days)``), read in ONE
  bulk readback per pass. Super rows are pinned hot (the fused gate's
  top-1 verdict must stay exact), rows touched within ``min_idle_s``
  are skipped, and a freshly promoted row is immune for
  ``hysteresis_s`` seconds so an access burst can't thrash it.
- **Promotion** is access-driven: the serving path reports cold rows
  that surfaced in final top-k results (``note_cold_hits``); a row
  reaching ``promote_hits`` distinct hits queues for promotion, applied
  by the next pump pass (never inline in a serve — promotion must not
  add a dispatch to a chat turn).

Mechanics: demotion moves rows in double-buffered chunks — the gather of
chunk i+1 is dispatched (async) before chunk i's host materialization
blocks, so device work overlaps the host copy — and each chunk's
zero-scatter goes through the index's donation gate (``tier_demote`` /
``*_copy``). A generation counter guards the gather→scatter window:
if any embedding write lands in between, the chunk aborts and retries
on the next pass instead of clobbering fresh data.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

logger = logging.getLogger("lazzaro_tpu.tier")


class TierManager:
    """Residency state + demote/promote mechanics for one index."""

    def __init__(self, index, hot_budget_rows: int, *,
                 high_watermark: float = 0.9, low_watermark: float = 0.75,
                 chunk_rows: int = 4096, min_idle_s: float = 0.0,
                 promote_hits: int = 1, hysteresis_s: float = 30.0,
                 cold_dir: Optional[str] = None,
                 w_salience: float = 0.5, w_recency: float = 0.2):
        from lazzaro_tpu.tier.cold_store import ColdStore

        self.index = index
        self.hot_budget_rows = max(1, int(hot_budget_rows))
        self.high_watermark = float(high_watermark)
        self.low_watermark = float(low_watermark)
        if not 0.0 < self.low_watermark <= self.high_watermark:
            raise ValueError("need 0 < low_watermark <= high_watermark")
        self.chunk_rows = max(1, int(chunk_rows))
        # Per-PASS demotion bound for the background pump: None drains the
        # whole watermark gap in one run_once (bulk/offline callers); a
        # bound spreads the drain across passes so each one steals only a
        # chunk's worth of device time from concurrent serving.
        self.max_demote_per_pass: Optional[int] = None
        self.min_idle_s = float(min_idle_s)
        self.promote_hits = max(1, int(promote_hits))
        self.hysteresis_s = float(hysteresis_s)
        self.w_salience = float(w_salience)
        self.w_recency = float(w_recency)
        self.cold_dir = cold_dir

        n = index.state.salience.shape[0]
        self._n_parts = int(getattr(index, "_n_parts",
                                    getattr(index, "n_parts", 1)) or 1)
        self.stores: List[ColdStore] = [
            ColdStore(index.dim, dtype=index.state.emb.dtype,
                      path=(None if cold_dir is None else
                            f"{cold_dir}/cold_shard{p}.bin"))
            for p in range(self._n_parts)]
        self.cold_np = np.zeros((n,), bool)
        self._cold_dev = None              # built lazily / on change
        self._lock = threading.RLock()
        # LEAF lock for the device-mask cache alone: the serving boost
        # path reads the mask while holding the index's _state_lock, and
        # the pump takes (manager lock → state lock) — guarding the mask
        # with the manager lock would close a deadlock cycle.
        self._mask_lock = threading.Lock()
        self._hits: Dict[int, int] = {}
        self._promote_queue: set = set()
        # Archive verdicts from the device-side lifecycle sweep (ISSUE 19):
        # rows the importance scoring picked as each tenant's coldest —
        # preferred demotion candidates, consumed before the pump falls
        # back to its own host-side bulk-readback scoring.
        self._demote_queue: set = set()
        self._no_demote_until: Dict[int, float] = {}
        # serving counters (tier.cold_hit_rate)
        self.turns = 0
        self.cold_turns = 0
        self.demoted_total = 0
        self.promoted_total = 0

    # ------------------------------------------------------------ residency
    @property
    def cold_count(self) -> int:
        return sum(len(s) for s in self.stores)

    @property
    def hot_rows(self) -> int:
        return max(0, len(self.index.row_to_id) - self.cold_count)

    @property
    def telemetry(self):
        return self.index.telemetry

    def is_cold_rows(self, rows: np.ndarray) -> np.ndarray:
        r = np.clip(np.asarray(rows, np.int64), 0, len(self.cold_np) - 1)
        return self.cold_np[r]

    def cold_mask_dev(self):
        """The residency column as device data (row-sharded under a mesh),
        re-uploaded only after a residency change. Guarded by the LEAF
        mask lock only — safe to call while holding the index state lock
        (the serving boost path does)."""
        import jax
        import jax.numpy as jnp

        with self._mask_lock:
            if self._cold_dev is not None:
                return self._cold_dev
            dev = jnp.asarray(self.cold_np.copy())
            sh = (getattr(self.index, "_row_sharding", None)
                  or getattr(self.index, "_row_sh", None))
            if sh is not None and getattr(self.index, "mesh",
                                          None) is not None:
                dev = jax.device_put(dev, sh)
            self._cold_dev = dev
            return dev

    def _invalidate_mask(self) -> None:
        with self._mask_lock:
            self._cold_dev = None

    def _part_of(self, row: int) -> int:
        part_rows = -(-len(self.cold_np) // self._n_parts)
        return min(int(row) // part_rows, self._n_parts - 1)

    def _find_store(self, row: int):
        s = self.stores[self._part_of(row)]
        if row in s:
            return s
        for other in self.stores:          # bucket may predate a grow
            if row in other:
                return other
        return None

    def gather_cold(self, rows: Sequence[int]) -> np.ndarray:
        """Exact vectors (arena dtype) for a mixed list of cold rows."""
        out = None
        for i, r in enumerate(rows):
            s = self._find_store(int(r))
            v = (s.gather([int(r)])[0] if s is not None else None)
            if out is None:
                dt = self.stores[0].dtype
                out = np.zeros((len(rows), self.index.dim), dt)
            if v is not None:
                out[i] = v
        if out is None:
            dt = self.stores[0].dtype
            out = np.zeros((0, self.index.dim), dt)
        return out

    def snapshot_codes(self):
        """(rows, codes, scales) across every shard store — the shadow-
        rebuild patch."""
        parts = [s.snapshot_codes() for s in self.stores if len(s)]
        if not parts:
            return (np.zeros((0,), np.int64),
                    np.zeros((0, self.index.dim), np.int8),
                    np.zeros((0,), np.float32))
        return (np.concatenate([p[0] for p in parts]),
                np.concatenate([p[1] for p in parts]),
                np.concatenate([p[2] for p in parts]))

    # ------------------------------------------------------------ mechanics
    def demote_rows(self, rows: Sequence[int], now: Optional[float] = None
                    ) -> int:
        """Move ``rows`` to the cold tier in double-buffered chunks;
        returns how many actually moved (super rows, already-cold rows and
        chunks that lost the write race are skipped)."""
        import jax.numpy as jnp

        from lazzaro_tpu.core import state as S
        from lazzaro_tpu.ops.quant import quantize_rows

        idx = self.index
        supers = getattr(idx, "_super_rows", set())
        with self._lock:
            todo = [int(r) for r in rows
                    if not self.cold_np[r] and r not in supers
                    and r in idx.row_to_id]
        if not todo:
            return 0
        chunks = [todo[i:i + self.chunk_rows]
                  for i in range(0, len(todo), self.chunk_rows)]

        def issue(chunk):
            st = idx.state
            rows_dev = jnp.asarray(np.asarray(chunk, np.int32))
            gen = getattr(idx, "_emb_gen", 0)
            vec_dev = st.emb[S._phys(st, rows_dev)]
            q_dev, s_dev = quantize_rows(vec_dev)
            return chunk, gen, vec_dev, q_dev, s_dev

        moved = 0
        pending = issue(chunks[0])
        for ci in range(len(chunks)):
            chunk, gen, vec_dev, q_dev, s_dev = pending
            if ci + 1 < len(chunks):
                pending = issue(chunks[ci + 1])   # overlap the next gather
            t0 = time.perf_counter()
            vecs = np.asarray(vec_dev)            # blocks on the transfer
            codes = np.asarray(q_dev)
            scales = np.asarray(s_dev)
            with self._lock, idx._state_lock:
                if getattr(idx, "_emb_gen", 0) != gen:
                    # an embedding write landed mid-flight: the gathered
                    # bytes may be stale — retry this chunk next pass
                    logger.debug("tier: demote chunk aborted (write race)")
                    continue
                by_store: Dict[int, List[int]] = {}
                for i, r in enumerate(chunk):
                    by_store.setdefault(self._part_of(r), []).append(i)
                # COMMIT-then-zero (ISSUE 10 satellite): the cold copy is
                # written AND durably flushed before the hot scatter
                # zeroes the master row — a crash between the two leaves
                # the row live in BOTH tiers (benign residue, dropped on
                # the next write), never zeroed in the master with no
                # committed cold copy.
                for p, idxs in by_store.items():
                    rs = [chunk[i] for i in idxs]
                    self.stores[p].put(rs, vecs[idxs], codes[idxs],
                                       scales[idxs])
                    self.stores[p].flush()
                try:
                    from lazzaro_tpu.reliability import faults
                    # Fault point "pump.mid_chunk": the pump dying between
                    # the cold commit and the hot zero-scatter.
                    faults.fire("pump.mid_chunk", chunk=len(chunk))
                    padded = S.pad_rows(np.asarray(chunk, np.int32),
                                        idx.state.capacity)
                    if getattr(idx, "_pager", None) is not None:
                        # Paged arena (ISSUE 17): the zero-scatter ALSO
                        # pushes the rows' pool slots back on the free
                        # list — demotion reclaims real HBM capacity.
                        pushes = idx._apply_arena_paged(
                            S.tier_demote_paged, S.tier_demote_paged_copy,
                            jnp.asarray(padded),
                            replay=lambda p: p.free(chunk))
                        idx.telemetry.bump("arena.page_pushes", pushes)
                    else:
                        idx._apply_arena(S.tier_demote, S.tier_demote_copy,
                                         jnp.asarray(padded))
                except BaseException:
                    # zero-scatter never ran (or failed with the master
                    # intact): the rows are still HOT — drop the cold
                    # residue so serving keeps reading the master only.
                    for p, idxs in by_store.items():
                        self.stores[p].drop([chunk[i] for i in idxs])
                    raise
                self.cold_np[chunk] = True
                self._invalidate_mask()
                # Online IVF (ISSUE 12): demoted rows drop out of the live
                # member tables — their zeroed master row must never feed
                # the exact in-kernel rescore. Rides the commit-then-zero
                # ordering: the scrub only runs after the cold copy is
                # durable and the hot row is zeroed.
                hook = getattr(idx, "_ivf_on_demoted", None)
                if hook is not None:
                    hook(chunk)
                # Semantic cache (ISSUE 20): a cached window holding one
                # of these rows scored it EXACTLY; the next fresh scan
                # scores it coarse — evict so hits never serve a score
                # the miss path can no longer reproduce.
                sem = getattr(idx, "_sem_host", None)
                if sem is not None:
                    sem.invalidate_rows(chunk)
                moved += len(chunk)
            ms = (time.perf_counter() - t0) * 1e3
            self.telemetry.record("tier.pump_chunk_ms", ms,
                                  labels={"dir": "demote"})
            self.telemetry.gauge("tier.pump_chunk_ms", ms)
        self.demoted_total += moved
        if moved:
            # ISSUE 17 satellite: demote scrubs member slots to -1 — run
            # the hole compactor so reclaimed member capacity is reusable
            # now, not only at the next re-seed (no-op below hole_frac).
            repack = getattr(idx, "ivf_member_repack", None)
            if repack is not None:
                try:
                    repack()
                except Exception:       # noqa: BLE001 — pump must survive
                    logger.exception("tier: ivf member repack failed")
        self.update_gauges()
        return moved

    def promote_rows(self, rows: Sequence[int], now: Optional[float] = None
                     ) -> int:
        """Move cold ``rows`` back to the hot tier (exact bytes restored;
        shadow codes were never invalidated). Returns how many moved."""
        import jax.numpy as jnp

        from lazzaro_tpu.core import state as S

        idx = self.index
        now = time.time() if now is None else now
        moved = 0
        with self._lock:
            todo = [int(r) for r in rows if self.cold_np[r]]
            if not todo:
                return 0
            for i in range(0, len(todo), self.chunk_rows):
                chunk = todo[i:i + self.chunk_rows]
                t0 = time.perf_counter()
                if getattr(idx, "_pager", None) is not None:
                    # pre-grow the pool BEFORE capturing the generation:
                    # a grow bumps _emb_gen and must not abort this chunk
                    idx._ensure_pool(chunk)
                gen = getattr(idx, "_emb_gen", 0)
                vecs = self.gather_cold(chunk)
                padded = S.pad_rows(np.asarray(chunk, np.int32),
                                    idx.state.capacity)
                vp = np.zeros((len(padded), idx.dim), vecs.dtype)
                vp[:len(chunk)] = vecs
                with idx._state_lock:
                    if getattr(idx, "_emb_gen", 0) != gen:
                        # a concurrent embedding write may have re-homed
                        # one of these rows — retry next pass
                        continue
                    if getattr(idx, "_pager", None) is not None:
                        # re-bind pool slots for the returning rows
                        pops = idx._apply_arena_paged(
                            S.tier_promote_paged, S.tier_promote_paged_copy,
                            jnp.asarray(padded), jnp.asarray(vp),
                            replay=lambda p: p.alloc(chunk))
                        idx.telemetry.bump("arena.page_pops", pops)
                    else:
                        idx._apply_arena(S.tier_promote,
                                         S.tier_promote_copy,
                                         jnp.asarray(padded),
                                         jnp.asarray(vp))
                    for r in chunk:
                        s = self._find_store(r)
                        if s is not None:
                            s.drop([r])
                    self.cold_np[chunk] = False
                    self._invalidate_mask()
                    # Online IVF (ISSUE 12): the exact master row is back;
                    # re-cover it through the exact-scan extras (the slot
                    # it held in the member tables was scrubbed on demote)
                    hook = getattr(idx, "_ivf_on_promoted", None)
                    if hook is not None:
                        hook(chunk)
                    # Semantic cache (ISSUE 20): cached coarse scores for
                    # these rows are stale now that fresh scans rescore
                    # them exactly
                    sem = getattr(idx, "_sem_host", None)
                    if sem is not None:
                        sem.invalidate_rows(chunk)
                for r in chunk:
                    self._no_demote_until[r] = now + self.hysteresis_s
                    self._hits.pop(r, None)
                    self._promote_queue.discard(r)
                moved += len(chunk)
                ms = (time.perf_counter() - t0) * 1e3
                self.telemetry.record("tier.pump_chunk_ms", ms,
                                      labels={"dir": "promote"})
                self.telemetry.gauge("tier.pump_chunk_ms", ms)
        self.promoted_total += moved
        self.update_gauges()
        return moved

    # --------------------------------------------------------------- hooks
    def on_rows_written(self, rows: Sequence[int]) -> None:
        """An embedding write landed on these rows (re-add / restore):
        their master is fresh again, so any cold residue is dropped."""
        with self._lock:
            dirty = [int(r) for r in rows
                     if r < len(self.cold_np) and self.cold_np[r]]
            if not dirty:
                return
            for r in dirty:
                s = self._find_store(r)
                if s is not None:
                    s.drop([r])
                self._hits.pop(r, None)
                self._promote_queue.discard(r)
            self.cold_np[dirty] = False
            self._invalidate_mask()
        self.update_gauges()

    on_rows_deleted = on_rows_written

    def on_grow(self, new_n: int) -> None:
        with self._lock:
            if new_n <= len(self.cold_np):
                return
            grown = np.zeros((new_n,), bool)
            grown[:len(self.cold_np)] = self.cold_np
            self.cold_np = grown
            self._invalidate_mask()

    # ------------------------------------------------------------- serving
    def note_turns(self, n_turns: int, n_cold_turns: int) -> None:
        with self._lock:
            self.turns += int(n_turns)
            self.cold_turns += int(n_cold_turns)
        self.update_gauges()

    def note_cold_hits(self, rows: Sequence[int]) -> None:
        """Cold rows that surfaced in final top-k results: bump their hit
        counters; rows reaching ``promote_hits`` queue for the pump."""
        with self._lock:
            for r in rows:
                r = int(r)
                if not (r < len(self.cold_np) and self.cold_np[r]):
                    continue
                self._hits[r] = self._hits.get(r, 0) + 1
                if self._hits[r] >= self.promote_hits:
                    self._promote_queue.add(r)

    # -------------------------------------------------------------- policy
    def select_demotion_candidates(self, n: int,
                                   now: Optional[float] = None
                                   ) -> List[int]:
        """The ``n`` coldest demotable rows by the salience/recency score
        (ONE bulk readback), excluding cold rows, super rows, hysteresis-
        protected rows and rows idle less than ``min_idle_s``."""
        from lazzaro_tpu.utils.batching import fetch_packed

        idx = self.index
        now = time.time() if now is None else now
        now_rel = now - idx.epoch
        st = idx.state
        sal, la = fetch_packed(st.salience, st.last_accessed)
        n_rows = len(sal)
        alive = np.zeros((n_rows,), bool)
        live_rows = np.fromiter(idx.row_to_id.keys(), np.int64,
                                len(idx.row_to_id))
        alive[live_rows[live_rows < n_rows]] = True
        ok = alive & ~self.cold_np[:n_rows]
        supers = getattr(idx, "_super_rows", set())
        if supers:
            sup = np.fromiter(supers, np.int64, len(supers))
            ok[sup[sup < n_rows]] = False
        idle = np.maximum(now_rel - la, 0.0)
        if self.min_idle_s > 0:
            ok &= idle >= self.min_idle_s
        with self._lock:
            if self._no_demote_until:
                dead = [r for r, t in self._no_demote_until.items()
                        if t <= now]
                for r in dead:
                    del self._no_demote_until[r]
                for r in self._no_demote_until:
                    if r < n_rows:
                        ok[r] = False
        score = (self.w_salience * sal
                 + self.w_recency / (1.0 + idle / 86400.0))
        score = np.where(ok, score, np.inf)
        n = min(n, int(ok.sum()))
        if n <= 0:
            return []
        cand = np.argpartition(score, n - 1)[:n]
        return [int(r) for r in cand if np.isfinite(score[r])]

    def queue_demotions(self, rows) -> int:
        """Feed lifecycle archive verdicts into the demote queue (ISSUE
        19). Rows wait here until the watermark policy actually needs
        evictions — "archived" is a standing nomination, the demotion
        itself still happens on the pump (demote-to-cold, never delete).
        Already-cold and out-of-range rows are dropped; returns queued."""
        n = 0
        with self._lock:
            for r in rows:
                r = int(r)
                if 0 <= r < len(self.cold_np) and not self.cold_np[r]:
                    self._demote_queue.add(r)
                    n += 1
        return n

    def run_once(self, now: Optional[float] = None) -> Dict[str, int]:
        """One pump pass: apply queued promotions, then watermark-driven
        demotion. Returns {"promoted": n, "demoted": n}."""
        now = time.time() if now is None else now
        with self._lock:
            promote = sorted(self._promote_queue)
        promoted = self.promote_rows(promote, now=now) if promote else 0
        demoted = 0
        hot = self.hot_rows
        if hot > self.high_watermark * self.hot_budget_rows:
            target = int(self.low_watermark * self.hot_budget_rows)
            need = hot - target
            if self.max_demote_per_pass:
                need = min(need, self.max_demote_per_pass)
            # lifecycle verdicts first (already importance-ranked on
            # device, zero extra readback), host scoring for the rest
            with self._lock:
                queued = [r for r in sorted(self._demote_queue)
                          if not self.cold_np[r]
                          and self._no_demote_until.get(r, 0.0) <= now]
            cand = queued[:need]
            if len(cand) < need:
                have = set(cand)
                cand += [r for r in self.select_demotion_candidates(
                             need - len(cand), now=now) if r not in have]
            if cand:
                demoted = self.demote_rows(cand, now=now)
            with self._lock:
                self._demote_queue.difference_update(cand)
        self.update_gauges()
        return {"promoted": promoted, "demoted": demoted}

    # ----------------------------------------------------------- telemetry
    def update_gauges(self) -> None:
        tel = self.telemetry
        tel.gauge("tier.hot_rows", self.hot_rows)
        tel.gauge("tier.cold_rows", self.cold_count)
        tel.gauge("tier.cold_hit_rate",
                  (self.cold_turns / self.turns) if self.turns else 0.0)

    def stats(self) -> Dict[str, object]:
        return {
            "hot_budget_rows": self.hot_budget_rows,
            "hot_rows": self.hot_rows,
            "cold_rows": self.cold_count,
            "cold_hit_rate": ((self.cold_turns / self.turns)
                              if self.turns else 0.0),
            "turns": self.turns,
            "cold_turns": self.cold_turns,
            "demoted_total": self.demoted_total,
            "promoted_total": self.promoted_total,
            "cold_store_bytes": sum(s.nbytes for s in self.stores),
            "watermarks": [self.low_watermark, self.high_watermark],
        }

    # ---------------------------------------------------------- checkpoint
    def export_arrays(self) -> Dict[str, np.ndarray]:
        """Tier state as flat arrays for the binary checkpoint: residency
        column + the cold store payload (vectors in the wire dtype)."""
        parts = [s.snapshot_all() for s in self.stores if len(s)]
        if parts:
            rows = np.concatenate([p[0] for p in parts])
            vecs = np.concatenate([p[1] for p in parts])
            codes = np.concatenate([p[2] for p in parts])
            scales = np.concatenate([p[3] for p in parts])
        else:
            dim = self.index.dim
            rows = np.zeros((0,), np.int64)
            vecs = np.zeros((0, dim), self.stores[0]._wire)
            codes = np.zeros((0, dim), np.int8)
            scales = np.zeros((0,), np.float32)
        return {"tier_cold_mask": self.cold_np,
                "tier_cold_rows": rows, "tier_cold_vecs": vecs,
                "tier_cold_codes": codes, "tier_cold_scales": scales}

    def import_arrays(self, data) -> None:
        """Restore residency + cold store contents from checkpoint arrays
        (the arena columns were already restored — cold rows hold zeroed
        embeddings there, exactly as saved)."""
        mask = np.asarray(data["tier_cold_mask"]).astype(bool)
        with self._lock:
            n = len(self.cold_np)
            self.cold_np[:] = False
            self.cold_np[:min(n, len(mask))] = mask[:n]
            rows = np.asarray(data["tier_cold_rows"], np.int64)
            vecs = np.asarray(data["tier_cold_vecs"])
            codes = np.asarray(data["tier_cold_codes"])
            scales = np.asarray(data["tier_cold_scales"])
            store = self.stores[0]
            if store._bf16 and vecs.dtype == store._wire:
                vecs = vecs.view(store.dtype)  # uint16 bits → bf16, no cast
            for i in range(0, len(rows), self.chunk_rows):
                sl = slice(i, i + self.chunk_rows)
                by_store: Dict[int, List[int]] = {}
                for j, r in enumerate(rows[sl]):
                    by_store.setdefault(self._part_of(int(r)), []).append(j)
                base = i
                for p, idxs in by_store.items():
                    rs = [int(rows[base + j]) for j in idxs]
                    self.stores[p].put(rs, vecs[sl][idxs], codes[sl][idxs],
                                       scales[sl][idxs])
            self._invalidate_mask()
        self.update_gauges()


class TierPump:
    """Async wrapper: run ``manager.run_once()`` every ``interval_s`` on a
    daemon thread so tier traffic overlaps serving dispatches."""

    def __init__(self, manager: TierManager, interval_s: float = 1.0,
                 name: str = "lz-tier-pump"):
        self.manager = manager
        self.interval_s = max(0.01, float(interval_s))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._name = name

    def start(self) -> "TierPump":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=self._name)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.manager.run_once()
            except Exception:               # noqa: BLE001 — pump must survive
                logger.exception("tier pump pass failed")
                self.manager.telemetry.bump(
                    "reliability.worker_restarts", labels={"actor": "pump"})

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()
