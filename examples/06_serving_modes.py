"""Four serving modes over one arena: exact, int8, IVF, IVF-PQ.

Retrieval at scale is HBM-bandwidth-bound: an exact 1M×768 bf16 scan
streams ~1.5 GB per query batch. The int8 shadow halves the bytes
(~0.4% cosine error, consolidation keeps the exact master); the IVF
coarse stage visits only the nprobe nearest clusters' rows (~25× less
traffic, recall set by nprobe, fresh rows exact via a residual); IVF-PQ
stores members as dim/8-byte codes and re-scores the shortlist exactly
from the master (LanceDB's default index family, measured curves in
bench_artifacts/).

    python examples/06_serving_modes.py   # offline, CPU or TPU
"""

import _bootstrap  # noqa: F401  (repo-root sys.path)

import numpy as np

from lazzaro_tpu.core.index import MemoryIndex

rng = np.random.default_rng(0)
n, d = 6000, 64
emb = rng.standard_normal((n, d)).astype(np.float32)
emb /= np.linalg.norm(emb, axis=1, keepdims=True)
ids = [f"m{i}" for i in range(n)]

idx = MemoryIndex(dim=d, capacity=n + 64)
for s in range(0, n, 1000):
    idx.add(ids[s:s + 1000], emb[s:s + 1000], [0.5] * 1000, [0.0] * 1000,
            ["semantic"] * 1000, ["default"] * 1000, "demo")

probe = rng.integers(0, n, 20)
queries = emb[probe]

for mode, setup in [
    ("exact", lambda: None),
    ("int8 ", lambda: setattr(idx, "int8_serving", True)),
    ("ivf  ", lambda: (setattr(idx, "int8_serving", False),
                       setattr(idx, "ivf_nprobe", 8),
                       idx.ivf_maintenance())),   # builds run in background
                                                  # maintenance, not queries
    ("ivfpq", lambda: (setattr(idx, "pq_serving", True),
                       setattr(idx, "_ivf_pack", None),
                       idx.ivf_maintenance())),   # retrain WITH the codebook
]:
    setup()
    res = idx.search_batch(queries, "demo", k=1)
    hits = sum(1 for p, (got, _) in zip(probe, res) if got == [f"m{p}"])
    print(f"{mode}: self-lookup recall {hits}/{len(probe)}   "
          f"stats={idx.stats().get('ivf') or idx.stats()['int8_serving']}")

print("\nall four modes answer from the same HBM arena; consolidation's")
print("dedup/link thresholds always use the exact master (exact=True).")
