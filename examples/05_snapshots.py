"""Binary snapshots: checkpoint/restore a memory system at index scale.

save_snapshot writes the device arena as raw columns (bf16-safe, versioned
behind an atomically-flipped CURRENT pointer) plus a small host JSON —
orders of magnitude faster than row-wise persistence at large node counts.

    python examples/05_snapshots.py
"""

import _bootstrap  # noqa: F401  (repo-root sys.path)

from lazzaro_tpu import MemorySystem

ms = MemorySystem(db_dir="snap_db", enable_async=False)
ms.start_conversation()
ms.chat("My cat is named Whiskers and loves tuna.")
ms.chat("I am training for a marathon in October.")
ms.end_conversation()
print(ms.save_snapshot("memory_snapshot"))
ms.close()

# A brand-new process restores the whole system — embeddings stay in the
# arena; host nodes are rebuilt without materializing vectors.
ms2 = MemorySystem(db_dir="snap_db2", enable_async=False,
                   load_from_disk=False)
print(ms2.load_snapshot("memory_snapshot"))
for node in ms2.search_memories("cat tuna"):
    print("  →", node.content)
ms2.close()
