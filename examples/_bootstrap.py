"""Make the in-repo package importable when examples run as scripts.

``python examples/0N_*.py`` puts examples/ (not the repo root) on
``sys.path``; importing this module from each example adds the root once,
in one place. Installing the package (``pip install -e .``) makes this a
no-op."""

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
