"""Quickstart: offline agent memory in six lines.

No API keys, no downloads — the default providers are the on-device hashing
embedder and the heuristic LLM, so this runs anywhere JAX does (CPU or TPU).

    python examples/01_quickstart.py
"""

import _bootstrap  # noqa: F401  (repo-root sys.path)

from lazzaro_tpu import MemorySystem

ms = MemorySystem(db_dir="quickstart_db", enable_async=False)

ms.start_conversation()
print(ms.chat("I work as a data engineer on a big ETL project."))
print(ms.chat("I love hiking in the mountains on weekends."))
ms.end_conversation()          # LLM fact extraction → graph consolidation

print("\nRecalled memories:")
for node in ms.search_memories("what does the user do for work?"):
    print(f"  [{node.type}] {node.content}  (salience {node.salience:.2f})")

print("\nStats:", ms.get_stats()["index"])
ms.close()
