"""Real checkpoints, zero egress: drop local HF weights into the in-tree
models. This example builds tiny RANDOM torch models in memory (stand-ins
for files you already have on disk) — swap in your own paths.

    python examples/03_hf_checkpoints.py
"""

import _bootstrap  # noqa: F401  (repo-root sys.path)

import numpy as np
import torch
import transformers

from lazzaro_tpu.models.encoder import TextEncoder
from lazzaro_tpu.models.llm import LanguageModel

# --- Encoder: a BERT/bge-class checkpoint + its vocab.txt ------------------
bert_cfg = transformers.BertConfig(
    vocab_size=100, hidden_size=32, num_hidden_layers=2,
    num_attention_heads=4, intermediate_size=64, max_position_embeddings=64)
bert = transformers.BertModel(bert_cfg).eval()

vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "the", "quick", "fox", "hello",
         "world"] + [f"tok{i}" for i in range(91)]
with open("/tmp/example_vocab.txt", "w") as f:
    f.write("\n".join(vocab) + "\n")

enc = TextEncoder.from_hf(bert, vocab_file="/tmp/example_vocab.txt", max_len=16)
vecs = enc.encode_batch(["the quick fox", "hello world"])
print("encoder vectors:", vecs.shape, "norms:", np.linalg.norm(vecs, axis=1))

# --- Decoder: a Gemma-1-class causal LM ------------------------------------
gemma_cfg = transformers.GemmaConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    head_dim=8, max_position_embeddings=64)
gemma = transformers.GemmaForCausalLM(gemma_cfg).eval()

lm = LanguageModel.from_hf(gemma, max_seq=64)
ids = np.random.RandomState(0).randint(3, 128, (1, 8))
print("decoder logits:", lm.model.apply(
    {"params": lm.params},
    np.asarray(ids, np.int32),
    np.arange(8)[None, :].astype(np.int32))[0].shape)

# With a real checkpoint you'd also pass its tokenizer:
#   tok = transformers.AutoTokenizer.from_pretrained("/path/to/gemma")
#   lm = LanguageModel.from_hf(gemma, hf_tokenizer=tok)
#   print(lm.generate("The capital of France is", max_new_tokens=16))
