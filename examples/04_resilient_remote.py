"""Remote providers with failure detection: circuit breaker + offline
fallback, instead of the silent ""/zero-vector degradation remote APIs
usually cause. This example scripts a flaky "remote" provider; swap in
OpenAILLM/OpenAIEmbedder (same protocols) for the real thing.

    python examples/04_resilient_remote.py
"""

import _bootstrap  # noqa: F401  (repo-root sys.path)

from lazzaro_tpu import MemorySystem
from lazzaro_tpu.core.resilience import ResilientEmbedder, ResilientLLM


class FlakyRemoteLLM:
    """Stands in for a remote API that dies mid-session."""
    def __init__(self):
        self.calls = 0

    def completion(self, messages, response_format=None):
        self.calls += 1
        if self.calls > 2:
            raise ConnectionError("remote API down")
        return ""   # reference-style swallowed failure — ALSO detected


llm = ResilientLLM(FlakyRemoteLLM(), max_retries=1,
                   breaker_threshold=3, cooldown=30.0)

ms = MemorySystem(db_dir="resilient_db", enable_async=False,
                  llm_provider=llm)
ms.start_conversation()
print(ms.chat("I collect rare stamps from the 1950s."))
ms.end_conversation()

print("\nmemories survived the dead API:")
for node in ms.search_memories("stamps collection"):
    print("  →", node.content)

print("\nprovider health:", llm.health())
ms.close()
