"""Pod-scale serving: the SAME orchestrator code, sharded over a mesh.

The arena index row-shards over the mesh 'data' axis; GSPMD partitions every
kernel (search matmul, scatters, decay, linking) and inserts the collectives.
Run on real chips, or simulate a pod on CPU:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/02_mesh_serving.py
"""

import _bootstrap  # noqa: F401  (repo-root sys.path)

import jax

from lazzaro_tpu import MemorySystem
from lazzaro_tpu.parallel.mesh import make_mesh

n = len(jax.devices())
mesh = make_mesh(("data",), (n,))
print(f"mesh: {n} devices on the 'data' axis")

ms = MemorySystem(db_dir="mesh_db", enable_async=False, mesh=mesh)
ms.start_conversation()
ms.chat("My research area is sparse retrieval over TPU pods.")
ms.chat("I maintain a 1M-node memory graph for a fleet of agents.")
ms.end_conversation()

# Fleet serving: many agents' queries in ONE batched kernel dispatch.
queries = [
    "what is the research area?",
    "how big is the memory graph?",
    "sparse retrieval pods",
]
for q, nodes in zip(queries, ms.search_memories_batch(queries, limit=2)):
    print(f"\n{q}")
    for node in nodes:
        print(f"  → {node.content}")

print("\nindex:", ms.get_stats()["index"])   # note the mesh field
ms.close()
