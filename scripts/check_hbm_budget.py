#!/usr/bin/env python3
"""CI gate: every serving geometry must fit the per-chip HBM budget —
measured AND predicted.

Each fusion round grows the live set of the one big dispatch (arena +
shadow + IVF tables + edge arena + packed readback). Before ISSUE 11 this
gate only *observed* geometries a bench happened to compile — the
``kernel.peak_hbm_bytes{...}`` AOT gauges PR 6/PR 9 record — so a novel
(mode × batch × rows × mesh) request could still OOM at runtime. "Memory
Safe Computations with XLA" (PAPERS.md) argues the bound should be
*guaranteed* before compilation; the admission-time planner
(``lazzaro_tpu/plan``) now does that live, and this script closes the CI
loop around it. It walks the checked-in artifacts and

- FAILS (exit 1) when any recorded kernel's MEASURED peak exceeds the
  budget (``--budget-gb``, default 16 — a v5e chip); write-path
  (``path="ingest"``) gauges included, summary reports coverage;
- FAILS when any recorded AOT gauge exceeds the cost model's PREDICTION
  for its geometry (model-soundness: the planner's admission decisions
  are only a guarantee while predictions over-bound every measurement).
  ``--calibrate`` instead grows the persisted multipliers
  (``bench_artifacts/plan_calibration.json`` — the residual log beside
  the kernel-cache artifacts) until they do, for maintainer runs;
- SWEEPS the planner's prediction over every geometry the benches
  *exercised* (gauge labels + any ``geometries_exercised`` list an
  artifact embeds — not just ones that compiled) and FAILS on any
  predicted-over-budget geometry for which ``plan_geometry`` finds NO
  feasible split (batch sub-dispatches riding the pad buckets, or the
  chunked arena scan): a geometry that would OOM with no planned
  degradation path turns red here instead of in production;
- GATES ``"hbm_plan": true`` artifacts (the BENCH_HBM_PLAN stage): they
  must record a ``plan`` block whose ``split_dispatches`` show the
  planner actually split something, a measured
  ``resource_exhausted_crashes == 0``, and a
  ``planned_dispatches_per_turn`` matching the measured count — a
  planned multi-dispatch turn is recorded, never silent;
- GATES ``"paged": true`` artifacts (the BENCH_PAGED_ARENA stage,
  ISSUE 17): post-demote ``pages_free`` must be measured > 0 (demotion
  really PUSHED slots back — reclaimed capacity, not an accounting
  fiction), the re-ingest after it must NOT have grown the pool (the
  freed pages were actually reused), the growth step must record
  ``grow_copied_pool == false`` (logical growth reuses the emb pool
  buffer by reference), and the planner's post-growth paged
  resident-bytes prediction must stay at or below the dense twin's —
  the copy-free-growth claim in admission-model terms;
- RECORDS the headroom back into each artifact (an ``hbm_budget``
  block). ``--no-write`` skips the write-back.

Usage:
    python scripts/check_hbm_budget.py [--budget-gb G] [--no-write]
        [--calibrate] [--calibration PATH] [artifact.json ...]
"""

from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import sys

GAUGE_PREFIX = "kernel.peak_hbm_bytes"
_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
_DEFAULT_CALIBRATION = os.path.join(_ROOT, "bench_artifacts",
                                    "plan_calibration.json")


def _load_plan_model():
    """Load ``lazzaro_tpu/plan/model.py`` by file path — pure stdlib, so
    the CI sweep never pays a jax import."""
    path = os.path.join(_ROOT, "lazzaro_tpu", "plan", "model.py")
    spec = importlib.util.spec_from_file_location("_lz_plan_model", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_lz_plan_model"] = mod   # dataclasses resolves __module__
    spec.loader.exec_module(mod)
    return mod


def _parse_labels(key: str) -> dict:
    if "{" not in key:
        return {}
    inner = key[key.index("{") + 1:key.rindex("}")]
    out = {}
    for part in inner.split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            out[k.strip()] = v.strip().strip('"')
    return out


def _mesh_parts(label: str) -> int:
    try:
        return max(1, int(str(label).split("x")[0]))
    except (ValueError, AttributeError):
        return 1


def _find(obj, key):
    if isinstance(obj, dict):
        if key in obj:
            return obj[key]
        for v in obj.values():
            hit = _find(v, key)
            if hit is not None:
                return hit
    elif isinstance(obj, list):
        for v in obj:
            hit = _find(v, key)
            if hit is not None:
                return hit
    return None


def _geometry_from_gauge(plan_mod, key: str, artifact: dict):
    """Reconstruct the planner geometry one gauge key describes; labels
    carry (mode|path, k, rows, batch, mesh), the artifact supplies dim
    and dtype. Older gauges without a batch label sweep at a
    conservative default."""
    lab = _parse_labels(key)
    dim = _find(artifact, "dim") or 768
    dtype = str(_find(artifact, "dtype") or "float32")
    dtype_bytes = 2 if "16" in dtype else 4
    rows = int(lab.get("rows") or 0)
    if rows <= 0:
        return None
    # ISSUE 16: pq=true labels (serve mode="pq"/"pq_tiered" + ingest with
    # in-kernel code maintenance) sweep through the PQ resident/transient
    # terms of the cost model.
    pq = 1 if lab.get("pq") == "true" else 0
    # ISSUE 18: replica-group placements label their gauges with the
    # fleet-wide replication factor; mesh_parts is already per-GROUP.
    groups = int(lab.get("groups") or 1)
    # ISSUE 19: path="lifecycle" labels (the fused all-tenant maintenance
    # sweep) carry the verdict-tenant count, archive depth, and edge-pool
    # capacity — the [tenants, rows] importance tile + edge working set
    # the cost model's lifecycle branch bounds.
    if lab.get("path") == "lifecycle":
        return plan_mod.Geometry(
            kind="lifecycle", mode="lifecycle",
            batch=int(lab.get("tenants") or 1), rows=rows, dim=int(dim),
            k=int(lab.get("k") or 8), dtype_bytes=dtype_bytes,
            mesh_parts=_mesh_parts(lab.get("mesh", "1")),
            edge_cap=int(lab.get("edge_cap") or 0))
    if lab.get("path") == "ingest":
        return plan_mod.Geometry(
            kind="ingest", mode="ingest",
            batch=int(lab.get("batch") or 256), rows=rows, dim=int(dim),
            k=3, dtype_bytes=dtype_bytes,
            mesh_parts=_mesh_parts(lab.get("mesh", "1")),
            ivf=1 if lab.get("ivf") == "true" else 0, pq=pq,
            replica_groups=groups)
    return plan_mod.Geometry(
        kind="serve", mode=lab.get("mode", "exact"),
        batch=int(lab.get("batch") or 128), rows=rows, dim=int(dim),
        k=int(lab.get("k") or 128), dtype_bytes=dtype_bytes,
        mesh_parts=_mesh_parts(lab.get("mesh", "1")), pq=pq,
        slack=int(lab.get("slack") or 8), replica_groups=groups,
        # ISSUE 20: semantic-cache serving labels its gauges with the
        # ring geometry — the resident ring + probe tile sweep through
        # the cost model's sem terms
        sem_slots=int(lab.get("sem_slots") or 0),
        sem_width=int(lab.get("sem_width") or 0))


def _geometry_from_dict(plan_mod, d: dict):
    try:
        return plan_mod.Geometry(
            kind=str(d.get("kind", "serve")),
            mode=str(d.get("mode", "exact")),
            batch=int(d.get("batch", 8)), rows=int(d.get("rows", 1024)),
            dim=int(d.get("dim", 768)), k=int(d.get("k", 128)),
            dtype_bytes=int(d.get("dtype_bytes", 4)),
            mesh_parts=int(d.get("mesh_parts", 1)),
            edge_cap=int(d.get("edge_cap", 0)),
            nprobe=int(d.get("nprobe", 0)),
            ivf=int(d.get("ivf", 0)),
            pq=int(d.get("pq", 0)),
            slack=int(d.get("slack", 8)),
            pool_rows=int(d.get("pool_rows", 0)),
            replica_groups=int(d.get("replica_groups", 1)),
            sem_slots=int(d.get("sem_slots", 0)),
            sem_width=int(d.get("sem_width", 0)))
    except (TypeError, ValueError):
        return None


def _collect(obj, found):
    """Every ``kernel.peak_hbm_bytes{...}`` gauge anywhere in the artifact
    (telemetry blocks, registry snapshots, metrics_summary embeds)."""
    if isinstance(obj, dict):
        for k, v in obj.items():
            if isinstance(k, str) and k.startswith(GAUGE_PREFIX) \
                    and isinstance(v, (int, float)):
                found[k] = max(float(v), found.get(k, 0.0))
            else:
                _collect(v, found)
    elif isinstance(obj, list):
        for v in obj:
            _collect(v, found)


def _collect_sweeps(obj, sweeps):
    """Every ``geometries_exercised`` list anywhere in the artifact —
    the geometries a bench stage SERVED, compiled or not."""
    if isinstance(obj, dict):
        for k, v in obj.items():
            if k == "geometries_exercised" and isinstance(v, list):
                sweeps.extend(x for x in v if isinstance(x, dict))
            else:
                _collect_sweeps(v, sweeps)
    elif isinstance(obj, list):
        for v in obj:
            _collect_sweeps(v, sweeps)


def _hbm_plan_roots(obj, path, roots):
    if isinstance(obj, dict):
        if obj.get("hbm_plan") is True:
            roots.append((path, obj))
        for k, v in obj.items():
            _hbm_plan_roots(v, f"{path}.{k}", roots)
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            _hbm_plan_roots(v, f"{path}[{i}]", roots)


def _check_hbm_plan_root(loc, root, bad):
    """The ISSUE 11 gate on one ``"hbm_plan": true`` dict."""
    plan = root.get("plan")
    if not isinstance(plan, dict):
        bad.append((loc, "hbm_plan artifact records no 'plan' block"))
        return
    try:
        splits_ok = float(plan.get("split_dispatches", 0)) >= 1
    except (TypeError, ValueError):
        splits_ok = False
    if not splits_ok:
        bad.append((loc, "plan block records no split_dispatches — the "
                         "budget ladder never forced a planned split"))
    if plan.get("resource_exhausted_crashes") != 0:
        bad.append((loc, f"resource_exhausted_crashes == "
                         f"{plan.get('resource_exhausted_crashes')!r} "
                         f"(must be a measured 0)"))
    measured = _find(root, "dispatches_per_turn")
    planned = root.get("planned_dispatches_per_turn")
    if planned is None:
        bad.append((loc, "hbm_plan artifact must record "
                         "'planned_dispatches_per_turn' next to the "
                         "measured count"))
    elif measured is not None and float(measured) != float(planned):
        bad.append((loc, f"measured dispatches_per_turn {measured!r} != "
                         f"planned_dispatches_per_turn {planned!r} — an "
                         f"UNplanned split happened"))
    probe = root.get("fused_probe")
    if not isinstance(probe, dict):
        bad.append((loc, "hbm_plan artifact must record a 'fused_probe' "
                         "(an under-budget ladder point)"))
    else:
        got = probe.get("measured_dispatches_per_turn")
        try:
            ok = float(got) == 1.0
        except (TypeError, ValueError):
            ok = False
        if not ok:
            bad.append((loc, f"fused_probe measured_dispatches_per_turn "
                             f"== {got!r} (an UNDER-budget geometry must "
                             f"still cost exactly ONE dispatch)"))
    sweeps: list = []
    _collect_sweeps(root, sweeps)
    if not sweeps:
        bad.append((loc, "hbm_plan artifact must embed the "
                         "'geometries_exercised' sweep list"))


def _paged_roots(obj, path, roots):
    if isinstance(obj, dict):
        if obj.get("paged") is True:
            roots.append((path, obj))
        for k, v in obj.items():
            _paged_roots(v, f"{path}.{k}", roots)
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            _paged_roots(v, f"{path}[{i}]", roots)


def _check_paged_root(loc, root, bad):
    """The ISSUE 17 gate on one ``"paged": true`` dict."""
    after = root.get("page_stats_after_demote")
    free = after.get("pages_free") if isinstance(after, dict) else None
    try:
        free_ok = float(free) > 0
    except (TypeError, ValueError):
        free_ok = False
    if not free_ok:
        bad.append((loc, f"post-demote pages_free == {free!r} (demotion "
                         f"must measurably push slots back to the free "
                         f"list)"))
    if root.get("reingest_grew_pool") is not False:
        bad.append((loc, f"reingest_grew_pool == "
                         f"{root.get('reingest_grew_pool')!r} (the "
                         f"re-ingest after demotion must reuse the "
                         f"reclaimed pages, not grow the pool)"))
    growth = root.get("growth")
    copied = growth.get("grow_copied_pool") if isinstance(growth, dict) \
        else None
    if copied is not False:
        bad.append((loc, f"growth.grow_copied_pool == {copied!r} (logical "
                         f"growth must keep the emb pool buffer by "
                         f"reference — zero bytes copied)"))
    plan = root.get("planner")
    if not isinstance(plan, dict):
        bad.append((loc, "paged artifact must record a 'planner' block "
                         "(resident-bytes predictions, dense vs paged)"))
        return
    paged_b = plan.get("resident_bytes_paged_after_grow")
    dense_b = plan.get("resident_bytes_dense_after_grow")
    try:
        ok = float(paged_b) <= float(dense_b)
    except (TypeError, ValueError):
        ok = False
    if not ok:
        bad.append((loc, f"resident_bytes_paged_after_grow {paged_b!r} > "
                         f"dense {dense_b!r} (growth must not drag the "
                         f"pool along with logical capacity)"))


def check_artifact(path: str, budget_bytes: float, write: bool):
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        print(f"[hbm] skipping unreadable {path}: {e}", file=sys.stderr)
        return None, {}, []
    found: dict = {}
    _collect(data, found)
    over = [(k, v) for k, v in sorted(found.items()) if v > budget_bytes]
    if found and write:
        worst_key = max(found, key=found.get)
        worst = found[worst_key]
        data["hbm_budget"] = {
            "budget_bytes": budget_bytes,
            "kernels_checked": len(found),
            "max_peak_bytes": worst,
            "worst_kernel": worst_key,
            "headroom_bytes": budget_bytes - worst,
            "headroom_fraction": round(1.0 - worst / budget_bytes, 4),
            "ok": not over,
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1)
        os.replace(tmp, path)
    return data, found, [(path, k, v) for k, v in over]


def main(argv):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", help="artifact JSONs "
                    "(default: bench_artifacts/*.json)")
    ap.add_argument("--budget-gb", type=float, default=16.0,
                    help="per-chip HBM budget in GiB (default 16)")
    ap.add_argument("--no-write", action="store_true",
                    help="verify only; do not record headroom back")
    ap.add_argument("--calibration", default=_DEFAULT_CALIBRATION,
                    help="cost-model calibration JSON (multipliers + "
                         "residual log)")
    ap.add_argument("--calibrate", action="store_true",
                    help="maintainer mode: GROW the calibration until "
                         "every gauge is over-bounded and persist it, "
                         "instead of failing on unsound predictions")
    args = ap.parse_args(argv)
    if args.paths:
        paths = args.paths
    else:
        root = os.path.join(_ROOT, "bench_artifacts")
        paths = sorted(p for p in glob.glob(os.path.join(root, "*.json"))
                       if os.path.basename(p) != "plan_calibration.json")
    budget = args.budget_gb * (1 << 30)
    plan_mod = _load_plan_model()
    model = plan_mod.CostModel.load_or_default(
        args.calibration if os.path.exists(args.calibration) else None)
    checked = 0
    checked_ingest = 0
    checked_sound = 0
    checked_swept = 0
    checked_plan_roots = 0
    checked_paged_roots = 0
    breaches = []
    unsound = []
    infeasible = []
    bad_plan: list = []
    with_gauges = 0
    for p in paths:
        data, found, over = check_artifact(p, budget,
                                           write=not args.no_write)
        if data is None:
            continue
        base = os.path.basename(p)
        checked += len(found)
        if found:
            with_gauges += 1
            checked_ingest += sum(1 for k in found
                                  if 'path="ingest"' in k)
        breaches.extend(over)
        geoms = []
        for key, measured in sorted(found.items()):
            g = _geometry_from_gauge(plan_mod, key, data)
            if g is None:
                continue
            geoms.append((f"{base}:{key}", g))
            # model soundness: the prediction must over-bound the
            # measured AOT peak, or the admission guarantee is hollow
            checked_sound += 1
            if args.calibrate:
                model.observe(g, measured)
            elif model.predict(g) < measured:
                unsound.append((base, key, measured, model.predict(g)))
        sweeps: list = []
        _collect_sweeps(data, sweeps)
        for d in sweeps:
            g = _geometry_from_dict(plan_mod, d)
            if g is not None:
                geoms.append((f"{base}:geometries_exercised", g))
        # the planner sweep: every exercised geometry must either fit or
        # have a feasible planned split
        for loc, g in geoms:
            checked_swept += 1
            d = plan_mod.plan_geometry(
                model, g, int(budget),
                chunkable=(g.kind == "serve" and g.mesh_parts == 1))
            if not d.feasible:
                infeasible.append((loc, g, d))
        roots: list = []
        _hbm_plan_roots(data, base, roots)
        for loc, rootd in roots:
            checked_plan_roots += 1
            _check_hbm_plan_root(loc, rootd, bad_plan)
        proots: list = []
        _paged_roots(data, base, proots)
        for loc, rootd in proots:
            checked_paged_roots += 1
            _check_paged_root(loc, rootd, bad_plan)
    if args.calibrate:
        model.save(args.calibration)
        print(f"[hbm] calibration persisted to {args.calibration} "
              f"({len(model.residuals)} residual(s), multipliers "
              f"{model.multipliers})")
    for path, key, val in breaches:
        print(f"HBM-BUDGET-EXCEEDED: {os.path.basename(path)}: {key} = "
              f"{val / (1 << 30):.2f} GiB > {args.budget_gb} GiB")
    for base, key, measured, predicted in unsound:
        print(f"MODEL-UNSOUND: {base}: {key} measured "
              f"{measured / (1 << 20):.1f} MiB > predicted "
              f"{predicted / (1 << 20):.1f} MiB — recalibrate with "
              f"--calibrate")
    for loc, g, d in infeasible:
        print(f"PLAN-INFEASIBLE: {loc}: {g.kind}/{g.mode} batch={g.batch}"
              f" rows={g.rows} k={g.k} mesh={g.mesh_parts} predicts "
              f"{d.predicted_bytes / (1 << 30):.2f} GiB and no split "
              f"fits {args.budget_gb} GiB")
    for loc, msg in bad_plan:
        print(f"HBM-PLAN-REGRESSION: {loc}: {msg}")
    n_bad = (len(breaches) + len(unsound) + len(infeasible)
             + len(bad_plan))
    print(f"[hbm] {checked} kernel gauge(s) ({checked_ingest} write-path) "
          f"across {with_gauges}/{len(paths)} artifact(s) checked against "
          f"{args.budget_gb} GiB; {checked_sound} soundness check(s), "
          f"{checked_swept} geometry sweep(s), {checked_plan_roots} "
          f"hbm_plan gate(s), {checked_paged_roots} paged-arena "
          f"gate(s); {n_bad} failure(s)")
    return 1 if n_bad else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
