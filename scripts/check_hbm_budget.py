#!/usr/bin/env python3
"""CI gate: every compiled serving geometry must fit the per-chip HBM budget.

Each fusion round grows the live set of the one big dispatch (arena +
shadow + IVF tables + edge arena + packed readback), and before this gate
the only OOM signal was a runtime crash at a new (size × mode × mesh)
combination. "Memory Safe Computations with XLA" (PAPERS.md) argues the
fix is compile-time enforcement — and PR 6 already records the measured
half: ``MemoryIndex._maybe_record_hbm`` AOT-lowers every fused serving
geometry's read twin once and lands its ``memory_analysis()`` peak in the
``kernel.peak_hbm_bytes{mode,k,rows,mesh}`` gauge, which every bench
artifact embeds in its telemetry block. This script (ROADMAP item 8 seed,
ISSUE 8 satellite) walks the checked-in artifacts and

- FAILS (exit 1) when any recorded kernel's peak exceeds the budget
  (``--budget-gb``, default 16 — a v5e chip), so a geometry that will OOM
  in production turns red in CI instead; since ISSUE 9 the ingest path
  records ``kernel.peak_hbm_bytes{path="ingest",batch,rows,mesh}`` via
  the same AOT read-twin lowering, so WRITE-path geometries (the fused
  ingest program's arena + edge arena + shadow + link-scan tiles) are
  gated here too — the summary line reports serve/ingest coverage
  separately;
- RECORDS the headroom back into each artifact (an ``hbm_budget`` block:
  max peak, worst kernel, headroom bytes and fraction), so the next
  size-doubling PR knows how much room the current programs leave.
  ``--no-write`` skips the write-back (plain verification mode).

Usage:
    python scripts/check_hbm_budget.py [--budget-gb G] [--no-write] \
        [artifact.json ...]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

GAUGE_PREFIX = "kernel.peak_hbm_bytes"


def _collect(obj, found):
    """Every ``kernel.peak_hbm_bytes{...}`` gauge anywhere in the artifact
    (telemetry blocks, registry snapshots, metrics_summary embeds)."""
    if isinstance(obj, dict):
        for k, v in obj.items():
            if isinstance(k, str) and k.startswith(GAUGE_PREFIX) \
                    and isinstance(v, (int, float)):
                found[k] = max(float(v), found.get(k, 0.0))
            else:
                _collect(v, found)
    elif isinstance(obj, list):
        for v in obj:
            _collect(v, found)


def check_artifact(path: str, budget_bytes: float, write: bool):
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        print(f"[hbm] skipping unreadable {path}: {e}", file=sys.stderr)
        return 0, []
    found: dict = {}
    _collect(data, found)
    if not found:
        return 0, []
    worst_key = max(found, key=found.get)
    worst = found[worst_key]
    over = [(k, v) for k, v in sorted(found.items()) if v > budget_bytes]
    if write:
        data["hbm_budget"] = {
            "budget_bytes": budget_bytes,
            "kernels_checked": len(found),
            "max_peak_bytes": worst,
            "worst_kernel": worst_key,
            "headroom_bytes": budget_bytes - worst,
            "headroom_fraction": round(1.0 - worst / budget_bytes, 4),
            "ok": not over,
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1)
        os.replace(tmp, path)
    return len(found), [(path, k, v) for k, v in over]


def main(argv):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", help="artifact JSONs "
                    "(default: bench_artifacts/*.json)")
    ap.add_argument("--budget-gb", type=float, default=16.0,
                    help="per-chip HBM budget in GiB (default 16)")
    ap.add_argument("--no-write", action="store_true",
                    help="verify only; do not record headroom back")
    args = ap.parse_args(argv)
    if args.paths:
        paths = args.paths
    else:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir, "bench_artifacts")
        paths = sorted(glob.glob(os.path.join(root, "*.json")))
    budget = args.budget_gb * (1 << 30)
    checked = 0
    checked_ingest = 0
    breaches = []
    with_gauges = 0
    for p in paths:
        n, over = check_artifact(p, budget, write=not args.no_write)
        checked += n
        if n:
            with_gauges += 1
            try:
                with open(p) as f:
                    found: dict = {}
                    _collect(json.load(f), found)
                checked_ingest += sum(1 for k in found
                                      if 'path="ingest"' in k)
            except (OSError, ValueError):
                pass
        breaches.extend(over)
    for path, key, val in breaches:
        print(f"HBM-BUDGET-EXCEEDED: {os.path.basename(path)}: {key} = "
              f"{val / (1 << 30):.2f} GiB > {args.budget_gb} GiB")
    print(f"[hbm] {checked} kernel gauge(s) ({checked_ingest} write-path) "
          f"across {with_gauges}/{len(paths)} artifact(s) checked against "
          f"{args.budget_gb} GiB; {len(breaches)} breach(es)")
    return 1 if breaches else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
