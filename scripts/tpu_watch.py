"""TPU-opportunistic capture loop (r4 verdict, next-round item #1).

The tunnel to the TPU backend flaps on a multi-hour scale (r1 down,
r2 up, r3 down, r4 up for the first ~25 min then wedged). bench.py
converts availability into evidence exactly once, at process start —
this watcher converts ANY window of availability, whenever it occurs:

  every PROBE_EVERY seconds, probe the backend in a subprocess with a
  hard timeout; on the first healthy probe run the capture ladder,
  cheapest rung first, writing each result to bench_artifacts/
  IMMEDIATELY (a later wedge cannot eat a captured artifact):

    1. kernels_1m  — synthetic-arena kernel + IVF capture (~5 min of
                     tunnel time; scripts/bench_tpu_kernels.py)
    2. graph_full  — the full 1M-graph bench.py against the prebuilt
                     BENCH_WORKDIR (reload + search + serving modes +
                     consolidation + LLM loop). Only when the prebuild
                     marker says the ingest is COMPLETE and no other
                     bench.py is running (two processes would race on
                     the store's delta segments).

Each rung runs at most CAPTURE_ATTEMPTS times (a rung that died on a
mid-run wedge is retried on the next healthy probe). State lives in
bench_artifacts/r5_watch_state.json; the log is append-only.

Run:  nohup python scripts/tpu_watch.py >> bench_artifacts/tpu_watch.log 2>&1 &
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "bench_artifacts")
STATE_PATH = os.path.join(ART, "r5_watch_state.json")
WORKDIR = os.path.join(REPO, "bench_workdir")
PROBE_EVERY = float(os.environ.get("WATCH_PROBE_EVERY", 420))
PROBE_TIMEOUT = float(os.environ.get("WATCH_PROBE_TIMEOUT", 90))
CAPTURE_ATTEMPTS = 3

_PROBE_SNIPPET = r"""
import json, sys
from lazzaro_tpu.utils import backend_probe
h = backend_probe.ensure_healthy_or_cpu(timeout={t}, retries=0)
print(json.dumps(h))
sys.exit(0 if h.get("ok") else 1)
"""


def log(msg: str) -> None:
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def load_state() -> dict:
    try:
        with open(STATE_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def save_state(st: dict) -> None:
    tmp = STATE_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(st, f, indent=1)
    os.replace(tmp, STATE_PATH)


def probe_healthy() -> bool:
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SNIPPET.format(t=PROBE_TIMEOUT)],
            cwd=REPO, capture_output=True, text=True,
            timeout=PROBE_TIMEOUT + 60)
        out = (r.stdout or "").strip().splitlines()
        log(f"probe rc={r.returncode} {out[-1][:160] if out else ''}")
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        log("probe: hard timeout (tunnel wedged)")
        return False


def ingest_complete() -> bool:
    marker = os.path.join(WORKDIR, "INGESTED_1000000_768_g2")
    try:
        with open(marker) as f:
            saved = json.load(f)
        return int(saved.get("convs_done", 0)) >= 200
    except (OSError, ValueError):
        return False


def other_bench_running() -> bool:
    r = subprocess.run(["pgrep", "-f", "python bench.py"],
                       capture_output=True, text=True)
    return bool(r.stdout.strip())


def run_capture(name: str, cmd, env_extra: dict, timeout_s: float) -> bool:
    """Run one rung; write the artifact + timestamped copy on success.
    Success = rc 0 AND a parseable JSON tail with a non-null value AND no
    tpu_unreachable error (a CPU-fallback run is NOT a TPU capture)."""
    env = dict(os.environ)
    env.update(env_extra)
    log(f"capture {name}: starting (timeout {timeout_s:.0f}s)")
    t0 = time.time()
    try:
        r = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                           text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        log(f"capture {name}: TIMED OUT after {time.time() - t0:.0f}s")
        return False
    tail = (r.stdout or "").strip().splitlines()
    stamp = time.strftime("%m%d_%H%M%S")
    err_path = os.path.join(ART, f"r5_{name}_{stamp}.stderr.txt")
    with open(err_path, "w") as f:
        f.write((r.stderr or "")[-20000:])
    if not tail:
        log(f"capture {name}: rc={r.returncode}, no stdout")
        return False
    try:
        doc = json.loads(tail[-1])
    except ValueError:
        log(f"capture {name}: unparseable tail: {tail[-1][:200]}")
        return False
    path = os.path.join(ART, f"r5_{name}_{stamp}.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    ok = (r.returncode == 0 and doc.get("value") is not None
          and "tpu_unreachable" not in str(doc.get("error", "")))
    dev = str(doc.get("extra", {}).get("device", ""))
    log(f"capture {name}: rc={r.returncode} ok={ok} device={dev!r} -> {path}")
    if ok and "TPU" not in dev and "tpu" not in dev:
        log(f"capture {name}: device is not TPU — counting as failed")
        return False
    return ok


RUNGS = [
    ("kernels_1m",
     [sys.executable, "scripts/bench_tpu_kernels.py"],
     {"BENCH_N": "1000000", "BENCH_DIM": "768"},
     45 * 60,
     lambda: True),
    ("graph_full",
     [sys.executable, "bench.py"],
     {"BENCH_WORKDIR": WORKDIR, "BENCH_INGEST_BUDGET_S": "4000",
      "BENCH_LLM_LOOP": "1", "BENCH_CONSOLIDATE": "1",
      "BENCH_REFDEFAULT": "1", "BENCH_LLM_GEOMETRY": "base2b"},
     150 * 60,
     lambda: ingest_complete() and not other_bench_running()),
]


def main() -> None:
    os.makedirs(ART, exist_ok=True)
    st = load_state()
    log(f"watcher up: probe every {PROBE_EVERY:.0f}s, state={st}")
    while True:
        todo = [(n, c, e, t) for n, c, e, t, gate in RUNGS
                if not st.get(n, {}).get("done")
                and st.get(n, {}).get("attempts", 0) < CAPTURE_ATTEMPTS
                and gate()]
        if not todo:
            blocked = [n for n, *_rest, gate in RUNGS
                       if not st.get(n, {}).get("done") and not gate()]
            if not blocked and all(st.get(n, {}).get("done")
                                   or st.get(n, {}).get("attempts", 0)
                                   >= CAPTURE_ATTEMPTS
                                   for n, *_ in RUNGS):
                log("all rungs done or exhausted — watcher exiting")
                return
            time.sleep(PROBE_EVERY)
            continue
        if probe_healthy():
            for name, cmd, env_extra, timeout_s in todo:
                rung_state = st.setdefault(name, {})
                rung_state["attempts"] = rung_state.get("attempts", 0) + 1
                save_state(st)
                if run_capture(name, cmd, env_extra, timeout_s):
                    rung_state["done"] = True
                    rung_state["ts"] = time.strftime("%Y-%m-%d %H:%M:%S")
                    save_state(st)
                else:
                    break   # tunnel likely wedged mid-run; re-probe first
        time.sleep(PROBE_EVERY)


if __name__ == "__main__":
    main()
