#!/usr/bin/env python3
"""Guard: fail when a bench artifact records a fused-serving dispatch
regression.

The fused serving acceptance bar (ISSUE 2/3) is ONE device dispatch per
coalesced retrieval batch. Bench stages that measure a fused path record a
MEASURED ``dispatches_per_turn`` in their JSON artifacts (bench.py
``bench_fused_quant`` wraps the jit entry points and counts); this script
walks every ``bench_artifacts/*.json`` (or the paths passed as arguments)
for ``dispatches_per_turn`` keys and exits nonzero if any value != 1 — so
a refactor that quietly splits the fused program back into multiple
dispatches turns red in CI instead of shipping.

Usage:
    python scripts/check_dispatch_counts.py [artifact.json ...]
"""

from __future__ import annotations

import glob
import json
import os
import sys


def _walk(obj, path, hits):
    if isinstance(obj, dict):
        for k, v in obj.items():
            here = f"{path}.{k}"
            if k == "dispatches_per_turn":
                hits.append((here, v))
            else:
                _walk(v, here, hits)
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            _walk(v, f"{path}[{i}]", hits)


def main(argv):
    if argv:
        paths = argv
    else:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir, "bench_artifacts")
        paths = sorted(glob.glob(os.path.join(root, "*.json")))
    checked = 0
    bad = []
    for p in paths:
        try:
            with open(p) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            print(f"[check] skipping unreadable {p}: {e}", file=sys.stderr)
            continue
        hits = []
        _walk(data, os.path.basename(p), hits)
        for loc, v in hits:
            checked += 1
            if v != 1:
                bad.append((loc, v))
    for loc, v in bad:
        print(f"REGRESSION: {loc} == {v!r} (expected 1)")
    print(f"[check] {checked} dispatches_per_turn value(s) across "
          f"{len(paths)} artifact(s); {len(bad)} regression(s)")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
