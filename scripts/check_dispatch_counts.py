#!/usr/bin/env python3
"""Guard: fail when a bench artifact records a fused-serving regression.

The fused serving acceptance bar (ISSUE 2/3/4/5) is ONE device dispatch per
coalesced retrieval batch — on the mesh path ONE *distributed* dispatch —
and for the approximate coarse stages (int8, IVF) a recall floor the
artifact itself records. Bench stages that measure a fused path record a
MEASURED ``dispatches_per_turn`` in their JSON artifacts (bench.py
``bench_fused_quant`` / ``bench_fused_ivf`` wrap the jit entry points,
``bench_fused_sharded`` wraps the pod index's ``_dispatch`` hook), and
recall-bearing stages record ``recall_at_10`` next to their
``recall_floor``. This script walks every ``bench_artifacts/*.json`` (or
the paths passed as arguments) and exits nonzero if:

  - any ``dispatches_per_turn`` != 1 (a refactor quietly split a fused
    program back into multiple dispatches — single-chip or distributed),
    UNLESS the same dict records a matching ``planned_dispatches_per_
    turn`` (ISSUE 11: the HBM planner may split an over-budget turn into
    planned sub-dispatches — a PLANNED count is accepted when measured
    == planned, a silent one never is),
  - any dict carrying both keys has ``recall_at_10`` < ``recall_floor``
    (a coarse-stage change quietly traded recall for throughput),
  - any dict carrying both keys has ``fused_vs_classic_speedup`` <
    ``speedup_floor`` (the fused path quietly lost its throughput edge
    over the semantics-equivalent classic sequence), or
  - a SHARDED artifact (any dict carrying a ``mesh`` sub-dict) does NOT
    record a measured ``dispatches_per_turn`` at all — a pod-path stage
    that stops measuring its dispatch count must fail loudly, not pass
    vacuously,
  - (ISSUE 6) a post-observability artifact measuring a fused path (any
    dict carrying ``dispatches_per_turn``) has NO ``telemetry`` block —
    every fused bench stage embeds ``bench._telemetry_block`` (pad-waste
    fraction, batch occupancy, queue-wait p50/p95, peak-HBM gauges) so
    the ragged-serving and HBM-budget directions always have a measured
    baseline; pre-ISSUE-6 artifacts (``pr2_``…``pr5_`` prefixes) are
    grandfathered,
  - (ISSUE 6) a ``telemetry`` block is malformed — missing the required
    keys — or its registry snapshot PROVES padding waste happened
    (``serve.padded_slots`` > ``serve.live_requests``) while the block's
    ``pad_waste_fraction`` fails to record it: measured waste that the
    artifact under-reports is the one observability regression this
    whole layer exists to prevent,
  - (ISSUE 7) a RAGGED artifact (any top-level dict with ``"ragged":
    true``) records a ``pad_waste_fraction`` above 0.15 — the whole
    point of the ragged layout is killing the pow2 padding tax, so
    waste creeping back past the linear-bucket ceiling is a
    regression — or records ``compile_cache_entries`` >
    ``modes_exercised`` (a per-k or per-shape kernel specialization
    snuck back in; ragged kernels are keyed per (mode × geometry)
    only); pre-ragged artifacts (``pr2_``…``pr6_`` prefixes) are
    grandfathered,
  - (ISSUE 12) an ONLINE-IVF artifact (any dict with ``"ivf_online":
    true``) does not record a measured ``dispatches_per_conversation``
    (gated == 1 by the generic rule — in-dispatch IVF maintenance must
    never grow the write path past ONE dispatch), lacks a
    ``recall_at_10``/``recall_floor`` pair (online tables must match the
    offline rebuild they replaced), lacks an
    ``ingest_overhead_fraction``, or records an
    ``assignment_staleness_fraction`` that is missing or above its
    recorded ``assignment_staleness_max`` (default 0.02 — mini-batch
    centroid drift stranding members is the failure mode online IVF must
    bound),
  - (ISSUE 9) a SHARDED-INGEST artifact (any dict with
    ``"ingest_sharded": true``) does not record a measured
    ``dispatches_per_conversation`` (gated to == 1 like
    ``dispatches_per_turn`` — one coalesced mega-batch must cost ONE
    distributed dispatch on the fused pod write path), or lacks a
    ``write_scaling``/``write_scaling_floor`` pair, or records
    ``write_scaling`` below its floor (the sharded write path must never
    regress below the single-chip fused path; real >1 scaling is the
    TPU-window item — on a shared-socket CPU mesh the chips share
    cores). ``dispatches_per_conversation`` values anywhere are gated to
    == 1 exactly like ``dispatches_per_turn``, and a ``mesh``-carrying
    artifact satisfies its measured-count requirement with either key,
    not record ``cold_hit_rate`` and ``hot_fraction``, or lacks a
    ``recall_at_10``/``recall_floor`` pair (the generic recall gate then
    enforces the floor — tiering must never silently trade recall for
    capacity), or records a missing/over-budget
    ``cold_hit_dispatches_per_turn`` (> 2: a cold hit is allowed the ONE
    bounded finish dispatch on top of the coarse scan, never a cascade;
    the hot-only probe's ``dispatches_per_turn`` stays pinned to 1 by
    the generic dispatch gate). Earlier artifacts never carry the flag,
    so they are grandfathered by construction,
  - (ISSUE 17) a PAGED-ARENA artifact (any dict with ``"paged": true``)
    does not record a measured ``dispatches_per_turn`` (gated == 1 by
    the generic rule — the free-list pop/push and the row_map gather
    ride INSIDE the fused programs, never as sibling dispatches), lacks
    a ``paged_qps_ratio``/``paged_qps_floor`` pair or records the ratio
    below its floor (the indirection gather must stay within 10% of the
    dense scan), or records a missing/nonzero ``mirror_mismatches``
    (the host free-list mirror must agree with the device readback tail
    on every pop — a drifted mirror silently corrupts slot reuse),
  - (ISSUE 16) a FUSED-PQ artifact (any dict with ``"pq_fused": true``)
    does not record a measured ``dispatches_per_turn`` (gated == 1 by
    the generic rule — the m-byte ADC member scan, exact rescore, and
    the gate/CSR/boost tail must stay ONE dispatch), lacks a
    ``recall_at_10``/``recall_floor`` pair vs the classic
    ``ivf_pq_search`` path on the same fixture, or does not record
    ``bytes_per_row`` (the resident-footprint headline — PQ's whole
    reason to exist — must stay measured, and below the int8 shadow's
    when both are present as ``bytes_per_row``/``int8_bytes_per_row``),

  - (ISSUE 19) a LIFECYCLE artifact (any dict with ``"lifecycle": true``)
    does not record a measured ``dispatches_per_sweep`` (gated == 1 by
    the generic rule — decay + weak-edge prune + archive verdicts for
    ALL tenants must stay ONE donated all-tenant dispatch, never the
    classic 3-dispatches-per-tenant host loop), does not record
    ``"bit_parity": true`` (the fused sweep must stay bit-identical to
    the classic decay/prune/evict host loop on the churn fixture —
    approximate maintenance silently corrupts every downstream recall
    number), lacks a ``serve_p99_ratio``/``serve_p99_bound`` pair or
    records the ratio above its bound (lifecycle ticks run UNDER live
    serving — blowing the serving tail is exactly the host-stall
    failure mode this sweep exists to kill), or lacks a
    ``host_stall_speedup``/``host_stall_floor`` pair or records the
    speedup below its floor (the one-dispatch sweep quietly lost its
    wall-clock edge over the per-tenant loop),

  - (ISSUE 18) a REPLICA artifact (any dict with ``"replica": true``)
    does not record a measured ``dispatches_per_turn`` (gated == 1 by
    the generic rule — a routed turn must cost ONE group-local dispatch
    fleet-wide, no stray dispatch on any other group), lacks a
    ``qps_scaling``/``qps_scaling_floor`` pair or records the scaling
    below its floor (adding replica groups must keep buying aggregate
    QPS — the whole reason the placement layer exists), lacks a
    ``recall_at_10``/``recall_floor`` pair (the generic recall gate then
    enforces it — group-local serving must stay exact), records a
    missing/over-bound ``replica_staleness_s`` vs its
    ``staleness_bound_s`` (the journal fan-out's bounded-staleness
    window is a measured promise, not an assumption), or records a
    crash-replay cell with ``lost_facts`` or ``doubled_facts`` != 0
    (journal-subscriber recovery must converge exactly),

  - (ISSUE 20) a SEMANTIC-CACHE artifact (any dict with
    ``"semantic_cache": true``) does not record a measured
    ``dispatches_per_turn`` (gated == 1 by the generic rule — the
    similarity probe, the hit early-out, and the ring writeback all
    ride INSIDE the one fused dispatch, never as sibling dispatches),
    lacks a ``semantic_hit_rate``/``hit_rate_floor`` pair or records
    the rate below its floor (the Zipf repeated-intent workload stopped
    hitting — the ring geometry or the probe eligibility mask
    regressed), records a missing/nonzero ``stale_hits`` (under
    ingest/delete churn a cached window served results a fresh scan
    would not — the ONE correctness failure the invalidation reverse
    index exists to prevent), does not record ``"miss_parity": true``
    (a cold probe must be a bit-identical pass-through: ids AND scores
    of a never-seen population must match the cache-off twin), lacks a
    ``recall_at_10``/``recall_floor`` pair (the generic recall gate
    then enforces it — a hit-served window must BE the exact answer),
    or records ``semantic_vs_off_speedup`` below its ``speedup_floor``
    (hits stopped buying back their scan blocks),

so any of these regressions turns red in CI instead of shipping.

Usage:
    python scripts/check_dispatch_counts.py [artifact.json ...]
"""

from __future__ import annotations

import glob
import json
import os
import sys

# Artifacts from before the observability layer existed: exempt from the
# telemetry-block requirement (their numbers are still gate-checked).
_PRE_TELEMETRY_PREFIXES = ("pr2_", "pr3_", "pr4_", "pr5_")

# Artifacts from before ragged serving existed: exempt from the padding
# ceiling and the compile-cache bound (their pow2 waste is the measured
# BASELINE the ragged numbers are judged against, not a regression).
_PRE_RAGGED_PREFIXES = _PRE_TELEMETRY_PREFIXES + ("pr6_",)

# Hard ceiling on recorded padding waste for ragged artifacts: linear
# pad buckets admit at most ~15% dead slots at the smallest bucket.
_RAGGED_PAD_WASTE_MAX = 0.15

_TELEMETRY_KEYS = ("pad_waste_fraction", "queue_wait_ms_p50",
                   "queue_wait_ms_p95", "peak_hbm_bytes")


_DISPATCH_KEYS = ("dispatches_per_turn", "dispatches_per_conversation",
                  "dispatches_per_sweep")


def _walk(obj, path, hits, recalls, speedups, meshes, tel_blocks, raggeds,
          tiereds, ingests, online_ivfs, pq_fuseds, pageds, replicas,
          lifecycles, semantics):
    if isinstance(obj, dict):
        if "recall_at_10" in obj and "recall_floor" in obj:
            recalls.append((path, obj["recall_at_10"], obj["recall_floor"]))
        if "fused_vs_classic_speedup" in obj and "speedup_floor" in obj:
            speedups.append((path, obj["fused_vs_classic_speedup"],
                             obj["speedup_floor"]))
        if isinstance(obj.get("mesh"), dict):
            meshes.append((path, any(k in obj for k in _DISPATCH_KEYS)))
        if any(k in obj for k in _DISPATCH_KEYS) or "telemetry" in obj:
            tel_blocks.append((path,
                               any(k in obj for k in _DISPATCH_KEYS),
                               obj.get("telemetry")))
        if obj.get("ragged") is True:
            raggeds.append((path, obj))
        if obj.get("tiered") is True:
            tiereds.append((path, obj))
        if obj.get("ingest_sharded") is True:
            ingests.append((path, obj))
        if obj.get("ivf_online") is True:
            online_ivfs.append((path, obj))
        if obj.get("pq_fused") is True:
            pq_fuseds.append((path, obj))
        if obj.get("paged") is True:
            pageds.append((path, obj))
        if obj.get("replica") is True:
            replicas.append((path, obj))
        if obj.get("lifecycle") is True:
            lifecycles.append((path, obj))
        if obj.get("semantic_cache") is True:
            semantics.append((path, obj))
        for k, v in obj.items():
            here = f"{path}.{k}"
            if k in _DISPATCH_KEYS:
                # ISSUE 11: a planner-split turn records its PLANNED
                # count next to the measured one — accepted iff equal.
                hits.append((here, v, obj.get("planned_" + k)))
            else:
                _walk(v, here, hits, recalls, speedups, meshes, tel_blocks,
                      raggeds, tiereds, ingests, online_ivfs, pq_fuseds,
                      pageds, replicas, lifecycles, semantics)
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            _walk(v, f"{path}[{i}]", hits, recalls, speedups, meshes,
                  tel_blocks, raggeds, tiereds, ingests, online_ivfs,
                  pq_fuseds, pageds, replicas, lifecycles, semantics)


def _check_telemetry(loc, measured_fused, block, grandfathered, bad):
    """The ISSUE 6 observability gate on one artifact dict."""
    if block is None:
        if measured_fused and not grandfathered:
            bad.append((loc, "fused-path artifact (has dispatches_per_turn)"
                             " records no 'telemetry' block"))
        return
    if not isinstance(block, dict):
        bad.append((loc, f"'telemetry' is {type(block).__name__}, "
                         f"expected a dict"))
        return
    for key in _TELEMETRY_KEYS:
        if key not in block:
            bad.append((loc, f"telemetry block missing '{key}'"))
    counters = (block.get("snapshot") or {}).get("counters") or {}
    live = sum(v for k, v in counters.items()
               if k.split("{")[0] == "serve.live_requests")
    padded = sum(v for k, v in counters.items()
                 if k.split("{")[0] == "serve.padded_slots")
    if padded > live > 0:
        truth = 1.0 - live / padded
        got = block.get("pad_waste_fraction")
        try:
            ok = abs(float(got) - truth) < 1e-3
        except (TypeError, ValueError):
            ok = False
        if not ok:
            bad.append((loc, f"padding waste happened (padded_slots="
                             f"{padded} > live_requests={live}, waste="
                             f"{truth:.4f}) but pad_waste_fraction "
                             f"records {got!r}"))


def _check_ragged(loc, obj, bad):
    """The ISSUE 7 ragged-serving gate on one ``"ragged": true`` dict."""
    tel = obj.get("telemetry")
    waste = (tel or {}).get("pad_waste_fraction") \
        if isinstance(tel, dict) else None
    try:
        waste_ok = float(waste) <= _RAGGED_PAD_WASTE_MAX
    except (TypeError, ValueError):
        waste_ok = False
    if not waste_ok:
        bad.append((loc, f"ragged artifact records pad_waste_fraction "
                         f"{waste!r} (must be <= {_RAGGED_PAD_WASTE_MAX} "
                         f"— the pow2 padding tax crept back)"))
    entries = obj.get("compile_cache_entries")
    modes = obj.get("modes_exercised")
    if entries is None or modes is None:
        bad.append((loc, "ragged artifact must record both "
                         "'compile_cache_entries' and 'modes_exercised'"))
        return
    try:
        cache_ok = int(entries) <= int(modes)
    except (TypeError, ValueError):
        cache_ok = False
    if not cache_ok:
        bad.append((loc, f"compile_cache_entries == {entries!r} > "
                         f"modes_exercised {modes!r} (a per-k kernel "
                         f"specialization snuck back in)"))


def _check_online_ivf(loc, obj, bad):
    """The ISSUE 12 online-IVF gate on one ``"ivf_online": true`` dict."""
    if "dispatches_per_conversation" not in obj:
        bad.append((loc, "online-ivf artifact must record a measured "
                         "'dispatches_per_conversation'"))
    if "recall_at_10" not in obj or "recall_floor" not in obj:
        bad.append((loc, "online-ivf artifact must record a recall_at_10/"
                         "recall_floor pair vs the offline rebuild"))
    if "ingest_overhead_fraction" not in obj:
        bad.append((loc, "online-ivf artifact must record "
                         "'ingest_overhead_fraction' (in-dispatch "
                         "maintenance cost vs maintenance-free ingest)"))
    stale = obj.get("assignment_staleness_fraction")
    ceiling = obj.get("assignment_staleness_max", 0.02)
    try:
        ok = float(stale) <= float(ceiling)
    except (TypeError, ValueError):
        ok = False
    if not ok:
        bad.append((loc, f"assignment_staleness_fraction == {stale!r} "
                         f"(must record a measured value <= {ceiling!r} — "
                         f"mini-batch centroid drift is stranding "
                         f"members)"))


def _check_pq_fused(loc, obj, bad):
    """The ISSUE 16 fused-PQ gate on one ``"pq_fused": true`` dict."""
    if "dispatches_per_turn" not in obj:
        bad.append((loc, "fused-pq artifact must record a measured "
                         "'dispatches_per_turn'"))
    if "recall_at_10" not in obj or "recall_floor" not in obj:
        bad.append((loc, "fused-pq artifact must record a recall_at_10/"
                         "recall_floor pair vs the classic ivf_pq_search "
                         "path"))
    bpr = obj.get("bytes_per_row")
    try:
        bpr_ok = float(bpr) > 0
    except (TypeError, ValueError):
        bpr_ok = False
    if not bpr_ok:
        bad.append((loc, f"fused-pq artifact records bytes_per_row == "
                         f"{bpr!r} (must be a measured positive number — "
                         f"the resident-footprint headline)"))
    int8_bpr = obj.get("int8_bytes_per_row")
    if bpr_ok and int8_bpr is not None:
        try:
            smaller = float(bpr) < float(int8_bpr)
        except (TypeError, ValueError):
            smaller = False
        if not smaller:
            bad.append((loc, f"fused-pq bytes_per_row {bpr!r} is not "
                             f"below the int8 shadow's {int8_bpr!r} — "
                             f"the PQ footprint advantage regressed"))


def _check_paged(loc, obj, bad):
    """The ISSUE 17 paged-arena gate on one ``"paged": true`` dict."""
    if "dispatches_per_turn" not in obj:
        bad.append((loc, "paged-arena artifact must record a measured "
                         "'dispatches_per_turn' (page maintenance must "
                         "ride inside the fused program)"))
    ratio = obj.get("paged_qps_ratio")
    floor = obj.get("paged_qps_floor")
    if ratio is None or floor is None:
        bad.append((loc, "paged-arena artifact must record both "
                         "'paged_qps_ratio' and 'paged_qps_floor'"))
    else:
        try:
            ok = float(ratio) >= float(floor)
        except (TypeError, ValueError):
            ok = False
        if not ok:
            bad.append((loc, f"paged_qps_ratio == {ratio!r} < "
                             f"paged_qps_floor {floor!r} (the row_map "
                             f"gather cost regressed past the floor)"))
    mism = obj.get("mirror_mismatches")
    if mism != 0:
        bad.append((loc, f"mirror_mismatches == {mism!r} (must record a "
                         f"measured 0 — the host free-list mirror drifted "
                         f"from the device page table)"))


def _check_replica(loc, obj, bad):
    """The ISSUE 18 replica-serving gate on one ``"replica": true``
    dict."""
    if "dispatches_per_turn" not in obj:
        bad.append((loc, "replica artifact must record a measured "
                         "'dispatches_per_turn' (one group-local dispatch "
                         "per routed turn, fleet-wide)"))
    if "recall_at_10" not in obj or "recall_floor" not in obj:
        bad.append((loc, "replica artifact must record a recall_at_10/"
                         "recall_floor pair"))
    for i, grp in enumerate(obj.get("per_group") or []):
        measured = grp.get("measured_dispatches_per_turn")
        if measured != 1.0:
            bad.append((f"{loc}.per_group[{i}]",
                        f"measured_dispatches_per_turn == {measured!r} "
                        f"(every group count must serve a routed turn in "
                        f"exactly ONE group-local dispatch)"))
    scaling = obj.get("qps_scaling")
    floor = obj.get("qps_scaling_floor")
    if scaling is None or floor is None:
        bad.append((loc, "replica artifact must record both 'qps_scaling' "
                         "and 'qps_scaling_floor'"))
    else:
        try:
            ok = float(scaling) >= float(floor)
        except (TypeError, ValueError):
            ok = False
        if not ok:
            bad.append((loc, f"qps_scaling == {scaling!r} < "
                             f"qps_scaling_floor {floor!r} (adding replica "
                             f"groups stopped buying aggregate QPS)"))
    stale = obj.get("replica_staleness_s")
    bound = obj.get("staleness_bound_s", 5.0)
    try:
        stale_ok = float(stale) <= float(bound)
    except (TypeError, ValueError):
        stale_ok = False
    if not stale_ok:
        bad.append((loc, f"replica_staleness_s == {stale!r} (must record "
                         f"a measured value <= {bound!r} — the journal "
                         f"fan-out's bounded-staleness window broke)"))
    crash = obj.get("crash_replay")
    if not isinstance(crash, dict):
        bad.append((loc, "replica artifact must record a 'crash_replay' "
                         "cell (injected mid-replay crash + journal "
                         "catch-up)"))
    else:
        for key in ("lost_facts", "doubled_facts"):
            if crash.get(key) != 0:
                bad.append((loc, f"crash_replay.{key} == "
                                 f"{crash.get(key)!r} (must record a "
                                 f"measured 0 — journal-subscriber "
                                 f"recovery diverged)"))


def _check_lifecycle(loc, obj, bad):
    """The ISSUE 19 lifecycle-sweep gate on one ``"lifecycle": true``
    dict."""
    if "dispatches_per_sweep" not in obj:
        bad.append((loc, "lifecycle artifact must record a measured "
                         "'dispatches_per_sweep' (decay + prune + archive "
                         "verdicts for ALL tenants in ONE dispatch)"))
    if obj.get("bit_parity") is not True:
        bad.append((loc, f"bit_parity == {obj.get('bit_parity')!r} (the "
                         f"fused sweep must record a measured true — "
                         f"bit-identical to the classic decay/prune/evict "
                         f"host loop)"))
    ratio = obj.get("serve_p99_ratio")
    bound = obj.get("serve_p99_bound")
    if ratio is None or bound is None:
        bad.append((loc, "lifecycle artifact must record both "
                         "'serve_p99_ratio' and 'serve_p99_bound' "
                         "(serving tail under concurrent maintenance)"))
    else:
        try:
            ok = float(ratio) <= float(bound)
        except (TypeError, ValueError):
            ok = False
        if not ok:
            bad.append((loc, f"serve_p99_ratio == {ratio!r} > "
                             f"serve_p99_bound {bound!r} (maintenance "
                             f"sweeps are blowing the live serving tail)"))
    speedup = obj.get("host_stall_speedup")
    floor = obj.get("host_stall_floor")
    if speedup is None or floor is None:
        bad.append((loc, "lifecycle artifact must record both "
                         "'host_stall_speedup' and 'host_stall_floor'"))
    else:
        try:
            ok = float(speedup) >= float(floor)
        except (TypeError, ValueError):
            ok = False
        if not ok:
            bad.append((loc, f"host_stall_speedup == {speedup!r} < "
                             f"host_stall_floor {floor!r} (the one-"
                             f"dispatch sweep lost its edge over the "
                             f"per-tenant host loop)"))


def _check_semantic(loc, obj, bad):
    """The ISSUE 20 semantic-cache gate on one ``"semantic_cache": true``
    dict."""
    if "dispatches_per_turn" not in obj:
        bad.append((loc, "semantic-cache artifact must record a measured "
                         "'dispatches_per_turn' (probe + early-out + "
                         "writeback ride INSIDE the one fused dispatch)"))
    rate = obj.get("semantic_hit_rate")
    floor = obj.get("hit_rate_floor")
    if rate is None or floor is None:
        bad.append((loc, "semantic-cache artifact must record both "
                         "'semantic_hit_rate' and 'hit_rate_floor'"))
    else:
        try:
            ok = float(rate) >= float(floor)
        except (TypeError, ValueError):
            ok = False
        if not ok:
            bad.append((loc, f"semantic_hit_rate == {rate!r} < "
                             f"hit_rate_floor {floor!r} (the Zipf "
                             f"repeated-intent workload stopped hitting)"))
    stale = obj.get("stale_hits")
    if stale != 0:
        bad.append((loc, f"stale_hits == {stale!r} (must record a "
                         f"measured 0 — a cached window outlived the "
                         f"ingest/delete churn that invalidated it)"))
    if obj.get("miss_parity") is not True:
        bad.append((loc, f"miss_parity == {obj.get('miss_parity')!r} "
                         f"(a cold probe must record a measured true — "
                         f"bit-identical ids AND scores vs the cache-off "
                         f"twin on a never-seen population)"))
    if "recall_at_10" not in obj or "recall_floor" not in obj:
        bad.append((loc, "semantic-cache artifact must record a "
                         "recall_at_10/recall_floor pair (a hit-served "
                         "window must BE the exact answer)"))
    speedup = obj.get("semantic_vs_off_speedup")
    sfloor = obj.get("speedup_floor")
    if speedup is None or sfloor is None:
        bad.append((loc, "semantic-cache artifact must record both "
                         "'semantic_vs_off_speedup' and 'speedup_floor'"))
    else:
        try:
            ok = float(speedup) >= float(sfloor)
        except (TypeError, ValueError):
            ok = False
        if not ok:
            bad.append((loc, f"semantic_vs_off_speedup == {speedup!r} < "
                             f"speedup_floor {sfloor!r} (hits stopped "
                             f"buying back their scan blocks)"))


def _check_ingest(loc, obj, bad):
    """The ISSUE 9 sharded-ingest gate on one ``"ingest_sharded": true``
    dict."""
    if "dispatches_per_conversation" not in obj:
        bad.append((loc, "sharded-ingest artifact must record a measured "
                         "'dispatches_per_conversation'"))
    scaling = obj.get("write_scaling")
    floor = obj.get("write_scaling_floor")
    if scaling is None or floor is None:
        bad.append((loc, "sharded-ingest artifact must record both "
                         "'write_scaling' and 'write_scaling_floor'"))
        return
    try:
        ok = float(scaling) >= float(floor)
    except (TypeError, ValueError):
        ok = False
    if not ok:
        bad.append((loc, f"write_scaling == {scaling!r} < "
                         f"write_scaling_floor {floor!r} (the pod write "
                         f"path regressed below the single-chip fused "
                         f"path)"))


def _check_tiered(loc, obj, bad):
    """The ISSUE 8 tiered-memory gate on one ``"tiered": true`` dict."""
    for key in ("cold_hit_rate", "hot_fraction"):
        if key not in obj:
            bad.append((loc, f"tiered artifact must record '{key}'"))
    if "recall_at_10" not in obj or "recall_floor" not in obj:
        bad.append((loc, "tiered artifact must record a recall_at_10/"
                         "recall_floor pair"))
    if "dispatches_per_turn" not in obj:
        bad.append((loc, "tiered artifact must record the hot-only "
                         "probe's measured dispatches_per_turn"))
    cold_d = obj.get("cold_hit_dispatches_per_turn")
    try:
        ok = float(cold_d) <= 2.0
    except (TypeError, ValueError):
        ok = False
    if not ok:
        bad.append((loc, f"cold_hit_dispatches_per_turn == {cold_d!r} "
                         f"(must record a measured value <= 2 — coarse "
                         f"scan + ONE bounded finish)"))


def main(argv):
    if argv:
        paths = argv
    else:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir, "bench_artifacts")
        paths = sorted(glob.glob(os.path.join(root, "*.json")))
    checked = 0
    checked_recall = 0
    checked_speedup = 0
    checked_mesh = 0
    checked_telemetry = 0
    checked_ragged = 0
    checked_tiered = 0
    checked_ingest = 0
    checked_online_ivf = 0
    checked_pq = 0
    checked_paged = 0
    checked_replica = 0
    checked_lifecycle = 0
    checked_semantic = 0
    bad = []
    for p in paths:
        try:
            with open(p) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            print(f"[check] skipping unreadable {p}: {e}", file=sys.stderr)
            continue
        (hits, recalls, speedups, meshes, tel_blocks, raggeds, tiereds,
         ingests, online_ivfs, pq_fuseds, pageds, replicas, lifecycles,
         semantics) = (
            [], [], [], [], [], [], [], [], [], [], [], [], [], [])
        _walk(data, os.path.basename(p), hits, recalls, speedups, meshes,
              tel_blocks, raggeds, tiereds, ingests, online_ivfs,
              pq_fuseds, pageds, replicas, lifecycles, semantics)
        grandfathered = os.path.basename(p).startswith(
            _PRE_TELEMETRY_PREFIXES)
        for loc, measured_fused, block in tel_blocks:
            checked_telemetry += 1
            _check_telemetry(loc, measured_fused, block, grandfathered, bad)
        if not os.path.basename(p).startswith(_PRE_RAGGED_PREFIXES):
            for loc, obj in raggeds:
                checked_ragged += 1
                _check_ragged(loc, obj, bad)
        for loc, obj in tiereds:
            checked_tiered += 1
            _check_tiered(loc, obj, bad)
        for loc, obj in ingests:
            checked_ingest += 1
            _check_ingest(loc, obj, bad)
        for loc, obj in online_ivfs:
            checked_online_ivf += 1
            _check_online_ivf(loc, obj, bad)
        for loc, obj in pq_fuseds:
            checked_pq += 1
            _check_pq_fused(loc, obj, bad)
        for loc, obj in pageds:
            checked_paged += 1
            _check_paged(loc, obj, bad)
        for loc, obj in replicas:
            checked_replica += 1
            _check_replica(loc, obj, bad)
        for loc, obj in lifecycles:
            checked_lifecycle += 1
            _check_lifecycle(loc, obj, bad)
        for loc, obj in semantics:
            checked_semantic += 1
            _check_semantic(loc, obj, bad)
        for loc, v, planned in hits:
            checked += 1
            if v == 1:
                continue
            try:
                planned_ok = planned is not None \
                    and float(v) == float(planned) >= 1
            except (TypeError, ValueError):
                planned_ok = False
            if planned_ok:
                # a PLANNED multi-dispatch turn (the HBM planner split
                # it, recorded it, and the artifact says so) — accepted;
                # an unplanned or unrecorded split still fails below
                continue
            bad.append((loc, f"{loc.rsplit('.', 1)[-1]} == {v!r} "
                             f"(expected 1, or a matching planned_"
                             f"{loc.rsplit('.', 1)[-1]})"))
        for loc, got, floor in recalls:
            checked_recall += 1
            try:
                ok = float(got) >= float(floor)
            except (TypeError, ValueError):
                ok = False
            if not ok:
                bad.append((loc, f"recall_at_10 == {got!r} "
                                 f"< recall_floor {floor!r}"))
        for loc, got, floor in speedups:
            checked_speedup += 1
            try:
                ok = float(got) >= float(floor)
            except (TypeError, ValueError):
                ok = False
            if not ok:
                bad.append((loc, f"fused_vs_classic_speedup == {got!r} "
                                 f"< speedup_floor {floor!r}"))
        for loc, has_count in meshes:
            checked_mesh += 1
            if not has_count:
                bad.append((loc, "sharded artifact (has a 'mesh' dict) "
                                 "records no measured dispatches_per_turn"))
    for loc, msg in bad:
        print(f"REGRESSION: {loc}: {msg}")
    print(f"[check] {checked} dispatch-count value(s), "
          f"{checked_recall} recall pair(s), {checked_speedup} speedup "
          f"pair(s), {checked_mesh} sharded artifact(s), "
          f"{checked_telemetry} telemetry block(s), "
          f"{checked_ragged} ragged gate(s), "
          f"{checked_tiered} tiered gate(s), "
          f"{checked_ingest} sharded-ingest gate(s), "
          f"{checked_online_ivf} online-ivf gate(s), "
          f"{checked_pq} fused-pq gate(s), "
          f"{checked_paged} paged-arena gate(s), "
          f"{checked_replica} replica gate(s), "
          f"{checked_lifecycle} lifecycle gate(s), and "
          f"{checked_semantic} semantic-cache gate(s) across "
          f"{len(paths)} artifact(s); {len(bad)} regression(s)")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
