#!/usr/bin/env python3
"""Guard: fail when a bench artifact records a fused-serving regression.

The fused serving acceptance bar (ISSUE 2/3/4) is ONE device dispatch per
coalesced retrieval batch, and for the approximate coarse stages (int8,
IVF) a recall floor the artifact itself records. Bench stages that measure
a fused path record a MEASURED ``dispatches_per_turn`` in their JSON
artifacts (bench.py ``bench_fused_quant`` / ``bench_fused_ivf`` wrap the
jit entry points and count), and recall-bearing stages record
``recall_at_10`` next to their ``recall_floor``. This script walks every
``bench_artifacts/*.json`` (or the paths passed as arguments) and exits
nonzero if:

  - any ``dispatches_per_turn`` != 1 (a refactor quietly split the fused
    program back into multiple dispatches), or
  - any dict carrying both keys has ``recall_at_10`` < ``recall_floor``
    (a coarse-stage change quietly traded recall for throughput),

so either regression turns red in CI instead of shipping.

Usage:
    python scripts/check_dispatch_counts.py [artifact.json ...]
"""

from __future__ import annotations

import glob
import json
import os
import sys


def _walk(obj, path, hits, recalls):
    if isinstance(obj, dict):
        if "recall_at_10" in obj and "recall_floor" in obj:
            recalls.append((path, obj["recall_at_10"], obj["recall_floor"]))
        for k, v in obj.items():
            here = f"{path}.{k}"
            if k == "dispatches_per_turn":
                hits.append((here, v))
            else:
                _walk(v, here, hits, recalls)
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            _walk(v, f"{path}[{i}]", hits, recalls)


def main(argv):
    if argv:
        paths = argv
    else:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir, "bench_artifacts")
        paths = sorted(glob.glob(os.path.join(root, "*.json")))
    checked = 0
    checked_recall = 0
    bad = []
    for p in paths:
        try:
            with open(p) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            print(f"[check] skipping unreadable {p}: {e}", file=sys.stderr)
            continue
        hits = []
        recalls = []
        _walk(data, os.path.basename(p), hits, recalls)
        for loc, v in hits:
            checked += 1
            if v != 1:
                bad.append((loc, f"dispatches_per_turn == {v!r} "
                                 f"(expected 1)"))
        for loc, got, floor in recalls:
            checked_recall += 1
            try:
                ok = float(got) >= float(floor)
            except (TypeError, ValueError):
                ok = False
            if not ok:
                bad.append((loc, f"recall_at_10 == {got!r} "
                                 f"< recall_floor {floor!r}"))
    for loc, msg in bad:
        print(f"REGRESSION: {loc}: {msg}")
    print(f"[check] {checked} dispatches_per_turn value(s) and "
          f"{checked_recall} recall pair(s) across {len(paths)} "
          f"artifact(s); {len(bad)} regression(s)")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
