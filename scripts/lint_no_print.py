#!/usr/bin/env python3
"""Lint: no bare ``print(`` in the lazzaro_tpu serving modules.

ISSUE 6 satellite: the serving stack reports through the Telemetry
registry and the ``lazzaro_tpu`` logging hierarchy — a stray ``print`` in
a library hot path can't be silenced, redirected, or scraped, so it fails
CI here. User-facing entry points (``cli/``, ``dashboard`` startup,
``backend_probe``'s subprocess protocol, examples, bench) are exempt:
stdout IS their interface.

A line may opt out with a trailing ``# noqa: print`` (e.g. a __main__
debugging harness), which keeps the lint grep-simple and the exemptions
visible in review.

Usage:
    python scripts/lint_no_print.py          # lint the default scope
    python scripts/lint_no_print.py a.py ... # lint specific files
"""

from __future__ import annotations

import glob
import os
import re
import sys

# Serving-path scope: every module a request or an ingest batch flows
# through. cli/, dashboard/, models/, integrations/ and scripts stay out.
SCOPE = (
    "lazzaro_tpu/core/*.py",
    "lazzaro_tpu/serve/*.py",
    "lazzaro_tpu/parallel/*.py",
    "lazzaro_tpu/ops/*.py",
    "lazzaro_tpu/tier/*.py",
    "lazzaro_tpu/models/*.py",
    "lazzaro_tpu/utils/batching.py",
    "lazzaro_tpu/utils/telemetry.py",
    "lazzaro_tpu/utils/compat.py",
)

# A call statement, not the word: start-of-expression ``print(``.
_PRINT = re.compile(r"(?<![\w.])print\(")
_EXEMPT = "# noqa: print"


def lint(paths):
    bad = []
    for path in paths:
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.readlines()
        except OSError as e:
            print(f"[lint] unreadable {path}: {e}", file=sys.stderr)
            continue
        for no, line in enumerate(lines, 1):
            code = line.split("#", 1)[0]
            if _PRINT.search(code) and _EXEMPT not in line:
                bad.append((path, no, line.rstrip()))
    return bad


def main(argv):
    if argv:
        paths = argv
    else:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir)
        paths = []
        for pattern in SCOPE:
            paths.extend(sorted(glob.glob(os.path.join(root, pattern))))
    bad = lint(paths)
    for path, no, line in bad:
        print(f"PRINT-IN-SERVING-MODULE: {path}:{no}: {line}")
    print(f"[lint] {len(paths)} file(s) checked; {len(bad)} violation(s)")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
