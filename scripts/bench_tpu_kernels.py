"""Standalone TPU kernel + IVF capture at 1M rows — no graph needed.

The r4 post-mortem: a wedged tunnel at bench start voided the whole
round's TPU evidence. This script is the smallest unit of capture — a
synthetic (clustered, bench-geometry) 1M-row arena and the raw serving
kernels over it:

  exact XLA / exact Pallas / int8 single-query p50, batch-64 amortized,
  scatter throughput      (bench.bench_kernels — shared code path)
  IVF build time + p50 + recall@5 vs the exact oracle at several nprobe
  settings                (ops/ivf.py — the claims in its docstring)

It needs only ~2-5 min of healthy tunnel, so the watcher runs it FIRST
whenever the backend comes back. Prints ONE JSON line (same contract as
bench.py). Timed regions end in a forced device->host readback; the
roofline self-check flags physically impossible numbers.

Env: BENCH_N / BENCH_DIM as bench.py; KERNELS_SKIP_IVF=1 for speed.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

os.environ.setdefault("BENCH_N", "1000000")
import bench  # noqa: E402  (runs the subprocess backend-health gate)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from lazzaro_tpu.core import state as S  # noqa: E402


def clustered_arena(n_rows: int, dim: int, group: int = 4,
                    n_topics: int = 12, seed: int = 0) -> jax.Array:
    """Vectorized bench-geometry corpus (0.5 topic + 0.794 group + 0.346
    noise, unit rows) — same cluster statistics as the graph bench's
    ``_fact_vec``, generated in bulk. Built on host in chunks, shipped to
    the device as ONE bf16 matrix."""
    rng = np.random.default_rng(seed)
    n_groups = max(1, n_rows // group)
    topics = rng.standard_normal((n_topics, dim)).astype(np.float32)
    topics /= np.linalg.norm(topics, axis=1, keepdims=True)
    out = np.empty((n_rows, dim), np.float32)
    chunk = 131072
    for lo in range(0, n_rows, chunk):
        hi = min(n_rows, lo + chunk)
        idx = np.arange(lo, hi)
        g = idx % n_groups
        g_rng = np.random.default_rng(seed + 2 + lo)   # fresh noise per chunk
        # group dirs must be reproducible per group id without holding a
        # [n_groups, dim] matrix: derive each chunk's group dirs from a
        # per-group Philox stream
        gd = np.empty((hi - lo, dim), np.float32)
        uniq, inv = np.unique(g, return_inverse=True)
        dirs = np.empty((len(uniq), dim), np.float32)
        for j, gid in enumerate(uniq.tolist()):
            r = np.random.default_rng(1_000_000_000 + gid)
            v = r.standard_normal(dim).astype(np.float32)
            dirs[j] = v / np.linalg.norm(v)
        gd[:] = dirs[inv]
        noise = g_rng.standard_normal((hi - lo, dim)).astype(np.float32)
        noise /= np.linalg.norm(noise, axis=1, keepdims=True)
        v = (bench.TOPIC_W * topics[g % n_topics]
             + bench.GROUP_W * gd + bench.NOISE_W * noise)
        out[lo:hi] = v / np.linalg.norm(v, axis=1, keepdims=True)
    return jnp.asarray(out, jnp.bfloat16)


def main():
    t_start = time.perf_counter()
    dev = jax.devices()[0]
    on_tpu = jax.default_backend() in ("tpu", "axon")
    n = bench.N
    dim = bench.DIM

    t0 = time.perf_counter()
    p50s, batch64_ms, int8_batch64_ms, kernel_rows, scatter = \
        bench.bench_kernels(on_tpu)
    t_kernels = time.perf_counter() - t0

    ivf = None
    if os.environ.get("KERNELS_SKIP_IVF") != "1":
        from lazzaro_tpu.ops.ivf import build_ivf, ivf_search

        t0 = time.perf_counter()
        emb = clustered_arena(n, dim)
        mask = np.ones((n,), bool)
        t_corpus = time.perf_counter() - t0

        t0 = time.perf_counter()
        index = build_ivf(emb, mask)
        jax.block_until_ready(index.centroids)
        np.asarray(index.centroids[:1])          # forced readback
        build_s = time.perf_counter() - t0

        # exact oracle top-5 for 64 held-out-style queries (existing rows —
        # self-hit excluded by looking at ranks 1..5 is unnecessary: IVF
        # must reproduce the oracle INCLUDING the self hit)
        rng = np.random.default_rng(7)
        qrows = rng.integers(0, n, size=64)
        queries = np.asarray(emb[qrows].astype(jnp.float32))
        mask_dev = jnp.asarray(mask)

        def exact_topk(q, k=5):
            scores = jnp.dot(emb.astype(jnp.float32), jnp.asarray(q).T,
                             preferred_element_type=jnp.float32)  # [n, Q]
            _, rows = jax.lax.top_k(scores.T, k)
            return np.asarray(rows)

        oracle = exact_topk(queries)
        ivf = {"build_s": round(build_s, 2),
               "corpus_gen_s": round(t_corpus, 1),
               "n_clusters": int(index.n_clusters),
               "by_nprobe": {}}
        for nprobe in (4, 8, 16):
            sc, rows = ivf_search(index.centroids, index.members,
                                  index.residual, emb, mask_dev,
                                  jnp.asarray(queries), 5, nprobe=nprobe)
            got = np.asarray(rows)
            recall = float(np.mean([
                len(set(got[i]) & set(oracle[i])) / 5.0
                for i in range(len(qrows))]))
            # p50 latency: single-query dispatches, forced readback
            lat = []
            for i in range(12):
                t0 = time.perf_counter()
                _, r = ivf_search(index.centroids, index.members,
                                  index.residual, emb, mask_dev,
                                  jnp.asarray(queries[i:i + 1]), 5,
                                  nprobe=nprobe)
                np.asarray(r)
                lat.append((time.perf_counter() - t0) * 1e3)
            ivf["by_nprobe"][str(nprobe)] = {
                "recall_at_5": round(recall, 4),
                "p50_ms": round(float(np.percentile(lat[2:], 50)), 3)}

        # IVF-PQ: same coarse build, m-byte member scan + exact refine
        from lazzaro_tpu.ops.pq import encode_pq, ivf_pq_search, train_pq

        t0 = time.perf_counter()
        book = train_pq(emb, mask)
        codes = encode_pq(book.centroids, emb)
        np.asarray(codes[:1])                    # forced readback
        pq_build_s = time.perf_counter() - t0
        _, rows = ivf_pq_search(index.centroids, index.members,
                                index.residual, book.centroids, codes, emb,
                                mask_dev, jnp.asarray(queries), 5,
                                nprobe=8, r=128)
        got = np.asarray(rows)
        pq_recall = float(np.mean([
            len(set(got[i]) & set(oracle[i])) / 5.0
            for i in range(len(qrows))]))
        lat = []
        for i in range(12):
            t0 = time.perf_counter()
            _, r = ivf_pq_search(index.centroids, index.members,
                                 index.residual, book.centroids, codes, emb,
                                 mask_dev, jnp.asarray(queries[i:i + 1]), 5,
                                 nprobe=8, r=128)
            np.asarray(r)
            lat.append((time.perf_counter() - t0) * 1e3)
        ivf["pq"] = {"train_encode_s": round(pq_build_s, 2),
                     "bytes_per_row": int(book.m),
                     "recall_at_5": round(pq_recall, 4),
                     "p50_ms": round(float(np.percentile(lat[2:], 50)), 3),
                     "nprobe": 8, "shortlist_r": 128}

    rl = {
        "exact_xla": bench._roofline(kernel_rows, dim, 2, p50s["xla"], 1, on_tpu),
        "int8": bench._roofline(kernel_rows, dim, 1, p50s["int8"], 1, on_tpu),
        "batch64": bench._roofline(kernel_rows, dim, 2, batch64_ms, 64, on_tpu),
    }
    if "pallas" in p50s:
        rl["pallas"] = bench._roofline(kernel_rows, dim, 2, p50s["pallas"], 1,
                                       on_tpu)
    out = {
        "metric": f"arena_kernels_{n // 1000}k_rows",
        "value": round(p50s["xla"], 4),
        "unit": "ms",
        "vs_baseline": round(100.0 / p50s["xla"], 2),
        "roofline_suspect": any(v.get("suspect") for v in rl.values()),
        "extra": {
            "arena_search_xla_p50_ms": round(p50s["xla"], 4),
            "arena_search_pallas_p50_ms": (round(p50s["pallas"], 4)
                                           if "pallas" in p50s else None),
            "arena_search_int8_p50_ms": round(p50s["int8"], 4),
            "arena_search_batch64_ms": round(batch64_ms, 4),
            "arena_search_int8_batch64_ms": round(int8_batch64_ms, 4),
            "arena_scatter_rows_per_sec": round(scatter, 1),
            "ivf": ivf,
            "roofline": rl,
            "kernel_rows": kernel_rows,
            "dim": dim,
            "phase_s": {"kernels": round(t_kernels, 1),
                        "total_wall": round(time.perf_counter() - t_start, 1)},
            "device": str(dev),
        },
    }
    if bench._degraded_error:
        out["error"] = bench._degraded_error
    print(json.dumps(out))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        import traceback
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"metric": "arena_kernels", "value": None,
                          "unit": "ms",
                          "error": f"{type(e).__name__}: {e}"[:500]}))
        sys.exit(0)
