#!/usr/bin/env python3
"""Guard: fail when a reliability bench artifact is incomplete or red.

The fault-recovery acceptance bar (ISSUE 10) is a CI'd recovery matrix:
every named injection point — failed donated dispatch, worker-thread
death, pump crash mid-chunk, torn checkpoint write, cold-store read
error — must end in state parity with an uninjected run, and the
artifact must record the recovery counters that prove the layer was
actually exercised (a reliability stage that silently stops measuring
retries/sheds must fail loudly, not pass vacuously). This script walks
every ``bench_artifacts/*.json`` (or the paths passed as arguments) and
exits nonzero when an artifact flagged ``"reliability": true``

  - has NO ``fault_matrix`` (in the flagged dict or any of its
    sub-dicts), or an EMPTY one,
  - has any matrix cell with ``recovered`` != true or ``parity`` !=
    true — an injected fault that does not recover to parity is exactly
    the regression this layer exists to prevent,
  - omits the recovery counters block or any required counter
    (``dispatch_retries``, ``load_shed``, ``watchdog_timeouts``,
    ``worker_restarts``, ``journal_replayed``),
  - omits the measured ``recovery_latency_ms_p95`` or ``shed_rate``
    (the two headline numbers the stage exists to record), or
  - records a ``shed`` block whose ``hung_futures`` != 0 — a future
    that resolves with neither a result nor a typed error is the one
    outcome the failure model forbids, or
  - (ISSUE 11) carries any ``plan.oom:*`` replan-recovery cell (an
    injected ``RESOURCE_EXHAUSTED`` recovered by the planner splitting
    the dispatch through the copy twins) but omits the ``oom_replans``
    counter that proves the replan machinery — not a silent retry —
    did the recovering.

Usage:
    python scripts/check_fault_matrix.py [artifact.json ...]
"""

from __future__ import annotations

import glob
import json
import os
import sys

_REQUIRED_COUNTERS = ("dispatch_retries", "load_shed",
                      "watchdog_timeouts", "worker_restarts",
                      "journal_replayed")
_REQUIRED_HEADLINES = ("recovery_latency_ms_p95", "shed_rate")


def _find(obj, key):
    """First value of ``key`` found in ``obj`` or any descendant dict."""
    if isinstance(obj, dict):
        if key in obj:
            return obj[key]
        for v in obj.values():
            hit = _find(v, key)
            if hit is not None:
                return hit
    elif isinstance(obj, list):
        for v in obj:
            hit = _find(v, key)
            if hit is not None:
                return hit
    return None


def _reliability_roots(obj, path, roots):
    """Top-most dicts flagged ``"reliability": true`` (nested re-flags
    inside a found root are part of that root's payload)."""
    if isinstance(obj, dict):
        if obj.get("reliability") is True:
            roots.append((path, obj))
            return
        for k, v in obj.items():
            _reliability_roots(v, f"{path}.{k}", roots)
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            _reliability_roots(v, f"{path}[{i}]", roots)


def check_artifact(path: str, bad: list) -> int:
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        bad.append((path, f"unreadable artifact: {e}"))
        return 0
    roots: list = []
    _reliability_roots(data, os.path.basename(path), roots)
    for loc, root in roots:
        matrix = _find(root, "fault_matrix")
        if not isinstance(matrix, dict) or not matrix:
            bad.append((loc, "reliability artifact has no (non-empty) "
                             "'fault_matrix'"))
        else:
            for cell, verdict in sorted(matrix.items()):
                if not isinstance(verdict, dict):
                    bad.append((loc, f"matrix cell '{cell}' is not a dict"))
                    continue
                if verdict.get("recovered") is not True:
                    bad.append((loc, f"matrix cell '{cell}' is UNRECOVERED"))
                if "parity" in verdict and verdict["parity"] is not True:
                    bad.append((loc, f"matrix cell '{cell}' recovered "
                                     f"WITHOUT state parity"))
        counters = _find(root, "counters")
        if not isinstance(counters, dict):
            bad.append((loc, "reliability artifact omits its recovery "
                             "'counters' block"))
        else:
            for key in _REQUIRED_COUNTERS:
                if key not in counters:
                    bad.append((loc, f"recovery counters omit '{key}'"))
            has_replan_cells = isinstance(matrix, dict) and any(
                str(cell).startswith("plan.oom") for cell in matrix)
            if has_replan_cells and "oom_replans" not in counters:
                bad.append((loc, "matrix has plan.oom replan cells but "
                                 "counters omit 'oom_replans'"))
        for key in _REQUIRED_HEADLINES:
            if _find(root, key) is None:
                bad.append((loc, f"reliability artifact omits '{key}'"))
        shed = _find(root, "shed")
        if isinstance(shed, dict) and shed.get("hung_futures") not in (0,):
            bad.append((loc, f"shed block records hung_futures="
                             f"{shed.get('hung_futures')} (must be 0)"))
    return len(roots)


def main(argv) -> int:
    paths = argv[1:]
    if not paths:
        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(here, "bench_artifacts",
                                              "*.json")))
    if not paths:
        print("check_fault_matrix: no artifacts found", file=sys.stderr)
        return 0
    bad: list = []
    n_rel = 0
    for p in paths:
        n_rel += check_artifact(p, bad)
    if bad:
        print("check_fault_matrix: FAIL", file=sys.stderr)
        for loc, msg in bad:
            print(f"  {loc}: {msg}", file=sys.stderr)
        return 1
    print(f"check_fault_matrix: OK ({len(paths)} artifact(s), "
          f"{n_rel} reliability block(s) checked)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
