"""Device JSON automaton (models/json_device.py) vs the host oracle.

Random legal walks: at every step the host automaton enumerates the legal
byte set; we assert the device mask matches it EXACTLY, pick a random legal
byte, feed both, and repeat. Any divergence in masks or done-ness fails —
this is the exactness contract that lets generate_json run its whole loop
on device."""

# Compile-heavy (multi-second XLA compiles / 100k-row arenas): the
# default lane must stay inside a driver window; run the full lane
# with no -m filter for round gates.
pytestmark = __import__("pytest").mark.slow

import json

import numpy as np
import jax.numpy as jnp
import pytest

from lazzaro_tpu.models import json_constrain as H
from lazzaro_tpu.models import json_device as D

EOS = 258
VOCAB = 259


def _device_mask(st):
    return np.asarray(D.allowed_mask(st, VOCAB, EOS))


def _host_mask(js):
    m = np.zeros((VOCAB,), bool)
    for b in js.allowed():
        m[b] = True
    if js.done:
        m[EOS] = True
    return m


@pytest.mark.parametrize("force_object", [False, True])
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_random_walk_masks_match(force_object, seed):
    rng = np.random.default_rng(seed)
    js = H.JsonState(force_object=force_object)
    ds = D.initial_state(force_object=force_object)
    doc = bytearray()
    for step in range(300):
        hm = _host_mask(js)
        dm = _device_mask(ds)
        if js.stack and len(js.stack) >= D.MAX_DEPTH:
            # device-only depth cap: open brackets masked off at the cap
            hm[ord("{")] = hm[ord("[")] = False
        assert (hm == dm).all(), (
            f"step {step} mode={js.mode} doc={bytes(doc)!r}: "
            f"host^device bytes {np.nonzero(hm != dm)[0]}")
        legal = np.nonzero(hm)[0]
        # bias away from whitespace/closers so documents grow structure
        weights = np.ones(len(legal))
        for i, b in enumerate(legal):
            if b < 256 and b in b" \t\n\r":
                weights[i] = 0.05
            elif b == EOS:
                weights[i] = 0.02
        b = int(rng.choice(legal, p=weights / weights.sum()))
        if b == EOS:
            break
        doc.append(b)
        js.feed(b)
        ds = D.feed(ds, jnp.int32(b))
        assert bool(js.done) == bool(np.asarray(D._is_done(ds))), (
            f"done divergence at step {step}, doc={bytes(doc)!r}")
    # whatever we have, the host repair must complete it to valid JSON
    tail = js.closing_suffix()
    json.loads((bytes(doc) + tail).decode("utf-8", errors="replace"))


def test_scaffold_state_translation():
    scaffold = b'{"memories": [{"content": "abc'
    js = H.JsonState(force_object=True)
    for b in scaffold:
        js.feed(b)
    ds = D.encode_host_state(js)
    assert (_host_mask(js) == _device_mask(ds)).all()
    # continue the walk from the translated state
    for b in b'", "type": "semantic"}]}':
        assert _device_mask(ds)[b], f"byte {bytes([b])!r} illegal on device"
        js.feed(b)
        ds = D.feed(ds, jnp.int32(b))
        assert (_host_mask(js) == _device_mask(ds)).all()
    assert bool(np.asarray(D._is_done(ds)))


def test_literal_states_translate():
    js = H.JsonState()
    for b in b"[tr":
        js.feed(b)
    ds = D.encode_host_state(js)
    assert (_host_mask(js) == _device_mask(ds)).all()


@pytest.mark.parametrize("scaffold", [None, '{"memories": [{"content": "'])
def test_device_loop_matches_host_loop_greedy(scaffold):
    from lazzaro_tpu.models.llm import LanguageModel, LMConfig

    lm = LanguageModel(LMConfig.tiny(), seed=0)
    kw = dict(max_new_tokens=48, scaffold=scaffold)
    host_doc = lm.generate_json("Extract facts.", device_loop=False, **kw)
    dev_doc = lm.generate_json("Extract facts.", device_loop=True, **kw)
    assert dev_doc == host_doc
    json.loads(dev_doc)
    if scaffold:
        assert dev_doc.startswith(scaffold)


def test_device_loop_sampled_is_valid_json():
    from lazzaro_tpu.models.llm import LanguageModel, LMConfig

    lm = LanguageModel(LMConfig.tiny(), seed=0)
    for seed in range(3):
        doc = lm.generate_json("Extract.", max_new_tokens=40,
                               temperature=0.9, seed=seed)
        json.loads(doc)


def test_device_loop_parity_free_value_and_top_level_numbers():
    # force_object=False drives the device loop through free top-level
    # values. Parity with the host loop must hold for every seed, and at
    # least one seed must exercise an extendable top-level number (the
    # '42' -> '4' truncation class the host loop once had).
    from lazzaro_tpu.models.llm import LanguageModel, LMConfig

    saw_number = False
    for seed in range(10):
        lm = LanguageModel(LMConfig.tiny(), seed=seed)
        host_doc = lm.generate_json("v:", max_new_tokens=24,
                                    force_object=False, device_loop=False)
        dev_doc = lm.generate_json("v:", max_new_tokens=24,
                                   force_object=False, device_loop=True)
        assert dev_doc == host_doc, f"seed {seed}"
        parsed = json.loads(dev_doc)
        if isinstance(parsed, (int, float)):
            saw_number = True
    assert saw_number, "no seed produced a top-level number; widen the sweep"
