"""Hybrid (multi-slice) mesh helper: single-slice fallback path on CPU."""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from lazzaro_tpu.parallel.mesh import make_hybrid_mesh


def test_single_slice_fallback_shape():
    mesh = make_hybrid_mesh(("data",), (8,))
    assert mesh.axis_names == ("slice", "data")
    assert mesh.shape["slice"] == 1 and mesh.shape["data"] == 8


def test_hybrid_mesh_drives_sharded_compute():
    mesh = make_hybrid_mesh(("data",), (8,))
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    xs = jax.device_put(x, NamedSharding(mesh, P(None, "data")))
    out = jax.jit(lambda a: (a * 2).sum())(xs)
    assert float(out) == x.sum() * 2


def test_hybrid_mesh_with_two_ici_axes():
    mesh = make_hybrid_mesh(("data", "model"), (4, 2))
    assert mesh.axis_names == ("slice", "data", "model")
    assert mesh.shape == {"slice": 1, "data": 4, "model": 2}


def test_explicit_num_slices_on_flat_topology():
    # CPU devices expose no slice topology; forcing num_slices>1 must fail
    # loudly, not build a bogus cross-"slice" mesh.
    with pytest.raises(ValueError, match="slices"):
        make_hybrid_mesh(("data",), (4,), num_slices=2)


def test_too_large_ici_request_fails_loudly():
    with pytest.raises(ValueError, match="devices"):
        make_hybrid_mesh(("data",), (512,))
