"""Batched multi-query retrieval: index kernel, sharded index, orchestrator."""

import jax
import numpy as np
import pytest

from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.core.memory_system import MemorySystem
from lazzaro_tpu.parallel.index import ShardedMemoryIndex
from lazzaro_tpu.parallel.mesh import make_mesh


def _filled_index(n=30, d=16, seed=0):
    idx = MemoryIndex(dim=d, capacity=64, edge_capacity=16)
    rng = np.random.RandomState(seed)
    emb = rng.randn(n, d).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    idx.add([f"n{i}" for i in range(n)], emb, [0.5] * n, [0.0] * n,
            ["semantic"] * n, ["work"] * n, "default")
    return idx, emb


def test_batch_matches_single_query():
    idx, emb = _filled_index()
    queries = emb[[3, 7, 11, 19]]
    batched = idx.search_batch(queries, "default", k=5)
    for q, (ids, scores) in zip(queries, batched):
        s_ids, s_scores = idx.search(q, "default", k=5)
        assert ids == s_ids
        np.testing.assert_allclose(scores, s_scores, rtol=1e-6)
        assert ids[0] in {f"n{i}" for i in [3, 7, 11, 19]}


def test_batch_edge_cases():
    idx, emb = _filled_index()
    assert idx.search_batch(np.zeros((0, 16)), "default") == []
    assert idx.search_batch(emb[:2], "ghost-tenant") == [([], [])] * 2
    # 1-D query promoted to a single-row batch
    out = idx.search_batch(emb[0], "default", k=3)
    assert len(out) == 1 and out[0][0][0] == "n0"
    # Non-power-of-two batch sizes hit the padding path
    out = idx.search_batch(emb[:5], "default", k=3)
    assert len(out) == 5 and all(ids for ids, _ in out)


def test_sharded_batch_matches_single():
    n_dev = min(8, len(jax.devices()))
    mesh = make_mesh(("data",), (n_dev,), devices=jax.devices()[:n_dev])
    idx = ShardedMemoryIndex(mesh, dim=16, capacity=64 * n_dev, k=5)
    rng = np.random.RandomState(1)
    emb = rng.randn(40, 16).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    idx.add([f"s{i}" for i in range(40)], emb, "default")

    batched = idx.search_batch(emb[[2, 9, 33]], "default")
    for qi, (ids, scores) in zip([2, 9, 33], batched):
        s_ids, s_scores = idx.search(emb[qi], "default")
        assert ids == s_ids
        assert ids[0] == f"s{qi}"
        np.testing.assert_allclose(scores, s_scores, rtol=1e-6)


def test_memory_system_batch(tmp_path):
    ms = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False)
    ms.start_conversation()
    ms.chat("I work as a data engineer on a big ETL project.")
    ms.chat("I love hiking in the mountains on weekends.")
    ms.chat("My cat is named Whiskers.")
    ms.end_conversation()

    # Hashing-embedder retrieval is token-overlap based: queries share
    # tokens with their target facts.
    queries = ["data engineer work?", "hiking mountains?", "cat Whiskers name?"]
    batched = ms.search_memories_batch(queries, limit=3)
    assert len(batched) == 3
    singles = [ms.search_memories(q, limit=3) for q in queries]
    for b, s in zip(batched, singles):
        assert [n.id for n in b] == [n.id for n in s]
    assert any("data engineer" in n.content for n in batched[0])
    assert any("Whiskers" in n.content for n in batched[2])
    assert ms.search_memories_batch([]) == []
    ms.close()
