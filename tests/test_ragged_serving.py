"""Ragged continuous serving (ISSUE 7; tier-1 smoke, CPU, tiny arenas).

Per-query k / cap_take / nprobe ride into the fused serving kernels as
int32 sidecar DATA instead of trace constants: the scan bodies compute to
the static per-mode ceiling (``serve_k_max``) and each query masks at its
own top-k boundary, so ONE compiled kernel per (mode × geometry) serves any
mix of request shapes. These tests pin:

- bit-exact parity of a mixed-k ragged batch against per-request
  non-ragged fused serving across exact / quant / IVF / sharded, on
  gate-hit, gate-miss, and multi-tenant fixtures (including boost
  numerics on the arena columns);
- the jit-counter claim: ONE compiled ragged kernel serves k ∈ {4, 16,
  100} in one dispatch — no per-k retraces;
- continuous batching: a lone request on an idle scheduler dispatches
  immediately (never waits the flush timeout), and per-tenant admission
  control caps a flooding tenant per dispatch with oldest-first fairness;
- the LRU bound on the compiled-kernel caches and ``warmup_serving``
  (a warmed geometry adds no jit entries on the first live request).
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.serve import (QueryScheduler, RetrievalRequest,
                               RetrievalResult)
from lazzaro_tpu.utils.batching import LRUKernelCache, bucket_size
from lazzaro_tpu.utils.telemetry import Telemetry

D = 16
KW = dict(cap_take=5, max_nbr=8, super_gate=0.4, acc_boost=0.05,
          nbr_boost=0.02)
MIXED_K = (4, 16, 100, 1, 7)


def _build(n=120, seed=1, supers=True, two_tenants=True, edges=True, **kw):
    """Tiny two-tenant arena with supers (gate tier) and a chain graph."""
    rng = np.random.default_rng(seed)
    kw.setdefault("serve_k_max", 32)
    idx = MemoryIndex(dim=D, capacity=256, edge_capacity=1024, **kw)
    emb = rng.standard_normal((n, D)).astype(np.float32)
    n_a = n - 20 if two_tenants else n
    ids_a = [f"a{i}" for i in range(n_a)]
    sup = [supers and i % 11 == 0 for i in range(n_a)]
    idx.ingest_batch(ids_a, emb[:n_a], [0.5] * n_a, [0.0] * n_a,
                     ["semantic"] * n_a, ["s"] * n_a, "ta",
                     is_super=sup,
                     chain_pairs=(list(zip(ids_a, ids_a[1:]))
                                  if edges else ()))
    if two_tenants:
        ids_b = [f"b{i}" for i in range(20)]
        idx.ingest_batch(ids_b, emb[n_a:], [0.5] * 20, [0.0] * 20,
                         ["semantic"] * 20, ["s"] * 20, "tb")
    return idx, emb


def _mixed_reqs(emb, boost=False):
    reqs = []
    for i, k in enumerate(MIXED_K):
        reqs.append(RetrievalRequest(query=emb[3 * i], tenant="ta", k=k,
                                     gate_enabled=(i % 2 == 0),
                                     boost=boost))
    reqs.append(RetrievalRequest(query=emb[-1], tenant="tb", k=6,
                                 boost=boost))
    return reqs


def _assert_matches_per_request(ragged_res, reqs, classic_idx, k_max):
    """Each ragged result must equal the same request served alone through
    the non-ragged fused path (k above the ceiling truncates to it)."""
    for req, got in zip(reqs, ragged_res):
        solo = classic_idx.search_fused_requests(
            [RetrievalRequest(query=req.query, tenant=req.tenant,
                              k=req.k, gate_enabled=req.gate_enabled)],
            **KW)[0]
        kc = min(int(req.k), k_max)
        assert got.ids == solo.ids[:kc], (req.k, got.ids[:3], solo.ids[:3])
        np.testing.assert_allclose(got.scores, solo.scores[:kc], rtol=1e-5)
        assert got.fast == solo.fast
        if got.gate_id is not None and kc == min(int(req.k), k_max):
            assert got.gate_id == solo.gate_id


# ------------------------------------------------------------ mixed-k parity
def test_mixed_k_parity_exact():
    idx, emb = _build()
    classic, _ = _build(serve_ragged=False)
    reqs = _mixed_reqs(emb)
    res = idx.search_fused_requests(reqs, **KW)
    for req, r in zip(reqs, res):
        assert len(r.ids) == min(int(req.k), 32)
    _assert_matches_per_request(res, reqs, classic, k_max=32)


def test_mixed_k_parity_quant():
    idx, emb = _build(int8_serving=True)
    classic, _ = _build(serve_ragged=False, int8_serving=True)
    reqs = _mixed_reqs(emb)
    res = idx.search_fused_requests(reqs, **KW)
    _assert_matches_per_request(res, reqs, classic, k_max=32)


def test_mixed_k_parity_ivf():
    idx, emb = _build(ivf_nprobe=4, serve_k_max=8)
    idx._IVF_MIN_ROWS = 1
    assert idx.ivf_maintenance()
    classic, _ = _build(serve_ragged=False, ivf_nprobe=4)
    classic._IVF_MIN_ROWS = 1
    assert classic.ivf_maintenance()
    reqs = _mixed_reqs(emb)
    res = idx.search_fused_requests(reqs, **KW)
    # both paths assemble candidates via ops.ivf.gather_rows at the same
    # nprobe; the ragged ceiling is 8 so every k clamps to ≤ 8
    _assert_matches_per_request(res, reqs, classic, k_max=8)


def test_mixed_k_boost_parity_exact():
    """Boost numerics: ONE ragged mixed-k boosting batch leaves the arena
    columns exactly where the same requests served one-by-one through the
    non-ragged fused path leave them (positive capped adds commute)."""
    idx, emb = _build()
    classic, _ = _build(serve_ragged=False)
    reqs = _mixed_reqs(emb, boost=True)
    now = 123.0
    idx.search_fused_requests(reqs, now=now + idx.epoch, **KW)
    for r in reqs:
        classic.search_fused_requests(
            [RetrievalRequest(query=r.query, tenant=r.tenant, k=r.k,
                              gate_enabled=r.gate_enabled, boost=True)],
            now=now + classic.epoch, **KW)
    np.testing.assert_allclose(np.asarray(idx.state.salience),
                               np.asarray(classic.state.salience),
                               atol=1e-6)
    np.testing.assert_array_equal(np.asarray(idx.state.access_count),
                                  np.asarray(classic.state.access_count))


def test_per_request_cap_take_and_nprobe():
    """The other two sidecar columns: a per-request ``cap_take`` bounds the
    device boost rows (readback counter), a per-request ``nprobe`` narrows
    the probe width without losing the self-hit."""
    tel = Telemetry()
    idx, emb = _build(telemetry=tel)
    idx.search_fused_requests(
        [RetrievalRequest(query=emb[0], tenant="ta", k=10, boost=True,
                          cap_take=2)], **KW)
    assert tel.counter_total("device.boost_rows") == 2
    ivf, embi = _build(ivf_nprobe=4, serve_k_max=8)
    ivf._IVF_MIN_ROWS = 1
    assert ivf.ivf_maintenance()
    res = ivf.search_fused_requests(
        [RetrievalRequest(query=embi[5], tenant="ta", k=5, nprobe=1),
         RetrievalRequest(query=embi[5], tenant="ta", k=5)], **KW)
    assert res[0].ids[0] == res[1].ids[0] == "a5"  # own cluster is rank 1
    assert len(res[0].ids) == len(res[1].ids) == 5


def test_shortfall_counts_against_requested_k():
    """A request whose k exceeds the ceiling (or the live row count) reads
    back a per-query live LENGTH below its k — the PR 6 shortfall tail
    generalized to ragged decode."""
    tel = Telemetry()
    idx, emb = _build(telemetry=tel, serve_k_max=16)
    res = idx.search_fused_requests(
        [RetrievalRequest(query=emb[0], tenant="ta", k=100)], **KW)
    assert len(res[0].ids) == 16               # ceiling-truncated
    assert tel.counter_total("device.topk_shortfall") == 100 - 16


# -------------------------------------------------- one kernel, one dispatch
def test_one_compiled_kernel_serves_mixed_k(monkeypatch):
    """The acceptance jit-counter: ONE compiled ragged kernel serves
    k ∈ {4, 16, 100} — the mixed batch costs one dispatch, and successive
    batches with different k mixes (same geometry) add ZERO new jit cache
    entries to the ragged read twin."""
    idx, emb = _build()
    calls = {"n": 0}
    orig = S.search_fused_ragged_read

    def wrapped(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(S, "search_fused_ragged_read", wrapped)
    reqs = [RetrievalRequest(query=emb[i], tenant="ta", k=k)
            for i, k in enumerate((4, 16, 100, 4))]
    idx.search_fused_requests(reqs, **KW)
    assert calls["n"] == 1                     # ONE dispatch, mixed k
    size_after_first = orig._cache_size()
    for ks in ((4, 4, 4, 4), (100, 100, 100, 100), (16, 1, 100, 7)):
        idx.search_fused_requests(
            [RetrievalRequest(query=emb[i], tenant="ta", k=k)
             for i, k in enumerate(ks)], **KW)
    assert orig._cache_size() == size_after_first   # no per-k recompiles
    assert calls["n"] == 4
    # the index-side kernel-key ledger agrees: one key for the mode
    assert len(idx._serve_kernel_keys) == 1


def test_sharded_ragged_one_kernel_mixed_k():
    """Pod path: one ragged distributed program (per-mode cache key)
    serves a mixed-k mega-batch in ONE distributed dispatch, with parity
    against the non-ragged pod kernels per request."""
    from lazzaro_tpu.parallel.index import ShardedMemoryIndex
    from lazzaro_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 host devices")
    mesh = make_mesh(("data",), (2,), devices=jax.devices()[:2])
    rng = np.random.default_rng(3)
    emb = rng.standard_normal((60, D)).astype(np.float32)

    def fill(idx):
        idx.add([f"a{i}" for i in range(40)], emb[:40], "ta",
                supers=[i % 13 == 0 for i in range(40)])
        idx.add([f"b{i}" for i in range(20)], emb[40:], "tb")
        idx.add_edges([(f"a{i}", f"a{i + 1}", 0.8) for i in range(10)])
        return idx

    idx = fill(ShardedMemoryIndex(mesh, dim=D, capacity=255, k=8,
                                  serve_k_max=32))
    classic = fill(ShardedMemoryIndex(mesh, dim=D, capacity=255, k=8,
                                      serve_ragged=False))
    reqs = [RetrievalRequest(query=emb[1], tenant="ta", k=4,
                             gate_enabled=True),
            RetrievalRequest(query=emb[41], tenant="tb", k=100),
            RetrievalRequest(query=emb[3], tenant="ta", k=16)]
    before = idx.dispatch_count
    res = idx.serve_requests(reqs)
    assert idx.dispatch_count == before + 1    # ONE distributed dispatch
    assert len(idx._fused_cache) == 1          # per-MODE kernel key
    for req, got in zip(reqs, res):
        solo = classic.serve_requests(
            [RetrievalRequest(query=req.query, tenant=req.tenant, k=req.k,
                              gate_enabled=req.gate_enabled)])[0]
        kc = min(int(req.k), 32)
        assert got.ids == solo.ids[:kc]
        np.testing.assert_allclose(got.scores, solo.scores[:kc], rtol=1e-5)
    # tenant isolation survives the ragged merge
    assert all(i.startswith("b") for i in res[1].ids)
    # a second mixed-k batch re-uses the same compiled program
    idx.serve_requests([RetrievalRequest(query=emb[9], tenant="ta", k=30)])
    assert len(idx._fused_cache) == 1


def test_ragged_pallas_topk_matches_per_k():
    """The ragged-K blocked scan: ceiling compute + per-query boundary mask
    equals per-k ``lax.top_k`` results for every k in the batch."""
    from lazzaro_tpu.ops.pallas_topk import pallas_masked_topk_ragged

    rng = np.random.default_rng(0)
    emb = jnp.asarray(rng.standard_normal((512, D)).astype(np.float32))
    madd = jnp.where(jnp.arange(512) % 7 == 0, -1e30, 0.0
                     ).astype(jnp.float32)
    q = jnp.asarray(rng.standard_normal((4, D)).astype(np.float32))
    k_q = jnp.asarray([2, 8, 1, 5], jnp.int32)
    s, i = pallas_masked_topk_ragged(emb, madd, q, k_q, k=8,
                                     block_rows=128, interpret=True)
    scores = q @ emb.T + madd[None, :]
    for qi, kk in enumerate([2, 8, 1, 5]):
        ts, ti = jax.lax.top_k(scores[qi], kk)
        np.testing.assert_allclose(np.asarray(s)[qi, :kk], np.asarray(ts),
                                   rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(i)[qi, :kk],
                                      np.asarray(ti))
        assert (np.asarray(i)[qi, kk:] == -1).all()


# --------------------------------------------------- continuous batching
def test_lone_request_dispatches_immediately():
    """Regression (ISSUE 7 satellite): a single request on an idle
    continuous scheduler must NOT wait the flush timeout — latency is the
    dispatch time, not ``serve_flush_us``."""
    def echo(reqs):
        return [RetrievalResult(ids=["x"], scores=[1.0]) for _ in reqs]

    flush_s = 0.5
    s = QueryScheduler(echo, max_batch=64, max_wait_us=int(flush_s * 1e6),
                       continuous=True)
    try:
        t0 = time.perf_counter()
        fut = s.submit(RetrievalRequest(query=np.zeros(1, np.float32),
                                        tenant="u"))
        fut.result(timeout=10)
        elapsed = time.perf_counter() - t0
        assert elapsed < flush_s / 2, (
            f"lone request waited {elapsed:.3f}s — flush-boundary latency "
            f"leaked into the continuous scheduler")
    finally:
        s.close()


def test_flush_boundary_mode_still_waits():
    """The A/B control: with continuous OFF, a lone request is held until
    the flush window closes (the PR 2–6 policy, kept for fallback)."""
    def echo(reqs):
        return [RetrievalResult(ids=["x"], scores=[1.0]) for _ in reqs]

    flush_s = 0.3
    s = QueryScheduler(echo, max_batch=64, max_wait_us=int(flush_s * 1e6),
                       continuous=False)
    try:
        t0 = time.perf_counter()
        fut = s.submit(RetrievalRequest(query=np.zeros(1, np.float32),
                                        tenant="u"))
        fut.result(timeout=10)
        assert time.perf_counter() - t0 >= flush_s * 0.8
    finally:
        s.close()


def test_continuous_admits_arrivals_into_next_dispatch():
    """Requests arriving while a dispatch is in flight admit into the NEXT
    dispatch as one dense batch (the in-flight dispatch is the batching
    window — no timer involved)."""
    release = threading.Event()
    batches = []

    def blocking(reqs):
        batches.append(len(reqs))
        if len(batches) == 1:
            release.wait(timeout=10)
        return [RetrievalResult(ids=["x"], scores=[1.0]) for _ in reqs]

    s = QueryScheduler(blocking, max_batch=64, max_wait_us=10_000_000,
                       continuous=True)
    try:
        first = s.submit(RetrievalRequest(query=np.zeros(1, np.float32),
                                          tenant="u"))
        time.sleep(0.05)
        rest = s.submit_many([
            RetrievalRequest(query=np.zeros(1, np.float32), tenant="u")
            for _ in range(9)])
        release.set()
        first.result(timeout=10)
        for f in rest:
            f.result(timeout=10)
        assert batches == [1, 9]
    finally:
        s.close()


def test_tenant_admission_cap_with_oldest_first_fairness():
    """Per-tenant admission control: a flooding tenant is capped per
    dispatch; deferred requests keep their queue position and ship in the
    following dispatches (every future still completes)."""
    release = threading.Event()
    batches = []

    def executor(reqs):
        batches.append([r.tenant for r in reqs])
        if len(batches) == 1:
            release.wait(timeout=10)
        return [RetrievalResult(ids=[r.tenant], scores=[1.0])
                for r in reqs]

    s = QueryScheduler(executor, max_batch=8, max_wait_us=500,
                       continuous=True, tenant_max_inflight=2)
    try:
        first = s.submit(RetrievalRequest(query=np.zeros(1, np.float32),
                                          tenant="warm"))
        time.sleep(0.05)
        flood = s.submit_many([
            RetrievalRequest(query=np.zeros(1, np.float32), tenant="hog")
            for _ in range(6)])
        trickle = s.submit_many([
            RetrievalRequest(query=np.zeros(1, np.float32), tenant="small")
            for _ in range(2)])
        release.set()
        for f in [first] + flood + trickle:
            f.result(timeout=10)
        # no post-warmup batch carries more than 2 of the flooding tenant,
        # and the small tenant rode the FIRST post-warmup dispatch (it was
        # not starved behind the hog's queue depth)
        for b in batches[1:]:
            assert b.count("hog") <= 2
        assert "small" in batches[1]
        assert s.requests_deferred > 0
        assert sum(len(b) for b in batches) == 9
    finally:
        s.close()


# ------------------------------------------------------- LRU + warmup
def test_lru_kernel_cache_bounds_entries():
    c = LRUKernelCache(2)
    c.put("a", 1)
    c.put("b", 2)
    assert c.get("a") == 1                     # refresh a
    c.put("c", 3)                              # evicts b (LRU)
    assert len(c) == 2 and c.evictions == 1
    assert c.get("b") is None
    assert c.get("a") == 1 and c.get("c") == 3


def test_pod_kernel_cache_is_lru_capped():
    """Non-ragged mixed-k traffic used to grow the pod kernel cache one
    entry per k-bucket with no bound; the cap evicts the stale buckets."""
    from lazzaro_tpu.parallel.index import ShardedMemoryIndex
    from lazzaro_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 host devices")
    mesh = make_mesh(("data",), (2,), devices=jax.devices()[:2])
    rng = np.random.default_rng(5)
    idx = ShardedMemoryIndex(mesh, dim=D, capacity=255, k=4,
                             serve_ragged=False, serve_kernel_cache_max=2)
    emb = rng.standard_normal((30, D)).astype(np.float32)
    idx.add([f"n{i}" for i in range(30)], emb, "u")
    for k in (4, 16, 32, 64):                  # four distinct k-buckets
        idx.serve_requests([RetrievalRequest(query=emb[0], tenant="u",
                                             k=k)])
    assert len(idx._fused_cache) <= 2
    assert idx._fused_cache.evictions >= 2


def test_warmup_precompiles_serving_kernels():
    """``warmup_serving`` drives the real dispatch path on a tenant that
    owns no rows: the arena numerics are untouched, ``kernel.warmup_ms``
    is recorded, and the first live request at a warmed geometry adds
    ZERO new jit cache entries to the ragged twins."""
    tel = Telemetry()
    idx, emb = _build(telemetry=tel)
    sal_before = np.asarray(idx.state.salience).copy()
    out = idx.warmup_serving((3,), **KW)
    assert out and all(v > 0 for v in out.values())
    np.testing.assert_array_equal(np.asarray(idx.state.salience),
                                  sal_before)
    assert tel.timer_count("kernel.warmup_ms") == 1
    # warmup must not skew the serving counters
    assert tel.counter_total("serve.live_requests") == 0
    read_size = S.search_fused_ragged_read._cache_size()
    serve_size = S.search_fused_ragged._cache_size()
    idx.search_fused_requests(
        [RetrievalRequest(query=emb[i], tenant="ta", k=5 + i,
                          boost=(i == 0)) for i in range(3)], **KW)
    idx.search_fused_requests(
        [RetrievalRequest(query=emb[i], tenant="ta", k=9)
         for i in range(3)], **KW)
    assert S.search_fused_ragged_read._cache_size() == read_size
    assert S.search_fused_ragged._cache_size() == serve_size


def test_bucket_size_schedule():
    """Linear buckets above the granularity, pow2 below: a lone request
    stays a 1-slot dispatch, a 33-request batch pays 40 slots (pow2 paid
    64 — the padding tax), and specializations stay bounded."""
    assert bucket_size(1, 8) == 1
    assert bucket_size(2, 8) == 2
    assert bucket_size(3, 8) == 4
    assert bucket_size(8, 8) == 8
    assert bucket_size(9, 8) == 16
    assert bucket_size(33, 8) == 40            # pow2 would pay 64
    assert bucket_size(63, 8) == 64
