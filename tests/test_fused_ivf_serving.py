"""Fused IVF serving (ISSUE 4; tier-1 smoke, CPU, small arenas).

With a published IVF build, the per-chat-turn retrieval sequence must STILL
run as ONE device program: ``state.search_fused_ivf`` scores the query batch
against the centroids, gathers the top-``nprobe`` clusters' member rows plus
the exact-scan extras (sealed+fresh residual, super rows), scores only those
candidates (exact, or int8-gathered coarse + exact rescore with the shadow
on), and runs the super gate / CSR neighbor gather / boost scatter tail
unchanged. These tests count the actual jit entry points in IVF mode, pin
recall@10 parity against the classic multi-dispatch IVF path on a clustered
10k fixture at nprobe ∈ {4, 8}, check residual freshness (rows added
post-build are served through the fused path), pin boost-numerics parity
with the classic IVF path across gate-hit/gate-miss, and guard the
k-shortfall case where visited clusters hold fewer than k live rows.
"""

import tempfile

import numpy as np
import pytest

from lazzaro_tpu.config import MemoryConfig
from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.core.memory_system import MemorySystem
from lazzaro_tpu.serve import RetrievalRequest
from tests.test_fused_ingest import ClusteredEmb, QueueLLM

D = 24


def _system(tmp, serve_fused=True, nprobe=4, per=20, super_threshold=100,
            int8=False):
    ms = MemorySystem(
        enable_async=False, db_dir=tmp, verbose=False, load_from_disk=False,
        llm_provider=QueueLLM(per), embedding_provider=ClusteredEmb(),
        auto_prune=False, max_buffer_size=10_000,
        super_node_threshold=super_threshold,
        config=MemoryConfig(journal=False, auto_consolidate=False,
                            decay_rate=0.0, ivf_serving=nprobe,
                            int8_serving=int8,
                            # tier-1 arenas are tiny: the ragged k ceiling
                            # must stay below the visited-candidate count
                            # or the IVF pack falls back to the dense scan
                            serve_k_max=16))
    ms.config.serve_fused = serve_fused
    return ms


def _ingest_built(ms, convs=2):
    """Ingest a couple of conversations, then force the IVF build the
    background maintenance hook would normally run once the arena passes
    ~4k rows (tier-1 arenas are tiny, so the threshold is lowered)."""
    for c in range(convs):
        ms.start_conversation()
        ms.add_to_short_term(f"conv {c}", "episodic", 0.7)
        ms.end_conversation()
    ms.index._IVF_MIN_ROWS = 1
    assert ms.index.ivf_maintenance()
    return ms


_COUNTED = ("search_fused_ivf", "search_fused_ivf_copy",
            "search_fused_ivf_read", "search_fused_quant",
            "search_fused_quant_copy", "search_fused_quant_read",
            "search_fused", "search_fused_copy", "search_fused_read",
            "search_fused_ivf_ragged", "search_fused_ivf_ragged_copy",
            "search_fused_ivf_ragged_read", "search_fused_quant_ragged",
            "search_fused_quant_ragged_copy",
            "search_fused_quant_ragged_read", "search_fused_ragged",
            "search_fused_ragged_copy", "search_fused_ragged_read",
            "arena_search", "arena_update_access",
            "arena_update_access_copy", "arena_boost", "arena_boost_copy",
            "arena_apply_boosts", "arena_apply_boosts_copy")


def _count_dispatches(monkeypatch):
    calls = {name: 0 for name in _COUNTED}
    for name in _COUNTED:
        orig = getattr(S, name)

        def wrapped(*a, __orig=orig, __name=name, **kw):
            calls[__name] += 1
            return __orig(*a, **kw)

        monkeypatch.setattr(S, name, wrapped)
    return calls


def test_one_ivf_dispatch_per_chat_turn(monkeypatch):
    """The jit-call counter: with a published IVF build, a chat turn's
    retrieval (centroid prefilter + member gather + gate + neighbor boost
    + access boost) costs exactly ONE device dispatch — the donated
    ``search_fused_ivf`` program — and zero dense/quant/classic search or
    boost dispatches."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest_built(_system(tmp))
        ms.start_conversation()
        ms.chat("fact 3 body")                 # warm: compiles the kernel
        calls = _count_dispatches(monkeypatch)
        ms.chat("fact 7 body")
        assert calls["search_fused_ivf_ragged"] == 1   # donated single-writer
        for name in calls:
            if name != "search_fused_ivf_ragged":
                assert calls[name] == 0, (name, calls)
        ms.close()


def test_ivf_search_memories_takes_readonly_twin(monkeypatch):
    """A pure IVF read batch must take ``search_fused_ivf_read`` — same
    coarse prefilter + candidate scan, no donation dance, ONE dispatch per
    coalesced batch."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest_built(_system(tmp))
        ms.search_memories("fact 1 body")      # warm the kernel
        calls = _count_dispatches(monkeypatch)
        hits = ms.search_memories("fact 3 body")
        assert hits
        assert calls["search_fused_ivf_ragged_read"] == 1
        assert calls["search_fused_ivf_ragged"] == 0
        ms.search_memories_batch([f"fact {i} body" for i in range(8)])
        assert calls["search_fused_ivf_ragged_read"] == 2
        ms.close()


def test_ivf_cached_hit_turn_pays_zero_dispatches(monkeypatch):
    """Zero-RTT query-cache hits survive IVF mode: a cached turn queues
    boost counts host-side and the flush stays ONE scatter."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest_built(_system(tmp))
        ms.start_conversation()
        ms.chat("fact 7 body")                 # populates the query cache
        calls = _count_dispatches(monkeypatch)
        ms.chat("fact 7 body")                 # cache hit
        for name in calls:
            assert calls[name] == 0, (name, calls)
        assert ms._pending_boosts
        ms.end_conversation()
        assert calls["arena_apply_boosts"] == 1
        ms.close()


def _clustered_fixture(n=10_000, d=48, n_centers=64, seed=42, spread=0.5):
    """Genuinely clustered unit vectors: ``spread`` is the TOTAL noise norm
    relative to the unit center (per-dim noise would swamp the center at
    this d), so intra-cluster cosine ≈ 1/sqrt(1+spread²) ≈ 0.89."""
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((n_centers, d)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    lbl = rng.integers(0, n_centers, n)
    emb = centers[lbl] + (spread / np.sqrt(d)) * rng.standard_normal(
        (n, d)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    return rng, emb


def _recall(result_rows, truth_rows, k):
    hits = sum(len(set(r) & set(t[:k])) for r, t in
               zip(result_rows, truth_rows))
    return hits / (k * len(result_rows))


@pytest.mark.parametrize("nprobe", [4, 8])
def test_fused_ivf_recall_parity_with_classic_ivf_10k(nprobe):
    """recall@10 vs the exact ranking on a clustered 10k fixture: the fused
    single-dispatch IVF path must be at least as good as the classic
    multi-dispatch IVF path (``search_batch`` routing through
    ``_ivf_search``) — both assemble the SAME candidate set
    (``ops.ivf.gather_rows``) and score it exactly, so fused recall can
    only differ through the in-kernel dedup, which mirrors the host
    decode's."""
    n, d, k, nq = 10_000, 48, 10, 64
    rng, emb = _clustered_fixture(n=n, d=d)
    idx = MemoryIndex(dim=d, capacity=n + 64, ivf_nprobe=nprobe)
    idx.add([f"m{i}" for i in range(n)], emb, [0.5] * n, [0.0] * n,
            ["semantic"] * n, ["default"] * n, "u0")
    assert idx.ivf_maintenance()
    base = rng.integers(0, n, size=nq)
    queries = emb[base] + (0.3 / np.sqrt(d)) * rng.standard_normal(
        (nq, d)).astype(np.float32)
    queries /= np.linalg.norm(queries, axis=1, keepdims=True)
    truth = np.argsort(-(queries @ emb.T), axis=1)[:, :k]

    classic = idx.search_batch(queries, "u0", k=k)      # classic IVF path
    classic_rows = [[idx.id_to_row[i] for i in ids_] for ids_, _ in classic]

    reqs = [RetrievalRequest(query=queries[i], tenant="u0", k=k)
            for i in range(nq)]
    fused = idx.search_fused_requests(reqs, cap_take=5, max_nbr=8,
                                      super_gate=0.4, acc_boost=0.05,
                                      nbr_boost=0.02)
    fused_rows = [[idx.id_to_row[i] for i in r.ids] for r in fused]

    r_classic = _recall(classic_rows, truth, k)
    r_fused = _recall(fused_rows, truth, k)
    assert r_fused >= r_classic - 1e-9, (r_fused, r_classic)
    assert r_fused >= 0.85, r_fused
    # no duplicate rows in any fused result (in-kernel dedup)
    for rows in fused_rows:
        assert len(rows) == len(set(rows))


def test_ivf_residual_freshness_through_fused_path():
    """Rows added AFTER the build land in the fresh residual and must be
    served exactly through the fused kernel (the extras array carries
    them) — and a rebuilt residual cache can never hide them."""
    n, d = 5_000, 32
    rng, emb = _clustered_fixture(n=n, d=d, seed=7)
    idx = MemoryIndex(dim=d, capacity=n + 64, ivf_nprobe=4)
    idx.add([f"m{i}" for i in range(n)], emb, [0.5] * n, [0.0] * n,
            ["semantic"] * n, ["default"] * n, "u0")
    assert idx.ivf_maintenance()
    # post-build rows: orthogonal one-hot vectors, far from every centroid
    fresh = np.zeros((4, d), np.float32)
    for i in range(4):
        fresh[i, i] = 1.0
    idx.add([f"f{i}" for i in range(4)], fresh, [0.5] * 4, [0.0] * 4,
            ["semantic"] * 4, ["default"] * 4, "u0")
    reqs = [RetrievalRequest(query=fresh[i], tenant="u0", k=3)
            for i in range(4)]
    res = idx.search_fused_requests(reqs, cap_take=3, max_nbr=8,
                                    super_gate=0.4, acc_boost=0.05,
                                    nbr_boost=0.02)
    for i, r in enumerate(res):
        assert r.ids and r.ids[0] == f"f{i}", (i, r.ids)
        assert r.scores[0] > 0.999


def _numeric_cols(ms):
    cols = ms.index.pull_numeric()
    n = len(ms.index.id_to_row)
    return {k: cols[k][: n + 2] for k in ("salience", "access_count")}


def test_ivf_matches_classic_ivf_chat_turns():
    """Gate-miss boost parity: ids and boost side effects (salience +
    access counts on the arena AND host copies) match the classic
    multi-dispatch IVF serving path for plain ANN turns — including
    repeated (cached) turns."""
    a = _ingest_built(_system(tempfile.mkdtemp(), serve_fused=True))
    b = _ingest_built(_system(tempfile.mkdtemp(), serve_fused=False))
    try:
        a.start_conversation()
        b.start_conversation()
        for q in ("fact 3 body", "fact 17 body", "fact 31 body",
                  "fact 3 body"):             # last one is a cache hit
            ra = a.chat(q)
            rb = b.chat(q)
            assert ra == rb
        a.end_conversation()
        b.end_conversation()
        ca, cb = _numeric_cols(a), _numeric_cols(b)
        np.testing.assert_allclose(ca["salience"], cb["salience"], atol=1e-6)
        np.testing.assert_array_equal(ca["access_count"], cb["access_count"])
        ha = {n: (round(a.buffer.nodes[n].salience, 5),
                  a.buffer.nodes[n].access_count) for n in a.buffer.nodes}
        hb = {n: (round(b.buffer.nodes[n].salience, 5),
                  b.buffer.nodes[n].access_count) for n in b.buffer.nodes}
        assert ha == hb
    finally:
        a.close()
        b.close()


def test_ivf_matches_classic_super_gate_hit():
    """Gate-hit parity in IVF mode: the extras array carries EVERY super
    row, so the in-kernel gate top-1 is exact regardless of centroid
    routing — the device skips boosts exactly when the classic exact gate
    search would have fired, and the host fast path serves identical
    children."""
    def build(serve_fused):
        ms = _ingest_built(_system(tempfile.mkdtemp(),
                                   serve_fused=serve_fused,
                                   super_threshold=5))
        assert ms.super_nodes
        return ms

    a, b = build(True), build(False)
    try:
        sid = sorted(a.super_nodes)[0]
        centroid = np.asarray(a.super_nodes[sid].embedding, np.float32)
        ids_a, mode_a = a._retrieve_for_chat(centroid.tolist(), "probe-q")
        ids_b, mode_b = b._retrieve_for_chat(centroid.tolist(), "probe-q")
        assert ids_a == ids_b
        assert mode_a == "classic"             # device skipped boosts
        assert mode_b == "classic"
        children = a.super_nodes[sid].child_ids
        assert ids_a[0] == children[0]
        a.start_conversation()
        b.start_conversation()
        a.chat("fact 5 body")
        b.chat("fact 5 body")
        ca, cb = _numeric_cols(a), _numeric_cols(b)
        np.testing.assert_allclose(ca["salience"], cb["salience"], atol=1e-6)
        np.testing.assert_array_equal(ca["access_count"], cb["access_count"])
    finally:
        a.close()
        b.close()


def test_ivf_k_shortfall_guard():
    """Visited clusters holding fewer than k live rows must yield exactly
    the live candidates — never phantom rows, never duplicates, never a
    crash — and deleted member rows must not surface."""
    n, d, k = 256, 16, 10
    rng = np.random.default_rng(9)
    emb = rng.standard_normal((n, d)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    idx = MemoryIndex(dim=d, capacity=511, ivf_nprobe=1)
    ids = [f"m{i}" for i in range(n)]
    idx.add(ids, emb, [0.5] * n, [0.0] * n, ["semantic"] * n,
            ["default"] * n, "u0")
    idx._IVF_MIN_ROWS = 1
    assert idx.ivf_maintenance()
    # kill most of the arena so any visited cluster is nearly empty
    dead = ids[: n - 12]
    idx.delete(dead)
    res = idx.search_fused_requests(
        [RetrievalRequest(query=emb[n - 1], tenant="u0", k=k)],
        cap_take=5, max_nbr=8, super_gate=0.4, acc_boost=0.05,
        nbr_boost=0.02)
    got = res[0].ids
    assert got, "shortfall must not empty the result"
    assert len(got) == len(set(got))           # no duplicates
    assert len(got) <= k
    live = set(ids[n - 12:])
    assert all(g in live for g in got), got    # no dead rows surface


def test_ivf_int8_composition_single_dispatch(monkeypatch):
    """IVF + int8 shadow together: the candidate scan inside the fused IVF
    program becomes two-stage (int8 gathered coarse + exact rescore) and
    the turn is STILL one ``search_fused_ivf`` dispatch with exact top-1
    self-hits."""
    n, d = 5_000, 32
    rng, emb = _clustered_fixture(n=n, d=d, seed=13)
    idx = MemoryIndex(dim=d, capacity=n + 64, ivf_nprobe=4,
                      int8_serving=True)
    idx.add([f"m{i}" for i in range(n)], emb, [0.5] * n, [0.0] * n,
            ["semantic"] * n, ["default"] * n, "u0")
    assert idx.ivf_maintenance()
    reqs = [RetrievalRequest(query=emb[i], tenant="u0", k=5)
            for i in range(8)]
    kw = dict(cap_take=5, max_nbr=8, super_gate=0.4, acc_boost=0.05,
              nbr_boost=0.02)
    idx.search_fused_requests(reqs, **kw)      # warm + shadow build
    calls = _count_dispatches(monkeypatch)
    res = idx.search_fused_requests(reqs, **kw)
    assert calls["search_fused_ivf_ragged_read"] == 1
    assert sum(calls.values()) == 1
    for i, r in enumerate(res):
        assert r.ids[0] == f"m{i}"             # exact rescore self-hit
        assert r.scores[0] > 0.999             # no quantization error


def test_ivf_multi_tenant_batch_isolation():
    """One coalesced IVF batch serving several tenants keeps isolation:
    the per-request tenant column masks the gathered candidates."""
    n, d = 5_000, 32
    rng, emb = _clustered_fixture(n=n, d=d, seed=21)
    idx = MemoryIndex(dim=d, capacity=n + 64, ivf_nprobe=4)
    idx.add([f"m{i}" for i in range(n)], emb, [0.5] * n, [0.0] * n,
            ["semantic"] * n, ["default"] * n, "u0")
    idx.add(["alien"], emb[:1], [0.9], [0.0], ["semantic"], ["default"],
            "t2")
    assert idx.ivf_maintenance()
    reqs = [RetrievalRequest(query=emb[0], tenant="u0", k=5),
            RetrievalRequest(query=emb[0], tenant="t2", k=5)]
    res = idx.search_fused_requests(reqs, cap_take=5, max_nbr=8,
                                    super_gate=0.4, acc_boost=0.05,
                                    nbr_boost=0.02)
    assert res[0].ids and res[0].ids[0] == "m0"
    assert "alien" not in res[0].ids
    assert res[1].ids == ["alien"]


def test_no_build_falls_back_to_dense_fused(monkeypatch):
    """IVF configured but not yet built: ``search_fused_requests`` serves
    the dense fused kernel (still one dispatch) instead of bailing out of
    fusion — builds belong to background maintenance, never the query
    path."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _system(tmp)
        for c in range(2):
            ms.start_conversation()
            ms.add_to_short_term(f"conv {c}", "episodic", 0.7)
            ms.end_conversation()
        assert ms.index._ivf is None           # below the build threshold
        ms.search_memories("fact 1 body")      # warm
        calls = _count_dispatches(monkeypatch)
        hits = ms.search_memories("fact 3 body")
        assert hits
        assert calls["search_fused_ragged_read"] == 1
        assert calls["search_fused_ivf_ragged_read"] == 0
        ms.close()
