"""Quantized fused serving (ISSUE 3; tier-1 smoke, CPU, tiny arena).

With the int8 serving shadow active, the per-chat-turn retrieval sequence
must STILL run as ONE device program: ``state.search_fused_quant`` streams
the int8 codes for a coarse top-(k+slack), exactly rescores the survivors
from the master arena, and runs the super gate / CSR neighbor gather /
boost scatter unchanged. These tests count the actual jit entry points in
int8 mode, pin recall@10 against the pre-existing int8 shadow path on a
10k-row fixture, and pin boost-numerics parity with the classic int8 path
across gate-hit / gate-miss / multi-tenant cases.
"""

import tempfile

import numpy as np
import pytest

import lazzaro_tpu.ops.quant as Q
from lazzaro_tpu.config import MemoryConfig
from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.core.memory_system import MemorySystem
from lazzaro_tpu.serve import RetrievalRequest
from tests.test_fused_ingest import ClusteredEmb, QueueLLM

D = 24


def _system(tmp, serve_fused=True, int8=True, per=20, super_threshold=100):
    ms = MemorySystem(
        enable_async=False, db_dir=tmp, verbose=False, load_from_disk=False,
        llm_provider=QueueLLM(per), embedding_provider=ClusteredEmb(),
        auto_prune=False, max_buffer_size=10_000,
        super_node_threshold=super_threshold,
        config=MemoryConfig(journal=False, auto_consolidate=False,
                            decay_rate=0.0, int8_serving=int8))
    ms.config.serve_fused = serve_fused
    return ms


def _ingest(ms, convs=2):
    for c in range(convs):
        ms.start_conversation()
        ms.add_to_short_term(f"conv {c}", "episodic", 0.7)
        ms.end_conversation()
    return ms


_COUNTED = ("search_fused_quant", "search_fused_quant_copy",
            "search_fused_quant_read", "search_fused", "search_fused_copy",
            "search_fused_read", "search_fused_quant_ragged",
            "search_fused_quant_ragged_copy",
            "search_fused_quant_ragged_read", "search_fused_ragged",
            "search_fused_ragged_copy", "search_fused_ragged_read",
            "arena_search", "arena_update_access",
            "arena_update_access_copy", "arena_boost", "arena_boost_copy",
            "arena_apply_boosts", "arena_apply_boosts_copy")


def _count_dispatches(monkeypatch):
    calls = {name: 0 for name in _COUNTED}
    for name in _COUNTED:
        orig = getattr(S, name)

        def wrapped(*a, __orig=orig, __name=name, **kw):
            calls[__name] += 1
            return __orig(*a, **kw)

        monkeypatch.setattr(S, name, wrapped)
    # the classic int8 shadow scan must not fire either
    orig_qt = Q.quantized_topk
    calls["quantized_topk"] = 0

    def wrapped_qt(*a, **kw):
        calls["quantized_topk"] += 1
        return orig_qt(*a, **kw)

    monkeypatch.setattr(Q, "quantized_topk", wrapped_qt)
    return calls


def test_one_quant_dispatch_per_chat_turn(monkeypatch):
    """The jit-call counter: in int8 mode a chat turn's retrieval (coarse
    int8 scan + exact rescore + gate + neighbor boost + access boost) costs
    exactly ONE device dispatch — the donated ``search_fused_quant``
    program — and zero classic search/boost/shadow-scan dispatches."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest(_system(tmp))
        ms.start_conversation()
        ms.chat("fact 3 body")                 # warm: builds the int8 shadow
        calls = _count_dispatches(monkeypatch)
        ms.chat("fact 7 body")
        assert calls["search_fused_quant_ragged"] == 1  # donated single-writer
        for name in calls:
            if name != "search_fused_quant_ragged":
                assert calls[name] == 0, (name, calls)
        ms.close()


def test_quant_search_memories_takes_readonly_twin(monkeypatch):
    """A pure int8 read batch must take ``search_fused_quant_read`` — same
    two-stage compute, no donation dance, ONE dispatch per batch."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest(_system(tmp))
        ms.search_memories("fact 1 body")      # warm the shadow + kernel
        calls = _count_dispatches(monkeypatch)
        hits = ms.search_memories("fact 3 body")
        assert hits
        assert calls["search_fused_quant_ragged_read"] == 1
        assert calls["search_fused_quant_ragged"] == 0
        assert calls["quantized_topk"] == 0
        ms.search_memories_batch([f"fact {i} body" for i in range(8)])
        assert calls["search_fused_quant_ragged_read"] == 2
        ms.close()


def test_quant_cached_hit_turn_pays_zero_dispatches(monkeypatch):
    """Zero-RTT query-cache hits survive quantized mode: a cached turn
    queues boost counts host-side and the flush stays ONE scatter."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest(_system(tmp))
        ms.start_conversation()
        ms.chat("fact 7 body")                 # populates the query cache
        calls = _count_dispatches(monkeypatch)
        ms.chat("fact 7 body")                 # cache hit
        for name in calls:
            assert calls[name] == 0, (name, calls)
        assert ms._pending_boosts
        ms.end_conversation()
        assert calls["arena_apply_boosts"] == 1
        ms.close()


def _recall(result_ids_rows, truth_rows, k):
    hits = sum(len(set(r) & set(t[:k])) for r, t in
               zip(result_ids_rows, truth_rows))
    return hits / (k * len(result_ids_rows))


def test_quant_fused_recall_not_worse_than_shadow_path_10k():
    """recall@10 vs the exact ranking on a 10k-row fixture: the fused
    coarse-scan + exact-rescore path must be at least as good as the
    pre-existing pure-int8 shadow scan (`search_batch` in int8 mode) — the
    exact rescore can only fix int8 ranking errors inside the slack
    window, never introduce new ones."""
    n, d, k, nq = 10_000, 48, 10, 64
    rng = np.random.default_rng(42)
    emb = rng.standard_normal((n, d)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    idx = MemoryIndex(dim=d, capacity=n + 64, int8_serving=True)
    ids = [f"m{i}" for i in range(n)]
    idx.add(ids, emb, [0.5] * n, [0.0] * n, ["semantic"] * n,
            ["default"] * n, "u0")
    # queries near (not on) arena rows so the top-10 boundary has real ties
    base = rng.integers(0, n, size=nq)
    queries = emb[base] + 0.35 * rng.standard_normal((nq, d)).astype(np.float32)
    queries /= np.linalg.norm(queries, axis=1, keepdims=True)
    truth = np.argsort(-(queries @ emb.T), axis=1)[:, :k]

    shadow = idx.search_batch(queries, "u0", k=k)          # classic int8 path
    shadow_rows = [[idx.id_to_row[i] for i in ids_] for ids_, _ in shadow]

    reqs = [RetrievalRequest(query=queries[i], tenant="u0", k=k)
            for i in range(nq)]
    fused = idx.search_fused_requests(reqs, cap_take=5, max_nbr=8,
                                      super_gate=0.4, acc_boost=0.05,
                                      nbr_boost=0.02)
    fused_rows = [[idx.id_to_row[i] for i in r.ids] for r in fused]

    r_shadow = _recall(shadow_rows, truth, k)
    r_fused = _recall(fused_rows, truth, k)
    assert r_fused >= r_shadow, (r_fused, r_shadow)
    assert r_fused >= 0.95, r_fused


def test_quant_matches_classic_int8_chat_turns():
    """Ids and boost side effects (salience + access counts on the arena
    AND host copies) match the classic int8 serving path for plain ANN
    turns — including repeated (cached) turns."""
    a = _ingest(_system(tempfile.mkdtemp(), serve_fused=True))
    b = _ingest(_system(tempfile.mkdtemp(), serve_fused=False))
    try:
        a.start_conversation()
        b.start_conversation()
        for q in ("fact 3 body", "fact 17 body", "fact 31 body",
                  "fact 3 body"):             # last one is a cache hit
            ra = a.chat(q)
            rb = b.chat(q)
            assert ra == rb
        a.end_conversation()
        b.end_conversation()

        def cols(ms):
            c = ms.index.pull_numeric()
            nn = len(ms.index.id_to_row)
            return {k: c[k][: nn + 2] for k in ("salience", "access_count")}

        ca, cb = cols(a), cols(b)
        np.testing.assert_allclose(ca["salience"], cb["salience"], atol=1e-6)
        np.testing.assert_array_equal(ca["access_count"], cb["access_count"])
        ha = {n: (round(a.buffer.nodes[n].salience, 5),
                  a.buffer.nodes[n].access_count) for n in a.buffer.nodes}
        hb = {n: (round(b.buffer.nodes[n].salience, 5),
                  b.buffer.nodes[n].access_count) for n in b.buffer.nodes}
        assert ha == hb
    finally:
        a.close()
        b.close()


def test_quant_matches_classic_int8_super_gate_hit():
    """Gate-hit parity in int8 mode: the fused kernel's gate verdict uses
    the EXACT rescored super score (the 0.4 threshold is quantization-
    sensitive), so the device skips boosts exactly when the classic exact
    gate search would have fired, and the host fast path serves identical
    children."""
    def build(serve_fused):
        ms = _ingest(_system(tempfile.mkdtemp(), serve_fused=serve_fused,
                             super_threshold=5))
        assert ms.super_nodes
        return ms

    a, b = build(True), build(False)
    try:
        sid = sorted(a.super_nodes)[0]
        centroid = np.asarray(a.super_nodes[sid].embedding, np.float32)
        ids_a, mode_a = a._retrieve_for_chat(centroid.tolist(), "probe-q")
        ids_b, mode_b = b._retrieve_for_chat(centroid.tolist(), "probe-q")
        assert ids_a == ids_b
        assert mode_a == "classic"             # device skipped boosts
        assert mode_b == "classic"
        children = a.super_nodes[sid].child_ids
        assert ids_a[0] == children[0]
        a.start_conversation()
        b.start_conversation()
        a.chat("fact 5 body")
        b.chat("fact 5 body")

        def cols(ms):
            c = ms.index.pull_numeric()
            nn = len(ms.index.id_to_row)
            return {k: c[k][: nn + 2] for k in ("salience", "access_count")}

        ca, cb = cols(a), cols(b)
        np.testing.assert_allclose(ca["salience"], cb["salience"], atol=1e-6)
        np.testing.assert_array_equal(ca["access_count"], cb["access_count"])
    finally:
        a.close()
        b.close()


def test_quant_multi_tenant_batch_isolation():
    """One coalesced int8 batch serving several tenants keeps isolation:
    the per-request tenant column masks the coarse scan AND the rescore."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest(_system(tmp))
        emb = ClusteredEmb()
        ms.index.add(["t2:alien_1"],
                     np.asarray([emb.embed("fact 3 body")], np.float32),
                     [0.9], [0.0], ["semantic"], ["default"], "t2")
        reqs = [
            RetrievalRequest(query=np.asarray(emb.embed("fact 3 body"),
                                              np.float32),
                             tenant=ms.user_id, k=5),
            RetrievalRequest(query=np.asarray(emb.embed("fact 3 body"),
                                              np.float32),
                             tenant="t2", k=5),
        ]
        res = ms.index.search_fused_requests(
            reqs, cap_take=5, max_nbr=8, super_gate=0.4,
            acc_boost=0.05, nbr_boost=0.02)
        assert res[0].ids and all(i.startswith(f"{ms.user_id}:")
                                  for i in res[0].ids)
        assert res[1].ids == ["t2:alien_1"]
        ms.close()


def test_quant_k_shortfall_guard():
    """Satellite fix: the coarse over-fetch slack is config-driven and the
    quantized path returns k live rows whenever k live rows exist — the
    exact rescore + host decode can never shrink the result below k."""
    n, d, k = 64, 16, 10
    rng = np.random.default_rng(7)
    emb = rng.standard_normal((n, d)).astype(np.float32)
    idx = MemoryIndex(dim=d, capacity=255, int8_serving=True, coarse_slack=4)
    assert idx.coarse_slack == 4               # ctor knob wired
    idx.add([f"m{i}" for i in range(n)], emb, [0.5] * n, [0.0] * n,
            ["semantic"] * n, ["default"] * n, "u0")
    res = idx.search_fused_requests(
        [RetrievalRequest(query=rng.standard_normal(d).astype(np.float32),
                          tenant="u0", k=k)],
        cap_take=5, max_nbr=8, super_gate=0.4, acc_boost=0.05,
        nbr_boost=0.02)
    assert len(res[0].ids) == k


@pytest.mark.slow
def test_fused_quant_1m_rows_fixture(monkeypatch):
    """1M-row bench fixture (slow lane ONLY — tier-1 stays fast, ISSUE 3
    satellite): dense quantized fused serving at the north-star row count
    (reduced dim so the CPU lane finishes). Pins ONE dispatch per batch at
    scale and exact top-1 agreement with the classic int8 shadow path."""
    n, d, k = 1_048_576, 64, 10
    rng = np.random.default_rng(5)
    import jax.numpy as jnp
    idx = MemoryIndex(dim=d, capacity=n + 64, dtype=jnp.bfloat16,
                      int8_serving=True)
    chunk = 131_072
    for c in range(0, n, chunk):
        emb = rng.standard_normal((chunk, d)).astype(np.float32)
        idx.add([f"f{c + i}" for i in range(chunk)], emb, [0.5] * chunk,
                [0.0] * chunk, ["semantic"] * chunk, ["default"] * chunk,
                "u0")
    probe_rows = rng.integers(0, n, size=16)
    queries = np.asarray(idx.state.emb[jnp.asarray(probe_rows)], np.float32)
    reqs = [RetrievalRequest(query=queries[i], tenant="u0", k=k)
            for i in range(len(probe_rows))]
    kw = dict(cap_take=5, max_nbr=8, super_gate=0.4, acc_boost=0.05,
              nbr_boost=0.02)
    idx.search_fused_requests(reqs, **kw)      # warm + shadow build
    calls = _count_dispatches(monkeypatch)
    res = idx.search_fused_requests(reqs, **kw)
    assert calls["search_fused_quant_ragged_read"] == 1
    assert sum(calls.values()) == 1
    shadow = idx.search_batch(queries, "u0", k=1)
    for i, r in enumerate(probe_rows):
        assert res[i].ids[0] == f"f{r}"        # exact self-hit at 1M rows
        assert shadow[i][0][0] == res[i].ids[0]


def test_sharded_serve_requests_single_dispatch_multi_tenant():
    """ROADMAP ceiling #4: the pod path serves a mixed-tenant coalesced
    batch with ONE distributed dispatch (per-row tenant column), with
    isolation intact per request."""
    from lazzaro_tpu.parallel.index import ShardedMemoryIndex
    from lazzaro_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(("data",), (8,))
    idx = ShardedMemoryIndex(mesh, dim=16, capacity=256, dtype=np.float32)

    def basis(i):
        v = np.zeros(16, np.float32)
        v[i % 16] = 1.0
        return v

    idx.add([f"a{i}" for i in range(4)], np.stack([basis(i) for i in range(4)]),
            "alice")
    idx.add(["b0"], basis(0).reshape(1, -1), "bob")
    reqs = [RetrievalRequest(query=basis(0), tenant="alice", k=2),
            RetrievalRequest(query=basis(0), tenant="bob", k=2),
            RetrievalRequest(query=basis(2), tenant="alice", k=2),
            RetrievalRequest(query=basis(0), tenant="nobody", k=2)]
    calls = {"n": 0}
    res0 = idx.serve_requests(reqs)            # builds + warms the kernels
    orig = idx._dispatch

    def counting(fn, *a, **kw):
        calls["n"] += 1
        return orig(fn, *a, **kw)

    idx._dispatch = counting
    res = idx.serve_requests(reqs)
    assert calls["n"] == 1                     # ONE dispatch, 3 tenants
    for r0, r in zip(res0, res):
        assert r0.ids == r.ids
    assert res[0].ids[0] == "a0" and all(i.startswith("a") for i in res[0].ids)
    assert res[1].ids == ["b0"]
    assert res[2].ids[0] == "a2"
    assert res[3].ids == []                    # unknown tenant matches nothing
