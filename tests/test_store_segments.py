"""Segmented (LSM-lite) ArrowStore behavior: delta-segment upserts instead of
full rewrites, tombstone deletes, last-wins merge, compaction, legacy-layout
migration, columnar bulk readers, and the sys-meta sidecar."""

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from lazzaro_tpu.core.store import ArrowStore


@pytest.fixture()
def store(tmp_path):
    s = ArrowStore(str(tmp_path / "db"))
    yield s
    s.close()


def _node(i, dim=4, **kw):
    row = {"id": f"node_{i}", "content": f"fact {i}",
           "embedding": [float(i)] * dim, "salience": 0.5}
    row.update(kw)
    return row


def _segments(store, table="nodes", user="default"):
    with open(store._manifest_path(table, user)) as f:
        return json.load(f)


def test_upsert_appends_segment_not_rewrite(store):
    store.add_nodes([_node(i) for i in range(100)])
    man1 = _segments(store)
    store.add_nodes([_node(100)])
    man2 = _segments(store)
    assert len(man2["segments"]) == len(man1["segments"]) + 1
    # the delta holds ONE row, not 101
    seg = os.path.join(store.db_dir, man2["segments"][-1])
    assert pq.read_metadata(seg).num_rows == 1
    assert len(store.get_nodes()) == 101


def test_last_wins_and_tombstones(store):
    store.add_nodes([_node(1, salience=0.3), _node(2)])
    store.add_nodes([_node(1, salience=0.9)])     # upsert
    store.delete_nodes(["node_2"])                # tombstone
    rows = store.get_nodes()
    assert [r["id"] for r in rows] == ["node_1"]
    assert rows[0]["salience"] == pytest.approx(0.9)


def test_segment_folding_bounds_read_amplification(store):
    for i in range(20):   # > _COMPACT_MAX_SEGMENTS individual writes
        store.add_nodes([_node(i)])
    man = _segments(store)
    # tiny deltas don't justify an O(base) rewrite: they fold into one
    # segment once the count cap trips, keeping the manifest shallow
    assert len(man["segments"]) < 16
    assert len(store.get_nodes()) == 20
    # the folded segment files are gone; only live ones remain
    segs = [f for f in os.listdir(store.db_dir) if ".seg-" in f]
    assert len(segs) == len(man["segments"])


def test_row_heavy_deltas_trigger_base_compaction(store):
    store.add_nodes([_node(i) for i in range(3000)])
    store.add_nodes([_node(i) for i in range(3000, 6000)])   # crosses 4096 rows
    man = _segments(store)
    assert man["base"] is not None
    assert man["segments"] == []
    assert len(store.get_nodes()) == 6000


def test_tombstones_survive_segment_folding(store):
    store.add_nodes([_node(i) for i in range(5)])
    store.compact()                           # rows now live in the base
    store.delete_nodes(["node_2"])
    for i in range(20):                       # force a segments-only fold
        store.add_nodes([_node(100 + i)])
    man = _segments(store)
    assert man["base"] is not None            # base untouched by the fold
    ids = {r["id"] for r in store.get_nodes()}
    assert "node_2" not in ids                # tombstone still effective
    assert {"node_0", "node_104"} <= ids


def test_explicit_compact_and_versions(store):
    store.add_nodes([_node(1)])
    store.add_nodes([_node(2)])
    v_before = store.get_latest_version()
    store.compact()
    assert store.get_latest_version() > v_before
    assert {r["id"] for r in store.get_nodes()} == {"node_1", "node_2"}


def test_legacy_single_file_layout_still_reads(store):
    # simulate a round-1 database: one parquet, no manifest, no new columns
    legacy = pa.Table.from_pylist([{
        "id": "node_9", "user_id": "default", "content": "old row",
        "embedding": [1.0, 0.0], "type": "semantic", "timestamp": 5.0,
        "access_count": 2, "last_accessed": 6.0, "salience": 0.7,
        "is_super_node": False, "child_ids": "[]", "parent_id": "",
        "shard_key": "work", "metadata": "{}",
    }])
    buf = pa.BufferOutputStream()
    pq.write_table(legacy, buf)
    with open(os.path.join(store.db_dir, "nodes__default.parquet"), "wb") as f:
        f.write(buf.getvalue().to_pybytes())

    rows = store.get_nodes()
    assert rows[0]["id"] == "node_9"
    assert rows[0]["decay_pass"] == 0       # missing column defaulted
    # incremental write on top of the legacy base keeps both rows
    store.add_nodes([_node(10, dim=2)])
    assert {r["id"] for r in store.get_nodes()} == {"node_9", "node_10"}


def test_columnar_node_reader(store):
    store.add_nodes([_node(i, dim=3) for i in range(5)])
    store.add_nodes([{"id": "super_1", "content": "topic", "embedding": [],
                      "is_super_node": True, "child_ids": ["node_0"]}])
    cols = store.get_nodes_columns()
    assert cols["embedding"].shape == (6, 3)
    assert cols["embedding"].dtype == np.float32
    assert cols["has_embedding"].sum() == 5          # super row has no vector
    sup = cols["id"].index("super_1")
    assert bool(cols["is_super_node"][sup])
    assert json.loads(cols["child_ids"][sup]) == ["node_0"]


def test_columnar_edge_reader(store):
    store.add_edges([{"source": "a", "target": "b", "weight": 0.6},
                     {"source": "b", "target": "c", "weight": 0.4}])
    cols = store.get_edges_columns()
    assert cols["source_id"] == ["a", "b"]
    np.testing.assert_allclose(cols["weight"], [0.6, 0.4])


def test_delete_all_parity_drops_everything(store):
    store.add_nodes([_node(1)])
    store.delete_nodes([])
    assert store.get_nodes() == []
    assert store.get_nodes_columns() is None


def test_sys_meta_roundtrip(store):
    assert store.load_sys_meta() == {}
    store.save_sys_meta({"decay_pass": 7, "node_counter": 42})
    assert store.load_sys_meta() == {"decay_pass": 7, "node_counter": 42}
    # per-user isolation
    assert store.load_sys_meta("alice") == {}


def test_search_nodes_over_segments(store):
    store.add_nodes([_node(1, embedding=[1.0, 0.0, 0.0, 0.0])])
    store.add_nodes([_node(2, embedding=[0.0, 1.0, 0.0, 0.0])])
    assert store.search_nodes([1.0, 0.05, 0.0, 0.0], limit=1) == ["node_1"]


def test_cross_process_reader_sees_segments(tmp_path):
    a = ArrowStore(str(tmp_path / "db"))
    b = ArrowStore(str(tmp_path / "db"))
    a.add_nodes([_node(1)])
    v1 = b.get_latest_version()
    a.add_nodes([_node(2)])
    assert b.get_latest_version() > v1
    assert {r["id"] for r in b.get_nodes()} == {"node_1", "node_2"}


def test_empty_embedding_upsert_preserves_stored_vector(store):
    store.add_nodes([_node(1, embedding=[0.1, 0.2, 0.3, 0.4])])
    # metadata-only upsert (no vector on host): the stored vector survives
    store.add_nodes([{"id": "node_1", "content": "updated", "embedding": [],
                      "salience": 0.9}])
    rows = store.get_nodes()
    assert rows[0]["content"] == "updated"
    assert rows[0]["embedding"] == pytest.approx([0.1, 0.2, 0.3, 0.4])


def test_mixed_dimension_rows_search_and_survive(store):
    store.add_nodes([{"id": "old", "content": "legacy", "embedding": [1.0] * 8},
                     {"id": "new1", "content": "n1", "embedding": [0.5] * 4},
                     {"id": "new2", "content": "n2", "embedding": [-0.5] * 4}])
    # non-modal query still serves its rows
    assert store.search_nodes([1.0] * 8, limit=1) == ["old"]
    # metadata upsert of the non-modal row keeps its 8-dim vector
    store.add_nodes([{"id": "old", "content": "legacy2", "embedding": []}])
    row = [r for r in store.get_nodes() if r["id"] == "old"][0]
    assert len(row["embedding"]) == 8


def test_get_all_users_with_tricky_names(tmp_path):
    s = ArrowStore(str(tmp_path / "db"))
    s.add_nodes([_node(1)], user_id="metrics.seg-a")
    s.add_nodes([_node(2)], user_id="default")
    assert s.get_all_users() == ["default", "metrics.seg-a"]


def test_columnar_bulk_insert_matches_dict_path(tmp_path):
    """add_nodes_columns (the ingest hot path: one flat embedding buffer)
    round-trips identically to add_nodes dict rows."""
    store = ArrowStore(str(tmp_path / "db"))
    emb = np.arange(12, dtype=np.float32).reshape(3, 4)
    store.add_nodes_columns(
        ids=["a", "b", "c"], contents=["one", "two", "three"],
        embeddings=emb, types=["semantic", "episodic", "semantic"],
        saliences=[0.5, 0.6, 0.7], timestamps=[1.0, 2.0, 3.0],
        shard_keys=["work", "", "health"], decay_pass=4)
    store.add_nodes([{"id": "d", "content": "four", "embedding": [9.0] * 4,
                      "type": "semantic", "salience": 0.8, "timestamp": 4.0,
                      "shard_key": "work", "decay_pass": 4}])
    rows = {r["id"]: r for r in store.get_nodes()}
    assert len(rows) == 4
    assert rows["b"]["type"] == "episodic"
    assert rows["b"]["embedding"] == [4.0, 5.0, 6.0, 7.0]
    assert rows["c"]["salience"] == 0.7 and rows["c"]["shard_key"] == "health"
    assert rows["a"]["decay_pass"] == 4 and rows["a"]["access_count"] == 0
    # last-wins upsert across the two paths
    store.add_nodes_columns(ids=["d"], contents=["four v2"],
                            embeddings=np.full((1, 4), 2.0, np.float32),
                            types=["semantic"], saliences=[0.9],
                            timestamps=[5.0], shard_keys=["work"])
    rows = {r["id"]: r for r in store.get_nodes()}
    assert rows["d"]["content"] == "four v2" and rows["d"]["salience"] == 0.9
    store.close()
