"""Semantic query cache (ISSUE 20): similarity-keyed hits ride the fused
dispatch.

A device-resident ring of recent (query embedding, packed top-k) entries is
probed INSIDE the fused serving program: a query whose top-1 cosine against
its tenant's cached entries clears the threshold early-outs its arena scan
and returns the cached window — still ONE dispatch, ONE packed readback for
the whole batch. These tests pin the contract:

  * cold serve = bit-parity with a cache-off twin (ids, scores, gate);
    warm serve = hit, same window; a near-dup paraphrase also hits
  * hits are tenant-scoped — the same vector under another tenant misses
  * every mutation path invalidates exactly (add, delete, dedup-merge),
    so a stale window is never served
  * the ring survives a same-geometry checkpoint restore, is dropped on a
    geometry mismatch, and is ignored by a cache-off restore
  * a warm hit turn is still exactly one jit entry (counter test)
  * the pod path (ShardedMemoryIndex) carries the same semantics
"""

import numpy as np
import jax
import pytest

from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.checkpoint import save_index, load_index
from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.core.query_cache import QueryCache
from lazzaro_tpu.parallel.index import ShardedMemoryIndex
from lazzaro_tpu.parallel.mesh import make_mesh
from lazzaro_tpu.serve import RetrievalRequest
from lazzaro_tpu.utils.telemetry import Telemetry

D = 32
EPOCH = 1000.0
KW = dict(cap_take=5, max_nbr=8, super_gate=0.4, acc_boost=0.05,
          nbr_boost=0.02, now=1234.5)
SEM_KW = dict(semantic_cache=True, semantic_cache_slots=16,
              semantic_cache_threshold=0.99)


def _vecs(n, seed, dim=D):
    r = np.random.default_rng(seed)
    v = r.standard_normal((n, dim)).astype(np.float32)
    return v / np.linalg.norm(v, axis=1, keepdims=True)


def _build(**extra):
    """4 tenants x 32 rows with chain edges, telemetry attached."""
    idx = MemoryIndex(dim=D, capacity=255, epoch=EPOCH,
                      telemetry=Telemetry(), **extra)
    emb = _vecs(128, 0)
    for t in range(4):
        ids = [f"t{t}n{i}" for i in range(32)]
        idx.add(ids, emb[t * 32:(t + 1) * 32], [0.5] * 32, [0.0] * 32,
                ["semantic"] * 32, ["default"] * 32, f"u{t}")
        idx.add_edges([(f"t{t}n{i}", f"t{t}n{i + 1}", 0.7)
                       for i in range(31)], f"u{t}", now=EPOCH)
    return idx, emb


def _reqs(emb, boost=False, jitter=0.0, seed=7):
    """Two queries per tenant; jitter>0 makes near-dup paraphrases."""
    out = []
    r = np.random.default_rng(seed)
    for t in range(4):
        for j in range(2):
            q = emb[t * 32 + j] + jitter * r.standard_normal(D).astype(
                np.float32)
            out.append(RetrievalRequest(query=q, tenant=f"u{t}", k=8,
                                        gate_enabled=True, boost=boost))
    return out


def _sem_counts(idx):
    c = idx.telemetry.snapshot()["counters"]
    return (c.get("serve.semantic_hits", 0),
            c.get("serve.semantic_misses", 0))


def _same(a_list, b_list, tag):
    for a, b in zip(a_list, b_list):
        assert a.ids == b.ids, (tag, a.ids, b.ids)
        assert a.scores == b.scores, (tag, a.scores, b.scores)
        assert a.gate_id == b.gate_id, tag


# ------------------------------------------------- core hit/miss semantics
def test_cold_warm_paraphrase_parity_vs_cache_off():
    idx, emb = _build(**SEM_KW)
    off, _ = _build()
    r1 = idx.search_fused_requests(list(_reqs(emb)), **KW)
    assert _sem_counts(idx) == (0, 8)
    r_off = off.search_fused_requests(list(_reqs(emb)), **KW)
    _same(r1, r_off, "cold-vs-off")

    r2 = idx.search_fused_requests(list(_reqs(emb)), **KW)
    assert _sem_counts(idx) == (8, 8)
    _same(r2, r_off, "warm-vs-off")

    # a paraphrase (tiny jitter, cosine still above threshold) hits and
    # serves the cached intent's window
    r3 = idx.search_fused_requests(list(_reqs(emb, jitter=0.003)), **KW)
    assert _sem_counts(idx) == (16, 8)
    _same(r3, r_off, "paraphrase-vs-off")


def test_hits_are_tenant_scoped():
    idx, emb = _build(**SEM_KW)
    idx.search_fused_requests(list(_reqs(emb)), **KW)
    idx.search_fused_requests(list(_reqs(emb)), **KW)
    h, m = _sem_counts(idx)
    assert h == 8
    # u0's warmed query asked under u1 must NOT hit u0's slots
    alien = [RetrievalRequest(query=emb[0], tenant="u1", k=8,
                              gate_enabled=True)]
    idx.search_fused_requests(alien, **KW)
    h2, m2 = _sem_counts(idx)
    assert h2 == h and m2 == m + 1, (h2, m2)


def test_ingest_invalidates_only_the_writing_tenant():
    idx, emb = _build(**SEM_KW)
    idx.search_fused_requests(list(_reqs(emb)), **KW)
    idx.search_fused_requests(list(_reqs(emb)), **KW)
    h, m = _sem_counts(idx)
    idx.add(["t0new"], _vecs(1, 99), [0.9], [0.0], ["semantic"],
            ["default"], "u0")
    idx.search_fused_requests(list(_reqs(emb)), **KW)
    h2, m2 = _sem_counts(idx)
    # u0's two queries miss again; the other six tenants' stay warm
    assert h2 - h == 6 and m2 - m == 2, (h2 - h, m2 - m)


def test_delete_evicts_slots_serving_the_row():
    idx, emb = _build(**SEM_KW)
    rq = _reqs(emb)
    idx.search_fused_requests(list(rq), **KW)
    idx.search_fused_requests(list(rq), **KW)
    _, m = _sem_counts(idx)
    idx.delete(["t0n0"])                   # t0n0 sits in u0's windows
    res = idx.search_fused_requests(list(rq), **KW)
    _, m2 = _sem_counts(idx)
    assert m2 > m, "delete must evict the slots whose window holds the row"
    for r in res:
        assert "t0n0" not in r.ids


def test_dedup_merge_invalidates_cached_window():
    """Ingest-readback slot invalidation: a device dedup-merge into a row
    inside a cached window must evict that window — the next serve misses
    and matches a cache-off twin that took the same merge."""
    idx, emb = _build(**SEM_KW)
    off, _ = _build()
    rq = _reqs(emb)
    idx.search_fused_requests(list(rq), **KW)
    idx.search_fused_requests(list(rq), **KW)
    h, m = _sem_counts(idx)
    assert h == 8

    # near-dup of t0n0 (= emb[0]): cosine ~1 clears the 0.9 dedup gate,
    # so the device merges it into t0n0 (salience/recency bump in place)
    dup = emb[0] + 0.001 * _vecs(1, 5)[0]
    dup = (dup / np.linalg.norm(dup)).astype(np.float32).reshape(1, -1)
    for target in (idx, off):
        pending = target.ingest_batch_dedup(
            dup, [0.9], [50.0], ["semantic"], ["default"], "u0",
            dedup_gate=0.9, link_k=3, link_gate=0.5, now=EPOCH + 1.0)
        _, _, merges, _ = target.commit_ingest_dedup(pending, ["dupe0"])
        assert merges and merges[0][1] == "t0n0", merges

    # row-level precision: only the ONE window holding t0n0 is evicted
    # (u0's other cached query stays warm, as do the other tenants')
    res = idx.search_fused_requests(list(rq), **KW)
    h2, m2 = _sem_counts(idx)
    assert m2 - m == 1 and h2 - h == 7, (h2 - h, m2 - m)
    _same(res, off.search_fused_requests(list(rq), **KW), "post-merge")


def test_boost_path_hits_match_cache_off_ids():
    idx, emb = _build(**SEM_KW)
    off, _ = _build()
    b1 = idx.search_fused_requests(list(_reqs(emb, boost=True)), **KW)
    _same(b1, off.search_fused_requests(list(_reqs(emb, boost=True)), **KW),
          "boost-cold-vs-off")
    b2 = idx.search_fused_requests(list(_reqs(emb, boost=True)), **KW)
    h, _ = _sem_counts(idx)
    assert h == 8
    # both twins accrued one round of boost drift; ids must still agree
    b_off = off.search_fused_requests(list(_reqs(emb, boost=True)), **KW)
    for a, b in zip(b2, b_off):
        assert a.ids == b.ids, (a.ids, b.ids)


def test_semantic_invalidate_public_api():
    idx, emb = _build(**SEM_KW)
    idx.search_fused_requests(list(_reqs(emb)), **KW)
    assert idx.semantic_invalidate("u0") > 0
    assert idx.semantic_invalidate("u0") == 0      # already clean
    assert idx.semantic_invalidate("nope") == 0    # unknown tenant
    assert idx.semantic_invalidate() >= 0          # full flush
    st = idx.stats()["semantic_cache"]
    assert st["occupied"] == 0 and st["slots"] == 16


def test_cache_off_serve_records_no_semantic_counters():
    """sem_active gating: without the ring, no semantic counters move."""
    idx, emb = _build()
    idx.search_fused_requests(list(_reqs(emb)), **KW)
    assert _sem_counts(idx) == (0, 0)
    assert idx.stats()["semantic_cache"] is None


# -------------------------------------------------- one-dispatch guarantee
_COUNTED = ("search_fused", "search_fused_copy", "search_fused_read",
            "search_fused_ragged", "search_fused_ragged_copy",
            "search_fused_ragged_read",
            "arena_search", "arena_update_access", "arena_update_access_copy",
            "arena_boost", "arena_boost_copy", "arena_apply_boosts",
            "arena_apply_boosts_copy")


def _count_dispatches(monkeypatch):
    calls = {name: 0 for name in _COUNTED}
    for name in _COUNTED:
        orig = getattr(S, name)

        def wrapped(*a, __orig=orig, __name=name, **kw):
            calls[__name] += 1
            return __orig(*a, **kw)

        monkeypatch.setattr(S, name, wrapped)
    return calls


def test_warm_hit_turn_is_still_one_dispatch(monkeypatch):
    """The ring probe adds ZERO dispatches: a fully-warm batch (every
    query a hit) is still exactly one fused jit entry and no classic
    search/boost calls — the probe, early-out, and writeback all live
    inside the one program."""
    idx, emb = _build(**SEM_KW)
    rq = _reqs(emb)
    idx.search_fused_requests(list(rq), **KW)          # populate ring
    calls = _count_dispatches(monkeypatch)
    idx.search_fused_requests(list(rq), **KW)          # all 8 hit
    h, _ = _sem_counts(idx)
    assert h == 8
    fused = sum(calls[n] for n in _COUNTED if n.startswith("search_fused"))
    assert fused == 1, calls
    for n in _COUNTED:
        if not n.startswith("search_fused"):
            assert calls[n] == 0, (n, calls)


# ------------------------------------------------------ checkpoint ring
def test_checkpoint_ring_round_trip(tmp_path):
    dim = 16
    tel = Telemetry()
    idx = MemoryIndex(dim=dim, capacity=127, telemetry=tel,
                      semantic_cache=True, semantic_cache_slots=16,
                      semantic_cache_threshold=0.99)
    emb = _vecs(20, 11, dim)
    idx.add([f"n{i}" for i in range(20)], emb, [0.5] * 20,
            [1000.0 + i for i in range(20)], ["semantic"] * 20,
            ["s"] * 20, "alice")
    rq = [RetrievalRequest(query=emb[3], tenant="alice", k=4)]
    cold = [r.ids for r in idx.search_fused_requests(list(rq), **KW)]
    assert _sem_counts(idx) == (0, 1)
    save_index(idx, str(tmp_path))

    # same geometry -> ring survives; the very first serve is a HIT
    idx2 = load_index(str(tmp_path), telemetry=Telemetry(),
                      semantic_cache=True, semantic_cache_slots=16,
                      semantic_cache_threshold=0.99)
    warm = [r.ids for r in idx2.search_fused_requests(list(rq), **KW)]
    assert _sem_counts(idx2) == (1, 0)
    assert warm == cold

    # geometry mismatch -> ring dropped: cold start, never a wrong hit
    idx3 = load_index(str(tmp_path), telemetry=Telemetry(),
                      semantic_cache=True, semantic_cache_slots=8,
                      semantic_cache_threshold=0.99)
    res = [r.ids for r in idx3.search_fused_requests(list(rq), **KW)]
    assert _sem_counts(idx3) == (0, 1)
    assert res == cold

    # cache-off restore of a cache-on snapshot just ignores the ring
    idx4 = load_index(str(tmp_path), telemetry=Telemetry())
    res = [r.ids for r in idx4.search_fused_requests(list(rq), **KW)]
    assert res == cold
    assert _sem_counts(idx4) == (0, 0)


# ------------------------------------------------------------- pod path
def test_pod_semantic_cache_end_to_end():
    dim = 16
    tel = Telemetry()
    mesh = make_mesh(("data",), (4,), devices=jax.devices()[:4])
    idx = ShardedMemoryIndex(mesh, dim=dim, capacity=127, telemetry=tel,
                             semantic_cache=True, semantic_cache_slots=16,
                             semantic_cache_threshold=0.99)
    off = ShardedMemoryIndex(make_mesh(("data",), (4,),
                                       devices=jax.devices()[:4]),
                             dim=dim, capacity=127, telemetry=Telemetry())
    rng = np.random.default_rng(7)
    emb_a = rng.standard_normal((12, dim)).astype(np.float32)
    emb_b = rng.standard_normal((6, dim)).astype(np.float32)
    for target in (idx, off):
        target.add([f"a{i}" for i in range(12)], emb_a, "alice")
        target.add([f"b{i}" for i in range(6)], emb_b, "bob")

    def counts():
        return (tel.counter_total("serve.semantic_hits"),
                tel.counter_total("serve.semantic_misses"))

    rq = [RetrievalRequest(query=emb_a[1], tenant="alice", k=3),
          RetrievalRequest(query=emb_b[0], tenant="bob", k=3)]
    cold = [r.ids for r in idx.serve_requests(rq)]
    assert counts() == (0, 2)
    warm = [r.ids for r in idx.serve_requests(rq)]
    h1, m1 = counts()
    assert (h1, m1) == (2, 2) and warm == cold
    assert [r.ids for r in off.serve_requests(rq)] == warm

    # add() invalidates only alice; bob's entry stays warm
    idx.add(["a_new"], (emb_a[1] + 0.01).reshape(1, -1), "alice")
    off.add(["a_new"], (emb_a[1] + 0.01).reshape(1, -1), "alice")
    res = [r.ids for r in idx.serve_requests(rq)]
    h2, m2 = counts()
    assert h2 == h1 + 1 and m2 == m1 + 1    # bob hit, alice miss
    assert res == [r.ids for r in off.serve_requests(rq)]

    # delete() evicts the touched rows — no stale id in served windows
    victim = res[1][0]
    idx.delete([victim])
    res2 = [r.ids for r in idx.serve_requests(rq)]
    assert victim not in res2[1]

    snap = tel.snapshot()
    assert any("semantic_ring_occupancy" in k for k in snap["gauges"]), (
        snap["gauges"].keys())


# --------------------------------------------- observability surfaces
def test_metrics_summary_reports_both_cache_tiers():
    """serve.cache_hit_rate lands in the registry tier-labeled, and
    metrics_summary()/get_stats() surface both tiers' headline rates."""
    import tempfile

    from lazzaro_tpu.config import MemoryConfig
    from lazzaro_tpu.core.memory_system import MemorySystem
    from tests.test_fused_ingest import ClusteredEmb, QueueLLM

    with tempfile.TemporaryDirectory() as tmp:
        ms = MemorySystem(
            enable_async=False, db_dir=tmp, verbose=False,
            load_from_disk=False, llm_provider=QueueLLM(20),
            embedding_provider=ClusteredEmb(), auto_prune=False,
            config=MemoryConfig(journal=False, auto_consolidate=False,
                                decay_rate=0.0, semantic_cache=True,
                                semantic_cache_slots=16,
                                semantic_cache_threshold=0.99))
        ms.start_conversation()
        ms.add_to_short_term("conv 0", "episodic", 0.7)
        ms.end_conversation()
        ms.search_memories("fact 3 body")
        ms.search_memories("fact 3 body")      # second pass: semantic hit
        summary = ms.metrics_summary()
        rates = summary["cache_hit_rate"]
        assert set(rates) == {"exact", "semantic"}
        assert rates["semantic"] is not None and rates["semantic"] > 0.0
        assert summary["semantic_stale_evictions"] >= 0
        stats = ms.get_stats()
        assert stats["performance"]["semantic_cache_hit_rate"] is not None
        gauges = ms.telemetry.snapshot()["gauges"]
        tiers = {k for k in gauges if k.startswith("serve.cache_hit_rate")}
        assert any('tier="exact"' in k for k in tiers), tiers
        assert any('tier="semantic"' in k for k in tiers), tiers
        ms.close()


# ------------------------------------------- QueryCache result tenancy
def test_query_cache_results_are_tenant_keyed():
    """Regression (ISSUE 20 satellite): the SAME query text cached by two
    tenants stores two distinct entries — a tenant can never be served
    another tenant's node ids."""
    qc = QueryCache(max_size=16)
    qc.set_results("what did I say", ["alice:n1"], tenant="alice")
    qc.set_results("what did I say", ["bob:n9"], tenant="bob")
    assert qc.get_results("what did I say", "alice") == ["alice:n1"]
    assert qc.get_results("what did I say", "bob") == ["bob:n9"]
    # untenanted lookups don't alias a tenant's entry either way
    assert qc.get_results("what did I say") is None
    qc.set_results("shared", ["s1"])
    assert qc.get_results("shared") == ["s1"]
    assert qc.get_results("shared", "alice") is None
