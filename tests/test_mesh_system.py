"""Mesh-backed MemoryIndex/MemorySystem: full-orchestrator SPMD parity.

The arena columns are row-sharded over an 8-device CPU mesh; every kernel
(search matmul, scatter adds, decay sweeps, link matmuls, edge ops) runs
SPMD via GSPMD propagation. Results must be IDENTICAL to the single-device
index — sharding is a placement decision, not a semantic one.
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.core.memory_system import MemorySystem
from lazzaro_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh():
    n = min(8, len(jax.devices()))
    return make_mesh(("data",), (n,), devices=jax.devices()[:n])


def _fill(idx, n, d, seed=0):
    rng = np.random.RandomState(seed)
    emb = rng.randn(n, d).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    ids = [f"n{i}" for i in range(n)]
    idx.add(ids, emb, [0.3 + 0.01 * i for i in range(n)],
            [100.0 * i for i in range(n)], ["semantic"] * n,
            ["work" if i % 2 else "personal" for i in range(n)], "default")
    return ids, emb


def test_capacity_rounded_to_mesh(mesh):
    idx = MemoryIndex(dim=8, capacity=10, edge_capacity=10, mesh=mesh)
    n = mesh.shape["data"]
    assert (idx.state.capacity + 1) % n == 0
    assert (idx.edge_state.capacity + 1) % n == 0
    assert idx.state.emb.sharding.spec == P("data", None)
    assert idx.state.alive.sharding.spec == P("data")


def test_search_parity_with_unsharded(mesh):
    plain = MemoryIndex(dim=16, capacity=63, edge_capacity=31)
    meshed = MemoryIndex(dim=16, capacity=63, edge_capacity=31, mesh=mesh)
    _, emb = _fill(plain, 20, 16)
    _fill(meshed, 20, 16)
    for q in emb[:6]:
        a = plain.search(q, "default", k=5)
        b = meshed.search(q, "default", k=5)
        assert a[0] == b[0]
        np.testing.assert_allclose(a[1], b[1], rtol=1e-5)


def test_mutations_keep_sharding(mesh):
    """Scatter adds, decay, deletes, and growth must not silently
    replicate the arena."""
    idx = MemoryIndex(dim=8, capacity=15, edge_capacity=15, mesh=mesh)
    ids, emb = _fill(idx, 10, 8)
    idx.add_edges([("n0", "n1", 0.9), ("n1", "n2", 0.4)], "default")
    idx.decay("default", 0.01)
    idx.delete(["n3"])
    # growth: push past capacity
    rng = np.random.RandomState(9)
    more = rng.randn(30, 8).astype(np.float32)
    idx.add([f"m{i}" for i in range(30)], more, [0.5] * 30, [0.0] * 30,
            ["episodic"] * 30, ["work"] * 30, "default")
    assert idx.state.emb.sharding.spec == P("data", None)
    assert idx.state.salience.sharding.spec == P("data")
    assert idx.edge_state.weight.sharding.spec == P("data")
    assert (idx.state.capacity + 1) % mesh.shape["data"] == 0
    ids_out, _ = idx.search(more[0], "default", k=3)
    assert ids_out[0] == "m0"


def test_full_system_parity_on_mesh(mesh, tmp_path):
    """End-to-end orchestrator (ingest → retrieval → consolidation →
    persistence) produces identical memories with and without a mesh."""
    def run(db, m):
        ms = MemorySystem(enable_async=False, db_dir=db, verbose=False,
                          load_from_disk=False, mesh=m)
        ms.start_conversation()
        ms.chat("I work as a data engineer on a big ETL project.")
        ms.chat("I love hiking in the mountains on weekends.")
        ms.end_conversation()
        ms.run_consolidation()
        hits = [n.content for n in ms.search_memories("data engineer work")]
        nodes = sorted(n.content for n in ms.buffer.nodes.values())
        edges = sorted((e.source, e.target) for s in ms.shards.values()
                       for e in s.edges.values())
        ms.close()
        return hits, nodes, edges

    plain = run(str(tmp_path / "db1"), None)
    meshed = run(str(tmp_path / "db2"), mesh)
    assert plain == meshed


def test_mesh_chat_turn_is_one_distributed_dispatch(mesh, tmp_path,
                                                    monkeypatch):
    """ISSUE 5: under a mesh the chat-turn retrieval (gate + ANN +
    neighbor/access boosts) routes through the fused sharded program —
    ONE distributed shard_map dispatch per coalesced batch, zero classic
    search/boost dispatches. Counted by wrapping the factory's jit entry
    points AND the classic kernels."""
    from lazzaro_tpu.core import state as S

    calls = {"serve": 0, "read": 0, "classic": 0}
    orig_factory = S.make_fused_sharded

    def counting_factory(*a, **kw):
        kern = orig_factory(*a, **kw)

        def wrap(fn, key):
            def g(*aa, **kk):
                calls[key] += 1
                return fn(*aa, **kk)
            return g

        return S.FusedShardedKernels(wrap(kern.serve, "serve"),
                                     wrap(kern.serve_copy, "serve"),
                                     wrap(kern.read, "read"))

    monkeypatch.setattr(S, "make_fused_sharded", counting_factory)
    for name in ("arena_search", "arena_update_access", "arena_boost"):
        orig = getattr(S, name)

        def wrapped(*a, __orig=orig, **kw):
            calls["classic"] += 1
            return __orig(*a, **kw)

        monkeypatch.setattr(S, name, wrapped)

    ms = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False, mesh=mesh)
    ms.start_conversation()
    ms.chat("I work as a data engineer on a big ETL project.")
    ms.end_conversation()
    calls.update(serve=0, read=0, classic=0)
    ms.start_conversation()
    ms.chat("What do I do for work, the ETL project?")
    assert calls["serve"] == 1             # ONE distributed dispatch
    assert calls["classic"] == 0           # no classic search/boost path
    ms.close()


def test_snapshot_round_trip_on_mesh(mesh, tmp_path):
    ms = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False, mesh=mesh)
    ms.start_conversation()
    ms.chat("My cat is named Whiskers.")
    ms.end_conversation()
    snap = str(tmp_path / "snap")
    ms.save_snapshot(snap)
    ms.close()

    ms2 = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db2"),
                       verbose=False, load_from_disk=False, mesh=mesh)
    ms2.load_snapshot(snap)
    assert ms2.index.state.emb.sharding.spec == P("data", None)
    hits = [n.content for n in ms2.search_memories("cat Whiskers")]
    assert any("Whiskers" in h for h in hits)
    ms2.close()
