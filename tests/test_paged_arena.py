"""Paged embedding arena (ISSUE 17, tier-1, CPU, tiny arenas).

The master embedding table becomes a fixed-size-page HBM pool behind an
int32 ``row_map`` indirection with a device-side free list: delete and
tier-demote PUSH slots back (reclaimed capacity the next ingest reuses),
logical growth rewrites metadata only (the pool is never copied), and the
free-list pop rides INSIDE the fused ingest dispatch. These tests pin the
three contracts the whole feature stands on:

  * parity — a paged index answers every serving mode (exact / int8 /
    IVF / IVF-PQ / tiered) identically to a dense index fed the SAME
    corpus through the same ingest → delete → re-ingest → grow churn;
  * zero added dispatches — the jit-entry counters on an ingest+serve
    round are IDENTICAL dense vs paged (the page maintenance is fused,
    not a sibling dispatch), and the host free-list mirror never
    disagrees with the device readback tail;
  * durability — ``row_map`` + free list survive a checkpoint
    round-trip and the restored free list keeps allocating.

Parity is EXACT, full-list (ISSUE 18): dense demote zero-fills rows
that stay alive, but the residency column now masks them to -inf in the
exact dense scan — the same rows the paged layout drops by freeing the
slot — so a demoted row can no longer surface as a score-0.0 top-k tail
in either layout and the comparisons assert the complete k-list.
"""

import tempfile

import numpy as np
import pytest

from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.checkpoint import load_index, save_index
from lazzaro_tpu.core.index import MemoryIndex

D = 16
CAP = 64


def _corpus(n, d=D, seed=7):
    rng = np.random.default_rng(seed)
    e = rng.standard_normal((n, d)).astype(np.float32)
    e /= np.linalg.norm(e, axis=1, keepdims=True)
    return e


def _clustered(n, d=D, seed=9, centers=8):
    rng = np.random.default_rng(seed)
    c = rng.standard_normal((centers, d)).astype(np.float32)
    e = (c[np.arange(n) % centers]
         + 0.15 * rng.standard_normal((n, d)).astype(np.float32))
    e /= np.linalg.norm(e, axis=1, keepdims=True)
    return e


def _add(idx, ids, emb, ts=0.0):
    n = len(ids)
    idx.add(ids, emb, [0.5] * n, [ts] * n, ["semantic"] * n,
            ["default"] * n, "t")


def _churn(idx, e):
    """Shared ingest → delete → dedup-ingest → grow sequence. Both the
    dense and the paged variant run EXACTLY this, on the same ``e``."""
    _add(idx, [f"m{i}" for i in range(48)], e[:48])
    idx.delete([f"m{i}" for i in range(0, 20, 2)])        # 10 holes
    pend = idx.ingest_batch_dedup(
        e[48:64], [0.6] * 16, [1.0] * 16, ["semantic"] * 16,
        ["default"] * 16, "t", dedup_gate=0.99)
    idx.commit_ingest_dedup(pend, [f"d{i}" for i in range(16)])
    _add(idx, [f"g{i}" for i in range(60)], e[64:124], ts=2.0)  # forces grow


def _pos(ids, scores):
    """(id, score) pairs over the FULL result list — the residency mask
    (ISSUE 18) closed the dense-demote score-0.0 tail, so nothing is
    filtered before comparing."""
    return [(i, round(float(s), 5)) for i, s in zip(ids, scores)]


def _parity_search(dense, paged, queries, k=10, **kw):
    """FULL-list parity: ids in order and scores to float tolerance —
    the dense-demote residency mask (ISSUE 18) closed the score-0.0
    tail divergence, so nothing is filtered before comparing."""
    for q in queries:
        di, ds = dense.search(q, "t", k=k, **kw)
        pi, ps = paged.search(q, "t", k=k, **kw)
        assert di == pi, (list(zip(di, ds)), list(zip(pi, ps)))
        np.testing.assert_allclose(ds, ps, atol=1e-5)


def test_paged_dense_parity_exact_churn():
    e = _corpus(124)
    dense = MemoryIndex(dim=D, capacity=CAP)
    paged = MemoryIndex(dim=D, capacity=CAP, paged=True, page_rows=8)
    for idx in (dense, paged):
        _churn(idx, e)
    _parity_search(dense, paged, e[:6])
    _parity_search(dense, paged, e[70:74], exact=True)
    # the churn exercised the free list both ways, and the host mirror
    # never disagreed with the device readback tail
    st = paged.stats()["paged"]
    assert st["pops_total"] > 0 and st["pushes_total"] > 0
    assert paged.telemetry.counter_total(
        "arena.page_mirror_mismatches") == 0
    # per-id vector readout goes through the same indirection
    for rid in ("m21", "g3", "d0"):
        np.testing.assert_allclose(dense.get_embedding(rid),
                                   paged.get_embedding(rid), atol=1e-6)


def test_paged_growth_is_metadata_only():
    """Copy-free growth: logical capacity doubles with block rounding
    while the pool grows only on live-set demand — after the churn the
    emb pool is strictly SMALLER than the logical table (dense would
    carry capacity+1 embedding rows), and the raw grow step reuses the
    pool buffer by reference (no copy of any embedding byte)."""
    e = _corpus(124)
    paged = MemoryIndex(dim=D, capacity=CAP, paged=True, page_rows=8)
    _churn(paged, e)
    assert paged.capacity > CAP                      # churn forced growth
    assert paged.state.emb.shape[0] - 1 < paged.capacity
    st = paged.state
    st2 = S.grow_arena_paged(st, paged.capacity * 2 + 1)
    assert st2.emb is st.emb                         # SAME buffer, no copy
    assert st2.capacity == paged.capacity * 2 + 1
    assert st2.row_map.shape[0] == st2.capacity + 1


def test_paged_dense_parity_int8():
    e = _corpus(124)
    dense = MemoryIndex(dim=D, capacity=CAP, int8_serving=True)
    paged = MemoryIndex(dim=D, capacity=CAP, int8_serving=True,
                        paged=True, page_rows=8)
    for idx in (dense, paged):
        _churn(idx, e)
    _parity_search(dense, paged, e[:6])


def test_paged_dense_parity_ivf():
    e = _clustered(320)
    dense = MemoryIndex(dim=D, capacity=256, ivf_nprobe=4)
    paged = MemoryIndex(dim=D, capacity=256, ivf_nprobe=4,
                        paged=True, page_rows=16)
    for idx in (dense, paged):
        idx._IVF_MIN_ROWS = 1
        _add(idx, [f"m{i}" for i in range(256)], e[:256])
        assert idx.ivf_maintenance()
        idx.delete([f"m{i}" for i in range(0, 64, 4)])     # member holes
        _add(idx, [f"f{i}" for i in range(32)], e[256:288], ts=1.0)
    _parity_search(dense, paged, e[::40][:6], k=5)
    assert paged.stats()["paged"]["pages_free"] >= 0


def test_paged_dense_parity_pq():
    e = _clustered(320, d=32)
    dense = MemoryIndex(dim=32, capacity=256, ivf_nprobe=4,
                        pq_serving=True)
    paged = MemoryIndex(dim=32, capacity=256, ivf_nprobe=4,
                        pq_serving=True, paged=True, page_rows=16)
    for idx in (dense, paged):
        idx._IVF_MIN_ROWS = 1
        _add(idx, [f"m{i}" for i in range(256)], e[:256])
        assert idx.ivf_maintenance()
        assert idx._pq_book is not None
        _add(idx, [f"f{i}" for i in range(16)], e[256:272], ts=1.0)
    _parity_search(dense, paged, e[::40][:6], k=5)


def test_paged_tiering_reclaims_pages_and_parity():
    """Tier demote must PUSH freed slots (reclaimed capacity), the pump's
    IVF repack hook must keep member lists hole-free, and the meaningful
    top-k must match the dense tiered index."""
    e = _corpus(124)
    dense = MemoryIndex(dim=D, capacity=CAP, int8_serving=True)
    paged = MemoryIndex(dim=D, capacity=CAP, int8_serving=True,
                        paged=True, page_rows=8)
    for idx in (dense, paged):
        _add(idx, [f"m{i}" for i in range(48)], e[:48])
        tm = idx.enable_tiering(hot_budget_rows=16)
        tm.run_once()
        assert tm.demoted_total > 0
    assert dense.tiering.demoted_total == paged.tiering.demoted_total
    st = paged.stats()["paged"]
    assert st["pages_free"] > 0
    assert st["pushes_total"] == paged.tiering.demoted_total
    assert paged.telemetry.counter_total(
        "arena.page_mirror_mismatches") == 0
    _parity_search(dense, paged, e[:6], k=4)
    # re-ingest after demote REUSES the freed pages: no pool growth
    pool = paged.state.emb.shape[0]
    _add(paged, [f"r{i}" for i in range(8)], e[64:72], ts=3.0)
    assert paged.state.emb.shape[0] == pool
    assert paged.stats()["paged"]["pages_free"] < st["pages_free"]


def test_paged_mesh_warns_and_falls_back_dense():
    import jax

    from lazzaro_tpu.parallel.mesh import make_mesh

    e = _corpus(80)
    mesh = make_mesh(("data",), (2,), jax.devices()[:2])
    with pytest.warns(UserWarning, match="paged arena is single-chip"):
        meshed = MemoryIndex(dim=D, capacity=CAP, mesh=mesh,
                             paged=True, page_rows=8)
    assert not meshed.paged and meshed.state.row_map is None
    # the fallback still answers exactly like a single-chip paged index
    single = MemoryIndex(dim=D, capacity=CAP, paged=True, page_rows=8)
    for idx in (meshed, single):
        _add(idx, [f"m{i}" for i in range(48)], e[:48])
        idx.delete([f"m{i}" for i in range(0, 12, 2)])
        _add(idx, [f"g{i}" for i in range(8)], e[48:56], ts=1.0)
    for q in e[:5]:
        mi, msc = meshed.search(q, "t", k=6)
        si, ssc = single.search(q, "t", k=6)
        mp, sp = _pos(mi, msc), _pos(si, ssc)
        assert [i for i, _ in mp] == [i for i, _ in sp]
        np.testing.assert_allclose([s for _, s in mp],
                                   [s for _, s in sp], atol=1e-5)


_COUNTED = ("ingest_fused", "ingest_fused_copy", "ingest_dedup_fused",
            "ingest_dedup_fused_copy", "search_fused", "search_fused_copy",
            "search_fused_ragged", "search_fused_ragged_copy",
            "arena_add", "arena_add_copy", "arena_delete", "arena_delete_copy",
            "arena_add_paged", "arena_add_paged_copy",
            "arena_delete_paged", "arena_delete_paged_copy",
            "tier_demote_paged", "tier_demote_paged_copy",
            "tier_promote_paged", "tier_promote_paged_copy")


def _count_dispatches(monkeypatch):
    calls = {name: 0 for name in _COUNTED}
    for name in _COUNTED:
        orig = getattr(S, name)

        def wrapped(*a, __orig=orig, __name=name, **kw):
            calls[__name] += 1
            return __orig(*a, **kw)

        monkeypatch.setattr(S, name, wrapped)
    return calls


def test_paging_adds_zero_dispatches(monkeypatch):
    """The jit-call counter, dense vs paged, same ops: the free-list pop
    rides INSIDE the one fused ingest program and the serve path is the
    same one fused search — paging must not add a single extra dispatch
    on the steady-state path."""
    e = _corpus(40)
    common = dict(saliences=[0.5] * 12, timestamps=[0.0] * 12,
                  types=["semantic"] * 12, shard_keys=["default"] * 12)

    def run(paged):
        idx = MemoryIndex(dim=D, capacity=CAP, paged=paged, page_rows=8)
        _add(idx, [f"s{i}" for i in range(16)], e[:16])   # warm (uncounted)
        idx.search(e[0], "t", k=5)
        calls = _count_dispatches(monkeypatch)
        before = idx.ingest_dispatch_count
        idx.ingest_batch([f"n{i}" for i in range(12)], e[16:28],
                         tenant="t", link_k=3, **common)
        assert idx.ingest_dispatch_count - before == 1
        idx.search(e[20], "t", k=5)
        return idx, dict(calls)

    dense_idx, dense_calls = run(False)
    paged_idx, paged_calls = run(True)
    assert dense_calls == paged_calls, (dense_calls, paged_calls)
    assert (paged_calls["ingest_fused"]
            + paged_calls["ingest_fused_copy"]) == 1
    # page maintenance never surfaced as a sibling dispatch
    for name in ("arena_add_paged", "arena_add_paged_copy",
                 "tier_demote_paged", "tier_demote_paged_copy",
                 "tier_promote_paged", "tier_promote_paged_copy"):
        assert paged_calls[name] == 0, (name, paged_calls)
    assert paged_idx.telemetry.counter_total(
        "arena.page_mirror_mismatches") == 0


def test_paged_checkpoint_roundtrip():
    """``row_map`` + free list survive save/load: identical answers, an
    identical page table, and a free list that KEEPS allocating (delete →
    re-add reuses a reclaimed slot, no pool growth)."""
    e = _corpus(126)
    idx = MemoryIndex(dim=D, capacity=CAP, paged=True, page_rows=8)
    _churn(idx, e)
    want = [_pos(*idx.search(q, "t", k=8)) for q in e[:5]]
    with tempfile.TemporaryDirectory() as ck:
        save_index(idx, ck)
        idx2 = load_index(ck)
    assert idx2.paged and idx2.state.row_map is not None
    np.testing.assert_array_equal(np.asarray(idx.state.row_map),
                                  np.asarray(idx2.state.row_map))
    np.testing.assert_array_equal(np.asarray(idx.state.inv_map),
                                  np.asarray(idx2.state.inv_map))
    assert int(idx2._ptable.free_top) == int(idx._ptable.free_top)
    assert idx2._pager.page_stats() == idx._pager.page_stats()
    got = [_pos(*idx2.search(q, "t", k=8)) for q in e[:5]]
    assert got == want
    # the restored free list still allocates: freed slot is reused
    pool = idx2.state.emb.shape[0]
    idx2.delete(["g0", "g1"])
    free_after_del = idx2.stats()["paged"]["pages_free"]
    _add(idx2, ["post0", "post1"], e[124:126], ts=9.0)
    assert idx2.state.emb.shape[0] == pool
    assert idx2.stats()["paged"]["pages_free"] <= free_after_del
    (ids, scores) = idx2.search(e[124], "t", k=3)
    assert _pos(ids, scores)[0][0] == "post0"
    assert idx2.telemetry.counter_total("arena.page_mirror_mismatches") == 0


def test_paged_checkpoint_rejects_mesh_load():
    e = _corpus(40)
    idx = MemoryIndex(dim=D, capacity=CAP, paged=True, page_rows=8)
    _add(idx, [f"m{i}" for i in range(16)], e[:16])
    with tempfile.TemporaryDirectory() as ck:
        save_index(idx, ck)
        import jax

        from lazzaro_tpu.parallel.mesh import make_mesh
        with pytest.raises(ValueError, match="single-chip"):
            load_index(ck, mesh=make_mesh(("data",), (2,),
                                          jax.devices()[:2]))
