"""Hang-proofing utilities (utils/backend_probe.py).

These guard the round-3 failure mode: a wedged accelerator tunnel that makes
``jax.devices()`` hang (not raise), so every backend decision must be
subprocess-probed or env-derived (VERDICT.md weak #1/#6)."""

import os

from lazzaro_tpu.utils import backend_probe as bp


def test_env_forced_cpu_devices_parses(monkeypatch):
    for var in bp.ACCEL_ENV_VARS:
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    assert bp.env_forced_cpu_devices() == 8
    monkeypatch.setenv("XLA_FLAGS", "")
    assert bp.env_forced_cpu_devices() == 1   # cpu pinned, default 1 device
    monkeypatch.setenv("JAX_PLATFORMS", "")
    assert bp.env_forced_cpu_devices() == 0   # platform not pinned -> unknown


def test_env_forced_cpu_devices_rejects_live_accel_plugin(monkeypatch):
    # The tunneled-TPU sitecustomize registers its backend whenever its env
    # vars are set, OVERRIDING a shell-level JAX_PLATFORMS=cpu — so the env
    # gate must refuse to call that "CPU-pinned" (r4 review finding: the
    # bypass defeated every probe gate on this very host).
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    monkeypatch.setenv(bp.ACCEL_ENV_VARS[0], "10.0.0.1")
    assert bp.env_forced_cpu_devices() == 0


def test_cpu_env_strips_accelerator_vars(monkeypatch):
    monkeypatch.setenv(bp.ACCEL_ENV_VARS[0], "10.0.0.1")
    env = bp.cpu_env(n_devices=4)
    assert bp.ACCEL_ENV_VARS[0] not in env
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "--xla_force_host_platform_device_count=4" in env["XLA_FLAGS"]
    # re-deriving with a different count must replace, not append
    env2 = bp.cpu_env(n_devices=2, base=env)
    assert env2["XLA_FLAGS"].count("--xla_force_host_platform_device_count") == 1
    assert "--xla_force_host_platform_device_count=2" in env2["XLA_FLAGS"]


def test_probe_backend_ok_on_cpu():
    res = bp.probe_backend(timeout=120.0, env=bp.cpu_env())
    assert res["ok"] is True
    assert res["platform"] == "cpu"
    assert res["device_count"] >= 1


def test_probe_backend_timeout_never_hangs():
    # A 0.01 s budget cannot complete backend init: must report, not hang.
    res = bp.probe_backend(timeout=0.01, env=bp.cpu_env())
    assert res["ok"] is False
    assert "timed out" in res["error"]


def test_ensure_healthy_or_cpu_noop_when_env_forced(monkeypatch):
    for var in bp.ACCEL_ENV_VARS:
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    called = []
    monkeypatch.setattr(bp, "probe_backend",
                        lambda **kw: called.append(1) or {"ok": False})
    health = bp.ensure_healthy_or_cpu(timeout=1.0)
    assert health["ok"] and health.get("forced_by_env")
    assert not called                      # genuinely env-gated: no probe


def test_ensure_healthy_or_cpu_steers_cpu_on_failure(monkeypatch):
    monkeypatch.setenv(bp.ACCEL_ENV_VARS[0], "10.0.0.1")  # accel plugin "live"
    attempts = []

    def fake_probe(**kw):
        attempts.append(1)
        return {"ok": False, "error": "wedged"}

    steered = []
    monkeypatch.setattr(bp, "probe_backend", fake_probe)
    monkeypatch.setattr(bp, "force_cpu", lambda *a, **k: steered.append(1))
    health = bp.ensure_healthy_or_cpu(timeout=1.0, retries=1, retry_wait=0.0)
    assert health["ok"] is False
    assert len(attempts) == 2              # initial + one retry
    assert steered                         # fell back to CPU
