"""Device arena / MemoryIndex unit tests: add/search/delete, tenant isolation,
decay parity math, eviction ranking, linking, merge candidates, components,
and the 8-device sharded top-k collective."""

import numpy as np
import pytest

from lazzaro_tpu.core.index import MemoryIndex


def basis(dim, i):
    v = np.zeros(dim, np.float32)
    v[i] = 1.0
    return v


@pytest.fixture()
def idx():
    return MemoryIndex(dim=8, capacity=16, edge_capacity=32, epoch=1000.0)


def fill(idx, n=3, tenant="u1", t0=1000.0):
    ids = [f"n{i}" for i in range(n)]
    embs = np.stack([basis(8, i) for i in range(n)])
    idx.add(ids, embs, [0.5] * n, [t0] * n, ["semantic"] * n,
            ["default"] * n, tenant)
    return ids


def test_add_search_exact(idx):
    fill(idx, 3)
    ids, scores = idx.search(basis(8, 1), "u1", k=2)
    assert ids[0] == "n1"
    assert scores[0] == pytest.approx(1.0, abs=1e-5)


def test_tenant_isolation(idx):
    fill(idx, 2, tenant="u1")
    idx.add(["m0"], basis(8, 5).reshape(1, -1), [0.5], [1000.0],
            ["semantic"], ["default"], "u2")
    ids, _ = idx.search(basis(8, 5), "u1", k=3)
    assert "m0" not in ids
    ids2, _ = idx.search(basis(8, 5), "u2", k=3)
    assert ids2 == ["m0"]


def test_delete_removes_from_search(idx):
    fill(idx, 3)
    idx.delete(["n1"])
    ids, _ = idx.search(basis(8, 1), "u1", k=3)
    assert "n1" not in ids
    assert len(idx) == 2


def test_decay_parity_math(idx):
    ids = ["a"]
    idx.add(ids, basis(8, 0).reshape(1, -1), [0.9], [1000.0],
            ["semantic"], ["default"], "u1")
    idx.decay("u1", rate=0.01, salience_floor=0.2)
    sal = idx.pull_numeric()["salience"][idx.id_to_row["a"]]
    assert sal == pytest.approx(0.2 + (0.9 - 0.2) * 0.99, abs=1e-6)


def test_decay_is_tenant_scoped(idx):
    idx.add(["a"], basis(8, 0).reshape(1, -1), [0.9], [1000.0],
            ["semantic"], ["default"], "u1")
    idx.add(["b"], basis(8, 1).reshape(1, -1), [0.9], [1000.0],
            ["semantic"], ["default"], "u2")
    idx.decay("u1", rate=0.01)
    cols = idx.pull_numeric()
    assert cols["salience"][idx.id_to_row["a"]] == pytest.approx(0.893, abs=1e-5)
    assert cols["salience"][idx.id_to_row["b"]] == pytest.approx(0.9, abs=1e-6)


def test_capacity_growth(idx):
    n = 40  # > initial capacity 16
    ids = [f"g{i}" for i in range(n)]
    embs = np.random.RandomState(0).randn(n, 8).astype(np.float32)
    idx.add(ids, embs, [0.5] * n, [1000.0] * n, ["semantic"] * n,
            ["default"] * n, "u1")
    assert idx.capacity >= n
    got, _ = idx.search(embs[37], "u1", k=1)
    assert got == ["g37"]


def test_evict_candidates_ranking(idx):
    now = 1000.0
    idx.add(["low", "high"], np.stack([basis(8, 0), basis(8, 1)]),
            [0.1, 0.9], [now, now], ["semantic"] * 2, ["default"] * 2, "u1")
    idx.update_access(["high"], boost=0.0, now=now)
    cands = idx.evict_candidates("u1", 1, now=now)
    assert cands[0][0] == "low"


def test_edges_add_reinforce_prune(idx):
    fill(idx, 3)
    idx.add_edges([("n0", "n1", 0.6)], "u1", now=1000.0)
    idx.add_edges([("n0", "n1", 0.6)], "u1", now=1000.0)  # reinforce +0.1
    w, co = idx.edge_weights()[("n0", "n1")]
    assert w == pytest.approx(0.7, abs=1e-6)
    assert co == 2
    idx.add_edges([("n1", "n2", 0.3)], "u1", now=1000.0)
    removed = idx.prune_edges("u1", 0.5)
    assert removed == [("n1", "n2")]
    assert ("n0", "n1") in idx.edge_slots


def test_link_candidates_same_shard(idx):
    embs = np.stack([basis(8, 0),
                     (basis(8, 0) * 0.9 + basis(8, 1) * 0.435),
                     basis(8, 2)])
    idx.add(["a", "b", "c"], embs, [0.5] * 3, [1000.0] * 3,
            ["semantic"] * 3, ["work", "work", "play"], "u1")
    cands = idx.link_candidates(["a"], "u1", k=2, shard_mode=1)
    got = cands["a"]
    assert got and got[0][0] == "b"
    assert got[0][1] > 0.85
    assert all(c != "c" for c, _ in got)


def test_merge_candidates_all_pairs(idx):
    # three mutually >0.95 duplicates plus one distinct — the intended
    # all-pairs semantics (NOT the reference's last-node-only bug)
    dup = basis(8, 3)
    embs = np.stack([dup, dup, dup, basis(8, 6)])
    idx.add(["d1", "d2", "d3", "x"], embs, [0.5] * 4, [1000.0] * 4,
            ["semantic"] * 4, ["default"] * 4, "u1")
    pairs = idx.merge_candidates("u1", threshold=0.95)
    merge_ids = {(a, b) for a, b, _ in pairs}
    assert ("d1", "d2") in merge_ids or ("d2", "d1") in merge_ids
    assert all("x" not in p[:2] for p in pairs)


def test_components(idx):
    fill(idx, 4)
    idx.add_edges([("n0", "n1", 0.8), ("n2", "n3", 0.8)], "u1")
    comps = sorted([sorted(c) for c in idx.components()])
    assert ["n0", "n1"] in comps
    assert ["n2", "n3"] in comps


def test_sharded_topk_matches_reference():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from lazzaro_tpu.ops import make_sharded_topk
    from lazzaro_tpu.parallel import make_mesh

    mesh = make_mesh(("data",), (8,))
    N, d, k = 2048, 32, 7
    rng = np.random.RandomState(42)
    emb = rng.randn(N, d).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    mask = rng.rand(N) > 0.3
    q = emb[123]

    search = make_sharded_topk(mesh, "data", k=k)
    emb_s = jax.device_put(emb, NamedSharding(mesh, P("data", None)))
    mask_s = jax.device_put(mask, NamedSharding(mesh, P("data")))
    scores, rows = search(emb_s, mask_s, q)

    ref = np.where(mask, emb @ q, -1e30)
    expect = set(np.argsort(-ref)[:k].tolist())
    assert set(np.asarray(rows)[0].tolist()) == expect


def test_arena_search_pallas_dispatch_parity():
    """The blocked Pallas top-k (arena_search impl='pallas', interpret on
    CPU) agrees with the XLA path on a block-aligned arena — the serving
    dispatch contract (verdict r2 weak #3: in the path, with parity)."""
    import jax.numpy as jnp
    from lazzaro_tpu.core import state as S

    n_rows, dim, k = 2 * S.TOPK_BLOCK, 64, 8
    rng = np.random.RandomState(0)
    emb = S.normalize(jnp.asarray(rng.randn(n_rows, dim), jnp.float32))
    zeros_i = jnp.zeros((n_rows,), jnp.int32)
    alive = jnp.ones((n_rows,), bool).at[n_rows - 5:].set(False)
    arena = S.ArenaState(
        emb=emb, salience=jnp.full((n_rows,), 0.5), timestamp=jnp.zeros((n_rows,)),
        last_accessed=jnp.zeros((n_rows,)), access_count=zeros_i,
        type_id=zeros_i, shard_id=zeros_i, tenant_id=zeros_i,
        alive=alive, is_super=jnp.zeros((n_rows,), bool))
    q = jnp.asarray(rng.randn(3, dim), jnp.float32)
    sx, rx = S.arena_search(arena, q, jnp.int32(0), k, impl="xla")
    sp, rp = S.arena_search(arena, q, jnp.int32(0), k, impl="pallas")
    np.testing.assert_array_equal(np.asarray(rx), np.asarray(rp))
    np.testing.assert_allclose(np.asarray(sx), np.asarray(sp), atol=1e-5)
    assert not np.isin(np.arange(n_rows - 5, n_rows), np.asarray(rp)).any()


def test_index_capacity_block_aligned():
    """Big arenas allocate row counts in TOPK_BLOCK multiples so the Pallas
    path never pads; small arenas stay exact."""
    from lazzaro_tpu.core import state as S

    big = MemoryIndex(dim=8, capacity=S.TOPK_BLOCK + 7, edge_capacity=8)
    assert big.state.emb.shape[0] % S.TOPK_BLOCK == 0
    assert len(big._free_rows) == big.state.capacity
    small = MemoryIndex(dim=8, capacity=64, edge_capacity=8)
    assert small.state.capacity == 64
