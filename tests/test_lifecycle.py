"""Device-side lifecycle sweep (ISSUE 19; tier-1 smoke, CPU, tiny arena).

``MemoryIndex.lifecycle_sweep`` folds salience decay, edge decay +
weak-edge prune, and importance-ranked archive verdicts for ALL tenants
into ONE donated dispatch + ONE packed readback. These tests pin:

- the jit-call count (exactly one ``lifecycle_sweep`` entry, single chip
  AND 2-way mesh — no sibling decay/prune/evict dispatches);
- bit-parity of the arena columns, the edge pool, and the per-tenant
  verdicts against the classic host loop (the A/B oracle) on a
  multi-tenant churn fixture;
- the satellites: fused classic ``decay()`` (one dispatch, not two),
  O(pruned) host reclamation through the ``_EdgeSlotMap`` reverse
  index, tenant-scoped query-cache invalidation, the scheduler-aware
  tick deferral, the TierPump demote-queue feed, and closed-form decay
  replay across a checkpoint restart.
"""

import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lazzaro_tpu.config import MemoryConfig
from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.index import MemoryIndex, _EdgeSlotMap
from lazzaro_tpu.core.memory_system import MemorySystem
from lazzaro_tpu.core.query_cache import QueryCache

D = 16
RATE, FLOOR, THRESH = 0.01, 0.2, 0.5
WEIGHTS = (0.5, 0.3, 0.2)
TENANTS = ("alice", "bob", "carol")


def _fill(idx, n=10, edges=9, tenants=TENANTS):
    """Multi-tenant churn fixture: per-tenant chains with saliences and
    weights straddling the floor/threshold so every sweep decays, prunes,
    and ranks somewhere interesting."""
    rng = np.random.RandomState(7)
    for t in tenants:
        ids = [f"{t}:n{i}" for i in range(n)]
        emb = rng.randn(n, D).astype(np.float32)
        idx.add(ids, emb, [0.25 + 0.05 * i for i in range(n)],
                [100.0] * n, ["episodic"] * n, ["s0"] * n, t)
        idx.add_edges([(ids[i], ids[i + 1], 0.42 + 0.02 * i)
                       for i in range(edges)], t, now=100.0)
    return idx


def _index(mesh=None, cap=64, ecap=128):
    return _fill(MemoryIndex(dim=D, capacity=cap, edge_capacity=ecap,
                             mesh=mesh, epoch=0.0))


def _classic(idx, archive_k=4, now=200.0):
    removed, verdicts = [], {}
    for t in TENANTS:
        idx.decay(t, RATE, FLOOR)
        removed.extend(idx.prune_edges(t, THRESH))
        verdicts[t] = idx.evict_candidates(t, archive_k, now=now,
                                           weights=WEIGHTS)
    return removed, verdicts


def _sweep(idx, archive_k=4, now=200.0, passes=None):
    return idx.lifecycle_sweep(passes or {t: 1 for t in TENANTS},
                               rate=RATE, salience_floor=FLOOR,
                               prune_threshold=THRESH, weights=WEIGHTS,
                               archive_k=archive_k, now=now)


def _assert_parity(a, b):
    """Arena columns + edge pool bitwise-identical between two indexes
    (b may be mesh-padded — compare the prefix; the sentinel scratch slot
    is fair game for padded scatters, like every other kernel)."""
    ncap = a.state.capacity
    for col in ("salience", "last_accessed", "access_count", "tenant_id"):
        av = np.asarray(getattr(a.state, col))[:ncap]
        bv = np.asarray(getattr(b.state, col))[:ncap]
        if av.dtype == np.float32:
            av, bv = av.view(np.int32), bv.view(np.int32)
        np.testing.assert_array_equal(av, bv, err_msg=col)
    ecap = a.edge_state.capacity
    for col in ("src", "tgt", "weight", "alive", "tenant_id"):
        av = np.asarray(getattr(a.edge_state, col))[:ecap]
        bv = np.asarray(getattr(b.edge_state, col))[:ecap]
        if av.dtype == np.float32:
            av, bv = av.view(np.int32), bv.view(np.int32)
        np.testing.assert_array_equal(av, bv, err_msg=f"edge.{col}")


# ------------------------------------------------------------- jit counter
_COUNTED = ("lifecycle_sweep", "lifecycle_sweep_copy",
            "decay_fused", "decay_fused_copy",
            "arena_decay", "arena_decay_copy",
            "edges_decay", "edges_decay_copy",
            "edges_prune", "edges_prune_copy")


def _count_dispatches(monkeypatch):
    calls = {name: 0 for name in _COUNTED}
    for name in _COUNTED:
        orig = getattr(S, name)

        def wrapped(*a, __orig=orig, __name=name, **kw):
            calls[__name] += 1
            return __orig(*a, **kw)

        monkeypatch.setattr(S, name, wrapped)
    return calls


def test_sweep_is_one_dispatch_single_chip(monkeypatch):
    """The jit-call counter: an all-tenant sweep (3 tenants × decay +
    prune + verdicts) is exactly ONE donated program — zero classic
    decay/prune siblings."""
    idx = _index()
    calls = _count_dispatches(monkeypatch)
    before = idx.lifecycle_dispatch_count
    out = _sweep(idx)
    assert idx.lifecycle_dispatch_count - before == 1
    assert out["dispatches"] == 1
    assert calls["lifecycle_sweep"] == 1        # donated (sole owner)
    for name in _COUNTED:
        if name != "lifecycle_sweep":
            assert calls[name] == 0, (name, calls)
    assert out["decayed_rows"] == 30 and out["decayed_edges"] == 27
    assert out["pruned_edges"] > 0 and not out["prune_overflow"]


def test_sweep_is_one_dispatch_mesh(monkeypatch):
    """Same counter under a 2-way mesh: the ``make_lifecycle_sharded``
    composition is still ONE distributed dispatch — shard-local compaction
    and the verdict merge ride inside it, no per-shard host round trips."""
    from lazzaro_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(("data",), (2,), devices=jax.devices()[:2])
    idx = _index(mesh=mesh)
    calls = _count_dispatches(monkeypatch)
    before = idx.lifecycle_dispatch_count
    out = _sweep(idx)
    assert idx.lifecycle_dispatch_count - before == 1
    assert out["dispatches"] == 1
    for name in _COUNTED:                       # sharded path never falls
        assert calls[name] == 0, (name, calls)  # back to single-chip jits


def test_classic_decay_is_one_dispatch(monkeypatch):
    """Satellite: the classic ``decay()`` (arena + edge-weight decay) is
    ONE fused dispatch now, not the old two-program sequence."""
    idx = _index()
    calls = _count_dispatches(monkeypatch)
    idx.decay("alice", RATE, FLOOR)
    assert calls["decay_fused"] + calls["decay_fused_copy"] == 1
    assert calls["arena_decay"] == calls["arena_decay_copy"] == 0
    assert calls["edges_decay"] == calls["edges_decay_copy"] == 0


# -------------------------------------------------------------- bit parity
def test_sweep_bit_parity_single_chip():
    """Fused sweep vs classic host loop on the churn fixture: arena
    columns, edge pool, removed-edge set, free-list, and per-tenant
    verdicts all bit-identical."""
    a, b = _index(), _index()
    removed_a, verdicts_a = _classic(a)
    out = _sweep(b)
    _assert_parity(a, b)
    assert sorted(removed_a) == sorted(out["removed_edges"])
    assert sorted(a._free_edge_slots) == sorted(b._free_edge_slots)
    assert set(a.edge_slots) == set(b.edge_slots)
    for t in TENANTS:
        assert verdicts_a[t] == [(n, i) for n, i, _r in out["verdicts"][t]]
    # churn AFTER the sweep: both indexes keep answering identically
    rng = np.random.RandomState(11)
    for idx in (a, b):
        idx.add([f"alice:x{i}" for i in range(4)],
                rng.randn(4, D).astype(np.float32).copy(), [0.6] * 4,
                [210.0] * 4, ["episodic"] * 4, ["s0"] * 4, "alice")
        rng = np.random.RandomState(11)
    _assert_parity(a, b)


def test_sweep_bit_parity_mesh():
    """2-way mesh sweep vs single-chip classic loop: row-sharded decay,
    shard-local prune compaction, and the negated-importance verdict
    merge reproduce the host loop bit-for-bit."""
    from lazzaro_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(("data",), (2,), devices=jax.devices()[:2])
    a, b = _index(), _index(mesh=mesh)
    removed_a, verdicts_a = _classic(a)
    out = _sweep(b)
    _assert_parity(a, b)
    assert sorted(removed_a) == sorted(out["removed_edges"])
    for t in TENANTS:
        assert verdicts_a[t] == [(n, i) for n, i, _r in out["verdicts"][t]]


def test_sweep_multi_pass_matches_closed_form():
    """Catch-up ticks (owed passes > 1) use the closed form — the same
    formula the checkpoint loader replays — not p repeated multiplies."""
    idx = _index()
    _sweep(idx, passes={"alice": 3})
    sal = np.asarray(idx.state.salience)
    row = idx.id_to_row["alice:n5"]
    want = FLOOR + (0.5 - FLOOR) * (1.0 - RATE) ** 3
    assert sal[row] == pytest.approx(want, abs=1e-6)
    # bob owed nothing: untouched bitwise
    brow = idx.id_to_row["bob:n5"]
    assert sal[brow] == np.float32(0.5)


# ------------------------------------------------- O(pruned) host cleanup
def test_edge_slot_map_reverse_index_stays_consistent():
    """Satellite: every ``edge_slots`` mutation path keeps ``by_slot``
    exact — add, prune-reclaim, checkpoint-style wholesale rebuild."""
    idx = _index()
    es = idx.edge_slots
    assert isinstance(es, _EdgeSlotMap)
    assert es.by_slot == {v: k for k, v in es.items()}
    out = _sweep(idx)
    assert out["removed_edges"]
    es = idx.edge_slots
    assert es.by_slot == {v: k for k, v in es.items()}
    for key in out["removed_edges"]:
        assert key not in es
    # wholesale rebuild (the checkpoint-load path)
    rebuilt = _EdgeSlotMap(dict(es))
    assert rebuilt.by_slot == es.by_slot
    # single-key ops
    rebuilt[("x", "y")] = 97
    assert rebuilt.by_slot[97] == ("x", "y")
    del rebuilt[("x", "y")]
    assert 97 not in rebuilt.by_slot


def test_prune_returns_slots_and_frees_them():
    """``prune_edges`` reclaims through the compacted device slot vector:
    freed slots return to the free list and the next add reuses them."""
    idx = _index()
    free0 = len(idx._free_edge_slots)
    live0 = len(idx.edge_slots)
    removed = idx.prune_edges("alice", THRESH)
    assert removed                               # weak chain edges died
    assert len(idx._free_edge_slots) == free0 + len(removed)
    assert len(idx.edge_slots) == live0 - len(removed)
    alive = np.asarray(idx.edge_state.alive)
    for slot in idx._free_edge_slots[-len(removed):]:
        assert not alive[slot]


# ----------------------------------------------------- query-cache scoping
def test_query_cache_invalidate_is_tenant_scoped():
    qc = QueryCache(max_size=16)
    qc.set_results("qa", ["n1"], tenant="alice")
    qc.set_results("qb", ["n2"], tenant="bob")
    qc.set_results("qu", ["n3"])                 # untagged: owner unknown
    qc.invalidate_results("alice")
    assert qc.get_results("qa", "alice") is None
    assert qc.get_results("qb", "bob") == ["n2"]
    assert qc.get_results("qu") is None          # dropped either way
    qc.invalidate_results()
    assert qc.get_results("qb", "bob") is None


# --------------------------------------------------- system tick + pump
_DIRS = np.random.default_rng(3).standard_normal((10, D))
_DIRS /= np.linalg.norm(_DIRS, axis=1, keepdims=True)


class _ClusteredEmb:
    """Same-group facts land ~0.8 cosine apart: above the link gate,
    below the dedup gate — real edges, distinct nodes (deterministic)."""

    dim = D

    def _v(self, t):
        try:
            idx = int(t.split()[1])
        except (IndexError, ValueError):
            idx = abs(hash(t)) % 100
        rng = np.random.default_rng(500 + idx)
        v = 0.85 * _DIRS[idx % 10] + 0.55 * rng.standard_normal(D)
        return (v / np.linalg.norm(v)).tolist()

    def embed(self, t):
        return self._v(t)

    def batch_embed(self, ts):
        return [self._v(t) for t in ts]


class _FactLLM:
    """Deterministic consolidator: per-fact DISTINCT saliences so verdict
    ranking has no ties for timestamp jitter to flip."""

    def __init__(self, per=12):
        self.c = 0
        self.per = per

    def completion(self, messages, response_format=None):
        import json

        base = self.c * self.per
        self.c += 1
        return json.dumps({"memories": [
            {"content": f"fact {base + i} body", "type": "semantic",
             "salience": round(0.25 + 0.03 * ((base + i) % 20), 4),
             "topic": ["work", "personal", "learning"][(base + i) % 3]}
            for i in range(self.per)]})

    def completion_stream(self, messages, response_format=None):
        yield self.completion(messages, response_format)


def _system(tmp, fused=True, interval=0.0, load=False, per=12, **cfg_kw):
    return MemorySystem(
        enable_async=False, db_dir=tmp, verbose=False, load_from_disk=load,
        llm_provider=_FactLLM(per), embedding_provider=_ClusteredEmb(),
        auto_prune=False, max_buffer_size=10_000,
        config=MemoryConfig(journal=False, auto_consolidate=False,
                            decay_rate=RATE, salience_floor=FLOOR,
                            prune_threshold=THRESH, lifecycle_fused=fused,
                            lifecycle_interval_s=interval,
                            lifecycle_archive_k=4,
                            importance_w_salience=WEIGHTS[0],
                            importance_w_access=WEIGHTS[1],
                            importance_w_recency=WEIGHTS[2], **cfg_kw))


def _seed_system(ms):
    """One consolidated conversation: 12 facts with distinct saliences,
    gated link edges between clustered facts. Applies ONE decay pass."""
    ms.start_conversation()
    ms.add_to_short_term("conv 0", "episodic", 0.7)
    ms.end_conversation()
    return sorted(nid for nid in ms.buffer.nodes)


def test_lifecycle_tick_fused_matches_classic():
    """System-level A/B: ``lifecycle_fused`` on vs off over identical
    graphs — same saliences (bitwise), same pruned edges, same verdict
    node sets, and the same rows land in the TierPump demote queue."""
    with tempfile.TemporaryDirectory() as ta, \
            tempfile.TemporaryDirectory() as tb:
        msa, msb = _system(ta, fused=False), _system(tb, fused=True)
        try:
            _seed_system(msa)
            _seed_system(msb)
            tma = msa.index.enable_tiering(8, hysteresis_s=0.0)
            tmb = msb.index.enable_tiering(8, hysteresis_s=0.0)
            outa = msa.lifecycle_tick(now=200.0, force=True)
            outb = msb.lifecycle_tick(now=200.0, force=True)
            assert not outa["deferred"] and not outb["deferred"]
            assert sorted(outa["removed_edges"]) == \
                sorted(outb["removed_edges"])
            va = {t: [n for n, *_ in v]
                  for t, v in outa["verdicts"].items()}
            vb = {t: [n for n, *_ in v]
                  for t, v in outb["verdicts"].items()}
            assert va == vb
            np.testing.assert_array_equal(
                np.asarray(msa.index.state.salience).view(np.int32),
                np.asarray(msb.index.state.salience).view(np.int32))
            assert outb["archived"] == outa["archived"] > 0
            assert tma._demote_queue == tmb._demote_queue
            assert msa._decay_pass == msb._decay_pass == 2
            # host mirrors synced: buffer salience tracks the arena
            for qid, row in msb.index.id_to_row.items():
                node = msb.buffer.get_node(qid.partition(":")[2])
                if node is not None:
                    arena = np.asarray(msb.index.state.salience)[row]
                    assert np.float32(node.salience) == arena, qid
        finally:
            msa.close()
            msb.close()


def test_tick_defers_while_scheduler_busy():
    """Scheduler-awareness: queued serving load parks the tick (counted,
    no sweep); ``force=True`` overrides."""

    class Busy:
        closed = False

        @staticmethod
        def load():
            return 3

    with tempfile.TemporaryDirectory() as tmp:
        ms = _system(tmp)
        try:
            _seed_system(ms)
            ms.query_scheduler = Busy()
            out = ms.lifecycle_tick()
            assert out == {"deferred": True}
            assert ms.telemetry.counter_total("lifecycle.deferred_busy") == 1
            out = ms.lifecycle_tick(force=True)
            assert not out["deferred"]
            assert ms.telemetry.counter_total("lifecycle.ticks") == 1
        finally:
            ms.query_scheduler = None
            ms.close()


def test_demote_queue_feeds_watermark_demotions():
    """Archive verdicts are standing nominations: the pump demotes queued
    rows FIRST when the watermark trips — archived means demoted-to-cold,
    the rows stay servable."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _system(tmp)
        try:
            _seed_system(ms)
            tm = ms.index.enable_tiering(8, high_watermark=0.5,
                                         low_watermark=0.25,
                                         hysteresis_s=0.0)
            out = ms.lifecycle_tick(now=200.0, force=True)
            assert out["archived"] > 0
            queued = set(tm._demote_queue)
            stats = tm.run_once(now=201.0)
            assert stats["demoted"] > 0
            cold = np.nonzero(tm.cold_np)[0]
            assert queued & set(cold.tolist())   # nominations demoted first
            assert tm._demote_queue.isdisjoint(cold.tolist())
            # demoted ≠ deleted: node ids still resolve
            for r in cold:
                assert ms.index.row_to_id.get(int(r)) is not None
        finally:
            ms.close()


def test_lifecycle_pump_runs_ticks():
    """``lifecycle_interval_s > 0`` starts the background metronome and
    ``close()`` stops it."""
    import time as _time

    with tempfile.TemporaryDirectory() as tmp:
        ms = MemorySystem(
            enable_async=True, db_dir=tmp, verbose=False,
            load_from_disk=False, embedding_provider=_ClusteredEmb(),
            config=MemoryConfig(journal=False, auto_consolidate=False,
                                lifecycle_interval_s=0.05))
        try:
            assert ms.lifecycle_pump is not None
            deadline = _time.time() + 5.0
            while (_time.time() < deadline
                   and ms.telemetry.counter_total("lifecycle.ticks") == 0):
                _time.sleep(0.05)
            assert ms.telemetry.counter_total("lifecycle.ticks") > 0
        finally:
            ms.close()
        assert not ms.lifecycle_pump._thread.is_alive()


# ------------------------------------------- checkpoint decay replay (sat 3)
def test_decay_replay_bit_parity_across_restart():
    """Satellite: ``decay_pass`` stamping survives a save/load restart and
    the restarted system replays to BIT-parity with the never-restarted
    run — same stamps, same salience bits, before and after further
    sweeps."""
    def _bits(ms):
        sal = np.asarray(ms.index.state.salience)
        return {qid: sal[row].view(np.int32).item()
                for qid, row in ms.index.id_to_row.items()}

    with tempfile.TemporaryDirectory() as ta, \
            tempfile.TemporaryDirectory() as tb:
        msa, msb = _system(ta), _system(tb)
        try:
            _seed_system(msa)                          # pass 1 (+ save)
            _seed_system(msb)
            for _ in range(3):                         # passes 2..4
                msa.lifecycle_tick(now=200.0, force=True)
                msb.lifecycle_tick(now=200.0, force=True)
            # the seed conversation's save stamped rows at pass 1; the
            # three tick sweeps never rewrote them, so the restart must
            # REPLAY 3 missed passes from the stamp — the interesting path
            msb.store.save_sys_meta(
                {"decay_pass": msb._decay_pass,
                 "node_counter": msb.node_counter}, user_id=msb.user_id)
            msb.close()
            msb = _system(tb, load=True)               # the restart
            assert msb._decay_pass == msa._decay_pass == 4  # stamp survived
            assert _bits(msa) == _bits(msb)            # replay == lived-it
            # further sweeps on BOTH: the restarted arena keeps bit-parity
            for _ in range(2):
                msa.lifecycle_tick(now=300.0, force=True)
                msb.lifecycle_tick(now=300.0, force=True)
            assert msb._decay_pass == msa._decay_pass == 6
            assert _bits(msa) == _bits(msb)
        finally:
            msa.close()
            msb.close()


# ----------------------------------------------------------- planner gate
def test_lifecycle_geometry_admission():
    """The sweep asks the planner before dispatch: an absurdly small HBM
    budget rejects the lifecycle transient with PlanInfeasible."""
    from lazzaro_tpu.reliability.errors import PlanInfeasible

    idx = _index()
    idx.planner.budget_bytes = 1                 # nothing fits
    with pytest.raises(PlanInfeasible):
        _sweep(idx)
